"""Partition-quality benchmark: edge cut + halo volume per method.

Reference role: the reference gets its quality partitions from METIS
(``experiments/GraphCast/data_utils/preprocess.py:14-31``,
``experiments/OGB/preprocess.py:15-27``); this harness measures how close
the native multilevel+FM partitioner gets on the same two graph classes
that matter here (power-law/papers-like and clustered/SBM), against the
cheap baselines. Emits one JSON line per (graph, method) to ``--log_path``.

Halo volume is the per-rank mean count of DISTINCT remote source vertices
(what the framework actually exchanges per layer: deduped halo slots, see
plan.build_edge_plan), not raw cross edges — the number that sets the
all_to_all bytes.
"""

from __future__ import annotations

import dataclasses
import json
import time


@dataclasses.dataclass
class Config:
    num_nodes: int = 1_000_000
    avg_degree: float = 14.5
    world_size: int = 8
    graphs: str = "power_law,sbm"  # comma list
    methods: str = "random,greedy_bfs,multilevel"
    seed: int = 0
    log_path: str = "logs/partition_quality.jsonl"


def halo_stats(edge_index, part, world_size):
    """Mean/max distinct remote-src halo slots per rank (deduped, the
    plan's exchange volume) + cross-edge fraction.

    Edges are SYMMETRIZED first: the training pipelines run undirected
    message passing (both directions materialized — see bench.py /
    ogb_gcn), so a faithful wire-volume count must include the reverse
    needs too. Measuring the raw directed list understates hub dedup and
    can even move opposite to the real exchange volume."""
    import numpy as np

    src, dst = edge_index[0], edge_index[1]
    ps, pd = part[src], part[dst]
    cross = ps != pd
    # distinct (needing_rank, needed_vertex) pairs = halo slots. Dedup each
    # direction separately, then union: peak memory stays ~1x the cross
    # edges instead of materializing the full symmetrized list (4x for
    # generators that already emit both directions).
    fwd = np.unique(pd[cross].astype(np.int64) * len(part)
                    + src[cross].astype(np.int64))
    rev = np.unique(ps[cross].astype(np.int64) * len(part)
                    + dst[cross].astype(np.int64))
    slots = np.union1d(fwd, rev)
    per_rank = np.bincount(slots // len(part), minlength=world_size)
    return {
        "cross_edge_fraction": round(float(np.mean(cross)), 4),
        "halo_slots_mean": int(per_rank.mean()),
        "halo_slots_max": int(per_rank.max()),
        "balance": round(
            float(np.bincount(part, minlength=world_size).max()
                  / (len(part) / world_size)), 4),
    }


def main(cfg: Config):
    import os

    import numpy as np

    from dgraph_tpu import partition as pt
    from dgraph_tpu.data.synthetic import power_law_graph, sbm_classification_graph

    # plain file append, NOT ExperimentLog. jax IS imported transitively
    # (package __init__), but its BACKEND never initializes here — all the
    # work is numpy, and a wedged TPU lease hangs backend init, not the
    # import. ExperimentLog would not hang either, but keeping the output
    # path jax-free makes that property obvious (verified: full 5.5M-node
    # runs completed during the r4 wedge)
    os.makedirs(os.path.dirname(cfg.log_path) or ".", exist_ok=True)

    def write(rec):
        with open(cfg.log_path, "a") as fh:
            fh.write(json.dumps(rec) + "\n")
    for gname in cfg.graphs.split(","):
        if gname == "power_law":
            edges = power_law_graph(cfg.num_nodes, cfg.avg_degree, seed=cfg.seed)
        elif gname == "sbm":
            # clustered graph at the same scale: num-classes scaled so
            # communities stay partition-sized
            data = sbm_classification_graph(
                num_nodes=cfg.num_nodes,
                num_classes=max(cfg.world_size * 4, 32),
                feat_dim=1,
                avg_degree=cfg.avg_degree,
                seed=cfg.seed,
            )
            edges = data["edge_index"]
        else:
            raise SystemExit(f"unknown graph {gname!r}")
        for method in cfg.methods.split(","):
            t0 = time.perf_counter()
            if method == "random":
                part = pt.random_partition(cfg.num_nodes, cfg.world_size, cfg.seed)
            elif method == "greedy_bfs":
                part = pt.greedy_bfs_partition(
                    edges, cfg.num_nodes, cfg.world_size, cfg.seed)
            elif method == "multilevel":
                part = pt.multilevel_partition(
                    edges, cfg.num_nodes, cfg.world_size, cfg.seed)
            elif method == "multilevel_big":
                part = pt.multilevel_big_partition(
                    edges, cfg.num_nodes, cfg.world_size, cfg.seed)
            elif method == "multilevel_sampled":
                part = pt.multilevel_sampled_partition(
                    edges, cfg.num_nodes, cfg.world_size, cfg.seed)
            elif method == "rcm":
                part = pt.rcm_partition(edges, cfg.num_nodes, cfg.world_size)
            else:
                raise SystemExit(f"unknown method {method!r}")
            rec = {
                "graph": gname,
                "nodes": cfg.num_nodes,
                "edges": int(edges.shape[1]),
                "world_size": cfg.world_size,
                "method": method,
                "partition_s": round(time.perf_counter() - t0, 2),
                **halo_stats(edges, np.asarray(part), cfg.world_size),
            }
            write(rec)
            print(json.dumps(rec))


if __name__ == "__main__":
    import os as _os, sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
