"""Gather/scatter collective microbenchmarks.

Reference parity: ``experiments/Benchmarks/TestNCCL.py`` /
``TestNVSHMEM.py`` — synthetic all-pairs communication patterns, per-op
timing, ``.npy`` dumps + summary stats (``TestNCCL.py:199-284``). One
harness covers what the reference needed three backend harnesses for: the
TPU collective path is the only wire.

Produces logs/comm_bench_{gather,scatter}_times.npy and a JSON summary line
per configuration.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time


@dataclasses.dataclass
class Config:
    """Distributed gather/scatter microbenchmark."""

    num_vertices: int = 100_000
    avg_degree: float = 10.0
    feat_dim: int = 128
    world_size: int = 0
    iters: int = 30
    partition: str = "random"  # 'random' = worst-case all-pairs traffic
    out_dir: str = "logs"


def main(cfg: Config):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.comm import collectives, make_graph_mesh
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.testing import spmd_apply

    world = cfg.world_size or len(jax.devices())
    mesh = make_graph_mesh(ranks_per_graph=world)
    edges = synthetic.power_law_graph(cfg.num_vertices, cfg.avg_degree)
    feats = np.random.default_rng(0).normal(
        size=(cfg.num_vertices, cfg.feat_dim)
    ).astype(np.float32)
    g = DistributedGraph.from_global(
        edges, feats, None, None, world_size=world, partition_method=cfg.partition
    )
    plan = jax.tree.map(jnp.asarray, g.plan)
    x = jnp.asarray(g.features)

    os.makedirs(cfg.out_dir, exist_ok=True)
    results = {}
    for name, fn, args in [
        ("gather", collectives.gather, (x,)),
        (
            "scatter",
            collectives.scatter_sum,
            (jnp.zeros((world, g.plan.e_pad, cfg.feat_dim)),),
        ),
    ]:
        side = "src"
        out = spmd_apply(mesh, fn, plan, *args, static_args=(side, "graph"))
        jax.block_until_ready(out)
        times = []
        for _ in range(cfg.iters):
            t0 = time.perf_counter()
            out = spmd_apply(mesh, fn, plan, *args, static_args=(side, "graph"))
            jax.block_until_ready(out)
            times.append((time.perf_counter() - t0) * 1000)
        times = np.asarray(times)
        np.save(os.path.join(cfg.out_dir, f"comm_bench_{name}_times.npy"), times)
        results[name] = {"mean_ms": float(times.mean()), "std_ms": float(times.std())}

    # comm volume accounting (the reference's plan memory report,
    # _NCCLCommPlan.py:68-100 / Trainer.py:113-123)
    bytes_exchanged = int(
        np.asarray(g.plan.halo.send_mask).sum() * cfg.feat_dim * 4
    )
    summary = {
        "world_size": world,
        "num_vertices": cfg.num_vertices,
        "num_edges": int(edges.shape[1]),
        "feat_dim": cfg.feat_dim,
        "halo_bytes_per_exchange": bytes_exchanged,
        **results,
    }
    print(json.dumps(summary))


if __name__ == "__main__":
    import os as _os, sys as _sys

    # direct-invocation support (repo not pip-installed): put the repo
    # root on sys.path so `python experiments/<script>.py` works
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
