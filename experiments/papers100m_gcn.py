"""ogbn-papers100M-scale full-graph GCN (the reference's headline scale
target; BASELINE.md north star: papers100M epoch time on a v5p-32).

111M vertices / 1.6B edges don't fit one chip; the recipe here is the
framework's memory-scaling stack (SURVEY §7 step 9):
- vertices int32-renumbered, sharded over the full `graph` axis
- hash-keyed on-disk plan cache so the multi-hour plan build happens once
  (``train/checkpoint.cached_edge_plan``; reference pattern
  ``MAG240M_dataset.py:237-260``)
- remat (``jax.checkpoint``) on the conv layers to trade FLOPs for HBM
- bfloat16 compute

Data: ``--data_npz`` pointing at either a ``.npz`` archive (loaded eagerly)
or a DIRECTORY of ``edge_index.npy`` / ``features.npy`` / ``labels.npy`` /
``train_mask.npy`` files — the directory form is opened with
``np.load(..., mmap_mode="r")``, so shard materialization streams rows from
disk instead of first building a second in-RAM copy of the feature matrix.
Device placement streams per-device blocks (``shard_rows_to_device``), so
host residency during sharding is ONE device's ``[n_pad, F]`` block — the
stacked ``[W, n_pad, F]`` copy (~57 GB at real scale) never exists, and
multi-controller hosts materialize only their own devices' rows.
``--synthetic_scale`` gives a shape-matched power-law synthetic at a chosen
fraction of papers100M (use ``data/memmap.synthetic_papers_like`` +
``--data_npz <dir>`` to keep even the synthetic source on disk at large
fractions).

This script is single-controller; each run partitions and shards the full
graph host-side. For multi-controller pods,
``comm.multihost.process_local_shards`` picks which shards each host
should materialize.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass
class Config:
    """papers100M-scale full-graph GCN."""

    data_npz: Optional[str] = None
    synthetic_scale: float = 0.001  # fraction of papers100M (111M nodes)
    hidden: int = 256
    num_layers: int = 3
    lr: float = 1e-3
    epochs: int = 10
    world_size: int = 0
    bfloat16: bool = True
    remat: bool = True
    # partition/plan knobs — keep in lock-step with setup_comms.py (both
    # feed the plan-cache fingerprint; a mismatch silently misses the
    # offline-built cache and repeats the hours-long build)
    partition_method: str = "greedy_bfs"
    pad_multiple: int = 128
    plan_cache: str = "cache/plans"  # "" disables the on-disk plan cache
    log_path: str = "logs/papers100m.jsonl"
    # per-step obs records (grad-norm costs one global_norm at this scale,
    # so it is opt-in on the billion-edge path)
    step_metrics: bool = False
    # Build the partition + comm plan and stop (no features, no training).
    # The full-scale proof mode (VERDICT r1 #3): at synthetic_scale=1.0
    # (111M nodes / 1.6B edges) the features alone are 57 GB, but the plan
    # build is the scaling-critical artifact — this measures its wall time
    # and peak RSS the way the reference's offline per-rank plan precompute
    # would be measured (MAG240M_dataset.py:237-260).
    # NOTE: at synthetic_scale=1.0 prefer scripts/p100m_r5.sh — the
    # single-process flow stacks the edge list, sample, and plan
    # transients in one address space (OOM-killed at 130.7 GB on a 125 GB
    # host); the staged pipeline keeps each phase's peak standalone.
    plan_only: bool = False


def _peak_rss_gb() -> float:
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


class _HostLog:
    """Append-JSONL writer that never touches JAX (ExperimentLog's
    is-lead check calls jax.process_index(), which initializes the
    accelerator backend — exactly what the offline plan-only flow must
    avoid on a wedged tunnel)."""

    def __init__(self, path: str):
        import os

        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self.path = path

    def write(self, rec: dict) -> None:
        import json

        with open(self.path, "a") as f:
            f.write(json.dumps(rec) + "\n")


def _plan_only(cfg: Config, world: int) -> None:
    """Partition + plan build only, with wall-time and peak-RSS telemetry.
    Memory discipline matters more than style here: references to the raw
    edge list are dropped as soon as the renumbered copy exists (each
    [2, E] int64 array is 26 GB at full papers100M scale)."""
    import gc

    import numpy as np

    log = _HostLog(cfg.log_path)
    from dgraph_tpu.obs import startup_record

    # snapshot_backend=False: this host-only flow must NEVER dial the
    # accelerator (a wedged tunnel must not block an offline plan build)
    log.write(startup_record(
        "experiments.papers100m_gcn.plan_only", snapshot_backend=False))

    from dgraph_tpu import partition as pt
    from dgraph_tpu.data.synthetic import power_law_graph
    from dgraph_tpu.plan import plan_memory_usage
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    V = max(int(111_059_956 * cfg.synthetic_scale), 10_000)
    t0 = time.perf_counter()
    edge_index = power_law_graph(V, 14.5)
    t_gen = time.perf_counter() - t0
    E = int(edge_index.shape[1])
    log.write({"phase": "generate", "nodes": V, "edges": E,
               "wall_s": round(t_gen, 1), "peak_rss_gb": round(_peak_rss_gb(), 1)})

    t0 = time.perf_counter()
    new_edges, ren = pt.partition_graph(
        edge_index, V, world, method=cfg.partition_method
    )
    del edge_index
    gc.collect()
    t_part = time.perf_counter() - t0
    # directed edge cut on the renumbered list (native O(E) streaming count
    # when built; the VERDICT r4 #6 quality gate is cut <= 0.76)
    from dgraph_tpu import native as _native

    if _native.available():
        cut = _native.edge_cut_count(new_edges, ren.partition) / max(E, 1)
    else:
        cut = pt.edge_cut(new_edges, ren.partition)
    log.write({"phase": "partition", "method": cfg.partition_method,
               "wall_s": round(t_part, 1), "cut": round(float(cut), 4),
               "peak_rss_gb": round(_peak_rss_gb(), 1)})

    t0 = time.perf_counter()
    plan_np, layout = cached_edge_plan(
        cfg.plan_cache, new_edges, ren.partition, world_size=world,
        pad_multiple=cfg.pad_multiple,
    )
    t_plan = time.perf_counter() - t0
    mem = plan_memory_usage(plan_np, feature_dim=128)
    log.write({
        "phase": "plan_build", "wall_s": round(t_plan, 1),
        "peak_rss_gb": round(_peak_rss_gb(), 1),
        "e_pad": int(plan_np.e_pad), "s_pad": int(plan_np.halo.s_pad),
        "halo_pairs": int(layout.halo_counts.sum()),
        # unique (needer, vertex) pairs per edge — a DEDUPED halo-volume
        # measure (hub endpoints collapse), not the raw cross-edge fraction
        "halo_pair_fraction": round(
            float(layout.halo_counts.sum()) / max(E, 1), 4),
        "plan_bytes": {k: int(v) for k, v in mem.items()},
    })
    print(f"plan_only done: E={E} partition {t_part:.0f}s + plan {t_plan:.0f}s, "
          f"peak RSS {_peak_rss_gb():.1f} GB")


def main(cfg: Config):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu import partition as pt
    from dgraph_tpu.data import memmap as mm
    from dgraph_tpu.train.checkpoint import cached_edge_plan
    from dgraph_tpu.models import GCN
    from dgraph_tpu.train.loop import init_params, make_train_step
    from dgraph_tpu.utils import ExperimentLog, TimingReport

    if cfg.plan_only:
        # host-only flow: never touch the accelerator backend (a wedged
        # tunnel must not block an offline plan build); world_size required
        if cfg.data_npz:
            raise SystemExit(
                "--plan_only works on the synthetic generator; for offline "
                "plan builds from real exports use experiments/setup_comms.py"
            )
        if not cfg.world_size:
            raise SystemExit("--plan_only requires an explicit --world_size")
        _plan_only(cfg, cfg.world_size)
        return

    from dgraph_tpu.obs import plan_footprint, startup_record
    from dgraph_tpu.obs.metrics import step_record

    world = cfg.world_size or len(jax.devices())
    mesh = make_graph_mesh(ranks_per_graph=world)
    comm = Communicator.init_process_group("tpu", world_size=world)
    log = ExperimentLog(cfg.log_path)
    log.write(startup_record("experiments.papers100m_gcn"))

    if cfg.data_npz:
        import os

        if os.path.isdir(cfg.data_npz):
            # directory of .npy files: true memmaps, nothing loaded eagerly
            z = mm.open_memmap_dataset(
                cfg.data_npz,
                names=("edge_index", "features", "labels", "train_mask"),
            )
        else:
            z = np.load(cfg.data_npz)  # .npz archive (eager)
        edge_index, feats = z["edge_index"], z["features"]
        labels = np.asarray(z["labels"]).squeeze()
        train_mask = z["train_mask"]
        # OGB papers100M labels are float with NaN on the ~98% unlabeled
        # nodes; map NaN -> class 0 (loss-masked by train_mask anyway).
        if np.issubdtype(labels.dtype, np.floating):
            labels = np.where(np.isnan(labels), 0, labels)
        labels = labels.astype(np.int64)
        C = int(labels.max()) + 1
    else:
        from dgraph_tpu.data.synthetic import power_law_graph

        V = max(int(111_059_956 * cfg.synthetic_scale), 10_000)
        F, C = 128, 172
        rng = np.random.default_rng(0)
        edge_index = power_law_graph(V, 14.5)  # papers100M avg degree ~14.5
        feats = rng.normal(size=(V, F)).astype(np.float32)
        labels = rng.integers(0, C, V).astype(np.int32)
        train_mask = rng.random(V) < 0.01
        log.write({"synthetic_nodes": V, "edges": int(edge_index.shape[1])})

    V = feats.shape[0]
    TimingReport.start("partition")
    new_edges, ren = pt.partition_graph(
        edge_index, V, world, method=cfg.partition_method
    )
    TimingReport.stop("partition")

    TimingReport.start("plan_build")
    plan_np, layout = cached_edge_plan(
        cfg.plan_cache, new_edges, ren.partition, world_size=world,
        pad_multiple=cfg.pad_multiple,
    )
    TimingReport.stop("plan_build")
    n_pad = plan_np.n_src_pad
    # static comm accounting at the training dtype/width before sharding
    log.write({
        "kind": "plan_footprint",
        **plan_footprint(
            plan_np,
            "bfloat16" if cfg.bfloat16 else "float32",
            feat_dim=int(feats.shape[1]),
        ),
    })

    TimingReport.start("shard_data")
    # blocks stream from the (possibly memmapped) source straight onto the
    # mesh, one device's rows at a time — neither feats[ren.inv] nor the
    # stacked [W, n_pad, F] copy ever exists host-side (~57 GB at real
    # papers100M scale); multi-controller hosts materialize only their own
    # devices' blocks
    x = mm.shard_rows_to_device(
        feats, ren.inv, ren.offsets, n_pad, mesh, dtype=np.float32
    )
    y = mm.shard_rows_to_device(
        labels, ren.inv, ren.offsets, n_pad, mesh, dtype=np.int32
    )
    m = mm.shard_rows_to_device(
        train_mask, ren.inv, ren.offsets, n_pad, mesh, dtype=np.float32
    )
    TimingReport.stop("shard_data")

    dtype = jnp.bfloat16 if cfg.bfloat16 else None
    if cfg.remat:
        import flax.linen as nn

        cls = nn.remat(GCN)
    else:
        cls = GCN
    model = cls(cfg.hidden, C, comm=comm, num_layers=cfg.num_layers, dtype=dtype)

    plan = jax.tree.map(jnp.asarray, plan_np)
    batch = {"x": x, "y": y, "mask": m}
    params = init_params(model, mesh, plan, batch)
    optimizer = optax.adam(cfg.lr)
    opt_state = optimizer.init(params)
    step = make_train_step(
        model, optimizer, mesh, plan, step_metrics=cfg.step_metrics
    )

    with jax.set_mesh(mesh):
        times = []
        for epoch in range(cfg.epochs):
            t0 = time.perf_counter()
            params, opt_state, metrics = step(params, opt_state, batch, plan)
            jax.block_until_ready(metrics["loss"])
            dt = time.perf_counter() - t0
            times.append(dt)
            rec = step_record(metrics, step=epoch, epoch_s=round(dt, 3))
            rec["epoch"] = epoch  # legacy key, kept for plot scripts
            log.write(rec)
    log.write(
        {
            "avg_epoch_s_excl_first": round(float(np.mean(times[1:])), 3) if len(times) > 1 else None,
            "timing": TimingReport.report(),
        }
    )


if __name__ == "__main__":
    import os as _os, sys as _sys

    # direct-invocation support (repo not pip-installed): put the repo
    # root on sys.path so `python experiments/<script>.py` works
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
