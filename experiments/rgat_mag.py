"""Heterogeneous RGAT training (the reference's ``experiments/OGB-LSC``:
RGAT on MAG240M or a degree-calibrated synthetic MAG-like graph).

The real MAG240M requires the ogb.lsc package + a 1.4TB download; like the
reference's ``SyntheticHeterogeneousDataset`` fallback
(``lsc_datasets/synthetic_dataset.py``), the default here is the synthetic
generator with the same relation structure (3 node types, 5 relations).
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class Config:
    """RGAT paper-classification training."""

    # MAG240M memmap directory (prepare_mag240m_memmap /
    # synthetic_mag240m_memmap layout); overrides the in-memory generator
    memmap_dir: str = ""
    num_papers: int = 5000
    num_authors: int = 3000
    num_institutions: int = 300
    feat_dim: int = 64
    num_classes: int = 8
    hidden: int = 64
    num_layers: int = 2
    num_heads: int = 2
    batch_norm: bool = True
    bn_recompute: bool = False  # remat the BN normalization in backward
    lr: float = 3e-3
    epochs: int = 60
    world_size: int = 0
    # 'multilevel' = union-graph locality partitioning (halo volume shrinks
    # with community structure); 'random' = the worst case
    partition_method: str = "multilevel"
    plan_cache: str = "cache/plans_rgat"  # "" disables
    log_path: str = "logs/rgat_mag.jsonl"
    # thread grad-norm through the jitted step + emit obs step records;
    # build-time flag (False = byte-identical un-instrumented step)
    step_metrics: bool = False


def main(cfg: Config):
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu import compat as _compat
    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
    from dgraph_tpu.data.hetero import DistributedHeteroGraph, synthetic_mag
    from dgraph_tpu.models import RGAT
    from dgraph_tpu.obs import startup_record
    from dgraph_tpu.obs.metrics import StepMetrics
    from dgraph_tpu.utils import ExperimentLog

    world = cfg.world_size or len(jax.devices())
    mesh = make_graph_mesh(ranks_per_graph=world)
    comm = Communicator.init_process_group("tpu", world_size=world)

    from dgraph_tpu.plan import plan_efficiency

    if cfg.memmap_dir:
        from dgraph_tpu.data.mag240m import load_mag240m_memmap

        nf, rels, labels, masks, meta = load_mag240m_memmap(cfg.memmap_dir)
        num_classes = meta["num_classes"]
    else:
        nf, rels, labels, masks = synthetic_mag(
            cfg.num_papers, cfg.num_authors, cfg.num_institutions,
            cfg.feat_dim, cfg.num_classes,
        )
        num_classes = cfg.num_classes
    t0 = time.perf_counter()
    g = DistributedHeteroGraph.from_global(
        nf, rels, world, labels=labels, masks=masks,
        partition_method=cfg.partition_method,
        plan_cache=cfg.plan_cache or None,
    )
    log = ExperimentLog(cfg.log_path)
    log.write(startup_record("experiments.rgat_mag"))
    # per-relation padding-efficiency + halo-volume telemetry (VERDICT r1
    # #7/#8): the numbers that decide all_to_all vs ppermute and quantify
    # what the locality partition bought
    for key, plan_r in g.plans.items():
        eff = plan_efficiency(plan_r, g.layouts[key])
        log.write({
            "relation": "-".join(key),
            "partition": cfg.partition_method,
            "halo_pairs": int(g.layouts[key].halo_counts.sum()),
            **{k: (round(v, 4) if isinstance(v, float) else v) for k, v in eff.items()},
        })
    log.write({"plan_build_s": round(time.perf_counter() - t0, 1)})

    model = RGAT(
        hidden_features=cfg.hidden,
        out_features=num_classes,
        comm=comm,
        relations=list(g.plans),
        num_layers=cfg.num_layers,
        num_heads=cfg.num_heads,
        use_batch_norm=cfg.batch_norm,
        bn_recompute=cfg.bn_recompute,
    )

    feats = {t: jnp.asarray(v) for t, v in g.features.items()}
    plans = {k: jax.tree.map(jnp.asarray, p) for k, p in g.plans.items()}
    vmasks = {t: jnp.asarray(v) for t, v in g.vertex_masks.items()}
    y = jnp.asarray(g.labels["paper"])
    mask = jnp.asarray(g.masks[("paper", "train")])

    feat_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), feats)
    plan_specs = {k: plan_in_specs(p) for k, p in plans.items()}
    vm_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), vmasks)

    def unshard(tree):
        feats_, plans_, vmasks_ = tree
        return (
            {t: v[0] for t, v in feats_.items()},
            {k: squeeze_plan(p) for k, p in plans_.items()},
            {t: v[0] for t, v in vmasks_.items()},
        )

    def init_body(feats_, plans_, vmasks_):
        f, p, v = unshard((feats_, plans_, vmasks_))
        return model.init(jax.random.key(0), f, p, v, train=False)

    with jax.set_mesh(mesh):
        variables = jax.jit(
            jax.shard_map(
                init_body,
                mesh=mesh,
                in_specs=(feat_specs, plan_specs, vm_specs),
                out_specs=P(),
            )
        )(feats, plans, vmasks)

    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt = optax.adam(cfg.lr)
    opt_state = opt.init(params)

    def train_body(params, batch_stats, feats_, plans_, vmasks_, y_, m_):
        f, p, v = unshard((feats_, plans_, vmasks_))
        yy, mm = y_[0], m_[0]

        def lf(pp):
            out, mut = model.apply(
                {"params": pp, "batch_stats": batch_stats},
                f, p, v, train=True, mutable=["batch_stats"],
            )
            logp = jax.nn.log_softmax(out)
            ll = jnp.take_along_axis(logp, yy[:, None], axis=1)[:, 0]
            cnt = jax.lax.psum(mm.sum(), GRAPH_AXIS)
            loss = -(ll * mm).sum() / jnp.maximum(cnt, 1.0)
            correct = ((jnp.argmax(out, -1) == yy) * mm).sum()
            return loss, (mut.get("batch_stats", {}), correct, cnt)

        (loss, (new_bs, correct, cnt)), grads = jax.value_and_grad(lf, has_aux=True)(params)
        # jax<0.6: in-body grads of replicated params need the explicit
        # graph-axis psum (no-op on 0.6+, where vma tracking inserts it)
        grads = _compat.sync_inbody_grads(grads, (GRAPH_AXIS,))
        acc = jax.lax.psum(correct, GRAPH_AXIS) / jnp.maximum(cnt, 1.0)
        return jax.lax.psum(loss, GRAPH_AXIS), acc, grads, new_bs

    body = jax.shard_map(
        train_body,
        mesh=mesh,
        in_specs=(P(), P(), feat_specs, plan_specs, vm_specs, P(GRAPH_AXIS), P(GRAPH_AXIS)),
        out_specs=(P(), P(), P(), P()),
    )

    @jax.jit
    def step(params, batch_stats, opt_state):
        loss, acc, grads, new_bs = body(params, batch_stats, feats, plans, vmasks, y, mask)
        # build-time flag: False traces the exact un-instrumented step
        gn = optax.global_norm(grads) if cfg.step_metrics else None
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_bs, opt_state, StepMetrics(loss=loss, accuracy=acc, grad_norm=gn)

    with jax.set_mesh(mesh):
        for epoch in range(cfg.epochs):
            t0 = time.perf_counter()
            params, batch_stats, opt_state, sm = step(params, batch_stats, opt_state)
            jax.block_until_ready(sm.loss)
            if epoch % 10 == 0 or epoch == cfg.epochs - 1:
                rec = sm.record(
                    step=epoch,
                    epoch_ms=round((time.perf_counter() - t0) * 1000, 2),
                )
                rec["epoch"] = epoch  # legacy key, kept for plot scripts
                log.write(rec)


if __name__ == "__main__":
    import os as _os, sys as _sys

    # direct-invocation support (repo not pip-installed): put the repo
    # root on sys.path so `python experiments/<script>.py` works
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
