"""Plot benchmark/training artifacts.

Reference parity: ``experiments/Benchmarks/generate_plots.py`` (mean+-std
latency bars from .npy dumps) and ``experiments/OGB/plot_timing_reports.py``
(stacked phase bars) / ``utils.py:33-49`` (mean+-std training trajectories).
"""

from __future__ import annotations

import dataclasses
import glob
import json
import os


@dataclasses.dataclass
class Config:
    """Render plots from logs/ artifacts."""

    log_dir: str = "logs"
    out_dir: str = "logs/plots"


def main(cfg: Config):
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt
    import numpy as np

    os.makedirs(cfg.out_dir, exist_ok=True)

    # --- comm benchmark latency bars ---
    npys = sorted(glob.glob(os.path.join(cfg.log_dir, "comm_bench_*_times.npy")))
    if npys:
        names, means, stds = [], [], []
        for p in npys:
            t = np.load(p)
            names.append(os.path.basename(p).replace("comm_bench_", "").replace("_times.npy", ""))
            means.append(t.mean())
            stds.append(t.std())
        fig, ax = plt.subplots(figsize=(6, 4))
        ax.bar(names, means, yerr=stds, capsize=4)
        ax.set_ylabel("latency (ms)")
        ax.set_title("distributed gather/scatter latency (mean ± std)")
        fig.tight_layout()
        fig.savefig(os.path.join(cfg.out_dir, "comm_latency.png"), dpi=120)
        print(f"wrote {cfg.out_dir}/comm_latency.png")

    # --- training trajectories from JSONL logs ---
    for log in sorted(glob.glob(os.path.join(cfg.log_dir, "*.jsonl"))):
        rows = []
        for line in open(log):
            if line.startswith("{"):
                try:
                    rows.append(json.loads(line))
                except json.JSONDecodeError:
                    pass
        xs = [r.get("epoch", r.get("step")) for r in rows if "loss" in r]
        ys = [r["loss"] for r in rows if "loss" in r]
        if len(ys) >= 2 and all(x is not None for x in xs):
            fig, ax = plt.subplots(figsize=(6, 4))
            ax.plot(xs, ys)
            ax.set_xlabel("epoch/step")
            ax.set_ylabel("loss")
            ax.set_title(os.path.basename(log))
            fig.tight_layout()
            name = os.path.basename(log).replace(".jsonl", "") + "_loss.png"
            fig.savefig(os.path.join(cfg.out_dir, name), dpi=120)
            print(f"wrote {cfg.out_dir}/{name}")


if __name__ == "__main__":
    import os as _os, sys as _sys

    # direct-invocation support (repo not pip-installed): put the repo
    # root on sys.path so `python experiments/<script>.py` works
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
