"""Offline comm-plan build + cache (run once before training at scale).

Reference parity: ``experiments/OGB-LSC/setup_dataset_comms.py`` — the
reference builds per-relation comm plans offline because the MAG240M build
takes hours, then training loads them from disk
(``distributed_graph_dataset.py:399-422``). Same flow here: partition the
graph, build the padded EdgePlan, validate it, print the memory accounting
(``_NCCLCommPlan.py:68-100`` analogue), and leave it in the hash-keyed
cache that ``experiments/papers100m_gcn.py`` / ``ogb_gcn.py`` hit on their
first step.

Input: ``--data`` as an ``.npz`` archive or a directory of ``.npy`` memmaps
(``edge_index`` required); or ``--synthetic_nodes N`` to pre-generate a
papers100M-shaped on-disk dataset via ``data.memmap.synthetic_papers_like``.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Optional


@dataclasses.dataclass
class Config:
    """Offline partition + comm-plan build with on-disk caching."""

    data: Optional[str] = None  # .npz or memmap directory
    synthetic_nodes: int = 0  # generate an on-disk synthetic first
    synthetic_out: str = "cache/synthetic_papers"
    world_size: int = 8
    partition_method: str = "greedy_bfs"
    pad_multiple: int = 128
    feature_dim: int = 128  # for the memory report only
    plan_cache: str = "cache/plans"


def main(cfg: Config):
    import numpy as np

    from dgraph_tpu import partition as pt
    from dgraph_tpu.data.memmap import open_memmap_dataset, synthetic_papers_like
    from dgraph_tpu.plan import plan_memory_usage, validate_plan
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    if cfg.synthetic_nodes:
        print(f"generating on-disk synthetic ({cfg.synthetic_nodes} nodes)...")
        cfg.data = synthetic_papers_like(cfg.synthetic_out, cfg.synthetic_nodes)
    if not cfg.data:
        raise SystemExit("need --data <npz|dir> or --synthetic_nodes N")

    import os

    if os.path.isdir(cfg.data):
        z = open_memmap_dataset(cfg.data, names=["edge_index"])
        feat_path = os.path.join(cfg.data, "features.npy")
        if os.path.exists(feat_path):
            z["features"] = np.load(feat_path, mmap_mode="r")
    else:
        z = np.load(cfg.data)
    edge_index = np.asarray(z["edge_index"])
    # V must match what training uses (feature row count, which can exceed
    # max edge endpoint when top-id vertices are isolated) or the plan-cache
    # fingerprints diverge and the offline build is silently wasted.
    def _num_feature_rows(z):
        if isinstance(z, dict):
            return int(z["features"].shape[0]) if "features" in z else None
        if "features" not in z.files:
            return None
        # .npz: read just the member's .npy header — z["features"] would
        # decompress the whole (papers100M-scale) array to learn its shape
        with z.zip.open("features.npy") as f:
            version = np.lib.format.read_magic(f)
            shape, _, _ = np.lib.format._read_array_header(f, version)
        return int(shape[0])

    n_rows = _num_feature_rows(z)
    V = n_rows if n_rows is not None else int(edge_index.max()) + 1

    t0 = time.perf_counter()
    new_edges, ren = pt.partition_graph(
        edge_index, V, cfg.world_size, method=cfg.partition_method
    )
    t_part = time.perf_counter() - t0
    cut = pt.edge_cut(edge_index, ren.partition[ren.perm])

    t0 = time.perf_counter()
    plan, layout = cached_edge_plan(
        cfg.plan_cache,
        new_edges,
        ren.partition,
        world_size=cfg.world_size,
        pad_multiple=cfg.pad_multiple,
    )
    t_plan = time.perf_counter() - t0
    validate_plan(plan)

    report = {
        "nodes": V,
        "edges": int(edge_index.shape[1]),
        "world_size": cfg.world_size,
        "partition_method": cfg.partition_method,
        "edge_cut_frac": round(cut, 4),
        "partition_s": round(t_part, 2),
        "plan_build_s": round(t_plan, 2),
        "plan_cache": cfg.plan_cache,
        "memory": plan_memory_usage(plan, cfg.feature_dim),
    }
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    import os as _os, sys as _sys

    # direct-invocation support (repo not pip-installed): put the repo
    # root on sys.path so `python experiments/<script>.py` works
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
