"""GraphCast training (the reference's
``experiments/GraphCast/train_graphcast.py``): distributed mesh GNN on
synthetic ERA5-like weather, 3-phase LR schedule, checkpointing, and a
``--microbenchmark`` mode timing comm-vs-compute per block
(``microbenchmark_graphcast.py`` parity).
"""

from __future__ import annotations

import dataclasses
import time


@dataclasses.dataclass
class Config:
    """Distributed GraphCast training on synthetic weather."""

    mesh_level: int = 4
    num_lat: int = 181  # 1-degree grid default; 721 = ERA5 0.25-degree
    num_lon: int = 360
    channels: int = 73
    latent: int = 128
    processor_layers: int = 4
    peak_lr: float = 1e-3
    warmup_steps: int = 100
    decay_steps: int = 10_000
    steps: int = 200
    world_size: int = 0
    ckpt_dir: str = ""
    save_freq: int = 100
    microbenchmark: bool = False
    # GraphCast evaluates with Polyak-averaged weights (train/ema.py);
    # 0 disables the EMA track entirely
    ema_decay: float = 0.999
    # >0: after training, run an autoregressive rollout of this many steps
    # against the dataset's true trajectory (raw AND ema weights) and log
    # per-step RMSE — GraphCast's eval protocol (models.graphcast.rollout)
    eval_rollout: int = 0
    log_path: str = "logs/graphcast.jsonl"
    # elastic knobs (train/elastic.py): SIGTERM/SIGINT triggers a final
    # checkpoint + clean exit; a >0 deadline arms the per-step wedge
    # watchdog (exit 17 = restart+resume me)
    step_deadline_s: float = 0.0
    # thread grad-norm through the jitted step and emit obs step records;
    # build-time flag: False keeps the step byte-identical to before
    step_metrics: bool = False


def main(cfg: Config):
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu import compat as _compat
    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
    from dgraph_tpu.data.weather import SyntheticWeatherDataset
    from dgraph_tpu.obs import startup_record
    from dgraph_tpu.obs.metrics import StepMetrics
    from dgraph_tpu.models.graphcast import GraphCast, build_graphcast_graphs
    from dgraph_tpu.train.checkpoint import (
        checkpoint_keys, restore_checkpoint, save_checkpoint)
    from dgraph_tpu.train.ema import ema_init, ema_update
    from dgraph_tpu.train.schedules import graphcast_three_phase
    from dgraph_tpu.utils import ExperimentLog, TimingReport

    world = cfg.world_size or len(jax.devices())
    mesh = make_graph_mesh(ranks_per_graph=world)
    comm = Communicator.init_process_group("tpu", world_size=world)
    log = ExperimentLog(cfg.log_path)
    log.write(startup_record("experiments.graphcast_train"))

    TimingReport.start("graph_build")
    graphs = build_graphcast_graphs(cfg.mesh_level, cfg.num_lat, cfg.num_lon, world)
    TimingReport.stop("graph_build")
    ds = SyntheticWeatherDataset(graphs, cfg.num_lat, cfg.num_lon, cfg.channels)

    model = GraphCast(
        comm=comm,
        latent=cfg.latent,
        processor_layers=cfg.processor_layers,
        out_channels=cfg.channels,
    )

    statics = {
        "grid_node_static": jnp.asarray(graphs.grid_node_static),
        "mesh_node_static": jnp.asarray(graphs.mesh_node_static),
        "mesh_edge_static": jnp.asarray(graphs.mesh_edge_static),
        "g2m_edge_static": jnp.asarray(graphs.g2m_edge_static),
        "m2g_edge_static": jnp.asarray(graphs.m2g_edge_static),
    }
    plans = {
        "mesh": jax.tree.map(jnp.asarray, graphs.mesh_plan),
        "g2m": jax.tree.map(jnp.asarray, graphs.g2m_plan),
        "m2g": jax.tree.map(jnp.asarray, graphs.m2g_plan),
    }
    gmask = jnp.asarray(graphs.grid_mask)
    st_specs = {k: P(GRAPH_AXIS) for k in statics}
    pl_specs = {k: plan_in_specs(p) for k, p in plans.items()}

    def init_body(x, statics_, plans_):
        return model.init(
            jax.random.key(0),
            x[0],
            {k: v[0] for k, v in statics_.items()},
            {k: squeeze_plan(p) for k, p in plans_.items()},
        )

    x0, _ = ds.get_sharded(0)
    with jax.set_mesh(mesh):
        params = jax.jit(
            jax.shard_map(
                init_body,
                mesh=mesh,
                in_specs=(P(GRAPH_AXIS), st_specs, pl_specs),
                out_specs=P(),
            )
        )(jnp.asarray(x0), statics, plans)

    schedule = graphcast_three_phase(cfg.peak_lr, cfg.warmup_steps, cfg.decay_steps)
    opt = optax.adamw(schedule, weight_decay=0.1)
    opt_state = opt.init(params)
    ema = ema_init(params) if cfg.ema_decay > 0 else None
    step_idx = 0
    if cfg.ckpt_dir:
        base = {"params": params, "opt_state": opt_state, "step": 0}
        with_ema = dict(base, ema=ema if ema is not None else ema_init(params))
        # pick the template from what the checkpoint ACTUALLY contains
        # (ema track present or not) instead of try/except-ing a mismatch —
        # genuine corruption/IO errors now propagate with their original
        # traceback (ADVICE r3 #5). A pre-EMA checkpoint under an EMA run
        # restarts the track from the restored params; an EMA-bearing
        # checkpoint under ema_decay=0 drops the track.
        keys = checkpoint_keys(cfg.ckpt_dir)
        if keys is not None:
            ckpt_has_ema = "ema" in keys
            restored = restore_checkpoint(
                cfg.ckpt_dir, with_ema if ckpt_has_ema else base)
        else:
            # metadata unreadable (older orbax layout / partially synced
            # dir) but a checkpoint may still exist: fall back to the
            # two-template probe. A template mismatch is the ONLY error
            # retried; corruption/IO errors propagate from the retry.
            ckpt_has_ema = ema is not None
            try:
                restored = restore_checkpoint(
                    cfg.ckpt_dir, with_ema if ckpt_has_ema else base)
            except Exception:
                ckpt_has_ema = not ckpt_has_ema
                restored = restore_checkpoint(
                    cfg.ckpt_dir, with_ema if ckpt_has_ema else base)
        if restored:
            if ema is not None and not ckpt_has_ema:
                restored["ema"] = ema_init(restored["params"])
            elif ema is None:
                restored.pop("ema", None)
            params, opt_state, step_idx = (
                restored["params"],
                restored["opt_state"],
                int(restored["step"]),
            )
            ema = restored.get("ema", ema)
            log.write({"resumed_at_step": step_idx})

    def train_body(params, x, y, mask, statics_, plans_):
        x_, y_, m_ = x[0], y[0], mask[0]
        st = {k: v[0] for k, v in statics_.items()}
        pln = {k: squeeze_plan(p) for k, p in plans_.items()}

        def lf(p):
            pred = model.apply(p, x_, st, pln)
            se = ((pred - y_) ** 2).sum(-1) * m_
            cnt = jax.lax.psum(m_.sum(), GRAPH_AXIS)
            return se.sum() / jnp.maximum(cnt, 1.0)

        loss, grads = jax.value_and_grad(lf)(params)
        # jax<0.6: in-body grads of replicated params need the explicit
        # graph-axis psum (no-op on 0.6+, where vma tracking inserts it)
        grads = _compat.sync_inbody_grads(grads, (GRAPH_AXIS,))
        return jax.lax.psum(loss, GRAPH_AXIS), grads

    body = jax.shard_map(
        train_body,
        mesh=mesh,
        in_specs=(P(), P(GRAPH_AXIS), P(GRAPH_AXIS), P(GRAPH_AXIS), st_specs, pl_specs),
        out_specs=(P(), P()),
    )

    @jax.jit
    def step(params, opt_state, ema, x, y):
        loss, grads = body(params, x, y, gmask, statics, plans)
        # build-time flag: the default (False) step is byte-identical to
        # the un-instrumented program — no overhead, no extra recompiles
        gn = optax.global_norm(grads) if cfg.step_metrics else None
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if ema is not None:  # trace-time constant (pytree vs None)
            ema = ema_update(ema, params, cfg.ema_decay)
        return params, opt_state, ema, StepMetrics(loss=loss, grad_norm=gn)

    if cfg.microbenchmark:
        _microbenchmark(model, params, statics, plans, mesh, comm, ds, log)
        return

    import contextlib

    from dgraph_tpu.train.elastic import PreemptionGuard, StepWatchdog

    # hand-rolled rather than run_elastic(): this loop owns per-step data
    # feeding (ds.get_sharded) and custom logging; the elastic pieces used
    # are the same objects, incl. watchdog suspension around saves
    guard = PreemptionGuard()
    dog = StepWatchdog(cfg.step_deadline_s) if cfg.step_deadline_s > 0 else None
    try:
        with jax.set_mesh(mesh):
            while step_idx < cfg.steps:
                x, y = ds.get_sharded(step_idx)
                t0 = time.perf_counter()
                params, opt_state, ema, sm = step(
                    params, opt_state, ema, jnp.asarray(x), jnp.asarray(y))
                jax.block_until_ready(sm.loss)
                if dog is not None:
                    dog.beat()
                dt = (time.perf_counter() - t0) * 1000
                step_idx += 1
                preempted = guard.should_stop()
                if step_idx % 10 == 0 or step_idx == cfg.steps or preempted:
                    log.write(sm.record(
                        step=step_idx,
                        step_ms=round(dt, 2),
                        lr=float(schedule(step_idx)),
                    ))
                if cfg.ckpt_dir and (step_idx % cfg.save_freq == 0 or preempted):
                    # a long orbax write is not a wedged device — suspend
                    # the watchdog for the duration (elastic.py:_save)
                    with (dog.suspended() if dog is not None
                          else contextlib.nullcontext()):
                        state = {"params": params, "opt_state": opt_state,
                                 "step": step_idx}
                        if ema is not None:
                            state["ema"] = ema
                        save_checkpoint(cfg.ckpt_dir, state, step_idx)
                if preempted:
                    log.write({"preempted_at_step": step_idx})
                    break
    finally:
        if dog is not None:
            dog.stop()
        guard.uninstall()

    # a preemption asked for a prompt exit — the final checkpoint is saved;
    # don't spend the grace period compiling a multi-minute rollout
    if cfg.eval_rollout > 0 and not guard.should_stop():
        from dgraph_tpu.models.graphcast import rollout as gc_rollout

        x0, truth = ds.trajectory_sharded(0, cfg.eval_rollout)

        def eval_body(p, x0_, statics_, plans_):
            st = {k: v[0] for k, v in statics_.items()}
            pln = {k: squeeze_plan(pp) for k, pp in plans_.items()}
            traj = gc_rollout(model, p, x0_[0], st, pln, cfg.eval_rollout)
            return traj[:, None]  # add the shard axis back: [T, 1, n, C]

        run_rollout = jax.jit(jax.shard_map(
            eval_body, mesh=mesh,
            in_specs=(P(), P(GRAPH_AXIS), st_specs, pl_specs),
            out_specs=P(None, GRAPH_AXIS),
        ))
        import numpy as np

        m_np = np.asarray(gmask)[None, :, :, None]  # [1, W, n, 1]
        denom = m_np.sum() * cfg.channels
        tracks = [("raw", params)] + ([("ema", ema)] if ema is not None else [])
        with jax.set_mesh(mesh):
            for label, p in tracks:
                traj = np.asarray(run_rollout(p, jnp.asarray(x0), statics, plans))
                rmse = np.sqrt(
                    ((traj - truth) ** 2 * m_np).sum(axis=(1, 2, 3)) / denom
                )
                log.write({
                    "rollout_eval": label, "steps": cfg.eval_rollout,
                    "rmse_per_step": [round(float(r), 5) for r in rmse],
                })
    log.write({"timing": TimingReport.report()})


def _microbenchmark(model, params, statics, plans, mesh, comm, ds, log):
    """Comm-vs-compute split of one MeshEdgeBlock — parity with
    ``microbenchmark_graphcast.py:63-247``."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
    from dgraph_tpu.comm import collectives

    x0, _ = ds.get_sharded(0)
    x0 = jnp.asarray(x0)
    latent = model.latent
    mesh_plan = plans["mesh"]

    def gather_only(h, plan_):
        return collectives.gather(h, squeeze_plan(plan_), "src", GRAPH_AXIS)

    def local_only(h, plan_):
        p = squeeze_plan(plan_)
        return collectives.gather(h, p, "dst", GRAPH_AXIS)  # dst side = no comm

    h = jnp.zeros((mesh_plan.src_index.shape[0], mesh_plan.n_src_pad, latent))
    for name, fn in [("comm_gather", gather_only), ("local_gather", local_only)]:
        f = jax.jit(
            jax.shard_map(
                lambda h_, p_: fn(h_[0], p_)[None],
                mesh=mesh,
                in_specs=(P(GRAPH_AXIS), plan_in_specs(mesh_plan)),
                out_specs=P(GRAPH_AXIS),
            )
        )
        with jax.set_mesh(mesh):
            out = f(h, mesh_plan)
            jax.block_until_ready(out)
            import time as _t

            times = []
            for _ in range(20):
                t0 = _t.perf_counter()
                out = f(h, mesh_plan)
                jax.block_until_ready(out)
                times.append((_t.perf_counter() - t0) * 1000)
        import numpy as np

        log.write({f"{name}_ms_mean": float(np.mean(times)), f"{name}_ms_std": float(np.std(times))})


if __name__ == "__main__":
    import os as _os, sys as _sys

    # direct-invocation support (repo not pip-installed): put the repo
    # root on sys.path so `python experiments/<script>.py` works
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
