"""Long-context LM training over a sequence-sharded mesh (ring attention).

The sequence-parallel counterpart of the graph experiment CLIs: trains
:class:`~dgraph_tpu.models.transformer.SeqTransformerLM` on a synthetic
induction corpus (second half repeats the first half, so exact causal
attention over the FULL sequence is required to get below the unigram
floor — a model whose attention were truncated to its local shard cannot
copy across the T/2 boundary once T/2 > T/W).

Every attention layer is exact ring attention over the mesh
(:mod:`dgraph_tpu.parallel.sequence`); per-device memory is O(T/W), so
sequence length scales with the mesh.

Usage:
    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        python experiments/long_context_lm.py --seq_len 2048 --steps 200
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional


@dataclasses.dataclass
class Config:
    """Sequence-parallel causal LM on synthetic induction data."""

    seq_len: int = 2048
    vocab: int = 64
    latent: int = 128
    num_layers: int = 2
    num_heads: int = 4
    steps: int = 200
    lr: float = 3e-3
    world_size: Optional[int] = None  # None = all devices
    # 'ring' (O(T/W) memory) or 'ulysses' (all-to-all head sharding; its
    # full-sequence dense stage uses the Mosaic flash kernel on TPU when
    # config.use_flash_attention allows AND the chip self-check passes)
    attn_impl: str = "ring"
    # >0: expert-parallel MoE FFN over the same axis (one expert per rank,
    # DeepSpeed-MoE axis fusion); k = experts per token
    moe_k: int = 0
    moe_aux_weight: float = 0.01
    seed: int = 0
    log_path: str = "logs/long_context_lm.jsonl"
    log_every: int = 20
    # thread grad-norm through the jitted step + emit obs step records
    # (build-time flag; False = byte-identical un-instrumented step)
    step_metrics: bool = False


def main(cfg: Config):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import Mesh, PartitionSpec as P

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.models.transformer import SeqTransformerLM
    from dgraph_tpu.obs import startup_record
    from dgraph_tpu.obs.metrics import StepMetrics
    from dgraph_tpu.utils import ExperimentLog

    W = cfg.world_size or len(jax.devices())
    T = cfg.seq_len
    if T % W or T % 2:
        raise SystemExit(
            f"seq_len {T} must be even (induction corpus halves) and divide "
            f"by world_size {W}"
        )
    mesh = Mesh(np.array(jax.devices()[:W]), ("graph",))
    comm = Communicator.init_process_group("tpu", world_size=W)
    from dgraph_tpu import config as fw_cfg
    from dgraph_tpu.parallel.sequence import flash_attention_selfcheck

    if fw_cfg.flash_attention_enabled():
        # chip veto before the kernel is trusted (Mosaic divergence is
        # invisible to CPU CI — same gate as bench.py's scatter kernels)
        fw_cfg.set_flags(use_flash_attention=flash_attention_selfcheck())
    model = SeqTransformerLM(
        vocab=cfg.vocab, latent=cfg.latent, num_layers=cfg.num_layers,
        num_heads=cfg.num_heads, max_len=T, comm=comm,
        attn_impl=cfg.attn_impl, moe_k=cfg.moe_k,
    )
    rng = np.random.default_rng(cfg.seed)
    pos = jnp.arange(T, dtype=jnp.int32)

    def batch():
        half = rng.integers(1, cfg.vocab, T // 2)
        return jnp.asarray(np.concatenate([half, half]).astype(np.int32))

    def shard_loss(params, toks, pos):
        # Score ALL T-1 next-token predictions, not just each shard's
        # local T_loc-1: every shard's last position predicts the right
        # neighbor's first token (fetched by ppermute), so the objective —
        # and the logged loss — is identical for any world size
        # (ADVICE r2 #3: W=1 vs W=8 curves must be comparable).
        aux = 0.0
        if cfg.moe_k > 0:
            logits, mut = model.apply(params, toks, pos, mutable=["losses"])
            aux = sum(jnp.sum(v) for v in jax.tree.leaves(mut))
            aux = cfg.moe_aux_weight * aux / max(cfg.num_layers, 1)
        else:
            logits = model.apply(params, toks, pos)
        left = [(i, (i - 1) % W) for i in range(W)]
        nxt = jax.lax.ppermute(toks[:1], "graph", left)
        targets = jnp.concatenate([toks[1:], nxt])
        logp = jax.nn.log_softmax(logits)
        ll = jnp.take_along_axis(logp, targets[:, None], axis=1)[:, 0]
        # the globally-last position's "target" is the wrapped-around
        # first token — mask it out
        t_loc = toks.shape[0]
        is_last = jax.lax.axis_index("graph") == W - 1
        valid = jnp.where(
            is_last, jnp.arange(t_loc) < t_loc - 1, jnp.ones(t_loc, bool)
        )
        return (
            -jax.lax.psum((ll * valid).sum(), "graph") / (T - 1) + aux
        )

    from dgraph_tpu.models.transformer import moe_param_specs

    toks0 = batch()
    # paths only (the MoE blocks trace collectives, so even shape
    # derivation must run under shard_map; out_specs=P() is fine for
    # PATH discovery — the real init below uses the derived specs)
    shapes = jax.eval_shape(
        jax.shard_map(
            lambda tk, ps: model.init(jax.random.key(cfg.seed), tk, ps),
            mesh=mesh, in_specs=(P("graph"), P("graph")), out_specs=P(),
            check_vma=False,
        ),
        toks0, pos,
    )
    pspecs = moe_param_specs(shapes)

    loss_sm = jax.shard_map(
        shard_loss, mesh=mesh,
        in_specs=(pspecs, P("graph"), P("graph")), out_specs=P(),
        check_vma=False,
    )

    with jax.set_mesh(mesh):
        params = jax.shard_map(
            lambda tk, ps: model.init(jax.random.key(cfg.seed), tk, ps),
            mesh=mesh, in_specs=(P("graph"), P("graph")), out_specs=pspecs,
            check_vma=False,
        )(toks0, pos)
        opt = optax.adam(cfg.lr)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, toks):
            l, g = jax.value_and_grad(
                lambda p, tk: loss_sm(p, tk, pos)
            )(params, toks)
            # build-time flag: False traces the exact un-instrumented step
            gn = optax.global_norm(g) if cfg.step_metrics else None
            updates, opt_state = opt.update(g, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, StepMetrics(loss=l, grad_norm=gn)

        log = ExperimentLog(cfg.log_path)
        log.write(startup_record("experiments.long_context_lm"))
        uniform = float(np.log(cfg.vocab))
        t0 = time.perf_counter()
        for i in range(cfg.steps):
            params, opt_state, sm = step(params, opt_state, batch())
            if i % cfg.log_every == 0 or i == cfg.steps - 1:
                log.write(sm.record(
                    step=i, uniform_nats=uniform, seq_len=T, world=W,
                    ms_per_step=(time.perf_counter() - t0) / (i + 1) * 1e3,
                ))


if __name__ == "__main__":
    import os as _os, sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
