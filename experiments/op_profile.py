"""Where does a GCN epoch go? Times every constituent op of the arxiv-scale
epoch on the local accelerator, in the exact form the model invokes it
(collectives layer + gradient round trips), so regressions in any one
VJP/kernel routing show up as a single line.

The epoch-level companion of ``kernel_benchmarks.py`` — that file times raw
kernels; this one times the framework ops (gather/scatter with plan
routing, sort-route VJPs) whose composition IS the training step. Mirrors
the reference's per-phase timing harness (``experiments/OGB/main.py:129-221``
prints gather/scatter/comm phase times per epoch).

Usage:
    python experiments/op_profile.py              # arxiv scale, bf16
    DGRAPH_TPU_PALLAS_SCATTER=0 python experiments/op_profile.py  # XLA-only
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from typing import Optional


@dataclasses.dataclass
class Config:
    num_nodes: int = 169_343
    num_edges_half: int = 1_166_243  # symmetrized x2
    hidden: int = 256
    dtype: str = "bfloat16"
    reps: int = 3
    n_long: int = 8
    out: Optional[str] = "logs/op_profile.jsonl"


def log(m):
    print(f"[{time.strftime('%H:%M:%S')}] {m}", file=sys.stderr, flush=True)


def main(cfg: Config):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import config as fw_cfg
    from dgraph_tpu.comm import collectives as coll
    from dgraph_tpu.ops import local as L
    from dgraph_tpu.plan import build_edge_plan

    V, H = cfg.num_nodes, cfg.hidden
    rng = np.random.default_rng(0)
    src = rng.integers(0, V, cfg.num_edges_half)
    dst = rng.integers(0, V, cfg.num_edges_half)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)
    plan_np, _ = build_edge_plan(
        edge_index, np.zeros(V, np.int32), world_size=1, edge_owner="dst",
        pad_multiple=128,
    )
    plan = jax.tree.map(lambda leaf: jnp.asarray(np.asarray(leaf)[0]), plan_np)
    jax.block_until_ready([t for t in jax.tree.leaves(plan)])
    log(f"plan: e_pad={plan_np.e_pad} n_src_pad={plan_np.n_src_pad} "
        f"scatter_mc={plan_np.scatter_mc} halo_sort_mc={plan_np.halo_sort_mc} "
        f"pallas={fw_cfg.pallas_scatter_enabled()}")

    dt = jnp.bfloat16 if cfg.dtype in ("bfloat16", "bf16") else jnp.float32
    Np, Ep = plan_np.n_src_pad, plan_np.e_pad
    x_n = jax.random.normal(jax.random.key(0), (Np, H), dt)
    x_e = jax.random.normal(jax.random.key(1), (Ep, H), dt)
    w = jax.random.normal(jax.random.key(2), (H, H), dt)
    jax.block_until_ready((x_n, x_e, w))

    from dgraph_tpu.utils.timing import timed_scan_ms

    records = []

    def timed(name, fn):
        """fn(salt) -> array; shared scan protocol (utils.timing)."""
        best = timed_scan_ms(fn, reps=cfg.reps, n_long=cfg.n_long)
        rec = {"op": name, "ms": round(best, 3) if best else None,
               "H": H, "dtype": cfg.dtype, "ts": time.time()}
        records.append(rec)
        print(json.dumps(rec))
        return best

    # Salt MUST keep a live data dependency on the scan carry — the ONE
    # hoist-proof implementation lives in utils.timing.salt_input (see its
    # docstring for the r3 `* 0`-folding incident)
    from dgraph_tpu.utils.timing import salt_input

    c = lambda salt: salt_input(jnp.zeros((), dt), salt)

    timed("matmul_NxHxH", lambda cc: (x_n + c(cc)) @ w)
    timed("gather_dst_owner", lambda cc: coll.gather(x_n + c(cc), plan, "dst", None))
    timed("gather_src_halo", lambda cc: coll.gather(x_n + c(cc), plan, "src", None))
    timed("scatter_sum_dst", lambda cc: coll.scatter_sum(x_e + c(cc), plan, "dst", None))
    timed("scatter_sum_src_halo", lambda cc: coll.scatter_sum(x_e + c(cc), plan, "src", None))

    def g_loss(xn, cc, side):
        out = coll.gather(xn + c(cc), plan, side, None)
        return (out.astype(jnp.float32) ** 2).sum()

    timed("grad_gather_dst", lambda cc: jax.grad(g_loss)(x_n, cc, "dst"))
    timed("grad_gather_src", lambda cc: jax.grad(g_loss)(x_n, cc, "src"))

    def s_loss(xe, cc, side):
        out = coll.scatter_sum(xe + c(cc), plan, side, None)
        return (out.astype(jnp.float32) ** 2).sum()

    timed("grad_scatter_dst", lambda cc: jax.grad(s_loss)(x_e, cc, "dst"))

    # the FUSED bias+relu aggregation (the op the GCN fwd actually runs).
    # UNWEIGHTED first: that is the model's path, and its backward is the
    # r4c kernel pair (chunk-major gd kernel + epilogue="act" reduction);
    # the weighted variant keeps the composed backward, so its grad row
    # measures a different program.
    # NOTE label semantics (ADVICE r4): before r4c these two rows measured
    # the WEIGHTED op; jsonl rows from r4 logs under the same names are a
    # different program. The "_unweighted" suffix makes the break explicit.
    ew = jax.random.uniform(jax.random.key(3), (Ep,), dt)
    timed("fused_scatter_bias_relu_unweighted",
          lambda cc: coll.scatter_bias_relu(
              x_e + c(cc), x_n, plan, "dst", None))

    def f_loss(xe, cc, w):
        out = coll.scatter_bias_relu(xe + c(cc), x_n, plan, "dst", None,
                                     edge_weight=w)
        return (out.astype(jnp.float32) ** 2).sum()

    timed("grad_fused_scatter_unweighted",
          lambda cc: jax.grad(f_loss)(x_e, cc, None))
    timed("fused_scatter_bias_relu_weighted",
          lambda cc: coll.scatter_bias_relu(
              x_e + c(cc), x_n, plan, "dst", None, edge_weight=ew))
    timed("grad_fused_scatter_weighted",
          lambda cc: jax.grad(f_loss)(x_e, cc, ew))

    # chunk-width variants: the models invoke every edge op through the
    # feature-chunked pipeline (<= gather_col_block wide), so the epoch is
    # composed of THESE calls, not the full-width ones above
    cw = min(fw_cfg.gather_col_block or H, H)
    if cw < H:
        x_nc, x_ec = x_n[:, :cw], x_e[:, :cw]
        timed(f"gather_src_halo_w{cw}",
              lambda cc: coll.gather(x_nc + c(cc), plan, "src", None))
        timed(f"fused_scatter_bias_relu_w{cw}",
              lambda cc: coll.scatter_bias_relu(
                  x_ec + c(cc), x_nc, plan, "dst", None))

        def fc_loss(xe, cc):
            out = coll.scatter_bias_relu(xe + c(cc), x_nc, plan, "dst",
                                         None)
            return (out.astype(jnp.float32) ** 2).sum()

        timed(f"grad_fused_scatter_w{cw}",
              lambda cc: jax.grad(fc_loss)(x_ec, cc))

    # whole-layer anchors: one GraphConvLayer forward and its grad — the
    # per-op sum above must land within ~20% of 2x these (2-layer GCN) or
    # the residual is unattributed (VERDICT r2 next #2)
    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.models.gcn import GraphConvLayer

    comm = Communicator.init_process_group("single")
    layer = GraphConvLayer(H, comm=comm, dtype=dt)
    lp = layer.init(jax.random.key(4), x_n.astype(jnp.float32), plan, ew)

    timed("conv_layer_fwd",
          lambda cc: layer.apply(lp, x_n + c(cc), plan, ew))

    def l_loss(xn, cc):
        out = layer.apply(lp, xn + c(cc), plan, ew)
        return (out.astype(jnp.float32) ** 2).sum()

    timed("grad_conv_layer", lambda cc: jax.grad(l_loss)(x_n, cc))

    # --- the decomposition ladder (VERDICT r3 #5: name the 2x residual) ---
    # The EXACT bench_gcn model/step, timed at four composition levels with
    # the same scan protocol. Sum-of-ops above vs these four numbers
    # localizes the residual: ops vs fwd -> XLA fusion/overlap differences;
    # fwd+bwd vs 3x fwd -> backward accounting; epoch vs fwd_bwd+adam ->
    # optimizer/loss cost. bench.py's epoch number must match `full_epoch`
    # here (same composition) or the harnesses disagree.
    import optax

    from dgraph_tpu.models import GCN

    F_in, C = 128, 40
    model = GCN(hidden_features=H, out_features=C, comm=comm, num_layers=2,
                dtype=dt)
    x_f = jax.random.normal(jax.random.key(5), (Np, F_in), jnp.float32)
    y_l = jax.random.randint(jax.random.key(6), (Np,), 0, C)
    vmask = (jnp.arange(Np) < V).astype(jnp.float32)
    # NO edge_weight: bench_gcn's epoch calls model.apply(p, x, plan) —
    # the ladder must be the EXACT same composition or the bench-vs-ladder
    # delta misattributes the per-edge-multiply cost
    params = model.init(jax.random.key(7), x_f, plan)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    def model_loss(p, cc):
        logits = model.apply(p, x_f + c(cc).astype(jnp.float32), plan)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32))
        ll = jnp.take_along_axis(logp, y_l[:, None], axis=1)[:, 0]
        return -(ll * vmask).sum() / jnp.maximum(vmask.sum(), 1.0)

    def consume(tree):
        # timed_scan_ms consumes ARRAY outputs; reduce pytrees to a scalar
        # that touches every leaf (sliced/dropped leaves would be DCE'd —
        # the r3 timing-integrity lesson)
        return sum(t.astype(jnp.float32).sum() for t in jax.tree.leaves(tree))

    timed("model_fwd", lambda cc: model.apply(
        params, x_f + c(cc).astype(jnp.float32), plan))
    timed("model_fwd_bwd",
          lambda cc: consume(jax.grad(model_loss)(params, cc)))

    grads0 = jax.grad(model_loss)(params, jnp.int32(0))

    def adam_step(cc):
        g = jax.tree.map(lambda t: t + c(cc).astype(t.dtype), grads0)
        updates, _ = optimizer.update(g, opt_state, params)
        return consume(optax.apply_updates(params, updates))

    timed("adam_update", adam_step)

    def full_epoch(cc):
        loss, grads = jax.value_and_grad(model_loss)(params, cc)
        updates, _ = optimizer.update(grads, opt_state, params)
        return consume(optax.apply_updates(params, updates)) + loss

    timed("full_epoch", full_epoch)

    if cfg.out:
        os.makedirs(os.path.dirname(cfg.out) or ".", exist_ok=True)
        with open(cfg.out, "a") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
