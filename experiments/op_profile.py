"""Where does a GCN epoch go? Times every constituent op of the arxiv-scale
epoch on the local accelerator, in the exact form the model invokes it
(collectives layer + gradient round trips), so regressions in any one
VJP/kernel routing show up as a single line.

The epoch-level companion of ``kernel_benchmarks.py`` — that file times raw
kernels; this one times the framework ops (gather/scatter with plan
routing, sort-route VJPs) whose composition IS the training step. Mirrors
the reference's per-phase timing harness (``experiments/OGB/main.py:129-221``
prints gather/scatter/comm phase times per epoch).

Usage:
    python experiments/op_profile.py              # arxiv scale, bf16
    DGRAPH_TPU_PALLAS_SCATTER=0 python experiments/op_profile.py  # XLA-only
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time
from typing import Optional


@dataclasses.dataclass
class Config:
    num_nodes: int = 169_343
    num_edges_half: int = 1_166_243  # symmetrized x2
    hidden: int = 256
    dtype: str = "bfloat16"
    reps: int = 3
    n_long: int = 8
    out: Optional[str] = "logs/op_profile.jsonl"


def log(m):
    print(f"[{time.strftime('%H:%M:%S')}] {m}", file=sys.stderr, flush=True)


def main(cfg: Config):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from dgraph_tpu import config as fw_cfg
    from dgraph_tpu.comm import collectives as coll
    from dgraph_tpu.ops import local as L
    from dgraph_tpu.plan import build_edge_plan

    V, H = cfg.num_nodes, cfg.hidden
    rng = np.random.default_rng(0)
    src = rng.integers(0, V, cfg.num_edges_half)
    dst = rng.integers(0, V, cfg.num_edges_half)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)
    plan_np, _ = build_edge_plan(
        edge_index, np.zeros(V, np.int32), world_size=1, edge_owner="dst",
        pad_multiple=128,
    )
    plan = jax.tree.map(lambda leaf: jnp.asarray(np.asarray(leaf)[0]), plan_np)
    jax.block_until_ready([t for t in jax.tree.leaves(plan)])
    log(f"plan: e_pad={plan_np.e_pad} n_src_pad={plan_np.n_src_pad} "
        f"scatter_mc={plan_np.scatter_mc} halo_sort_mc={plan_np.halo_sort_mc} "
        f"pallas={fw_cfg.pallas_scatter_enabled()}")

    dt = jnp.bfloat16 if cfg.dtype in ("bfloat16", "bf16") else jnp.float32
    Np, Ep = plan_np.n_src_pad, plan_np.e_pad
    x_n = jax.random.normal(jax.random.key(0), (Np, H), dt)
    x_e = jax.random.normal(jax.random.key(1), (Ep, H), dt)
    w = jax.random.normal(jax.random.key(2), (H, H), dt)
    jax.block_until_ready((x_n, x_e, w))

    from dgraph_tpu.utils.timing import timed_scan_ms

    records = []

    def timed(name, fn):
        """fn(salt) -> array; shared scan protocol (utils.timing)."""
        best = timed_scan_ms(fn, reps=cfg.reps, n_long=cfg.n_long)
        rec = {"op": name, "ms": round(best, 3) if best else None,
               "H": H, "dtype": cfg.dtype, "ts": time.time()}
        records.append(rec)
        print(json.dumps(rec))
        return best

    c = lambda salt: salt.astype(dt) * 0  # fold salt in without promotion

    timed("matmul_NxHxH", lambda cc: (x_n + c(cc)) @ w)
    timed("gather_dst_owner", lambda cc: coll.gather(x_n + c(cc), plan, "dst", None))
    timed("gather_src_halo", lambda cc: coll.gather(x_n + c(cc), plan, "src", None))
    timed("scatter_sum_dst", lambda cc: coll.scatter_sum(x_e + c(cc), plan, "dst", None))
    timed("scatter_sum_src_halo", lambda cc: coll.scatter_sum(x_e + c(cc), plan, "src", None))

    def g_loss(xn, cc, side):
        out = coll.gather(xn + c(cc), plan, side, None)
        return (out.astype(jnp.float32) ** 2).sum()

    timed("grad_gather_dst", lambda cc: jax.grad(g_loss)(x_n, cc, "dst"))
    timed("grad_gather_src", lambda cc: jax.grad(g_loss)(x_n, cc, "src"))

    def s_loss(xe, cc, side):
        out = coll.scatter_sum(xe + c(cc), plan, side, None)
        return (out.astype(jnp.float32) ** 2).sum()

    timed("grad_scatter_dst", lambda cc: jax.grad(s_loss)(x_e, cc, "dst"))

    if cfg.out:
        os.makedirs(os.path.dirname(cfg.out) or ".", exist_ok=True)
        with open(cfg.out, "a") as f:
            for r in records:
                f.write(json.dumps(r) + "\n")


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
