"""Closed-loop serving load generator: throughput/latency for the bench
JSON trajectory.

Spins up the full serving stack (``dgraph_tpu.serve``: warmed engine +
micro-batcher) over a synthetic graph, then drives it with N closed-loop
client threads (each submits a uniformly-sized random request, waits for
the result, repeats). Reports one ``kind="serve_bench"`` JSONL record:
throughput (requests and target-nodes per second), latency percentiles
(p50/p95/p99 end-to-end through the queue), batch occupancy, rejection
counts, and the recompile counter (must be 0 — a nonzero value means the
bucket ladder leaked a shape and latency numbers are compile noise).

Run (single host; CPU works — the point is trajectory, not absolute ms):
    JAX_PLATFORMS=cpu python experiments/serve_bench.py --clients 4
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time


@dataclasses.dataclass
class Config:
    """Closed-loop load generation against the serving stack."""

    # serving stack (forwarded to dgraph_tpu.serve.__main__.build_serving)
    num_nodes: int = 5000
    num_classes: int = 8
    feat_dim: int = 32
    avg_degree: float = 8.0
    model: str = "gcn"
    hidden: int = 32
    num_layers: int = 2
    world_size: int = 0
    partition: str = "random"
    min_bucket: int = 8
    max_bucket: int = 256
    growth: float = 2.0
    max_batch_size: int = 8
    max_delay_ms: float = 2.0
    max_queue_depth: int = 128
    request_timeout_s: float = 60.0
    # load
    clients: int = 4
    requests_per_client: int = 50
    min_request: int = 1
    max_request: int = 128
    seed: int = 0
    log_path: str = "logs/serve_bench.jsonl"
    # multi-tenant OPEN-LOOP mode (--tenants N): each tenant issues
    # requests on its own fixed schedule regardless of completions (open
    # loop — a flooding tenant keeps offering load while being shed, which
    # is exactly the contention a closed loop can't create). tenant_rps is
    # the OFFERED per-tenant rate (comma list, broadcast when single);
    # quota_rps the per-tenant admission quota ("" = no rate cap, queue
    # shares only). The report grows a per-tenant section with
    # p99-under-contention — the tracked isolation artifact.
    tenants: int = 0
    tenant_rps: str = "20"
    quota_rps: str = ""
    quota_burst: int = 8
    tenant_queue_share: float = 0.5
    tenant_duration_s: float = 3.0
    # per-request span export (obs.spans): one kind="span" line per
    # request in the JSONL, sharing one trace id with the report — the
    # raw material for the queue-wait/infer/pad breakdown below. Off =
    # zero tracing overhead (the disabled one-attr-read path).
    trace: bool = True


def main(cfg: Config) -> dict:
    from dgraph_tpu.utils import ExperimentLog

    if cfg.max_request > cfg.max_bucket:
        raise SystemExit(
            f"max_request {cfg.max_request} exceeds max_bucket {cfg.max_bucket}"
        )
    from dgraph_tpu.obs import spans

    log = ExperimentLog(cfg.log_path, echo=False)
    trace_id, enabled_here = None, False
    if cfg.trace and not spans.enabled():
        # per-request spans ride the same JSONL as the report (ExperimentLog
        # is a valid sink), under one trace id the report carries
        trace_id, enabled_here = spans.enable(sink=log), True
    elif spans.enabled():
        trace_id = spans.current_trace_id()
    try:
        report = _run(cfg, log, trace_id)
    finally:
        if enabled_here:
            spans.disable()  # don't leak an enabled global tracer to callers
    print(json.dumps(report))
    return report


def _run(cfg: Config, log, trace_id) -> dict:
    import numpy as np

    from dgraph_tpu.obs.health import startup_record
    from dgraph_tpu.serve.__main__ import Config as ServeConfig, build_serving
    from dgraph_tpu.serve.errors import ServeError
    from dgraph_tpu.serve.health import _STAGES, serve_health_record

    log.write(startup_record("experiments.serve_bench"))

    serve_cfg = ServeConfig(
        num_nodes=cfg.num_nodes,
        num_classes=cfg.num_classes,
        feat_dim=cfg.feat_dim,
        avg_degree=cfg.avg_degree,
        partition=cfg.partition,
        world_size=cfg.world_size,
        model=cfg.model,
        hidden=cfg.hidden,
        num_layers=cfg.num_layers,
        seed=cfg.seed,
        min_bucket=cfg.min_bucket,
        max_bucket=cfg.max_bucket,
        growth=cfg.growth,
        max_batch_size=cfg.max_batch_size,
        max_delay_ms=cfg.max_delay_ms,
        max_queue_depth=cfg.max_queue_depth,
        request_timeout_s=cfg.request_timeout_s,
    )
    if cfg.tenants > 0:
        # per-tenant admission lives in the batcher: build the ONE stack
        # with the TenantTable wired in
        from dgraph_tpu.serve.tenancy import TenantQuota, TenantTable

        quota_rps = _per_tenant(cfg.quota_rps, cfg.tenants, default=0.0)
        table = TenantTable(quotas={
            f"t{i}": TenantQuota(
                rps=quota_rps[i], burst=cfg.quota_burst,
                max_queue_share=cfg.tenant_queue_share,
            )
            for i in range(cfg.tenants)
        })
        engine, batcher, _g = build_serving(serve_cfg, tenants=table)
        log.write(engine.warmup())
        return _run_open_loop(cfg, log, trace_id, engine, batcher, table)

    engine, batcher, _g = build_serving(serve_cfg)
    log.write(engine.warmup())

    ok = [0] * cfg.clients
    rejected = [0] * cfg.clients
    nodes_served = [0] * cfg.clients

    def client(i: int) -> None:
        rng = np.random.default_rng(cfg.seed * 1000 + i)
        for _ in range(cfg.requests_per_client):
            n = int(rng.integers(cfg.min_request, cfg.max_request + 1))
            ids = rng.integers(0, engine.num_nodes, n)
            try:
                batcher.infer(ids)
                ok[i] += 1
                nodes_served[i] += n
            except ServeError as e:
                rejected[i] += 1
                log.write(e.record())

    threads = [
        threading.Thread(target=client, args=(i,), name=f"client-{i}")
        for i in range(cfg.clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    batcher.stop()

    snap = engine.registry.snapshot()
    lat = snap["histograms"].get("serve.request_ms", {"count": 0})
    occ = snap["histograms"].get("serve.batch_occupancy", {})
    # queue-wait vs infer vs pad-overhead breakdown (the per-stage
    # histograms the span instrumentation feeds): groundwork for the
    # p99-under-contention artifact — contention shows up as queue_wait
    # p99 growth while infer p99 stays flat
    q = ("count", "mean", "p50", "p95", "p99", "max")
    stages = {}
    for stage in _STAGES:
        hist = snap["histograms"].get(f"serve.stage.{stage}_ms")
        if hist and hist.get("count"):
            stages[stage] = {k: hist.get(k) for k in q}
    completed = sum(ok)
    report = {
        "kind": "serve_bench",
        # headline for the bench trajectory: completed requests per second
        "value": round(completed / wall_s, 2) if wall_s > 0 else None,
        "throughput_rps": round(completed / wall_s, 2) if wall_s > 0 else None,
        "throughput_nodes_per_s": (
            round(sum(nodes_served) / wall_s, 1) if wall_s > 0 else None
        ),
        "wall_s": round(wall_s, 3),
        "clients": cfg.clients,
        "completed": completed,
        "rejected": sum(rejected),
        "latency_ms": {
            k: lat.get(k) for k in ("count", "mean", "p50", "p95", "p99", "max")
        },
        "stages_ms": stages,
        "trace_id": trace_id,
        "batch_occupancy_mean": occ.get("mean"),
        "recompiles_since_warmup": engine.recompiles_since_warmup(),
        "buckets": [int(b) for b in engine.ladder.sizes],
        # the adopted tuning record (dgraph_tpu.tune) these throughput
        # numbers ran under, or None for the hard-coded defaults
        "tuning_record": getattr(engine, "tuning_record_id", None),
        "config": dataclasses.asdict(cfg),
    }
    log.write(report)
    log.write(serve_health_record(engine, batcher))
    return report


def _per_tenant(spec: str, n: int, default: float) -> list:
    """Parse a comma list of per-tenant floats; a single value broadcasts,
    '' yields the default for every tenant."""
    if not spec.strip():
        return [float(default)] * n
    vals = [float(v) for v in spec.split(",") if v.strip()]
    if len(vals) == 1:
        return vals * n
    if len(vals) != n:
        raise SystemExit(
            f"need 1 or {n} comma-separated values, got {len(vals)}: {spec!r}"
        )
    return vals


def _run_open_loop(cfg: Config, log, trace_id, engine, batcher, table) -> dict:
    """Open-loop multi-tenant load: every tenant offers requests on its own
    clock for ``tenant_duration_s``; completions are gathered out of band.
    Emits per-tenant p50/p95/p99-under-contention into the report JSON so
    isolation regressions (a noisy tenant inflating a quiet tenant's tail)
    become a tracked artifact."""
    import numpy as np

    from dgraph_tpu.serve.errors import ServeError
    from dgraph_tpu.serve.health import serve_health_record

    rates = _per_tenant(cfg.tenant_rps, cfg.tenants, default=20.0)
    offered = [0] * cfg.tenants
    completed = [0] * cfg.tenants
    rejected = [0] * cfg.tenants
    futures: list = [[] for _ in range(cfg.tenants)]

    def tenant_loop(i: int) -> None:
        rng = np.random.default_rng(cfg.seed * 1000 + i)
        interval = 1.0 / max(rates[i], 1e-6)
        deadline = time.monotonic() + cfg.tenant_duration_s
        next_at = time.monotonic()
        while time.monotonic() < deadline:
            now = time.monotonic()
            if now < next_at:
                time.sleep(min(next_at - now, 0.01))
                continue
            next_at += interval  # fixed schedule: OPEN loop, no backoff
            n = int(rng.integers(cfg.min_request, cfg.max_request + 1))
            ids = rng.integers(0, engine.num_nodes, n)
            offered[i] += 1
            try:
                futures[i].append(batcher.submit(ids, tenant=f"t{i}"))
            except ServeError:
                rejected[i] += 1

    threads = [
        threading.Thread(target=tenant_loop, args=(i,), name=f"tenant-{i}")
        for i in range(cfg.tenants)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for i in range(cfg.tenants):
        for f in futures[i]:
            try:
                f.result(timeout=cfg.request_timeout_s)
                completed[i] += 1
            except Exception:  # noqa: BLE001 — queued-side rejection
                rejected[i] += 1
    wall_s = time.perf_counter() - t0
    batcher.stop()

    snap = engine.registry.snapshot()
    q = ("count", "mean", "p50", "p95", "p99", "max")
    tenant_stats = {}
    table_snap = table.snapshot()
    for i in range(cfg.tenants):
        name = f"t{i}"
        hist = snap["histograms"].get(
            f"serve.tenant.{name}.request_ms", {}
        )
        tenant_stats[name] = {
            "offered_rps": rates[i],
            "offered": offered[i],
            "completed": completed[i],
            "rejected": rejected[i],
            # p99 UNDER CONTENTION: the isolation SLO — a well-isolated
            # quiet tenant keeps this flat while a noisy one floods
            "latency_ms": {k: hist.get(k) for k in q} if hist else None,
            **table_snap.get(name, {}),
        }
    total_completed = sum(completed)
    report = {
        "kind": "serve_bench",
        "mode": "multi_tenant_open_loop",
        "value": round(total_completed / wall_s, 2) if wall_s > 0 else None,
        "throughput_rps": (
            round(total_completed / wall_s, 2) if wall_s > 0 else None
        ),
        "wall_s": round(wall_s, 3),
        "tenants": tenant_stats,
        "offered": sum(offered),
        "completed": total_completed,
        "rejected": sum(rejected),
        "trace_id": trace_id,
        "recompiles_since_warmup": engine.recompiles_since_warmup(),
        "buckets": [int(b) for b in engine.ladder.sizes],
        "tuning_record": getattr(engine, "tuning_record_id", None),
        "config": dataclasses.asdict(cfg),
    }
    log.write(report)
    log.write(serve_health_record(engine, batcher))
    return report


if __name__ == "__main__":
    import os as _os, sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
