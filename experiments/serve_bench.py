"""Closed-loop serving load generator: throughput/latency for the bench
JSON trajectory.

Spins up the full serving stack (``dgraph_tpu.serve``: warmed engine +
micro-batcher) over a synthetic graph, then drives it with N closed-loop
client threads (each submits a uniformly-sized random request, waits for
the result, repeats). Reports one ``kind="serve_bench"`` JSONL record:
throughput (requests and target-nodes per second), latency percentiles
(p50/p95/p99 end-to-end through the queue), batch occupancy, rejection
counts, and the recompile counter (must be 0 — a nonzero value means the
bucket ladder leaked a shape and latency numbers are compile noise).

Run (single host; CPU works — the point is trajectory, not absolute ms):
    JAX_PLATFORMS=cpu python experiments/serve_bench.py --clients 4
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time


@dataclasses.dataclass
class Config:
    """Closed-loop load generation against the serving stack."""

    # serving stack (forwarded to dgraph_tpu.serve.__main__.build_serving)
    num_nodes: int = 5000
    num_classes: int = 8
    feat_dim: int = 32
    avg_degree: float = 8.0
    model: str = "gcn"
    hidden: int = 32
    num_layers: int = 2
    world_size: int = 0
    partition: str = "random"
    min_bucket: int = 8
    max_bucket: int = 256
    growth: float = 2.0
    max_batch_size: int = 8
    max_delay_ms: float = 2.0
    max_queue_depth: int = 128
    request_timeout_s: float = 60.0
    # load
    clients: int = 4
    requests_per_client: int = 50
    min_request: int = 1
    max_request: int = 128
    seed: int = 0
    log_path: str = "logs/serve_bench.jsonl"
    # per-request span export (obs.spans): one kind="span" line per
    # request in the JSONL, sharing one trace id with the report — the
    # raw material for the queue-wait/infer/pad breakdown below. Off =
    # zero tracing overhead (the disabled one-attr-read path).
    trace: bool = True


def main(cfg: Config) -> dict:
    from dgraph_tpu.utils import ExperimentLog

    if cfg.max_request > cfg.max_bucket:
        raise SystemExit(
            f"max_request {cfg.max_request} exceeds max_bucket {cfg.max_bucket}"
        )
    from dgraph_tpu.obs import spans

    log = ExperimentLog(cfg.log_path, echo=False)
    trace_id, enabled_here = None, False
    if cfg.trace and not spans.enabled():
        # per-request spans ride the same JSONL as the report (ExperimentLog
        # is a valid sink), under one trace id the report carries
        trace_id, enabled_here = spans.enable(sink=log), True
    elif spans.enabled():
        trace_id = spans.current_trace_id()
    try:
        report = _run(cfg, log, trace_id)
    finally:
        if enabled_here:
            spans.disable()  # don't leak an enabled global tracer to callers
    print(json.dumps(report))
    return report


def _run(cfg: Config, log, trace_id) -> dict:
    import numpy as np

    from dgraph_tpu.obs.health import startup_record
    from dgraph_tpu.serve.__main__ import Config as ServeConfig, build_serving
    from dgraph_tpu.serve.errors import ServeError
    from dgraph_tpu.serve.health import _STAGES, serve_health_record

    log.write(startup_record("experiments.serve_bench"))

    serve_cfg = ServeConfig(
        num_nodes=cfg.num_nodes,
        num_classes=cfg.num_classes,
        feat_dim=cfg.feat_dim,
        avg_degree=cfg.avg_degree,
        partition=cfg.partition,
        world_size=cfg.world_size,
        model=cfg.model,
        hidden=cfg.hidden,
        num_layers=cfg.num_layers,
        seed=cfg.seed,
        min_bucket=cfg.min_bucket,
        max_bucket=cfg.max_bucket,
        growth=cfg.growth,
        max_batch_size=cfg.max_batch_size,
        max_delay_ms=cfg.max_delay_ms,
        max_queue_depth=cfg.max_queue_depth,
        request_timeout_s=cfg.request_timeout_s,
    )
    engine, batcher, _g = build_serving(serve_cfg)
    log.write(engine.warmup())

    ok = [0] * cfg.clients
    rejected = [0] * cfg.clients
    nodes_served = [0] * cfg.clients

    def client(i: int) -> None:
        rng = np.random.default_rng(cfg.seed * 1000 + i)
        for _ in range(cfg.requests_per_client):
            n = int(rng.integers(cfg.min_request, cfg.max_request + 1))
            ids = rng.integers(0, engine.num_nodes, n)
            try:
                batcher.infer(ids)
                ok[i] += 1
                nodes_served[i] += n
            except ServeError as e:
                rejected[i] += 1
                log.write(e.record())

    threads = [
        threading.Thread(target=client, args=(i,), name=f"client-{i}")
        for i in range(cfg.clients)
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t0
    batcher.stop()

    snap = engine.registry.snapshot()
    lat = snap["histograms"].get("serve.request_ms", {"count": 0})
    occ = snap["histograms"].get("serve.batch_occupancy", {})
    # queue-wait vs infer vs pad-overhead breakdown (the per-stage
    # histograms the span instrumentation feeds): groundwork for the
    # p99-under-contention artifact — contention shows up as queue_wait
    # p99 growth while infer p99 stays flat
    q = ("count", "mean", "p50", "p95", "p99", "max")
    stages = {}
    for stage in _STAGES:
        hist = snap["histograms"].get(f"serve.stage.{stage}_ms")
        if hist and hist.get("count"):
            stages[stage] = {k: hist.get(k) for k in q}
    completed = sum(ok)
    report = {
        "kind": "serve_bench",
        # headline for the bench trajectory: completed requests per second
        "value": round(completed / wall_s, 2) if wall_s > 0 else None,
        "throughput_rps": round(completed / wall_s, 2) if wall_s > 0 else None,
        "throughput_nodes_per_s": (
            round(sum(nodes_served) / wall_s, 1) if wall_s > 0 else None
        ),
        "wall_s": round(wall_s, 3),
        "clients": cfg.clients,
        "completed": completed,
        "rejected": sum(rejected),
        "latency_ms": {
            k: lat.get(k) for k in ("count", "mean", "p50", "p95", "p99", "max")
        },
        "stages_ms": stages,
        "trace_id": trace_id,
        "batch_occupancy_mean": occ.get("mean"),
        "recompiles_since_warmup": engine.recompiles_since_warmup(),
        "buckets": [int(b) for b in engine.ladder.sizes],
        # the adopted tuning record (dgraph_tpu.tune) these throughput
        # numbers ran under, or None for the hard-coded defaults
        "tuning_record": getattr(engine, "tuning_record_id", None),
        "config": dataclasses.asdict(cfg),
    }
    log.write(report)
    log.write(serve_health_record(engine, batcher))
    return report


if __name__ == "__main__":
    import os as _os, sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
