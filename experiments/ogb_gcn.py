"""Full-graph node classification (the reference's ``experiments/OGB/main.py``).

Trains GCN / GraphSAGE / GAT on a partitioned graph over a TPU mesh, with
per-epoch timing, accuracy logs, and TimingReport phase breakdown. Data: a
synthetic SBM graph by default (this environment has no ogb package / no
egress), or any ``.npz`` with edge_index/features/labels/train_mask/... via
``--data.path`` — the `ogbn-*` datasets exported to npz load unchanged.

Run (single host; mesh = all visible devices):
    python experiments/ogb_gcn.py --model gcn --epochs 100
    python experiments/ogb_gcn.py --data.num_nodes 100000 --world_size 4
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional

import numpy as np


@dataclasses.dataclass
class DataConfig:
    path: Optional[str] = None  # npz with edge_index [2,E], features, labels, masks
    ogb_name: Optional[str] = None  # e.g. 'ogbn-arxiv' — needs the ogb
    # package, OR a raw download in the official layout under `root`
    # (data/ogb_raw.py parses it directly), OR path pointing at an
    # export_npz() artifact (data/ogbn.py)
    root: str = "dataset"  # where the ogb package / raw downloads live
    num_nodes: int = 5000  # synthetic SBM size when path is None
    num_classes: int = 8
    feat_dim: int = 64
    avg_degree: float = 10.0
    partition: str = "multilevel"  # METIS-shaped native partitioner


@dataclasses.dataclass
class Config:
    """Distributed full-graph GCN training."""

    model: str = "gcn"  # gcn | sage | gat | gt (GraphTransformer)
    hidden: int = 128
    num_layers: int = 2
    lr: float = 5e-3
    epochs: int = 100
    world_size: int = 0  # 0 = all devices
    log_path: str = "logs/ogb_gcn.jsonl"
    # thread grad-norm/mask-count through the jitted step (obs.metrics);
    # build-time flag — the default False keeps the timed step
    # byte-identical to the historical one so epoch_ms stays comparable
    # against recorded baselines (step records are emitted either way,
    # just without the in-step extras)
    step_metrics: bool = False
    data: DataConfig = dataclasses.field(default_factory=DataConfig)


def _num_classes(labels: np.ndarray) -> int:
    # multi-label float targets ([V, C], e.g. ogbn-proteins): C is the width
    if labels.ndim > 1:
        return int(labels.shape[1])
    return int(labels.max()) + 1


def _normalize_split_names(masks: dict) -> dict:
    """OGB says "valid"; the training loop's split name is "val"
    (DistributedGraph.batch falls back to ALL vertices on an unknown split
    — a silent eval-on-everything without this rename)."""
    if "valid" in masks and "val" not in masks:
        masks["val"] = masks.pop("valid")
    return masks


def load_data(cfg: DataConfig):
    if cfg.ogb_name:
        from dgraph_tpu.data import ogbn

        arrs = (
            ogbn.from_npz(cfg.path) if cfg.path
            else ogbn.load_ogb_arrays(cfg.ogb_name, root=cfg.root)
        )
        labels = np.asarray(arrs["labels"])
        masks = {
            k.removesuffix("_mask"): np.asarray(v)
            for k, v in arrs.items()
            if k.endswith("_mask")
        }
        return {
            "edge_index": np.asarray(arrs["edge_index"]),
            "features": np.asarray(arrs["features"]),
            "labels": labels,
            "masks": _normalize_split_names(masks),
            "num_classes": _num_classes(labels),
        }
    if cfg.path:
        z = np.load(cfg.path)
        masks = _normalize_split_names({
            k.removesuffix("_mask"): z[k] for k in z.files if k.endswith("_mask")
        })
        return {
            "edge_index": z["edge_index"],
            "features": z["features"],
            "labels": z["labels"],
            "masks": masks,
            "num_classes": _num_classes(np.asarray(z["labels"])),
        }
    from dgraph_tpu.data import synthetic

    return synthetic.sbm_classification_graph(
        num_nodes=cfg.num_nodes,
        num_classes=cfg.num_classes,
        feat_dim=cfg.feat_dim,
        avg_degree=cfg.avg_degree,
    )


def main(cfg: Config):
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu.data import DistributedGraph
    from dgraph_tpu.models import GAT, GCN, GraphSAGE, GraphTransformer
    from dgraph_tpu.train.loop import (
        init_params,
        make_eval_step,
        make_train_step,
        masked_bce_multilabel,
        masked_cross_entropy,
        vmask_batch_args,
    )
    from dgraph_tpu.obs import plan_footprint, startup_record
    from dgraph_tpu.obs.metrics import step_record
    from dgraph_tpu.utils import ExperimentLog, TimingReport

    world = cfg.world_size or len(jax.devices())
    mesh = make_graph_mesh(ranks_per_graph=world)
    comm = Communicator.init_process_group("tpu", world_size=world)
    log = ExperimentLog(cfg.log_path)
    log.write(startup_record("experiments.ogb_gcn"))
    data = load_data(cfg.data)

    TimingReport.start("partition+plan")
    g = DistributedGraph.from_global(
        data["edge_index"],
        data["features"],
        data["labels"],
        data["masks"],
        world_size=world,
        partition_method=cfg.data.partition,
        add_symmetric_norm=cfg.model == "gcn",
    )
    TimingReport.stop("partition+plan")
    # static comm accounting BEFORE any device step: what will this plan
    # move per halo exchange, and how imbalanced is it?
    log.write({
        "kind": "plan_footprint",
        **plan_footprint(g.plan, feat_dim=int(data["features"].shape[1])),
    })

    C = data["num_classes"]
    if cfg.model == "gcn":
        model = GCN(cfg.hidden, C, comm=comm, num_layers=cfg.num_layers)
    elif cfg.model == "sage":
        model = GraphSAGE(cfg.hidden, C, comm=comm, num_layers=cfg.num_layers)
    elif cfg.model == "gat":
        model = GAT(cfg.hidden, C, comm=comm, num_layers=cfg.num_layers)
    elif cfg.model in ("gt", "graph_transformer"):
        model = GraphTransformer(cfg.hidden, C, comm=comm, num_layers=cfg.num_layers)
    else:
        raise SystemExit(f"unknown model {cfg.model}")
    bargs = vmask_batch_args if cfg.model in ("gt", "graph_transformer") else None

    plan = jax.tree.map(jnp.asarray, g.plan)

    def _batch(split):
        return jax.tree.map(
            jnp.asarray, dict(g.batch(split), y=g.labels, vmask=g.vertex_mask)
        )

    batch_tr = _batch("train")
    batch_va = _batch("val")

    params = init_params(model, mesh, plan, batch_tr, batch_args=bargs)
    optimizer = optax.adam(cfg.lr)
    opt_state = optimizer.init(params)
    loss_fn = (
        masked_bce_multilabel if np.asarray(g.labels).ndim > 2 else masked_cross_entropy
    )
    train_step = make_train_step(
        model, optimizer, mesh, plan, loss_fn=loss_fn, batch_args=bargs,
        step_metrics=cfg.step_metrics,
    )
    eval_step = make_eval_step(model, mesh, loss_fn=loss_fn, batch_args=bargs)

    epoch_times = []
    with jax.set_mesh(mesh):
        for epoch in range(cfg.epochs):
            t0 = time.perf_counter()
            params, opt_state, m = train_step(params, opt_state, batch_tr, plan)
            jax.block_until_ready(m["loss"])
            dt = (time.perf_counter() - t0) * 1000
            epoch_times.append(dt)
            rec = step_record(m, step=epoch, wall_ms=dt)
            rec["epoch"] = epoch  # legacy key, kept for plot scripts
            if epoch % 10 == 0 or epoch == cfg.epochs - 1:
                ev = eval_step(params, batch_va, plan)
                rec["val_acc"] = float(ev["accuracy"])
                rec["val_loss"] = float(ev["loss"])
            # one structured record per step — the obs metrics pipeline
            log.write(rec)
    # final held-out accuracy (the reference reports test accuracy for the
    # OGB runs; ~72% is the public GCN bar on real ogbn-arxiv)
    if "test" in g.masks:
        batch_te = _batch("test")
        with jax.set_mesh(mesh):
            te = eval_step(params, batch_te, plan)
        log.write({"test_acc": float(te["accuracy"]), "test_loss": float(te["loss"])})
    # avg excluding first (compile) epoch — the reference's convention
    # (experiments/OGB/main.py:129-221)
    log.write(
        {
            "avg_epoch_ms_excl_first": round(float(np.mean(epoch_times[1:])), 2),
            "timing": TimingReport.report(),
        }
    )


if __name__ == "__main__":
    import os as _os, sys as _sys

    # direct-invocation support (repo not pip-installed): put the repo
    # root on sys.path so `python experiments/<script>.py` works
    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
