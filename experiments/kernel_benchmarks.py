"""Hot-op microbenchmarks on the local accelerator: row gather, segment
sum (XLA vs Pallas), one-hot scatter variants.

The kernel-level companion of ``comm_benchmarks.py`` (together they mirror
the reference's ``experiments/Benchmarks`` suite, ``TestNCCL.py:23-111``),
pointed at the per-chip primitives instead of the wire.

Timing protocol (see ``bench.py``): on the tunneled single-chip setup
``block_until_ready`` is not a reliable completion barrier and identical
dispatches can be memoized, so every op is timed as an in-jit ``lax.scan``
of n iterations with a scalar fetch, reporting the delta between two scan
lengths (per-call RPC latency cancels).

Usage:
    python experiments/kernel_benchmarks.py --num_nodes 169343 \
        --num_edges 2332486 --feat_dims 128,256 --out logs/kernels.jsonl
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from functools import partial
from typing import Optional


@dataclasses.dataclass
class Config:
    """Per-chip hot-op microbenchmarks."""

    num_nodes: int = 169_343  # ogbn-arxiv scale
    num_edges: int = 2_332_486
    feat_dims: str = "128,256"
    reps: int = 3
    n_long: int = 11
    out: Optional[str] = "logs/kernel_benchmarks.jsonl"
    pallas: bool = True  # include the Pallas sorted-segment-sum variants
    dtypes: str = "float32"  # comma list: float32,bfloat16
    # tile sweep for the Pallas kernel (grid-step overhead dominates at
    # small block_e: fewer/bigger DMAs win until VMEM pressure pushes back)
    sweep: bool = False
    sweep_block_e: str = "512,1024,2048,4096"
    sweep_block_n: str = "256,512"
    # comma list of op names to skip (resume after a tunnel wedge without
    # re-dispatching the op that hung; r4: gather_sorted_xla)
    skip_ops: str = ""


def _bench(op, arg, *, reps: int, n_long: int, label: str = "?"):
    """One op's in-jit scan timing — delegates to the shared protocol
    (``dgraph_tpu.utils.timing.timed_scan_ms``; ``salt_input`` keeps bf16
    inputs bf16). A per-op failure (e.g. a Mosaic compile crash at an
    untried width) records NaN instead of killing the remaining ops —
    during a scarce lease window every surviving row counts
    (adopt_sweep filters non-finite ms, so NaN rows cannot win a tile)."""
    import sys
    import traceback

    from dgraph_tpu.utils.timing import salt_input, timed_scan_ms

    try:
        t = timed_scan_ms(
            lambda s: op(salt_input(arg, s)), reps=reps, n_long=n_long
        )
    except Exception as e:  # noqa: BLE001
        print(f"bench op {label} raised {type(e).__name__}: "
              f"{str(e).splitlines()[0] if str(e) else ''}",
              file=sys.stderr)
        # negative limit = innermost frames (the Mosaic/pallas one that
        # names the failed lowering); a positive limit shows only _bench
        traceback.print_exc(limit=-5, file=sys.stderr)
        return float("nan")
    return t if t is not None else float("nan")  # NaN survives round()


def main(cfg: Config):
    import numpy as np
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.ops import local as local_ops
    from dgraph_tpu.ops.pallas_segment import max_chunks_hint, sorted_segment_sum

    if cfg.out:
        os.makedirs(os.path.dirname(cfg.out) or ".", exist_ok=True)

    def record(**kw):
        # non-finite ms/gbps (per-op failure) become null: json.dumps
        # would emit a bare NaN token, which Python's json re-reads but
        # strict parsers (jq) reject on the streamed jsonl (ADVICE r4).
        # adopt_sweep already drops None rows.
        for k in ("ms", "gbps"):
            v = kw.get(k)
            if isinstance(v, float) and not np.isfinite(v):
                kw[k] = None
        kw["ts"] = time.time()
        line = json.dumps(kw)
        print(line)
        # stream to disk immediately: a tunnel wedge mid-sweep killed the
        # process in r4 and the buffered write-at-end lost every completed
        # measurement (only the stdout tail survived)
        if cfg.out:
            with open(cfg.out, "a") as f:
                f.write(line + "\n")

    skipped = {s.strip() for s in cfg.skip_ops.split(",") if s.strip()}
    rng = np.random.default_rng(0)
    V, E = cfg.num_nodes, cfg.num_edges
    N = ((V + 127) // 128) * 128
    E_pad = ((E + 127) // 128) * 128
    idx = jnp.asarray(rng.integers(0, V, E_pad).astype(np.int32))
    sids_np = np.sort(rng.integers(0, V, E_pad)).astype(np.int32)
    sids = jnp.asarray(sids_np)
    on_tpu = jax.default_backend() == "tpu"

    dtype_list = [
        jnp.bfloat16 if d.strip() in ("bfloat16", "bf16") else jnp.float32
        for d in cfg.dtypes.split(",")
    ]
    for F in [int(f) for f in cfg.feat_dims.split(",")]:
      for dt in dtype_list:
        b = 2 if dt == jnp.bfloat16 else 4
        dname = "bf16" if dt == jnp.bfloat16 else "f32"
        x = jnp.asarray(rng.standard_normal((N, F)), dt)
        ed = jnp.asarray(rng.standard_normal((E_pad, F)), dt)
        bench = partial(_bench, reps=cfg.reps, n_long=cfg.n_long)

        if "gather_plain" not in skipped:
            t = bench(lambda a: a[idx], x, label=f"gather_plain/{dname}/F{F}")
            record(op="gather_plain", F=F, dtype=dname, ms=round(t, 3),
                   gbps=round(E_pad * F * b / t / 1e6, 1))
        if "gather_col_split" not in skipped:
            t = bench(lambda a: local_ops.row_take(a, idx, col_block=128), x,
                      label=f"gather_col_split/{dname}/F{F}")
            record(op="gather_col_split", F=F, dtype=dname, ms=round(t, 3),
                   gbps=round(E_pad * F * b / t / 1e6, 1))
        # sorted-id gathers: the owner-side case (XLA vs the Pallas
        # transpose kernel — the A/B that decides use_pallas_gather)
        if "gather_sorted_xla" not in skipped:
            t = bench(lambda a: local_ops.row_take(a, sids, col_block=128), x,
                      label=f"gather_sorted_xla/{dname}/F{F}")
            record(op="gather_sorted_xla", F=F, dtype=dname, ms=round(t, 3),
                   gbps=round(E_pad * F * b / t / 1e6, 1))
        if cfg.pallas and on_tpu:
            from dgraph_tpu.ops.pallas_segment import (
                max_vblocks_hint,
                sorted_row_gather,
            )

            mv = max_vblocks_hint(sids_np, N)
            mc0 = max_chunks_hint(sids_np, N)
            prec0 = "default" if dt == jnp.bfloat16 else "highest"
            if "gather_sorted_pallas" not in skipped:
                t = bench(
                    lambda a: sorted_row_gather(
                        a, sids, max_vblocks=mv, scatter_mc=mc0,
                        precision=prec0,
                    ),
                    x,
                    label=f"gather_sorted_pallas/{dname}/F{F}",
                )
                record(op="gather_sorted_pallas", F=F, dtype=dname, mv=mv,
                       ms=round(t, 3),
                       gbps=round(E_pad * F * b / t / 1e6, 1))
        if "segment_sum_xla" not in skipped:
            t = bench(
                lambda a: local_ops.segment_sum(
                    a, sids, N, indices_are_sorted=True), ed,
                label=f"segment_sum_xla/{dname}/F{F}",
            )
            record(op="segment_sum_xla", F=F, dtype=dname, ms=round(t, 3),
                   gbps=round(E_pad * F * b / t / 1e6, 1))
        if cfg.pallas and on_tpu:
            if cfg.sweep:
                tiles = [
                    (int(be), int(bn))
                    for be in cfg.sweep_block_e.split(",")
                    for bn in cfg.sweep_block_n.split(",")
                ]
            else:
                tiles = [(1024, 256)]
            for be, bn in tiles:
                mc = max_chunks_hint(sids_np, N, block_e=be, block_n=bn)
                precs = ("default",) if dt == jnp.bfloat16 else ("highest", "default")
                for prec in precs:
                    # match the family name OR the full recorded op name
                    # (a user copies the latter from the jsonl/stdout)
                    if {"segment_sum_pallas",
                            f"segment_sum_pallas_{prec}"} & skipped:
                        continue
                    t = bench(
                        lambda a, prec=prec, be=be, bn=bn, mc=mc: sorted_segment_sum(
                            a, sids, N, max_chunks_per_block=mc,
                            block_e=be, block_n=bn, precision=prec,
                        ),
                        ed,
                        label=(f"segment_sum_pallas_{prec}/{dname}"
                               f"/F{F}/be{be}bn{bn}"),
                    )
                    record(op=f"segment_sum_pallas_{prec}", F=F, dtype=dname,
                           block_e=be, block_n=bn, mc=mc, ms=round(t, 3),
                           gbps=round(E_pad * F * b / t / 1e6, 1))
                # the gather kernel shares the plan's (block_e, block_n)
                # fields, so tile winners must be picked for BOTH kernels
                if cfg.sweep and "gather_sorted_pallas_sweep" not in skipped:
                    # max_vblocks_hint / sorted_row_gather / prec0 are in
                    # scope from the non-sweep gather block above (same
                    # cfg.pallas-and-on_tpu guard)
                    mv = max_vblocks_hint(sids_np, N, block_e=be, block_n=bn)
                    t = bench(
                        lambda a, be=be, bn=bn, mv=mv, mc=mc, prec0=prec0:
                        sorted_row_gather(
                            a, sids, max_vblocks=mv, block_e=be, block_n=bn,
                            scatter_mc=mc, precision=prec0),
                        x,
                        label=(f"gather_sorted_pallas_sweep/{dname}"
                               f"/F{F}/be{be}bn{bn}"),
                    )
                    record(op="gather_sorted_pallas_sweep", F=F, dtype=dname,
                           block_e=be, block_n=bn, mv=mv, ms=round(t, 3),
                           gbps=round(E_pad * F * b / t / 1e6, 1))

    # records were streamed to cfg.out by record() as they completed


if __name__ == "__main__":
    import os as _os, sys as _sys

    _sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    from dgraph_tpu.utils.cli import parse_config

    main(parse_config(Config))
