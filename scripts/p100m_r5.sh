#!/bin/bash
# Staged full-papers100M partition+plan (restartable; each stage skips if
# its artifact exists). Commits the log when all stages land.
cd /root/repo
set -o pipefail
exec >> logs/p100m_r5_stages.log 2>&1
export DGRAPH_HOST_FM_TABLE_GB=12
date -u +"%Y-%m-%dT%H:%M:%SZ p100m r5 staged run start"
for stage in generate partition plan; do
  date -u +"%Y-%m-%dT%H:%M:%SZ stage $stage start"
  python scripts/p100m_r5_stages.py "$stage"
  rc=$?
  if [ $rc -ne 0 ]; then
    date -u +"%Y-%m-%dT%H:%M:%SZ stage $stage FAILED rc=$rc"
    exit 1
  fi
done
date -u +"%Y-%m-%dT%H:%M:%SZ all stages done"
git add -f logs/p100m_fullscale_r5.jsonl logs/p100m_r5_stages.log
git commit -q -m "Full-scale papers100M multilevel_sampled partition + plan artifacts

No-Verification-Needed: measurement logs only" || true
