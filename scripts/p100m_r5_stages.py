"""Staged full-papers100M partition + plan build (VERDICT r4 #6).

The one-process plan_only flow OOM-killed at 130.7 GB with
multilevel_sampled p=0.5: the in-RAM edge list (25.8 GB) + the sample +
the WGraph build transients stacked. This splits the flow into three
PROCESSES so each phase's peak stands alone and a failure never re-pays
an earlier phase:

  generate   power_law(111M, 14.5) -> cache/p100m/edges.npy (disk, 26 GB)
  partition  memmap edges -> multilevel_sampled(p=0.35) -> part.npy + cut
  plan       memmap edges + part -> renumber (to disk) -> streaming
             per-rank plan shards (cache/p100m/plan_shards/, format v8:
             resumable + memory-budgeted, dgraph_tpu.plan_shards)

Usage: python scripts/p100m_r5_stages.py {generate|partition|plan}
(scripts/p100m_r5.sh runs all three and commits the log.)

Same generator/seed as experiments/papers100m_gcn.py --plan_only, so the
phase rows in logs/p100m_fullscale_r5.jsonl are comparable with r4's
greedy_bfs full-scale record (logs/p100m_fullscale.jsonl).
"""

from __future__ import annotations

import hashlib
import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V = 111_059_956
AVG_DEGREE = 14.5
WORLD = 8
SAMPLE_FRAC = 0.35
SEED = 0
# >0 co-balances owner-side edge volume (e_pad) via vw = 16 + 16*a*deg;
# the unblended record measured e_imb 1.28 at cut 0.7454
EDGE_BALANCE = float(os.environ.get("DGRAPH_P100M_EDGE_BALANCE", "0"))
_SUF = f"_eb{EDGE_BALANCE:g}" if EDGE_BALANCE > 0 else ""
CACHE = "cache/p100m"
LOG = "logs/p100m_fullscale_r5.jsonl"
EDGES = os.path.join(CACHE, "edges.npy")
PART = os.path.join(CACHE, f"part{_SUF}.npy")


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def _log(rec: dict) -> None:
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    rec["peak_rss_gb"] = round(_rss_gb(), 1)
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def generate() -> None:
    if os.path.exists(EDGES):
        print(f"{EDGES} exists; skipping generate", flush=True)
        return
    from dgraph_tpu.data.synthetic import power_law_graph

    os.makedirs(CACHE, exist_ok=True)
    t0 = time.perf_counter()
    edges = power_law_graph(V, AVG_DEGREE, seed=SEED)
    np.save(EDGES + ".tmp.npy", edges)
    os.replace(EDGES + ".tmp.npy", EDGES)
    _log({"phase": "generate", "nodes": V, "edges": int(edges.shape[1]),
          "wall_s": round(time.perf_counter() - t0, 1), "on_disk": EDGES})


def _chunked_cut_and_edge_counts(
    edges: np.ndarray, part: np.ndarray, chunk: int = 1 << 26
) -> tuple[float, np.ndarray]:
    """One streaming pass over the (memmapped) edge list: directed cut
    fraction + owner-side (dst) edge count per rank."""
    E = edges.shape[1]
    cross = 0
    ec = np.zeros(WORLD, np.int64)
    for lo in range(0, E, chunk):
        blk = np.asarray(edges[:, lo:lo + chunk])
        pd = part[blk[1]]
        cross += int((part[blk[0]] != pd).sum())
        ec += np.bincount(pd, minlength=WORLD)
    return cross / max(E, 1), ec


def partition() -> None:
    if os.path.exists(PART):
        print(f"{PART} exists; skipping partition", flush=True)
        return
    from dgraph_tpu import partition as pt

    edges = np.load(EDGES, mmap_mode="r")
    t0 = time.perf_counter()
    part = pt.multilevel_sampled_partition(
        edges, V, WORLD, seed=SEED, sample_frac=SAMPLE_FRAC,
        edge_balance=EDGE_BALANCE,
    )
    wall = time.perf_counter() - t0
    np.save(PART + ".tmp.npy", part)
    os.replace(PART + ".tmp.npy", PART)
    cut, ec = _chunked_cut_and_edge_counts(edges, part)
    counts = np.bincount(part, minlength=WORLD)
    _log({"phase": "partition", "method": "multilevel_sampled",
          "sample_frac": SAMPLE_FRAC, "edge_balance": EDGE_BALANCE,
          "wall_s": round(wall, 1), "cut": round(float(cut), 4),
          "balance": round(float(counts.max() / (V / WORLD)), 4),
          "edge_imbalance": round(float(ec.max() / ec.mean()), 4)})


def plan() -> None:
    import gc

    from dgraph_tpu import partition as pt
    from dgraph_tpu.data.memmap import renumber_edges_chunked

    edges = np.load(EDGES, mmap_mode="r")
    part = np.load(PART)
    # every derived artifact below (renumber resume marker, shard
    # fingerprint) is bound to the partition CONTENT: a regenerated
    # part.npy must invalidate both, or a resumed run would splice
    # artifacts from two different partitions — shape checks and a
    # constant name cannot tell them apart
    part_sha = hashlib.sha256(np.ascontiguousarray(part).data).hexdigest()[:16]
    t0 = time.perf_counter()
    ren = pt.renumber_contiguous(part, WORLD)
    del part
    # renumber the memmapped edge list chunk-wise TO DISK: an in-RAM
    # [2, E] int64 copy (25.8 GB anon) on top of the plan core's own
    # transients OOM-killed the first attempt at ~130 GB
    E = edges.shape[1]
    ne_path = os.path.join(CACHE, f"new_edges{_SUF}.npy")
    ne_ok = ne_path + ".ok"
    try:
        with open(ne_ok) as fh:
            ne_marker = fh.read().strip()
    except OSError:
        ne_marker = ""
    if os.path.exists(ne_path) and ne_marker == part_sha:
        # the .ok marker (holding part.npy's content hash) is written
        # only AFTER the tmp+rename completes, so a matching marker means
        # a COMPLETED renumber of THIS partition: a resumed run after a
        # mid-build SIGKILL skips re-streaming the ~26 GB copy.  A file
        # without it — the pre-v8 in-place writer's full-size-but-partial
        # file the r5 SIGKILL left behind, or a renumber of a stale
        # part.npy — is re-renumbered, not adopted
        new_edges = np.load(ne_path, mmap_mode="r")
        assert new_edges.shape == (2, E), new_edges.shape
    else:
        tmp_path = ne_path + ".tmp.npy"
        renumber_edges_chunked(edges, ren.perm, tmp_path)
        os.replace(tmp_path, ne_path)
        with open(ne_ok, "w") as fh:
            fh.write(part_sha)
        new_edges = np.load(ne_path, mmap_mode="r")
    partition_arr = ren.partition
    del ren
    gc.collect()
    # sharded plan artifact (cache format v8, plan.build_plan_shards):
    # per-rank shard pickles + checksummed manifest instead of the ~40+ GB
    # monolithic EdgePlan pickle that killed r5 (attempt 1's orphaned tmp
    # pickle filled the disk and SIGBUS'd attempt 2's memmap writes; the
    # in-RAM [W, E_pad] stack OOM-killed attempt 3 at ~130 GB).  Each host
    # later loads ONLY its ranks' shards
    # (comm.multihost.process_local_plan_shards); a SIGKILL here resumes
    # from the manifest on rerun, and DGRAPH_PLAN_MEMORY_BUDGET_MB turns
    # an over-budget shard into a structured PlanBuildMemoryExceeded
    # instead of an OOM kill
    from dgraph_tpu.plan import build_plan_shards, shard_nbytes_estimate

    plan_dir = os.path.join(CACHE, f"plan_shards{_SUF}")
    # write_layout=False: the O(E) layout sidecar pickles to ~25 GB here
    # (and atomic_pickle_dump transiently doubles it on the disk that
    # attempt 1's orphaned tmp pickle filled); nothing downstream of this
    # stage consumes it — per-host loading skips it by design
    # fingerprint defaults to a streaming content hash of
    # (new_edges, partition) — a regenerated edge list or partition can
    # never resume against the other's durable shards, even when counts
    # and pads coincide (the hash streams the 26 GB memmap in windows,
    # seconds against a multi-hour build)
    manifest = build_plan_shards(
        new_edges, partition_arr, out_dir=plan_dir, world_size=WORLD,
        pad_multiple=128, write_layout=False,
    )
    os.remove(ne_path)
    os.remove(ne_ok)
    st = manifest["statics"]
    shard_bytes = [int(e["bytes"]) for e in manifest["shards"].values()]
    _log({
        "phase": "plan_build", "edge_balance": EDGE_BALANCE, "part": PART,
        "wall_s": round(time.perf_counter() - t0, 1),
        "e_pad": int(st["e_pad"]), "s_pad": int(st["s_pad"]),
        "plan_dir": plan_dir, "format_version": int(manifest["format_version"]),
        "shards": len(shard_bytes),
        "shard_bytes_max": max(shard_bytes),
        "shard_bytes_total": sum(shard_bytes),
        "shard_nbytes_estimate": int(shard_nbytes_estimate(st)),
    })


if __name__ == "__main__":
    {"generate": generate, "partition": partition, "plan": plan}[sys.argv[1]]()
