"""Staged full-papers100M partition + plan build (VERDICT r4 #6).

The one-process plan_only flow OOM-killed at 130.7 GB with
multilevel_sampled p=0.5: the in-RAM edge list (25.8 GB) + the sample +
the WGraph build transients stacked. This splits the flow into three
PROCESSES so each phase's peak stands alone and a failure never re-pays
an earlier phase:

  generate   power_law(111M, 14.5) -> cache/p100m/edges.npy (disk, 26 GB)
  partition  memmap edges -> multilevel_sampled(p=0.35) -> part.npy + cut
  plan       memmap edges + part -> renumber -> cached plan build

Usage: python scripts/p100m_r5_stages.py {generate|partition|plan}
(scripts/p100m_r5.sh runs all three and commits the log.)

Same generator/seed as experiments/papers100m_gcn.py --plan_only, so the
phase rows in logs/p100m_fullscale_r5.jsonl are comparable with r4's
greedy_bfs full-scale record (logs/p100m_fullscale.jsonl).
"""

from __future__ import annotations

import json
import os
import resource
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V = 111_059_956
AVG_DEGREE = 14.5
WORLD = 8
SAMPLE_FRAC = 0.35
SEED = 0
# >0 co-balances owner-side edge volume (e_pad) via vw = 16 + 16*a*deg;
# the unblended record measured e_imb 1.28 at cut 0.7454
EDGE_BALANCE = float(os.environ.get("DGRAPH_P100M_EDGE_BALANCE", "0"))
_SUF = f"_eb{EDGE_BALANCE:g}" if EDGE_BALANCE > 0 else ""
CACHE = "cache/p100m"
LOG = "logs/p100m_fullscale_r5.jsonl"
EDGES = os.path.join(CACHE, "edges.npy")
PART = os.path.join(CACHE, f"part{_SUF}.npy")


def _rss_gb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1e6


def _log(rec: dict) -> None:
    os.makedirs(os.path.dirname(LOG), exist_ok=True)
    rec["peak_rss_gb"] = round(_rss_gb(), 1)
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def generate() -> None:
    if os.path.exists(EDGES):
        print(f"{EDGES} exists; skipping generate", flush=True)
        return
    from dgraph_tpu.data.synthetic import power_law_graph

    os.makedirs(CACHE, exist_ok=True)
    t0 = time.perf_counter()
    edges = power_law_graph(V, AVG_DEGREE, seed=SEED)
    np.save(EDGES + ".tmp.npy", edges)
    os.replace(EDGES + ".tmp.npy", EDGES)
    _log({"phase": "generate", "nodes": V, "edges": int(edges.shape[1]),
          "wall_s": round(time.perf_counter() - t0, 1), "on_disk": EDGES})


def _chunked_cut_and_edge_counts(
    edges: np.ndarray, part: np.ndarray, chunk: int = 1 << 26
) -> tuple[float, np.ndarray]:
    """One streaming pass over the (memmapped) edge list: directed cut
    fraction + owner-side (dst) edge count per rank."""
    E = edges.shape[1]
    cross = 0
    ec = np.zeros(WORLD, np.int64)
    for lo in range(0, E, chunk):
        blk = np.asarray(edges[:, lo:lo + chunk])
        pd = part[blk[1]]
        cross += int((part[blk[0]] != pd).sum())
        ec += np.bincount(pd, minlength=WORLD)
    return cross / max(E, 1), ec


def partition() -> None:
    if os.path.exists(PART):
        print(f"{PART} exists; skipping partition", flush=True)
        return
    from dgraph_tpu import partition as pt

    edges = np.load(EDGES, mmap_mode="r")
    t0 = time.perf_counter()
    part = pt.multilevel_sampled_partition(
        edges, V, WORLD, seed=SEED, sample_frac=SAMPLE_FRAC,
        edge_balance=EDGE_BALANCE,
    )
    wall = time.perf_counter() - t0
    np.save(PART + ".tmp.npy", part)
    os.replace(PART + ".tmp.npy", PART)
    cut, ec = _chunked_cut_and_edge_counts(edges, part)
    counts = np.bincount(part, minlength=WORLD)
    _log({"phase": "partition", "method": "multilevel_sampled",
          "sample_frac": SAMPLE_FRAC, "edge_balance": EDGE_BALANCE,
          "wall_s": round(wall, 1), "cut": round(float(cut), 4),
          "balance": round(float(counts.max() / (V / WORLD)), 4),
          "edge_imbalance": round(float(ec.max() / ec.mean()), 4)})


def plan() -> None:
    import gc

    from dgraph_tpu import partition as pt
    from dgraph_tpu.plan import plan_memory_usage

    edges = np.load(EDGES, mmap_mode="r")
    part = np.load(PART)
    t0 = time.perf_counter()
    ren = pt.renumber_contiguous(part, WORLD)
    del part
    # renumber the memmapped edge list chunk-wise TO DISK: an in-RAM
    # [2, E] int64 copy (25.8 GB anon) on top of the plan core's own
    # transients OOM-killed the first attempt at ~130 GB; the core reads
    # src/dst in sequential passes, so file-backed pages reclaim under
    # pressure instead of counting against the OOM killer
    E = edges.shape[1]
    ne_path = os.path.join(CACHE, "new_edges.npy")
    new_edges = np.lib.format.open_memmap(
        ne_path, mode="w+", dtype=np.int64, shape=(2, E)
    )
    chunk = 1 << 26
    for lo in range(0, E, chunk):
        blk = np.asarray(edges[:, lo:lo + chunk])
        new_edges[:, lo:lo + blk.shape[1]] = ren.perm[blk]
    new_edges.flush()
    partition_arr = ren.partition
    del ren, new_edges
    gc.collect()
    new_edges = np.load(ne_path, mmap_mode="r")
    # no on-disk plan cache: the full-scale EdgePlan pickle is ~40+ GB
    # (attempt 1's orphaned tmp pickle filled the disk and SIGBUS'd
    # attempt 2's memmap writes); the logged build stats are the
    # artifact, and part.npy lets any later run rebuild in ~1 h
    from dgraph_tpu.plan import build_edge_plan

    plan_np, layout = build_edge_plan(
        new_edges, partition_arr, world_size=WORLD, pad_multiple=128,
    )
    os.remove(ne_path)
    mem = plan_memory_usage(plan_np, feature_dim=128)
    _log({
        "phase": "plan_build", "edge_balance": EDGE_BALANCE, "part": PART,
        "wall_s": round(time.perf_counter() - t0, 1),
        "e_pad": int(plan_np.e_pad), "s_pad": int(plan_np.halo.s_pad),
        "halo_pairs": int(layout.halo_counts.sum()),
        "halo_pair_fraction": round(float(layout.halo_counts.sum()) / max(E, 1), 4),
        "plan_bytes": {k: int(v) for k, v in mem.items()},
    })


if __name__ == "__main__":
    {"generate": generate, "partition": partition, "plan": plan}[sys.argv[1]]()
