#!/bin/bash
# Round-4c on-chip queue: regression hunt + the stages the 04:01Z re-wedge
# killed in onchip_r4.sh. Context: stage-1 headline landed (597.7 ms,
# logs/bench_r4_gcn.json — a REGRESSION vs 456.9 ms r1 / 421.1 ms r2
# interim), the sweep's first two rows landed (XLA plain gather 3.9 ms
# BEATS col_split 5.8 ms at F=128), then the tunnel wedged during
# gather_sorted_xla dispatch. Ordering below: cheapest decisive A/Bs
# first, known-wedge-risk stages (GraphCast L6, p100m) last.
cd /root/repo
set -o pipefail
exec >> logs/onchip_r4c.log 2>&1
date -u +"%Y-%m-%dT%H:%M:%SZ r4c queue start"

probe() { timeout 90 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu', jax.default_backend()
float(jnp.ones((8,128)).sum())" >/dev/null 2>&1; }

commit_stage() {
  name=$1; shift
  for f in "$@" logs/onchip_r4c.log; do
    [ -e "$f" ] && git add -f "$f"
  done
  git commit -q -m "On-chip r4c queue: $name artifacts

No-Verification-Needed: measurement logs only" || true
}

run_stage() {
  name=$1; shift
  if ! probe; then
    date -u +"%Y-%m-%dT%H:%M:%SZ $name skipped (lease wedged)"
    return 1
  fi
  "$@"
  rc=$?
  date -u +"%Y-%m-%dT%H:%M:%SZ $name done rc=$rc"
  return $rc
}

bench_ab() {  # bench_ab NAME "ENV=VAL ..."
  name=$1; env_str=$2
  if ! probe; then
    # return BEFORE tail/commit: committing a pre-existing
    # logs/bench_r4b_${name}.json from an earlier run would label stale
    # data as this stage's artifact
    date -u +"%Y-%m-%dT%H:%M:%SZ bench_$name skipped (lease wedged)"
    return 1
  fi
  bash -c "env $env_str DGRAPH_BENCH_GRAPHCAST=0 \
    DGRAPH_BENCH_TIMEOUT=2400 python bench.py \
    > logs/bench_r4b_${name}.json 2>logs/bench_r4b_${name}.err"
  rc=$?
  date -u +"%Y-%m-%dT%H:%M:%SZ bench_$name done rc=$rc json: $(tail -1 logs/bench_r4b_${name}.json 2>/dev/null)"
  commit_stage "$name" "logs/bench_r4b_${name}.json" "logs/bench_r4b_${name}.err"
  return $rc
}

# --- regression hunt: one-variable A/Bs on the exact headline harness ---
# 1. default with the Mosaic bf16 [:,None] fix (fused kernel should now
#    pass its self-check)
bench_ab fusedfix ""
# 1b. fused-backward kernel pair OFF (the r5 kill switch,
#     DGRAPH_TPU_PALLAS_FUSED_BWD): isolates the pair's contribution to
#     the headline; the fused fwd stays on with the composed backward
bench_ab fusedbwd0 "DGRAPH_TPU_PALLAS_FUSED_BWD=0"
# 2. column chunking OFF — the invalidated-default suspect; the surviving
#    sweep rows already show plain beating col_split at F=128
bench_ab nocolblk "DGRAPH_TPU_GATHER_COL_BLOCK=0"
# 3. Pallas scatter OFF (pure XLA segment_sum path)
bench_ab noscatter "DGRAPH_TPU_PALLAS_SCATTER=0 DGRAPH_TPU_PALLAS_FUSED=0"
# 4. all-XLA minimal path
bench_ab allxla "DGRAPH_TPU_PALLAS_SCATTER=0 DGRAPH_TPU_PALLAS_FUSED=0 DGRAPH_TPU_GATHER_COL_BLOCK=0"
# 4b. float32 control (rules dtype in/out as the regression variable vs
#     the r1 456.9 ms recording)
bench_ab f32 "DGRAPH_BENCH_DTYPE=float32"

# 5. op profile (VERDICT r3 #5: the 2x residual; now also localizes the
#    597 ms regression per-op)
run_stage op_profile bash -c 'set -o pipefail; timeout 1800 python experiments/op_profile.py 2>&1 | tail -20'
commit_stage op_profile logs/op_profile.jsonl

# 6. Pallas sorted-row-gather pinned on (original queue stage 3)
bench_ab gatherk "DGRAPH_TPU_PALLAS_GATHER=1"

# 7. kernel sweep, split per (dtype, F) so one wedge loses at most a
#    quarter; records stream to the jsonl as they complete now.
for dt in float32 bfloat16; do
  for F in 128 256; do
    run_stage "sweep_${dt}_${F}" bash -c "set -o pipefail; timeout 1800 \
      python experiments/kernel_benchmarks.py --sweep true --dtypes $dt \
      --feat_dims $F 2>&1 | tail -5"
    commit_stage "sweep_${dt}_${F}" logs/kernel_benchmarks.jsonl
  done
done
python scripts/adopt_sweep.py logs/kernel_benchmarks.jsonl > logs/sweep_winners.txt 2>&1 || true
commit_stage sweep_winners logs/sweep_winners.txt

# 7b. NARROW widths (F = num_heads scale): decides whether GAT/RGAT's
#     [E, heads] attention-softmax ops get the Pallas route (r4c audit;
#     the XLA scatter there is per-row, so narrow may cost like wide).
#     Also the first on-chip Mosaic compile of the kernels at F < 8 —
#     split per (dtype, F) so one Mosaic crash loses a quarter, and
#     logged to a SEPARATE jsonl so the single-tile narrow rows cannot
#     vote in adopt_sweep's tile consensus on a queue re-run.
for dt in float32 bfloat16; do
  for F in 2 8; do
    if run_stage "sweep_narrow_${dt}_${F}" bash -c "set -o pipefail; \
      timeout 900 python experiments/kernel_benchmarks.py --dtypes $dt \
      --feat_dims $F --out logs/kernel_narrow.jsonl 2>&1 | tail -5"; then
      commit_stage "sweep_narrow_${dt}_${F}" logs/kernel_narrow.jsonl
    fi
  done
done

# 8. flash-attention A/B at seq 8192 (original stage 5)
# distinct bf16 paths: r3/r4 queued the same A/B in f32 to
# logs/lm_flash{0,1}_onchip.jsonl — appending mixed-dtype rows to those
# would make the committed artifact unreadable
flash_ran=0
for fl in 0 1; do
  run_stage "lm flash=$fl" bash -c "set -o pipefail; DGRAPH_TPU_FLASH_ATTN=$fl DGRAPH_TPU_COMPUTE_DTYPE=bfloat16 timeout 1200 python experiments/long_context_lm.py --seq_len 8192 --steps 30 --world_size 1 --latent 256 --num_heads 2 --attn_impl ulysses --log_path logs/lm_flash${fl}_bf16_onchip.jsonl 2>&1 | tail -2" && flash_ran=1 || break
done
if [ "$flash_ran" = 1 ]; then
  commit_stage flash_ab logs/lm_flash0_bf16_onchip.jsonl logs/lm_flash1_bf16_onchip.jsonl
fi

# 8b. First on-chip RGAT record (arxiv-scale synthetic MAG, bf16): also
#     measures the narrow [E, heads] attention-softmax XLA scatters the
#     r4c audit flagged — decides whether they get the Pallas pad-route.
if run_stage rgat bash -c 'set -o pipefail; DGRAPH_TPU_COMPUTE_DTYPE=bfloat16 timeout 1800 python experiments/rgat_mag.py --num_papers 200000 --num_authors 120000 --num_institutions 12000 --epochs 12 --world_size 1 --plan_cache "" --log_path logs/rgat_onchip.jsonl 2>&1 | tail -3'; then
  # commit only on a completed run: a probe-skip must not relabel a prior
  # partial jsonl as this stage's artifact (same hazard bench_ab guards)
  commit_stage rgat logs/rgat_onchip.jsonl
fi

# 9. GraphCast ladder (original stage 6; known wedge risk — late)
run_stage bench_graphcast bash -c 'DGRAPH_BENCH_TIMEOUT=3000 python bench.py > logs/bench_r4_full.json 2>logs/bench_r4_full.err'
date -u +"%Y-%m-%dT%H:%M:%SZ full json: $(tail -1 logs/bench_r4_full.json 2>/dev/null)"
commit_stage bench_graphcast logs/bench_r4_full.json logs/bench_r4_full.err

# 10. papers100M ladder (original stage 7; 0.05 rung added in r5 — the
#     streamed per-device sharding removed the host-side [W,n_pad,F]
#     stack, so the data path no longer caps the rung before HBM does)
for s in 0.002 0.005 0.01 0.02 0.05; do
  run_stage "p100m scale=$s" bash -c "set -o pipefail; timeout 2400 python experiments/papers100m_gcn.py --synthetic_scale $s --epochs 3 --world_size 1 --log_path logs/p100m_step.jsonl 2>&1 | tail -5" || break
done
commit_stage p100m logs/p100m_step.jsonl

date -u +"%Y-%m-%dT%H:%M:%SZ r4c queue done"
