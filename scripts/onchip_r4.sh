#!/bin/bash
# Round-4 on-chip queue. Runs the VERDICT-r3-ordered measurements once the
# TPU lease recovers. Wedge-risk-aware ordering: the headline GCN epoch
# number is captured and COMMITTED before any stage that has previously
# wedged the lease (GraphCast level 6 OOM'd and wedged it in r2).
# Artifacts are committed after EVERY stage, not just at queue end.
cd /root/repo
set -o pipefail
exec >> logs/onchip_r4.log 2>&1
date -u +"%Y-%m-%dT%H:%M:%SZ r4 queue start"

probe() { timeout 90 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu', jax.default_backend()
float(jnp.ones((8,128)).sum())" >/dev/null 2>&1; }

commit_stage() {  # commit_stage NAME FILES...
  name=$1; shift
  # add one file per invocation, existing files only: a single git add
  # with a missing pathspec stages NOTHING, which would lose every
  # artifact of a partially-completed stage
  for f in "$@" logs/onchip_r4.log; do
    [ -e "$f" ] && git add -f "$f"
  done
  git commit -q -m "On-chip r4 queue: $name artifacts

No-Verification-Needed: measurement logs only" || true
}

run_stage() {
  name=$1; shift
  if ! probe; then
    date -u +"%Y-%m-%dT%H:%M:%SZ $name skipped (lease wedged)"
    return 1
  fi
  "$@"
  rc=$?
  date -u +"%Y-%m-%dT%H:%M:%SZ $name done rc=$rc"
  return $rc
}

# 1. Headline number FIRST: GCN-only bench (GraphCast stage disabled).
#    This is the metric three rounds have failed to produce; nothing
#    risky runs before it.
run_stage bench_gcn bash -c 'DGRAPH_BENCH_GRAPHCAST=0 DGRAPH_BENCH_TIMEOUT=2400 python bench.py > logs/bench_r4_gcn.json 2>logs/bench_r4_gcn.err'
date -u +"%Y-%m-%dT%H:%M:%SZ gcn json: $(tail -1 logs/bench_r4_gcn.json 2>/dev/null)"
commit_stage bench_gcn logs/bench_r4_gcn.json logs/bench_r4_gcn.err

# 2. Kernel tile sweep (VERDICT r3 #2: settle both gather defaults on the
#    fixed timing harness; low memory risk).
if run_stage sweep bash -c 'set -o pipefail; timeout 2400 python experiments/kernel_benchmarks.py --sweep true --dtypes float32,bfloat16 2>&1 | tail -30'; then
  # winners ONLY from a completed r4 sweep — a skipped/killed stage would
  # leave stale r3 rows (broken timing harness) and the analysis would
  # silently bless them
  python scripts/adopt_sweep.py logs/kernel_benchmarks.jsonl > logs/sweep_winners.txt 2>&1 || true
fi
commit_stage sweep logs/kernel_benchmarks.jsonl logs/sweep_winners.txt

# 3. Gather-kernel A/B: GCN bench with the sorted-row-gather kernel
#    pinned on (self-check-vetoed). Compare value vs logs/bench_r4_gcn.json.
run_stage bench_gatherk bash -c 'DGRAPH_TPU_PALLAS_GATHER=1 DGRAPH_BENCH_GRAPHCAST=0 DGRAPH_BENCH_TIMEOUT=2400 python bench.py > logs/bench_r4_gatherk.json 2>logs/bench_r4_gatherk.err'
date -u +"%Y-%m-%dT%H:%M:%SZ gatherk json: $(tail -1 logs/bench_r4_gatherk.json 2>/dev/null)"
commit_stage bench_gatherk logs/bench_r4_gatherk.json logs/bench_r4_gatherk.err

# 4. op profile (VERDICT r3 #5: explain the 2x epoch residual)
run_stage op_profile bash -c 'set -o pipefail; timeout 1500 python experiments/op_profile.py 2>&1 | tail -20'
commit_stage op_profile logs/op_profile.jsonl

# 5. Flash-attention A/B at seq 8192 (VERDICT r3 #8) — before the
#    known-wedge-risk stages.
for fl in 0 1; do
  run_stage "lm flash=$fl" bash -c "set -o pipefail; DGRAPH_TPU_FLASH_ATTN=$fl timeout 1200 python experiments/long_context_lm.py --seq_len 8192 --steps 30 --world_size 1 --latent 256 --num_heads 2 --attn_impl ulysses --log_path logs/lm_flash${fl}_onchip.jsonl 2>&1 | tail -2" || break
done
commit_stage flash_ab logs/lm_flash0_onchip.jsonl logs/lm_flash1_onchip.jsonl

# 6. GraphCast level 6 (VERDICT r3 #3). RISK: this exact stage OOM'd and
#    wedged the lease in r2; everything above is already committed.
run_stage bench_graphcast bash -c 'DGRAPH_BENCH_TIMEOUT=3000 python bench.py > logs/bench_r4_full.json 2>logs/bench_r4_full.err'
date -u +"%Y-%m-%dT%H:%M:%SZ full json: $(tail -1 logs/bench_r4_full.json 2>/dev/null)"
commit_stage bench_graphcast logs/bench_r4_full.json logs/bench_r4_full.err

# 7. papers100M ladder (VERDICT r3 #4): ascending fractions, stop at the
#    first failure so a success is recorded before risking an OOM.
for s in 0.002 0.005 0.01 0.02; do
  run_stage "p100m scale=$s" bash -c "set -o pipefail; timeout 2400 python experiments/papers100m_gcn.py --synthetic_scale $s --epochs 3 --world_size 1 --log_path logs/p100m_step.jsonl 2>&1 | tail -5" || break
done
commit_stage p100m logs/p100m_step.jsonl

date -u +"%Y-%m-%dT%H:%M:%SZ r4 queue done"
