#!/bin/bash
# Round-4b regression hunt: the first driver-verifiable GCN number
# (logs/bench_r4_gcn.json, 597.7 ms) is a REGRESSION vs the r1 456.9 ms
# baseline. One-variable A/Bs on the exact bench harness to bisect where
# the epoch goes. Each stage commits its artifact (append-only pattern
# from onchip_r4.sh). GraphCast disabled throughout (GCN-only, fast).
cd /root/repo
set -o pipefail
exec >> logs/ab_r4b.log 2>&1
date -u +"%Y-%m-%dT%H:%M:%SZ r4b A/B start"

probe() { timeout 90 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu', jax.default_backend()
float(jnp.ones((8,128)).sum())" >/dev/null 2>&1; }

commit_stage() {
  name=$1; shift
  for f in "$@" logs/ab_r4b.log; do
    [ -e "$f" ] && git add -f "$f"
  done
  git commit -q -m "r4b A/B: $name artifacts

No-Verification-Needed: measurement logs only" || true
}

run_ab() {  # run_ab NAME ENVSTR
  name=$1; env_str=$2
  if ! probe; then
    date -u +"%Y-%m-%dT%H:%M:%SZ $name skipped (lease wedged)"
    return 1
  fi
  env $env_str DGRAPH_BENCH_GRAPHCAST=0 DGRAPH_BENCH_TIMEOUT=2400 \
    python bench.py > "logs/bench_r4b_${name}.json" 2>"logs/bench_r4b_${name}.err"
  rc=$?
  date -u +"%Y-%m-%dT%H:%M:%SZ $name rc=$rc json: $(tail -1 logs/bench_r4b_${name}.json 2>/dev/null)"
  commit_stage "$name" "logs/bench_r4b_${name}.json" "logs/bench_r4b_${name}.err"
  return $rc
}

# 1. Fused kernel with the Mosaic bf16 [:,None] fix: does it pass the
#    self-check now, and what does fusion buy end-to-end?
run_ab fusedfix ""

# 2. Pallas scatter OFF (pure XLA segment_sum path): measures the Pallas
#    scatter's total contribution to the epoch.
run_ab noscatter "DGRAPH_TPU_PALLAS_SCATTER=0 DGRAPH_TPU_PALLAS_FUSED=0"

# 3. Column chunking OFF (gather_col_block=0): the 128 default rests on
#    invalidated r2 data (VERDICT r3 weak #2).
run_ab nocolblk "DGRAPH_TPU_GATHER_COL_BLOCK=0"

# 4. Both off: the minimal all-XLA path.
run_ab allxla "DGRAPH_TPU_PALLAS_SCATTER=0 DGRAPH_TPU_PALLAS_FUSED=0 DGRAPH_TPU_GATHER_COL_BLOCK=0"

# 5. float32 control (r1's 456.9 baseline may predate the bf16 default;
#    rules dtype in or out as the regression variable).
run_ab f32 "DGRAPH_BENCH_DTYPE=float32"

date -u +"%Y-%m-%dT%H:%M:%SZ r4b A/B done"
