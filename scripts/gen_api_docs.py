"""Generate docs/reference.md from the package's docstrings.

The reference's docs site drives this page from mkdocstrings
(``mkdocs.yml`` + ``docs/reference.md`` ``:::`` directives); this repo
can't install mkdocs plugins (no egress), so the same content is emitted
as plain markdown by introspection — rerun after API changes:

    python scripts/gen_api_docs.py
"""

from __future__ import annotations

import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# (section title, module path, names; None = module __all__ or public defs)
SECTIONS = [
    ("Communicator", "dgraph_tpu.comm.communicator",
     ["Communicator", "TpuComm", "SingleComm"]),
    ("Differentiable collectives", "dgraph_tpu.comm.collectives", None),
    ("Device mesh", "dgraph_tpu.comm.mesh", None),
    ("Multi-host launch", "dgraph_tpu.comm.multihost", None),
    ("Elastic world membership", "dgraph_tpu.comm.membership",
     ["Membership", "RankLost", "MembershipChanged", "Straggler",
      "RankLostError", "DeadlineExceeded", "read_roster",
      "RANK_LOST_EXIT_CODE", "Joiner", "JoinRequest", "RankJoinError",
      "grant_join", "read_joins", "RANK_JOIN_EXIT_CODE"]),
    ("Communication plans", "dgraph_tpu.plan",
     ["CommPattern", "EdgePlan", "OverlapSpec", "build_edge_plan",
      "build_comm_pattern", "compute_comm_map", "validate_plan",
      "plan_memory_usage", "interior_boundary_edge_counts",
      "pick_halo_impl", "resolve_halo_impl"]),
    ("Sharded plan builds (cache format v8)", "dgraph_tpu.plan",
     ["build_plan_shards", "build_edge_plan_sharded", "load_sharded_plan",
      "assemble_plan", "shard_nbytes_estimate", "reshard_vertex_data"]),
    ("Halo schedule compiler", "dgraph_tpu.sched", None),
    ("Wire formats: registry & resolution", "dgraph_tpu.wire.spec",
     ["WireFormat", "get_format", "fp8_available", "resolve_wire_format",
      "np_encode", "np_decode", "np_roundtrip_bound",
      "np_encode_compensated", "delta_skip_rows", "WIRE_FORMAT_NAMES",
      "FP8_SCALE_BYTES"]),
    ("Wire formats: jax codecs", "dgraph_tpu.wire.codec",
     ["make_wire_transform", "make_wire_codec", "make_a2a_codec",
      "make_ppermute_codec", "encode_compensated", "fp8_jnp_ok"]),
    ("Wire formats: hub-row dedup", "dgraph_tpu.wire.dedup",
     ["HubRow", "RelayTransfer", "DedupPlan", "pair_live_rows",
      "detect_hub_rows", "build_dedup_plan", "verify_dedup_coverage",
      "dedup_stats"]),
    ("Plan shard IO & integrity", "dgraph_tpu.plan_shards",
     ["PlanShardWriter", "PlanManifestError", "PlanShardError",
      "PlanBuildMemoryExceeded", "read_manifest", "write_manifest",
      "atomic_write_json", "read_shard", "write_shard", "bad_shards",
      "payload_nbytes", "resolve_memory_budget"]),
    ("Partitioning", "dgraph_tpu.partition", None),
    ("Rank-local ops", "dgraph_tpu.ops.local", None),
    ("Pallas kernels", "dgraph_tpu.ops.pallas_segment",
     ["sorted_segment_sum", "sorted_segment_sum_bias_relu",
      "sorted_row_gather", "max_chunks_hint", "max_vblocks_hint"]),
    ("Pallas one-sided halo transport", "dgraph_tpu.ops.pallas_p2p",
     ["p2p_transport", "p2p_interpret_mode", "transport_fused_mask",
      "FUSED_MASK_VMEM_BUDGET", "P2P_COLLECTIVE_ID"]),
    ("Models", "dgraph_tpu.models", None),
    ("GraphCast", "dgraph_tpu.models.graphcast", None),
    ("Tensor parallelism", "dgraph_tpu.parallel.tensor", None),
    ("Pipeline parallelism", "dgraph_tpu.parallel.pipeline", None),
    ("Sequence/context parallelism", "dgraph_tpu.parallel.sequence", None),
    ("Expert parallelism (MoE)", "dgraph_tpu.parallel.expert", None),
    ("Data layer", "dgraph_tpu.data", None),
    ("Training utilities", "dgraph_tpu.train.loop", None),
    ("Elastic / failure handling", "dgraph_tpu.train.elastic", None),
    ("Train supervisor", "dgraph_tpu.train.supervise",
     ["supervise", "supervise_group"]),
    ("Shrink-to-fit recovery", "dgraph_tpu.train.shrink",
     ["init_world", "shrink_world", "read_world", "write_world",
      "ShrinkError"]),
    ("Grow-to-fit expansion", "dgraph_tpu.train.grow",
     ["grow_world", "grant_joined", "grow_record", "GrowError"]),
    ("Non-finite step guard", "dgraph_tpu.train.guard",
     ["NonFiniteMonitor", "NonFiniteAbort"]),
    ("Chaos fault injection", "dgraph_tpu.chaos",
     ["ChaosFault", "Clause", "parse_spec", "fire", "arm", "disarm",
      "active_spec", "poison_array", "poison_pytree"]),
    ("Checkpointing", "dgraph_tpu.train.checkpoint", None),
    ("Serving: engine", "dgraph_tpu.serve.engine", ["ServeEngine"]),
    ("Serving: shape bucketing", "dgraph_tpu.serve.bucketing",
     ["BucketLadder", "pad_ids"]),
    ("Serving: micro-batching", "dgraph_tpu.serve.batcher", ["MicroBatcher"]),
    ("Serving: errors & health", "dgraph_tpu.serve.errors",
     ["ServeError", "RequestTooLarge", "QueueFull", "RequestTimeout",
      "EngineStopped", "QuotaExceeded", "TenantDegraded", "SwapRejected"]),
    ("Serving: health record", "dgraph_tpu.serve.health",
     ["serve_health_record"]),
    ("Serving: hot-swap rollover", "dgraph_tpu.serve.rollover",
     ["swap_params", "params_mismatch", "nonfinite_param_leaves"]),
    ("Serving: model registry", "dgraph_tpu.serve.registry",
     ["ModelRegistry"]),
    ("Serving: tenant isolation", "dgraph_tpu.serve.tenancy",
     ["TenantTable", "TenantQuota", "TokenBucket", "DEFAULT_TENANT"]),
    ("Serving: live graph deltas", "dgraph_tpu.serve.deltas",
     ["init_world", "append_delta", "replan", "load_generation",
      "build_engine", "read_world", "write_world", "assign_new_vertices",
      "staged_delta_paths", "DeltaError"]),
    ("Timing & tracing", "dgraph_tpu.utils.timing", None),
    ("Observability: comm footprint", "dgraph_tpu.obs.footprint",
     ["plan_footprint", "dtype_bytes"]),
    ("Observability: step metrics", "dgraph_tpu.obs.metrics",
     ["StepMetrics", "Metrics", "step_record"]),
    ("Observability: run health", "dgraph_tpu.obs.health",
     ["RunHealth", "classify_wedge", "startup_record"]),
    ("Observability: span tracing", "dgraph_tpu.obs.spans",
     ["Tracer", "Span", "span", "enable", "disable", "enabled",
      "current_span", "current_trace_id", "child_env", "read_spans",
      "export_perfetto"]),
    ("Observability: step-time attribution", "dgraph_tpu.obs.attribution",
     ["scan_delta_attribution", "multichip_family_table"]),
    ("Observability: perf-trajectory ledger", "dgraph_tpu.obs.ledger",
     ["normalize_record", "ingest", "maybe_ingest", "read_ledger",
      "backfill", "resolve_ledger_dir", "atomic_append_jsonl",
      "ledger_path", "LEDGER_SCHEMA_VERSION",
      "SERVE_HEALTH_SCHEMA_VERSION"]),
    ("Observability: drift sentinel", "dgraph_tpu.obs.regress",
     ["check_ledger", "metric_class", "baseline_stats",
      "dropped_tier_verdicts"]),
    ("Observability: trajectory report", "dgraph_tpu.obs.report",
     ["render_trajectory", "sparkline"]),
    ("Autotuning: signatures", "dgraph_tpu.tune.signature",
     ["graph_signature", "signature_key", "degree_histogram"]),
    ("Autotuning: records & adoption", "dgraph_tpu.tune.record",
     ["TuningRecord", "lookup_record", "adopt_record",
      "default_record_dir"]),
    ("Autotuning: search", "dgraph_tpu.tune.search",
     ["search", "candidate_cost", "choose_ladder", "SearchResult"]),
    ("Autotuning: measured phase", "dgraph_tpu.tune.measure",
     ["measure_plan_ms"]),
    ("Autotuning: kernel-sweep winners", "dgraph_tpu.tune.adopt",
     ["pick_winners", "sweep_report"]),
    ("Static analysis: trace auditor", "dgraph_tpu.analysis.trace",
     ["walk_eqns", "collect_collectives", "build_audit_workload",
      "audit_workload", "donation_unmatched", "schedule_drift_record"]),
    ("Static analysis: lowered-artifact auditor", "dgraph_tpu.analysis.hlo",
     ["lower_program", "collect_stablehlo", "audit_workload_hlo",
      "donation_entries", "hlo_drift_record", "COLLECTIVE_HLO_OPS"]),
    ("Static analysis: Pallas DMA-discipline verifier",
     "dgraph_tpu.analysis.kernel",
     ["collect_transports", "verify_transport", "audit_workload_kernels",
      "kernel_selftest_failures"]),
    ("Static analysis: cross-rank SPMD divergence auditor",
     "dgraph_tpu.analysis.spmd",
     ["build_spmd_fixture", "build_shrink_fixture", "build_rank_workload",
      "rank_live_deltas", "canonical_module_text",
      "canonicalize_rank_modules", "collective_sequence",
      "resolution_agreement", "audit_plan_dir_spmd", "spmd_drift_record",
      "spmd_selftest"]),
    ("Static analysis: host concurrency & durability auditor",
     "dgraph_tpu.analysis.host",
     ["scan_module", "class_concurrency_findings", "build_lock_graph",
      "lock_order_findings", "durable_write_findings",
      "pointer_flip_findings", "chaos_coverage_findings",
      "run_host_audit", "host_selftest_failures"]),
    ("Static analysis: contract linter", "dgraph_tpu.analysis.lint",
     ["Finding", "Rule", "rule", "path_matcher", "lint_file", "run_lint"]),
    ("Config & flags", "dgraph_tpu.config", None),
]


def public_names(mod):
    if hasattr(mod, "__all__"):
        return list(mod.__all__)
    out = []
    for n, obj in vars(mod).items():
        if n.startswith("_"):
            continue
        if inspect.isclass(obj) or inspect.isfunction(obj):
            if getattr(obj, "__module__", None) == mod.__name__:
                out.append(n)
    return out


def scrub_addresses(text):
    """Default-value reprs embed object addresses (flax's _Sentinel, jax
    PjitFunction, custom_jvp...) in signatures AND dataclass
    auto-docstrings — scrub them or regeneration is nondeterministic."""
    import re

    return re.sub(r" at 0x[0-9a-fA-F]+", "", text)


def fmt_signature(name, obj):
    try:
        sig = str(inspect.signature(obj))
    except (TypeError, ValueError):
        sig = "(...)"
    return scrub_addresses(f"{name}{sig}")


def emit_obj(lines, name, obj, depth):
    head = "#" * depth
    kind = "class" if inspect.isclass(obj) else "function"
    lines.append(f"{head} `{fmt_signature(name, obj)}`\n")
    doc = inspect.getdoc(obj)
    if doc:
        lines.append(scrub_addresses(doc) + "\n")
    else:
        lines.append(f"*(undocumented {kind})*\n")
    if inspect.isclass(obj):
        for mn, m in sorted(vars(obj).items()):
            if mn.startswith("_") or not callable(m):
                continue
            mdoc = inspect.getdoc(m)
            if not mdoc:
                continue
            first = mdoc.splitlines()[0]
            lines.append(f"- **`{fmt_signature(mn, m)}`** — {first}\n")


def main():
    import importlib

    lines = [
        "# API reference\n",
        "*Generated by `scripts/gen_api_docs.py` — do not edit by hand.*\n",
    ]
    for title, modpath, names in SECTIONS:
        mod = importlib.import_module(modpath)
        lines.append(f"## {title}\n")
        mod_doc = inspect.getdoc(mod)
        if mod_doc:
            # first paragraph of the module docstring as the section intro
            lines.append(mod_doc.split("\n\n")[0] + "\n")
        lines.append(f"*Module: `{modpath}`*\n")
        for name in names or public_names(mod):
            obj = getattr(mod, name, None)
            if obj is None:
                raise SystemExit(f"{modpath}.{name} does not exist")
            if not (inspect.isclass(obj) or callable(obj)):
                lines.append(f"### `{name}`\n\n{type(obj).__name__} constant.\n")
                continue
            emit_obj(lines, name, obj, 3)
    out = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "docs", "reference.md")
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as fh:
        fh.write("\n".join(lines))
    print(f"wrote {out} ({sum(len(l) for l in lines)} chars, "
          f"{len(lines)} blocks)")


if __name__ == "__main__":
    main()
