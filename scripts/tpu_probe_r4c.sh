#!/bin/bash
# Probe the tunneled TPU every 2 min; on recovery run the r4c on-chip queue.
cd /root/repo
while true; do
  if timeout 120 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu'
float(jnp.ones((8,128)).sum())" >/dev/null 2>&1; then
    date -u +"%Y-%m-%dT%H:%M:%SZ recovered - launching r4c queue" >> logs/tpu_probe.log
    bash scripts/onchip_r4c.sh
    exit 0
  fi
  date -u +"%Y-%m-%dT%H:%M:%SZ still-wedged" >> logs/tpu_probe.log
  sleep 120
done
