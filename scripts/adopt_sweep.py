"""Pick kernel tile winners from the sweep log (VERDICT r3 #1/#2).

Reads ``logs/kernel_benchmarks.jsonl`` (the ``kernel_benchmarks.py
--sweep true`` output), prints the fastest (block_e, block_n) per
(kernel, dtype) plus the XLA-vs-Pallas verdicts the config defaults hang
on. Pure stdlib — runs with the TPU lease in any state.

    python scripts/adopt_sweep.py [logs/kernel_benchmarks.jsonl]
"""

from __future__ import annotations

import json
import sys
from collections import defaultdict


def main(path: str = "logs/kernel_benchmarks.jsonl") -> None:
    rows = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if line.startswith("{"):
                rows.append(json.loads(line))
    if not rows:
        raise SystemExit(f"no records in {path}")

    # latest record wins for identical keys (the log is append-only)
    def key(r, *names):
        return tuple(r.get(n) for n in names)

    sweep = defaultdict(dict)   # (op, dtype, F) -> {(be, bn): ms}
    flat = {}                   # (op, dtype, F) -> ms (non-sweep rows)
    for r in rows:
        ms = r.get("ms")
        # NaN rows mark per-op failures (a crashed compile, a noisy
        # tunnel); min() over a dict containing NaN can crown the crashed
        # tile as WINNER (every x < nan is False), so drop non-finite
        if ms is None or ms != ms:
            continue
        k = key(r, "op", "dtype", "F")
        if "block_e" in r:
            sweep[k][(r["block_e"], r["block_n"])] = r["ms"]
        else:
            flat[k] = r["ms"]

    print("== tile winners (lowest ms) ==")
    winners = {}
    for k, tiles in sorted(sweep.items()):
        best = min(tiles, key=tiles.get)
        winners[k] = best
        ranked = sorted(tiles.items(), key=lambda kv: kv[1])
        line = ", ".join(f"{be}x{bn}={ms:.3f}" for (be, bn), ms in ranked[:4])
        print(f"{k[0]} [{k[1]} F={k[2]}]: WINNER block_e={best[0]} "
              f"block_n={best[1]}  ({line})")

    # the precision the framework actually DEPLOYS per dtype
    # (ops/local.py: prec="highest" whenever dtype != bfloat16 — comparing
    # the bf16-MXU "default" variant for f32 would judge a kernel that
    # never runs in f32 training)
    def deployed_scatter_op(dtype):
        # kernel_benchmarks logs dtype as "bf16"/"f32"
        is_bf16 = dtype in ("bf16", "bfloat16")
        return ("segment_sum_pallas_default" if is_bf16
                else "segment_sum_pallas_highest")

    print("\n== XLA vs Pallas verdicts (deployed precision per dtype) ==")
    for k, ms_x in sorted(flat.items()):
        op, dtype, F = k
        if op == "segment_sum_xla":
            pl_ops, flag = [deployed_scatter_op(dtype)], "use_pallas_scatter"
        elif op == "gather_sorted_xla":
            pl_ops = ["gather_sorted_pallas", "gather_sorted_pallas_sweep"]
            flag = "use_pallas_gather"
        else:
            continue
        best_p = None
        for pl_op in pl_ops:
            k_pl = (pl_op, dtype, F)
            cands = [flat[k_pl]] if k_pl in flat else []
            if k_pl in sweep:
                cands.append(min(sweep[k_pl].values()))
            for ms in cands:
                best_p = ms if best_p is None else min(best_p, ms)
        if best_p is None:
            continue
        verdict = "PALLAS" if best_p < ms_x else "XLA"
        print(f"{flag} [{dtype} F={F}]: xla={ms_x:.3f} "
              f"pallas={best_p:.3f} -> {verdict} ({ms_x / best_p:.2f}x)")

    if winners:
        # consensus tile across kernels/dtypes: the plan carries ONE
        # (scatter_block_e, scatter_block_n) pair serving BOTH kernels, so
        # each (kernel FAMILY, dtype, F) gets exactly one vote — counting
        # both precision variants of the scatter would double-weight it
        # against the gather
        def family(op, dtype):
            if op.startswith("segment_sum_pallas"):
                return ("scatter", dtype) if op == deployed_scatter_op(
                    dtype) else None
            if op.startswith("gather_sorted_pallas"):
                return ("gather", dtype)
            return None

        votes = defaultdict(int)
        for (op, dtype, F), best in winners.items():
            if family(op, dtype) is None:
                continue
            votes[best] += 1
        if votes:
            (be, bn), n = max(votes.items(), key=lambda kv: kv[1])
            print(f"\n== consensus: block_e={be} block_n={bn} "
                  f"({n}/{sum(votes.values())} family votes) ==")
            print("adopt in: dgraph_tpu/plan.py (scatter_block_e/_n "
                  "defaults) + PLAN_FORMAT_VERSION bump if changed")


if __name__ == "__main__":
    main(*sys.argv[1:])
