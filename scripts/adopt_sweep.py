"""Pick kernel tile winners from the sweep log (VERDICT r3 #1/#2).

Thin wrapper: the winner-picking (including the NaN-row guard) now lives
in ``dgraph_tpu/tune/adopt.py`` so the autotuner can consume the same
measured data. This script keeps the historical entry point:

    python scripts/adopt_sweep.py [logs/kernel_benchmarks.jsonl]

The module is loaded by file path, NOT via the package (whose __init__
imports jax): pure stdlib, so the script keeps running with the TPU lease
in any state — same discipline as bench.py's supervisor.
"""

from __future__ import annotations

import importlib.util
import os
import sys


def _load_adopt():
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "dgraph_tpu", "tune", "adopt.py",
    )
    spec = importlib.util.spec_from_file_location("_dgraph_tune_adopt", path)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_dgraph_tune_adopt"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(path: str = "logs/kernel_benchmarks.jsonl") -> None:
    _load_adopt().main(path)


if __name__ == "__main__":
    main(*sys.argv[1:])
