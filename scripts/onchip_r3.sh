#!/bin/bash
# Round-3 on-chip queue: runs the VERDICT-ordered measurements once the
# TPU lease recovers. Logs under /root/repo/logs/.
cd /root/repo
set -o pipefail  # rc must reflect the python step, not the trailing tail
exec >> logs/onchip_r3.log 2>&1
date -u +"%Y-%m-%dT%H:%M:%SZ queue start"

probe() { timeout 90 python -c "
import jax, jax.numpy as jnp
assert jax.default_backend() == 'tpu', jax.default_backend()
float(jnp.ones((8,128)).sum())" >/dev/null 2>&1; }

# run_stage NAME CMD...: probe first (a failed probe logs "skipped
# (wedged)", NOT a stage rc — the artifact must distinguish
# never-started from crashed), then run and log the stage's own rc.
run_stage() {
  name=$1; shift
  if ! probe; then
    date -u +"%Y-%m-%dT%H:%M:%SZ $name skipped (lease wedged)"
    return 1
  fi
  "$@"
  rc=$?
  date -u +"%Y-%m-%dT%H:%M:%SZ $name done rc=$rc"
  return $rc
}

# 1. op profile (VERDICT #2: explain the epoch residual)
run_stage op_profile bash -c 'set -o pipefail; timeout 1500 python experiments/op_profile.py 2>&1 | tail -20'

# 2. kernel tile sweep (VERDICT #3)
run_stage sweep bash -c 'set -o pipefail; timeout 2400 python experiments/kernel_benchmarks.py --sweep true --dtypes float32,bfloat16 2>&1 | tail -30'

# 3. full bench (GCN epoch + GraphCast level 6) — supervisor makes this
#    un-losable; budget generous since the queue owns the window
run_stage bench bash -c 'DGRAPH_BENCH_TIMEOUT=3000 python bench.py > logs/bench_r3.json 2>logs/bench_r3.err'
[ $? -eq 0 ] && date -u +"%Y-%m-%dT%H:%M:%SZ bench json: $(tail -1 logs/bench_r3.json 2>/dev/null)"

# 3b. gather-kernel A/B: same bench with the sorted-row-gather kernel
#     pinned on (self-check-vetoed). Compare value vs logs/bench_r3.json.
run_stage bench_gatherk bash -c 'DGRAPH_TPU_PALLAS_GATHER=1 DGRAPH_BENCH_TIMEOUT=3000 python bench.py > logs/bench_r3_gatherk.json 2>logs/bench_r3_gatherk.err'
[ $? -eq 0 ] && date -u +"%Y-%m-%dT%H:%M:%SZ gatherk json: $(tail -1 logs/bench_r3_gatherk.json 2>/dev/null)"

# 4. papers100M ladder: ascending fractions, stop at first failure
#    (a success is recorded before risking an OOM at the next rung)
for s in 0.002 0.005 0.01 0.02; do
  run_stage "p100m scale=$s" bash -c "set -o pipefail; timeout 2400 python experiments/papers100m_gcn.py --synthetic_scale $s --epochs 3 --world_size 1 --log_path logs/p100m_step.jsonl 2>&1 | tail -5" || break
done
# 5. long-context attention A/B on one chip: Ulysses dense stage with the
#    Mosaic flash kernel (self-check-gated) vs the XLA dense path
#    (seq 8192, head_dim 128 — flash shape gate satisfied)
for fl in 0 1; do
  run_stage "lm flash=$fl" bash -c "set -o pipefail; DGRAPH_TPU_FLASH_ATTN=$fl timeout 1200 python experiments/long_context_lm.py --seq_len 8192 --steps 30 --world_size 1 --latent 256 --num_heads 2 --attn_impl ulysses --log_path logs/lm_flash${fl}_onchip.jsonl 2>&1 | tail -2" || break
done
date -u +"%Y-%m-%dT%H:%M:%SZ queue done"
# logs/ is gitignored; the round's measurement artifacts must be committed
git add -f logs/onchip_r3.log logs/op_profile.jsonl logs/kernel_benchmarks.jsonl \
  logs/bench_r3.json logs/bench_r3_gatherk.json logs/p100m_step.jsonl \
  logs/lm_flash0_onchip.jsonl logs/lm_flash1_onchip.jsonl 2>/dev/null
git commit -q -m "On-chip measurement artifacts from the round-3 queue

No-Verification-Needed: measurement logs only" || true
