#!/usr/bin/env python
"""One-shot static-analysis gate: lint + trace audit + selftest.

Runs the analysis CLI in subprocesses (each pinned to the virtual-CPU
backend — this script never dials an accelerator and works on a machine
with no chip at all) and exits nonzero if ANY pass fails:

    python scripts/check.py            # lint + audit + analysis selftest
    python scripts/check.py --all      # also the chaos/tune/serve selftests
    python scripts/check.py --jobs 4   # fan the independent selftest
                                       # subprocesses out 4 wide (default
                                       # stays serial)

Intended as the pre-merge gate and as the cheap first half of a bench
round: everything here is compile-free (abstract tracing only), so a full
run is ~30 s on a laptop CPU.  This file stays jax-free on purpose — it
must be able to report a broken environment rather than hang in it.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

PASSES = [
    # default analysis = lint + ALL audit tiers (jaxpr trace, lowered
    # StableHLO, pallas_p2p DMA discipline) on the canonical workload
    ("analysis", [sys.executable, "-m", "dgraph_tpu.analysis"]),
    ("analysis-selftest",
     [sys.executable, "-m", "dgraph_tpu.analysis", "--selftest", "true"]),
    # Pallas DMA-discipline verifier standalone: the broken-kernel
    # vacuity guards (dropped dma_wait & co.) plus the real-transport
    # audit — make_jaxpr only, zero XLA compiles
    ("kernel-verifier-selftest",
     [sys.executable, "-m", "dgraph_tpu.analysis.kernel",
      "--selftest", "true"]),
    # host-side concurrency & durability auditor: guarded-field/lock
    # discipline, lock-order cycles, atomic durable writes,
    # pointer-flip-last commits, chaos-registry coverage — stdlib ast,
    # zero compiles by construction (the vacuity mutants must go RED)
    ("host-auditor-selftest",
     [sys.executable, "-m", "dgraph_tpu.analysis.host",
      "--selftest", "true"]),
    ("spans-selftest",
     [sys.executable, "-m", "dgraph_tpu.obs.spans", "--selftest", "true"]),
    # sharded plan artifacts (cache format v8): manifest/shard integrity,
    # writer resume, memory budget, chaos points — pure numpy+stdlib IO
    ("plan-shards-selftest",
     [sys.executable, "-m", "dgraph_tpu.plan_shards", "--selftest", "true"]),
    # elastic world membership: heartbeat/lease liveness, barriers,
    # rendezvous, straggler/loss events — pure stdlib, fake-clock driven
    ("membership-selftest",
     [sys.executable, "-m", "dgraph_tpu.comm.membership",
      "--selftest", "true"]),
    # device-initiated one-sided halo transport: interpret-mode put
    # parity vs the masked all_to_all on 2- and 4-shard rings (tiny CPU
    # compiles only — the kernels never dial an accelerator here)
    ("pallas-p2p-selftest",
     [sys.executable, "-m", "dgraph_tpu.ops.pallas_p2p",
      "--selftest", "true"]),
    # cross-rank SPMD divergence auditor standalone: per-rank lowered-
    # module identity + collective issue order on 2/4-shard worlds and a
    # real shrink transition, plus the seeded-divergence vacuity mutants
    # — lower-only, zero XLA compiles
    ("spmd-selftest",
     [sys.executable, "-m", "dgraph_tpu.analysis.spmd",
      "--selftest", "true"]),
    # halo schedule compiler: IR round-trip identity, pass-pipeline
    # invariants (conflict-freedom, exact coverage, split/pack bounds),
    # and the vacuity mutants (a conflicting round and a dropped
    # transfer must each go RED) — pure stdlib, zero XLA compiles
    ("sched-selftest",
     [sys.executable, "-m", "dgraph_tpu.sched", "--selftest", "true"]),
    # perf-trajectory drift sentinel: the seven seeded-drift vacuity
    # mutants (inflated wire bytes, slowed scan-delta, fattened p99,
    # dropped fallback tier, drifted schedule, drifted wire-format
    # bytes, drifted grown world) must each go RED and the clean fixture
    # ledger must gate GREEN — pure stdlib, zero compiles
    ("regress-selftest",
     [sys.executable, "-m", "dgraph_tpu.obs.regress",
      "--selftest", "true"]),
    # grow-to-fit transition smoke: join rendezvous -> background W+k
    # re-plan -> reshard -> atomic adoption on a tiny fixture run, plus
    # the two subprocess sigterm pins (commit boundary AND mid-shard
    # stream must both leave world.json on a complete generation) —
    # compile-free, fake-clock driven
    ("grow-selftest",
     [sys.executable, "-m", "dgraph_tpu.train.grow",
      "--selftest", "true"]),
    # wire codec layer: registry byte pins, numpy round-trip bounds per
    # format, the wrong-scale/dropped-row vacuity mutants, the resolver
    # ladder, the hub-dedup plan fixtures, and the jax-free guard —
    # pure stdlib + numpy, zero compiles
    ("wire-selftest",
     [sys.executable, "-m", "dgraph_tpu.wire", "--selftest", "true"]),
]

EXTRA_SELFTESTS = [
    ("chaos-selftest",
     [sys.executable, "-m", "dgraph_tpu.chaos", "--selftest", "true"]),
    ("tune-selftest",
     [sys.executable, "-m", "dgraph_tpu.tune", "--selftest", "true"]),
    ("serve-selftest",
     [sys.executable, "-m", "dgraph_tpu.serve", "--selftest", "true"]),
]


def run_pass(name: str, argv: list, timeout: float) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    # hard assignment, not setdefault: every pass is a host-side static
    # check, and an ambient JAX_PLATFORMS=tpu (a TPU VM's default) would
    # send all of them dialing a possibly-wedged lease
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        proc = subprocess.run(
            argv, cwd=REPO, env=env, capture_output=True, text=True,
            timeout=timeout,
        )
        rc = proc.returncode
        lines = (proc.stdout or "").strip().splitlines()
        last = lines[-1] if lines else ""
        try:
            parsed = json.loads(last)
        except ValueError:
            parsed = None
        detail = (
            (parsed or {}).get("failures")
            or (proc.stderr or "").strip().splitlines()[-1:]
            if rc else None
        )
    except subprocess.TimeoutExpired:
        rc, detail = 124, [f"timed out after {timeout}s"]
    return {"pass": name, "rc": rc, "ok": rc == 0, "detail": detail}


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--all", action="store_true",
                    help="also run the chaos/tune/serve CLI selftests")
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-pass timeout in seconds")
    ap.add_argument("--jobs", type=int, default=1,
                    help="run up to N selftest subprocesses concurrently "
                         "(every pass is an independent subprocess; the "
                         "serial default keeps tier-1 timing unchanged)")
    args = ap.parse_args()

    passes = PASSES + (EXTRA_SELFTESTS if args.all else [])
    results = []
    # the passes are independent subprocesses by construction — fan them
    # out --jobs wide (max_workers=1 reproduces the serial gate exactly),
    # PRINTING in submission order so logs stay stable either way
    from concurrent.futures import ThreadPoolExecutor

    with ThreadPoolExecutor(max_workers=max(1, args.jobs)) as pool:
        futures = [
            (name, argv, pool.submit(run_pass, name, argv, args.timeout))
            for name, argv in passes
        ]
        for name, argv, fut in futures:
            print(f"[check] {name}: {' '.join(argv[1:])}", flush=True)
            res = fut.result()
            print(f"[check] {name}: {'OK' if res['ok'] else 'FAILED'}"
                  + (f" — {res['detail']}" if not res["ok"] else ""),
                  flush=True)
            results.append(res)
    ok = all(r["ok"] for r in results)
    print(json.dumps({"kind": "check_report", "ok": ok, "passes": results}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
