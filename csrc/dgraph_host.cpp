// Native host-side graph toolkit for dgraph_tpu.
//
// Role: the TPU-native counterpart of the reference's native layer. The
// reference's C++/CUDA lives in the device path
// (DGraph/distributed/csrc/*: gather/scatter kernels, NVSHMEM runtime); on
// TPU the device path is XLA/Pallas, so native code belongs where Python is
// actually the bottleneck: HOST-side plan building and partitioning of
// billion-edge graphs (SURVEY.md §7 "papers100M plan build memory/time").
//
// Exposed via a plain C ABI and loaded with ctypes (no pybind11 in this
// environment). Every entry point has a numpy fallback in
// dgraph_tpu/partition.py / plan.py — the reference's dual
// native/fallback pattern (RankLocalOps.py:21-31).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <queue>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// Build an undirected CSR adjacency from a directed edge list.
// indptr must hold V+1 entries; if indices == nullptr, only fills indptr
// (call once to size, once to fill).
void build_sym_csr(const int64_t* src, const int64_t* dst, int64_t num_edges,
                   int64_t num_vertices, int64_t* indptr, int64_t* indices) {
  std::vector<int64_t> deg(num_vertices, 0);
  for (int64_t e = 0; e < num_edges; ++e) {
    ++deg[src[e]];
    ++deg[dst[e]];
  }
  indptr[0] = 0;
  for (int64_t v = 0; v < num_vertices; ++v) indptr[v + 1] = indptr[v] + deg[v];
  if (!indices) return;
  std::vector<int64_t> cur(indptr, indptr + num_vertices);
  for (int64_t e = 0; e < num_edges; ++e) {
    indices[cur[src[e]]++] = dst[e];
    indices[cur[dst[e]]++] = src[e];
  }
}

// Greedy BFS region-growing partition with hard balance cap — the METIS
// substitute for very large graphs. Deterministic for a fixed seed.
void greedy_bfs_partition(const int64_t* src, const int64_t* dst,
                          int64_t num_edges, int64_t num_vertices,
                          int32_t world_size, uint64_t seed, int32_t* out_part) {
  std::vector<int64_t> indptr(num_vertices + 1);
  std::vector<int64_t> indices;
  build_sym_csr(src, dst, num_edges, num_vertices, indptr.data(), nullptr);
  indices.resize(indptr[num_vertices]);
  build_sym_csr(src, dst, num_edges, num_vertices, indptr.data(), indices.data());

  std::fill(out_part, out_part + num_vertices, -1);
  std::vector<int64_t> order(num_vertices);
  for (int64_t i = 0; i < num_vertices; ++i) order[i] = i;
  std::mt19937_64 rng(seed);
  std::shuffle(order.begin(), order.end(), rng);

  const int64_t cap = (num_vertices + world_size - 1) / world_size;
  int64_t seed_ptr = 0;
  std::vector<int64_t> stack;
  stack.reserve(1024);
  for (int32_t r = 0; r < world_size; ++r) {
    int64_t count = 0;
    stack.clear();
    while (count < cap) {
      if (stack.empty()) {
        while (seed_ptr < num_vertices && out_part[order[seed_ptr]] >= 0) ++seed_ptr;
        if (seed_ptr >= num_vertices) break;
        stack.push_back(order[seed_ptr]);
      }
      int64_t v = stack.back();
      stack.pop_back();
      if (out_part[v] >= 0) continue;
      out_part[v] = r;
      ++count;
      for (int64_t k = indptr[v]; k < indptr[v + 1]; ++k) {
        int64_t n = indices[k];
        if (out_part[n] < 0) stack.push_back(n);
      }
    }
  }
  for (int64_t v = 0; v < num_vertices; ++v)
    if (out_part[v] < 0) out_part[v] = world_size - 1;
}

namespace {

// Weighted undirected graph in CSR form for the multilevel partitioner.
struct WGraph {
  int64_t nv = 0;
  std::vector<int64_t> indptr;
  std::vector<int64_t> adj;   // neighbor ids (deduped, no self loops)
  std::vector<int64_t> ew;    // edge weights (parallel-edge multiplicity)
  std::vector<int64_t> vw;    // vertex weights (coarse vertices aggregate)
};

// Build a WGraph from UNIQUE UNDIRECTED weighted pairs (u < v, no self
// loops, no duplicates — the contract the chunked numpy contraction in
// partition.multilevel_big_partition delivers) plus per-vertex weights.
// Both directions are inserted directly; no dedup pass needed.
WGraph build_wgraph_weighted(const int64_t* usrc, const int64_t* udst,
                             const int64_t* uw, int64_t num_pairs,
                             const int64_t* vw, int64_t num_vertices) {
  WGraph g;
  g.nv = num_vertices;
  g.vw.assign(vw, vw + num_vertices);
  std::vector<int64_t> deg(num_vertices, 0);
  for (int64_t e = 0; e < num_pairs; ++e) {
    ++deg[usrc[e]];
    ++deg[udst[e]];
  }
  g.indptr.assign(num_vertices + 1, 0);
  for (int64_t v = 0; v < num_vertices; ++v)
    g.indptr[v + 1] = g.indptr[v] + deg[v];
  g.adj.assign(g.indptr[num_vertices], 0);
  g.ew.assign(g.indptr[num_vertices], 0);
  std::vector<int64_t> cur(g.indptr.begin(), g.indptr.end() - 1);
  for (int64_t e = 0; e < num_pairs; ++e) {
    const int64_t a = usrc[e], b = udst[e], w = uw[e];
    g.adj[cur[a]] = b; g.ew[cur[a]++] = w;
    g.adj[cur[b]] = a; g.ew[cur[b]++] = w;
  }
  return g;
}

// Build the level-0 weighted graph from a directed edge list: symmetrize,
// drop self loops, merge parallel edges into weights.
WGraph build_wgraph(const int64_t* src, const int64_t* dst, int64_t num_edges,
                    int64_t num_vertices) {
  WGraph g;
  g.nv = num_vertices;
  g.vw.assign(num_vertices, 1);
  std::vector<int64_t> deg(num_vertices, 0);
  for (int64_t e = 0; e < num_edges; ++e) {
    if (src[e] == dst[e]) continue;
    ++deg[src[e]];
    ++deg[dst[e]];
  }
  g.indptr.assign(num_vertices + 1, 0);
  for (int64_t v = 0; v < num_vertices; ++v) g.indptr[v + 1] = g.indptr[v] + deg[v];
  std::vector<int64_t> raw(g.indptr[num_vertices]);
  std::vector<int64_t> cur(g.indptr.begin(), g.indptr.end() - 1);
  for (int64_t e = 0; e < num_edges; ++e) {
    if (src[e] == dst[e]) continue;
    raw[cur[src[e]]++] = dst[e];
    raw[cur[dst[e]]++] = src[e];
  }
  // dedup neighbors per vertex, accumulating multiplicity as weight
  g.adj.reserve(raw.size());
  g.ew.reserve(raw.size());
  std::vector<int64_t> new_indptr(num_vertices + 1, 0);
  for (int64_t v = 0; v < num_vertices; ++v) {
    int64_t lo = g.indptr[v], hi = g.indptr[v + 1];
    std::sort(raw.begin() + lo, raw.begin() + hi);
    for (int64_t k = lo; k < hi;) {
      int64_t n = raw[k], w = 0;
      while (k < hi && raw[k] == n) { ++w; ++k; }
      g.adj.push_back(n);
      g.ew.push_back(w);
    }
    new_indptr[v + 1] = static_cast<int64_t>(g.adj.size());
  }
  g.indptr = std::move(new_indptr);
  return g;
}

// Heavy-edge matching: returns match[v] (== v for unmatched/self-matched)
// and the number of coarse vertices; cmap[v] = coarse id. max_vw > 0
// hard-bounds the merged vertex weight — without it a giant supernode can
// exceed the initial partition's per-rank cap, and region growth then
// overshoots by that whole supernode (observed 1.27x imbalance on a
// half-sampled 120k power-law; METIS bounds supernode weight the same way).
int64_t heavy_edge_matching(const WGraph& g, std::mt19937_64& rng,
                            std::vector<int64_t>& cmap,
                            int64_t max_vw = 0) {
  // Visit low-degree vertices first (random within a degree class) and
  // score candidates by edge weight normalized by the partner's vertex
  // weight. Plain max-weight matching merges across weak bridges when all
  // weights tie (level 0) — bridge endpoints tend to have higher degree,
  // so degree-ordered visiting lets cluster-internal vertices pair up
  // before a bridge endpoint can grab them, and the normalization keeps
  // supernodes from snowballing.
  std::vector<int64_t> order(g.nv);
  for (int64_t i = 0; i < g.nv; ++i) order[i] = i;
  std::shuffle(order.begin(), order.end(), rng);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return (g.indptr[a + 1] - g.indptr[a]) < (g.indptr[b + 1] - g.indptr[b]);
  });
  std::vector<int64_t> match(g.nv, -1);
  for (int64_t idx = 0; idx < g.nv; ++idx) {
    int64_t v = order[idx];
    if (match[v] >= 0) continue;
    int64_t best = -1;
    double best_score = 0.0;
    for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
      int64_t n = g.adj[k];
      if (match[n] >= 0) continue;
      if (max_vw > 0 && g.vw[v] + g.vw[n] > max_vw) continue;
      double score = double(g.ew[k]) / double(g.vw[n]);
      if (score > best_score) { best = n; best_score = score; }
    }
    if (best >= 0) { match[v] = best; match[best] = v; }
    else match[v] = v;
  }
  cmap.assign(g.nv, -1);
  int64_t nc = 0;
  for (int64_t v = 0; v < g.nv; ++v) {
    if (cmap[v] >= 0) continue;
    cmap[v] = nc;
    if (match[v] != v) cmap[match[v]] = nc;
    ++nc;
  }
  return nc;
}

// Contract g by cmap into a coarse weighted graph.
WGraph contract(const WGraph& g, const std::vector<int64_t>& cmap, int64_t nc) {
  WGraph c;
  c.nv = nc;
  c.vw.assign(nc, 0);
  for (int64_t v = 0; v < g.nv; ++v) c.vw[cmap[v]] += g.vw[v];
  // gather coarse edges per coarse vertex, then dedup-accumulate
  std::vector<std::pair<int64_t, int64_t>> edges;  // (enc(cu,cv), w) cu<cv
  edges.reserve(g.adj.size() / 2);
  for (int64_t v = 0; v < g.nv; ++v) {
    int64_t cu = cmap[v];
    for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
      int64_t cv = cmap[g.adj[k]];
      if (cu < cv) edges.emplace_back(cu * nc + cv, g.ew[k]);
    }
  }
  std::sort(edges.begin(), edges.end());
  std::vector<int64_t> deg(nc, 0);
  std::vector<std::pair<int64_t, int64_t>> merged;  // (enc, w)
  merged.reserve(edges.size());
  for (size_t i = 0; i < edges.size();) {
    int64_t enc = edges[i].first, w = 0;
    while (i < edges.size() && edges[i].first == enc) { w += edges[i].second; ++i; }
    merged.emplace_back(enc, w);
    ++deg[enc / nc];
    ++deg[enc % nc];
  }
  c.indptr.assign(nc + 1, 0);
  for (int64_t v = 0; v < nc; ++v) c.indptr[v + 1] = c.indptr[v] + deg[v];
  c.adj.assign(c.indptr[nc], 0);
  c.ew.assign(c.indptr[nc], 0);
  std::vector<int64_t> cur(c.indptr.begin(), c.indptr.end() - 1);
  for (auto& [enc, w] : merged) {
    int64_t a = enc / nc, b = enc % nc;
    c.adj[cur[a]] = b; c.ew[cur[a]++] = w;
    c.adj[cur[b]] = a; c.ew[cur[b]++] = w;
  }
  return c;
}

// Weighted greedy region growing on the (coarsest) graph — METIS-style
// GGGP: always absorb the frontier vertex with the STRONGEST connection to
// the growing region. A DFS stack here is catastrophically order-sensitive
// (it dives along weak chain edges, stranding heavy partners on the stack);
// the max-connection heap follows the weight structure instead.
void initial_partition(const WGraph& g, int32_t world_size, std::mt19937_64& rng,
                       std::vector<int32_t>& part) {
  part.assign(g.nv, -1);
  int64_t total_vw = 0;
  for (auto w : g.vw) total_vw += w;
  const int64_t cap = (total_vw + world_size - 1) / world_size;
  std::vector<int64_t> order(g.nv);
  for (int64_t i = 0; i < g.nv; ++i) order[i] = i;
  std::shuffle(order.begin(), order.end(), rng);
  int64_t seed_ptr = 0;
  std::vector<int64_t> conn(g.nv, 0);
  // lazy max-heap of (connection-to-region, vertex); stale entries skipped
  std::priority_queue<std::pair<int64_t, int64_t>> heap;
  for (int32_t r = 0; r < world_size; ++r) {
    int64_t weight = 0;
    while (!heap.empty()) heap.pop();
    std::fill(conn.begin(), conn.end(), 0);
    while (weight < cap) {
      int64_t v = -1;
      while (!heap.empty()) {
        auto [w, u] = heap.top();
        heap.pop();
        if (part[u] < 0 && w == conn[u]) { v = u; break; }
      }
      if (v < 0) {
        while (seed_ptr < g.nv && part[order[seed_ptr]] >= 0) ++seed_ptr;
        if (seed_ptr >= g.nv) break;
        v = order[seed_ptr];
      }
      part[v] = r;
      weight += g.vw[v];
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
        int64_t n = g.adj[k];
        if (part[n] < 0) {
          conn[n] += g.ew[k];
          heap.emplace(conn[n], n);
        }
      }
    }
  }
  for (int64_t v = 0; v < g.nv; ++v)
    if (part[v] < 0) part[v] = world_size - 1;
}

// Force every rank under the balance cap: over-cap ranks shed vertices to
// the best under-cap neighbor rank (by connection, falling back to the
// most underfull rank). Gain-driven refinement can never FIX a violation
// — its feasibility check only refuses to create new ones — so this runs
// wherever an unbalanced partition can enter (initial growth overshoot,
// a projected partition from differently-weighted levels).
void rebalance_to_cap(const WGraph& g, int32_t world_size,
                      std::vector<int32_t>& part, double imbalance) {
  int64_t total_vw = 0;
  for (auto w : g.vw) total_vw += w;
  const int64_t cap =
      static_cast<int64_t>((double(total_vw) / world_size) * imbalance) + 1;
  std::vector<int64_t> pw(world_size, 0);
  for (int64_t v = 0; v < g.nv; ++v) pw[part[v]] += g.vw[v];
  std::vector<int64_t> conn(world_size, 0);
  for (int sweep = 0; sweep < 8; ++sweep) {
    bool over = false;
    for (int32_t r = 0; r < world_size; ++r) over |= pw[r] > cap;
    if (!over) return;
    bool moved = false;
    for (int64_t v = 0; v < g.nv; ++v) {
      const int32_t pv = part[v];
      if (pw[pv] <= cap) continue;
      std::fill(conn.begin(), conn.end(), 0);
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k)
        conn[part[g.adj[k]]] += g.ew[k];
      int32_t best = -1;
      int64_t best_conn = -1, best_pw = INT64_MAX;
      for (int32_t r = 0; r < world_size; ++r) {
        if (r == pv || pw[r] + g.vw[v] > cap) continue;
        // prefer connection, tie-break toward the most underfull rank
        if (conn[r] > best_conn ||
            (conn[r] == best_conn && pw[r] < best_pw)) {
          best = r;
          best_conn = conn[r];
          best_pw = pw[r];
        }
      }
      if (best >= 0) {
        pw[pv] -= g.vw[v];
        pw[best] += g.vw[v];
        part[v] = best;
        moved = true;
      }
    }
    if (!moved) return;  // nothing placeable (oversized vertices)
  }
}

// Greedy boundary refinement (FM-lite): move boundary vertices to the
// neighbor partition with the largest positive cut gain, under a balance
// cap. A few passes per level.
void refine(const WGraph& g, int32_t world_size, std::vector<int32_t>& part,
            int passes, double imbalance) {
  int64_t total_vw = 0;
  for (auto w : g.vw) total_vw += w;
  const int64_t cap =
      static_cast<int64_t>((double(total_vw) / world_size) * imbalance) + 1;
  std::vector<int64_t> pw(world_size, 0);
  for (int64_t v = 0; v < g.nv; ++v) pw[part[v]] += g.vw[v];
  std::vector<int64_t> conn(world_size, 0);
  for (int p = 0; p < passes; ++p) {
    int64_t moves = 0;
    for (int64_t v = 0; v < g.nv; ++v) {
      int32_t pv = part[v];
      bool boundary = false;
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k)
        if (part[g.adj[k]] != pv) { boundary = true; break; }
      if (!boundary) continue;
      std::fill(conn.begin(), conn.end(), 0);
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k)
        conn[part[g.adj[k]]] += g.ew[k];
      int32_t best = pv;
      int64_t best_gain = 0;
      for (int32_t r = 0; r < world_size; ++r) {
        if (r == pv || pw[r] + g.vw[v] > cap) continue;
        int64_t gain = conn[r] - conn[pv];
        if (gain > best_gain) { best = r; best_gain = gain; }
      }
      if (best != pv) {
        pw[pv] -= g.vw[v];
        pw[best] += g.vw[v];
        part[v] = best;
        ++moves;
      }
    }
    if (!moves) break;
  }
}

// Shared setup for the table-based refiners: env-tunable memory gate for
// the [nv, W] connection table, balance cap, per-rank weights, and the
// table itself (conn[v*W + r] = edge weight from v into rank r).
// Returns false when the table would exceed the gate (strtoll saturates
// on out-of-range input — atoll is UB there; the clamp keeps <<30 from
// overflowing into a negative gate that would silently disable the
// refiner everywhere).
bool build_conn_table(const WGraph& g, int32_t W,
                      const std::vector<int32_t>& part, double imbalance,
                      int64_t* cap_out, std::vector<int64_t>& pw,
                      std::vector<int64_t>& conn) {
  int64_t gate_gb = 6;
  if (const char* ge = std::getenv("DGRAPH_HOST_FM_TABLE_GB")) {
    const int64_t v = std::strtoll(ge, nullptr, 10);
    if (v > 0) gate_gb = std::min<int64_t>(v, int64_t(1) << 20);
  }
  if (g.nv * int64_t(W) * 8 > (gate_gb << 30)) return false;
  int64_t total_vw = 0;
  for (auto w : g.vw) total_vw += w;
  *cap_out = static_cast<int64_t>((double(total_vw) / W) * imbalance) + 1;
  pw.assign(W, 0);
  for (int64_t v = 0; v < g.nv; ++v) pw[part[v]] += g.vw[v];
  conn.assign(size_t(g.nv) * W, 0);
  for (int64_t v = 0; v < g.nv; ++v)
    for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k)
      conn[size_t(v) * W + part[g.adj[k]]] += g.ew[k];
  return true;
}

// Proper FM (KL/FM-class) k-way refinement with hill climbing: moves are
// taken in gain order from a lazy max-heap, each vertex moves at most once
// per pass, NEGATIVE-gain moves are allowed, and the pass rolls back to
// the best cumulative-cut prefix. This escapes the local minima the
// positive-gain-only refine() above gets stuck in — the difference between
// "26% better than random" and METIS-class cuts (VERDICT r3 #6).
//
// Cost model (the classic FM implementation): a [nv, W] connection table
// updated incrementally — O(deg) per applied move, O(W) per gain read —
// instead of recomputing neighbor gains from adjacency (O(deg^2) per move,
// which power-law hubs turn quadratic). Levels whose table would exceed
// the memory gate skip FM and keep the greedy refine result.
void fm_refine_impl(const WGraph& g, int32_t W, std::vector<int32_t>& part,
                    int passes, int64_t cap, std::vector<int64_t>& pw,
                    std::vector<int64_t>& conn) {
  std::vector<uint8_t> locked(g.nv, 0);
  std::vector<int64_t> cur_gain(g.nv, INT64_MIN);

  // best balance-feasible move for v from its conn row; INT64_MIN when
  // interior or nothing feasible
  auto best_from_row = [&](int64_t v, int32_t* out_r) -> int64_t {
    const int32_t pv = part[v];
    const int64_t* row = conn.data() + size_t(v) * W;
    int32_t best = pv;
    int64_t best_gain = INT64_MIN;
    for (int32_t r = 0; r < W; ++r) {
      if (r == pv || (row[r] == 0 && best_gain != INT64_MIN)) continue;
      if (pw[r] + g.vw[v] > cap) continue;
      const int64_t gain = row[r] - row[pv];
      if (gain > best_gain) { best = r; best_gain = gain; }
    }
    // interior vertices (no edge into any other part) are not worth
    // queueing: their best gain is -row[pv], a pure-loss move
    bool boundary = false;
    for (int32_t r = 0; r < W; ++r)
      if (r != pv && row[r] > 0) { boundary = true; break; }
    if (!boundary || best == pv) { *out_r = pv; return INT64_MIN; }
    *out_r = best;
    return best_gain;
  };

  // move v from pv to tgt, updating part/pw/conn rows of neighbors
  auto apply_move = [&](int64_t v, int32_t pv, int32_t tgt) {
    pw[pv] -= g.vw[v];
    pw[tgt] += g.vw[v];
    part[v] = tgt;
    for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
      int64_t* row = conn.data() + size_t(g.adj[k]) * W;
      row[pv] -= g.ew[k];
      row[tgt] += g.ew[k];
    }
  };

  struct Move { int64_t v; int32_t from, to; };
  std::vector<Move> trail;
  std::priority_queue<std::pair<int64_t, int64_t>> heap;  // (gain, v)

  for (int p = 0; p < passes; ++p) {
    std::fill(locked.begin(), locked.end(), 0);
    std::fill(cur_gain.begin(), cur_gain.end(), INT64_MIN);
    while (!heap.empty()) heap.pop();
    for (int64_t v = 0; v < g.nv; ++v) {
      int32_t tgt;
      const int64_t gain = best_from_row(v, &tgt);
      if (gain != INT64_MIN) { cur_gain[v] = gain; heap.emplace(gain, v); }
    }
    trail.clear();
    int64_t cum = 0, best_cum = 0;
    size_t best_len = 0;
    // stall cap (the classic FM early-out): once this many moves have
    // accumulated past the best prefix without improving it, the pass's
    // tail is already guaranteed rollback work — on power-law graphs the
    // uncapped tail is ~nv moves and dominates runtime while contributing
    // exactly nothing
    const size_t stall_cap =
        std::max<size_t>(1024, static_cast<size_t>(g.nv / 64));
    while (!heap.empty()) {
      if (trail.size() - best_len > stall_cap) break;
      auto [gain, v] = heap.top();
      heap.pop();
      if (locked[v] || gain != cur_gain[v]) continue;  // stale entry
      int32_t tgt;
      const int64_t now = best_from_row(v, &tgt);  // pw may have shifted
      if (now == INT64_MIN) { cur_gain[v] = INT64_MIN; continue; }
      if (now != gain) { cur_gain[v] = now; heap.emplace(now, v); continue; }
      const int32_t pv = part[v];
      apply_move(v, pv, tgt);
      locked[v] = 1;
      trail.push_back({v, pv, tgt});
      cum += now;
      if (cum > best_cum) { best_cum = cum; best_len = trail.size(); }
      // neighbors' rows changed by apply_move; refresh their queue keys
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
        const int64_t n = g.adj[k];
        if (locked[n]) continue;
        int32_t ntgt;
        const int64_t ngain = best_from_row(n, &ntgt);
        if (ngain != cur_gain[n]) {
          cur_gain[n] = ngain;
          if (ngain != INT64_MIN) heap.emplace(ngain, n);
        }
      }
    }
    // roll back to the best prefix (classic FM: the tail of the pass was
    // exploration that didn't pay off)
    for (size_t i = trail.size(); i > best_len; --i) {
      const Move& m = trail[i - 1];
      apply_move(m.v, m.to, m.from);
    }
    if (best_cum <= 0) break;  // pass found no net improvement
  }
}

// Communication-VOLUME polish: greedy positive-gain passes on the deduped
// halo-slot objective — the number of distinct (needing-rank, vertex)
// pairs, which is what actually sizes the halo all_to_all. FM above
// minimizes raw edge cut; on hub-heavy graphs the two diverge (a hub with
// 50 edges into rank r is 50 cut edges but ONE halo slot), so a final
// polish on the true wire metric recovers bytes the cut objective can't
// see. Gain of moving v from pv to tgt:
//   Δslots = [v needed by tgt before]        (that need disappears)
//          - [v needed by pv after]          (a new need appears)
//          + Σ_u∈N(v) ( [v was u's only pv-edge && owner(u)!=pv]
//                     - [u had no tgt-edge   && owner(u)!=tgt] )
// computed exactly from the same incremental [nv, W] connection table.
void volume_polish_impl(const WGraph& g, int32_t W,
                        std::vector<int32_t>& part, int passes, int64_t cap,
                        std::vector<int64_t>& pw,
                        std::vector<int64_t>& conn) {

  for (int p = 0; p < passes; ++p) {
    int64_t moves = 0;
    for (int64_t v = 0; v < g.nv; ++v) {
      const int32_t pv = part[v];
      const int64_t* row = conn.data() + size_t(v) * W;
      // candidate targets: ranks v already has edges into (moving toward
      // a rank with no edges can never reduce slots)
      int32_t best = pv;
      int64_t best_gain = 0, best_cut = 0;
      // the pv-side terms are target-independent: hoist them out of the
      // candidate loop (they're half the dominant inner-loop cost)
      int64_t pv_gain = row[pv] > 0 ? 0 : 1;  // tgt's need for v always
      // disappears (+1); pv starts needing v unless v has no pv edge
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
        const int64_t u = g.adj[k];
        if (conn[size_t(u) * W + pv] == g.ew[k] && part[u] != pv)
          pv_gain += 1;  // u stops being needed by pv (its only pv edge)
      }
      for (int32_t tgt = 0; tgt < W; ++tgt) {
        if (tgt == pv || row[tgt] == 0 || pw[tgt] + g.vw[v] > cap) continue;
        int64_t gain = pv_gain;
        for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
          const int64_t u = g.adj[k];
          if (conn[size_t(u) * W + tgt] == 0 && part[u] != tgt)
            gain -= 1;  // u becomes needed by tgt
        }
        const int64_t cut_gain = row[tgt] - row[pv];
        if (gain > best_gain ||
            (gain == best_gain && gain > 0 && cut_gain > best_cut)) {
          best = tgt;
          best_gain = gain;
          best_cut = cut_gain;
        }
      }
      if (best != pv && best_gain > 0) {
        pw[pv] -= g.vw[v];
        pw[best] += g.vw[v];
        part[v] = best;
        for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
          int64_t* urow = conn.data() + size_t(g.adj[k]) * W;
          urow[pv] -= g.ew[k];
          urow[best] += g.ew[k];
        }
        ++moves;
      }
    }
    if (!moves) break;
  }
}


// Public wrappers: env kill switches + the shared table build. The conn
// table is maintained incrementally across passes AND across rollbacks
// (apply/revert are the same table update with roles swapped), so one
// build serves FM and the volume polish back-to-back — at the finest
// level of a papers-fraction graph that's a multi-GB transient and an
// O(E) scan paid once instead of twice. Gate default 6 GB skips the
// papers100M finest level at W=8 (7.1 GB table); FM always runs on the
// coarser levels either way.
bool fm_enabled() {
  const char* env = std::getenv("DGRAPH_HOST_FM");
  return !(env && env[0] == '0');  // '0' = greedy-only A/B baseline
}

bool polish_enabled() {
  const char* env = std::getenv("DGRAPH_HOST_VOLUME_POLISH");
  if (env && env[0] == '0') return false;  // A/B kill switch
  // DGRAPH_HOST_FM=0 must yield the documented greedy-only baseline —
  // the polish counts as refinement
  return fm_enabled();
}

void fm_refine(const WGraph& g, int32_t world_size, std::vector<int32_t>& part,
               int passes, double imbalance) {
  if (!fm_enabled()) return;
  int64_t cap;
  std::vector<int64_t> pw, conn;
  if (!build_conn_table(g, world_size, part, imbalance, &cap, pw, conn))
    return;
  fm_refine_impl(g, world_size, part, passes, cap, pw, conn);
}

void fm_refine_and_polish(const WGraph& g, int32_t world_size,
                          std::vector<int32_t>& part, int fm_passes,
                          int polish_passes, double imbalance) {
  if (!fm_enabled()) return;
  int64_t cap;
  std::vector<int64_t> pw, conn;
  if (!build_conn_table(g, world_size, part, imbalance, &cap, pw, conn))
    return;
  fm_refine_impl(g, world_size, part, fm_passes, cap, pw, conn);
  if (polish_enabled())
    volume_polish_impl(g, world_size, part, polish_passes, cap, pw, conn);
}

// Multilevel body shared by the unweighted (raw edge list) and weighted
// (pre-coarsened) entries: coarsen by heavy-edge matching, partition the
// coarsest graph, project back with boundary refinement at every level.
void multilevel_core(WGraph&& g0, int32_t world_size, uint64_t seed,
                     int32_t* out_part) {
  const int64_t num_vertices = g0.nv;
  std::mt19937_64 rng(seed);
  std::vector<WGraph> levels;
  std::vector<std::vector<int64_t>> cmaps;
  levels.push_back(std::move(g0));
  // coarsen until ~16 coarse vertices per partition: deep enough that
  // locality clusters contract to single vertices (the initial partition
  // then only cuts inter-cluster links), shallow enough to stay balanced
  const int64_t coarse_target =
      std::max<int64_t>(static_cast<int64_t>(world_size) * 16, 64);
  int64_t total_vw = 0;
  for (auto w : levels[0].vw) total_vw += w;
  // supernode weight bound: 2x the average coarsest-level weight. Region
  // growth overshoots its cap by at most one vertex, so bounding vertex
  // weight bounds the initial imbalance at ~2/coarse_target (~1.6% at
  // W=8); rebalance_to_cap then enforces the 1.03 contract exactly.
  const int64_t max_vw = std::max<int64_t>(2 * total_vw / coarse_target, 1);
  while (levels.back().nv > coarse_target) {
    std::vector<int64_t> cmap;
    int64_t nc = heavy_edge_matching(levels.back(), rng, cmap, max_vw);
    if (nc > levels.back().nv * 95 / 100) break;  // matching stalled
    WGraph coarse = contract(levels.back(), cmap, nc);
    cmaps.push_back(std::move(cmap));
    levels.push_back(std::move(coarse));
  }
  std::vector<int32_t> part;
  initial_partition(levels.back(), world_size, rng, part);
  rebalance_to_cap(levels.back(), world_size, part, /*imbalance=*/1.03);
  // cheap greedy warmup, then hill-climbing FM (rollback makes the
  // negative-gain exploration safe at every level)
  refine(levels.back(), world_size, part, /*passes=*/4, /*imbalance=*/1.03);
  if (cmaps.empty()) {
    // no coarsening happened: the coarsest level IS the finest — run the
    // combined FM + volume polish here (the uncoarsening loop below won't)
    fm_refine_and_polish(levels[0], world_size, part, /*fm_passes=*/6,
                         /*polish_passes=*/4, /*imbalance=*/1.03);
  } else {
    fm_refine(levels.back(), world_size, part, /*passes=*/6,
              /*imbalance=*/1.03);
  }
  for (int64_t l = static_cast<int64_t>(cmaps.size()) - 1; l >= 0; --l) {
    const std::vector<int64_t>& cmap = cmaps[l];
    std::vector<int32_t> fine(levels[l].nv);
    for (int64_t v = 0; v < levels[l].nv; ++v) fine[v] = part[cmap[v]];
    part = std::move(fine);
    // greedy passes stay at the r3 value so DGRAPH_HOST_FM=0 reproduces
    // the pre-FM partitioner exactly (the A/B must isolate fm_refine)
    refine(levels[l], world_size, part, /*passes=*/2, /*imbalance=*/1.03);
    if (l == 0) {
      // finest level: FM + the halo-slot volume polish share ONE conn
      // table (the polish targets the metric that actually sizes the
      // padded all_to_all; only the finest level's slots ride the wire)
      fm_refine_and_polish(levels[0], world_size, part, /*fm_passes=*/3,
                           /*polish_passes=*/4, /*imbalance=*/1.03);
    } else {
      fm_refine(levels[l], world_size, part, /*passes=*/3,
                /*imbalance=*/1.03);
    }
  }
  std::memcpy(out_part, part.data(), num_vertices * sizeof(int32_t));
}

}  // namespace

// METIS-shaped multilevel k-way partition from a raw directed edge list.
void multilevel_partition(const int64_t* src, const int64_t* dst,
                          int64_t num_edges, int64_t num_vertices,
                          int32_t world_size, uint64_t seed,
                          int32_t* out_part) {
  multilevel_core(build_wgraph(src, dst, num_edges, num_vertices), world_size,
                  seed, out_part);
}

extern "C" void multilevel_partition_c(const int64_t* src, const int64_t* dst,
                                       int64_t num_edges, int64_t num_vertices,
                                       int32_t world_size, uint64_t seed,
                                       int32_t* out_part) {
  multilevel_partition(src, dst, num_edges, num_vertices, world_size, seed,
                       out_part);
}

// Raw-edge-list entry with CALLER vertex weights: same multilevel body,
// balance objective Σ vw per rank. The full-scale papers100M record
// showed why this exists: vertex-balanced partitions leave the EDGE
// distribution 1.28x imbalanced (e_pad 257.6M vs the 201M/rank mean,
// logs/p100m_fullscale_r5.jsonl), and e_pad sizes the dominant runtime
// edge buffers; vw = 1 + alpha*degree trades a little vertex padding for
// edge balance.
extern "C" void multilevel_partition_vw_c(
    const int64_t* src, const int64_t* dst, int64_t num_edges,
    const int64_t* vw, int64_t num_vertices, int32_t world_size,
    uint64_t seed, int32_t* out_part) {
  WGraph g = build_wgraph(src, dst, num_edges, num_vertices);
  g.vw.assign(vw, vw + num_vertices);
  multilevel_core(std::move(g), world_size, seed, out_part);
}

// Weighted entry: unique undirected pairs + weights + vertex weights (the
// chunked contraction's output). The balance objective is Σ vw per rank,
// so a partition of cluster-coarsened supernodes stays balanced in FINE
// vertices after projection.
extern "C" void multilevel_partition_w_c(
    const int64_t* usrc, const int64_t* udst, const int64_t* uw,
    int64_t num_pairs, const int64_t* vw, int64_t num_vertices,
    int32_t world_size, uint64_t seed, int32_t* out_part) {
  multilevel_core(
      build_wgraph_weighted(usrc, udst, uw, num_pairs, vw, num_vertices),
      world_size, seed, out_part);
}

namespace {

// Symmetrized int32 CSR (4 bytes x 2E adjacency, parallel edges kept —
// dedup would need a per-vertex sort; a multiplicity-2 neighbor just gets
// scanned twice). Shared by the memory-bounded partition entry points.
// Returns false when vertex ids would not fit int32 — callers must fail
// fast rather than wrap ids negative.
bool build_csr32(const int64_t* src, const int64_t* dst, int64_t num_edges,
                 int64_t num_vertices, std::vector<int64_t>& indptr,
                 std::vector<int32_t>& adj) {
  if (num_vertices >= INT32_MAX) return false;
  indptr.assign(num_vertices + 1, 0);
  {
    // per-vertex degree <= 2E < 2^32 needs int64 only if one vertex
    // touches >2^31 edges; ids are the int32-bound quantity here
    std::vector<int64_t> deg(num_vertices, 0);
    for (int64_t e = 0; e < num_edges; ++e) {
      if (src[e] == dst[e]) continue;
      ++deg[src[e]];
      ++deg[dst[e]];
    }
    for (int64_t v = 0; v < num_vertices; ++v)
      indptr[v + 1] = indptr[v] + deg[v];
  }
  adj.assign(indptr[num_vertices], 0);
  std::vector<int64_t> cur(indptr.begin(), indptr.end() - 1);
  for (int64_t e = 0; e < num_edges; ++e) {
    if (src[e] == dst[e]) continue;
    adj[cur[src[e]]++] = static_cast<int32_t>(dst[e]);
    adj[cur[dst[e]]++] = static_cast<int32_t>(src[e]);
  }
  return true;
}

// Force every rank under cap on an int32 CSR — the CSR-form sibling of
// rebalance_to_cap (same policy: shed over-cap ranks to the
// best-connected under-cap rank, tie-break most underfull; keep the two
// in lock-step when changing the heuristic). vw == nullptr means unit
// vertex weights; otherwise the cap is on Σ vw (edge-balance blends).
void rebalance_csr32(const std::vector<int64_t>& indptr,
                     const std::vector<int32_t>& adj, int64_t num_vertices,
                     int32_t W, int64_t cap, const int64_t* vw,
                     int32_t* part, std::vector<int64_t>& pw) {
  std::vector<int64_t> conn(W, 0);
  for (int sweep = 0; sweep < 8; ++sweep) {
    bool over = false;
    for (int32_t r = 0; r < W; ++r) over |= pw[r] > cap;
    if (!over) return;
    bool moved = false;
    for (int64_t v = 0; v < num_vertices; ++v) {
      const int32_t pv = part[v];
      if (pw[pv] <= cap) continue;
      const int64_t w = vw ? vw[v] : 1;
      std::fill(conn.begin(), conn.end(), 0);
      for (int64_t k = indptr[v]; k < indptr[v + 1]; ++k)
        ++conn[part[adj[k]]];
      int32_t best = -1;
      int64_t best_conn = -1, best_pw = INT64_MAX;
      for (int32_t r = 0; r < W; ++r) {
        if (r == pv || pw[r] + w > cap) continue;
        if (conn[r] > best_conn ||
            (conn[r] == best_conn && pw[r] < best_pw)) {
          best = r;
          best_conn = conn[r];
          best_pw = pw[r];
        }
      }
      if (best >= 0) {
        pw[pv] -= w;
        pw[best] += w;
        part[v] = best;
        moved = true;
      }
    }
    if (!moved) return;
  }
}

}  // namespace

// Capped greedy cluster coarsening for graphs whose in-RAM WGraph stack
// would blow the host (VERDICT r4 #6: 22M nodes -> 104 GB RSS; 111M is
// 5x out of reach). Memory here is ONE int32 CSR (4 bytes x 2E) + O(V)
// int64 arrays — ~18 GB at full papers100M against the WGraph path's
// >250 GB. Degree-ascending visiting (random within a degree class) lets
// cluster-interior vertices seed clusters before hubs can swallow
// cross-cluster neighborhoods — the same ordering rationale as
// heavy_edge_matching above. A second sweep merges the singleton clusters
// the greedy pass strands (hubs visited last find their neighbors taken).
// Returns the number of clusters (-1: ids would not fit int32);
// out_cmap[v] = cluster id.
extern "C" int64_t cluster_coarsen_c(const int64_t* src, const int64_t* dst,
                                     int64_t num_edges, int64_t num_vertices,
                                     int64_t max_cluster_weight, uint64_t seed,
                                     int64_t* out_cmap) {
  std::vector<int64_t> indptr;
  std::vector<int32_t> adj;
  if (!build_csr32(src, dst, num_edges, num_vertices, indptr, adj)) return -1;
  std::vector<int64_t> order(num_vertices);
  for (int64_t i = 0; i < num_vertices; ++i) order[i] = i;
  std::mt19937_64 rng(seed);
  std::shuffle(order.begin(), order.end(), rng);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return (indptr[a + 1] - indptr[a]) < (indptr[b + 1] - indptr[b]);
  });

  std::fill(out_cmap, out_cmap + num_vertices, int64_t(-1));
  std::vector<int64_t> cw;  // cluster weights
  cw.reserve(num_vertices / std::max<int64_t>(max_cluster_weight / 2, 1) + 16);
  int64_t nc = 0;
  // one-ring absorption, deliberately: a capped-BFS region-growth variant
  // was measured WORSE (2M power-law, W=8: cut 0.770 vs 0.757 at mcw=4 —
  // blob atoms are too coarse for the downstream FM), and deeper
  // coarsening cannot shrink the coarse EDGE count anyway (pairs stayed
  // ~0.93E even at 16x vertex reduction; hub-adjacent edges never merge)
  for (int64_t i = 0; i < num_vertices; ++i) {
    const int64_t v = order[i];
    if (out_cmap[v] >= 0) continue;
    const int64_t c = nc++;
    out_cmap[v] = c;
    int64_t w = 1;
    for (int64_t k = indptr[v]; k < indptr[v + 1] && w < max_cluster_weight;
         ++k) {
      const int32_t n = adj[k];
      if (out_cmap[n] < 0) {
        out_cmap[n] = c;
        ++w;
      }
    }
    cw.push_back(w);
  }
  // singleton-merge sweep: a stranded singleton joins the first neighbor
  // cluster with room (fragmented clusters inflate the coarse graph and
  // starve the initial partition of contiguous regions)
  for (int64_t i = 0; i < num_vertices; ++i) {
    const int64_t v = order[i];
    const int64_t c = out_cmap[v];
    if (cw[c] != 1) continue;
    for (int64_t k = indptr[v]; k < indptr[v + 1]; ++k) {
      const int64_t cn = out_cmap[adj[k]];
      if (cn != c && cw[cn] < max_cluster_weight) {
        out_cmap[v] = cn;
        ++cw[cn];
        --cw[c];
        break;
      }
    }
  }
  // compact away the emptied cluster ids so the coarse graph is dense
  std::vector<int64_t> remap(nc, -1);
  int64_t dense = 0;
  for (int64_t v = 0; v < num_vertices; ++v) {
    int64_t& c = out_cmap[v];
    if (remap[c] < 0) remap[c] = dense++;
    c = remap[c];
  }
  return dense;
}

// Greedy positive-gain boundary refinement on the FINE graph after
// projection, one int32 CSR — the memory-bounded counterpart of refine()
// for graphs whose WGraph doesn't fit. O(E) per pass (boundary check +
// conn scan are both neighbor scans). The cut GAIN is always unit edge
// counts; vw (nullable) only changes what the balance cap sums — the
// edge-balance blend must use the same vw here as in the coarse stage,
// or this refine's rebalance undoes the blend (measured: e_imb 1.14
// pre-refine -> 1.25 after a unit-count refine at 2M power-law).
// Returns 0 on success, -1 when build_csr32 refuses (vertex ids would
// not fit int32) — mirroring cluster_coarsen_c's -1 so non-Python
// callers cannot mistake a silent no-op for a refined partition
// (ADVICE r5; the Python wrappers additionally pre-check the bound).
namespace {
int32_t refine_csr_impl(const int64_t* src, const int64_t* dst,
                        int64_t num_edges, int64_t num_vertices, int32_t W,
                        int32_t passes, double imbalance, const int64_t* vw,
                        int32_t* part) {
  std::vector<int64_t> indptr;
  std::vector<int32_t> adj;
  if (!build_csr32(src, dst, num_edges, num_vertices, indptr, adj))
    return -1;
  int64_t total_w = 0;
  if (vw) {
    for (int64_t v = 0; v < num_vertices; ++v) total_w += vw[v];
  } else {
    total_w = num_vertices;
  }
  const int64_t cap =
      static_cast<int64_t>((double(total_w) / W) * imbalance) + 1;
  std::vector<int64_t> pw(W, 0);
  for (int64_t v = 0; v < num_vertices; ++v) pw[part[v]] += vw ? vw[v] : 1;
  // rebalance first: an over-cap input (e.g. a projected partition built
  // under different weights) can never be fixed by gain-driven passes —
  // they only refuse to create new violations
  rebalance_csr32(indptr, adj, num_vertices, W, cap, vw, part, pw);
  std::vector<int64_t> conn(W, 0);
  for (int32_t p = 0; p < passes; ++p) {
    int64_t moves = 0;
    for (int64_t v = 0; v < num_vertices; ++v) {
      const int32_t pv = part[v];
      bool boundary = false;
      for (int64_t k = indptr[v]; k < indptr[v + 1]; ++k)
        if (part[adj[k]] != pv) { boundary = true; break; }
      if (!boundary) continue;
      const int64_t w = vw ? vw[v] : 1;
      std::fill(conn.begin(), conn.end(), 0);
      for (int64_t k = indptr[v]; k < indptr[v + 1]; ++k)
        ++conn[part[adj[k]]];
      int32_t best = pv;
      int64_t best_gain = 0;
      for (int32_t r = 0; r < W; ++r) {
        if (r == pv || pw[r] + w > cap) continue;
        const int64_t gain = conn[r] - conn[pv];
        if (gain > best_gain) { best = r; best_gain = gain; }
      }
      if (best != pv) {
        pw[pv] -= w;
        pw[best] += w;
        part[v] = best;
        ++moves;
      }
    }
    if (!moves) break;
  }
  return 0;
}
}  // namespace

extern "C" int32_t refine_unweighted_csr_c(const int64_t* src,
                                           const int64_t* dst,
                                           int64_t num_edges,
                                           int64_t num_vertices, int32_t W,
                                           int32_t passes, double imbalance,
                                           int32_t* part) {
  return refine_csr_impl(src, dst, num_edges, num_vertices, W, passes,
                         imbalance, nullptr, part);
}

extern "C" int32_t refine_weighted_csr_c(const int64_t* src,
                                         const int64_t* dst,
                                         int64_t num_edges,
                                         int64_t num_vertices, int32_t W,
                                         int32_t passes, double imbalance,
                                         const int64_t* vw, int32_t* part) {
  return refine_csr_impl(src, dst, num_edges, num_vertices, W, passes,
                         imbalance, vw, part);
}

// Deduplicate (key, value) pairs encoded as key*stride+value, sorted.
// Returns the number of unique pairs written to out (caller allocates n).
int64_t unique_encoded_pairs(const int64_t* keys, const int64_t* vals,
                             int64_t n, int64_t stride, int64_t* out) {
  std::vector<int64_t> enc(n);
  for (int64_t i = 0; i < n; ++i) enc[i] = keys[i] * stride + vals[i];
  std::sort(enc.begin(), enc.end());
  auto end = std::unique(enc.begin(), enc.end());
  int64_t m = static_cast<int64_t>(end - enc.begin());
  std::memcpy(out, enc.data(), m * sizeof(int64_t));
  return m;
}

// ---------------------------------------------------------------------------
// Streaming edge-plan core for billion-edge graphs (SURVEY §7 "papers100M
// plan build"; the reference precomputes per-rank plans offline and caches
// them to disk for MAG240M, MAG240M_dataset.py:237-260).
//
// The numpy builder (dgraph_tpu/plan.py build_edge_plan) lexsorts and
// np.uniques over all E edges with ~10 int64 temporaries — at E=1.6e9
// that's >100 GB of transients on this single-core host. This core does
// the same computation with counting/radix sorts and bounded buffers:
//   1. owner rank per edge + counting sort by owner,
//   2. per-rank LSD radix sort by owner-side local vertex id (monotone
//      segment ids for the sorted-scatter kernels),
//   3. cross-edge (needer, halo-vid) pair sort + run-length dedup, with
//      halo-slot ids propagated back to edges during the scan (no
//      binary-search pass),
//   4. direct fill of the padded [W, E_pad] / [W, W, S_pad] plan arrays.
// Two-call protocol: begin() computes sizes (caller picks padding and
// allocates numpy outputs), fill() writes them, free() drops the context.
// ---------------------------------------------------------------------------

namespace {

struct PlanCtx {
  int64_t E = 0;
  int32_t W = 0;
  int edge_owner_dst = 1;
  std::vector<int32_t> owner;      // [E]
  std::vector<int64_t> e_counts;   // [W]
  std::vector<int32_t> edge_slot;  // [E] slot within owner rank (sorted order)
  std::vector<int64_t> halo_counts;  // [W*W] (sender, needer)
  std::vector<int32_t> edge_pair;  // [E] unique-pair id per cross edge, -1 local
  // per unique (needer, vid) pair, sorted by (needer, vid):
  std::vector<int64_t> pair_vid;
  std::vector<int32_t> pair_needer, pair_sender, pair_pos;
};

// LSD radix sort of (key, val) arrays by key, 8 bits per pass.
void radix_sort_u64(std::vector<uint64_t>& keys, std::vector<uint32_t>& vals,
                    uint64_t max_key) {
  int passes = 0;
  while (max_key >> (8 * passes)) ++passes;
  if (passes == 0) passes = 1;
  size_t n = keys.size();
  std::vector<uint64_t> kbuf(n);
  std::vector<uint32_t> vbuf(n);
  for (int p = 0; p < passes; ++p) {
    size_t count[257] = {0};
    int shift = 8 * p;
    for (size_t i = 0; i < n; ++i) ++count[((keys[i] >> shift) & 0xff) + 1];
    for (int b = 0; b < 256; ++b) count[b + 1] += count[b];
    for (size_t i = 0; i < n; ++i) {
      size_t pos = count[(keys[i] >> shift) & 0xff]++;
      kbuf[pos] = keys[i];
      vbuf[pos] = vals[i];
    }
    keys.swap(kbuf);
    vals.swap(vbuf);
  }
}

}  // namespace

// Phase 1: sort + halo analysis. Returns an opaque context; writes
// out_sizes = {max per-rank edge count, max per-(sender,needer) halo count,
// unique halo pairs, cross edge count}.
void* plan_core_begin(const int64_t* src, const int64_t* dst, int64_t E,
                      const int32_t* src_part, const int32_t* dst_part,
                      const int64_t* src_offsets, const int64_t* dst_offsets,
                      int64_t v_src, int64_t v_dst, int32_t W,
                      int32_t edge_owner_dst, int64_t* out_sizes) {
  // edge ids, per-rank slots, and pair ids are all stored in 32-bit
  // fields; the signed ones (edge_slot, edge_pair) wrap at 2^31 — refuse
  // anything that could overflow instead of silently corrupting the plan
  if (E >= (int64_t(1) << 31)) return nullptr;
  auto* ctx = new PlanCtx();
  ctx->E = E;
  ctx->W = W;
  ctx->edge_owner_dst = edge_owner_dst;
  const int64_t* owner_vid = edge_owner_dst ? dst : src;
  const int64_t* halo_vid = edge_owner_dst ? src : dst;
  const int32_t* owner_part = edge_owner_dst ? dst_part : src_part;
  const int32_t* halo_part = edge_owner_dst ? src_part : dst_part;
  const int64_t* owner_off = edge_owner_dst ? dst_offsets : src_offsets;

  // 1. owner rank per edge + counts
  ctx->owner.resize(E);
  ctx->e_counts.assign(W, 0);
  for (int64_t e = 0; e < E; ++e) {
    int32_t r = owner_part[owner_vid[e]];
    ctx->owner[e] = r;
    ++ctx->e_counts[r];
  }

  // 2. stable counting sort by owner, then per-rank radix by local owner vid
  std::vector<int64_t> rank_start(W + 1, 0);
  for (int32_t r = 0; r < W; ++r) rank_start[r + 1] = rank_start[r] + ctx->e_counts[r];
  ctx->edge_slot.resize(E);
  {
    std::vector<int64_t> cur(rank_start.begin(), rank_start.end() - 1);
    // bucket pass: per-rank (local_vid, orig_idx) entries
    std::vector<uint64_t> bkeys(E);
    std::vector<uint32_t> bvals(E);
    for (int64_t e = 0; e < E; ++e) {
      int32_t r = ctx->owner[e];
      int64_t pos = cur[r]++;
      bkeys[pos] = static_cast<uint64_t>(owner_vid[e] - owner_off[r]);
      bvals[pos] = static_cast<uint32_t>(e);
    }
    for (int32_t r = 0; r < W; ++r) {
      int64_t lo = rank_start[r], n = ctx->e_counts[r];
      if (n == 0) continue;
      uint64_t max_local = 0;
      for (int64_t i = lo; i < lo + n; ++i) max_local = std::max(max_local, bkeys[i]);
      std::vector<uint64_t> k(bkeys.begin() + lo, bkeys.begin() + lo + n);
      std::vector<uint32_t> v(bvals.begin() + lo, bvals.begin() + lo + n);
      radix_sort_u64(k, v, max_local);
      for (int64_t i = 0; i < n; ++i) ctx->edge_slot[v[i]] = static_cast<int32_t>(i);
    }
  }

  // 3. cross-pair dedup with slot propagation; bucket by needer (= owner)
  // first so the per-bucket radix ping-pong buffers are ~1/W of n_cross
  // (a full-width sort's transient is ~24 bytes/cross-edge — tens of GB
  // at papers100M scale)
  std::vector<int64_t> nc_counts(W, 0);
  for (int64_t e = 0; e < E; ++e)
    if (halo_part[halo_vid[e]] != ctx->owner[e]) ++nc_counts[ctx->owner[e]];
  std::vector<int64_t> nc_start(W + 1, 0);
  for (int32_t r = 0; r < W; ++r) nc_start[r + 1] = nc_start[r] + nc_counts[r];
  const int64_t n_cross = nc_start[W];
  ctx->edge_pair.assign(E, -1);
  ctx->halo_counts.assign(static_cast<size_t>(W) * W, 0);
  int64_t v_halo = edge_owner_dst ? v_src : v_dst;
  const int64_t* halo_off = edge_owner_dst ? src_offsets : dst_offsets;
  if (n_cross > 0) {
    std::vector<uint64_t> keys(n_cross);
    std::vector<uint32_t> vals(n_cross);
    {
      std::vector<int64_t> cur(nc_start.begin(), nc_start.end() - 1);
      for (int64_t e = 0; e < E; ++e) {
        int64_t hv = halo_vid[e];
        int32_t r = ctx->owner[e];
        if (halo_part[hv] != r) {
          int64_t pos = cur[r]++;
          keys[pos] = static_cast<uint64_t>(hv);
          vals[pos] = static_cast<uint32_t>(e);
        }
      }
    }
    for (int32_t r = 0; r < W; ++r) {
      int64_t lo = nc_start[r], n = nc_counts[r];
      if (n == 0) continue;
      std::vector<uint64_t> k(keys.begin() + lo, keys.begin() + lo + n);
      std::vector<uint32_t> v(vals.begin() + lo, vals.begin() + lo + n);
      radix_sort_u64(k, v, static_cast<uint64_t>(v_halo));
      std::copy(k.begin(), k.end(), keys.begin() + lo);
      std::copy(v.begin(), v.end(), vals.begin() + lo);
    }
    // re-encode to global (needer, vid) keys for the run-length scan
    for (int32_t r = 0; r < W; ++r)
      for (int64_t i = nc_start[r]; i < nc_start[r + 1]; ++i)
        keys[i] += static_cast<uint64_t>(r) * v_halo;
    // exact reserve (push_back doubling would spike ~2x at H ~ 1e8+)
    int64_t H_total = n_cross > 0 ? 1 : 0;
    for (int64_t i = 1; i < n_cross; ++i) H_total += keys[i] != keys[i - 1];
    ctx->pair_vid.reserve(H_total);
    ctx->pair_needer.reserve(H_total);
    ctx->pair_sender.reserve(H_total);
    ctx->pair_pos.reserve(H_total);
    // run-length scan: assign pair ids; pos within (needer, sender) run
    int64_t H = 0;
    int32_t run_needer = -1, run_sender = -1, pos = 0;
    uint64_t prev_key = ~0ull;
    for (int64_t i = 0; i < n_cross; ++i) {
      if (keys[i] != prev_key) {
        prev_key = keys[i];
        int32_t needer = static_cast<int32_t>(keys[i] / v_halo);
        int64_t vid = static_cast<int64_t>(keys[i] % v_halo);
        int32_t sender = halo_part[vid];
        if (needer != run_needer || sender != run_sender) {
          run_needer = needer;
          run_sender = sender;
          pos = 0;
        }
        ctx->pair_vid.push_back(vid);
        ctx->pair_needer.push_back(needer);
        ctx->pair_sender.push_back(sender);
        ctx->pair_pos.push_back(pos++);
        ++ctx->halo_counts[static_cast<size_t>(sender) * W + needer];
        ++H;
      }
      ctx->edge_pair[vals[i]] = static_cast<int32_t>(H - 1);
    }
    (void)halo_off;
  }

  int64_t e_max = 0, s_max = 0;
  for (int32_t r = 0; r < W; ++r) e_max = std::max(e_max, ctx->e_counts[r]);
  for (auto c : ctx->halo_counts) s_max = std::max(s_max, c);
  out_sizes[0] = e_max;
  out_sizes[1] = s_max;
  out_sizes[2] = static_cast<int64_t>(ctx->pair_vid.size());
  out_sizes[3] = n_cross;
  return ctx;
}

// Phase 2: fill the padded plan arrays (preallocated by the caller).
void plan_core_fill(void* ctx_, const int64_t* src, const int64_t* dst,
                    const int64_t* src_offsets, const int64_t* dst_offsets,
                    int64_t e_pad, int64_t s_pad, int64_t n_owner_pad,
                    int64_t n_halo_pad, int32_t* src_index, int32_t* dst_index,
                    float* edge_mask, int32_t* send_idx, float* send_mask,
                    int64_t* halo_counts_out, int32_t* edge_rank_out,
                    int64_t* edge_slot_out) {
  auto* ctx = static_cast<PlanCtx*>(ctx_);
  const int64_t E = ctx->E;
  const int32_t W = ctx->W;
  const int64_t* owner_vid = ctx->edge_owner_dst ? dst : src;
  const int64_t* halo_vid = ctx->edge_owner_dst ? src : dst;
  const int64_t* owner_off = ctx->edge_owner_dst ? dst_offsets : src_offsets;
  const int64_t* halo_off = ctx->edge_owner_dst ? src_offsets : dst_offsets;
  int32_t* owner_index = ctx->edge_owner_dst ? dst_index : src_index;
  int32_t* halo_index = ctx->edge_owner_dst ? src_index : dst_index;

  // padding conventions (plan.py build_edge_plan): owner-side padded slots
  // carry n_owner_pad (monotone tail, dropped by segment reductions);
  // halo-side and send arrays carry 0 with mask 0
  std::fill(owner_index, owner_index + static_cast<size_t>(W) * e_pad,
            static_cast<int32_t>(n_owner_pad));
  std::memset(halo_index, 0, static_cast<size_t>(W) * e_pad * sizeof(int32_t));
  std::memset(edge_mask, 0, static_cast<size_t>(W) * e_pad * sizeof(float));
  std::memset(send_idx, 0, static_cast<size_t>(W) * W * s_pad * sizeof(int32_t));
  std::memset(send_mask, 0, static_cast<size_t>(W) * W * s_pad * sizeof(float));

  for (int64_t e = 0; e < E; ++e) {
    int32_t r = ctx->owner[e];
    int64_t at = static_cast<int64_t>(r) * e_pad + ctx->edge_slot[e];
    owner_index[at] = static_cast<int32_t>(owner_vid[e] - owner_off[r]);
    int32_t p = ctx->edge_pair[e];
    if (p < 0) {
      halo_index[at] = static_cast<int32_t>(halo_vid[e] - halo_off[r]);
    } else {
      halo_index[at] = static_cast<int32_t>(
          n_halo_pad + static_cast<int64_t>(ctx->pair_sender[p]) * s_pad +
          ctx->pair_pos[p]);
    }
    edge_mask[at] = 1.0f;
    edge_rank_out[e] = r;
    edge_slot_out[e] = ctx->edge_slot[e];
  }

  for (size_t i = 0; i < ctx->pair_vid.size(); ++i) {
    int32_t s = ctx->pair_sender[i], n = ctx->pair_needer[i];
    int64_t at = (static_cast<int64_t>(s) * W + n) * s_pad + ctx->pair_pos[i];
    send_idx[at] = static_cast<int32_t>(ctx->pair_vid[i] - halo_off[s]);
    send_mask[at] = 1.0f;
  }
  std::memcpy(halo_counts_out, ctx->halo_counts.data(),
              static_cast<size_t>(W) * W * sizeof(int64_t));
}

void plan_core_free(void* ctx_) { delete static_cast<PlanCtx*>(ctx_); }

// Multi-threaded edge-cut count (partition quality metric at scale).
int64_t edge_cut_count(const int64_t* src, const int64_t* dst, int64_t num_edges,
                       const int32_t* part) {
  unsigned hw = std::thread::hardware_concurrency();
  int num_threads = hw ? static_cast<int>(hw) : 4;
  if (num_edges < (1 << 16)) num_threads = 1;
  std::vector<int64_t> partial(num_threads, 0);
  std::vector<std::thread> threads;
  int64_t chunk = (num_edges + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    threads.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi = std::min<int64_t>(num_edges, lo + chunk);
      int64_t c = 0;
      for (int64_t e = lo; e < hi; ++e)
        if (part[src[e]] != part[dst[e]]) ++c;
      partial[t] = c;
    });
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  for (auto c : partial) total += c;
  return total;
}

}  // extern "C"
