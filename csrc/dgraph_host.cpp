// Native host-side graph toolkit for dgraph_tpu.
//
// Role: the TPU-native counterpart of the reference's native layer. The
// reference's C++/CUDA lives in the device path
// (DGraph/distributed/csrc/*: gather/scatter kernels, NVSHMEM runtime); on
// TPU the device path is XLA/Pallas, so native code belongs where Python is
// actually the bottleneck: HOST-side plan building and partitioning of
// billion-edge graphs (SURVEY.md §7 "papers100M plan build memory/time").
//
// Exposed via a plain C ABI and loaded with ctypes (no pybind11 in this
// environment). Every entry point has a numpy fallback in
// dgraph_tpu/partition.py / plan.py — the reference's dual
// native/fallback pattern (RankLocalOps.py:21-31).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <queue>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// Build an undirected CSR adjacency from a directed edge list.
// indptr must hold V+1 entries; if indices == nullptr, only fills indptr
// (call once to size, once to fill).
void build_sym_csr(const int64_t* src, const int64_t* dst, int64_t num_edges,
                   int64_t num_vertices, int64_t* indptr, int64_t* indices) {
  std::vector<int64_t> deg(num_vertices, 0);
  for (int64_t e = 0; e < num_edges; ++e) {
    ++deg[src[e]];
    ++deg[dst[e]];
  }
  indptr[0] = 0;
  for (int64_t v = 0; v < num_vertices; ++v) indptr[v + 1] = indptr[v] + deg[v];
  if (!indices) return;
  std::vector<int64_t> cur(indptr, indptr + num_vertices);
  for (int64_t e = 0; e < num_edges; ++e) {
    indices[cur[src[e]]++] = dst[e];
    indices[cur[dst[e]]++] = src[e];
  }
}

// Greedy BFS region-growing partition with hard balance cap — the METIS
// substitute for very large graphs. Deterministic for a fixed seed.
void greedy_bfs_partition(const int64_t* src, const int64_t* dst,
                          int64_t num_edges, int64_t num_vertices,
                          int32_t world_size, uint64_t seed, int32_t* out_part) {
  std::vector<int64_t> indptr(num_vertices + 1);
  std::vector<int64_t> indices;
  build_sym_csr(src, dst, num_edges, num_vertices, indptr.data(), nullptr);
  indices.resize(indptr[num_vertices]);
  build_sym_csr(src, dst, num_edges, num_vertices, indptr.data(), indices.data());

  std::fill(out_part, out_part + num_vertices, -1);
  std::vector<int64_t> order(num_vertices);
  for (int64_t i = 0; i < num_vertices; ++i) order[i] = i;
  std::mt19937_64 rng(seed);
  std::shuffle(order.begin(), order.end(), rng);

  const int64_t cap = (num_vertices + world_size - 1) / world_size;
  int64_t seed_ptr = 0;
  std::vector<int64_t> stack;
  stack.reserve(1024);
  for (int32_t r = 0; r < world_size; ++r) {
    int64_t count = 0;
    stack.clear();
    while (count < cap) {
      if (stack.empty()) {
        while (seed_ptr < num_vertices && out_part[order[seed_ptr]] >= 0) ++seed_ptr;
        if (seed_ptr >= num_vertices) break;
        stack.push_back(order[seed_ptr]);
      }
      int64_t v = stack.back();
      stack.pop_back();
      if (out_part[v] >= 0) continue;
      out_part[v] = r;
      ++count;
      for (int64_t k = indptr[v]; k < indptr[v + 1]; ++k) {
        int64_t n = indices[k];
        if (out_part[n] < 0) stack.push_back(n);
      }
    }
  }
  for (int64_t v = 0; v < num_vertices; ++v)
    if (out_part[v] < 0) out_part[v] = world_size - 1;
}

namespace {

// Weighted undirected graph in CSR form for the multilevel partitioner.
struct WGraph {
  int64_t nv = 0;
  std::vector<int64_t> indptr;
  std::vector<int64_t> adj;   // neighbor ids (deduped, no self loops)
  std::vector<int64_t> ew;    // edge weights (parallel-edge multiplicity)
  std::vector<int64_t> vw;    // vertex weights (coarse vertices aggregate)
};

// Build the level-0 weighted graph from a directed edge list: symmetrize,
// drop self loops, merge parallel edges into weights.
WGraph build_wgraph(const int64_t* src, const int64_t* dst, int64_t num_edges,
                    int64_t num_vertices) {
  WGraph g;
  g.nv = num_vertices;
  g.vw.assign(num_vertices, 1);
  std::vector<int64_t> deg(num_vertices, 0);
  for (int64_t e = 0; e < num_edges; ++e) {
    if (src[e] == dst[e]) continue;
    ++deg[src[e]];
    ++deg[dst[e]];
  }
  g.indptr.assign(num_vertices + 1, 0);
  for (int64_t v = 0; v < num_vertices; ++v) g.indptr[v + 1] = g.indptr[v] + deg[v];
  std::vector<int64_t> raw(g.indptr[num_vertices]);
  std::vector<int64_t> cur(g.indptr.begin(), g.indptr.end() - 1);
  for (int64_t e = 0; e < num_edges; ++e) {
    if (src[e] == dst[e]) continue;
    raw[cur[src[e]]++] = dst[e];
    raw[cur[dst[e]]++] = src[e];
  }
  // dedup neighbors per vertex, accumulating multiplicity as weight
  g.adj.reserve(raw.size());
  g.ew.reserve(raw.size());
  std::vector<int64_t> new_indptr(num_vertices + 1, 0);
  for (int64_t v = 0; v < num_vertices; ++v) {
    int64_t lo = g.indptr[v], hi = g.indptr[v + 1];
    std::sort(raw.begin() + lo, raw.begin() + hi);
    for (int64_t k = lo; k < hi;) {
      int64_t n = raw[k], w = 0;
      while (k < hi && raw[k] == n) { ++w; ++k; }
      g.adj.push_back(n);
      g.ew.push_back(w);
    }
    new_indptr[v + 1] = static_cast<int64_t>(g.adj.size());
  }
  g.indptr = std::move(new_indptr);
  return g;
}

// Heavy-edge matching: returns match[v] (== v for unmatched/self-matched)
// and the number of coarse vertices; cmap[v] = coarse id.
int64_t heavy_edge_matching(const WGraph& g, std::mt19937_64& rng,
                            std::vector<int64_t>& cmap) {
  // Visit low-degree vertices first (random within a degree class) and
  // score candidates by edge weight normalized by the partner's vertex
  // weight. Plain max-weight matching merges across weak bridges when all
  // weights tie (level 0) — bridge endpoints tend to have higher degree,
  // so degree-ordered visiting lets cluster-internal vertices pair up
  // before a bridge endpoint can grab them, and the normalization keeps
  // supernodes from snowballing.
  std::vector<int64_t> order(g.nv);
  for (int64_t i = 0; i < g.nv; ++i) order[i] = i;
  std::shuffle(order.begin(), order.end(), rng);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return (g.indptr[a + 1] - g.indptr[a]) < (g.indptr[b + 1] - g.indptr[b]);
  });
  std::vector<int64_t> match(g.nv, -1);
  for (int64_t idx = 0; idx < g.nv; ++idx) {
    int64_t v = order[idx];
    if (match[v] >= 0) continue;
    int64_t best = -1;
    double best_score = 0.0;
    for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
      int64_t n = g.adj[k];
      if (match[n] >= 0) continue;
      double score = double(g.ew[k]) / double(g.vw[n]);
      if (score > best_score) { best = n; best_score = score; }
    }
    if (best >= 0) { match[v] = best; match[best] = v; }
    else match[v] = v;
  }
  cmap.assign(g.nv, -1);
  int64_t nc = 0;
  for (int64_t v = 0; v < g.nv; ++v) {
    if (cmap[v] >= 0) continue;
    cmap[v] = nc;
    if (match[v] != v) cmap[match[v]] = nc;
    ++nc;
  }
  return nc;
}

// Contract g by cmap into a coarse weighted graph.
WGraph contract(const WGraph& g, const std::vector<int64_t>& cmap, int64_t nc) {
  WGraph c;
  c.nv = nc;
  c.vw.assign(nc, 0);
  for (int64_t v = 0; v < g.nv; ++v) c.vw[cmap[v]] += g.vw[v];
  // gather coarse edges per coarse vertex, then dedup-accumulate
  std::vector<std::pair<int64_t, int64_t>> edges;  // (enc(cu,cv), w) cu<cv
  edges.reserve(g.adj.size() / 2);
  for (int64_t v = 0; v < g.nv; ++v) {
    int64_t cu = cmap[v];
    for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
      int64_t cv = cmap[g.adj[k]];
      if (cu < cv) edges.emplace_back(cu * nc + cv, g.ew[k]);
    }
  }
  std::sort(edges.begin(), edges.end());
  std::vector<int64_t> deg(nc, 0);
  std::vector<std::pair<int64_t, int64_t>> merged;  // (enc, w)
  merged.reserve(edges.size());
  for (size_t i = 0; i < edges.size();) {
    int64_t enc = edges[i].first, w = 0;
    while (i < edges.size() && edges[i].first == enc) { w += edges[i].second; ++i; }
    merged.emplace_back(enc, w);
    ++deg[enc / nc];
    ++deg[enc % nc];
  }
  c.indptr.assign(nc + 1, 0);
  for (int64_t v = 0; v < nc; ++v) c.indptr[v + 1] = c.indptr[v] + deg[v];
  c.adj.assign(c.indptr[nc], 0);
  c.ew.assign(c.indptr[nc], 0);
  std::vector<int64_t> cur(c.indptr.begin(), c.indptr.end() - 1);
  for (auto& [enc, w] : merged) {
    int64_t a = enc / nc, b = enc % nc;
    c.adj[cur[a]] = b; c.ew[cur[a]++] = w;
    c.adj[cur[b]] = a; c.ew[cur[b]++] = w;
  }
  return c;
}

// Weighted greedy region growing on the (coarsest) graph — METIS-style
// GGGP: always absorb the frontier vertex with the STRONGEST connection to
// the growing region. A DFS stack here is catastrophically order-sensitive
// (it dives along weak chain edges, stranding heavy partners on the stack);
// the max-connection heap follows the weight structure instead.
void initial_partition(const WGraph& g, int32_t world_size, std::mt19937_64& rng,
                       std::vector<int32_t>& part) {
  part.assign(g.nv, -1);
  int64_t total_vw = 0;
  for (auto w : g.vw) total_vw += w;
  const int64_t cap = (total_vw + world_size - 1) / world_size;
  std::vector<int64_t> order(g.nv);
  for (int64_t i = 0; i < g.nv; ++i) order[i] = i;
  std::shuffle(order.begin(), order.end(), rng);
  int64_t seed_ptr = 0;
  std::vector<int64_t> conn(g.nv, 0);
  // lazy max-heap of (connection-to-region, vertex); stale entries skipped
  std::priority_queue<std::pair<int64_t, int64_t>> heap;
  for (int32_t r = 0; r < world_size; ++r) {
    int64_t weight = 0;
    while (!heap.empty()) heap.pop();
    std::fill(conn.begin(), conn.end(), 0);
    while (weight < cap) {
      int64_t v = -1;
      while (!heap.empty()) {
        auto [w, u] = heap.top();
        heap.pop();
        if (part[u] < 0 && w == conn[u]) { v = u; break; }
      }
      if (v < 0) {
        while (seed_ptr < g.nv && part[order[seed_ptr]] >= 0) ++seed_ptr;
        if (seed_ptr >= g.nv) break;
        v = order[seed_ptr];
      }
      part[v] = r;
      weight += g.vw[v];
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k) {
        int64_t n = g.adj[k];
        if (part[n] < 0) {
          conn[n] += g.ew[k];
          heap.emplace(conn[n], n);
        }
      }
    }
  }
  for (int64_t v = 0; v < g.nv; ++v)
    if (part[v] < 0) part[v] = world_size - 1;
}

// Greedy boundary refinement (FM-lite): move boundary vertices to the
// neighbor partition with the largest positive cut gain, under a balance
// cap. A few passes per level.
void refine(const WGraph& g, int32_t world_size, std::vector<int32_t>& part,
            int passes, double imbalance) {
  int64_t total_vw = 0;
  for (auto w : g.vw) total_vw += w;
  const int64_t cap =
      static_cast<int64_t>((double(total_vw) / world_size) * imbalance) + 1;
  std::vector<int64_t> pw(world_size, 0);
  for (int64_t v = 0; v < g.nv; ++v) pw[part[v]] += g.vw[v];
  std::vector<int64_t> conn(world_size, 0);
  for (int p = 0; p < passes; ++p) {
    int64_t moves = 0;
    for (int64_t v = 0; v < g.nv; ++v) {
      int32_t pv = part[v];
      bool boundary = false;
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k)
        if (part[g.adj[k]] != pv) { boundary = true; break; }
      if (!boundary) continue;
      std::fill(conn.begin(), conn.end(), 0);
      for (int64_t k = g.indptr[v]; k < g.indptr[v + 1]; ++k)
        conn[part[g.adj[k]]] += g.ew[k];
      int32_t best = pv;
      int64_t best_gain = 0;
      for (int32_t r = 0; r < world_size; ++r) {
        if (r == pv || pw[r] + g.vw[v] > cap) continue;
        int64_t gain = conn[r] - conn[pv];
        if (gain > best_gain) { best = r; best_gain = gain; }
      }
      if (best != pv) {
        pw[pv] -= g.vw[v];
        pw[best] += g.vw[v];
        part[v] = best;
        ++moves;
      }
    }
    if (!moves) break;
  }
}

}  // namespace

// Multilevel k-way partition (the METIS-shaped algorithm the reference
// leans on via pymetis: coarsen by heavy-edge matching, partition the
// coarsest graph, project back with boundary refinement at every level).
void multilevel_partition(const int64_t* src, const int64_t* dst,
                          int64_t num_edges, int64_t num_vertices,
                          int32_t world_size, uint64_t seed,
                          int32_t* out_part) {
  std::mt19937_64 rng(seed);
  std::vector<WGraph> levels;
  std::vector<std::vector<int64_t>> cmaps;
  levels.push_back(build_wgraph(src, dst, num_edges, num_vertices));
  // coarsen until ~16 coarse vertices per partition: deep enough that
  // locality clusters contract to single vertices (the initial partition
  // then only cuts inter-cluster links), shallow enough to stay balanced
  const int64_t coarse_target =
      std::max<int64_t>(static_cast<int64_t>(world_size) * 16, 64);
  while (levels.back().nv > coarse_target) {
    std::vector<int64_t> cmap;
    int64_t nc = heavy_edge_matching(levels.back(), rng, cmap);
    if (nc > levels.back().nv * 95 / 100) break;  // matching stalled
    WGraph coarse = contract(levels.back(), cmap, nc);
    cmaps.push_back(std::move(cmap));
    levels.push_back(std::move(coarse));
  }
  std::vector<int32_t> part;
  initial_partition(levels.back(), world_size, rng, part);
  refine(levels.back(), world_size, part, /*passes=*/4, /*imbalance=*/1.03);
  for (int64_t l = static_cast<int64_t>(cmaps.size()) - 1; l >= 0; --l) {
    const std::vector<int64_t>& cmap = cmaps[l];
    std::vector<int32_t> fine(levels[l].nv);
    for (int64_t v = 0; v < levels[l].nv; ++v) fine[v] = part[cmap[v]];
    part = std::move(fine);
    refine(levels[l], world_size, part, /*passes=*/2, /*imbalance=*/1.03);
  }
  std::memcpy(out_part, part.data(), num_vertices * sizeof(int32_t));
}

extern "C" void multilevel_partition_c(const int64_t* src, const int64_t* dst,
                                       int64_t num_edges, int64_t num_vertices,
                                       int32_t world_size, uint64_t seed,
                                       int32_t* out_part) {
  multilevel_partition(src, dst, num_edges, num_vertices, world_size, seed,
                       out_part);
}

// Deduplicate (key, value) pairs encoded as key*stride+value, sorted.
// Returns the number of unique pairs written to out (caller allocates n).
int64_t unique_encoded_pairs(const int64_t* keys, const int64_t* vals,
                             int64_t n, int64_t stride, int64_t* out) {
  std::vector<int64_t> enc(n);
  for (int64_t i = 0; i < n; ++i) enc[i] = keys[i] * stride + vals[i];
  std::sort(enc.begin(), enc.end());
  auto end = std::unique(enc.begin(), enc.end());
  int64_t m = static_cast<int64_t>(end - enc.begin());
  std::memcpy(out, enc.data(), m * sizeof(int64_t));
  return m;
}

// Multi-threaded edge-cut count (partition quality metric at scale).
int64_t edge_cut_count(const int64_t* src, const int64_t* dst, int64_t num_edges,
                       const int32_t* part) {
  unsigned hw = std::thread::hardware_concurrency();
  int num_threads = hw ? static_cast<int>(hw) : 4;
  if (num_edges < (1 << 16)) num_threads = 1;
  std::vector<int64_t> partial(num_threads, 0);
  std::vector<std::thread> threads;
  int64_t chunk = (num_edges + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    threads.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi = std::min<int64_t>(num_edges, lo + chunk);
      int64_t c = 0;
      for (int64_t e = lo; e < hi; ++e)
        if (part[src[e]] != part[dst[e]]) ++c;
      partial[t] = c;
    });
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  for (auto c : partial) total += c;
  return total;
}

}  // extern "C"
