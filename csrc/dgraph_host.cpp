// Native host-side graph toolkit for dgraph_tpu.
//
// Role: the TPU-native counterpart of the reference's native layer. The
// reference's C++/CUDA lives in the device path
// (DGraph/distributed/csrc/*: gather/scatter kernels, NVSHMEM runtime); on
// TPU the device path is XLA/Pallas, so native code belongs where Python is
// actually the bottleneck: HOST-side plan building and partitioning of
// billion-edge graphs (SURVEY.md §7 "papers100M plan build memory/time").
//
// Exposed via a plain C ABI and loaded with ctypes (no pybind11 in this
// environment). Every entry point has a numpy fallback in
// dgraph_tpu/partition.py / plan.py — the reference's dual
// native/fallback pattern (RankLocalOps.py:21-31).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <queue>
#include <random>
#include <thread>
#include <vector>

extern "C" {

// Build an undirected CSR adjacency from a directed edge list.
// indptr must hold V+1 entries; if indices == nullptr, only fills indptr
// (call once to size, once to fill).
void build_sym_csr(const int64_t* src, const int64_t* dst, int64_t num_edges,
                   int64_t num_vertices, int64_t* indptr, int64_t* indices) {
  std::vector<int64_t> deg(num_vertices, 0);
  for (int64_t e = 0; e < num_edges; ++e) {
    ++deg[src[e]];
    ++deg[dst[e]];
  }
  indptr[0] = 0;
  for (int64_t v = 0; v < num_vertices; ++v) indptr[v + 1] = indptr[v] + deg[v];
  if (!indices) return;
  std::vector<int64_t> cur(indptr, indptr + num_vertices);
  for (int64_t e = 0; e < num_edges; ++e) {
    indices[cur[src[e]]++] = dst[e];
    indices[cur[dst[e]]++] = src[e];
  }
}

// Greedy BFS region-growing partition with hard balance cap — the METIS
// substitute for very large graphs. Deterministic for a fixed seed.
void greedy_bfs_partition(const int64_t* src, const int64_t* dst,
                          int64_t num_edges, int64_t num_vertices,
                          int32_t world_size, uint64_t seed, int32_t* out_part) {
  std::vector<int64_t> indptr(num_vertices + 1);
  std::vector<int64_t> indices;
  build_sym_csr(src, dst, num_edges, num_vertices, indptr.data(), nullptr);
  indices.resize(indptr[num_vertices]);
  build_sym_csr(src, dst, num_edges, num_vertices, indptr.data(), indices.data());

  std::fill(out_part, out_part + num_vertices, -1);
  std::vector<int64_t> order(num_vertices);
  for (int64_t i = 0; i < num_vertices; ++i) order[i] = i;
  std::mt19937_64 rng(seed);
  std::shuffle(order.begin(), order.end(), rng);

  const int64_t cap = (num_vertices + world_size - 1) / world_size;
  int64_t seed_ptr = 0;
  std::vector<int64_t> stack;
  stack.reserve(1024);
  for (int32_t r = 0; r < world_size; ++r) {
    int64_t count = 0;
    stack.clear();
    while (count < cap) {
      if (stack.empty()) {
        while (seed_ptr < num_vertices && out_part[order[seed_ptr]] >= 0) ++seed_ptr;
        if (seed_ptr >= num_vertices) break;
        stack.push_back(order[seed_ptr]);
      }
      int64_t v = stack.back();
      stack.pop_back();
      if (out_part[v] >= 0) continue;
      out_part[v] = r;
      ++count;
      for (int64_t k = indptr[v]; k < indptr[v + 1]; ++k) {
        int64_t n = indices[k];
        if (out_part[n] < 0) stack.push_back(n);
      }
    }
  }
  for (int64_t v = 0; v < num_vertices; ++v)
    if (out_part[v] < 0) out_part[v] = world_size - 1;
}

// Deduplicate (key, value) pairs encoded as key*stride+value, sorted.
// Returns the number of unique pairs written to out (caller allocates n).
int64_t unique_encoded_pairs(const int64_t* keys, const int64_t* vals,
                             int64_t n, int64_t stride, int64_t* out) {
  std::vector<int64_t> enc(n);
  for (int64_t i = 0; i < n; ++i) enc[i] = keys[i] * stride + vals[i];
  std::sort(enc.begin(), enc.end());
  auto end = std::unique(enc.begin(), enc.end());
  int64_t m = static_cast<int64_t>(end - enc.begin());
  std::memcpy(out, enc.data(), m * sizeof(int64_t));
  return m;
}

// Multi-threaded edge-cut count (partition quality metric at scale).
int64_t edge_cut_count(const int64_t* src, const int64_t* dst, int64_t num_edges,
                       const int32_t* part) {
  unsigned hw = std::thread::hardware_concurrency();
  int num_threads = hw ? static_cast<int>(hw) : 4;
  if (num_edges < (1 << 16)) num_threads = 1;
  std::vector<int64_t> partial(num_threads, 0);
  std::vector<std::thread> threads;
  int64_t chunk = (num_edges + num_threads - 1) / num_threads;
  for (int t = 0; t < num_threads; ++t) {
    threads.emplace_back([&, t]() {
      int64_t lo = t * chunk, hi = std::min<int64_t>(num_edges, lo + chunk);
      int64_t c = 0;
      for (int64_t e = lo; e < hi; ++e)
        if (part[src[e]] != part[dst[e]]) ++c;
      partial[t] = c;
    });
  }
  for (auto& th : threads) th.join();
  int64_t total = 0;
  for (auto c : partial) total += c;
  return total;
}

}  // extern "C"
