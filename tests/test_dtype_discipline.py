"""Edge-pipeline dtype discipline: a bf16 model must not materialize f32
[e_pad, F] intermediates.

Regression guard for the r4 on-chip finding: ``halo_exchange`` multiplied
send rows by the plan's f32 ``send_mask``, upcasting the halo rows, then the
``halo_extend`` concat upcast the whole vertex table — every [E, F] tensor
of the bf16 GCN epoch (takes, relu, scatter inputs, cotangents) silently ran
in f32. That doubled the HBM bytes of the edge pipeline (the dominant
traffic: E >> N) and flipped the Pallas segment-sum to its 3-pass "highest"
MXU precision, which is selected by input dtype. The reference hits the
same class of bug with implicit CUDA type promotion; its kernels pin dtypes
at the C++ signature level (``local_data_kernels.cuh``) — here the pin is
this jaxpr walk.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgraph_tpu.comm import Communicator
from dgraph_tpu.plan import build_edge_plan


# Ops whose edge-sized operands/results MUST materialize in HBM (fusion
# barriers). Elementwise f32 (convert/add/compare chains) fuses into
# registers and is allowed — e.g. the fused bwd decides its ReLU mask via
# an f32 add+compare whose streams are bf16.
_BARRIERS = frozenset({
    "gather", "scatter", "scatter-add", "pallas_call", "concatenate",
    "sort", "dynamic_update_slice", "all_to_all", "ppermute",
})


# ONE canonical jaxpr traversal, shared with the trace auditor (descent
# into custom_vjp/custom_jvp bodies, scan, pjit, remat) — the auditor's
# collective collectors and these dtype collectors must never disagree on
# which sub-jaxprs are reachable.
from dgraph_tpu.analysis.trace import walk_eqns as _walk_eqns


def _edge_sized_scatter_adds(jaxpr, e_pad, out):
    """Collect every scatter-add whose updates are [e_pad, ...] — with the
    Pallas scatter enabled these must not exist: the r4 bench's 597 ms
    regression was the fused-fallback path sending the model's main
    aggregation to XLA scatter-add while the healthy Pallas kernel sat
    idle (local.py sorted_segment_sum_bias_relu_any routing)."""

    def visit(eqn):
        if eqn.primitive.name in ("scatter-add", "scatter"):
            for v in eqn.invars:
                aval = getattr(v, "aval", None)
                if (
                    aval is not None
                    and getattr(aval, "shape", ())
                    and aval.shape[0] == e_pad
                    and len(aval.shape) > 1
                ):
                    out.append((eqn.primitive.name, tuple(aval.shape)))

    _walk_eqns(jaxpr, visit)
    return out


def _edge_sized_f32_at_barriers(jaxpr, e_pad, out):
    """Collect (primitive, shape) for every f32 operand/result with
    leading dim == e_pad at a fusion-barrier op."""

    def visit(eqn):
        if eqn.primitive.name in _BARRIERS:
            for v in list(eqn.outvars) + list(eqn.invars):
                aval = getattr(v, "aval", None)
                if (
                    aval is not None
                    and getattr(aval, "shape", ())
                    and aval.shape[0] == e_pad
                    and aval.dtype == jnp.float32
                ):
                    out.append((eqn.primitive.name, tuple(aval.shape)))

    _walk_eqns(jaxpr, visit)
    return out


def test_bf16_sage_fwd_bwd_discipline():
    """SAGE aggregates the INPUT features (not a projection), so it has
    its own upcast hazard: gathering the raw f32 x through the edge
    pipeline. Pinned after the r4 audit found exactly that."""
    from dgraph_tpu import config as cfg
    from dgraph_tpu.models.sage import GraphSAGE

    V, E_half, F = 2_048, 8_192, 32
    rng = np.random.default_rng(0)
    src = rng.integers(0, V, E_half)
    dst = rng.integers(0, V, E_half)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)
    plan_np, _ = build_edge_plan(
        edge_index, np.zeros(V, np.int32), world_size=1, edge_owner="dst",
        pad_multiple=128,
    )
    plan = jax.tree.map(lambda l: jnp.asarray(np.asarray(l)[0]), plan_np)
    e_pad = int(plan_np.e_pad)

    old = (cfg.use_pallas_scatter, cfg.use_pallas_fused)
    cfg.set_flags(use_pallas_scatter=True, use_pallas_fused=True)
    orig_db = jax.default_backend
    jax.default_backend = lambda: "tpu"
    try:
        comm = Communicator.init_process_group("single")
        model = GraphSAGE(
            hidden_features=64, out_features=8, comm=comm, num_layers=2,
            dtype=jnp.bfloat16,
        )
        x = jnp.zeros((plan_np.n_src_pad, F), jnp.float32)
        params = jax.eval_shape(lambda: model.init(jax.random.key(0), x, plan))
        params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)

        def lf(p):
            out = model.apply(p, x, plan)
            return (out.astype(jnp.float32) ** 2).sum()

        jaxpr = jax.make_jaxpr(jax.grad(lf))(params)
        bad = _edge_sized_f32_at_barriers(jaxpr.jaxpr, e_pad, [])
        bad = [(n, s) for (n, s) in bad if len(s) > 1 and s[-1] > 1]
        assert not bad, f"bf16 SAGE f32 edge tensors at barriers: {bad[:8]}"
        # rogue check: [e_pad, 1] degree-count scatters are allowed
        # (narrow, measured-decision pending — see r4c notes); WIDE
        # edge reductions must ride the Pallas path
        rogue = _edge_sized_scatter_adds(jaxpr.jaxpr, e_pad, [])
        rogue = [(n, s) for (n, s) in rogue if s[-1] > 8]
        assert not rogue, f"bf16 SAGE wide XLA edge scatters: {rogue[:8]}"
    finally:
        jax.default_backend = orig_db
        cfg.set_flags(use_pallas_scatter=old[0], use_pallas_fused=old[1])


@pytest.mark.parametrize("fused", [False, True])
def test_bf16_gcn_epoch_has_no_f32_edge_tensors(fused):
    from dgraph_tpu import config as cfg
    from dgraph_tpu.models import GCN

    V, E_half, F, C, H = 2_048, 8_192, 32, 8, 64
    rng = np.random.default_rng(0)
    src = rng.integers(0, V, E_half)
    dst = rng.integers(0, V, E_half)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)
    plan_np, _ = build_edge_plan(
        edge_index, np.zeros(V, np.int32), world_size=1, edge_owner="dst",
        pad_multiple=128,
    )
    plan = jax.tree.map(lambda l: jnp.asarray(np.asarray(l)[0]), plan_np)
    e_pad = int(plan_np.e_pad)

    old = (cfg.use_pallas_scatter, cfg.use_pallas_fused)
    # The discipline is a property of the TPU program: the dispatch gates
    # read jax.default_backend() at trace time, so patch it to "tpu" for
    # the make_jaxpr call (tracing never executes a kernel). The CPU
    # fallback intentionally upcasts to f32 for accumulation correctness
    # — that path is exempt by construction here.
    cfg.set_flags(use_pallas_scatter=True, use_pallas_fused=fused)
    orig_db = jax.default_backend
    jax.default_backend = lambda: "tpu"
    try:
        comm = Communicator.init_process_group("single")
        model = GCN(
            hidden_features=H, out_features=C, comm=comm, num_layers=2,
            dtype=jnp.bfloat16,
        )
        x = jnp.zeros((plan_np.n_src_pad, F), jnp.float32)
        y = jnp.zeros((plan_np.n_src_pad,), jnp.int32)
        mask = (jnp.arange(plan_np.n_src_pad) < V).astype(jnp.float32)
        params = jax.eval_shape(lambda: model.init(jax.random.key(0), x, plan))
        params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)

        def loss_and_grad(p):
            def lf(p_):
                logits = model.apply(p_, x, plan)
                logp = jax.nn.log_softmax(logits.astype(jnp.float32))
                ll = jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
                return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

            return jax.value_and_grad(lf)(p)

        jaxpr = jax.make_jaxpr(loss_and_grad)(params)
        bad = _edge_sized_f32_at_barriers(jaxpr.jaxpr, e_pad, [])
        # [e_pad]-sized 1-D f32 and [e_pad, 1] masks are fine (edge
        # weights/masks, skinny); the discipline is about [e_pad, F]
        # STREAMS
        bad = [(n, s) for (n, s) in bad if len(s) > 1 and s[-1] > 1]
        assert not bad, (
            f"bf16 GCN (fused={fused}) materializes f32 edge-sized tensors "
            f"(doubles edge-pipeline HBM traffic): {bad[:8]}"
        )
        rogue = _edge_sized_scatter_adds(jaxpr.jaxpr, e_pad, [])
        assert not rogue, (
            f"bf16 GCN (fused={fused}) with the Pallas scatter enabled "
            f"still routes edge-sized reductions to XLA scatter: {rogue[:8]}"
        )
    finally:
        jax.default_backend = orig_db
        cfg.set_flags(use_pallas_scatter=old[0], use_pallas_fused=old[1])
