"""Device-initiated one-sided halo exchange (halo_impl="pallas_p2p").

Parity strategy (the bar the overlap lowering set, test_overlap.py): the
one-sided put transport must be BIT-IDENTICAL to the padded all_to_all
path, forward and backward, on the 2- and 4-shard synthetic graphs. The
kernel is pure data movement (plus an exact elementwise mask multiply),
so the pins run on the CPU backend via Pallas interpret mode — no chip
needed. Resolution-ladder rows, knob rejection, and footprint pricing are
host-only (no compiles).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu import config as cfg
from dgraph_tpu import plan as pl
from dgraph_tpu.comm import collectives
from dgraph_tpu.comm.mesh import make_graph_mesh
from dgraph_tpu.plan import shard_edge_data, shard_vertex_data
from dgraph_tpu.testing import spmd_apply


@pytest.fixture
def impl_flags():
    saved = (cfg.halo_impl, cfg.tuned_halo_impl, cfg.use_pallas_p2p)
    yield
    cfg.set_flags(
        halo_impl=saved[0], tuned_halo_impl=saved[1], use_pallas_p2p=saved[2]
    )


def _case(rng, W, V=48, E=300):
    part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
    plan, layout = pl.build_edge_plan(edges, part, world_size=W, overlap=True)
    return edges, part, plan, layout


def _run_all(mesh, plan, xs, ed, ct_e, ct_v):
    """One jitted program per lowering: gather fwd+grad and halo-side
    scatter fwd+grad together (keeps the new-compile count low — the
    tier-1 budget rule)."""

    def everything(xs_, ed_):
        out_g = spmd_apply(
            mesh, collectives.gather, plan, xs_, static_args=("src", "graph")
        )
        g_g = jax.grad(
            lambda x: jnp.sum(
                spmd_apply(mesh, collectives.gather, plan, x,
                           static_args=("src", "graph")) * ct_e
            )
        )(xs_)
        out_s = spmd_apply(
            mesh, collectives.scatter_sum, plan, ed_,
            static_args=("src", "graph"),
        )
        g_s = jax.grad(
            lambda e: jnp.sum(
                spmd_apply(mesh, collectives.scatter_sum, plan, e,
                           static_args=("src", "graph")) * ct_v
            )
        )(ed_)
        return out_g, g_g, out_s, g_s

    with jax.set_mesh(mesh):
        return [np.asarray(a) for a in jax.jit(everything)(xs, ed)]


@pytest.mark.parametrize("W", [2, 4])
def test_p2p_bitwise_parity_with_all_to_all(rng, impl_flags, W):
    """halo_exchange_p2p / halo_scatter_sum_p2p (through the gather and
    halo-side scatter they lower) are bit-identical to the all_to_all
    path, forward AND backward — the one-sided puts move the exact same
    tiles; every arithmetic op is shared with the serial path."""
    edges, part, plan, layout = _case(rng, W)
    V, F = len(part), 5
    xs = jnp.asarray(shard_vertex_data(
        rng.normal(size=(V, F)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    ))
    ed = jnp.asarray(shard_edge_data(
        rng.normal(size=(edges.shape[1], F)).astype(np.float32),
        layout, plan.e_pad,
    ))
    ct_e = jnp.asarray(shard_edge_data(
        rng.normal(size=(edges.shape[1], F)).astype(np.float32),
        layout, plan.e_pad,
    ))
    ct_v = jnp.asarray(shard_vertex_data(
        rng.normal(size=(V, F)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    ))
    mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])

    cfg.set_flags(halo_impl="pallas_p2p", use_pallas_p2p=True)
    got_p2p = _run_all(mesh, plan, xs, ed, ct_e, ct_v)
    cfg.set_flags(halo_impl="all_to_all", use_pallas_p2p=None)
    got_a2a = _run_all(mesh, plan, xs, ed, ct_e, ct_v)
    for name, a, b in zip(
        ("gather fwd", "gather grad", "scatter fwd", "scatter grad"),
        got_p2p, got_a2a,
    ):
        np.testing.assert_array_equal(a, b, err_msg=f"{name} not bit-identical")


def test_p2p_models_match_all_to_all(rng, impl_flags):
    """Model-level routing (GCN fused + SAGE through split_active /
    halo_exchange_split) agrees with the serial lowering — allclose, not
    bitwise: the interior/boundary split regroups the owner-side float
    accumulation (the overlap precedent)."""
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
    from dgraph_tpu.models.gcn import GraphConvLayer
    from dgraph_tpu.models.sage import SAGEConv

    W, V, E, F = 2, 48, 300, 8
    edges, part, plan, layout = _case(rng, W, V, E)
    mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])
    comm = Communicator.init_process_group("tpu", world_size=W)
    xs = jnp.asarray(shard_vertex_data(
        rng.normal(size=(V, F)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    ))
    modules = [
        GraphConvLayer(out_features=8, comm=comm),  # fused bias+relu path
        SAGEConv(out_features=8, comm=comm),  # identity-message path
    ]

    def run(module, impl):
        cfg.set_flags(
            halo_impl=impl,
            use_pallas_p2p=True if impl == "pallas_p2p" else None,
        )

        def body(x_, p_):
            psq = squeeze_plan(p_)
            params = module.init(jax.random.key(0), x_[0], psq)
            return module.apply(params, x_[0], psq)[None]

        f = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(GRAPH_AXIS), plan_in_specs(plan)),
            out_specs=P(GRAPH_AXIS),
            **collectives.shard_map_checks(plan, GRAPH_AXIS),
        )
        with jax.set_mesh(mesh):
            return np.asarray(jax.jit(f)(xs, jax.tree.map(jnp.asarray, plan)))

    for module in modules:
        a = run(module, "pallas_p2p")
        b = run(module, "all_to_all")
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-5)


# ---------------------------------------------------------------------------
# Host-only: resolution ladder, knob rejection, footprint pricing
# ---------------------------------------------------------------------------


class TestResolveP2PLadder:
    """Decision-ladder rows for the new choice (mirror of test_plan's
    TestResolveHaloImplLadder, host-only)."""

    def test_env_pin_resolves(self, impl_flags):
        cfg.set_flags(halo_impl="pallas_p2p", use_pallas_p2p=True)
        impl, source = pl.resolve_halo_impl(2, (1,), overlap_available=True)
        assert (impl, source) == ("pallas_p2p", "env")

    def test_record_tier_resolves(self, impl_flags):
        cfg.set_flags(
            halo_impl="auto", tuned_halo_impl="pallas_p2p",
            use_pallas_p2p=True,
        )
        impl, source = pl.resolve_halo_impl(2, (1,), overlap_available=True)
        assert (impl, source) == ("pallas_p2p", "record")

    def test_env_pin_beats_p2p_record(self, impl_flags):
        cfg.set_flags(
            halo_impl="all_to_all", tuned_halo_impl="pallas_p2p",
            use_pallas_p2p=True,
        )
        impl, source = pl.resolve_halo_impl(2, (1,), overlap_available=True)
        assert (impl, source) == ("all_to_all", "env")

    def test_degrades_without_split(self, impl_flags):
        """A pallas_p2p pin on a plan with no interior/boundary split must
        fall to a lowerable tier, never half-lower."""
        cfg.set_flags(halo_impl="pallas_p2p", use_pallas_p2p=True)
        impl, source = pl.resolve_halo_impl(2, (1,), overlap_available=False)
        assert impl in ("ppermute", "all_to_all")
        assert source == "heuristic"

    def test_degrades_without_backend(self, impl_flags):
        """...and on a backend that cannot lower the kernels (no TPU, no
        interpret opt-in) — the record tier still gets its chance."""
        cfg.set_flags(
            halo_impl="pallas_p2p", tuned_halo_impl="overlap",
            use_pallas_p2p=False,
        )
        impl, source = pl.resolve_halo_impl(2, (1,), overlap_available=True)
        assert (impl, source) == ("overlap", "record")

    def test_heuristic_never_picks_p2p(self, impl_flags):
        """The heuristic tier must not auto-adopt an un-A/B'd kernel even
        where it is available (pin/record only — the use_pallas_gather
        precedent)."""
        cfg.set_flags(
            halo_impl="auto", tuned_halo_impl=None, use_pallas_p2p=True
        )
        impl, source = pl.resolve_halo_impl(2, (1,), overlap_available=True)
        assert impl == "overlap"
        assert source == "heuristic"

    def test_p2p_intent_builds_split(self, rng, impl_flags):
        """An env pin makes build_edge_plan(overlap=None) attach the
        interior/boundary split — same auto rule as overlap."""
        cfg.set_flags(halo_impl="pallas_p2p", use_pallas_p2p=True)
        W, V = 2, 32
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([np.arange(V), (np.arange(V) + 1) % V])
        plan, _ = pl.build_edge_plan(edges, part, world_size=W)  # auto
        assert plan.overlap is not None


class TestRejectIncompatibleP2PKnobs:
    def test_rejects_unsorted_edges(self, rng, impl_flags):
        cfg.set_flags(halo_impl="pallas_p2p", use_pallas_p2p=True)
        W, V = 2, 32
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([rng.integers(0, V, 64), rng.integers(0, V, 64)])
        with pytest.raises(ValueError, match="pallas_p2p.*sort_edges"):
            pl.build_edge_plan(
                edges, part, world_size=W, sort_edges=False, overlap=False
            )

    def test_rejects_unaligned_s_pad(self, rng, impl_flags):
        cfg.set_flags(halo_impl="pallas_p2p", use_pallas_p2p=True)
        W, V = 2, 32
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([np.arange(V), (np.arange(V) + 1) % V])
        with pytest.raises(ValueError, match="pallas_p2p.*s_pad"):
            pl.build_edge_plan(
                edges, part, world_size=W, s_pad=12, pad_multiple=1
            )


def test_footprint_p2p_pricing(rng, impl_flags):
    """The pinned pallas_p2p lowering is priced: boundary-only operand
    (same wire bytes as the rounds it replaces), a CONSERVATIVE headline
    HBM figure (the reverse leg always pre-stages its tiles — only the
    fused forward leg can skip a stream, reported separately), and a
    per-tile schedule with exposed <= serial."""
    _, _, plan, _ = _case(rng, 4)
    from dgraph_tpu.obs.footprint import plan_footprint

    cfg.set_flags(halo_impl="pallas_p2p", use_pallas_p2p=True)
    fp = plan_footprint(plan, "bfloat16", 32)
    ex = fp["collectives"]["halo_exchange"]
    assert ex["impl"] == "pallas_p2p"
    assert ex["impl_source"] == "env"
    wire = fp["halo"]["wire_bytes_per_shard"]
    assert wire["pallas_p2p"] == wire["ppermute"]
    assert ex["operand_bytes_per_shard"] < ex["a2a_operand_bytes_per_shard"]
    p2p = ex["pallas_p2p"]
    assert p2p["tiles"] == len(plan.halo_deltas)
    assert p2p["exposed_us"] <= p2p["serial_us"]
    assert p2p["hidden_us"] >= 0
    # headline HBM billing matches ppermute's (2*n + W) streams — the
    # tuner must not be handed a saving the reverse leg never delivers;
    # the fused-forward figure is strictly smaller and reported apart
    cfg.set_flags(halo_impl="ppermute")
    fp_pp = plan_footprint(plan, "bfloat16", 32)
    assert (
        ex["hbm_bytes_per_shard"]
        == fp_pp["collectives"]["halo_exchange"]["hbm_bytes_per_shard"]
    )
    assert p2p["fwd_fused_hbm_bytes_per_shard"] < ex["hbm_bytes_per_shard"]


def test_serve_zero_recompile_with_p2p_record(rng, impl_flags):
    """A serve engine whose forward routes the pallas_p2p lowering (as an
    adopted record would pin it) AOT-warms its bucket and then serves
    with ZERO steady-state compiles — the adoption surface the tuner
    hands records to."""
    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.models import GCN
    from dgraph_tpu.serve.bucketing import BucketLadder
    from dgraph_tpu.serve.engine import ServeEngine
    from dgraph_tpu.train.loop import init_params

    W, V = 2, 48
    _, part, plan, layout = _case(rng, W, V)
    mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])
    comm = Communicator.init_process_group("tpu", world_size=W)
    model = GCN(hidden_features=8, out_features=4, comm=comm, num_layers=2)
    x = shard_vertex_data(
        rng.normal(size=(V, 8)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    )
    batch = {"x": jnp.asarray(x)}
    # original id -> (owner rank, slot): the partition is contiguous, so
    # the slot is the index within the rank's block
    offsets = np.concatenate([[0], np.cumsum(np.bincount(part, minlength=W))])
    id_rank = part
    id_slot = (np.arange(V) - offsets[part]).astype(np.int32)

    # the record's pin: tuned_halo_impl the way adopt_record sets it
    cfg.set_flags(
        halo_impl="auto", tuned_halo_impl="pallas_p2p", use_pallas_p2p=True
    )
    plan_dev = jax.tree.map(jnp.asarray, plan)
    params = init_params(
        model, mesh, plan_dev, {**batch, "y": None, "mask": None},
        seed=0, batch_args=lambda b, p: (b["x"], p),
    )
    with jax.set_mesh(mesh):
        engine = ServeEngine(
            model, mesh, plan_dev, params, batch,
            id_rank=id_rank, id_slot=id_slot,
            ladder=BucketLadder((8,)),
        )
        engine.warmup()
        for start in (0, 5, 11):
            out = engine.infer(np.arange(start, start + 4) % V)
            assert out.shape == (4, 4)
        assert engine.recompiles_since_warmup() == 0
