"""Elastic world membership (dgraph_tpu/comm/membership.py): lease/
heartbeat liveness, straggler vs loss classification, deadline barriers,
retrying rendezvous with capped backoff, event plumbing through
spans/health, and the chaos points. Everything here is pure host code
driven by a FAKE clock — zero XLA compiles, zero real sleeps beyond the
sub-second chaos delay check."""

import json
import os
import subprocess
import sys

import pytest

from dgraph_tpu import chaos
from dgraph_tpu.comm import membership as ms

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_clean():
    yield
    chaos.reset()


# the ONE fake monotonic clock (sleep advances it) — membership ships it
# for its own selftest; reusing it keeps the semantics from forking
FakeClock = ms._FakeClock


def make_world(tmp_path, world_size, lease_s=2.0, **kw):
    clock = FakeClock()
    members = [
        ms.Membership(
            str(tmp_path), rank=r, world_size=world_size, lease_s=lease_s,
            clock=clock, sleep=clock.sleep, **kw,
        )
        for r in range(world_size)
    ]
    return clock, members


# ---------------------------------------------------------------------------
# liveness: heartbeat / straggler / loss / leave
# ---------------------------------------------------------------------------


def test_all_alive_after_heartbeats(tmp_path):
    clock, (a, b, c) = make_world(tmp_path, 3)
    for m in (a, b, c):
        m.heartbeat()
    evs = a.poll()
    assert a.alive() == (0, 1, 2)
    assert any(e.kind == "membership_changed" for e in evs)
    # a second quiet poll is event-free
    assert a.poll() == []


def test_straggler_then_loss_classification(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=2.0)
    a.heartbeat(), b.heartbeat()
    a.poll()
    # silent past straggler_after_s (lease/2 = 1.0) but inside the lease:
    # reported once, not evicted
    clock.sleep(1.2)
    a.heartbeat()
    evs = a.poll()
    stragglers = [e for e in evs if e.kind == "straggler"]
    assert [e.rank for e in stragglers] == [1]
    assert a.alive() == (0, 1)
    assert [e for e in a.poll() if e.kind == "straggler"] == []  # one/episode
    # a resumed heartbeat closes the episode and re-arms the detector
    b.heartbeat()
    assert a.poll() == []
    clock.sleep(1.2)
    assert [e.rank for e in a.poll() if e.kind == "straggler"] == [1]
    # ...and full silence past the lease is a loss
    clock.sleep(1.0)
    evs = a.poll()
    losses = [e for e in evs if e.kind == "rank_lost"]
    assert len(losses) == 1 and losses[0].rank == 1
    assert losses[0].silent_for_s > 2.0
    assert a.alive() == (0,) and a.lost() == (1,)
    changed = [e for e in evs if e.kind == "membership_changed"]
    assert changed[-1].lost == (1,) and changed[-1].world_size == 2
    # terminal: never re-reported
    assert a.poll() == []
    for rec in a.events:
        json.dumps(rec)


def test_graceful_leave_is_not_a_loss(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2)
    a.heartbeat(), b.heartbeat()
    a.poll()
    b.leave()
    evs = a.poll()
    assert a.alive() == (0,) and a.lost() == ()
    assert any(e.kind == "membership_changed" and 1 in e.left for e in evs)
    assert not any(e.kind == "rank_lost" for e in evs)


def test_never_seen_rank_is_pending_not_lost(tmp_path):
    clock, members = make_world(tmp_path, 3)
    a = members[0]
    a.heartbeat()
    clock.sleep(100.0)
    assert a.poll() == []  # join deadlines belong to rendezvous
    assert a.alive() == (0,) and a.lost() == ()


def test_events_flow_into_health(tmp_path):
    from dgraph_tpu.obs.health import RunHealth

    clock = FakeClock()
    h = RunHealth.begin("t")
    a = ms.Membership(str(tmp_path), rank=0, world_size=2, lease_s=1.0,
                      clock=clock, sleep=clock.sleep, health=h)
    b = ms.Membership(str(tmp_path), rank=1, world_size=2, lease_s=1.0,
                      clock=clock, sleep=clock.sleep)
    b.heartbeat()
    a.poll()
    clock.sleep(1.5)
    a.poll()
    kinds = [e["kind"] for e in h.events]
    assert "rank_lost" in kinds and "membership_changed" in kinds
    json.dumps(h.finish())


def test_background_heartbeats_survive_slow_steps(tmp_path):
    # REAL clock on purpose: the thread is what keeps a live-but-slow
    # member (one step stretched far past the lease by a long orbax
    # write or a loaded machine) from reading as dead to its peers —
    # liveness tracks the process, not the step cadence
    import time as _time

    a = ms.Membership(str(tmp_path), rank=0, world_size=2, lease_s=0.4)
    b = ms.Membership(str(tmp_path), rank=1, world_size=2, lease_s=0.4)
    a.heartbeat(), b.heartbeat()
    a.poll()
    b.start_heartbeats(interval_s=0.05)
    b.start_heartbeats()  # idempotent
    try:
        deadline = _time.monotonic() + 1.2  # 3x the lease, b never "steps"
        while _time.monotonic() < deadline:
            a.heartbeat()
            assert not [e for e in a.poll() if e.kind == "rank_lost"]
            _time.sleep(0.05)
        assert a.alive() == (0, 1)
    finally:
        b.stop_heartbeats()
    # once the thread stops (process death), the lease expires as usual
    t0 = _time.monotonic()
    lost = []
    while _time.monotonic() - t0 < 10.0 and not lost:
        a.heartbeat()
        lost = [e for e in a.poll() if e.kind == "rank_lost"]
        _time.sleep(0.05)
    assert [e.rank for e in lost] == [1]


# ---------------------------------------------------------------------------
# rendezvous + barrier
# ---------------------------------------------------------------------------


def test_rendezvous_joins_and_times_out(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2)
    b.heartbeat()
    assert a.rendezvous(deadline_s=10.0) == (0, 1)
    # a world that never fills names the missing ranks
    solo = ms.Membership(str(tmp_path / "solo"), rank=0, world_size=3,
                         lease_s=2.0, clock=clock, sleep=clock.sleep)
    with pytest.raises(ms.DeadlineExceeded) as ei:
        solo.rendezvous(deadline_s=3.0)
    assert ei.value.missing == (1, 2)
    json.dumps(ei.value.record())


def test_rendezvous_backoff_is_capped_with_jitter(tmp_path):
    clock = FakeClock()
    # world of 2 that never fills: observe the sleep schedule
    slept = []
    m = ms.Membership(str(tmp_path), rank=0, world_size=2, lease_s=2.0,
                      clock=clock, sleep=lambda s: (slept.append(s),
                                                    clock.sleep(s))[-1])
    with pytest.raises(ms.DeadlineExceeded):
        m.rendezvous(deadline_s=20.0, backoff_s=0.1, backoff_factor=2.0,
                     backoff_max_s=1.0)
    # exponential up to the cap, plus jitter in [0, backoff_s)
    bases = [min(0.1 * 2.0 ** k, 1.0) for k in range(len(slept))]
    for got, base in zip(slept, bases):
        assert base <= got < base + 0.1, (got, base)


def test_rendezvous_retries_through_chaos_fault(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2)
    b.heartbeat()
    chaos.arm("comm.rendezvous=raise@0:count=2")
    assert a.rendezvous(deadline_s=30.0) == (0, 1)
    assert chaos.call_count("comm.rendezvous") >= 3


def test_heartbeat_fires_chaos_point(tmp_path):
    clock, (a,) = make_world(tmp_path, 1)
    chaos.arm("comm.heartbeat=raise@1")  # seq counter starts at 1
    with pytest.raises(chaos.ChaosFault):
        a.heartbeat()


def test_chaos_delay_on_heartbeat_reads_as_straggler(tmp_path):
    # the injected straggler: a delay clause holds the heartbeat WRITE,
    # so the peer observes exactly a late member — reported, not evicted
    clock, (a, b) = make_world(tmp_path, 2, lease_s=2.0)
    a.heartbeat(), b.heartbeat()
    a.poll()

    def delayed_heartbeat():
        chaos.arm("comm.heartbeat=delay@0:count=99:sleep_s=0.01:seed=5")
        try:
            b.heartbeat()
        finally:
            chaos.disarm()

    clock.sleep(1.5)  # b silent past straggler_after, inside lease
    evs = a.poll()
    assert [e.rank for e in evs if e.kind == "straggler"] == [1]
    delayed_heartbeat()  # b eventually lands its (delayed) write
    evs = a.poll()
    assert a.alive() == (0, 1) and a.lost() == ()


def test_barrier_completes_and_reports_stragglers(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=60.0)
    a.heartbeat(), b.heartbeat()
    a.poll(), b.poll()
    a.arrive("e0")
    res = b.barrier("e0", deadline_s=10.0)
    assert res["arrived"] == [0, 1] and res["stragglers"] == []
    res = a.barrier("e0", deadline_s=10.0)
    assert res["arrived"] == [0, 1]


def test_barrier_deadline_names_missing_rank(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=60.0)
    a.heartbeat(), b.heartbeat()
    a.poll()
    with pytest.raises(ms.DeadlineExceeded) as ei:
        a.barrier("e1", deadline_s=1.0)
    assert ei.value.missing == (1,)
    assert "e1" in str(ei.value)


def test_barrier_fails_fast_on_rank_loss(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=2.0)
    a.heartbeat(), b.heartbeat()
    a.poll()
    clock.sleep(2.5)  # b's lease will expire during the wait
    with pytest.raises(ms.RankLostError) as ei:
        a.barrier("e2", deadline_s=50.0)
    assert ei.value.lost_ranks == (1,)
    rec = ei.value.record()
    assert rec["exit_code"] == ms.RANK_LOST_EXIT_CODE == 19
    json.dumps(rec)


# ---------------------------------------------------------------------------
# join announcements: the grow-to-fit rendezvous (train/grow.py's feed)
# ---------------------------------------------------------------------------


def make_joiner(tmp_path, clock, token="node-x1", **kw):
    return ms.Joiner(str(tmp_path), token, generation=0, lease_s=2.0,
                     clock=clock, sleep=clock.sleep, **kw)


def test_join_judged_from_first_observed_seq(tmp_path):
    # THE joiner-ageing pin: the observer's clock is 1000 s past its own
    # start when it FIRST sees the announcement — freshness must be
    # judged from first observation of the seq, never from any embedded
    # wall time, or every join announced before the observer's poll
    # would be born expired
    clock, (a,) = make_world(tmp_path, 1, lease_s=2.0)
    a.heartbeat()
    a.poll()
    j = make_joiner(tmp_path, clock)
    j.announce()
    clock.sleep(1000.0)
    evs = a.poll()
    joins = [e for e in evs if e.kind == "join_request"]
    assert [e.token for e in joins] == ["node-x1"]
    assert joins[0].generation == 0
    assert a.pending_joins() == ("node-x1",)
    # emitted ONCE per token, and a quiet follow-up poll inside the
    # lease keeps it pending
    clock.sleep(1.0)
    assert [e for e in a.poll() if e.kind == "join_request"] == []
    assert a.pending_joins() == ("node-x1",)


def test_join_expiry_is_quiet_withdrawal(tmp_path):
    # a joiner that stops announcing ages out of the pending set with NO
    # event: withdrawal is free, never a rank_lost
    clock, (a,) = make_world(tmp_path, 1, lease_s=2.0)
    a.heartbeat()
    a.poll()
    j = make_joiner(tmp_path, clock)
    j.announce()
    a.poll()
    assert a.pending_joins() == ("node-x1",)
    clock.sleep(2.5)
    evs = a.poll()
    assert evs == []
    assert a.pending_joins() == ()
    # ...and a RE-announcement (new seq) is a fresh request again
    j.announce()
    evs = a.poll()
    assert [e.token for e in evs if e.kind == "join_request"] == ["node-x1"]
    assert a.pending_joins() == ("node-x1",)


def test_join_refresh_keeps_lease_alive(tmp_path):
    # an announcing joiner (seq advancing) never ages out mid-wait
    clock, (a,) = make_world(tmp_path, 1, lease_s=2.0)
    a.heartbeat()
    a.poll()
    j = make_joiner(tmp_path, clock)
    for _ in range(3):
        j.announce()
        clock.sleep(1.5)  # inside the lease per refresh, 4.5 s total
        a.poll()
        assert a.pending_joins() == ("node-x1",)


def test_joiner_join_rendezvous_returns_grant(tmp_path):
    clock, (a,) = make_world(tmp_path, 1, lease_s=2.0)
    j = make_joiner(tmp_path, clock)
    assert j.grant() is None
    # the supervisor's answer names the NEXT generation's grown world
    ms.grant_join(str(tmp_path), "node-x1", rank=1, generation=1,
                  world_size=2)
    got = j.join(deadline_s=10.0)
    assert (got["rank"], got["generation"], got["world_size"]) == (1, 1, 2)
    # an ungranted token times out naming the join
    j2 = make_joiner(tmp_path, clock, token="node-x2")
    with pytest.raises(ms.DeadlineExceeded) as ei:
        j2.join(deadline_s=3.0)
    assert "node-x2" in str(ei.value)


def test_join_announce_fires_chaos_point(tmp_path):
    clock, _ = make_world(tmp_path, 1)
    j = make_joiner(tmp_path, clock)
    chaos.arm("comm.join=raise@1")  # seq counter starts at 1
    with pytest.raises(chaos.ChaosFault):
        j.announce()
    chaos.disarm()
    # join() retries through the fault like rendezvous does
    chaos.arm("comm.join=raise@0:count=2")
    ms.grant_join(str(tmp_path), "node-x1", rank=2, generation=1,
                  world_size=3)
    assert j.join(deadline_s=30.0)["rank"] == 2


def test_read_roster_renders_cross_generation_joins(tmp_path):
    # the roster must make a grow rendezvous legible after the fact:
    # join entries keyed "join:<token>", granted flag + the rank/
    # generation the supervisor answered with (generation g+1 — the
    # grant crosses generations by design)
    clock, (a, b) = make_world(tmp_path, 2, lease_s=2.0)
    a.heartbeat(), b.heartbeat()
    make_joiner(tmp_path, clock, token="node-g").announce()
    make_joiner(tmp_path, clock, token="node-u").announce()
    ms.grant_join(str(tmp_path), "node-g", rank=2, generation=1,
                  world_size=3)
    roster = ms.read_roster(str(tmp_path))
    assert sorted(k for k in roster if isinstance(k, int)) == [0, 1]
    granted = roster["join:node-g"]
    assert granted["granted"] is True
    assert granted["granted_rank"] == 2
    assert granted["granted_generation"] == 1
    ungranted = roster["join:node-u"]
    assert ungranted["granted"] is False
    assert "granted_rank" not in ungranted


def test_rank_join_error_record_and_exit_code():
    e = ms.RankJoinError(("node-b", "node-a"),
                         (ms.JoinRequest(token="node-a", generation=0),))
    assert e.tokens == ("node-a", "node-b")  # sorted, deterministic
    rec = e.record()
    assert rec["exit_code"] == ms.RANK_JOIN_EXIT_CODE == 23
    assert rec["kind"] == "rank_join_exit"
    json.dumps(rec)


# ---------------------------------------------------------------------------
# rank identity from the supervisor's env export
# ---------------------------------------------------------------------------


def test_rank_from_env(monkeypatch):
    from dgraph_tpu.utils.env import RANK_ENV_VAR

    monkeypatch.setenv(RANK_ENV_VAR, "3")
    assert ms.rank_from_env() == 3
    assert ms.rank_from_env(default=0) == 3  # env wins over the default
    monkeypatch.delenv(RANK_ENV_VAR)
    assert ms.rank_from_env(default=2) == 2
    with pytest.raises(RuntimeError):  # silent rank-0 would fight rank 0
        ms.rank_from_env()


# ---------------------------------------------------------------------------
# CLI selftest (tier-1 registration)
# ---------------------------------------------------------------------------


def test_membership_selftest_cli():
    r = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.comm.membership",
         "--selftest", "true"],
        capture_output=True, text=True, timeout=180, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "membership_selftest" and rec["failures"] == []
    assert rec["run_health"]["wedge"] == "none"
