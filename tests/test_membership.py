"""Elastic world membership (dgraph_tpu/comm/membership.py): lease/
heartbeat liveness, straggler vs loss classification, deadline barriers,
retrying rendezvous with capped backoff, event plumbing through
spans/health, and the chaos points. Everything here is pure host code
driven by a FAKE clock — zero XLA compiles, zero real sleeps beyond the
sub-second chaos delay check."""

import json
import os
import subprocess
import sys

import pytest

from dgraph_tpu import chaos
from dgraph_tpu.comm import membership as ms

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _chaos_clean():
    yield
    chaos.reset()


# the ONE fake monotonic clock (sleep advances it) — membership ships it
# for its own selftest; reusing it keeps the semantics from forking
FakeClock = ms._FakeClock


def make_world(tmp_path, world_size, lease_s=2.0, **kw):
    clock = FakeClock()
    members = [
        ms.Membership(
            str(tmp_path), rank=r, world_size=world_size, lease_s=lease_s,
            clock=clock, sleep=clock.sleep, **kw,
        )
        for r in range(world_size)
    ]
    return clock, members


# ---------------------------------------------------------------------------
# liveness: heartbeat / straggler / loss / leave
# ---------------------------------------------------------------------------


def test_all_alive_after_heartbeats(tmp_path):
    clock, (a, b, c) = make_world(tmp_path, 3)
    for m in (a, b, c):
        m.heartbeat()
    evs = a.poll()
    assert a.alive() == (0, 1, 2)
    assert any(e.kind == "membership_changed" for e in evs)
    # a second quiet poll is event-free
    assert a.poll() == []


def test_straggler_then_loss_classification(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=2.0)
    a.heartbeat(), b.heartbeat()
    a.poll()
    # silent past straggler_after_s (lease/2 = 1.0) but inside the lease:
    # reported once, not evicted
    clock.sleep(1.2)
    a.heartbeat()
    evs = a.poll()
    stragglers = [e for e in evs if e.kind == "straggler"]
    assert [e.rank for e in stragglers] == [1]
    assert a.alive() == (0, 1)
    assert [e for e in a.poll() if e.kind == "straggler"] == []  # one/episode
    # a resumed heartbeat closes the episode and re-arms the detector
    b.heartbeat()
    assert a.poll() == []
    clock.sleep(1.2)
    assert [e.rank for e in a.poll() if e.kind == "straggler"] == [1]
    # ...and full silence past the lease is a loss
    clock.sleep(1.0)
    evs = a.poll()
    losses = [e for e in evs if e.kind == "rank_lost"]
    assert len(losses) == 1 and losses[0].rank == 1
    assert losses[0].silent_for_s > 2.0
    assert a.alive() == (0,) and a.lost() == (1,)
    changed = [e for e in evs if e.kind == "membership_changed"]
    assert changed[-1].lost == (1,) and changed[-1].world_size == 2
    # terminal: never re-reported
    assert a.poll() == []
    for rec in a.events:
        json.dumps(rec)


def test_graceful_leave_is_not_a_loss(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2)
    a.heartbeat(), b.heartbeat()
    a.poll()
    b.leave()
    evs = a.poll()
    assert a.alive() == (0,) and a.lost() == ()
    assert any(e.kind == "membership_changed" and 1 in e.left for e in evs)
    assert not any(e.kind == "rank_lost" for e in evs)


def test_never_seen_rank_is_pending_not_lost(tmp_path):
    clock, members = make_world(tmp_path, 3)
    a = members[0]
    a.heartbeat()
    clock.sleep(100.0)
    assert a.poll() == []  # join deadlines belong to rendezvous
    assert a.alive() == (0,) and a.lost() == ()


def test_events_flow_into_health(tmp_path):
    from dgraph_tpu.obs.health import RunHealth

    clock = FakeClock()
    h = RunHealth.begin("t")
    a = ms.Membership(str(tmp_path), rank=0, world_size=2, lease_s=1.0,
                      clock=clock, sleep=clock.sleep, health=h)
    b = ms.Membership(str(tmp_path), rank=1, world_size=2, lease_s=1.0,
                      clock=clock, sleep=clock.sleep)
    b.heartbeat()
    a.poll()
    clock.sleep(1.5)
    a.poll()
    kinds = [e["kind"] for e in h.events]
    assert "rank_lost" in kinds and "membership_changed" in kinds
    json.dumps(h.finish())


def test_background_heartbeats_survive_slow_steps(tmp_path):
    # REAL clock on purpose: the thread is what keeps a live-but-slow
    # member (one step stretched far past the lease by a long orbax
    # write or a loaded machine) from reading as dead to its peers —
    # liveness tracks the process, not the step cadence
    import time as _time

    a = ms.Membership(str(tmp_path), rank=0, world_size=2, lease_s=0.4)
    b = ms.Membership(str(tmp_path), rank=1, world_size=2, lease_s=0.4)
    a.heartbeat(), b.heartbeat()
    a.poll()
    b.start_heartbeats(interval_s=0.05)
    b.start_heartbeats()  # idempotent
    try:
        deadline = _time.monotonic() + 1.2  # 3x the lease, b never "steps"
        while _time.monotonic() < deadline:
            a.heartbeat()
            assert not [e for e in a.poll() if e.kind == "rank_lost"]
            _time.sleep(0.05)
        assert a.alive() == (0, 1)
    finally:
        b.stop_heartbeats()
    # once the thread stops (process death), the lease expires as usual
    t0 = _time.monotonic()
    lost = []
    while _time.monotonic() - t0 < 10.0 and not lost:
        a.heartbeat()
        lost = [e for e in a.poll() if e.kind == "rank_lost"]
        _time.sleep(0.05)
    assert [e.rank for e in lost] == [1]


# ---------------------------------------------------------------------------
# rendezvous + barrier
# ---------------------------------------------------------------------------


def test_rendezvous_joins_and_times_out(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2)
    b.heartbeat()
    assert a.rendezvous(deadline_s=10.0) == (0, 1)
    # a world that never fills names the missing ranks
    solo = ms.Membership(str(tmp_path / "solo"), rank=0, world_size=3,
                         lease_s=2.0, clock=clock, sleep=clock.sleep)
    with pytest.raises(ms.DeadlineExceeded) as ei:
        solo.rendezvous(deadline_s=3.0)
    assert ei.value.missing == (1, 2)
    json.dumps(ei.value.record())


def test_rendezvous_backoff_is_capped_with_jitter(tmp_path):
    clock = FakeClock()
    # world of 2 that never fills: observe the sleep schedule
    slept = []
    m = ms.Membership(str(tmp_path), rank=0, world_size=2, lease_s=2.0,
                      clock=clock, sleep=lambda s: (slept.append(s),
                                                    clock.sleep(s))[-1])
    with pytest.raises(ms.DeadlineExceeded):
        m.rendezvous(deadline_s=20.0, backoff_s=0.1, backoff_factor=2.0,
                     backoff_max_s=1.0)
    # exponential up to the cap, plus jitter in [0, backoff_s)
    bases = [min(0.1 * 2.0 ** k, 1.0) for k in range(len(slept))]
    for got, base in zip(slept, bases):
        assert base <= got < base + 0.1, (got, base)


def test_rendezvous_retries_through_chaos_fault(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2)
    b.heartbeat()
    chaos.arm("comm.rendezvous=raise@0:count=2")
    assert a.rendezvous(deadline_s=30.0) == (0, 1)
    assert chaos.call_count("comm.rendezvous") >= 3


def test_heartbeat_fires_chaos_point(tmp_path):
    clock, (a,) = make_world(tmp_path, 1)
    chaos.arm("comm.heartbeat=raise@1")  # seq counter starts at 1
    with pytest.raises(chaos.ChaosFault):
        a.heartbeat()


def test_chaos_delay_on_heartbeat_reads_as_straggler(tmp_path):
    # the injected straggler: a delay clause holds the heartbeat WRITE,
    # so the peer observes exactly a late member — reported, not evicted
    clock, (a, b) = make_world(tmp_path, 2, lease_s=2.0)
    a.heartbeat(), b.heartbeat()
    a.poll()

    def delayed_heartbeat():
        chaos.arm("comm.heartbeat=delay@0:count=99:sleep_s=0.01:seed=5")
        try:
            b.heartbeat()
        finally:
            chaos.disarm()

    clock.sleep(1.5)  # b silent past straggler_after, inside lease
    evs = a.poll()
    assert [e.rank for e in evs if e.kind == "straggler"] == [1]
    delayed_heartbeat()  # b eventually lands its (delayed) write
    evs = a.poll()
    assert a.alive() == (0, 1) and a.lost() == ()


def test_barrier_completes_and_reports_stragglers(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=60.0)
    a.heartbeat(), b.heartbeat()
    a.poll(), b.poll()
    a.arrive("e0")
    res = b.barrier("e0", deadline_s=10.0)
    assert res["arrived"] == [0, 1] and res["stragglers"] == []
    res = a.barrier("e0", deadline_s=10.0)
    assert res["arrived"] == [0, 1]


def test_barrier_deadline_names_missing_rank(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=60.0)
    a.heartbeat(), b.heartbeat()
    a.poll()
    with pytest.raises(ms.DeadlineExceeded) as ei:
        a.barrier("e1", deadline_s=1.0)
    assert ei.value.missing == (1,)
    assert "e1" in str(ei.value)


def test_barrier_fails_fast_on_rank_loss(tmp_path):
    clock, (a, b) = make_world(tmp_path, 2, lease_s=2.0)
    a.heartbeat(), b.heartbeat()
    a.poll()
    clock.sleep(2.5)  # b's lease will expire during the wait
    with pytest.raises(ms.RankLostError) as ei:
        a.barrier("e2", deadline_s=50.0)
    assert ei.value.lost_ranks == (1,)
    rec = ei.value.record()
    assert rec["exit_code"] == ms.RANK_LOST_EXIT_CODE == 19
    json.dumps(rec)


# ---------------------------------------------------------------------------
# rank identity from the supervisor's env export
# ---------------------------------------------------------------------------


def test_rank_from_env(monkeypatch):
    from dgraph_tpu.utils.env import RANK_ENV_VAR

    monkeypatch.setenv(RANK_ENV_VAR, "3")
    assert ms.rank_from_env() == 3
    assert ms.rank_from_env(default=0) == 3  # env wins over the default
    monkeypatch.delenv(RANK_ENV_VAR)
    assert ms.rank_from_env(default=2) == 2
    with pytest.raises(RuntimeError):  # silent rank-0 would fight rank 0
        ms.rank_from_env()


# ---------------------------------------------------------------------------
# CLI selftest (tier-1 registration)
# ---------------------------------------------------------------------------


def test_membership_selftest_cli():
    r = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.comm.membership",
         "--selftest", "true"],
        capture_output=True, text=True, timeout=180, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "membership_selftest" and rec["failures"] == []
    assert rec["run_health"]["wedge"] == "none"
