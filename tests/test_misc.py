"""Smaller parity pieces: bf16 compute path, MessagePassing wrapper,
Communicator facade surface, TimingReport, LR schedule, utils."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp


def test_gcn_bfloat16_compute(rng):
    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models import GCN

    data = synthetic.sbm_classification_graph(num_nodes=100, seed=3)
    g = DistributedGraph.from_global(
        data["edge_index"], data["features"], data["labels"], data["masks"], 1
    )
    comm = Communicator.init_process_group("single")
    model = GCN(16, 4, comm=comm, dtype=jnp.bfloat16)
    plan = jax.tree.map(lambda l: jnp.asarray(l[0]), g.plan)
    x = jnp.asarray(g.features[0])
    params = model.init(jax.random.key(0), x, plan)
    out = model.apply(params, x, plan)
    assert out.dtype == jnp.float32  # head casts back
    assert np.isfinite(np.asarray(out)).all()
    # params stay float32
    assert all(p.dtype == jnp.float32 for p in jax.tree.leaves(params))


def test_message_passing_wrapper(rng):
    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models.message_passing import MessagePassing
    from dgraph_tpu.ops import local as local_ops

    data = synthetic.sbm_classification_graph(num_nodes=80, seed=4)
    g = DistributedGraph.from_global(
        data["edge_index"], data["features"], data["labels"], data["masks"], 1
    )
    comm = Communicator.init_process_group("single")

    def layer(full, plan):
        msgs = full[plan.src_index] * plan.edge_mask[:, None]
        return local_ops.segment_sum(msgs, plan.dst_index, plan.n_dst_pad)

    mp = MessagePassing(layer=layer, comm=comm)
    plan = jax.tree.map(lambda l: jnp.asarray(l[0]), g.plan)
    x = jnp.asarray(g.features[0])
    params = mp.init(jax.random.key(0), x, plan)
    out = mp.apply(params, x, plan)
    # oracle: dense scatter of src features to dst
    from dgraph_tpu.testing import dense_scatter_sum
    from dgraph_tpu.plan import unshard_vertex_data

    got = unshard_vertex_data(np.asarray(out)[None], g.ren.counts)
    x_global = unshard_vertex_data(g.features, g.ren.counts)
    expected = dense_scatter_sum(x_global[g.edge_index[0]], g.edge_index, "dst", g.num_nodes)
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)


def test_communicator_facade_surface():
    from dgraph_tpu.comm import Communicator, SingleComm, TpuComm

    c = Communicator.init_process_group("single")
    assert isinstance(c, SingleComm)
    assert c.get_world_size() == 1 and c.get_rank() == 0
    c.barrier()
    c.destroy()
    assert c.alloc_buffer((3, 4)).shape == (3, 4)

    t = Communicator.init_process_group("tpu", world_size=8)
    assert isinstance(t, TpuComm) and t.get_world_size() == 8
    with pytest.raises(ValueError, match="not supported"):
        Communicator.init_process_group("nccl")
    with pytest.raises(ValueError):
        Communicator.init_process_group("tpu")  # missing world_size


def test_timing_report():
    from dgraph_tpu.utils import TimingReport

    TimingReport.reset()
    TimingReport.start("phase")
    x = jnp.ones((100, 100)) @ jnp.ones((100, 100))
    TimingReport.stop("phase", sync=x)
    TimingReport.add_time("manual", 5.0)
    rep = TimingReport.report()
    assert rep["phase"]["count"] == 1 and rep["phase"]["mean_ms"] > 0
    assert rep["manual"]["mean_ms"] == 5.0
    TimingReport.reset()


def test_three_phase_schedule():
    from dgraph_tpu.train.schedules import graphcast_three_phase

    s = graphcast_three_phase(peak_lr=1e-3, warmup_steps=10, decay_steps=100, floor_lr=1e-6)
    assert float(s(0)) == 0.0
    assert float(s(10)) == pytest.approx(1e-3, rel=1e-5)
    assert float(s(60)) < 1e-3
    assert float(s(500)) == pytest.approx(1e-6, rel=1e-3)


def test_split_helpers():
    from dgraph_tpu.utils import largest_split, split_per_rank

    assert largest_split(10, 4) == 3
    assert [split_per_rank(10, r, 4) for r in range(4)] == [3, 3, 3, 1]


def test_parallel_namespace():
    from dgraph_tpu import parallel

    assert callable(parallel.halo_exchange)
    assert parallel.GRAPH_AXIS == "graph"


def test_fused_scatter_variants(rng):
    """Fused ReLU / sum+ReLU / sparse scatter vs dense-loop golden
    (Fused_ReLU_Scatter_Kernel, Fused_Sum_Norm_Scatter_Kernel,
    Sparse_Scatter_Kernel semantics)."""
    import numpy as np
    import jax.numpy as jnp
    from dgraph_tpu.ops import local as L

    E, N, F = 200, 40, 8
    ids = rng.integers(0, N, E).astype(np.int32)
    v1 = rng.normal(size=(E, F)).astype(np.float32)
    v2 = rng.normal(size=(E, F)).astype(np.float32)

    exp = np.zeros((N, F), np.float32)
    np.add.at(exp, ids, np.maximum(v1, 0))
    np.testing.assert_allclose(
        np.asarray(L.scatter_add_relu(jnp.asarray(v1), jnp.asarray(ids), N)),
        exp, rtol=1e-5, atol=1e-5)

    exp2 = np.zeros((N, F), np.float32)
    np.add.at(exp2, ids, np.maximum(v1 + v2, 0))
    np.testing.assert_allclose(
        np.asarray(L.scatter_add_sum_relu(jnp.asarray(v1), jnp.asarray(v2), jnp.asarray(ids), N)),
        exp2, rtol=1e-5, atol=1e-5)

    # sparse: -1 rows dropped, accumulates into existing dst
    sidx = ids.astype(np.int64).copy()
    sidx[: E // 4] = -1
    dst = rng.normal(size=(N, F)).astype(np.float32)
    exp3 = dst.copy()
    np.add.at(exp3, sidx[E // 4:], v1[E // 4:])
    got3 = L.sparse_scatter_add(jnp.asarray(dst), jnp.asarray(sidx), jnp.asarray(v1))
    np.testing.assert_allclose(np.asarray(got3), exp3, rtol=1e-5, atol=1e-5)


def test_row_take_column_split(rng):
    """row_take == x[idx] for widths straddling the 128-lane tile boundary,
    and its VJP matches the plain gather's (the column-split is a pure
    re-association)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from dgraph_tpu.ops import local as L

    N, E = 50, 173
    idx = rng.integers(0, N, E).astype(np.int32)
    for F in (8, 128, 200, 256, 384):
        x = rng.normal(size=(N, F)).astype(np.float32)
        got = L.row_take(jnp.asarray(x), jnp.asarray(idx), col_block=128)
        np.testing.assert_array_equal(np.asarray(got), x[idx])

    x = rng.normal(size=(N, 256)).astype(np.float32)
    g_out = rng.normal(size=(E, 256)).astype(np.float32)

    def loss_split(a):
        return (L.row_take(a, jnp.asarray(idx), col_block=128) * g_out).sum()

    def loss_plain(a):
        return (a[jnp.asarray(idx)] * g_out).sum()

    gs = jax.grad(loss_split)(jnp.asarray(x))
    gp = jax.grad(loss_plain)(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(gs), np.asarray(gp), rtol=1e-5, atol=1e-5)


def test_ema_update():
    """EMA converges toward the tracked params at rate (1-decay)."""
    import jax.numpy as jnp

    from dgraph_tpu.train.ema import ema_init, ema_update

    p0 = {"w": jnp.zeros(4), "b": jnp.zeros(2)}
    tgt = {"w": jnp.ones(4), "b": jnp.ones(2)}
    ema = ema_init(p0)
    for _ in range(10):
        ema = ema_update(ema, tgt, decay=0.9)
    expect = 1.0 - 0.9 ** 10
    np.testing.assert_allclose(np.asarray(ema["w"]), expect, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(ema["b"]), expect, rtol=1e-6)


def test_timed_scan_actually_measures_the_op():
    """Regression pin for the r3 measurement-integrity fix: the scan
    protocol must consume the WHOLE op output with a live carry
    dependency. Before the fix, XLA sliced through the single-element
    fetch (a row gather collapsed to one row) and constant-folded the
    `salt * 0` chain, so a 50000x-bigger op measured the same ~0 ms.
    A 3x time-ratio floor is far below the real ~1000x+ but far above
    the broken-case ratio (~1x)."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from dgraph_tpu.utils.timing import salt_input, timed_scan_ms

    rng = np.random.default_rng(0)
    big = jnp.asarray(rng.standard_normal((400_000, 128)), jnp.float32)
    idx_big = jnp.asarray(rng.integers(0, 400_000, 400_000), jnp.int32)
    idx_one = idx_big[:8]

    t_big = timed_scan_ms(
        lambda s: salt_input(big, s)[idx_big], reps=3, n_long=6)
    t_one = timed_scan_ms(
        lambda s: salt_input(big, s)[idx_one], reps=3, n_long=6)
    assert t_big is not None
    # t_one can be None (too fast for a positive delta) — that's fine;
    # the broken case made t_big equally immeasurable
    floor = 3 * (t_one or 0.05)
    assert t_big > floor, (t_big, t_one)
