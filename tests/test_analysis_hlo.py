"""Lowered-artifact auditor + Pallas DMA-discipline verifier (ISSUE 12).

Everything here is **lower-only**: programs reach StableHLO through
``jit(...).lower()`` and kernels through ``jax.make_jaxpr`` — zero new
XLA compiles (asserted explicitly via the jit-cache counter below; the
budget rule tests/README.md documents). The clean-tree GREEN pins run
the full 2- and 4-shard audits across all four halo lowerings; the
vacuity guards prove each new tier still goes RED on seeded drift — a
seeded extra all-gather, a dropped ``dma_wait``, and a dropped donation,
plus the raw-``shard_map`` lint shape.
"""

import warnings

import pytest

from dgraph_tpu.analysis import hlo as H
from dgraph_tpu.analysis import kernel as K
from dgraph_tpu.analysis import lint as L


@pytest.fixture(scope="module")
def workload2():
    from dgraph_tpu.analysis.trace import build_audit_workload

    return build_audit_workload(2)


@pytest.fixture(scope="module")
def workload4():
    from dgraph_tpu.analysis.trace import build_audit_workload

    return build_audit_workload(4)


# ---------------------------------------------------------------------------
# clean-tree GREEN pins (2- and 4-shard, all four lowerings)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("world", [2, 4])
def test_hlo_audit_clean_green(world, workload2, workload4):
    """The lowered schedule of every (program, lowering) pair matches the
    plan: op kinds/counts, replica_groups/rings, byte-exact footprint
    pricing, one transport family, donation survival."""
    w = workload2 if world == 2 else workload4
    rep = H.audit_workload_hlo(w)
    assert rep["ok"], rep["failures"]
    assert set(rep["exchange_legs"]) == {
        "train_step", "eval_step", "serve_forward"
    }
    # byte-exact footprint cross-check at the HLO level, every operand
    rows = 0
    for p in rep["programs"]:
        for op in p["collective_operands"]:
            assert op["bytes"] == op["footprint_bytes"] > 0, (p, op)
            rows += 1
    assert rows > 0
    # donation survived lowering for the donating train step
    don = rep["donation"]
    assert don["donor_args"] + don["alias_args"] == don["expected_donors"]
    assert don["uncovered"] == []


def test_hlo_count_pins_mirror_trace_tier(workload2):
    """Cross-lowering count discipline at the artifact level: permutes ==
    legs * deltas; the p2p interpret discharge lands exactly one
    tile-payload gather (plus two scalar index gathers) per remote put."""
    rep = H.audit_workload_hlo(workload2)
    assert rep["ok"], rep["failures"]
    n_deltas = rep["num_halo_deltas"]
    by = {(p["program"], p["impl"]): p for p in rep["programs"]}
    for prog, legs in rep["exchange_legs"].items():
        assert by[(prog, "all_to_all")]["num_all_to_all"] == legs
        for impl in ("ppermute", "overlap"):
            assert by[(prog, impl)]["num_collective_permute"] == (
                legs * n_deltas
            )
        p2p = by[(prog, "pallas_p2p")]
        assert p2p["num_tile_gathers"] == legs * n_deltas
        assert p2p["num_index_gathers"] == 2 * legs * n_deltas


def test_hlo_audit_is_lower_only(workload2):
    """Zero new XLA compiles: every program's jit cache must be EMPTY
    after a full audit — the counter the serve stack already trusts."""
    from dgraph_tpu.analysis.trace import PROGRAMS
    from dgraph_tpu import config as cfg

    rep = H.audit_workload_hlo(workload2)
    for p in rep["programs"]:
        assert p["jit_cache_entries"] == 0, p
    # and directly, on a freshly built program: lower() must not compile
    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    try:
        cfg.set_flags(halo_impl="all_to_all", tuned_halo_impl=None)
        fn, args = PROGRAMS["train_step"](workload2)
        H.lower_program(fn, args)
        assert fn._cache_size() == 0
    finally:
        cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


def test_kernel_audit_clean_green(workload2, workload4):
    """The real pallas_p2p transports (train/eval/serve, fwd+bwd legs)
    pass the DMA-discipline verifier at both shard counts — including
    W=4's three live deltas, which exercise the slot-reuse wait."""
    for w in (workload2, workload4):
        rep = K.audit_workload_kernels(w)
        assert rep["ok"], rep["failures"]
        assert len(rep["kernels"]) >= 4
    # W=4 traced at least one fused kernel with slot reuse in play
    fused = [k for k in rep["kernels"] if k["fused_mask"]]
    assert fused and any(k["n_deltas"] >= 3 for k in fused)


# ---------------------------------------------------------------------------
# vacuity guards: seeded drift must go RED
# ---------------------------------------------------------------------------


def test_seeded_extra_all_gather_goes_red(workload2):
    """An XLA-materialized all_gather the plan never scheduled — the
    class the relaxed replication checker can no longer catch — must
    fail the HLO audit."""
    import jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu import config as cfg
    from dgraph_tpu.analysis.trace import _train_program
    from dgraph_tpu.comm.collectives import shard_map_checks
    from dgraph_tpu.comm.mesh import GRAPH_AXIS

    w = workload2
    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    try:
        cfg.set_flags(halo_impl="all_to_all", tuned_halo_impl=None)
        fn, args = _train_program(w)

        def seeded(params, opt_state, batch, plan):
            out = fn(params, opt_state, batch, plan)
            extra = jax.shard_map(
                lambda x: lax.all_gather(x[0], GRAPH_AXIS),
                mesh=w.mesh, in_specs=(P(GRAPH_AXIS),), out_specs=P(),
                **shard_map_checks(relax="seeded test mutant"),
            )(batch["x"])
            return out, extra

        failures = []
        H._audit_one_lowering(
            "seeded", "all_to_all",
            H.lower_program(jax.jit(seeded, donate_argnums=(0, 1)), args),
            w.plan_np, w.mesh, failures,
        )
        assert any("unscheduled all_gather" in f for f in failures), failures
    finally:
        cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


def test_dropped_donation_goes_red(workload2):
    """Both donation-drop shapes fail at the artifact level: donate=False
    (no donor entries survive lowering) and a metrics-only output (donors
    survive but no output type can cover them)."""
    import jax

    from dgraph_tpu import config as cfg
    from dgraph_tpu.analysis.trace import _train_program
    from dgraph_tpu.train.loop import make_train_step

    w = workload2
    donated = len(jax.tree.leaves((w.params, w.opt_state)))
    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    try:
        cfg.set_flags(halo_impl="all_to_all", tuned_halo_impl=None)
        fn, args = _train_program(w)
        nd = make_train_step(w.model, w.optimizer, w.mesh, w.plan,
                             donate=False)
        failures = []
        H._donation_failures(H.donation_entries(H.lower_program(nd, args)),
                             donated, "no-donate", failures)
        assert failures
        mo = jax.jit(lambda p, o, b, pl: fn(p, o, b, pl)[2],
                     donate_argnums=(0, 1))
        failures = []
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            H._donation_failures(
                H.donation_entries(H.lower_program(mo, args)), donated,
                "metrics-only", failures)
        assert failures
    finally:
        cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


def test_dropped_dma_wait_goes_red():
    """Every seeded kernel-discipline mutation (dropped send wait,
    dropped recv wait, slot reuse without wait, wrong dst-row slot,
    oversized staging) is flagged; the clean kernel is not."""
    assert K.kernel_selftest_failures() == []


def test_kernel_verifier_flags_each_mutation_specifically():
    mism = []
    jaxpr = K._mutant_jaxpr(4, 8, 16, (1, 2, 3), "drop_send_wait")
    K.verify_transport(*K.collect_transports(jaxpr)[0], "m", mism)
    assert any("send semaphore" in m for m in mism), mism
    mism = []
    jaxpr = K._mutant_jaxpr(4, 8, 16, (1, 2, 3), "bad_dst_row")
    K.verify_transport(*K.collect_transports(jaxpr)[0], "m", mism)
    assert any("me*S" in m for m in mism), mism


def test_hlo_rejects_wrong_lowering_family(workload2):
    """Pin ppermute, audit the artifact as all_to_all -> RED."""
    from dgraph_tpu import config as cfg
    from dgraph_tpu.analysis.trace import _train_program

    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    try:
        cfg.set_flags(halo_impl="ppermute", tuned_halo_impl=None)
        fn, args = _train_program(workload2)
        failures = []
        H._audit_one_lowering(
            "t", "all_to_all", H.lower_program(fn, args),
            workload2.plan_np, workload2.mesh, failures,
        )
        assert failures
    finally:
        cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


# ---------------------------------------------------------------------------
# the no-unchecked-shard-map rule + pallas-kernel lint descent
# ---------------------------------------------------------------------------


def _run_rule(name, path, src):
    import ast

    tree = ast.parse(src)
    lines = src.splitlines()
    got = L.RULES[name].check(path, tree, lines)
    return [f for f in got if not L._suppressed(lines, f.line, f.rule)]


def test_raw_shard_map_site_flagged():
    """The two raw shapes this PR fixed (check_vma= kwarg and the blanket
    **RELAXED_CHECKS splat) fire; the routed spelling does not."""
    path = "dgraph_tpu/train/loop.py"
    bad_kwarg = (
        "import jax\n"
        "def build(body, mesh, specs):\n"
        "    return jax.shard_map(body, mesh=mesh, in_specs=specs,\n"
        "                         out_specs=specs, check_vma=False)\n"
    )
    bad_splat = (
        "import jax\n"
        "from dgraph_tpu import compat as _compat\n"
        "def build(body, mesh, specs):\n"
        "    return jax.shard_map(body, mesh=mesh, in_specs=specs,\n"
        "                         out_specs=specs, **_compat.RELAXED_CHECKS)\n"
    )
    good = (
        "import jax\n"
        "from dgraph_tpu.comm.collectives import shard_map_checks\n"
        "def build(body, mesh, specs, plan):\n"
        "    return jax.shard_map(body, mesh=mesh, in_specs=specs,\n"
        "                         out_specs=specs,\n"
        "                         **shard_map_checks(plan, 'graph'))\n"
    )
    assert _run_rule("no-unchecked-shard-map", path, bad_kwarg)
    assert _run_rule("no-unchecked-shard-map", path, bad_splat)
    assert not _run_rule("no-unchecked-shard-map", path, good)


def test_lint_descends_into_pallas_kernels():
    """A config read (or span) inside a kernel handed to pallas_call via
    a functools.partial alias fires — the pre-ISSUE-12 blind spot."""
    path = "dgraph_tpu/ops/pallas_p2p.py"
    bad = (
        "import functools\n"
        "from jax.experimental import pallas as pl\n"
        "from dgraph_tpu import config as _cfg\n"
        "def _kernel(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] * (2 if _cfg.use_pallas_p2p else 1)\n"
        "def transport(x, shape):\n"
        "    kern = functools.partial(_kernel)\n"
        "    return pl.pallas_call(kern, out_shape=shape)(x)\n"
    )
    good = bad.replace(
        "def _kernel(x_ref, o_ref):\n"
        "    o_ref[...] = x_ref[...] * (2 if _cfg.use_pallas_p2p else 1)\n",
        "def _kernel(x_ref, o_ref, *, scale):\n"
        "    o_ref[...] = x_ref[...] * scale\n",
    ).replace(
        "    kern = functools.partial(_kernel)\n",
        "    scale = 2 if _cfg.use_pallas_p2p else 1\n"
        "    kern = functools.partial(_kernel, scale=scale)\n",
    )
    assert _run_rule("no-config-read-in-trace", path, bad)
    assert not _run_rule("no-config-read-in-trace", path, good)
    span_bad = (
        "from jax.experimental import pallas as pl\n"
        "from dgraph_tpu.obs import spans\n"
        "def _kernel(x_ref, o_ref):\n"
        "    with spans.span('p2p.tile', stage='exchange'):\n"
        "        o_ref[...] = x_ref[...]\n"
        "def transport(x, shape):\n"
        "    return pl.pallas_call(_kernel, out_shape=shape)(x)\n"
    )
    assert _run_rule("no-span-in-trace", path, span_bad)


def test_shipped_tree_has_no_unchecked_shard_maps():
    """The clean-tree pin for the new rule: the five raw sites ISSUE 12
    fixed (train/loop.py init, ops/pallas_p2p.py selftest, the blanket
    RELAXED_CHECKS in parallel/sequence.py, and the two analysis-internal
    ones) stay fixed."""
    report = L.run_lint()
    raw = [f for f in report["findings"]
           if f["rule"] == "no-unchecked-shard-map"]
    assert raw == []


# ---------------------------------------------------------------------------
# bench fallback record
# ---------------------------------------------------------------------------


def test_hlo_drift_record_shape():
    """The third wedged-round fallback tier: non-null lowered-vs-priced
    bytes per lowering plus the donation census."""
    rec = H.hlo_drift_record(2, num_nodes=64, num_edges=256, feat_dim=8)
    assert rec["kind"] == "hlo_drift"
    assert rec["drift"] is False
    for impl in ("all_to_all", "ppermute", "overlap", "pallas_p2p"):
        row = rec["train_step_by_impl"][impl]
        assert row["lowered_bytes"] == row["footprint_bytes"] > 0
    don = rec["donation"]
    assert don["donor_args"] + don["alias_args"] == don["expected_donors"]
