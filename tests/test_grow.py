"""Grow-to-fit elastic world expansion (train/grow.py +
partition.unfold_partition + plan.reshard_vertex_data growth-direction +
supervise_group on_rank_join): deterministic waterfill donations, the
fold/unfold round trip, vertex-identity-preserving checkpoint resharding
to a LARGER world, atomic generation adoption, the grow-then-shrink
generation chain — and THE rank-join acceptance pin: a joiner announcing
into a live 2-rank world is detected at a step boundary, the world grows
2 -> 3 through a background re-plan, and the resumed expanded run is
bit-identical (params + opt_state) to a fault-free 3-rank run restored
from the same post-grow checkpoint.

Compile-free throughout (same budget discipline as test_shrink.py): host
numpy state, the streaming plan builder, subprocess workers that never
jit.  The sigterm crash-window pins (commit boundary + mid-shard-stream)
live in the grow CLI selftest, registered in scripts/check.py.
"""

import json
import os
import shutil
import sys
import threading
import time

import numpy as np
import pytest

from dgraph_tpu.partition import (
    fold_partition,
    renumber_contiguous,
    unfold_partition,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# unfold_partition: the deterministic waterfill inverse
# ---------------------------------------------------------------------------


def test_unfold_partition_donates_tails_to_newcomers():
    part = np.repeat(np.arange(2), [8, 8])
    new, donors = unfold_partition(part, 2, 1)
    # waterfill level 6: each donor sheds its 2 highest-id vertices
    assert donors == {0: 2, 1: 2}
    counts = np.bincount(new, minlength=3)
    assert counts.tolist() == [6, 6, 4]
    # kept vertices never move — the keepers are each block's PREFIX
    assert new[:6].tolist() == [0] * 6
    assert new[8:14].tolist() == [1] * 6
    # donated vertices are the TAILS, handed to the newcomer
    assert new[[6, 7, 14, 15]].tolist() == [2, 2, 2, 2]


def test_unfold_partition_balances_2_to_4():
    part = np.repeat(np.arange(2), [8, 8])
    new, donors = unfold_partition(part, 2, 2)
    assert donors == {0: 4, 1: 4}
    assert np.bincount(new, minlength=4).tolist() == [4, 4, 4, 4]
    # newcomer chunks are contiguous in vertex order: rank 2 gets the
    # earlier donated vertices, rank 3 the later ones
    assert new[[4, 5, 6, 7]].tolist() == [2, 2, 2, 2]
    assert new[[12, 13, 14, 15]].tolist() == [3, 3, 3, 3]


def test_unfold_partition_uneven_blocks_stay_leveled():
    part = np.repeat(np.arange(3), [9, 3, 6])
    new, donors = unfold_partition(part, 3, 1)
    counts = np.bincount(new, minlength=4)
    assert int(counts.sum()) == 18
    # no existing rank above the waterfill level, newcomer at most level
    assert counts[:3].max() <= max(counts[3], counts[:3].max())
    assert counts.max() - counts.min() <= 3
    # only over-level ranks donate
    assert set(donors) <= {0, 2}


def test_unfold_partition_deterministic_and_pure():
    rng = np.random.default_rng(11)
    part = rng.integers(0, 4, 100)
    before = part.copy()
    a, da = unfold_partition(part, 4, 2)
    b, db = unfold_partition(part, 4, 2)
    np.testing.assert_array_equal(a, b)
    assert da == db
    np.testing.assert_array_equal(part, before)  # input untouched


def test_unfold_partition_rejects_bad_inputs():
    part = np.array([0, 1])
    with pytest.raises(ValueError):
        unfold_partition(part, 2, 0)
    with pytest.raises(ValueError):
        unfold_partition(np.array([0, 5]), 2, 1)  # names rank >= W


def test_unfold_fold_round_trip_identity():
    """fold(unfold(p)) == p when the original blocks are balanced:
    killing exactly the newcomers undoes the growth vertex for vertex,
    because fold's waterfill sends every donated vertex straight back to
    its donor."""
    for W, k, blocks in ((2, 1, [8, 8]), (2, 2, [8, 8]), (4, 2, [6] * 4)):
        part = np.repeat(np.arange(W), blocks)
        grown, _ = unfold_partition(part, W, k)
        restored, survivor_map = fold_partition(
            grown, W + k, list(range(W, W + k))
        )
        np.testing.assert_array_equal(restored, part)
        assert survivor_map == {r: r for r in range(W)}


def test_unfold_fold_round_trip_keepers_stay_put():
    """On UNBALANCED blocks fold may re-level the donated vertices, but
    the round trip still never moves a vertex unfold kept in place — the
    locality contract both directions share."""
    part = np.repeat(np.arange(3), [9, 3, 6])
    grown, _ = unfold_partition(part, 3, 2)
    restored, survivor_map = fold_partition(grown, 5, [3, 4])
    assert survivor_map == {0: 0, 1: 1, 2: 2}
    keepers = grown < 3  # vertices unfold left on their original rank
    np.testing.assert_array_equal(restored[keepers], part[keepers])
    # vertex conservation: same total, a valid 3-way partition
    assert int(np.bincount(restored, minlength=3).sum()) == part.size


# ---------------------------------------------------------------------------
# reshard_vertex_data growth direction: rows follow their vertex to W+k
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("k,n_pad_new", [(1, 4), (2, 4)])
def test_reshard_vertex_data_growth_parity(k, n_pad_new):
    """2 -> 2+k reshard vs the per-vertex oracle: unsharding the grown
    world and undoing the renumber must recover every original row."""
    from dgraph_tpu.plan import reshard_vertex_data, unshard_vertex_data

    rng = np.random.default_rng(3)
    old_counts = np.array([5, 4])
    V = int(old_counts.sum())
    g = rng.normal(size=(V, 3))
    x = np.zeros((2, 6, 3))  # n_pad_old=6 > max count
    off = 0
    for r, c in enumerate(old_counts):
        x[r, :c] = g[off: off + c]
        off += c
    part = np.repeat(np.arange(2), old_counts)
    grown, _ = unfold_partition(part, 2, k)
    ren = renumber_contiguous(grown, 2 + k)
    out = reshard_vertex_data(x, old_counts, ren.inv, ren.counts, n_pad_new)
    assert out.shape == (2 + k, n_pad_new, 3)
    back = unshard_vertex_data(out, ren.counts)
    np.testing.assert_array_equal(back[ren.perm], g)
    for r, c in enumerate(ren.counts):
        assert np.all(out[r, c:] == 0)  # pad rows stay zero


# ---------------------------------------------------------------------------
# grow_world: the generational transition
# ---------------------------------------------------------------------------


def test_grow_world_adopts_and_reshards(tmp_path):
    from dgraph_tpu.train import grow, shrink
    from dgraph_tpu.train.checkpoint import restore_checkpoint

    run = str(tmp_path / "run")
    seed = grow._seed_world(run, n=16, world=2)

    # tokens assigned to new ranks in SORTED order regardless of input
    rec = grow.grow_world(run, tokens=["node-b", "node-a"])
    assert rec["generation"] == 1 and rec["world_size"] == 4
    assert rec["resume_step"] == 3
    last = rec["join_history"][-1]
    assert last["joined"] == {"node-a": 2, "node-b": 3}
    assert last["generation"] == 0 and last["resume_step"] == 3
    # the pointer IS the adoption
    assert shrink.read_world(run)["generation"] == 1

    g1 = np.load(shrink.graph_path(run, 1))
    # every original vertex survives the unfold exactly once
    assert sorted(g1["orig_ids"].tolist()) == sorted(
        seed["orig"].tolist())
    assert len(g1["counts"]) == 4 and int(g1["counts"].sum()) == 16
    offs = np.concatenate([[0], np.cumsum(g1["counts"])])
    for r in range(4):
        got = restore_checkpoint(shrink.rank_ckpt_dir(run, 1, r))
        assert int(got["step"]) == 3
        w = np.asarray(got["state"]["w"])
        orig_r = g1["orig_ids"][offs[r]: offs[r + 1]]
        np.testing.assert_array_equal(w[: g1["counts"][r]], orig_r + 1.0)
        assert np.all(w[g1["counts"][r]:] == 0)
        assert got["state"]["lr"] == 0.5  # replicated leaf carried over


def test_grow_world_requires_pending_joins(tmp_path):
    from dgraph_tpu.train import grow, shrink

    run = str(tmp_path / "run")
    grow._seed_world(run)
    with pytest.raises(grow.GrowError) as ei:
        grow.grow_world(run)  # nobody announced
    assert "no pending join" in str(ei.value)
    assert shrink.read_world(run)["generation"] == 0


def test_grow_world_requires_consistent_cut(tmp_path):
    from dgraph_tpu.train import grow, shrink

    run = str(tmp_path / "run")
    grow._seed_world(run)
    # rank 1 loses its checkpoints: no step durable on ALL old ranks
    shutil.rmtree(shrink.rank_ckpt_dir(run, 0, 1))
    with pytest.raises(grow.GrowError) as ei:
        grow.grow_world(run, tokens=["node-a"])
    assert "durable on all" in str(ei.value)
    # the failed transition changed nothing the readers see
    assert shrink.read_world(run)["generation"] == 0


# ---------------------------------------------------------------------------
# generation chain: g0 --grow--> g1 --shrink--> g2, every plan verified
# ---------------------------------------------------------------------------


def test_grow_then_shrink_generation_chain(tmp_path):
    """Grow and shrink transitions compose into one self-describing
    generation chain; each generation's plan passes validate_plan and
    the newcomer's later loss folds its block back cleanly."""
    from dgraph_tpu.plan import load_sharded_plan, validate_plan
    from dgraph_tpu.train import grow, shrink
    from dgraph_tpu.train.checkpoint import restore_checkpoint

    run = str(tmp_path / "run")
    seed = grow._seed_world(run, n=16, world=2)

    rec1 = grow.grow_world(run, tokens=["node-a"])
    assert (rec1["generation"], rec1["world_size"]) == (1, 3)
    grants = grow.grant_joined(run, rec1)
    assert grants["node-a"]["rank"] == 2

    rec2 = shrink.shrink_world(run, [2])  # the newcomer dies right back
    assert (rec2["generation"], rec2["world_size"]) == (2, 2)
    assert rec2["join_history"][-1]["generation"] == 0
    assert rec2["lost_history"][-1] == {
        "generation": 1, "lost": [2], "resume_step": 3,
    }
    assert shrink.read_world(run)["generation"] == 2

    for gen, world in ((0, 2), (1, 3), (2, 2)):
        plan, _ = load_sharded_plan(shrink.plan_dir(run, gen),
                                    load_layout=False)
        assert plan.world_size == world
        validate_plan(plan)
        g = np.load(shrink.graph_path(run, gen))
        # vertex identity is conserved across every transition
        assert sorted(g["orig_ids"].tolist()) == sorted(
            seed["orig"].tolist())

    # the surviving rows still carry their per-vertex payload after the
    # round trip through the grown world
    g2 = np.load(shrink.graph_path(run, 2))
    offs = np.concatenate([[0], np.cumsum(g2["counts"])])
    for r in range(2):
        got = restore_checkpoint(shrink.rank_ckpt_dir(run, 2, r))
        w = np.asarray(got["state"]["w"])
        orig_r = g2["orig_ids"][offs[r]: offs[r + 1]]
        np.testing.assert_array_equal(w[: g2["counts"][r]], orig_r + 1.0)


def test_grown_generation_passes_spmd_audit(tmp_path):
    """Cross-rank SPMD identity over a freshly-grown generation's plan:
    every rank of the W+k world lowers the identical module from its own
    shard-subset view (one impl/program pair — the audit is lower-only
    but tier-1 pays every extra lowering)."""
    from dgraph_tpu.analysis.spmd import audit_plan_dir_spmd
    from dgraph_tpu.analysis.trace import _train_program
    from dgraph_tpu.train import grow, shrink

    run = str(tmp_path / "run")
    grow._seed_world(run, n=16, world=2)
    rec = grow.grow_world(run, tokens=["node-a"])
    rep = audit_plan_dir_spmd(
        shrink.plan_dir(run, rec["generation"]),
        impls=("all_to_all",),
        programs={"train_step": _train_program},
    )
    assert rep["ok"], rep["failures"]
    assert rep["world_size"] == 3
    for prec in rep["programs"]:
        assert prec["identical"], prec
        assert len(set(prec["module_hash"].values())) == 1


# ---------------------------------------------------------------------------
# THE acceptance pin: join -> detect -> grow -> bit-identical resume
# ---------------------------------------------------------------------------


def test_e2e_join_detect_grow_resume_bit_identical(tmp_path):
    """A joiner announces into a live 2-rank world mid-epoch -> both
    members detect the join at a step boundary, checkpoint, and exit 23
    -> supervise_group runs the grow-to-fit recovery (background re-plan
    at W=3 + checkpoint reshard + atomic adoption + grant) -> the
    resumed 3-rank run completes and is BIT-IDENTICAL to a fault-free
    3-rank run restored from the same post-grow checkpoint — and exact
    against the global per-vertex oracle."""
    import dgraph_tpu.comm.membership as ms
    from dgraph_tpu.train import grow, shrink
    from dgraph_tpu.train.checkpoint import latest_step, restore_checkpoint
    from tests.test_shrink import _global_oracle, _run_group

    rng = np.random.default_rng(9)
    n, W, steps, sleep_s = 16, 2, 24, 0.1
    edges = rng.integers(0, n, (2, 40)).astype(np.int64)
    run_a = str(tmp_path / "chaotic")
    shrink.init_world(run_a, edges, n, W, pad_multiple=2, lease_s=2.0)

    run_b = str(tmp_path / "oracle")
    snapshots, grant_box = [], []

    def joiner_main():
        # a real prospective member: waits until the step-3 cut is
        # durable on BOTH ranks, then announces into the LIVE
        # generation's membership dir and keeps the lease fresh until
        # the supervisor's grant lands
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            if all((latest_step(shrink.rank_ckpt_dir(run_a, 0, r)) or -1)
                   >= 3 for r in range(W)):
                break
            time.sleep(0.05)
        j = ms.Joiner(shrink.membership_dir(run_a, 0, 0), "newcomer-a",
                      generation=0, lease_s=5.0)
        while time.monotonic() < deadline:
            j.announce()
            got = j.grant()
            if got is not None:
                grant_box.append(got)
                return
            time.sleep(0.2)

    def on_rank_join(world, attempt):
        rec = grow.grow_world(run_a, attempt=attempt)
        grow.grant_joined(run_a, rec, attempt=attempt)
        # snapshot the freshly-adopted grown world BEFORE anyone resumes
        # in it: the fault-free oracle replays from this exact state
        shutil.copytree(run_a, run_b)
        snapshots.append(rec)
        return rec["world_size"]

    joiner = threading.Thread(target=joiner_main, name="joiner")
    joiner.start()
    try:
        lineage = _run_group(run_a, steps, W, sleep_s,
                             on_rank_join=on_rank_join)
    finally:
        joiner.join(timeout=120.0)
    assert lineage["final_exit_code"] == 0, json.dumps(lineage, indent=1)
    assert lineage["final_world_size"] == 3
    assert lineage["grows"] == [
        {"attempt": 0, "old_world": 2, "new_world": 3}
    ]
    a0, a1 = lineage["attempts"]
    ranks0 = {r["rank"]: r for r in a0["ranks"]}
    # BOTH members observed the join and exited 23 after a durable save
    for r in range(W):
        assert ranks0[r]["outcome"] == "rank_join"
        assert ranks0[r]["exit_code"] == 23
    assert a1["world_size"] == 3 and a1["outcome"] == "ok"
    # the joiner's rendezvous completed: granted rank 2 in generation 1
    assert grant_box and grant_box[0]["rank"] == 2
    assert grant_box[0]["generation"] == 1
    assert grant_box[0]["world_size"] == 3
    # the resumed attempt started from the grow's consistent cut
    resume_step = snapshots[0]["resume_step"]
    assert 3 <= resume_step < steps
    assert snapshots[0]["join_history"][-1]["joined"] == {"newcomer-a": 2}

    # fault-free W+1 oracle: the SAME post-grow snapshot restored and
    # driven by the SAME step function the worker runs (imported, not
    # reimplemented), replayed in-process per rank — identical code on
    # identical state, no 4th jax subprocess start
    from tests._rank_worker import make_step_fn

    g1 = np.load(shrink.graph_path(run_b, 1))
    offs = np.concatenate([[0], np.cumsum(g1["counts"])])
    for r in range(3):
        final_a = restore_checkpoint(shrink.rank_ckpt_dir(run_a, 1, r))
        assert int(final_a["step"]) == steps
        got = restore_checkpoint(shrink.rank_ckpt_dir(run_b, 1, r))
        assert int(got["step"]) == resume_step
        count = int(g1["counts"][r])
        orig_r = g1["orig_ids"][offs[r]: offs[r + 1]]
        n_pad = np.asarray(got["state"]["params"]["w"]).shape[0]
        step_fn = make_step_fn(orig_r, count, n_pad, 0.0)
        state_b = {
            "params": {"w": np.asarray(got["state"]["params"]["w"])},
            "opt_state": {"m": np.asarray(got["state"]["opt_state"]["m"])},
        }
        for _ in range(resume_step, steps):
            state_b = step_fn(state_b)

        # THE pin: params + opt_state bit-identical, every rank
        np.testing.assert_array_equal(
            np.asarray(final_a["state"]["params"]["w"]),
            state_b["params"]["w"],
        )
        np.testing.assert_array_equal(
            np.asarray(final_a["state"]["opt_state"]["m"]),
            state_b["opt_state"]["m"],
        )

        # and CORRECT: exact against the global per-vertex recurrence
        w_want, m_want = _global_oracle(orig_r, steps)
        np.testing.assert_allclose(
            np.asarray(final_a["state"]["params"]["w"])[:count], w_want,
            rtol=0, atol=0,
        )
        np.testing.assert_allclose(
            np.asarray(final_a["state"]["opt_state"]["m"])[:count], m_want,
            rtol=0, atol=0,
        )
