"""Top-1 MoE over an 8-expert axis vs a dense single-device oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.parallel.expert import load_balance_loss, moe_apply

E = 8  # experts = devices
T, F = 64, 16  # tokens per shard, features


def _mesh():
    devs = jax.devices()
    if len(devs) < E:
        pytest.skip(f"need {E} devices")
    return Mesh(np.array(devs[:E]), ("expert",))


def _expert_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _params(rng):
    return [
        {
            "w": rng.standard_normal((F, F)).astype(np.float32) * 0.5,
            "b": rng.standard_normal(F).astype(np.float32) * 0.1,
        }
        for _ in range(E)
    ]


def _dense_oracle(x, logits, params_list, capacity):
    """Per-shard-equivalent dense computation incl. the capacity drop."""
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    expert = np.argmax(np.asarray(probs), axis=-1)
    gate = np.take_along_axis(np.asarray(probs), expert[:, None], 1)[:, 0]
    out = np.zeros_like(np.asarray(x))
    counts = np.zeros(E, np.int64)
    for t in range(len(x)):
        e = int(expert[t])
        if counts[e] < capacity:
            y = np.tanh(np.asarray(x)[t] @ params_list[e]["w"] + params_list[e]["b"])
            out[t] = gate[t] * y
        counts[e] += 1
    return out


@pytest.mark.parametrize("capacity", [16, 4])  # ample and overflowing
def test_moe_equals_dense_oracle(capacity):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    params_list = _params(rng)
    stacked = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack(xs)), *params_list
    )
    # identical tokens/logits on every shard (P() = replicated): each shard
    # routes the same T tokens, so the oracle is per-shard identical too
    x = jnp.asarray(rng.standard_normal((T, F)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)

    fn = jax.shard_map(
        lambda p, x_, lg: moe_apply(
            x_, lg, _expert_fn, jax.tree.map(lambda l: l[0], p),
            capacity, "expert",
        ),
        mesh=mesh,
        in_specs=(P("expert"), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    got = fn(stacked, x, logits)
    want = _dense_oracle(x, logits, params_list, capacity)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_moe_gradients_flow_to_router_and_experts():
    mesh = _mesh()
    rng = np.random.default_rng(1)
    params_list = _params(rng)
    stacked = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack(xs)), *params_list
    )
    x = jnp.asarray(rng.standard_normal((T, F)), jnp.float32)
    wr = jnp.asarray(rng.standard_normal((F, E)).astype(np.float32) * 0.3)

    def loss(stacked, wr, x):
        fn = jax.shard_map(
            lambda p, x_, wr_: moe_apply(
                x_, x_ @ wr_, _expert_fn, jax.tree.map(lambda l: l[0], p),
                16, "expert",
            ),
            mesh=mesh,
            in_specs=(P("expert"), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return (fn(stacked, x, wr) ** 2).sum()

    gs, gr = jax.grad(loss, argnums=(0, 1))(stacked, wr, x)
    assert any(float(jnp.abs(l).sum()) > 0 for l in jax.tree.leaves(gs)), (
        "no gradient reached the experts"
    )
    assert float(jnp.abs(gr).sum()) > 0, "no gradient reached the router"


def test_load_balance_loss_range():
    mesh = _mesh()
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)
    fn = jax.shard_map(
        lambda lg: load_balance_loss(lg, "expert"),
        mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False,
    )
    val = float(fn(logits))
    # perfectly balanced -> 1.0; collapsed -> E. Random logits near 1.
    assert 0.9 < val < E
