"""Top-1 MoE over an 8-expert axis vs a dense single-device oracle."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.parallel.expert import load_balance_loss, moe_apply

E = 8  # experts = devices
T, F = 64, 16  # tokens per shard, features


def _mesh():
    devs = jax.devices()
    if len(devs) < E:
        pytest.skip(f"need {E} devices")
    return Mesh(np.array(devs[:E]), ("expert",))


def _expert_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _params(rng):
    return [
        {
            "w": rng.standard_normal((F, F)).astype(np.float32) * 0.5,
            "b": rng.standard_normal(F).astype(np.float32) * 0.1,
        }
        for _ in range(E)
    ]


def _dense_oracle(x, logits, params_list, capacity):
    """Per-shard-equivalent dense computation incl. the capacity drop."""
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    expert = np.argmax(np.asarray(probs), axis=-1)
    gate = np.take_along_axis(np.asarray(probs), expert[:, None], 1)[:, 0]
    out = np.zeros_like(np.asarray(x))
    counts = np.zeros(E, np.int64)
    for t in range(len(x)):
        e = int(expert[t])
        if counts[e] < capacity:
            y = np.tanh(np.asarray(x)[t] @ params_list[e]["w"] + params_list[e]["b"])
            out[t] = gate[t] * y
        counts[e] += 1
    return out


@pytest.mark.parametrize("capacity", [16, 4])  # ample and overflowing
def test_moe_equals_dense_oracle(capacity):
    mesh = _mesh()
    rng = np.random.default_rng(0)
    params_list = _params(rng)
    stacked = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack(xs)), *params_list
    )
    # identical tokens/logits on every shard (P() = replicated): each shard
    # routes the same T tokens, so the oracle is per-shard identical too
    x = jnp.asarray(rng.standard_normal((T, F)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)

    fn = jax.shard_map(
        lambda p, x_, lg: moe_apply(
            x_, lg, _expert_fn, jax.tree.map(lambda l: l[0], p),
            capacity, "expert",
        ),
        mesh=mesh,
        in_specs=(P("expert"), P(), P()),
        out_specs=P(),
        check_vma=False,
    )
    got = fn(stacked, x, logits)
    want = _dense_oracle(x, logits, params_list, capacity)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_moe_gradients_flow_to_router_and_experts():
    mesh = _mesh()
    rng = np.random.default_rng(1)
    params_list = _params(rng)
    stacked = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack(xs)), *params_list
    )
    x = jnp.asarray(rng.standard_normal((T, F)), jnp.float32)
    wr = jnp.asarray(rng.standard_normal((F, E)).astype(np.float32) * 0.3)

    def loss(stacked, wr, x):
        fn = jax.shard_map(
            lambda p, x_, wr_: moe_apply(
                x_, x_ @ wr_, _expert_fn, jax.tree.map(lambda l: l[0], p),
                16, "expert",
            ),
            mesh=mesh,
            in_specs=(P("expert"), P(), P()),
            out_specs=P(),
            check_vma=False,
        )
        return (fn(stacked, x, wr) ** 2).sum()

    gs, gr = jax.grad(loss, argnums=(0, 1))(stacked, wr, x)
    assert any(float(jnp.abs(l).sum()) > 0 for l in jax.tree.leaves(gs)), (
        "no gradient reached the experts"
    )
    assert float(jnp.abs(gr).sum()) > 0, "no gradient reached the router"


def test_load_balance_loss_range():
    mesh = _mesh()
    rng = np.random.default_rng(2)
    logits = jnp.asarray(rng.standard_normal((T, E)), jnp.float32)
    fn = jax.shard_map(
        lambda lg: load_balance_loss(lg, "expert"),
        mesh=mesh, in_specs=(P(),), out_specs=P(), check_vma=False,
    )
    val = float(fn(logits))
    # perfectly balanced -> 1.0; collapsed -> E. Random logits near 1.
    assert 0.9 < val < E


def _dense_topk_oracle(x, logits, params_list, k):
    """Ample-capacity dense oracle for top-k: per token, the gate-weighted
    sum of its top-k experts' outputs with gates renormalized over k."""
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    order = np.argsort(-probs, axis=-1)[:, :k]  # [T, k]
    out = np.zeros_like(np.asarray(x))
    for t in range(x.shape[0]):
        sel = order[t]
        g = probs[t, sel]
        g = g / g.sum()
        for c, e in enumerate(sel):
            p = params_list[e]
            out[t] += g[c] * np.asarray(
                _expert_fn({k2: jnp.asarray(v) for k2, v in p.items()},
                           jnp.asarray(x[t][None]))
            )[0]
    return out


def test_top2_matches_dense_oracle():
    mesh = _mesh()
    rng = np.random.default_rng(3)
    x = rng.standard_normal((E, T, F)).astype(np.float32)
    logits = rng.standard_normal((E, T, E)).astype(np.float32)
    params_list = _params(rng)
    stacked = jax.tree.map(
        lambda *xs: jnp.asarray(np.stack(xs)), *params_list
    )
    CAP = E * T  # ample: nothing drops

    def body(x_, lg, ep):
        return moe_apply(
            x_, lg, _expert_fn, jax.tree.map(lambda l: l[0], ep), CAP,
            "expert", k=2,
        )

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P("expert"), P("expert"), P("expert")),
        out_specs=P("expert"),
        check_vma=False,
    )
    with jax.set_mesh(mesh):
        got = np.asarray(fn(
            jnp.asarray(x.reshape(E * T, F)),
            jnp.asarray(logits.reshape(E * T, E)), stacked,
        ))
    for s in range(E):  # every shard against the dense oracle
        want = _dense_topk_oracle(x[s], logits[s], params_list, k=2)
        np.testing.assert_allclose(
            got[s * T:(s + 1) * T], want, rtol=2e-5, atol=2e-5,
            err_msg=f"shard {s}",
        )


def test_top2_choice_major_priority_under_pressure():
    """First choices must claim capacity before ANY second choice (the
    GShard priority rule). Routes genuinely compete: ODD tokens' 1st
    choice is expert 0, EVEN tokens' 2nd choice is also expert 0 (and
    symmetrically for expert 1), with capacity = half the per-expert
    demand. Choice-major assignment keeps exactly every 1st-choice route
    and drops every 2nd-choice route; token-major assignment would let
    early even tokens' 2nd choices steal expert-0 slots from late odd
    tokens' 1st choices — a different, detectably wrong output."""
    mesh = _mesh()
    rng = np.random.default_rng(4)
    x = rng.standard_normal((E * T, F)).astype(np.float32)
    logits = np.zeros((E * T, E), np.float32)
    odd = (np.arange(E * T) % 2).astype(bool)
    logits[odd, 0], logits[odd, 1] = 4.0, 2.0   # odd: 1st->e0, 2nd->e1
    logits[~odd, 1], logits[~odd, 0] = 4.0, 2.0  # even: 1st->e1, 2nd->e0
    cap = T // 2  # = the number of 1st-choice routes per (shard, expert)

    params_list = _params(rng)
    stacked = jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)), *params_list)

    def body(x_, lg, ep):
        return moe_apply(
            x_, lg, _expert_fn, jax.tree.map(lambda l: l[0], ep), cap,
            "expert", k=2,
        )

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P("expert"), P("expert"), P("expert")),
        out_specs=P("expert"),
        check_vma=False,
    )
    with jax.set_mesh(mesh):
        got = np.asarray(fn(jnp.asarray(x), jnp.asarray(logits), stacked))

    # oracle: every token keeps ONLY its 1st choice (x its renormalized
    # 1st gate); every 2nd-choice route drops
    probs = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
    g1 = np.take_along_axis(probs, np.argmax(probs, -1)[:, None], 1)[:, 0]
    g2 = np.partition(probs, -2, axis=-1)[:, -2]
    w1 = g1 / (g1 + g2)
    first = np.where(odd, 0, 1)
    want = np.zeros_like(got)
    for e in (0, 1):
        sel = first == e
        p = {k2: jnp.asarray(v) for k2, v in params_list[e].items()}
        want[sel] = w1[sel, None] * np.asarray(
            _expert_fn(p, jnp.asarray(x[sel])))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_top2_router_gradients_flow():
    mesh = _mesh()
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.standard_normal((E * T, F)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((E * T, E)), jnp.float32)
    params_list = _params(rng)
    stacked = jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)), *params_list)

    def loss(lg):
        def body(x_, lg_, ep):
            out = moe_apply(
                x_, lg_, _expert_fn, jax.tree.map(lambda l: l[0], ep),
                2 * T, "expert", k=2,
            )
            return out

        fn = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P("expert"), P("expert"), P("expert")),
            out_specs=P("expert"),
            check_vma=False,
        )
        return (fn(x, lg, stacked) ** 2).sum()

    with jax.set_mesh(mesh):
        g = jax.grad(loss)(logits)
    assert float(jnp.abs(g).sum()) > 0  # the router learns through gates
