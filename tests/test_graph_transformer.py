"""GraphTransformer (GPS: local MPNN + global ring attention) tests:
single-device dense oracle vs 8-way distributed logits, and training.

Beyond-reference model family (the reference has only local-k-hop models,
SURVEY.md §2.5); the global branch rides ring attention over the SAME
graph mesh axis the vertices are sharded on."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dgraph_tpu.comm import Communicator
from dgraph_tpu.data import DistributedGraph, synthetic
from dgraph_tpu.models import GraphTransformer
from dgraph_tpu.testing import spmd_apply
from tests.test_models import build_graphs, to_original_order


@pytest.fixture(scope="module")
def sbm():
    return synthetic.sbm_classification_graph(num_nodes=400, seed=1)


def _model(comm):
    return GraphTransformer(
        latent=32, out_features=4, comm=comm, num_layers=2, num_heads=4
    )


def test_distributed_matches_single_device(mesh8, sbm):
    g1 = build_graphs(sbm, 1)
    g8 = build_graphs(sbm, 8)
    model1 = _model(Communicator.init_process_group("single"))
    model8 = _model(Communicator.init_process_group("tpu", world_size=8))

    plan1 = jax.tree.map(lambda l: jnp.asarray(l[0]), g1.plan)
    x1 = jnp.asarray(g1.features[0])
    vm1 = jnp.asarray(g1.vertex_mask[0])
    params = model1.init(jax.random.key(0), x1, plan1, vm1)
    ref = to_original_order(np.asarray(model1.apply(params, x1, plan1, vm1))[None], g1)

    def body(x, vm, plan_shard):
        return model8.apply(params, x, plan_shard, vm)

    out8 = spmd_apply(
        mesh8, body, g8.plan, jnp.asarray(g8.features), jnp.asarray(g8.vertex_mask)
    )
    got = to_original_order(out8, g8)
    np.testing.assert_allclose(got, ref, rtol=3e-4, atol=3e-4)


def test_padded_rows_stay_zero(mesh8, sbm):
    """Residual stream on padded slots must remain exactly zero — they feed
    the next layer's scatter via cross-shard padding edges."""
    g1 = build_graphs(sbm, 1)
    g8 = build_graphs(sbm, 8)
    model8 = _model(Communicator.init_process_group("tpu", world_size=8))
    # init via the single-comm twin (identical param tree; a TpuComm model
    # can only init inside shard_map)
    model1 = _model(Communicator.init_process_group("single"))
    plan1 = jax.tree.map(lambda l: jnp.asarray(l[0]), g1.plan)
    params = model1.init(
        jax.random.key(0), jnp.asarray(g1.features[0]), plan1,
        jnp.asarray(g1.vertex_mask[0]),
    )

    def body(x, vm, plan_shard):
        return model8.apply(params, x, plan_shard, vm)

    out8 = np.asarray(
        spmd_apply(
            mesh8, body, g8.plan, jnp.asarray(g8.features),
            jnp.asarray(g8.vertex_mask),
        )
    )
    vm = np.asarray(g8.vertex_mask)
    # head bias makes padded logits constant-but-nonzero at the OUTPUT; the
    # invariant we need is separability: padded rows all identical (no data
    # leaked into them from real vertices)
    pad_rows = out8[vm == 0]
    if len(pad_rows):
        np.testing.assert_allclose(
            pad_rows - pad_rows[0][None], 0.0, atol=1e-6
        )


def test_trains_on_sbm(mesh8, sbm):
    from dgraph_tpu.train.loop import fit, vmask_batch_args

    g8 = build_graphs(sbm, 8)
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    model = _model(comm8)
    params, history = fit(
        model, g8, mesh8, optimizer=optax.adam(3e-3), num_epochs=40,
        batch_args=vmask_batch_args,
    )
    assert history[-1]["loss"] < history[0]["loss"] * 0.7
