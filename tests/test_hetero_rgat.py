"""Heterogeneous graph + RGAT tests: plan correctness per relation,
single-device vs 8-way logit equivalence (including distributed BatchNorm
statistics), and a short training run.

Mirrors the reference's OGB-LSC stack (``experiments/OGB-LSC``, SURVEY §2.5).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from dgraph_tpu.comm import Communicator
from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
from dgraph_tpu.data.hetero import DistributedHeteroGraph, synthetic_mag
from dgraph_tpu.models import RGAT
from dgraph_tpu.plan import unshard_vertex_data


@pytest.fixture(scope="module")
def mag():
    return synthetic_mag(num_papers=200, num_authors=120, num_institutions=20, seed=2)


def build(mag, world):
    nf, rels, labels, masks = mag
    return DistributedHeteroGraph.from_global(
        nf, rels, world, labels=labels, masks=masks, partition_method="random"
    )


def to_orig(x_sharded, ren):
    xr = unshard_vertex_data(np.asarray(x_sharded), ren.counts)
    out = np.empty_like(xr)
    out[ren.inv] = xr
    return out


def hetero_in_specs(g):
    return (
        jax.tree.map(lambda _: P(GRAPH_AXIS), g.features),
        jax.tree.map(lambda _: P(GRAPH_AXIS), g.plans),
        jax.tree.map(lambda _: P(GRAPH_AXIS), g.vertex_masks),
    )


def hetero_args(g, shard=None):
    sel = (lambda a: jnp.asarray(a[shard])) if shard is not None else jnp.asarray
    feats = {t: sel(v) for t, v in g.features.items()}
    plans = {k: jax.tree.map(sel, p) for k, p in g.plans.items()}
    vmasks = {t: sel(v) for t, v in g.vertex_masks.items()}
    return feats, plans, vmasks


def test_relation_plans_cover_all_edges(mag):
    nf, rels, _, _ = mag
    g = build(mag, 4)
    for key, edges in rels.items():
        assert float(np.asarray(g.plans[key].edge_mask).sum()) == edges.shape[1]


@pytest.mark.parametrize("hidden,heads", [(16, 2), (64, 4)])
def test_rgat_distributed_matches_single(mesh8, mag, hidden, heads):
    # (64, 4): H*D = 256 > gather_col_block, so the head-group-chunked
    # attention path ENGAGES (the small config covers single-group)
    g1, g8 = build(mag, 1), build(mag, 8)
    rels = list(g8.plans)
    comm1 = Communicator.init_process_group("single")
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    kw = dict(
        hidden_features=hidden, out_features=4, relations=rels, num_layers=2,
        num_heads=heads,
    )
    m1 = RGAT(comm=comm1, **kw)
    m8 = RGAT(comm=comm8, **kw)

    f1, p1, v1 = hetero_args(g1, shard=0)
    variables = m1.init(jax.random.key(0), f1, p1, v1, train=False)
    out1, _ = m1.apply(variables, f1, p1, v1, train=True, mutable=["batch_stats"])
    ref = to_orig(np.asarray(out1)[None], g1.renumberings["paper"])

    def body(feats, plans, vmasks):
        feats = {t: v[0] for t, v in feats.items()}
        plans = {k: squeeze_plan(p) for k, p in plans.items()}
        vmasks = {t: v[0] for t, v in vmasks.items()}
        out, _ = m8.apply(variables, feats, plans, vmasks, train=True, mutable=["batch_stats"])
        return out[None]

    f8, p8, v8 = hetero_args(g8)
    fn = jax.shard_map(
        body, mesh=mesh8, in_specs=hetero_in_specs(g8), out_specs=P(GRAPH_AXIS)
    )
    with jax.set_mesh(mesh8):
        out8 = jax.jit(fn)(f8, p8, v8)
    got = to_orig(out8, g8.renumberings["paper"])
    np.testing.assert_allclose(got, ref, rtol=5e-4, atol=5e-4)


def test_rgat_trains(mesh8, mag):
    g8 = build(mag, 8)
    rels = list(g8.plans)
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    model = RGAT(
        hidden_features=16,
        out_features=4,
        comm=comm8,
        relations=rels,
        num_layers=2,
        use_batch_norm=False,
    )
    f8, p8, v8 = hetero_args(g8)
    y = jnp.asarray(g8.labels["paper"])
    mask = jnp.asarray(g8.masks[("paper", "train")])

    def init_body(feats, plans, vmasks):
        feats = {t: v[0] for t, v in feats.items()}
        plans = {k: squeeze_plan(p) for k, p in plans.items()}
        vmasks = {t: v[0] for t, v in vmasks.items()}
        return model.init(jax.random.key(0), feats, plans, vmasks)

    with jax.set_mesh(mesh8):
        params = jax.jit(
            jax.shard_map(
                init_body, mesh=mesh8, in_specs=hetero_in_specs(g8), out_specs=P()
            )
        )(f8, p8, v8)

    opt = optax.adam(5e-3)
    opt_state = opt.init(params)

    def train_body(params, feats, plans, vmasks, y, mask):
        feats = {t: v[0] for t, v in feats.items()}
        plans = {k: squeeze_plan(p) for k, p in plans.items()}
        vmasks = {t: v[0] for t, v in vmasks.items()}
        y_, m_ = y[0], mask[0]

        def lf(p):
            logits = model.apply(p, feats, plans, vmasks)
            logp = jax.nn.log_softmax(logits)
            ll = jnp.take_along_axis(logp, y_[:, None], axis=1)[:, 0]
            cnt = jax.lax.psum(m_.sum(), GRAPH_AXIS)
            return -(ll * m_).sum() / jnp.maximum(cnt, 1.0)

        loss, grads = jax.value_and_grad(lf)(params)
        from dgraph_tpu import compat as _compat

        grads = _compat.sync_inbody_grads(grads, (GRAPH_AXIS,))
        return jax.lax.psum(loss, GRAPH_AXIS), grads

    in_specs = (P(),) + hetero_in_specs(g8) + (P(GRAPH_AXIS), P(GRAPH_AXIS))
    step_body = jax.shard_map(
        train_body, mesh=mesh8, in_specs=in_specs, out_specs=(P(), P())
    )

    @jax.jit
    def step(params, opt_state):
        loss, grads = step_body(params, f8, p8, v8, y, mask)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    with jax.set_mesh(mesh8):
        for _ in range(30):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_locality_partition_cuts_halo_volume():
    """Union-graph locality partitioning must reduce total deduped halo
    pairs vs random (VERDICT r1 #7: random hetero partition makes RGAT halo
    volume worst-case by construction) while keeping every type's per-rank
    balance within the padding slack."""
    from dgraph_tpu.data.hetero import DistributedHeteroGraph, synthetic_mag

    W = 4
    nf, rels, labels, masks = synthetic_mag(2000, 1200, 120, 8, 4, seed=2)

    def halo_pairs(g):
        return sum(int(l.halo_counts.sum()) for l in g.layouts.values())

    g_rand = DistributedHeteroGraph.from_global(
        nf, rels, W, labels=labels, masks=masks, partition_method="random"
    )
    g_loc = DistributedHeteroGraph.from_global(
        nf, rels, W, labels=labels, masks=masks, partition_method="multilevel"
    )
    hp_rand, hp_loc = halo_pairs(g_rand), halo_pairs(g_loc)
    assert hp_loc < 0.8 * hp_rand, (hp_loc, hp_rand)
    # per-type balance: padded size within slack of the ideal share
    for t, ren in g_loc.renumberings.items():
        V = len(ren.perm)
        assert ren.counts.max() <= int(np.ceil(V / W * 1.05)) + 1, (t, ren.counts)
