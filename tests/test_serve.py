"""Serving stack: bucket-ladder edge cases, warmup/zero-recompile
invariant, bucketed-vs-eval-forward bit parity, micro-batcher backpressure
and deadline semantics, checkpoint/plan-cache corruption tolerance, and the
``python -m dgraph_tpu.serve --selftest`` smoke (the tier-1 pin for the
whole path)."""

import glob
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dgraph_tpu.serve.bucketing import BucketLadder, pad_ids
from dgraph_tpu.serve.errors import (
    QueueFull,
    RequestTimeout,
    RequestTooLarge,
)


# ---------------------------------------------------------------------------
# bucketing ladder
# ---------------------------------------------------------------------------


def test_geometric_ladder_shape():
    lad = BucketLadder.geometric(8, 64, 2.0)
    assert lad.sizes == (8, 16, 32, 64)
    assert lad.max_size == 64
    # non-power-of-two growth still ends exactly at max_size, ascending
    lad = BucketLadder.geometric(10, 100, 1.5)
    assert lad.sizes[0] == 10 and lad.sizes[-1] == 100
    assert all(b > a for a, b in zip(lad.sizes, lad.sizes[1:]))
    # degenerate single-bucket ladder
    assert BucketLadder.geometric(16, 16).sizes == (16,)
    with pytest.raises(ValueError):
        BucketLadder.geometric(8, 64, growth=1.0)
    with pytest.raises(ValueError):
        BucketLadder.geometric(8, 4)
    with pytest.raises(ValueError):
        BucketLadder((8, 8, 16))  # not strictly ascending
    with pytest.raises(ValueError):
        BucketLadder(())


def test_bucket_for_boundaries():
    lad = BucketLadder((8, 16, 32))
    assert lad.bucket_for(0) == 8  # empty request -> smallest bucket
    assert lad.bucket_for(1) == 8
    assert lad.bucket_for(8) == 8  # exact fit stays
    assert lad.bucket_for(9) == 16
    assert lad.bucket_for(32) == 32
    with pytest.raises(ValueError):
        lad.bucket_for(-1)
    # request larger than the max bucket: structured rejection
    with pytest.raises(RequestTooLarge) as ei:
        lad.bucket_for(33)
    rec = ei.value.record()
    assert rec["error"] == "too_large"
    assert rec["request_size"] == 33 and rec["max_bucket"] == 32
    json.dumps(rec)


def test_pad_ids():
    padded, n = pad_ids(np.array([5, 7, 9]), 8)
    assert n == 3 and padded.shape == (8,) and padded.dtype == np.int32
    np.testing.assert_array_equal(padded[:3], [5, 7, 9])
    np.testing.assert_array_equal(padded[3:], 0)
    padded, n = pad_ids(np.array([], np.int64), 8)
    assert n == 0 and (padded == 0).all()
    with pytest.raises(ValueError):
        pad_ids(np.zeros(9), 8)
    with pytest.raises(ValueError):
        pad_ids(np.zeros((2, 2)), 8)


# ---------------------------------------------------------------------------
# engine: warmup / recompiles / parity (one stack shared module-wide)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def serving(mesh8):
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models import GCN
    from dgraph_tpu.obs.metrics import Metrics
    from dgraph_tpu.serve.engine import ServeEngine
    from dgraph_tpu.train.loop import init_params, make_eval_step

    data = synthetic.sbm_classification_graph(
        num_nodes=200, num_classes=3, feat_dim=8, avg_degree=6.0
    )
    g = DistributedGraph.from_global(
        data["edge_index"], data["features"], data["labels"], data["masks"],
        world_size=8, partition_method="random",
    )
    comm = Communicator.init_process_group("tpu", world_size=8)
    model = GCN(8, 3, comm=comm, num_layers=2)
    plan = jax.tree.map(jnp.asarray, g.plan)
    batch = jax.tree.map(jnp.asarray, dict(g.batch("train"), y=g.labels))
    params = init_params(model, mesh8, plan, batch)
    engine = ServeEngine.from_distributed_graph(
        model, mesh8, g, params,
        ladder=BucketLadder((8, 16, 32)), registry=Metrics(),
    )
    warm = engine.warmup()
    eval_step = make_eval_step(model, mesh8)
    return engine, g, model, params, warm, eval_step


def test_warmup_compiles_all_buckets(serving):
    engine, _, _, _, warm, _ = serving
    assert warm["buckets"] == [8, 16, 32]
    # one steady-state executable per bucket (+1 for the full-logits
    # oracle); each bucket fn's own cache must be populated
    for b, f in engine._forwards.items():
        assert f._cache_size() >= 1, f"bucket {b} not compiled at warmup"
    assert warm["compiles_at_warmup"] == engine._total_compiles()


def test_steady_state_zero_recompiles(serving, rng):
    engine, *_ = serving
    assert engine.recompiles_since_warmup() == 0
    # every bucket, boundary sizes included — no novel shape may reach XLA
    for n in (0, 1, 7, 8, 9, 15, 16, 17, 31, 32):
        engine.infer(rng.choice(engine.num_nodes, size=n, replace=False))
    assert engine.recompiles_since_warmup() == 0
    snap = engine.registry.snapshot()
    assert snap["gauges"]["serve.recompiles_since_warmup"] == 0.0
    assert snap["histograms"]["serve.infer_ms"]["count"] >= 10


def test_served_logits_match_eval_forward_bitwise(serving, rng):
    """The acceptance pin: the bucketed, gathered serve path returns the
    SAME bits as the full eval forward (identical params/plan/model_apply
    body), across every bucket."""
    engine, *_ = serving
    full = engine.full_logits()
    for n in (1, 5, 8, 13, 27, 32):
        ids = rng.choice(engine.num_nodes, size=n, replace=False)
        out = engine.infer(ids)
        r, s = engine.rank_slot(ids)
        np.testing.assert_array_equal(out, full[r, s])


def test_served_metrics_match_make_eval_step(serving):
    """Tie serve output to make_eval_step semantics end to end: accuracy
    computed on host from served logits equals the jitted eval step's."""
    import jax
    import jax.numpy as jnp

    engine, g, model, params, _, eval_step = serving
    batch = jax.tree.map(jnp.asarray, dict(g.batch("val"), y=g.labels))
    plan = jax.tree.map(jnp.asarray, g.plan)
    with jax.set_mesh(engine.mesh):
        ev = eval_step(params, batch, plan)
    full = engine.full_logits()
    mask = np.asarray(g.masks["val"])
    y = np.asarray(g.labels)
    correct = ((full.argmax(-1) == y) * mask).sum()
    acc = correct / mask.sum()
    assert float(ev["accuracy"]) == pytest.approx(float(acc), abs=1e-6)


def test_engine_rejects_bad_requests(serving):
    engine, *_ = serving
    with pytest.raises(RequestTooLarge):
        engine.infer(np.zeros(33, np.int64))
    with pytest.raises(ValueError):
        engine.infer(np.array([engine.num_nodes]))  # out of range
    with pytest.raises(ValueError):
        engine.infer(np.array([-1]))
    with pytest.raises(ValueError):
        engine.infer(np.zeros((2, 2), np.int64))


def test_batcher_end_to_end_parity(serving, rng):
    """Concurrent mixed-size requests through the micro-batcher come back
    correctly sliced per request (and still bit-equal to the oracle)."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    engine, *_ = serving
    full = engine.full_logits()
    bat = MicroBatcher(
        engine, max_batch_size=4, max_delay_ms=1.0, max_queue_depth=64
    )
    try:
        futs, refs = [], []
        for _ in range(12):
            ids = rng.choice(
                engine.num_nodes, size=int(rng.integers(1, 33)), replace=False
            )
            futs.append(bat.submit(ids))
            r, s = engine.rank_slot(ids)
            refs.append(full[r, s])
        for fut, ref in zip(futs, refs):
            np.testing.assert_array_equal(fut.result(timeout=60), ref)
        assert engine.recompiles_since_warmup() == 0
    finally:
        bat.stop()


# ---------------------------------------------------------------------------
# micro-batcher policy (fake engine: no device work, deterministic control)
# ---------------------------------------------------------------------------


class _FakeEngine:
    """Engine stand-in: records batches, optionally blocks inside infer so
    tests can hold the worker at a known point."""

    def __init__(self, ladder, block=None, started=None):
        from dgraph_tpu.obs.metrics import Metrics

        self.ladder = ladder
        self.registry = Metrics()
        self.calls = []
        self._block = block  # threading.Event the worker waits on
        self._started = started  # set when infer begins

    def infer(self, ids):
        if self._started is not None:
            self._started.set()
        if self._block is not None:
            assert self._block.wait(timeout=30)
        self.calls.append(np.asarray(ids))
        return np.zeros((len(ids), 3), np.float32)


def test_batcher_backpressure_rejects_structured():
    from dgraph_tpu.serve.batcher import MicroBatcher

    block, started = threading.Event(), threading.Event()
    eng = _FakeEngine(BucketLadder((8,)), block=block, started=started)
    bat = MicroBatcher(
        eng, max_batch_size=1, max_delay_ms=0.0, max_queue_depth=1
    )
    try:
        f1 = bat.submit(np.arange(3))
        assert started.wait(timeout=10)  # worker is now inside infer
        f2 = bat.submit(np.arange(2))  # occupies the single queue slot
        with pytest.raises(QueueFull) as ei:
            bat.submit(np.arange(2))
        rec = ei.value.record()
        assert rec["error"] == "backpressure"
        assert rec["queue_depth"] == 1 and rec["max_queue_depth"] == 1
        json.dumps(rec)
        assert eng.registry.snapshot()["counters"][
            "serve.rejected_backpressure"
        ] == 1
        block.set()
        f1.result(timeout=10), f2.result(timeout=10)
    finally:
        block.set()
        bat.stop()


def test_batcher_oversize_request_never_queues():
    from dgraph_tpu.serve.batcher import MicroBatcher

    eng = _FakeEngine(BucketLadder((8,)))
    bat = MicroBatcher(eng, max_delay_ms=0.0)
    try:
        with pytest.raises(RequestTooLarge):
            bat.submit(np.arange(9))
        assert len(bat) == 0
    finally:
        bat.stop()


def test_batcher_invalid_ids_rejected_at_submit():
    """Out-of-range ids must fail at submit — the worker CONCATENATES
    requests, so one bad request reaching the engine would fan its failure
    to every innocent request coalesced into the same batch."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    eng = _FakeEngine(BucketLadder((8,)))
    eng.num_nodes = 100
    bat = MicroBatcher(eng, max_delay_ms=0.0)
    try:
        with pytest.raises(ValueError):
            bat.submit(np.array([100]))
        with pytest.raises(ValueError):
            bat.submit(np.array([-1]))
        assert len(bat) == 0 and not eng.calls
        bat.submit(np.array([99])).result(timeout=10)  # boundary id is fine
    finally:
        bat.stop()


def test_batcher_expired_request_flushes_empty():
    """A request whose deadline passed while queued is rejected with the
    structured timeout error and the engine is never called (the
    empty-batch flush)."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    block, started = threading.Event(), threading.Event()
    eng = _FakeEngine(BucketLadder((8,)), block=block, started=started)
    bat = MicroBatcher(
        eng, max_batch_size=1, max_delay_ms=0.0, max_queue_depth=8
    )
    try:
        f1 = bat.submit(np.arange(2))  # holds the worker inside infer
        assert started.wait(timeout=10)
        f2 = bat.submit(np.arange(2), timeout_s=0.01)  # will expire queued
        time.sleep(0.05)
        block.set()
        f1.result(timeout=10)
        with pytest.raises(RequestTimeout) as ei:
            f2.result(timeout=10)
        assert ei.value.record()["error"] == "timeout"
        assert ei.value.context["waited_s"] >= 0.01
        # the expired request never reached the engine
        assert len(eng.calls) == 1
        assert eng.registry.snapshot()["counters"]["serve.rejected_timeout"] == 1
    finally:
        block.set()
        bat.stop()


def test_batcher_coalesces_and_splits_on_bucket_capacity():
    """Waiting requests coalesce into one engine call; a request that would
    overflow the largest bucket starts the next batch instead."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    block, started = threading.Event(), threading.Event()
    eng = _FakeEngine(BucketLadder((4, 8)), block=block, started=started)
    bat = MicroBatcher(
        eng, max_batch_size=8, max_delay_ms=1.0, max_queue_depth=16
    )
    try:
        f0 = bat.submit(np.arange(1))  # taken immediately; holds the worker
        assert started.wait(timeout=10)
        futs = [bat.submit(np.full(3, i)) for i in range(3)]  # 3+3+3 > 8
        block.set()
        for f in (f0, *futs):
            f.result(timeout=10)
        # call 1: the lone request; then 3+3 coalesced (9 > 8 splits); then 3
        sizes = [len(c) for c in eng.calls]
        assert sizes[0] == 1 and sum(sizes) == 10
        assert all(s <= 8 for s in sizes)
        assert len(sizes) == 3
        reg = eng.registry.snapshot()
        assert reg["counters"]["serve.batches"] == 3
        assert reg["histograms"]["serve.requests_per_batch"]["max"] == 2
    finally:
        block.set()
        bat.stop()


def test_batcher_stop_rejects_new_submits():
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.errors import EngineStopped

    eng = _FakeEngine(BucketLadder((8,)))
    bat = MicroBatcher(eng)
    bat.stop()
    with pytest.raises(EngineStopped):
        bat.submit(np.arange(2))


def test_batcher_survives_client_cancelled_future():
    """A client cancelling its queued Future (a normal client-side timeout
    pattern) must be dropped like an expired request — resolving a
    cancelled Future raises InvalidStateError, which the worker's crash
    containment would otherwise escalate into stopping the whole batcher
    for every other client."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    block, started = threading.Event(), threading.Event()
    eng = _FakeEngine(BucketLadder((8,)), block=block, started=started)
    bat = MicroBatcher(
        eng, max_batch_size=1, max_delay_ms=0.0, max_queue_depth=8
    )
    try:
        f0 = bat.submit(np.arange(2))  # occupies the worker inside infer
        assert started.wait(timeout=10)
        f1 = bat.submit(np.arange(3))  # queued; cancel before it runs
        f2 = bat.submit(np.arange(2))  # innocent bystander
        assert f1.cancel()
        block.set()
        # the bystander is served normally — the cancelled future neither
        # crashed the worker nor reached the engine
        assert f2.result(timeout=10).shape == (2, 3)
        assert f0.result(timeout=10).shape == (2, 3)
        assert f1.cancelled()
        assert all(c.shape[0] != 3 for c in eng.calls)
        assert bat._worker.is_alive()
        assert eng.registry.snapshot()["counters"][
            "serve.rejected_cancelled"
        ] == 1
    finally:
        block.set()
        bat.stop()


def test_batcher_worker_crash_fails_pending_and_stops():
    """A top-level worker exception (here: a metrics callback, firing in
    _collect AFTER requests were popped off the queue) used to kill the
    thread silently and hang every waiter until client timeout. Now every
    pending/in-flight future fails fast with the typed WorkerCrashed and
    the batcher marks itself stopped."""
    from dgraph_tpu.obs.metrics import Metrics
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.errors import EngineStopped, WorkerCrashed

    class _BombRegistry(Metrics):
        # only the worker thread's metrics path blows up; client-side
        # submit keeps working so the request is queued normally first
        def gauge(self, name, value):
            if threading.current_thread().name == "serve-batcher":
                raise RuntimeError("metrics backend down")
            super().gauge(name, value)

    eng = _FakeEngine(BucketLadder((8,)))
    bat = MicroBatcher(eng, max_delay_ms=0.0, registry=_BombRegistry())
    try:
        fut = bat.submit(np.arange(3))
        with pytest.raises(WorkerCrashed) as ei:
            fut.result(timeout=10)
        rec = ei.value.record()
        assert rec["error"] == "worker_crashed"
        json.dumps(rec)
        bat._worker.join(timeout=10)
        assert not bat._worker.is_alive()
        # the crash marked the batcher stopped: immediate structured
        # rejection, no silent queueing into a dead worker
        with pytest.raises(EngineStopped):
            bat.submit(np.arange(2))
        assert eng.calls == []  # the crashed batch never reached the engine
    finally:
        bat.stop()


# ---------------------------------------------------------------------------
# engine self-healing: bounded retry + degraded shedding (chaos-driven)
# ---------------------------------------------------------------------------


def test_engine_retries_transient_device_error(serving, rng):
    from dgraph_tpu import chaos

    engine, *_ = serving
    full = engine.full_logits()
    try:
        # arm() zeroes the per-point call counters: the next infer's first
        # dispatch attempt is serve.infer index 0 and fails; the retry
        # (index 1) succeeds
        chaos.arm("serve.infer=raise@0")
        ids = rng.choice(engine.num_nodes, size=5, replace=False)
        out = engine.infer(ids)
        r, s = engine.rank_slot(ids)
        np.testing.assert_array_equal(out, full[r, s])
        assert not engine.degraded
        snap = engine.registry.snapshot()
        assert snap["counters"]["serve.infer_retries"] >= 1
        # a retry replays the cached executable — never a compile
        assert engine.recompiles_since_warmup() == 0
    finally:
        chaos.reset()
        engine.reset_degraded()


def test_engine_degrades_after_repeated_failures_and_resets(serving, rng):
    from dgraph_tpu import chaos

    engine, *_ = serving
    assert engine.degrade_after == 3 and engine.max_retries == 2
    try:
        chaos.arm("serve.infer=raise@0:count=1000")  # every attempt fails
        for _ in range(engine.degrade_after):
            with pytest.raises(chaos.ChaosFault):
                engine.infer(rng.choice(engine.num_nodes, size=3, replace=False))
        assert engine.degraded
        # degraded: shed fast with the structured backpressure error, no
        # device dispatch at all
        with pytest.raises(QueueFull) as ei:
            engine.infer(np.arange(3))
        rec = ei.value.record()
        assert rec["degraded"] is True and rec["error"] == "backpressure"
        snap = engine.registry.snapshot()
        assert snap["gauges"]["serve.degraded"] == 1.0
        assert snap["counters"]["serve.shed_degraded"] >= 1
        # the health record carries the state
        from dgraph_tpu.serve.health import serve_health_record

        assert serve_health_record(engine)["degraded"] is True

        # operator re-admits; the fault is gone; traffic flows again
        chaos.disarm()
        engine.reset_degraded()
        out = engine.infer(np.arange(4))
        assert out.shape[0] == 4
        assert serve_health_record(engine)["degraded"] is False
        assert engine.recompiles_since_warmup() == 0
    finally:
        chaos.reset()
        engine.reset_degraded()


# ---------------------------------------------------------------------------
# corruption tolerance: checkpoint fallback + plan-cache rebuild
# ---------------------------------------------------------------------------


def _truncate_tree(root: str, keep_bytes: int = 3) -> int:
    n = 0
    for p in glob.glob(os.path.join(root, "**", "*"), recursive=True):
        if os.path.isfile(p):
            with open(p, "r+b") as f:
                f.truncate(keep_bytes)
            n += 1
    return n


def test_restore_checkpoint_falls_back_over_corrupt_step(tmp_path, caplog):
    from dgraph_tpu.train.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    ckpt = str(tmp_path / "ckpt")
    template = {"params": {"w": np.zeros(3, np.float32)}, "step": 0}
    save_checkpoint(ckpt, {"params": {"w": np.ones(3, np.float32)}, "step": 1}, 1)
    save_checkpoint(ckpt, {"params": {"w": np.full(3, 2.0, np.float32)}, "step": 2}, 2)
    # intact: newest wins
    got = restore_checkpoint(ckpt, template)
    assert got["step"] == 2

    # newest step corrupted mid-save: restore logs and falls back to step 1
    assert _truncate_tree(str(tmp_path / "ckpt" / "step_00000002")) > 0
    with caplog.at_level("WARNING", logger="dgraph_tpu.checkpoint"):
        got = restore_checkpoint(ckpt, template)
    assert got["step"] == 1
    np.testing.assert_array_equal(np.asarray(got["params"]["w"]), np.ones(3))
    assert any("falling back" in r.message for r in caplog.records)

    # an explicitly NAMED step is strict: fallback would silently hand back
    # different state than the one named, mislabeling downstream metrics —
    # corrupt raises the underlying error, absent raises FileNotFoundError
    with pytest.raises(Exception):
        restore_checkpoint(ckpt, template, step=2)
    with pytest.raises(FileNotFoundError):
        restore_checkpoint(ckpt, template, step=7)
    got = restore_checkpoint(ckpt, template, step=1)  # readable name is fine
    assert got["step"] == 1

    # every step corrupt: the error propagates (silent fresh-start is worse)
    _truncate_tree(str(tmp_path / "ckpt" / "step_00000001"))
    with pytest.raises(Exception):
        restore_checkpoint(ckpt, template)
    # empty dir is still a clean None (no checkpoint vs broken checkpoint)
    assert restore_checkpoint(str(tmp_path / "nothing"), template) is None


def test_restore_checkpoint_quarantines_corrupt_step(tmp_path, caplog):
    # a known-bad step is renamed to <step>.corrupt so it is read (and
    # warned about) exactly ONCE — never silently re-read on every
    # subsequent load — and leaves the resume candidate set
    from dgraph_tpu.train.checkpoint import (
        all_steps,
        quarantined_steps,
        restore_checkpoint,
        save_checkpoint,
    )

    ckpt = str(tmp_path / "ckpt")
    template = {"params": {"w": np.zeros(3, np.float32)}, "step": 0}
    save_checkpoint(ckpt, {"params": {"w": np.ones(3, np.float32)},
                           "step": 1}, 1)
    save_checkpoint(ckpt, {"params": {"w": np.full(3, 2.0, np.float32)},
                           "step": 2}, 2)
    assert _truncate_tree(str(tmp_path / "ckpt" / "step_00000002")) > 0

    with caplog.at_level("WARNING", logger="dgraph_tpu.checkpoint"):
        got = restore_checkpoint(ckpt, template)
    assert got["step"] == 1
    assert sum("quarantined" in r.message for r in caplog.records) == 1
    # the rename is what makes "log once" true
    assert os.path.isdir(str(tmp_path / "ckpt" / "step_00000002.corrupt"))
    assert all_steps(ckpt) == [1]
    assert quarantined_steps(ckpt) == [2]

    # the second load never touches the bad step again: no new warning
    caplog.clear()
    with caplog.at_level("WARNING", logger="dgraph_tpu.checkpoint"):
        got = restore_checkpoint(ckpt, template)
    assert got["step"] == 1
    assert not caplog.records

    # quarantine is reversible: rename back -> a resume candidate again
    os.rename(str(tmp_path / "ckpt" / "step_00000002.corrupt"),
              str(tmp_path / "ckpt" / "step_00000002"))
    assert all_steps(ckpt) == [1, 2] and quarantined_steps(ckpt) == []

    # an explicitly NAMED step never quarantines: the failure may be a
    # template mismatch, and destroying evidence for a mislabeled read
    # would be worse than the retry
    with pytest.raises(Exception):
        restore_checkpoint(ckpt, template, step=2)
    assert all_steps(ckpt) == [1, 2]

    # ALL steps failing is likely systematic (template mismatch, broken
    # reader): nothing is quarantined — only a SUCCESSFUL older restore
    # proves the failures were genuine corruption
    _truncate_tree(str(tmp_path / "ckpt" / "step_00000001"))
    with pytest.raises(Exception):
        restore_checkpoint(ckpt, template)
    assert all_steps(ckpt) == [1, 2] and quarantined_steps(ckpt) == []


def test_cached_edge_plan_rebuilds_truncated_pickle(tmp_path, caplog):
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    cache = str(tmp_path / "plans")
    edge_index = np.array([[0, 1, 2, 3], [2, 3, 3, 0]])
    part = np.array([0, 0, 1, 1])
    plan1, _ = cached_edge_plan(cache, edge_index, part, world_size=2,
                                pad_multiple=1)
    # v8 sharded artifact: plan_<key>/ holds per-rank shard pickles + a
    # checksummed manifest; a torn shard rebuilds JUST that shard
    (plan_dir,) = glob.glob(os.path.join(cache, "plan_*"))
    pkl = os.path.join(plan_dir, "shard_0001.pkl")
    with open(pkl, "r+b") as f:
        f.truncate(7)  # torn write / killed mid-copy
    with caplog.at_level("WARNING", logger="dgraph_tpu.checkpoint"):
        plan2, _ = cached_edge_plan(cache, edge_index, part, world_size=2,
                                    pad_multiple=1)
    assert any("rebuilding" in r.getMessage() and "shard 1" in r.getMessage()
               for r in caplog.records)
    np.testing.assert_array_equal(plan1.src_index, plan2.src_index)
    np.testing.assert_array_equal(plan1.edge_mask, plan2.edge_mask)
    # the rebuild repaired the cache in place: third load is a clean hit
    plan3, _ = cached_edge_plan(cache, edge_index, part, world_size=2,
                                pad_multiple=1)
    np.testing.assert_array_equal(plan1.src_index, plan3.src_index)


# ---------------------------------------------------------------------------
# CLI selftest smoke (tier-1: the whole serving path on every run)
# ---------------------------------------------------------------------------


def test_serve_selftest_cli(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.serve", "--selftest", "true",
         "--requests", "4", "--num_nodes", "250", "--max_bucket", "16",
         "--log_path", str(tmp_path / "serve.jsonl")],
        capture_output=True, text=True, timeout=540,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "serve_health"
    assert rec["recompiles_since_warmup"] == 0
    assert rec["error"] is None
    assert rec["latency_ms"]["count"] == 4
    # the JSONL artifact carries warmup + health + the structured
    # too-large rejection record
    lines = [
        json.loads(l)
        for l in open(tmp_path / "serve.jsonl")
        if l.startswith("{")
    ]
    kinds = [l.get("kind") for l in lines]
    assert "serve_warmup" in kinds and "serve_health" in kinds
    assert any(
        l.get("kind") == "serve_error" and l.get("error") == "too_large"
        for l in lines
    )
