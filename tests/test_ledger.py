"""Perf-trajectory ledger + drift sentinel: per-kind ingestion
normalizers (including the wedge-era probe stub and corrupt-line
skip-with-reason paths), the median+MAD tolerance math, the exact-class
zero-tolerance contract, the seeded-drift vacuity mutants, backfill over
the repo's real artifact corpus, and the CLI surfaces — the contracts
docs/perf-ledger.md documents. Everything here is compile-free."""

import json
import os
import subprocess
import sys

import pytest

from dgraph_tpu.obs import regress, report
from dgraph_tpu.obs.ledger import (
    DEFAULT_LEDGER_DIR,
    LEDGER_SCHEMA_VERSION,
    SERVE_HEALTH_SCHEMA_VERSION,
    TIER_KINDS,
    _fixture_bench_round,
    atomic_append_jsonl,
    backfill,
    ingest,
    ledger_path,
    maybe_ingest,
    normalize_record,
    read_ledger,
    resolve_ledger_dir,
    summarize,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# normalizers: one per record kind
# ---------------------------------------------------------------------------


def test_bench_round_normalizes_with_tiers_and_git_rev():
    entries, skips = normalize_record(_fixture_bench_round(), "BENCH_r06.json")
    assert not skips
    kinds = {e["kind"] for e in entries}
    assert {"bench_round", "schedule_drift", "cpu_scan_delta"} <= kinds
    head = next(e for e in entries if e["kind"] == "bench_round")
    assert head["metrics"]["epoch_time_ms"] == 400.0
    assert head["git_rev"] == "abc1234"
    assert head["schema"] == LEDGER_SCHEMA_VERSION
    # tiers inherit the round's commit (the bisect key travels with them)
    assert all(e["git_rev"] == "abc1234" for e in entries)


def test_probe_stub_ingests_as_probe_wedge():
    # the BENCH_r05 shape: the driver wrapper whose child never produced
    # JSON — wedge history is trajectory, never a crash or a silent drop
    stub = {"n": 5, "cmd": "timeout 1500 python bench.py", "rc": 3,
            "tail": "probe attempt 7 hung (wedged lease)", "parsed": None}
    entries, skips = normalize_record(stub, "BENCH_r05.json")
    assert not skips and len(entries) == 1
    (e,) = entries
    assert e["kind"] == "probe_wedge" and e["round"] == 5
    assert "wedged lease" in e["meta"]["last_line"]


def test_structured_null_round_is_probe_wedge_but_tiers_survive():
    # the r03/r04 shape: parsed JSON with value null + attached tiers —
    # the round is wedge history but its fallback tiers are real signal
    obj = {"n": 4, "cmd": "python bench.py", "rc": 3, "tail": "",
           "parsed": dict(_fixture_bench_round(), value=None,
                          vs_baseline=None,
                          error="backend never initialized; wedged lease")}
    entries, _ = normalize_record(obj, "BENCH_r04.json")
    kinds = [e["kind"] for e in entries]
    assert "probe_wedge" in kinds and "bench_round" not in kinds
    assert "schedule_drift" in kinds and "cpu_scan_delta" in kinds


def test_multichip_tail_parses_families():
    obj = {"n": 3, "n_devices": 8, "ok": True, "rc": 0,
           "tail": ("dryrun GCN OK: step_ms=12.5\n"
                    "dryrun GraphTransformer OK:\n"
                    "dryrun dryrun_multichip OK:\n")}
    entries, skips = normalize_record(obj, "MULTICHIP_r03.json")
    assert not skips and len(entries) == 1
    m = entries[0]["metrics"]
    assert m["n_families"] == 2 and m["step_ms/GCN"] == 12.5
    assert "step_ms/GraphTransformer" not in m  # untimed dryrun
    assert entries[0]["meta"]["families"] == ["GCN", "GraphTransformer"]


def test_tune_record_and_serve_health_normalize():
    tune = {"kind": "tune_record", "record_id": "sig-abc", "phase": "train",
            "created_at": "2026-08-01T00:00:00Z",
            "config": {"halo_impl": "overlap", "pad_multiple": 8},
            "cost": {"step_ms": 12.0}}
    (e,), skips = normalize_record(tune, "tune_sig.json")
    assert not skips
    assert e["kind"] == "tune_record" and e["halo_impl"] == "overlap"
    assert e["workload"] == "sig-abc" and e["metrics"]["step_ms"] == 12.0

    serve = regress._fx_serve(0)
    (e,), skips = normalize_record(serve, "serve.jsonl")
    assert not skips
    assert e["kind"] == "serve_health"
    assert e["metrics"]["p99_ms"] == 50.0
    assert e["metrics"]["infer_p99_ms"] == 8.0
    assert e["metrics"]["recompiles_since_warmup"] == 0


def test_serve_health_newer_schema_skips_with_reason():
    serve = dict(regress._fx_serve(0),
                 schema_version=SERVE_HEALTH_SCHEMA_VERSION + 1)
    entries, skips = normalize_record(serve, "serve.jsonl")
    assert not entries and len(skips) == 1
    assert "newer than supported" in skips[0]["reason"]


def test_lineage_and_run_health_normalize():
    lineage = {"kind": "supervise_lineage", "restarts": 2, "gave_up": False,
               "final_exit_code": 0, "attempts": [{}, {}, {}],
               "run_health": {"wall_s": 30.0, "wedge": "none",
                              "git_rev": "rev9", "started_at": "t"}}
    (e,), skips = normalize_record(lineage, "logs/supervise.jsonl")
    assert not skips
    assert e["kind"] == "supervise_lineage" and e["git_rev"] == "rev9"
    assert e["metrics"]["restarts"] == 2 and e["metrics"]["attempts"] == 3

    rh = {"kind": "run_health", "component": "serve.engine", "wall_s": 1.0,
          "probes": [{}], "wedge": "none", "started_at": "t"}
    (e,), skips = normalize_record(rh, "logs/serve.jsonl")
    assert not skips
    assert e["kind"] == "run_health" and e["workload"] == "serve.engine"


def test_unrecognized_and_declined_payloads_skip_with_reason():
    entries, skips = normalize_record({"surprise": True}, "mystery.json")
    assert not entries and "unrecognized" in skips[0]["reason"]
    entries, skips = normalize_record({"kind": "span"}, "spans.jsonl")
    assert not entries and "high-volume" in skips[0]["reason"]
    entries, skips = normalize_record([1, 2, 3], "list.json")
    assert not entries and "not an object" in skips[0]["reason"]


# ---------------------------------------------------------------------------
# store: append durability, dedup, torn lines, the env knob
# ---------------------------------------------------------------------------


def test_ingest_is_idempotent_and_reads_back(tmp_path):
    d = str(tmp_path)
    r = ingest(_fixture_bench_round(), "BENCH_r06.json", d)
    assert r["appended"] >= 3 and r["deduped"] == 0
    r2 = ingest(_fixture_bench_round(), "BENCH_r06.json", d)
    assert r2["appended"] == 0 and r2["deduped"] == r["appended"]
    entries, skips = read_ledger(d)
    assert len(entries) == r["appended"] and not skips
    ids = [e["entry_id"] for e in entries]
    assert len(set(ids)) == len(ids)


def test_torn_trailing_line_skipped_earlier_entries_intact(tmp_path):
    d = str(tmp_path)
    ingest(_fixture_bench_round(), "BENCH_r06.json", d)
    n = len(read_ledger(d)[0])
    with open(ledger_path(d), "a") as fh:
        fh.write('{"schema": 1, "kind": "bench_ro')  # crash mid-append
    entries, skips = read_ledger(d)
    assert len(entries) == n and len(skips) == 1
    assert "torn" in skips[0]["reason"]
    # the next durable append lands on its own line regardless
    atomic_append_jsonl(ledger_path(d), [{"entry_id": "x", "kind": "t"}])
    entries, skips = read_ledger(d)
    assert len(entries) == n + 1 and len(skips) == 1


def test_ledger_dir_knob(tmp_path, monkeypatch):
    monkeypatch.delenv("DGRAPH_LEDGER_DIR", raising=False)
    assert resolve_ledger_dir(default_on=True) == DEFAULT_LEDGER_DIR
    assert resolve_ledger_dir(default_on=False) is None
    for off in ("0", "off", "none", ""):
        monkeypatch.setenv("DGRAPH_LEDGER_DIR", off)
        assert resolve_ledger_dir(default_on=True) is None
    monkeypatch.setenv("DGRAPH_LEDGER_DIR", str(tmp_path))
    assert resolve_ledger_dir() == str(tmp_path)
    # maybe_ingest honors the knob and swallows bad payloads
    assert maybe_ingest(_fixture_bench_round(), "t")["appended"] >= 3
    assert maybe_ingest(object(), "t") is not None  # skip, not crash
    monkeypatch.setenv("DGRAPH_LEDGER_DIR", "off")
    assert maybe_ingest(_fixture_bench_round(), "t", default_on=True) is None


def test_default_dir_matches_tune_record_dir(monkeypatch):
    # the ledger may not import tune.record (jax-free contract), so the
    # "artifacts that travel together live together" dir is a duplicated
    # literal — this pin is what keeps the two from drifting apart
    monkeypatch.delenv("DGRAPH_TUNE_DIR", raising=False)
    from dgraph_tpu.tune.record import default_record_dir

    assert DEFAULT_LEDGER_DIR == default_record_dir()


def test_serve_health_writer_shares_schema_constant():
    # serve/health.py stamps the SAME constant the normalizer validates —
    # read the source rather than build an engine (compile-free suite)
    src = open(os.path.join(REPO, "dgraph_tpu", "serve", "health.py")).read()
    assert "SERVE_HEALTH_SCHEMA_VERSION" in src
    assert '"schema_version": SERVE_HEALTH_SCHEMA_VERSION' in src


# ---------------------------------------------------------------------------
# sentinel: tolerance math + verdict classes
# ---------------------------------------------------------------------------


def test_baseline_stats_median_mad_math():
    s = regress.baseline_stats([20.0, 20.4, 19.8, 20.1, 20.3])
    assert s["median"] == pytest.approx(20.1)
    assert s["mad"] == pytest.approx(0.2)
    # REL_FLOOR dominates here: max(4*1.4826*0.2, 0.25*20.1, 0.5)
    assert s["tolerance"] == pytest.approx(0.25 * 20.1)
    # and the MAD term dominates for a noisy series
    s = regress.baseline_stats([10.0, 14.0, 6.0, 18.0, 2.0])
    assert s["mad"] == pytest.approx(4.0)
    assert s["tolerance"] == pytest.approx(4.0 * 1.4826 * 4.0)


def test_metric_class_partition():
    assert regress.metric_class("traced_bytes") == "exact"
    assert regress.metric_class("collective_count") == "exact"
    assert regress.metric_class("identical") == "exact"
    assert regress.metric_class("recompiles_since_warmup") == "exact"
    assert regress.metric_class("step_ms/GCN") == "timing"
    assert regress.metric_class("p99_ms") == "timing"
    assert regress.metric_class("vs_baseline") == "timing"
    assert regress.metric_class("wall_s") == "info"
    assert regress.metric_class("rc") == "info"


def test_exact_class_zero_tolerance(tmp_path):
    # +64 bytes is ~1.6% — invisible to any percentage gate; the exact
    # class must go RED on ANY change, which is the whole point of it
    d = str(tmp_path)
    regress._seed(d)
    ingest(regress._fx_round(6, traced_bytes=4096 + 64), "r06", d)
    rep = regress.check_ledger(d)
    reds = [v for v in rep["verdicts"] if v["verdict"] == "RED"]
    assert not rep["ok"]
    assert any(v["metric"] == "traced_bytes" and "zero tolerance"
               in v["reason"] for v in reds)
    # every RED names the offending ledger entry
    assert all(v["entry_id"] for v in reds)


def test_timing_class_tolerates_jitter_but_not_regression(tmp_path):
    d = str(tmp_path)
    regress._seed(d)
    rep = regress.check_ledger(d)
    assert rep["ok"] and rep["counts"]["RED"] == 0
    assert rep["counts"]["GREEN"] >= 8  # the gate is not vacuous
    # a within-tolerance wobble stays GREEN
    ingest(regress._fx_round(6, exchange_ms=21.0), "r06", d)
    assert regress.check_ledger(d)["ok"]
    # a real regression goes RED
    ingest(regress._fx_round(7, exchange_ms=36.0), "r07", d)
    rep = regress.check_ledger(d)
    assert not rep["ok"]
    assert any(v["metric"] == "exchange_ms" and v["verdict"] == "RED"
               for v in rep["verdicts"])


def test_no_baseline_verdict_below_min_points(tmp_path):
    d = str(tmp_path)
    for i in range(2):  # 1 prior point < MIN_TIMING_BASELINE
        ingest(regress._fx_serve(i), f"serve_r{i:02d}", d)
    rep = regress.check_ledger(d)
    nb = [v for v in rep["verdicts"] if v["verdict"] == "NO_BASELINE"]
    assert rep["ok"] and any(v["metric"] == "p99_ms" for v in nb)


def test_dropped_tier_goes_red(tmp_path):
    d = str(tmp_path)
    regress._seed(d)
    ingest(regress._fx_round(6, include_hlo=False), "r06", d)
    rep = regress.check_ledger(d)
    hit = next(v for v in rep["verdicts"]
               if v["metric"] == "fallback_tiers")
    assert hit["verdict"] == "RED" and "hlo_drift" in hit["reason"]
    assert set(TIER_KINDS) >= set(hit["baseline"]["tiers"])


def test_seeded_drift_selftests_pass():
    # the vacuity guards themselves: ledger fixtures, the four drift
    # mutants (each must go RED), and the report render pins
    from dgraph_tpu.obs import ledger

    assert ledger._selftest()["ok"]
    assert regress._selftest()["ok"]
    assert report._selftest()["ok"]


# ---------------------------------------------------------------------------
# backfill + report over the REAL artifact corpus
# ---------------------------------------------------------------------------


def test_backfill_real_corpus_and_report(tmp_path):
    d = str(tmp_path)
    rep = backfill(REPO, d)
    assert rep["files"] >= 11  # BASELINE + BENCH_r* + MULTICHIP_r*
    assert rep["appended"] >= 10
    s = summarize(d)
    # the wedge history (r01-r05) and the round-1 number are BOTH there
    assert s["by_kind"]["probe_wedge"] >= 4
    assert s["by_kind"]["bench_round"] >= 1
    entries, _ = read_ledger(d)
    baseline = next(e for e in entries if e["kind"] == "bench_round")
    assert baseline["metrics"]["epoch_time_ms"] == pytest.approx(
        456.898, abs=0.01)
    # idempotent: a second run appends nothing
    rep2 = backfill(REPO, d)
    assert rep2["appended"] == 0 and rep2["deduped"] == rep["appended"]
    # the real corpus gates GREEN (no synthetic drift in history)
    assert regress.check_ledger(d)["ok"]
    # and the trajectory renders the north-star number
    md = report.render_trajectory(entries, directory=d)
    assert "## Bench rounds" in md and "456.9" in md
    assert "WEDGED" in md  # the wedge history is visible, not elided


# ---------------------------------------------------------------------------
# CLI smokes (subprocesses kept to the compile-free minimum)
# ---------------------------------------------------------------------------


def _run_cli(args, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-m"] + args, cwd=REPO, env=env,
        capture_output=True, text=True, timeout=180,
    )


def test_cli_backfill_regress_report_roundtrip(tmp_path):
    d = str(tmp_path / "ledger")
    p = _run_cli(["dgraph_tpu.obs.ledger", "--backfill", REPO, "--dir", d])
    assert p.returncode == 0, p.stderr
    assert json.loads(p.stdout.splitlines()[-1])["appended"] >= 10

    log = str(tmp_path / "regress.jsonl")
    p = _run_cli(["dgraph_tpu.obs.regress", "--dir", d,
                  "--log_path", log])
    assert p.returncode == 0, p.stderr
    out = json.loads(p.stdout.splitlines()[-1])
    assert out["ok"] and out["kind"] == "regress_report"
    # RunHealth + report JSONL landed (the every-exit-path contract)
    lines = [json.loads(x) for x in open(log)]
    assert [x["kind"] for x in lines] == ["run_health", "regress_report"]

    md_path = str(tmp_path / "TRAJECTORY.md")
    p = _run_cli(["dgraph_tpu.obs.report", "--dir", d, "--out", md_path])
    assert p.returncode == 0, p.stderr
    assert "456.9" in open(md_path).read()


def test_cli_regress_exits_nonzero_on_red(tmp_path):
    d = str(tmp_path)
    regress._seed(d)
    ingest(regress._fx_round(6, exchange_ms=36.0), "r06", d)
    log = str(tmp_path / "regress.jsonl")
    p = _run_cli(["dgraph_tpu.obs.regress", "--dir", d, "--log_path", log])
    assert p.returncode == 1
    out = json.loads(p.stdout.splitlines()[-1])
    assert not out["ok"] and out["counts"]["RED"] >= 1
    # the log still landed on the failing path
    assert [json.loads(x)["kind"] for x in open(log)] == [
        "run_health", "regress_report"]
