"""Shrink-to-fit elastic recovery (train/shrink.py + partition.fold_partition
+ plan.reshard_vertex_data + supervise_group): deterministic world folds,
vertex-identity-preserving checkpoint resharding, atomic world adoption —
and THE rank-kill acceptance pin: a chaos-killed rank mid-epoch is
detected by membership within the lease deadline, the world shrinks
W -> W-1 through a background re-plan, and the resumed degraded run is
bit-identical (params + opt_state) to a fault-free W-1 run restored from
the same checkpoint.

Compile-free throughout: host numpy state, the streaming (numpy) plan
builder, python subprocess workers that never jit — tier-1 is
compile-dominated and near its budget.
"""

import json
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

from dgraph_tpu.partition import fold_partition, renumber_contiguous

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_rank_worker.py")


# ---------------------------------------------------------------------------
# fold_partition: deterministic waterfill
# ---------------------------------------------------------------------------


def test_fold_partition_balances_and_compacts():
    part = np.array([0, 0, 0, 1, 1, 2, 2, 2, 3])
    new, survivor_map = fold_partition(part, 4, [1])
    assert survivor_map == {0: 0, 2: 1, 3: 2}
    # survivors keep their vertices under compacted ids
    assert list(new[[0, 1, 2]]) == [0, 0, 0]
    assert list(new[[5, 6, 7]]) == [1, 1, 1]
    assert new[8] == 2
    # orphans (vertices 3, 4) land on the LIGHTEST survivor (old rank 3)
    assert list(new[[3, 4]]) == [2, 2]
    counts = np.bincount(new, minlength=3)
    assert counts.max() - counts.min() <= 3


def test_fold_partition_deterministic_and_pure():
    rng = np.random.default_rng(7)
    part = rng.integers(0, 5, 200)
    a, _ = fold_partition(part, 5, [1, 3])
    b, _ = fold_partition(part, 5, [3, 1])  # order-insensitive
    np.testing.assert_array_equal(a, b)
    # every vertex assigned, ids compact
    assert set(np.unique(a)) <= set(range(3))


def test_fold_partition_rejects_bad_inputs():
    part = np.array([0, 1])
    with pytest.raises(ValueError):
        fold_partition(part, 2, [])
    with pytest.raises(ValueError):
        fold_partition(part, 2, [5])
    with pytest.raises(ValueError):
        fold_partition(part, 2, [0, 1])  # no survivors


# ---------------------------------------------------------------------------
# reshard_vertex_data: rows follow their vertex
# ---------------------------------------------------------------------------


def test_reshard_vertex_data_tracks_vertex_identity():
    from dgraph_tpu.plan import reshard_vertex_data, unshard_vertex_data

    rng = np.random.default_rng(0)
    old_counts = np.array([3, 2, 4])
    V = int(old_counts.sum())
    x = np.zeros((3, 5, 2))  # n_pad=5 > max count
    g = rng.normal(size=(V, 2))
    off = 0
    for r, c in enumerate(old_counts):
        x[r, :c] = g[off: off + c]
        off += c
    part = np.repeat(np.arange(3), old_counts)
    folded, _ = fold_partition(part, 3, [1])
    ren = renumber_contiguous(folded, 2)
    out = reshard_vertex_data(x, old_counts, ren.inv, ren.counts, 6)
    assert out.shape == (2, 6, 2)
    # unsharding the new world and undoing the renumber recovers g exactly
    back = unshard_vertex_data(out, ren.counts)
    np.testing.assert_array_equal(back[ren.perm], g)
    # pad rows stay zero
    for r, c in enumerate(ren.counts):
        assert np.all(out[r, c:] == 0)


# ---------------------------------------------------------------------------
# init_world / shrink_world: the generational transition
# ---------------------------------------------------------------------------


def _seed_rank_states(run_dir, gen, step):
    """Per-rank momentum states keyed by ORIGINAL vertex id."""
    from dgraph_tpu.plan import load_sharded_plan
    from dgraph_tpu.train import shrink
    from dgraph_tpu.train.checkpoint import save_checkpoint

    graph = np.load(shrink.graph_path(run_dir, gen))
    counts = graph["counts"]
    orig = graph["orig_ids"]
    offs = np.concatenate([[0], np.cumsum(counts)])
    plan, _ = load_sharded_plan(shrink.plan_dir(run_dir, gen),
                                load_layout=False)
    n_pad = int(plan.n_dst_pad)
    for r in range(len(counts)):
        w = np.zeros(n_pad, np.float64)
        w[: counts[r]] = orig[offs[r]: offs[r + 1]] + 1.0
        m = np.zeros((n_pad, 2), np.float64)
        m[: counts[r], 0] = orig[offs[r]: offs[r + 1]] * 10.0
        save_checkpoint(
            shrink.rank_ckpt_dir(run_dir, gen, r),
            {"state": {"params": {"w": w}, "opt_state": {"m": m},
                       "lr": 0.5},
             "step": step},
            step,
        )
    return n_pad


def test_shrink_world_reshards_and_adopts(tmp_path):
    from dgraph_tpu.plan import load_sharded_plan
    from dgraph_tpu.train import shrink
    from dgraph_tpu.train.checkpoint import restore_checkpoint

    rng = np.random.default_rng(1)
    n, W = 24, 3
    edges = rng.integers(0, n, (2, 60)).astype(np.int64)
    run = str(tmp_path / "run")
    rec = shrink.init_world(run, edges, n, W, pad_multiple=2, lease_s=1.0)
    assert rec["generation"] == 0 and rec["world_size"] == 3
    _seed_rank_states(run, 0, step=4)

    out = shrink.shrink_world(run, [1])
    assert out["generation"] == 1 and out["world_size"] == 2
    assert out["resume_step"] == 4
    assert out["lost_history"] == [
        {"generation": 0, "lost": [1], "resume_step": 4}
    ]
    # the pointer IS the adoption: a fresh read sees the new world
    assert shrink.read_world(run)["generation"] == 1

    g0, g1 = (np.load(shrink.graph_path(run, g)) for g in (0, 1))
    # every original vertex survives the fold exactly once
    assert sorted(g1["orig_ids"].tolist()) == sorted(g0["orig_ids"].tolist())
    plan1, _ = load_sharded_plan(shrink.plan_dir(run, 1), load_layout=False)
    assert plan1.world_size == 2
    offs1 = np.concatenate([[0], np.cumsum(g1["counts"])])
    for r in range(2):
        got = restore_checkpoint(shrink.rank_ckpt_dir(run, 1, r))
        assert int(got["step"]) == 4
        w = np.asarray(got["state"]["params"]["w"])
        orig_r = g1["orig_ids"][offs1[r]: offs1[r + 1]]
        np.testing.assert_array_equal(
            w[: g1["counts"][r]], orig_r + 1.0
        )
        assert np.all(w[g1["counts"][r]:] == 0)
        m = np.asarray(got["state"]["opt_state"]["m"])
        np.testing.assert_array_equal(
            m[: g1["counts"][r], 0], orig_r * 10.0
        )
        # replicated (non-vertex) leaves are carried over
        assert got["state"]["lr"] == 0.5


def test_reshard_states_handles_tuple_and_namedtuple_leaves():
    # optimizer states are (Named)tuples — immutable, so the reshard must
    # REBUILD trees rather than assign into them
    import collections

    from dgraph_tpu.train.shrink import _reshard_states

    Momenta = collections.namedtuple("Momenta", ["m", "count"])
    old_counts = np.array([2, 2])
    n_pad_old = 3

    def state(r):
        m = np.zeros(n_pad_old, np.float64)
        m[:2] = [10 * r, 10 * r + 1]
        return {"opt": Momenta(m=m, count=7), "inner": (m * 2, "tag")}

    part = np.repeat(np.arange(2), old_counts)
    folded, _ = fold_partition(part, 2, [1])
    ren = renumber_contiguous(folded, 1)
    out = _reshard_states(
        [state(0), state(1)], old_counts, n_pad_old,
        ren.inv, ren.counts, 4, 1,
    )
    (new_state,) = out
    assert isinstance(new_state["opt"], Momenta)
    assert new_state["opt"].count == 7
    assert isinstance(new_state["inner"], tuple)
    assert new_state["inner"][1] == "tag"
    got = new_state["opt"].m
    assert got.shape == (4,)
    # rows follow their vertex through the fold (orphans appended)
    np.testing.assert_array_equal(np.sort(got), np.sort(
        np.array([0.0, 1.0, 10.0, 11.0])))
    np.testing.assert_array_equal(new_state["inner"][0], got * 2)


def test_shrink_world_requires_consistent_cut(tmp_path):
    from dgraph_tpu.train import shrink

    rng = np.random.default_rng(2)
    edges = rng.integers(0, 16, (2, 30)).astype(np.int64)
    run = str(tmp_path / "run")
    shrink.init_world(run, edges, 16, 2, pad_multiple=2)
    # rank 1 never checkpointed: no step is durable on ALL ranks
    from dgraph_tpu.train.checkpoint import save_checkpoint

    save_checkpoint(shrink.rank_ckpt_dir(run, 0, 0),
                    {"state": {"w": np.zeros(4)}, "step": 1}, 1)
    with pytest.raises(shrink.ShrinkError) as ei:
        shrink.shrink_world(run, [1])
    assert "durable on all" in str(ei.value)
    # the old world stays adopted — the failed transition changed nothing
    assert shrink.read_world(run)["generation"] == 0


# ---------------------------------------------------------------------------
# THE acceptance pin: rank-kill -> detect -> shrink -> bit-identical resume
# ---------------------------------------------------------------------------


def _worker_argv_fn(run_dir, steps, sleep_s):
    def argv_for_rank(rank, world, attempt):
        return [sys.executable, WORKER, run_dir, str(steps), str(sleep_s)]

    return argv_for_rank


def _run_group(run_dir, steps, world, sleep_s, extra_env=None, **kw):
    from dgraph_tpu.train.supervise import supervise_group

    env = dict(extra_env or {})
    env.setdefault("DGRAPH_CHAOS", "")  # never inherit the pytest env's
    return supervise_group(
        _worker_argv_fn(run_dir, steps, sleep_s), world,
        backoff_s=0.05, rank_loss_grace_s=60.0, **{**kw, "env": env},
    )


def _global_oracle(orig_ids, num_steps):
    """The worker's per-vertex recurrence, computed globally: any wrong
    row anywhere in fold/renumber/reshard diverges from this."""
    g = orig_ids.astype(np.float64) + 1.0
    w = np.zeros_like(g)
    m = np.zeros_like(g)
    for _ in range(num_steps):
        m = 0.5 * m + g
        w = w + 0.25 * m
    return w, m


def test_e2e_rank_kill_detect_shrink_resume_bit_identical(tmp_path):
    """Kill rank 1 of a 2-rank world mid-epoch -> membership detects the
    loss within the lease deadline -> supervise_group runs the
    shrink-to-fit recovery (background re-plan at W=1 + checkpoint
    reshard + atomic adoption) -> the resumed 1-rank run completes and is
    BIT-IDENTICAL to a fault-free 1-rank run restored from the same
    post-shrink checkpoint."""
    from dgraph_tpu.train import shrink
    from dgraph_tpu.train.checkpoint import latest_step, restore_checkpoint

    rng = np.random.default_rng(5)
    # sized so the survivor is still mid-run when detection fires on ANY
    # machine: the background heartbeat thread keeps the lease alive
    # through arbitrarily slow steps (loaded tier-1 box), and the
    # survivor's remaining wall after the step-3 kill (≥ 27 * sleep_s ≈
    # 3.2 s) comfortably exceeds lease_s + one poll period
    n, W, steps, sleep_s = 16, 2, 30, 0.12
    edges = rng.integers(0, n, (2, 40)).astype(np.int64)
    run_a = str(tmp_path / "chaotic")
    shrink.init_world(run_a, edges, n, W, pad_multiple=2, lease_s=2.0)

    run_b = str(tmp_path / "oracle")
    snapshots = []

    def on_rank_loss(lost, world):
        rec = shrink.shrink_world(run_a, lost)
        # snapshot the freshly-adopted degraded world BEFORE anyone
        # resumes in it: the fault-free oracle runs from this exact state
        shutil.copytree(run_a, run_b)
        snapshots.append(rec)
        return rec["world_size"]

    lineage = _run_group(
        run_a, steps, W, sleep_s,
        extra_env={"DGRAPH_CHAOS": "step=sigterm@3:rank=1:attempt=0"},
        on_rank_loss=on_rank_loss,
    )
    assert lineage["final_exit_code"] == 0, json.dumps(lineage, indent=1)
    assert lineage["final_world_size"] == 1
    assert lineage["shrinks"] == [
        {"attempt": 0, "lost": [1], "old_world": 2, "new_world": 1}
    ]
    a0, a1 = lineage["attempts"]
    ranks0 = {r["rank"]: r for r in a0["ranks"]}
    # the killed rank crashed; the survivor DETECTED the loss (exit 19)
    assert ranks0[1]["outcome"] == "crashed"
    assert ranks0[0]["outcome"] == "rank_lost"
    assert ranks0[0]["exit_code"] == 19
    # detection bounded by the heartbeat deadline, not the grace ceiling:
    # the survivor outlives the killed rank by roughly (steps-to-lease +
    # lease + one poll + checkpoint), never the 60 s grace window — the
    # bound is RELATIVE to the kill because absolute wall time on a
    # saturated CI box includes multi-second interpreter startups
    detect_lag = ranks0[0]["wall_s"] - ranks0[1]["wall_s"]
    assert 0.0 < detect_lag < 40.0, (ranks0, detect_lag)
    assert a1["world_size"] == 1 and a1["outcome"] == "ok"
    # the resumed attempt started from the shrink's consistent cut
    resume_step = snapshots[0]["resume_step"]
    assert 1 <= resume_step < steps

    # the chaotic run's final state, from the degraded world's checkpoint
    final_a = restore_checkpoint(shrink.rank_ckpt_dir(run_a, 1, 0))
    assert int(final_a["step"]) == steps

    # fault-free W-1 oracle: the SAME post-shrink snapshot restored and
    # driven by the SAME step function the worker runs (imported, not
    # reimplemented) — replayed in-process so tier-1 doesn't pay a 4th
    # jax+orbax subprocess start for what is by construction identical
    # code on identical state
    from tests._rank_worker import make_step_fn

    got = restore_checkpoint(shrink.rank_ckpt_dir(run_b, 1, 0))
    assert int(got["step"]) == resume_step
    g1b = np.load(shrink.graph_path(run_b, 1))
    count_b = int(g1b["counts"][0])
    step_fn = make_step_fn(
        g1b["orig_ids"][:count_b], count_b,
        np.asarray(got["state"]["params"]["w"]).shape[0], 0.0,
    )
    state_b = {
        "params": {"w": np.asarray(got["state"]["params"]["w"])},
        "opt_state": {"m": np.asarray(got["state"]["opt_state"]["m"])},
    }
    for _ in range(resume_step, steps):
        state_b = step_fn(state_b)

    # THE pin: params + opt_state bit-identical
    np.testing.assert_array_equal(
        np.asarray(final_a["state"]["params"]["w"]),
        state_b["params"]["w"],
    )
    np.testing.assert_array_equal(
        np.asarray(final_a["state"]["opt_state"]["m"]),
        state_b["opt_state"]["m"],
    )

    # and CORRECT: the degraded world's rows match the global per-vertex
    # recurrence by original vertex id (a wrong reshard row diverges)
    g1 = np.load(shrink.graph_path(run_a, 1))
    count = int(g1["counts"][0])
    orig = g1["orig_ids"][:count]
    w_want, m_want = _global_oracle(orig, steps)
    np.testing.assert_allclose(
        np.asarray(final_a["state"]["params"]["w"])[:count], w_want,
        rtol=0, atol=0,
    )
    np.testing.assert_allclose(
        np.asarray(final_a["state"]["opt_state"]["m"])[:count], m_want,
        rtol=0, atol=0,
    )

    # the run artifacts record the fault: chaotic lineage's health env
    assert lineage["run_health"]["env"]["chaos"] in (
        None, "", "step=sigterm@3:rank=1:attempt=0",
    )
