"""Sharded-PARAMETER training: tensor-parallel weights stay sharded through
forward, backward, and the optimizer update — no device ever holds the full
weight. The memory story tensor parallelism exists for, executed end to end
(replicated-params loops like train/loop.py cover the other regime)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.parallel.tensor import (
    shard_columns,
    shard_rows,
    tensor_parallel_mlp,
)

W = 8
B, F, H = 8, 16, 64


def test_sharded_param_training_matches_dense(tensor_mesh8):
    """N steps of adam on sharded params == N steps on the dense params."""
    mesh = tensor_mesh8
    rng = np.random.default_rng(0)
    w1 = rng.standard_normal((F, H)).astype(np.float32) * 0.3
    w2 = rng.standard_normal((H, F)).astype(np.float32) * 0.3
    x = jnp.asarray(rng.standard_normal((B, F)), jnp.float32)
    tgt = jnp.asarray(rng.standard_normal((B, F)), jnp.float32)

    # ---- sharded run: params enter shard_map with P('tensor') specs and
    # are updated PER SHARD (grads of sharded params need no collective —
    # each shard's weight slice only ever touches its own activations) ----
    params = {
        "w1": jnp.asarray(shard_columns(w1, W)),  # [W, F, H/W]
        "w2": jnp.asarray(shard_rows(w2, W)),  # [W, H/W, F]
    }
    opt = optax.adam(1e-2)

    def shard_step(p, o, x, tgt):
        # per-shard loss/grad: the ONLY collective is the row-parallel psum
        # in the forward (+ its transpose); param grads stay sharded
        def lf(p):
            y = tensor_parallel_mlp(
                x, p["w1"][0], None, p["w2"][0], None, "tensor"
            )
            return ((y - tgt) ** 2).sum()

        loss, g = jax.value_and_grad(lf)(p)
        updates, o = opt.update(g, o, p)
        return optax.apply_updates(p, updates), o, loss

    # init the opt state on the HOST over the stacked [W, ...] params: its
    # moment leaves inherit the sharded shapes, scalars (adam's count) stay
    # replicated — per-leaf specs express exactly that
    o0 = opt.init(params)
    o_specs = jax.tree.map(
        lambda l: P("tensor") if getattr(l, "ndim", 0) > 0 else P(), o0
    )
    step = jax.shard_map(
        shard_step,
        mesh=mesh,
        in_specs=(P("tensor"), o_specs, P(), P()),
        out_specs=(P("tensor"), o_specs, P()),
        check_vma=False,
    )

    with jax.set_mesh(mesh):
        o = o0
        losses = []
        for _ in range(5):
            params, o, l = step(params, o, x, tgt)
            losses.append(float(l))

    # ---- dense oracle ----
    dp = {"w1": jnp.asarray(w1), "w2": jnp.asarray(w2)}
    dopt_state = opt.init(dp)

    @jax.jit
    def dense_step(p, o):
        def lf(p):
            y = jax.nn.silu(x @ p["w1"]) @ p["w2"]
            return ((y - tgt) ** 2).sum()

        loss, g = jax.value_and_grad(lf)(p)
        updates, o = opt.update(g, o, p)
        return optax.apply_updates(p, updates), o, loss

    dlosses = []
    for _ in range(5):
        dp, dopt_state, dl = dense_step(dp, dopt_state)
        dlosses.append(float(dl))

    np.testing.assert_allclose(losses, dlosses, rtol=2e-4)
    # final sharded weights == re-sharded dense weights
    np.testing.assert_allclose(
        np.asarray(params["w1"]), shard_columns(np.asarray(dp["w1"]), W),
        rtol=2e-4, atol=2e-5,
    )
    np.testing.assert_allclose(
        np.asarray(params["w2"]), shard_rows(np.asarray(dp["w2"]), W),
        rtol=2e-4, atol=2e-5,
    )
    assert losses[-1] < losses[0]
