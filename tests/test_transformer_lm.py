"""Sequence-sharded causal LM: single-device vs 8-way ring equivalence and
end-to-end training over the mesh (the long-context story, trainable)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.comm import Communicator
from dgraph_tpu.models.transformer import SeqTransformerLM

W = 8
T, V, L = 128, 17, 32  # sequence length, vocab, latent


def _mesh():
    devs = jax.devices()
    if len(devs) < W:
        pytest.skip(f"need {W} devices")
    return Mesh(np.array(devs[:W]), ("graph",))


def _induction_batch(rng, T, V):
    """Repeated random segment: tokens[t] = tokens[t - T//2] for t >= T//2,
    so a causal model can learn to copy — loss must fall well below the
    uniform baseline."""
    half = rng.integers(1, V, T // 2)
    return np.concatenate([half, half]).astype(np.int32)


def test_distributed_logits_match_single():
    mesh = _mesh()
    rng = np.random.default_rng(0)
    toks = jnp.asarray(_induction_batch(rng, T, V))
    pos = jnp.arange(T, dtype=jnp.int32)

    m1 = SeqTransformerLM(
        vocab=V, latent=L, comm=Communicator.init_process_group("single"),
        max_len=T,
    )
    params = m1.init(jax.random.key(0), toks, pos)
    ref = m1.apply(params, toks, pos)

    m8 = SeqTransformerLM(
        vocab=V, latent=L,
        comm=Communicator.init_process_group("tpu", world_size=W), max_len=T,
    )

    def body(tk, ps):
        return m8.apply(params, tk, ps)

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(P("graph"), P("graph")),
        out_specs=P("graph"),
    )
    with jax.set_mesh(mesh):
        got = fn(toks, pos)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=3e-4, atol=3e-4
    )


def test_trains_on_induction_task():
    mesh = _mesh()
    rng = np.random.default_rng(1)
    comm = Communicator.init_process_group("tpu", world_size=W)
    model = SeqTransformerLM(vocab=V, latent=L, comm=comm, max_len=T)
    pos = jnp.arange(T, dtype=jnp.int32)

    def shard_loss(params, toks, pos):
        logits = model.apply(params, toks, pos)
        # next-token prediction within the shard (skip the last local
        # position; boundary tokens are a (T_loc)^-1 fraction — fine for a
        # smoke task)
        logp = jax.nn.log_softmax(logits[:-1])
        ll = jnp.take_along_axis(logp, toks[1:, None], axis=1)[:, 0]
        return -jax.lax.psum(ll.sum(), "graph") / (T - W)

    def loss_fn(params, toks):
        fn = jax.shard_map(
            lambda p, tk, ps: shard_loss(p, tk, ps),
            mesh=mesh,
            in_specs=(P(), P("graph"), P("graph")),
            out_specs=P(),
            check_vma=False,
        )
        return fn(params, toks, pos)

    toks0 = jnp.asarray(_induction_batch(rng, T, V))
    with jax.set_mesh(mesh):
        params = jax.shard_map(
            lambda tk, ps: model.init(jax.random.key(0), tk, ps),
            mesh=mesh, in_specs=(P("graph"), P("graph")), out_specs=P(),
            check_vma=False,
        )(toks0, pos)
        opt = optax.adam(3e-3)
        opt_state = opt.init(params)

        @jax.jit
        def step(params, opt_state, toks):
            l, g = jax.value_and_grad(loss_fn)(params, toks)
            updates, opt_state = opt.update(g, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, l

        # fixed sequence: memorization drives loss far below the uniform
        # baseline quickly — the point is end-to-end gradient flow through
        # the ring (scan + ppermute transposes), not generalization
        losses = []
        for i in range(80):
            params, opt_state, l = step(params, opt_state, toks0)
            losses.append(float(l))

    uniform = np.log(V)
    assert losses[-1] < losses[0] * 0.5
    assert losses[-1] < uniform * 0.5, (losses[0], losses[-1], uniform)


def test_moe_ffn_trains_with_sharded_experts():
    """MoE FFN over the sequence axis (one expert per rank): params carry
    sharded [E,...] expert leaves, the step runs end-to-end under jit, the
    loss falls, and the aux loss flows (router gradient nonzero)."""
    mesh = _mesh()
    comm = Communicator.init_process_group("tpu", world_size=W)
    model = SeqTransformerLM(
        vocab=V, latent=L, num_layers=1, num_heads=4, max_len=T, comm=comm,
        moe_k=2,
    )
    rng = np.random.default_rng(0)
    toks = jnp.asarray(_induction_batch(rng, T, V))
    pos = jnp.arange(T, dtype=jnp.int32)

    from dgraph_tpu.models.transformer import moe_param_specs

    shapes = jax.eval_shape(
        jax.shard_map(
            lambda tk, ps: model.init(jax.random.key(0), tk, ps),
            mesh=mesh, in_specs=(P("graph"),) * 2, out_specs=P(),
            check_vma=False,
        ),
        toks, pos,
    )
    pspecs = moe_param_specs(shapes)
    # the expert leaves exist and are the sharded ones
    flat = jax.tree_util.tree_flatten_with_path(pspecs)[0]
    moe_specs = [s for p, s in flat if "moe_w" in "/".join(
        str(getattr(k, "key", k)) for k in p)]
    assert moe_specs and all(s == P("graph") for s in moe_specs)

    def shard_loss(params, tk, ps):
        logits, mut = model.apply(params, tk, ps, mutable=["losses"])
        aux = sum(jnp.sum(v) for v in jax.tree.leaves(mut))
        logp = jax.nn.log_softmax(logits[:-1])
        ll = jnp.take_along_axis(logp, tk[1:, None], axis=1)[:, 0]
        return -jax.lax.psum(ll.sum(), "graph") / (T - W) + 0.01 * aux

    loss_sm = jax.shard_map(
        shard_loss, mesh=mesh, in_specs=(pspecs, P("graph"), P("graph")),
        out_specs=P(), check_vma=False,
    )
    with jax.set_mesh(mesh):
        params = jax.shard_map(
            lambda tk, ps: model.init(jax.random.key(0), tk, ps),
            mesh=mesh, in_specs=(P("graph"),) * 2, out_specs=pspecs,
            check_vma=False,
        )(toks, pos)
        opt = optax.adam(3e-3)
        ost = opt.init(params)

        @jax.jit
        def step(p, o, tk):
            l, g = jax.value_and_grad(lambda p: loss_sm(p, tk, pos))(p)
            up, o = opt.update(g, o, p)
            return optax.apply_updates(p, up), o, l, g

        losses = []
        for i in range(30):
            params, ost, l, g = step(params, ost, toks)
            losses.append(float(l))
    # router gradient must be nonzero (the aux + gate product paths)
    router_g = [
        leaf for path, leaf in jax.tree_util.tree_flatten_with_path(g)[0]
        if "router" in "/".join(str(getattr(k, "key", k)) for k in path)
    ]
    assert router_g and float(sum(jnp.abs(r).sum() for r in router_g)) > 0
    assert losses[-1] < losses[0]
