"""Observability layer: static footprint accounting vs hand-computed and
traced byte counts, the step-metrics pipeline (including the disabled ==
zero-recompile invariant), JSONL schema round-trips, and RunHealth
classification — the contracts docs/observability.md documents."""

import json

import numpy as np
import pytest

from dgraph_tpu.obs import footprint as fp
from dgraph_tpu.obs.health import RunHealth, classify_wedge, startup_record
from dgraph_tpu.obs.metrics import Metrics, StepMetrics, step_record
from dgraph_tpu.plan import build_edge_plan


# ---------------------------------------------------------------------------
# footprint: hand-computed tiny plan
# ---------------------------------------------------------------------------


def _tiny_plan():
    # V=4 split [0,0 | 1,1]; edges (src->dst): 0->2, 1->3, 2->3, 3->0.
    # dst ownership: ranks own edges (r0: 3->0), (r1: the other three).
    # halo: r1 needs {0,1} from r0; r0 needs {3} from r1.
    edge_index = np.array([[0, 1, 2, 3], [2, 3, 3, 0]])
    part = np.array([0, 0, 1, 1])
    return build_edge_plan(edge_index, part, world_size=2, pad_multiple=1)


def test_footprint_tiny_plan_hand_computed():
    plan, layout = _tiny_plan()
    np.testing.assert_array_equal(layout.halo_counts, [[0, 2], [1, 0]])
    out = fp.plan_footprint(plan, "float32", feat_dim=4)

    row = 4 * 4  # feat_dim * f32
    assert out["world_size"] == 2 and out["s_pad"] == 2
    assert out["halo"]["real_rows_total"] == 3
    assert out["halo"]["real_bytes_total"] == 3 * row
    assert out["halo"]["per_shard_send_rows"] == [2, 1]
    assert out["halo"]["per_shard_recv_rows"] == [1, 2]
    assert out["halo"]["per_shard_send_bytes"] == [2 * row, 1 * row]
    # padded collective volumes: a2a operand [W=2, S=2, F=4] f32 per shard
    ex = out["collectives"]["halo_exchange"]
    assert ex["a2a_operand_bytes_per_shard"] == 2 * 2 * row
    assert out["halo"]["wire_bytes_per_shard"]["all_to_all"] == 1 * 2 * row
    # one live delta (both directions are (peer-rank) mod 2 == 1)
    assert out["num_halo_deltas"] == 1
    assert out["halo"]["wire_bytes_per_shard"]["ppermute"] == 1 * 2 * row
    assert ex["impl"] == "ppermute"  # 1 delta <= W/2
    # scatter's remote leg is the exact transpose
    assert out["collectives"]["halo_scatter_sum"] == ex
    # wire_efficiency (derived from send_mask) must equal plan_efficiency's
    # halo_wire_fill (derived from layout.halo_counts) — two data paths,
    # one published number
    from dgraph_tpu.plan import plan_efficiency

    eff = plan_efficiency(plan, layout)
    assert ex["wire_efficiency"] == pytest.approx(
        eff["halo_wire_fill_ppermute"], abs=1e-4
    )
    # lowering-aware HBM model: ppermute gathers/reads only the 1 live
    # delta's [S, F] block but still writes the full [W*S, F] halo buffer
    assert ex["hbm_bytes_per_shard"] == (2 * 1 + 2) * 2 * row
    assert ex["operand_bytes_per_shard"] == 1 * 2 * row  # one [S, F] round
    # edges: per-rank [1, 3] -> e_pad 3, max/mean imbalance 1.5
    assert out["e_pad"] == 3
    assert out["imbalance"]["edges"]["max_over_mean"] == pytest.approx(1.5)
    assert out["local_streams"]["edge_tensor_bytes"] == 3 * row
    # bf16 halves every byte figure
    out16 = fp.plan_footprint(plan, "bfloat16", feat_dim=4)
    assert out16["halo"]["real_bytes_total"] == out["halo"]["real_bytes_total"] // 2
    # the whole report is JSONL-able as-is
    json.dumps(out)


def test_footprint_honors_halo_impl_pin():
    """A DGRAPH_TPU_HALO_IMPL pin overrides the cost model at runtime, so
    the report must account the pinned lowering, not the auto pick."""
    from dgraph_tpu import config as cfg

    plan, _ = _tiny_plan()
    row = 4 * 4
    prev = cfg.halo_impl
    try:
        cfg.set_flags(halo_impl="all_to_all")
        out = fp.plan_footprint(plan, "float32", feat_dim=4)
        ex = out["collectives"]["halo_exchange"]
        assert ex["impl"] == "all_to_all"
        assert ex["operand_bytes_per_shard"] == 2 * 2 * row
        assert ex["ici_bytes_per_shard"] == 1 * 2 * row
        assert ex["hbm_bytes_per_shard"] == (2 * 2 + 2) * 2 * row
    finally:
        cfg.set_flags(halo_impl=prev)


def test_footprint_none_impl_matches_runtime_no_collective(mesh8):
    """Empty halo_deltas: footprint reports impl 'none' / 0 ICI bytes, and
    the runtime must agree by issuing NO collective at all (the exchange
    is identically zero) — report and execution cannot diverge."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm import collectives
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan

    # all edges rank-local under the contiguous block partition
    edge_index = np.array([[0, 1, 2, 3], [1, 0, 3, 2]])
    part = np.array([0, 0, 1, 1])
    plan, _ = build_edge_plan(edge_index, part, world_size=2, pad_multiple=1)
    assert plan.halo_deltas == ()
    out = fp.plan_footprint(plan, "float32", feat_dim=4)
    ex = out["collectives"]["halo_exchange"]
    assert ex["impl"] == "none"
    assert ex["ici_bytes_per_shard"] == 0 and ex["operand_bytes_per_shard"] == 0

    recorded = []
    orig = jax.lax.all_to_all

    def spy(x, *args, **kwargs):
        recorded.append(x.shape)
        return orig(x, *args, **kwargs)

    plan_dev = jax.tree.map(jnp.asarray, plan)
    devices = np.array(jax.devices()[:2]).reshape(1, 2)
    mesh = jax.sharding.Mesh(devices, ("replica", GRAPH_AXIS))

    def body(x, plan_):
        p = squeeze_plan(plan_)
        return collectives.halo_exchange(
            x[0], p.halo, GRAPH_AXIS, deltas=p.halo_deltas
        )[None]

    f = jax.jit(jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(GRAPH_AXIS), plan_in_specs(plan_dev)),
        out_specs=P(GRAPH_AXIS),
    ))
    x = jnp.ones((2, plan.n_src_pad, 4), jnp.float32)
    try:
        jax.lax.all_to_all = spy
        got = np.asarray(f(x, plan_dev))
    finally:
        jax.lax.all_to_all = orig
    assert not recorded, "impl 'none' still lowered a collective"
    assert (got == 0).all()


def test_footprint_psum_grad_sync_accounting():
    plan, _ = _tiny_plan()
    out = fp.plan_footprint(plan, "float32", feat_dim=4, param_count=1000)
    psum = out["collectives"]["psum_grad_sync"]
    # ring all-reduce at f32: 2 * (W-1)/W of the payload per member
    assert psum["payload_bytes"] == 4000
    assert psum["ici_bytes_per_shard"] == 4000  # 2 * 4000 * 1/2
    assert psum["roofline"]["bound"] in ("ici", "hbm")


def test_footprint_matches_traced_all_to_all_arxiv(mesh8):
    """Acceptance pin: on the bench's arxiv-shaped synthetic graph, the
    static per-collective byte totals must match the operand the lowered
    program actually hands to all_to_all within 5% (they are exact)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu import partition as pt
    from dgraph_tpu.comm import collectives
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan

    V, E_half, F = 169_343, 1_166_243, 128
    rng = np.random.default_rng(0)
    src = rng.integers(0, V, E_half)
    dst = rng.integers(0, V, E_half)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)
    new_edges, ren = pt.partition_graph(edge_index, V, 8, method="block")
    plan, _ = build_edge_plan(
        new_edges, ren.partition, world_size=8, pad_multiple=128,
        sort_route=False,
    )
    report = fp.plan_footprint(plan, "float32", feat_dim=F)
    assert report["collectives"]["halo_exchange"]["impl"] == "all_to_all"

    recorded = []
    orig = jax.lax.all_to_all

    def spy(x, *args, **kwargs):
        recorded.append(int(np.prod(x.shape)) * x.dtype.itemsize)
        return orig(x, *args, **kwargs)

    plan_dev = jax.tree.map(jnp.asarray, plan)

    def body(x, plan_):
        p = squeeze_plan(plan_)
        return collectives.halo_exchange(
            x[0], p.halo, GRAPH_AXIS, deltas=p.halo_deltas
        )[None]

    f = jax.jit(jax.shard_map(
        body, mesh=mesh8,
        in_specs=(P(GRAPH_AXIS), plan_in_specs(plan_dev)),
        out_specs=P(GRAPH_AXIS),
    ))
    x = jnp.zeros((8, plan.n_src_pad, F), jnp.float32)
    try:
        jax.lax.all_to_all = spy
        f.lower(x, plan_dev)  # trace only; the spy sees the real operand
    finally:
        jax.lax.all_to_all = orig

    assert recorded, "halo_exchange lowered without an all_to_all"
    measured = recorded[0]
    predicted = report["collectives"]["halo_exchange"][
        "a2a_operand_bytes_per_shard"
    ]
    assert abs(measured - predicted) / measured < 0.05, (measured, predicted)


def test_footprint_cli_prints_json(capsys):
    report = fp.main(fp.Config(
        nodes=256, edges=1024, world=4, pad_multiple=8, feat_dim=8, indent=0
    ))
    out = capsys.readouterr().out.strip()
    assert json.loads(out.splitlines()[-1]) == report


# ---------------------------------------------------------------------------
# metrics: step pipeline + registry
# ---------------------------------------------------------------------------


def _sbm_training(step_metrics, nonfinite_guard=False):
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models import GCN
    from dgraph_tpu.train.loop import init_params, make_train_step

    data = synthetic.sbm_classification_graph(
        num_nodes=200, num_classes=3, feat_dim=8, avg_degree=6.0
    )
    g = DistributedGraph.from_global(
        data["edge_index"], data["features"], data["labels"], data["masks"],
        world_size=8, partition_method="random",
    )
    mesh = make_graph_mesh(ranks_per_graph=8)
    comm = Communicator.init_process_group("tpu", world_size=8)
    model = GCN(8, 3, comm=comm, num_layers=2)
    batch = jax.tree.map(
        jnp.asarray, dict(g.batch("train"), y=g.labels)
    )
    plan = jax.tree.map(jnp.asarray, g.plan)
    params = init_params(model, mesh, plan, batch)
    opt = optax.adam(1e-2)
    opt_state = opt.init(params)
    step = make_train_step(
        model, opt, mesh, plan, donate=False, step_metrics=step_metrics,
        nonfinite_guard=nonfinite_guard,
    )
    return mesh, step, params, opt_state, batch, plan


def test_step_metrics_disabled_no_recompile(mesh8):
    """The build-time flag must add NOTHING when off: same legacy dict
    shape, and repeated same-shape calls hit the jit cache (exactly one
    compile)."""
    import jax

    mesh, step, params, opt_state, batch, plan = _sbm_training(False)
    with jax.set_mesh(mesh):
        # two warm calls reach the steady state (the first call's outputs
        # carry mesh shardings its uncommitted inputs did not, which is a
        # legitimate one-time second compile on any jitted step)
        params, opt_state, m = step(params, opt_state, batch, plan)
        params, opt_state, m = step(params, opt_state, batch, plan)
        warm = step._cache_size() if hasattr(step, "_cache_size") else None
        params, opt_state, m = step(params, opt_state, batch, plan)
        params, opt_state, m = step(params, opt_state, batch, plan)
    assert set(m.keys()) == {"loss", "accuracy"}
    if warm is not None:
        assert step._cache_size() == warm, "metrics-off step recompiled"


def test_step_metrics_enabled_pipeline(mesh8, tmp_path):
    """Enabled: StepMetrics comes back (dict-compatible), grad_norm and
    mask_count are real, and the record round-trips through ExperimentLog's
    JSONL."""
    import jax

    from dgraph_tpu.utils import ExperimentLog

    mesh, step, params, opt_state, batch, plan = _sbm_training(True)
    with jax.set_mesh(mesh):
        params, opt_state, m = step(params, opt_state, batch, plan)
    assert isinstance(m, StepMetrics)
    assert float(m["loss"]) > 0 and float(m.grad_norm) > 0
    assert float(m.mask_count) == float(np.asarray(batch["mask"]).sum())

    log = ExperimentLog(str(tmp_path / "log.jsonl"), echo=False)
    log.write(step_record(m, step=0, wall_ms=1.25))
    rec = json.loads(
        [l for l in open(log.path) if l.startswith("{")][-1]
    )
    assert rec["kind"] == "step" and rec["step"] == 0
    back = StepMetrics.from_record(rec)
    assert back.loss == pytest.approx(float(m.loss), rel=1e-6)
    assert back.grad_norm == pytest.approx(float(m.grad_norm), rel=1e-6)


def test_nonfinite_guard_skips_poisoned_step_zero_recompiles(mesh8):
    """The chaos acceptance pin for the guard: a host-poisoned (NaN) batch
    makes that step's grads non-finite; the guard carries params/opt_state
    forward, reports nonfinite_skipped=1, and — because the select is
    jnp.where inside the one traced program — the jit cache does NOT grow
    (a poisoned step replays the same executable)."""
    import jax

    from dgraph_tpu import chaos

    mesh, step, params, opt_state, batch, plan = _sbm_training(
        True, nonfinite_guard=True
    )
    with jax.set_mesh(mesh):
        # reach the jit steady state (the usual one-time second compile)
        params, opt_state, m = step(params, opt_state, batch, plan)
        params, opt_state, m = step(params, opt_state, batch, plan)
        assert float(m.nonfinite_skipped) == 0.0
        warm = step._cache_size() if hasattr(step, "_cache_size") else None
        before = jax.tree.map(np.asarray, params)

        bad = dict(batch, x=jax.numpy.asarray(chaos.poison_array(batch["x"])))
        params, opt_state, m = step(params, opt_state, bad, plan)
        assert float(m.nonfinite_skipped) == 1.0
        assert not np.isfinite(float(m.grad_norm))
        # carried forward bit-for-bit: the poisoned update never landed
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), b),
            params, before,
        )
        if warm is not None:
            assert step._cache_size() == warm, "poisoned step recompiled"

        # clean step afterwards applies normally again
        params, opt_state, m = step(params, opt_state, batch, plan)
        assert float(m.nonfinite_skipped) == 0.0
        changed = any(
            not np.array_equal(np.asarray(a), b)
            for a, b in zip(
                jax.tree.leaves(params), jax.tree.leaves(before)
            )
        )
        assert changed, "clean step after a skip did not update params"
        if warm is not None:
            assert step._cache_size() == warm


def test_nonfinite_guard_clean_run_matches_unguarded(mesh8):
    """identical results on clean runs: one guarded step from the same
    (params, opt_state, batch) produces the same params as the unguarded
    step — the guard may only ever *select*, never perturb."""
    import jax

    mesh, step_g, params, opt_state, batch, plan = _sbm_training(
        False, nonfinite_guard=True
    )
    _, step_u, _, _, _, _ = _sbm_training(False, nonfinite_guard=False)
    with jax.set_mesh(mesh):
        pg, og, mg = step_g(params, opt_state, batch, plan)
        pu, ou, mu = step_u(params, opt_state, batch, plan)
    assert set(mg.keys()) == {"loss", "accuracy", "nonfinite_skipped"}
    assert float(mg["nonfinite_skipped"]) == 0.0
    assert float(mg["loss"]) == pytest.approx(float(mu["loss"]), rel=1e-6)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        ),
        pg, pu,
    )


def test_step_record_schema_roundtrip():
    sm = StepMetrics(loss=1.5, accuracy=0.25, grad_norm=2.0, mask_count=10.0)
    rec = json.loads(json.dumps(sm.record(step=3, wall_ms=12.5)))
    assert rec["kind"] == "step" and rec["schema"] == 1
    assert StepMetrics.from_record(rec) == sm
    # None fields vanish from the record (and from_record tolerates that)
    rec2 = StepMetrics(loss=0.5).record(step=0)
    assert "grad_norm" not in rec2 and "accuracy" not in rec2
    assert StepMetrics.from_record(rec2).loss == 0.5
    with pytest.raises(ValueError):
        StepMetrics.from_record({"kind": "run_health"})


def test_histogram_quantiles_match_numpy():
    """The registry's quantile math (linear interpolation between order
    statistics) must agree with np.percentile's default method, so live
    snapshots and offline JSONL analysis publish the same numbers."""
    rng = np.random.default_rng(7)
    vals = rng.lognormal(3.0, 1.0, size=257)
    m = Metrics()
    for v in vals:
        m.histogram("lat_ms", v)
    for q in (0.0, 0.25, 0.5, 0.9, 0.95, 0.99, 1.0):
        assert m.quantile("lat_ms", q) == pytest.approx(
            float(np.percentile(vals, q * 100)), rel=1e-12
        )
    snap = m.snapshot()["histograms"]["lat_ms"]
    assert snap["p50"] == pytest.approx(float(np.percentile(vals, 50)))
    assert snap["p95"] == pytest.approx(float(np.percentile(vals, 95)))
    assert snap["p99"] == pytest.approx(float(np.percentile(vals, 99)))
    json.dumps(snap)


def test_histogram_quantile_edge_cases():
    m = Metrics()
    m.histogram("one", 42.0)
    # single observation: every quantile is that value
    for q in (0.0, 0.5, 0.99, 1.0):
        assert m.quantile("one", q) == 42.0
    # two observations: exact midpoint interpolation
    m.histogram("two", 10.0)
    m.histogram("two", 20.0)
    assert m.quantile("two", 0.5) == 15.0
    assert m.quantile("two", 0.25) == 12.5
    # insertion order must not matter (quantile sorts)
    m.histogram("rev", 5.0)
    m.histogram("rev", 1.0)
    m.histogram("rev", 3.0)
    assert m.quantile("rev", 0.5) == 3.0
    # errors: out-of-range q, never-observed name, empty histogram
    with pytest.raises(ValueError):
        m.quantile("one", 1.5)
    with pytest.raises(ValueError):
        m.quantile("one", -0.1)
    with pytest.raises(KeyError):
        m.quantile("never", 0.5)
    from dgraph_tpu.obs.metrics import _Histogram

    with pytest.raises(ValueError):
        _Histogram().quantile(0.5)
    assert _Histogram().snapshot() == {"count": 0}


def test_histogram_memory_bounded_reservoir():
    """Past MAX_SAMPLES observations the histogram must stop growing
    (serving records several per request, forever); count/mean/min/max stay
    exact and reservoir quantiles stay close on a uniform stream."""
    from dgraph_tpu.obs.metrics import _Histogram

    h = _Histogram()
    n = h.MAX_SAMPLES * 4
    for i in range(n):
        h.observe(float(i))
    assert len(h.values) == h.MAX_SAMPLES
    snap = h.snapshot()
    assert snap["count"] == n
    assert snap["min"] == 0.0 and snap["max"] == float(n - 1)
    assert snap["mean"] == pytest.approx((n - 1) / 2)
    # uniform stream: reservoir p50 within a few percent of the true median
    assert snap["p50"] == pytest.approx((n - 1) / 2, rel=0.05)


def test_metrics_registry():
    m = Metrics()
    m.counter("plans_built")
    m.counter("plans_built", 2)
    m.gauge("halo_fill", 0.75)
    for v in (1.0, 2.0, 3.0, 4.0):
        m.histogram("step_ms", v)
    snap = m.snapshot()
    assert snap["counters"]["plans_built"] == 3
    assert snap["gauges"]["halo_fill"] == 0.75
    h = snap["histograms"]["step_ms"]
    assert h["count"] == 4 and h["min"] == 1.0 and h["max"] == 4.0
    json.dumps(snap)
    m.reset()
    assert m.snapshot() == {"counters": {}, "gauges": {}, "histograms": {}}


# ---------------------------------------------------------------------------
# health: RunHealth + wedge classification + bench failure path
# ---------------------------------------------------------------------------


def test_run_health_roundtrip_and_wedge_classification():
    h = RunHealth.begin("bench.supervisor")
    h.record_probe(1, 150.2, "hang", "probe hung (wedged lease)")
    h.record_probe(2, 148.9, "hang", "probe hung (wedged lease)")
    d = h.finish("backend never initialized within 2 probes; wedged TPU lease")
    assert d["wedge"] == "init_wedge"
    assert d["schema"] == 1 and len(d["probes"]) == 2
    assert d["probes"][0]["outcome"] == "hang"
    back = RunHealth.from_dict(json.loads(json.dumps(d)))
    assert back.component == "bench.supervisor" and back.wedge == "init_wedge"

    # fail-fast probes (bad platform) are an init FAILURE, not a wedge
    probes_err = [{"attempt": 1, "outcome": "error"}]
    assert classify_wedge("backend never initialized within 1 probes",
                          probes_err) == "init_failure"
    assert classify_wedge(None) == "none"
    assert classify_wedge("watchdog: incomplete within 2400s") == \
        "watchdog_timeout"
    assert classify_wedge("bench child hung past its own watchdog; killed") \
        == "dispatch_wedge"
    assert classify_wedge("supervisor received signal 15") == "interrupted"
    # platform mismatch mentions 'wedged lease' but is a config problem,
    # not a wedge — waiting can never fix it
    assert classify_wedge(
        "backend is 'cpu', need 'tpu' (silent CPU fallback from a wedged "
        "lease?)") == "backend_lost"
    assert classify_wedge("gcn stage failed: RuntimeError: boom") == \
        "stage_failure"
    # interpolated exception text can contain wedge-ish words; the stage
    # anchor must win over the generic substring scans
    assert classify_wedge(
        "gcn stage failed: RuntimeError: collective hung after mesh sync"
    ) == "stage_failure"


def test_startup_record_has_backend_snapshot():
    rec = startup_record("experiments.test", snapshot_backend=True)
    assert rec["kind"] == "run_health"
    assert rec["backend"]["platform"] == "cpu"
    assert rec["backend"]["device_count"] == 8
    json.dumps(rec)
    # host-only flows never dial the accelerator
    rec2 = startup_record("experiments.plan_only", snapshot_backend=False)
    assert rec2["backend"] is None


def test_bench_failure_json_embeds_run_health():
    """bench.py's one failure-path schema must carry the RunHealth record
    (the acceptance pin for 'a null benchmark is diagnosable from the
    artifact alone') — exercised in-process, no subprocess needed."""
    import importlib.util
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "_bench_under_test", os.path.join(repo, "bench.py")
    )
    bench = importlib.util.module_from_spec(spec)
    sys.modules["_bench_under_test"] = bench
    spec.loader.exec_module(bench)
    try:
        h = bench._health_mod().RunHealth.begin("bench.supervisor")
        h.record_probe(1, 12.0, "hang", "probe hung (wedged lease)")
        bench._HEALTH = h
        out, rc = bench._failure_json(
            "backend never initialized within 1 probes; wedged TPU lease",
            {}, bench.EXIT_EMPTY,
        )
        assert rc == bench.EXIT_EMPTY
        parsed = json.loads(json.dumps(out))
        rh = parsed["run_health"]["supervisor"]
        assert rh["wedge"] == "init_wedge" and rh["probes"]
        assert parsed["value"] is None and "error" in parsed
    finally:
        bench._HEALTH = None
        sys.modules.pop("_bench_under_test", None)
