"""Crash-safe sharded plan artifacts (cache format v8): streaming per-rank
builds, integrity manifests, resume, and fault-tolerant loaders.

The acceptance pins for ISSUE 8:

- a chaos-injected SIGTERM mid-build (``plan.write=sigterm@k`` — the
  deterministic stand-in for the OOM-killer's SIGKILL) leaves a resumable
  manifest, and the resumed build is **bit-identical** to an uninterrupted
  one (shard pickles compared by SHA-256);
- a single corrupt / truncated / missing shard is detected by checksum and
  rebuilt **alone** (the durable shards are not rewritten), logged with
  which shard triggered it;
- a memory-budget violation raises a structured
  :class:`~dgraph_tpu.plan_shards.PlanBuildMemoryExceeded` instead of
  getting OOM-killed (the r5 papers100M failure mode, ROADMAP item 3).

Everything here is host-side numpy + subprocess orchestration — zero new
XLA compiles (tier-1 budget is compile-dominated; tests/README.md).
"""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _graph(seed=0, n=48, e=300, w=4):
    """Deterministic tiny synthetic graph with contiguous per-rank blocks
    (reproducible across processes — the kill-and-resume worker rebuilds
    the same graph from the same seed)."""
    rng = np.random.default_rng(seed)
    part = np.sort(rng.integers(0, w, n)).astype(np.int64)
    edges = rng.integers(0, n, (2, e)).astype(np.int64)
    return edges, part, w


def _assert_plans_equal(a, b):
    import dataclasses

    for f in dataclasses.fields(a):
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if va is None or vb is None:
            assert va is vb, f.name
        elif f.name == "halo":
            assert np.array_equal(va.send_idx, vb.send_idx), "halo.send_idx"
            assert np.array_equal(va.send_mask, vb.send_mask), "halo.send_mask"
            assert va.s_pad == vb.s_pad
        elif f.name == "overlap":
            for of in dataclasses.fields(va):
                assert np.array_equal(
                    np.asarray(getattr(va, of.name)),
                    np.asarray(getattr(vb, of.name)),
                ), f"overlap.{of.name}"
        elif isinstance(va, np.ndarray):
            assert np.array_equal(va, vb), f.name
        else:
            assert va == vb, f.name


def _shard_shas(plan_dir):
    import dgraph_tpu.plan_shards as ps

    man = ps.read_manifest(plan_dir)
    return {r: e["sha256"] for r, e in man["shards"].items()}


# ---------------------------------------------------------------------------
# bit-parity: streamed == monolithic
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("overlap,sort_route", [
    (False, False), (False, True), (True, False), (True, True),
])
def test_sharded_build_bit_identical_to_monolithic(
    tmp_path, overlap, sort_route
):
    from dgraph_tpu.plan import build_edge_plan, build_edge_plan_sharded

    edges, part, w = _graph()
    mono, mono_layout = build_edge_plan(
        edges, part, world_size=w, overlap=overlap, sort_route=sort_route,
        use_native=False,
    )
    plan, layout = build_edge_plan_sharded(
        edges, part, out_dir=str(tmp_path / "shards"), world_size=w,
        overlap=overlap, sort_route=sort_route, fingerprint="parity",
    )
    _assert_plans_equal(mono, plan)
    import dataclasses

    for f in dataclasses.fields(mono_layout):
        assert np.array_equal(
            np.asarray(getattr(mono_layout, f.name)),
            np.asarray(getattr(layout, f.name)),
        ), f.name


def test_native_core_rejected_in_streaming_mode(tmp_path):
    from dgraph_tpu.plan import build_plan_shards

    edges, part, w = _graph()
    with pytest.raises(ValueError, match="use_native"):
        build_plan_shards(
            edges, part, out_dir=str(tmp_path), world_size=w,
            use_native=True,
        )


# ---------------------------------------------------------------------------
# kill-and-resume (the acceptance pin): SIGTERM after 2 durable shards,
# resume from the manifest, bit-identical to an uninterrupted build
# ---------------------------------------------------------------------------

_BUILD_WORKER = """
import numpy as np
import sys
from dgraph_tpu.plan import build_plan_shards

rng = np.random.default_rng(0)
part = np.sort(rng.integers(0, 4, 48)).astype(np.int64)
edges = rng.integers(0, 48, (2, 300)).astype(np.int64)
build_plan_shards(
    edges, part, out_dir=sys.argv[1], world_size=4, fingerprint="killres",
)
print("BUILD_COMPLETE")
"""


def _run_build(out_dir, chaos=""):
    env = dict(os.environ)
    env["DGRAPH_CHAOS"] = chaos
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-c", _BUILD_WORKER, str(out_dir)],
        capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
    )


def test_kill_and_resume_bit_identical(tmp_path):
    import dgraph_tpu.plan_shards as ps
    from dgraph_tpu.plan import load_sharded_plan

    killed = tmp_path / "killed"
    clean = tmp_path / "clean"

    # chaos plan.write=sigterm@2: the process dies BEFORE writing shard 2,
    # with shards 0 and 1 already durable in the manifest
    r = _run_build(killed, chaos="plan.write=sigterm@2")
    assert r.returncode == -signal.SIGTERM, (r.returncode, r.stderr[-500:])
    assert "BUILD_COMPLETE" not in r.stdout
    man = ps.read_manifest(str(killed))
    assert not man["complete"]
    assert sorted(man["shards"]) == ["0", "1"]

    # the durable shards must survive the resume UNTOUCHED (resumed, not
    # rebuilt): pin their inode mtimes across the second run
    durable = {
        r2: os.path.getmtime(os.path.join(str(killed), e["file"]))
        for r2, e in man["shards"].items()
    }

    r = _run_build(killed)  # no chaos: resume from the manifest
    assert r.returncode == 0, r.stderr[-800:]
    assert "BUILD_COMPLETE" in r.stdout
    man = ps.read_manifest(str(killed))
    assert man["complete"] and sorted(man["shards"]) == ["0", "1", "2", "3"]
    for r2, mtime in durable.items():
        path = os.path.join(str(killed), man["shards"][r2]["file"])
        assert os.path.getmtime(path) == mtime, f"shard {r2} was rewritten"

    # uninterrupted reference build: every shard pickle bit-identical
    r = _run_build(clean)
    assert r.returncode == 0, r.stderr[-800:]
    assert _shard_shas(str(killed)) == _shard_shas(str(clean))
    pk, _ = load_sharded_plan(str(killed))
    pc, _ = load_sharded_plan(str(clean))
    _assert_plans_equal(pk, pc)


def test_in_process_resume_skips_durable_shards(tmp_path):
    """Same resume contract without subprocesses: a build interrupted by a
    chaos raise at rank 2 resumes past ranks 0-1."""
    from dgraph_tpu import chaos
    from dgraph_tpu.plan import build_plan_shards
    import dgraph_tpu.plan_shards as ps

    edges, part, w = _graph()
    out = str(tmp_path / "shards")
    chaos.arm("plan.build_shard=raise@2")
    try:
        with pytest.raises(chaos.ChaosFault):
            build_plan_shards(
                edges, part, out_dir=out, world_size=w, fingerprint="res",
            )
    finally:
        chaos.reset()
    man = ps.read_manifest(out)
    assert sorted(man["shards"]) == ["0", "1"] and not man["complete"]
    mtimes = {
        r: os.path.getmtime(os.path.join(out, e["file"]))
        for r, e in man["shards"].items()
    }
    manifest = build_plan_shards(
        edges, part, out_dir=out, world_size=w, fingerprint="res",
    )
    assert manifest["complete"]
    for r, t in mtimes.items():
        path = os.path.join(out, manifest["shards"][r]["file"])
        assert os.path.getmtime(path) == t, f"shard {r} was rewritten"


# ---------------------------------------------------------------------------
# fault-tolerant loaders: single-shard repair, full rebuild only when the
# manifest itself is gone
# ---------------------------------------------------------------------------


def _cached(cache_dir, **kw):
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    edges, part, w = _graph()
    return cached_edge_plan(str(cache_dir), edges, part, world_size=w, **kw)


def _plan_dir(cache_dir):
    (d,) = [
        os.path.join(str(cache_dir), x)
        for x in os.listdir(str(cache_dir)) if x.startswith("plan_")
    ]
    return d


def test_corrupt_shard_detected_and_rebuilt_alone(tmp_path, caplog):
    import dgraph_tpu.plan_shards as ps

    plan0, _ = _cached(tmp_path)
    d = _plan_dir(tmp_path)
    man = ps.read_manifest(d)
    victim = os.path.join(d, man["shards"]["2"]["file"])
    blob = bytearray(open(victim, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(victim, "wb").write(bytes(blob))
    others = {
        r: os.path.getmtime(os.path.join(d, e["file"]))
        for r, e in man["shards"].items() if r != "2"
    }

    with caplog.at_level("WARNING"):
        plan1, _ = _cached(tmp_path)
    _assert_plans_equal(plan0, plan1)
    # the log names the shard that triggered the repair...
    assert any(
        "shard 2" in rec.getMessage() for rec in caplog.records
    ), [r.getMessage() for r in caplog.records]
    # ...and the intact shards were not rewritten
    man = ps.read_manifest(d)
    for r, t in others.items():
        assert os.path.getmtime(os.path.join(d, man["shards"][r]["file"])) == t
    assert not ps.bad_shards(d, man)


def test_missing_shard_rebuilt_not_the_world(tmp_path, caplog):
    """A manifest that references shards deleted out from under it rebuilds
    the missing shards, not the world (the satellite fix)."""
    import dgraph_tpu.plan_shards as ps

    plan0, _ = _cached(tmp_path)
    d = _plan_dir(tmp_path)
    man = ps.read_manifest(d)
    os.unlink(os.path.join(d, man["shards"]["1"]["file"]))
    survivors = {
        r: os.path.getmtime(os.path.join(d, e["file"]))
        for r, e in man["shards"].items() if r != "1"
    }

    with caplog.at_level("WARNING"):
        plan1, _ = _cached(tmp_path)
    _assert_plans_equal(plan0, plan1)
    assert any(
        "shard 1" in rec.getMessage() for rec in caplog.records
    ), [r.getMessage() for r in caplog.records]
    man = ps.read_manifest(d)
    assert man["complete"] and not ps.bad_shards(d, man)
    for r, t in survivors.items():
        assert os.path.getmtime(os.path.join(d, man["shards"][r]["file"])) == t


def test_unreadable_manifest_degrades_to_full_rebuild(tmp_path):
    import dgraph_tpu.plan_shards as ps

    plan0, _ = _cached(tmp_path)
    d = _plan_dir(tmp_path)
    open(ps.manifest_path(d), "w").write("{ not json")
    plan1, _ = _cached(tmp_path)
    _assert_plans_equal(plan0, plan1)
    assert ps.read_manifest(d)["complete"]


def test_truncated_shard_detected_by_size(tmp_path):
    import dgraph_tpu.plan_shards as ps
    from dgraph_tpu.plan import load_sharded_plan

    _cached(tmp_path)
    d = _plan_dir(tmp_path)
    man = ps.read_manifest(d)
    victim = os.path.join(d, man["shards"]["0"]["file"])
    blob = open(victim, "rb").read()
    open(victim, "wb").write(blob[: len(blob) // 2])
    with pytest.raises(ps.PlanShardError) as ei:
        load_sharded_plan(d)
    assert ei.value.rank == 0
    assert ps.bad_shards(d, man) == {0: "truncated"}


def test_load_rank_subset_and_multihost_path(tmp_path):
    """Each-host-loads-its-shard: a rank subset's leading axis is
    len(ranks) while the statics still describe the full world."""
    from dgraph_tpu.comm.multihost import process_local_plan_shards
    from dgraph_tpu.plan import load_sharded_plan

    full, _ = _cached(tmp_path)
    d = _plan_dir(tmp_path)
    sub, layout = load_sharded_plan(d, ranks=[1, 3], load_layout=False)
    assert layout is None
    assert sub.src_index.shape[0] == 2
    assert sub.world_size == full.world_size == 4
    assert sub.e_pad == full.e_pad
    assert np.array_equal(sub.src_index[0], full.src_index[1])
    assert np.array_equal(sub.src_index[1], full.src_index[3])
    assert np.array_equal(sub.edge_mask[1], full.edge_mask[3])

    plan, ranks = process_local_plan_shards(d, ranks=[2])
    assert ranks == [2]
    assert np.array_equal(plan.dst_index[0], full.dst_index[2])


@pytest.mark.parametrize("w", [2, 4])
def test_rank_subset_view_bit_identical_to_full_world_slice(tmp_path, w):
    """The substrate assumption of the cross-rank SPMD auditor
    (``analysis.spmd``): ``assemble_plan(load_sharded_plan(ranks=[r]))``
    yields per-rank rows BIT-identical to slicing the full-world plan,
    for EVERY rank — a subset view that disagreed with the full world on
    any array row or any static would make per-rank program builds
    diverge by construction."""
    import dataclasses

    from dgraph_tpu.plan import build_plan_shards, load_sharded_plan

    edges, part, _ = _graph(seed=3, w=w)
    d = str(tmp_path / f"shards_w{w}")
    build_plan_shards(
        edges, part, out_dir=d, world_size=w, overlap=True,
        write_layout=False,
    )
    full, _ = load_sharded_plan(d, load_layout=False)

    def leaves(plan):
        out = {}
        for f in dataclasses.fields(plan):
            v = getattr(plan, f.name)
            if isinstance(v, np.ndarray):
                out[f.name] = v
        for f in dataclasses.fields(plan.halo):
            v = getattr(plan.halo, f.name)
            if isinstance(v, np.ndarray):
                out[f"halo.{f.name}"] = v
        if plan.overlap is not None:
            for f in dataclasses.fields(plan.overlap):
                v = getattr(plan.overlap, f.name)
                if isinstance(v, np.ndarray):
                    out[f"overlap.{f.name}"] = v
        return out

    full_leaves = leaves(full)
    assert full_leaves, "no array leaves found — the comparison is vacuous"
    for r in range(w):
        sub, layout = load_sharded_plan(d, ranks=[r], load_layout=False)
        assert layout is None
        sub_leaves = leaves(sub)
        assert set(sub_leaves) == set(full_leaves)
        for name, leaf in sub_leaves.items():
            assert leaf.shape[0] == 1, (r, name)
            assert leaf.dtype == full_leaves[name].dtype, (r, name)
            assert np.array_equal(leaf[0], full_leaves[name][r]), (r, name)
        # every static the program build consumes must describe the FULL
        # world, not the subset
        assert sub.world_size == full.world_size == w
        for field in ("n_src_pad", "n_dst_pad", "e_pad", "halo_side",
                      "homogeneous", "owner_sorted", "halo_deltas",
                      "scatter_mc", "scatter_block_e", "scatter_block_n"):
            assert getattr(sub, field) == getattr(full, field), (r, field)
        assert sub.halo.s_pad == full.halo.s_pad
        assert (sub.overlap is None) == (full.overlap is None)
        if sub.overlap is not None:
            assert sub.overlap.e_int_pad == full.overlap.e_int_pad
            assert sub.overlap.e_bnd_pad == full.overlap.e_bnd_pad


def test_write_layout_opt_out(tmp_path):
    """write_layout=False skips the O(E) layout sidecar entirely — at
    papers100M scale it pickles to ~25 GB and nothing in the per-host
    load path consumes it (the p100m plan stage runs this way)."""
    import dgraph_tpu.plan_shards as ps
    from dgraph_tpu.plan import build_plan_shards, load_sharded_plan

    edges, part, w = _graph()
    out = str(tmp_path / "shards")
    manifest = build_plan_shards(
        edges, part, out_dir=out, world_size=w, write_layout=False,
    )
    assert manifest["complete"] and manifest["layout"] is None
    assert not os.path.exists(os.path.join(out, ps.LAYOUT_NAME))
    plan, layout = load_sharded_plan(out, load_layout=False)
    assert layout is None and plan.world_size == w


def test_cached_edge_plan_rank_subset_skips_layout(tmp_path):
    """ranks=[...] is the per-host path: it must not read (or verify)
    the O(E) layout sidecar."""
    _cached(tmp_path)  # warm the cache (full build writes the layout)
    d = _plan_dir(tmp_path)
    layout_path = os.path.join(d, "layout.pkl")
    # corrupt the sidecar: a subset load that touched it would raise
    open(layout_path, "wb").write(b"garbage")
    plan, layout = _cached(tmp_path, ranks=[0, 2])
    assert layout is None
    assert plan.src_index.shape[0] == 2
    # a full-world load DOES verify it — and repairs via full rebuild
    plan_full, layout_full = _cached(tmp_path)
    assert layout_full is not None


def test_cached_edge_plan_write_layout_false_round_trips(tmp_path):
    """write_layout=False passed through cached_edge_plan must build,
    cache, and warm-load (plan, None) — not chase a sidecar that was
    never written."""
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    edges, part, w = _graph()
    plan, layout = cached_edge_plan(
        str(tmp_path), edges, part, world_size=w, write_layout=False,
    )
    assert layout is None
    assert not os.path.exists(os.path.join(_plan_dir(tmp_path), "layout.pkl"))
    plan2, layout2 = cached_edge_plan(  # warm hit, no rebuild loop
        str(tmp_path), edges, part, world_size=w, write_layout=False,
    )
    assert layout2 is None
    _assert_plans_equal(plan, plan2)


def test_fresh_start_deletes_stale_artifact(tmp_path):
    """A fingerprint/statics mismatch discards stale progress AND deletes
    the orphaned shard/manifest files — orphaned tens-of-GB shards in a
    fixed out_dir are the r5 disk-exhaustion mode."""
    import dgraph_tpu.plan_shards as ps
    from dgraph_tpu.plan import build_plan_shards

    edges, part, w = _graph()
    out = str(tmp_path / "shards")
    build_plan_shards(edges, part, out_dir=out, world_size=w,
                      fingerprint="old")
    assert os.path.exists(os.path.join(out, ps.shard_filename(0)))
    w2 = ps.PlanShardWriter(out, fingerprint="new", world_size=w, statics={})
    assert not w2.done(0)
    assert not any(
        f.startswith("shard_") or f == ps.LAYOUT_NAME
        for f in os.listdir(out)
    ), os.listdir(out)
    assert not os.path.exists(ps.manifest_path(out))


def test_cached_edge_plan_ignores_use_native(tmp_path, caplog):
    """The v8 cache always streams through the numpy core (the native
    core fills the whole [W, E_pad] stack); an explicit use_native=True
    from an old caller is ignored with a warning, not a crash."""
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    edges, part, w = _graph()
    with caplog.at_level("WARNING", logger="dgraph_tpu.checkpoint"):
        plan, _ = cached_edge_plan(
            str(tmp_path), edges, part, world_size=w, use_native=True,
        )
    assert plan.world_size == w
    assert any(
        "use_native is ignored" in r.getMessage() for r in caplog.records
    )


def test_cached_edge_plan_ranks_requires_cache_dir():
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    edges, part, w = _graph()
    with pytest.raises(ValueError, match="cache_dir"):
        cached_edge_plan("", edges, part, world_size=w, ranks=[0])


def test_default_fingerprint_is_content_bound(tmp_path):
    """fingerprint="" defaults to a streaming content hash of the build
    inputs: byte-identical inputs (in-RAM or memmap'd) share it, and a
    changed edge list gets a NEW fingerprint so a resumed manifest can
    never adopt the old build's shards even when statics coincide."""
    from dgraph_tpu.plan import build_plan_shards

    edges, part, w = _graph()
    d = str(tmp_path / "shards")
    m1 = build_plan_shards(edges, part, out_dir=d, world_size=w)
    assert m1["fingerprint"].startswith("content:")
    mm_path = tmp_path / "edges.npy"
    np.save(mm_path, edges)
    mm = np.load(mm_path, mmap_mode="r")
    m1b = build_plan_shards(mm, part, out_dir=d, world_size=w)
    assert m1b["fingerprint"] == m1["fingerprint"]
    # same edge multiset, different bytes: the writer must start fresh
    # (fingerprint mismatch), not adopt the previous build's shards
    edges2 = np.ascontiguousarray(edges[:, ::-1])
    m2 = build_plan_shards(edges2, part, out_dir=d, world_size=w)
    assert m2["fingerprint"] != m1["fingerprint"]
    assert m2["complete"]


def test_write_layout_not_in_cache_key(tmp_path):
    """write_layout is an artifact-shape knob, not a plan knob: both
    spellings must hash to ONE cache dir, with the missing sidecar
    self-healed on the first load that wants it — not a duplicate
    multi-GB artifact under a second key."""
    import glob

    from dgraph_tpu.train.checkpoint import cached_edge_plan

    edges, part, w = _graph()
    cached_edge_plan(
        str(tmp_path), edges, part, world_size=w, write_layout=False,
    )
    dirs = glob.glob(str(tmp_path / "plan_*"))
    assert len(dirs) == 1
    plan, layout = cached_edge_plan(str(tmp_path), edges, part, world_size=w)
    assert glob.glob(str(tmp_path / "plan_*")) == dirs
    assert layout is not None  # sidecar written on demand by the repair


def test_cached_edge_plan_no_cache_drops_artifact_kwargs():
    """A falsy cache_dir (the --plan_cache "" convention) builds without
    caching; write_layout describes the on-disk artifact and must not
    leak into build_edge_plan (which rejects it)."""
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    edges, part, w = _graph()
    plan, layout = cached_edge_plan(
        "", edges, part, world_size=w, write_layout=False,
    )
    assert plan.world_size == w and layout is not None


def test_cached_edge_plan_verify_off_warm_hit_still_repairs(tmp_path):
    """verify=False skips the SHA pass on warm hits (the papers100M-scale
    load-cost knob) — but a truncated shard still fails to unpickle and
    takes the same single-shard repair path."""
    import glob

    import dgraph_tpu.plan_shards as ps
    from dgraph_tpu.train.checkpoint import cached_edge_plan

    edges, part, w = _graph()
    plan, _ = cached_edge_plan(str(tmp_path), edges, part, world_size=w)
    plan2, _ = cached_edge_plan(
        str(tmp_path), edges, part, world_size=w, verify=False,
    )
    _assert_plans_equal(plan, plan2)
    pdir = glob.glob(str(tmp_path / "plan_*"))[0]
    man = ps.read_manifest(pdir)
    shard = os.path.join(pdir, man["shards"]["1"]["file"])
    with open(shard, "r+b") as fh:
        fh.truncate(os.path.getsize(shard) // 2)
    plan3, _ = cached_edge_plan(
        str(tmp_path), edges, part, world_size=w, verify=False,
    )
    _assert_plans_equal(plan, plan3)


# ---------------------------------------------------------------------------
# memory budget: structured raise, never an OOM kill
# ---------------------------------------------------------------------------


def test_memory_budget_violation_raises_structured(tmp_path):
    from dgraph_tpu.plan import build_plan_shards
    from dgraph_tpu.plan_shards import PlanBuildMemoryExceeded

    edges, part, w = _graph()
    with pytest.raises(PlanBuildMemoryExceeded) as ei:
        build_plan_shards(
            edges, part, out_dir=str(tmp_path), world_size=w,
            memory_budget_bytes=1024,
        )
    rec = ei.value.record()
    assert rec["kind"] == "plan_build_memory_exceeded"
    assert rec["budget_bytes"] == 1024
    assert rec["needed_bytes"] > 1024
    # the upfront estimate fails BEFORE any shard is assembled
    assert rec["rank"] is None
    assert not os.path.exists(os.path.join(str(tmp_path), "shard_0000.pkl"))


def test_memory_budget_env_knob(tmp_path, monkeypatch):
    from dgraph_tpu.plan import build_plan_shards
    from dgraph_tpu.plan_shards import (
        MEMORY_BUDGET_ENV,
        PlanBuildMemoryExceeded,
    )

    edges, part, w = _graph()
    monkeypatch.setenv(MEMORY_BUDGET_ENV, "0.001")  # ~1 KiB
    with pytest.raises(PlanBuildMemoryExceeded):
        build_plan_shards(
            edges, part, out_dir=str(tmp_path), world_size=w,
        )
    monkeypatch.setenv(MEMORY_BUDGET_ENV, "64")  # plenty for the tiny graph
    manifest = build_plan_shards(
        edges, part, out_dir=str(tmp_path), world_size=w,
    )
    assert manifest["complete"]


def test_shard_nbytes_estimate_is_an_upper_bound(tmp_path):
    from dgraph_tpu.plan import build_plan_shards, shard_nbytes_estimate
    import dgraph_tpu.plan_shards as ps

    edges, part, w = _graph()
    manifest = build_plan_shards(
        edges, part, out_dir=str(tmp_path), world_size=w, overlap=True,
        sort_route=True,
    )
    est = shard_nbytes_estimate(manifest["statics"])
    for r in range(w):
        payload = ps.read_shard(
            str(tmp_path), r, manifest["shards"][str(r)]
        )
        assert ps.payload_nbytes(payload) <= est, r


# ---------------------------------------------------------------------------
# the standalone supervise twin (bench's wedge-surviving probe loop)
# ---------------------------------------------------------------------------


def test_supervise_standalone_twin_contract():
    """bench.py loads train/supervise.py by PATH with the spans/health
    twins pre-registered; the literal fallback constants in that branch
    must track the canonical package values."""
    import importlib.util

    from dgraph_tpu import chaos
    from dgraph_tpu.train import supervise as pkg
    from dgraph_tpu.train.elastic import WEDGED_EXIT_CODE

    def load(name, *rel):
        path = os.path.join(REPO, *rel)
        spec = importlib.util.spec_from_file_location(name, path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules[name] = mod
        spec.loader.exec_module(mod)
        return mod

    try:
        load("_dgraph_obs_health", "dgraph_tpu", "obs", "health.py")
        load("_dgraph_obs_spans", "dgraph_tpu", "obs", "spans.py")
        twin = load(
            "_dgraph_train_supervise", "dgraph_tpu", "train", "supervise.py"
        )
        from dgraph_tpu.comm.membership import RANK_LOST_EXIT_CODE

        assert twin.WEDGED_EXIT_CODE == WEDGED_EXIT_CODE == 17
        assert twin.ATTEMPT_ENV_VAR == chaos.ATTEMPT_ENV_VAR
        assert twin.RANK_ENV_VAR == chaos.RANK_ENV_VAR
        assert twin.RANK_LOST_EXIT_CODE == RANK_LOST_EXIT_CODE == 19
        assert pkg.WEDGED_EXIT_CODE == twin.WEDGED_EXIT_CODE
        # the constant's canonical home (dgraph_tpu/utils/env.py, jax-free
        # by lint contract): every consumer — chaos's rank=K matcher, the
        # supervisor's export, membership's rank_from_env, the twin's
        # literal fallback — must carry the SAME string
        from dgraph_tpu.comm import membership
        from dgraph_tpu.utils.env import RANK_ENV_VAR

        assert (
            RANK_ENV_VAR == chaos.RANK_ENV_VAR == pkg.RANK_ENV_VAR
            == membership.RANK_ENV_VAR == twin.RANK_ENV_VAR == "DGRAPH_RANK"
        )
        # the twin's supervise() runs end to end without the package
        lineage = twin.supervise(
            [sys.executable, "-c", "import sys; sys.exit(0)"],
            backoff_s=0.01,
        )
        assert lineage["final_exit_code"] == 0
        assert lineage["kind"] == "supervise_lineage"
    finally:
        for name in ("_dgraph_obs_health", "_dgraph_obs_spans",
                     "_dgraph_train_supervise"):
            sys.modules.pop(name, None)


# ---------------------------------------------------------------------------
# memmap helper: streamed renumbering
# ---------------------------------------------------------------------------


def test_renumber_edges_chunked_matches_in_ram(tmp_path):
    from dgraph_tpu.data.memmap import renumber_edges_chunked

    rng = np.random.default_rng(3)
    edges = rng.integers(0, 100, (2, 1000)).astype(np.int64)
    perm = rng.permutation(100).astype(np.int64)
    out_path = str(tmp_path / "renum.npy")
    got = renumber_edges_chunked(edges, perm, out_path, chunk_cols=128)
    assert isinstance(got, np.memmap)  # file-backed, reclaimable pages
    assert np.array_equal(np.asarray(got), perm[edges])
