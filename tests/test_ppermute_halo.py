"""ppermute neighbor-rounds halo exchange == all_to_all lowering, forward
and backward, on both sparse (ring) and dense (random) peer sets."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu import config as cfg
from dgraph_tpu import plan as pl
from dgraph_tpu.comm import collectives
from dgraph_tpu.plan import shard_edge_data, shard_vertex_data, unshard_vertex_data
from dgraph_tpu.testing import (
    dense_gather,
    dense_scatter_sum,
    spmd_apply,
    unshard_edge_data,
)


@pytest.fixture(params=["ring", "random"])
def case(request, rng):
    W, V = 8, 96
    if request.param == "ring":
        # block-partition a ring graph: traffic only to rank+-1 -> sparse deltas
        src = np.arange(V)
        dst = (src + 1) % V
        edges = np.stack([np.concatenate([src, dst]), np.concatenate([dst, src])])
        part = np.sort(np.arange(V) * W // V).astype(np.int32)
    else:
        edges = rng.integers(0, V, size=(2, 600))
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    plan, layout = pl.build_edge_plan(edges, part, world_size=W)
    return edges, part, plan, layout, request.param


@pytest.fixture(params=["ppermute", "all_to_all"])
def impl(request):
    old = cfg.halo_impl
    cfg.set_flags(halo_impl=request.param)
    yield request.param
    cfg.set_flags(halo_impl=old)


def test_ring_partition_has_sparse_deltas(rng):
    W, V = 8, 96
    src = np.arange(V)
    dst = (src + 1) % V
    edges = np.stack([np.concatenate([src, dst]), np.concatenate([dst, src])])
    part = np.sort(np.arange(V) * W // V).astype(np.int32)
    plan, _ = pl.build_edge_plan(edges, part, world_size=W)
    assert set(plan.halo_deltas) == {1, W - 1}


def test_gather_matches_dense(mesh8, case, impl, rng):
    edges, part, plan, layout, _ = case
    V, F = len(part), 6
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = shard_vertex_data(x, layout.src_counts, plan.n_src_pad)
    out = spmd_apply(mesh8, collectives.gather, plan, jnp.asarray(xs), static_args=("src", "graph"))
    got = unshard_edge_data(np.asarray(out), layout)
    np.testing.assert_allclose(got, dense_gather(x, edges, "src"), rtol=1e-6)


def test_scatter_to_halo_side_matches_dense(mesh8, case, impl, rng):
    edges, part, plan, layout, _ = case
    V, F = len(part), 4
    edata = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
    ed = shard_edge_data(edata, layout, plan.e_pad)
    out = spmd_apply(mesh8, collectives.scatter_sum, plan, jnp.asarray(ed), static_args=("src", "graph"))
    got = unshard_vertex_data(np.asarray(out), layout.src_counts)
    np.testing.assert_allclose(
        got, dense_scatter_sum(edata, edges, "src", V), rtol=1e-5, atol=1e-5
    )


def test_gather_grad_matches_dense(mesh8, case, impl, rng):
    edges, part, plan, layout, _ = case
    V, F = len(part), 3
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    ct = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
    ct_sh = jnp.asarray(shard_edge_data(ct, layout, plan.e_pad))

    def loss_fn(xs_):
        out = spmd_apply(mesh8, collectives.gather, plan, xs_, static_args=("src", "graph"))
        return jnp.sum(out * ct_sh)

    with jax.set_mesh(mesh8):
        grad = jax.jit(jax.grad(loss_fn))(xs)
    got = unshard_vertex_data(np.asarray(grad), layout.src_counts)
    np.testing.assert_allclose(
        got, dense_scatter_sum(ct, edges, "src", V), rtol=1e-5, atol=1e-5
    )
