"""Pallas sorted-segment-sum vs the jnp oracle (interpret mode on CPU —
the reference's CUDA-kernel-vs-dense-loop test pattern,
``tests/test_local_kernels.py:26-154``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.pallas_segment import max_chunks_hint, sorted_segment_sum


@pytest.mark.parametrize("E,N,F", [(1000, 300, 16), (4096, 512, 128), (37, 8, 4)])
def test_matches_oracle(rng, E, N, F):
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N, block_e=256, block_n=256)
    got = sorted_segment_sum(
        jnp.asarray(data),
        jnp.asarray(ids),
        N,
        max_chunks_per_block=mc,
        interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, data)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_masked_rows_dropped(rng):
    """Out-of-range ids (the plan's padded-edge convention) contribute 0."""
    E, N, F = 512, 100, 8
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-50:] = N + 1  # padded edges
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids[:-50], data[:-50])
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_skewed_segments(rng):
    """Hub vertex with most of the edges (power-law worst case)."""
    E, N, F = 2000, 64, 8
    ids = np.concatenate([np.zeros(1500, np.int32), np.sort(rng.integers(1, N, 500))])
    ids = np.sort(ids).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, data)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


def test_grad_is_gather_transpose(rng):
    """VJP == g[ids] with OOB ids dropped (gather-bwd = scatter-sum duality,
    the reference pins the same pair in ``tests/test_NCCLCommPlan.py``)."""
    import jax

    E, N, F = 600, 100, 8
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-40:] = N + 1  # padded edges
    data = rng.normal(size=(E, F)).astype(np.float32)
    g_out = rng.normal(size=(N, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N)

    def loss(d):
        out = sorted_segment_sum(
            d, jnp.asarray(ids), N, max_chunks_per_block=mc, interpret=True
        )
        return (out * g_out).sum()

    got = jax.grad(loss)(jnp.asarray(data))
    expected = np.zeros_like(data)
    expected[:-40] = g_out[ids[:-40]]
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_grad_relu_input_op(rng):
    import jax

    E, N, F = 300, 50, 4
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    g_out = rng.normal(size=(N, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N)

    def loss_pallas(d):
        return (
            sorted_segment_sum(
                d, jnp.asarray(ids), N, max_chunks_per_block=mc,
                interpret=True, input_op="relu",
            ) * g_out
        ).sum()

    def loss_ref(d):
        import jax.nn

        out = jax.ops.segment_sum(jax.nn.relu(d), jnp.asarray(ids), num_segments=N)
        return (out * g_out).sum()

    import jax

    got = jax.grad(loss_pallas)(jnp.asarray(data))
    want = jax.grad(loss_ref)(jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_fused_relu_input_op(rng):
    """input_op='relu' == relu-then-sum (Fused_ReLU_Scatter_Kernel parity)."""
    E, N, F = 777, 128, 16
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
        input_op="relu",
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, np.maximum(data, 0.0))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


class TestFusedBiasRelu:
    """sorted_segment_sum_bias_relu (the reference's fused scatter family,
    local_data_kernels.cuh:34-116): interpret-mode kernel vs numpy oracle,
    and the collectives.scatter_bias_relu fallback vs composed ops."""

    def _case(self, seed=0, E=2048, N=512, F=32):
        rng = np.random.default_rng(seed)
        ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
        ids[-32:] = N + 1  # padded-edge tail (OOB ids must drop)
        data = rng.standard_normal((E, F)).astype(np.float32)
        bias = rng.standard_normal((N, F)).astype(np.float32)
        w = rng.uniform(0.5, 2.0, E).astype(np.float32)
        return ids, data, bias, w

    def _oracle(self, ids, data, bias, w, N):
        out = np.zeros((N, bias.shape[1]), np.float32)
        for e in range(len(ids)):
            if ids[e] >= N:
                continue
            m = np.maximum(data[e] + bias[ids[e]], 0)
            out[ids[e]] += w[e] * m if w is not None else m
        return out

    @pytest.mark.parametrize("use_w", [False, True])
    def test_kernel_interpret_matches_oracle(self, use_w):
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            sorted_segment_sum_bias_relu,
        )

        ids, data, bias, w = self._case()
        N = bias.shape[0]
        got = np.asarray(
            sorted_segment_sum_bias_relu(
                jnp.asarray(data), jnp.asarray(ids), jnp.asarray(bias), N,
                edge_weight=jnp.asarray(w) if use_w else None,
                max_chunks_per_block=max_chunks_hint(ids, N),
                interpret=True,
            )
        )
        want = self._oracle(ids, data, bias, w if use_w else None, N)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_kernel_gradients_match_composite(self):
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            sorted_segment_sum_bias_relu,
        )

        ids, data, bias, w = self._case(1, E=1024, N=256, F=16)
        N = bias.shape[0]
        tgt = jnp.asarray(
            np.random.default_rng(2).standard_normal((N, 16)).astype(np.float32)
        )
        mc = max_chunks_hint(ids, N)
        safe = np.clip(ids, 0, N - 1).astype(np.int32)
        valid = (ids < N).astype(np.float32)[:, None]

        def fused(d, b, wgt):
            out = sorted_segment_sum_bias_relu(
                d, jnp.asarray(ids), b, N, edge_weight=wgt,
                max_chunks_per_block=mc, interpret=True,
            )
            return (out * tgt).sum()

        def composed(d, b, wgt):
            rows = jnp.take(b, jnp.asarray(safe), axis=0)
            m = jnp.maximum(d + rows, 0) * wgt[:, None] * jnp.asarray(valid)
            out = jax.ops.segment_sum(m, jnp.asarray(safe), num_segments=N)
            return (out * tgt).sum()

        args = (jnp.asarray(data), jnp.asarray(bias), jnp.asarray(w))
        ga = jax.grad(fused, argnums=(0, 1, 2))(*args)
        gb = jax.grad(composed, argnums=(0, 1, 2))(*args)
        for a, b, name in zip(ga, gb, ["d_data", "d_bias", "d_w"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                err_msg=name,
            )

    def test_collectives_fallback_equals_composed(self):
        """Off-TPU, scatter_bias_relu must equal gather+relu+scatter_sum."""
        from dgraph_tpu.comm import collectives as coll
        from dgraph_tpu.plan import build_edge_plan

        rng = np.random.default_rng(3)
        V, E, W = 64, 300, 1
        edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
        plan, _ = build_edge_plan(
            edges, np.zeros(V, np.int32), world_size=1, edge_owner="dst"
        )
        p0 = jax.tree.map(lambda l: jnp.asarray(np.asarray(l)[0]), plan)
        ed = jnp.asarray(rng.standard_normal((plan.e_pad, 8)), jnp.float32)
        bias = jnp.asarray(rng.standard_normal((plan.n_dst_pad, 8)), jnp.float32)
        w = jnp.asarray(rng.uniform(0.5, 2, plan.e_pad), jnp.float32)

        got = coll.scatter_bias_relu(ed, bias, p0, "dst", None, w)
        m = jax.nn.relu(ed + coll.gather(bias, p0, "dst", None)) * w[:, None]
        m = m * p0.edge_mask[:, None]
        want = coll.scatter_sum(m, p0, "dst", None)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
