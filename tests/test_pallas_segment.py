"""Pallas sorted-segment-sum vs the jnp oracle (interpret mode on CPU —
the reference's CUDA-kernel-vs-dense-loop test pattern,
``tests/test_local_kernels.py:26-154``)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu.ops.pallas_segment import max_chunks_hint, sorted_segment_sum


@pytest.mark.parametrize("E,N,F", [(1000, 300, 16), (4096, 512, 128), (37, 8, 4)])
def test_matches_oracle(rng, E, N, F):
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N, block_e=256, block_n=256)
    got = sorted_segment_sum(
        jnp.asarray(data),
        jnp.asarray(ids),
        N,
        max_chunks_per_block=mc,
        interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, data)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_masked_rows_dropped(rng):
    """Out-of-range ids (the plan's padded-edge convention) contribute 0."""
    E, N, F = 512, 100, 8
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-50:] = N + 1  # padded edges
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids[:-50], data[:-50])
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_skewed_segments(rng):
    """Hub vertex with most of the edges (power-law worst case)."""
    E, N, F = 2000, 64, 8
    ids = np.concatenate([np.zeros(1500, np.int32), np.sort(rng.integers(1, N, 500))])
    ids = np.sort(ids).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, data)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


def test_grad_is_gather_transpose(rng):
    """VJP == g[ids] with OOB ids dropped (gather-bwd = scatter-sum duality,
    the reference pins the same pair in ``tests/test_NCCLCommPlan.py``)."""
    import jax

    E, N, F = 600, 100, 8
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-40:] = N + 1  # padded edges
    data = rng.normal(size=(E, F)).astype(np.float32)
    g_out = rng.normal(size=(N, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N)

    def loss(d):
        out = sorted_segment_sum(
            d, jnp.asarray(ids), N, max_chunks_per_block=mc, interpret=True
        )
        return (out * g_out).sum()

    got = jax.grad(loss)(jnp.asarray(data))
    expected = np.zeros_like(data)
    expected[:-40] = g_out[ids[:-40]]
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_grad_relu_input_op(rng):
    import jax

    E, N, F = 300, 50, 4
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    g_out = rng.normal(size=(N, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N)

    def loss_pallas(d):
        return (
            sorted_segment_sum(
                d, jnp.asarray(ids), N, max_chunks_per_block=mc,
                interpret=True, input_op="relu",
            ) * g_out
        ).sum()

    def loss_ref(d):
        import jax.nn

        out = jax.ops.segment_sum(jax.nn.relu(d), jnp.asarray(ids), num_segments=N)
        return (out * g_out).sum()

    import jax

    got = jax.grad(loss_pallas)(jnp.asarray(data))
    want = jax.grad(loss_ref)(jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_fused_relu_input_op(rng):
    """input_op='relu' == relu-then-sum (Fused_ReLU_Scatter_Kernel parity)."""
    E, N, F = 777, 128, 16
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
        input_op="relu",
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, np.maximum(data, 0.0))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


class TestFusedBiasRelu:
    """sorted_segment_sum_bias_relu (the reference's fused scatter family,
    local_data_kernels.cuh:34-116): interpret-mode kernel vs numpy oracle,
    and the collectives.scatter_bias_relu fallback vs composed ops."""

    def _case(self, seed=0, E=2048, N=512, F=32):
        rng = np.random.default_rng(seed)
        ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
        ids[-32:] = N + 1  # padded-edge tail (OOB ids must drop)
        data = rng.standard_normal((E, F)).astype(np.float32)
        bias = rng.standard_normal((N, F)).astype(np.float32)
        w = rng.uniform(0.5, 2.0, E).astype(np.float32)
        return ids, data, bias, w

    def _oracle(self, ids, data, bias, w, N):
        out = np.zeros((N, bias.shape[1]), np.float32)
        for e in range(len(ids)):
            if ids[e] >= N:
                continue
            m = np.maximum(data[e] + bias[ids[e]], 0)
            out[ids[e]] += w[e] * m if w is not None else m
        return out

    @pytest.mark.parametrize("use_w", [False, True])
    def test_kernel_interpret_matches_oracle(self, use_w):
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            sorted_segment_sum_bias_relu,
        )

        ids, data, bias, w = self._case()
        N = bias.shape[0]
        got = np.asarray(
            sorted_segment_sum_bias_relu(
                jnp.asarray(data), jnp.asarray(ids), jnp.asarray(bias), N,
                edge_weight=jnp.asarray(w) if use_w else None,
                max_chunks_per_block=max_chunks_hint(ids, N),
                interpret=True,
            )
        )
        want = self._oracle(ids, data, bias, w if use_w else None, N)
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    def test_kernel_gradients_match_composite(self):
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            sorted_segment_sum_bias_relu,
        )

        ids, data, bias, w = self._case(1, E=1024, N=256, F=16)
        N = bias.shape[0]
        tgt = jnp.asarray(
            np.random.default_rng(2).standard_normal((N, 16)).astype(np.float32)
        )
        mc = max_chunks_hint(ids, N)
        safe = np.clip(ids, 0, N - 1).astype(np.int32)
        valid = (ids < N).astype(np.float32)[:, None]

        def fused(d, b, wgt):
            out = sorted_segment_sum_bias_relu(
                d, jnp.asarray(ids), b, N, edge_weight=wgt,
                max_chunks_per_block=mc, interpret=True,
            )
            return (out * tgt).sum()

        def composed(d, b, wgt):
            rows = jnp.take(b, jnp.asarray(safe), axis=0)
            m = jnp.maximum(d + rows, 0) * wgt[:, None] * jnp.asarray(valid)
            out = jax.ops.segment_sum(m, jnp.asarray(safe), num_segments=N)
            return (out * tgt).sum()

        args = (jnp.asarray(data), jnp.asarray(bias), jnp.asarray(w))
        ga = jax.grad(fused, argnums=(0, 1, 2))(*args)
        gb = jax.grad(composed, argnums=(0, 1, 2))(*args)
        for a, b, name in zip(ga, gb, ["d_data", "d_bias", "d_w"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                err_msg=name,
            )

    @pytest.mark.parametrize("be,bn", [(128, 128), (256, 64)])
    def test_kernel_bwd_pair_matches_composite(self, be, bn):
        """The unweighted KERNEL backward (chunk-major gd kernel + the
        epilogue='act' d_bias reduction — engaged when gather_mv > 0)
        must produce the same gradients as plain autodiff through the
        composed ops. This is the path the bf16 GCN epoch runs on TPU."""
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
            sorted_segment_sum_bias_relu,
        )

        ids, data, bias, _ = self._case(4, E=1024, N=256, F=16)
        N = bias.shape[0]
        tgt = jnp.asarray(
            np.random.default_rng(5).standard_normal((N, 16)).astype(np.float32)
        )
        mc = max_chunks_hint(ids, N, block_e=be, block_n=bn)
        mv = max_vblocks_hint(ids, N, block_e=be, block_n=bn)
        assert mv > 0
        safe = np.clip(ids, 0, N - 1).astype(np.int32)
        valid = (ids < N).astype(np.float32)[:, None]

        def fused(d, b):
            out = sorted_segment_sum_bias_relu(
                d, jnp.asarray(ids), b, N,
                max_chunks_per_block=mc, block_e=be, block_n=bn,
                gather_mv=mv, interpret=True,
            )
            return (out * tgt).sum()

        def composed(d, b):
            rows = jnp.take(b, jnp.asarray(safe), axis=0)
            m = jnp.maximum(d + rows, 0) * jnp.asarray(valid)
            out = jax.ops.segment_sum(m, jnp.asarray(safe), num_segments=N)
            return (out * tgt).sum()

        args = (jnp.asarray(data), jnp.asarray(bias))
        ga = jax.grad(fused, argnums=(0, 1))(*args)
        gb = jax.grad(composed, argnums=(0, 1))(*args)
        for a, b, name in zip(ga, gb, ["d_data", "d_bias"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                err_msg=name,
            )

    def test_kernel_bwd_pair_bf16_matches_composed_bwd(self):
        """bf16 KERNEL backward vs the bf16 COMPOSED backward (gather_mv=0
        disables the kernel pair): both decide the ReLU mask from the same
        bf16-rounded operands in f32, so they must agree to accumulation
        rounding — an f32 reference would differ by whole elements at
        ReLU-boundary flips, which is inherent to bf16, not a kernel bug."""
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
            sorted_segment_sum_bias_relu,
        )

        ids, data, bias, _ = self._case(6, E=1024, N=256, F=16)
        N = bias.shape[0]
        mc = max_chunks_hint(ids, N)
        mv = max_vblocks_hint(ids, N)
        tgt = jnp.asarray(
            np.random.default_rng(7).standard_normal((N, 16)).astype(np.float32)
        )

        def loss(d, b, gmv):
            out = sorted_segment_sum_bias_relu(
                jnp.asarray(d, jnp.bfloat16), jnp.asarray(ids),
                jnp.asarray(b, jnp.bfloat16), N,
                max_chunks_per_block=mc, gather_mv=gmv, interpret=True,
            )
            return (out.astype(jnp.float32) * tgt).sum()

        args = (jnp.asarray(data), jnp.asarray(bias))
        gk = jax.grad(lambda d, b: loss(d, b, mv), argnums=(0, 1))(*args)
        gc = jax.grad(lambda d, b: loss(d, b, 0), argnums=(0, 1))(*args)
        for a, b, name in zip(gk, gc, ["d_data", "d_bias"]):
            np.testing.assert_allclose(
                np.asarray(a, np.float32), np.asarray(b, np.float32),
                rtol=0.02, atol=0.02, err_msg=name,
            )

    def test_fused_bwd_kill_switch_routes_to_composed(self):
        """With use_pallas_fused_bwd=False the VJP must bypass the kernel
        pair even when gather_mv>0 (ADVICE r4: the pair needs its own
        disable for Mosaic-regression debugging), and grads must match the
        enabled path. The flag is read at trace time, so flipping it here
        exercises the branch without env vars."""
        from dgraph_tpu import config
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
            sorted_segment_sum_bias_relu,
        )

        ids, data, bias, _ = self._case(9, E=512, N=128, F=8)
        N = bias.shape[0]
        mc = max_chunks_hint(ids, N)
        mv = max_vblocks_hint(ids, N)
        tgt = jnp.asarray(
            np.random.default_rng(11).standard_normal((N, 8)).astype(np.float32)
        )

        def loss(d, b):
            out = sorted_segment_sum_bias_relu(
                d, jnp.asarray(ids), b, N,
                max_chunks_per_block=mc, gather_mv=mv, interpret=True,
            )
            return (out.astype(jnp.float32) * tgt).sum()

        # the pair and the composed bwd agree numerically by design, so a
        # silently-ignored flag would still pass an allclose — count the
        # kernel-pair factory's invocations to prove the ROUTING flips
        from dgraph_tpu.ops import pallas_segment as ps

        real_make = ps._make_fused_bwd
        calls = []

        def counting_make(*a, **kw):
            calls.append(1)
            return real_make(*a, **kw)

        args = (jnp.asarray(data), jnp.asarray(bias))
        old_flag = config.use_pallas_fused_bwd
        ps._make_fused_bwd = counting_make
        try:
            g_on = jax.grad(loss, argnums=(0, 1))(*args)
            assert calls, "kernel pair did not engage with the flag on"
            calls.clear()
            config.set_flags(use_pallas_fused_bwd=False)
            g_off = jax.grad(loss, argnums=(0, 1))(*args)
            assert not calls, "kill switch ignored: kernel pair still ran"
        finally:
            ps._make_fused_bwd = real_make
            config.set_flags(use_pallas_fused_bwd=old_flag)
        for a, b, name in zip(g_on, g_off, ["d_data", "d_bias"]):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4,
                err_msg=name,
            )

    def test_collectives_fallback_equals_composed(self):
        """Off-TPU, scatter_bias_relu must equal gather+relu+scatter_sum."""
        from dgraph_tpu.comm import collectives as coll
        from dgraph_tpu.plan import build_edge_plan

        rng = np.random.default_rng(3)
        V, E, W = 64, 300, 1
        edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
        plan, _ = build_edge_plan(
            edges, np.zeros(V, np.int32), world_size=1, edge_owner="dst"
        )
        p0 = jax.tree.map(lambda l: jnp.asarray(np.asarray(l)[0]), plan)
        ed = jnp.asarray(rng.standard_normal((plan.e_pad, 8)), jnp.float32)
        bias = jnp.asarray(rng.standard_normal((plan.n_dst_pad, 8)), jnp.float32)
        w = jnp.asarray(rng.uniform(0.5, 2, plan.e_pad), jnp.float32)

        got = coll.scatter_bias_relu(ed, bias, p0, "dst", None, w)
        m = jax.nn.relu(ed + coll.gather(bias, p0, "dst", None)) * w[:, None]
        m = m * p0.edge_mask[:, None]
        want = coll.scatter_sum(m, p0, "dst", None)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )


class TestSortedRowGather:
    """The transpose kernel: x[ids] for sorted ids as blocked one-hot MXU
    matmuls (interpret mode on CPU; the chip self-check gates real Mosaic)."""

    def _case(self, seed=0, N=2000, E=8192, F=128, masked_tail=100):
        rng = np.random.default_rng(seed)
        ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
        if masked_tail:
            ids[-masked_tail:] = N + 1
        x = rng.standard_normal((N, F)).astype(np.float32)
        want = np.where((ids < N)[:, None], x[np.clip(ids, 0, N - 1)], 0.0)
        return x, ids, want

    def test_matches_numpy_with_masked_tail(self):
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
            sorted_row_gather,
        )

        x, ids, want = self._case()
        mv = max_vblocks_hint(ids, x.shape[0])
        mc = max_chunks_hint(ids, x.shape[0])
        got = np.asarray(sorted_row_gather(
            jnp.asarray(x), jnp.asarray(ids), max_vblocks=mv, scatter_mc=mc,
            interpret=True, precision="highest",
        ))
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6)

    def test_odd_sizes_and_tiles(self):
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
            sorted_row_gather,
        )

        # non-multiple N and E force the padding paths in the schedule
        x, ids, want = self._case(seed=3, N=777, E=3001, F=64, masked_tail=7)
        for be, bn in [(256, 128), (1024, 512)]:
            mv = max_vblocks_hint(ids, x.shape[0], block_e=be, block_n=bn)
            mc = max_chunks_hint(ids, x.shape[0], block_e=be, block_n=bn)
            got = np.asarray(sorted_row_gather(
                jnp.asarray(x), jnp.asarray(ids), max_vblocks=mv,
                block_e=be, block_n=bn, scatter_mc=mc, interpret=True,
                precision="highest",
            ))
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-6,
                                       err_msg=f"tiles ({be},{bn})")

    def test_vjp_is_sorted_segment_sum(self):
        from dgraph_tpu.ops.pallas_segment import (
            max_chunks_hint,
            max_vblocks_hint,
            sorted_row_gather,
        )

        x, ids, _ = self._case(seed=5)
        N = x.shape[0]
        mv = max_vblocks_hint(ids, N)
        mc = max_chunks_hint(ids, N)
        rng = np.random.default_rng(6)
        g = rng.standard_normal((ids.shape[0], x.shape[1])).astype(np.float32)

        def loss(xx):
            out = sorted_row_gather(
                xx, jnp.asarray(ids), max_vblocks=mv, scatter_mc=mc,
                interpret=True, precision="highest",
            )
            return (out * jnp.asarray(g)).sum()

        dx = np.asarray(jax.grad(loss)(jnp.asarray(x)))
        want = np.zeros_like(x)
        np.add.at(want, ids[ids < N], g[ids < N])
        np.testing.assert_allclose(dx, want, rtol=1e-5, atol=1e-5)

    def test_take_rows_routes_to_kernel_when_pinned(self):
        """config.use_pallas_gather=True + sorted hints must swap the
        forward to the kernel (structural: pallas_call in the jaxpr);
        auto must NOT (explicit-opt-in contract)."""
        from dgraph_tpu import config as cfg
        from dgraph_tpu.ops import local as L

        x = jnp.zeros((512, 32), jnp.float32)
        ids = jnp.asarray(np.sort(np.random.default_rng(0).integers(
            0, 512, 1024)).astype(np.int32))

        def has_pallas(flag):
            old = cfg.use_pallas_gather
            try:
                cfg.set_flags(use_pallas_gather=flag)
                jx = jax.make_jaxpr(lambda a: L.take_rows(
                    a, ids, indices_are_sorted=True,
                    pallas_hints=(512, 256, 2), gather_mv=2,
                ))(x)
                return "pallas_call" in str(jx)
            finally:
                cfg.set_flags(use_pallas_gather=old)

        # off-TPU take_rows also gates on backend; emulate the TPU branch
        # by checking _make_take_rows directly
        from dgraph_tpu.ops.local import _make_take_rows

        fn = _make_take_rows(512, True, 128, True, 512, 256, 2, 2)
        jx = jax.make_jaxpr(lambda a: fn(a, ids))(x)
        assert "pallas_call" in str(jx), "mv>0 must route to the kernel"
        assert has_pallas(None) is False  # auto = OFF on CPU regardless
