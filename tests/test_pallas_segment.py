"""Pallas sorted-segment-sum vs the jnp oracle (interpret mode on CPU —
the reference's CUDA-kernel-vs-dense-loop test pattern,
``tests/test_local_kernels.py:26-154``)."""

import numpy as np
import pytest

import jax.numpy as jnp

from dgraph_tpu.ops.pallas_segment import max_chunks_hint, sorted_segment_sum


@pytest.mark.parametrize("E,N,F", [(1000, 300, 16), (4096, 512, 128), (37, 8, 4)])
def test_matches_oracle(rng, E, N, F):
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N, block_e=256, block_n=256)
    got = sorted_segment_sum(
        jnp.asarray(data),
        jnp.asarray(ids),
        N,
        max_chunks_per_block=mc,
        interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, data)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_masked_rows_dropped(rng):
    """Out-of-range ids (the plan's padded-edge convention) contribute 0."""
    E, N, F = 512, 100, 8
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-50:] = N + 1  # padded edges
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids[:-50], data[:-50])
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_skewed_segments(rng):
    """Hub vertex with most of the edges (power-law worst case)."""
    E, N, F = 2000, 64, 8
    ids = np.concatenate([np.zeros(1500, np.int32), np.sort(rng.integers(1, N, 500))])
    ids = np.sort(ids).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, data)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


def test_grad_is_gather_transpose(rng):
    """VJP == g[ids] with OOB ids dropped (gather-bwd = scatter-sum duality,
    the reference pins the same pair in ``tests/test_NCCLCommPlan.py``)."""
    import jax

    E, N, F = 600, 100, 8
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-40:] = N + 1  # padded edges
    data = rng.normal(size=(E, F)).astype(np.float32)
    g_out = rng.normal(size=(N, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N)

    def loss(d):
        out = sorted_segment_sum(
            d, jnp.asarray(ids), N, max_chunks_per_block=mc, interpret=True
        )
        return (out * g_out).sum()

    got = jax.grad(loss)(jnp.asarray(data))
    expected = np.zeros_like(data)
    expected[:-40] = g_out[ids[:-40]]
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_grad_relu_input_op(rng):
    import jax

    E, N, F = 300, 50, 4
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    g_out = rng.normal(size=(N, F)).astype(np.float32)
    mc = max_chunks_hint(ids, N)

    def loss_pallas(d):
        return (
            sorted_segment_sum(
                d, jnp.asarray(ids), N, max_chunks_per_block=mc,
                interpret=True, input_op="relu",
            ) * g_out
        ).sum()

    def loss_ref(d):
        import jax.nn

        out = jax.ops.segment_sum(jax.nn.relu(d), jnp.asarray(ids), num_segments=N)
        return (out * g_out).sum()

    import jax

    got = jax.grad(loss_pallas)(jnp.asarray(data))
    want = jax.grad(loss_ref)(jnp.asarray(data))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_fused_relu_input_op(rng):
    """input_op='relu' == relu-then-sum (Fused_ReLU_Scatter_Kernel parity)."""
    E, N, F = 777, 128, 16
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.normal(size=(E, F)).astype(np.float32)
    got = sorted_segment_sum(
        jnp.asarray(data), jnp.asarray(ids), N,
        max_chunks_per_block=max_chunks_hint(ids, N), interpret=True,
        input_op="relu",
    )
    expected = np.zeros((N, F), np.float32)
    np.add.at(expected, ids, np.maximum(data, 0.0))
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)
