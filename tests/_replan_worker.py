"""Serve graph-delta worker for the chaos mid-replan atomicity test
(test_serve_control.py). Run as:

    python tests/_replan_worker.py <run_dir> init     # gen 0 + one delta
    python tests/_replan_worker.py <run_dir> replan   # fold deltas -> g+1

The test arms ``DGRAPH_CHAOS="serve.replan=sigterm@1"`` (kill at the
commit boundary: every generation-1 artifact durable, pointer not yet
flipped) or ``"plan.write=sigterm@2"`` (kill mid shard stream) around the
``replan`` phase and asserts the adoption contract: the pointer names the
OLD generation after the kill, and a chaos-free rerun resumes the build
and adopts generation 1 — old or new, never torn.

Host-side only (plan builds are numpy): no devices, no jitted step — the
adoption machinery under test is all host code, and tier-1 cannot afford
an XLA compile per subprocess.
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def main() -> None:
    run_dir, phase = sys.argv[1], sys.argv[2]
    from dgraph_tpu.serve import deltas

    if phase == "init":
        rng = np.random.default_rng(7)
        num_nodes, feat = 48, 4
        edges = np.stack([
            np.arange(num_nodes), (np.arange(num_nodes) + 1) % num_nodes
        ])
        feats = rng.normal(size=(num_nodes, feat)).astype(np.float32)
        world = deltas.init_world(
            run_dir, edges, feats, world_size=4,
            partition_method="block", pad_multiple=4,
        )
        rec = deltas.append_delta(
            run_dir,
            rng.normal(size=(3, feat)).astype(np.float32),
            np.array([[0, 48], [48, 49]]),
        )
        print(json.dumps({"init": world, "delta": rec}), flush=True)
    elif phase == "replan":
        world = deltas.replan(run_dir)
        print(json.dumps({"replan": world}), flush=True)
    else:
        raise SystemExit(f"unknown phase {phase!r}")


if __name__ == "__main__":
    main()
