"""GPipe-style pipeline parallelism (parallel/pipeline.py) vs sequential
stage application — values and gradients, on the 8-device mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.parallel.pipeline import pipeline_apply, stack_stage_params

S = 8  # stages = devices
M = 5  # microbatches
MB, F = 4, 16  # microbatch rows, features


def _mesh():
    devs = jax.devices()
    if len(devs) < S:
        pytest.skip(f"need {S} devices")
    return Mesh(np.array(devs[:S]), ("pipe",))


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _params(rng):
    return [
        {
            "w": rng.standard_normal((F, F)).astype(np.float32) * 0.5,
            "b": rng.standard_normal(F).astype(np.float32) * 0.1,
        }
        for _ in range(S)
    ]


def _sequential(params_list, x_micro):
    y = x_micro
    for p in params_list:
        p = jax.tree.map(jnp.asarray, p)
        y = jax.vmap(lambda xb: _stage_fn(p, xb))(y)
    return y


def test_pipeline_equals_sequential():
    mesh = _mesh()
    rng = np.random.default_rng(0)
    params_list = _params(rng)
    stacked = jax.tree.map(jnp.asarray, stack_stage_params(params_list))
    x = jnp.asarray(rng.standard_normal((M, MB, F)), jnp.float32)

    fn = jax.shard_map(
        lambda p, xm: pipeline_apply(_stage_fn, jax.tree.map(lambda l: l[0], p), xm, "pipe"),
        mesh=mesh,
        in_specs=(P("pipe"), P()),
        out_specs=P(),
        check_vma=False,
    )
    got = fn(stacked, x)
    want = _sequential(params_list, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5)


def test_pipeline_gradients_equal_sequential():
    mesh = _mesh()
    rng = np.random.default_rng(1)
    params_list = _params(rng)
    stacked = jax.tree.map(jnp.asarray, stack_stage_params(params_list))
    x = jnp.asarray(rng.standard_normal((M, MB, F)), jnp.float32)
    tgt = jnp.asarray(rng.standard_normal((M, MB, F)), jnp.float32)

    def loss_pipe(stacked, x):
        fn = jax.shard_map(
            lambda p, xm: pipeline_apply(
                _stage_fn, jax.tree.map(lambda l: l[0], p), xm, "pipe"
            ),
            mesh=mesh,
            in_specs=(P("pipe"), P()),
            out_specs=P(),
            check_vma=False,
        )
        return ((fn(stacked, x) - tgt) ** 2).sum()

    def loss_seq(stacked, x):
        params_list2 = [
            jax.tree.map(lambda l: l[i], stacked) for i in range(S)
        ]
        y = x
        for p in params_list2:
            y = jax.vmap(lambda xb, p=p: _stage_fn(p, xb))(y)
        return ((y - tgt) ** 2).sum()

    gp = jax.grad(loss_pipe)(stacked, x)
    gs = jax.grad(loss_seq)(stacked, x)
    for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5
        )
