"""Wire codec layer (dgraph_tpu.wire): format registry + byte-pricing
pins, numpy/jax codec parity, the resolution ladder, hub-row dedup
coverage, and end-to-end parity of compressed halo payloads across the
halo lowerings — fp32 identity bit-identical (forward AND backward),
bf16/fp8 within the pinned round-trip bounds on 2- and 4-shard graphs.

Compile budget (tests/README.md): the analysis-tier tests here are
compile-FREE (make_jaxpr / lower only); the execution tests reuse one
small graph per world size and pin several formats against the SAME
all_to_all baseline, so the whole file adds only tiny-shape compiles.
"""

import logging
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu import config as cfg
from dgraph_tpu import plan as pl
from dgraph_tpu.comm import collectives
from dgraph_tpu.comm.mesh import make_graph_mesh
from dgraph_tpu.plan import shard_edge_data, shard_vertex_data, unshard_vertex_data
from dgraph_tpu.testing import (
    dense_gather,
    dense_scatter_sum,
    spmd_apply,
    unshard_edge_data,
)
from dgraph_tpu.wire import spec as WS
from dgraph_tpu.wire.spec import (
    FP8_SCALE_BYTES,
    WIRE_FORMAT_NAMES,
    WIRE_FORMATS,
    WireFormat,
    delta_skip_rows,
    fp8_available,
    get_format,
    np_decode,
    np_encode,
    np_encode_compensated,
    np_roundtrip_bound,
    resolve_wire_format,
)

requires_fp8 = pytest.mark.skipif(
    not fp8_available(), reason="float8_e4m3fn dtype unavailable"
)

# Global relative-error pins for one wire trip through a REAL lowering
# (metric: max |got - want| / max |want|). Looser than the per-row
# np_roundtrip_bound because the dense oracle compares across rows with
# different maxima; a broken codec (wrong scale, dropped lanes) misses
# these by orders of magnitude.
FWD_BOUND = {"bf16": 8e-3, "fp8": 9e-2}
GRAD_BOUND = {"bf16": 5e-2, "fp8": 3.5e-1}


@pytest.fixture
def wire_flags():
    """Save/restore every flag the wire + halo ladders read."""
    saved = (cfg.wire_format, cfg.tuned_wire_format, cfg.halo_impl,
             cfg.tuned_halo_impl, cfg.use_pallas_p2p)
    yield
    cfg.set_flags(wire_format=saved[0], tuned_wire_format=saved[1],
                  halo_impl=saved[2], tuned_halo_impl=saved[3],
                  use_pallas_p2p=saved[4])


def _graph(rng, W, V=96, E=600):
    edges = rng.integers(0, V, size=(2, E))
    part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    return edges, part


def _rel_err(got, want):
    got = np.asarray(got, np.float64)
    want = np.asarray(want, np.float64)
    denom = max(float(np.max(np.abs(want))), 1e-12)
    return float(np.max(np.abs(got - want))) / denom


# ---------------------------------------------------------------------------
# registry + pricing pins (pure — what footprint/tuner/trace/HLO all price)
# ---------------------------------------------------------------------------


def test_registry_byte_pins():
    F, b = 128, 4  # f32 activations
    assert WIRE_FORMAT_NAMES == ("fp32", "bf16", "fp8"), (
        "registry order is the lossless-first tuner tie-break — "
        "reordering silently changes what ties adopt"
    )
    rows = {n: get_format(n).wire_row_bytes(F, b) for n in WIRE_FORMAT_NAMES}
    assert rows == {"fp32": 512, "bf16": 256, "fp8": 132}
    assert get_format("fp8").wire_feat_dim(F) == F + FP8_SCALE_BYTES
    assert get_format("fp32").compression_ratio(F, b) == 1.0
    assert get_format("bf16").compression_ratio(F, b) == 2.0
    assert get_format("fp8").compression_ratio(F, b) == 512 / 132


def test_format_serialization_roundtrip():
    for name in WIRE_FORMAT_NAMES:
        fmt = get_format(name)
        back = WireFormat.from_dict(fmt.to_dict())
        assert back == fmt
        assert back.format_id == fmt.format_id
    with pytest.raises(ValueError, match="unknown wire format"):
        get_format("int4")


# ---------------------------------------------------------------------------
# numpy reference codecs: round-trip bounds + error compensation
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("name", WIRE_FORMAT_NAMES)
def test_np_roundtrip_within_pinned_bound(rng, name):
    if name == "fp8" and not fp8_available():
        pytest.skip("float8_e4m3fn dtype unavailable")
    x = rng.normal(size=(16, 8)).astype(np.float32)
    x[3] *= 1e-4   # tiny rows exercise the per-row fp8 scale
    x[5] *= 1e4
    x[7] = 0.0     # all-zero rows must decode to exactly 0.0
    y = np_encode(x, name)
    back = np_decode(y, name, np.float32)
    bound = np_roundtrip_bound(name)
    if name == "fp32":
        assert (back == x).all()
        return
    row_max = np.max(np.abs(x), axis=-1, keepdims=True)
    err = np.abs(back - x) / np.maximum(row_max, 1e-30)
    assert float(err.max()) <= bound, (name, float(err.max()))
    assert (back[7] == 0.0).all(), "all-zero row must decode to exact zeros"


@requires_fp8
def test_np_wrong_scale_blows_the_bound(rng):
    """Vacuity: a decode that disagrees with its encode scale must be
    caught by the same bound the parity tests pin."""
    x = rng.normal(size=(8, 8)).astype(np.float32)
    y = np_encode(x, "fp8", _scale_gain=2.0)
    back = np_decode(y, "fp8", np.float32)
    row_max = np.max(np.abs(x), axis=-1, keepdims=True)
    err = np.abs(back - x) / np.maximum(row_max, 1e-30)
    assert float(err.max()) > np_roundtrip_bound("fp8")


@requires_fp8
def test_compensated_accumulation_drift_bound(rng):
    """Error feedback: the receiver's T-step accumulation of decoded
    payloads telescopes to (fp32 sum - final residual), so its relative
    drift stays within ONE round-trip bound instead of growing with T."""
    T, F = 64, 8
    steps = rng.uniform(0.5, 1.5, size=(T, 4, F)).astype(np.float32)
    acc_fp32 = steps.sum(axis=0)
    resid = None
    acc_comp = np.zeros((4, F), np.float32)
    acc_plain = np.zeros((4, F), np.float32)
    for x in steps:
        y, resid = np_encode_compensated(x, resid, "fp8")
        acc_comp += np_decode(y, "fp8", np.float32)
        acc_plain += np_decode(np_encode(x, "fp8"), "fp8", np.float32)
    err_comp = _rel_err(acc_comp, acc_fp32)
    err_plain = _rel_err(acc_plain, acc_fp32)
    assert err_comp <= np_roundtrip_bound("fp8"), err_comp
    # uncompensated rounding of all-positive steps drifts with T
    assert err_comp < err_plain, (err_comp, err_plain)


# ---------------------------------------------------------------------------
# jax codecs vs the numpy ground truth (eager, tiny ops)
# ---------------------------------------------------------------------------


def test_jax_transform_identity_cases():
    from dgraph_tpu.wire.codec import make_wire_transform

    assert make_wire_transform("fp32", "float32") == (None, None)
    # activations already riding the wire dtype: casts would be noise
    assert make_wire_transform("bf16", "bfloat16") == (None, None)


def test_jax_bf16_matches_numpy(rng):
    from dgraph_tpu.wire.codec import make_wire_transform

    enc, dec = make_wire_transform("bf16", "float32")
    x = rng.normal(size=(6, 8)).astype(np.float32)
    y_j = np.asarray(enc(jnp.asarray(x)))
    y_np = np_encode(x, "bf16")
    assert y_j.dtype == y_np.dtype and (
        y_j.view(np.uint8) == y_np.view(np.uint8)
    ).all()
    back = np.asarray(dec(jnp.asarray(y_np)))
    assert (back == np_decode(y_np, "bf16", np.float32)).all()


@requires_fp8
def test_jax_fp8_matches_numpy(rng):
    from dgraph_tpu.wire.codec import make_wire_transform

    enc, dec = make_wire_transform("fp8", "float32")
    x = rng.normal(size=(6, 8)).astype(np.float32)
    x[2] = 0.0
    y_j = np.asarray(enc(jnp.asarray(x)))
    y_np = np_encode(x, "fp8")
    assert y_j.shape == y_np.shape == (6, 8 + FP8_SCALE_BYTES)
    assert (y_j == y_np).all(), "fp8 packing must match the reference bit for bit"
    back = np.asarray(dec(jnp.asarray(y_np)))
    assert (back == np_decode(y_np, "fp8", np.float32)).all()


def test_bf16_codec_cotangent_rides_the_wire_encoded(rng):
    """The custom-VJP pair: encode's bwd DECODES the cotangent (and
    vice versa) — AD never differentiates through the cast, and the
    cotangent crosses the wire in the same format as the forward."""
    from dgraph_tpu.wire.codec import make_wire_codec

    encode, decode = make_wire_codec("bf16", "float32")
    x = jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32))
    g_wire = jnp.asarray(rng.normal(size=(4, 8))).astype(jnp.bfloat16)
    _, vjp = jax.vjp(encode, x)
    (ct,) = vjp(g_wire)
    want = np_decode(np.asarray(g_wire), "bf16", np.float32)
    assert (np.asarray(ct) == want).all()
    g_act = jnp.asarray(rng.normal(size=(4, 8)).astype(np.float32))
    _, vjp = jax.vjp(decode, encode(x))
    (ct,) = vjp(g_act)
    assert (np.asarray(ct).view(np.uint8)
            == np_encode(np.asarray(g_act), "bf16").view(np.uint8)).all()


# ---------------------------------------------------------------------------
# resolution ladder (pure)
# ---------------------------------------------------------------------------


DELTAS = (1, 2)  # any non-empty cross-rank traffic


def test_resolver_env_beats_record_beats_plan(wire_flags):
    cfg.set_flags(wire_format="bf16", tuned_wire_format="fp32")
    assert resolve_wire_format(4, DELTAS, plan_format="fp32") == ("bf16", "env")
    cfg.set_flags(wire_format="auto", tuned_wire_format="bf16")
    assert resolve_wire_format(4, DELTAS, plan_format="fp32") == (
        "bf16", "record"
    )
    cfg.set_flags(wire_format="auto", tuned_wire_format=None)
    assert resolve_wire_format(4, DELTAS, plan_format="bf16") == (
        "bf16", "plan"
    )


def test_resolver_default_rows(wire_flags):
    cfg.set_flags(wire_format="auto", tuned_wire_format=None)
    # the attached fp32 default is not an adoption: source says 'default'
    assert resolve_wire_format(4, DELTAS, plan_format="fp32") == (
        "fp32", "default"
    )
    # no cross-rank traffic: there is no wire to encode
    assert resolve_wire_format(1, ()) == ("fp32", "plan")


def test_resolver_degrades_with_one_warning(wire_flags, caplog):
    cfg.set_flags(wire_format="int4", tuned_wire_format=None)
    WS._degrade_warned.clear()
    with caplog.at_level(logging.WARNING, logger="dgraph_tpu.wire"):
        assert resolve_wire_format(4, DELTAS, plan_format="bf16") == (
            "bf16", "plan"
        )
        n_first = len(caplog.records)
        assert n_first == 1, "unknown env pin must warn exactly once"
        assert resolve_wire_format(4, DELTAS, plan_format="bf16") == (
            "bf16", "plan"
        )
        assert len(caplog.records) == n_first, "repeat resolution re-warned"
    # fp8 without the e4m3 dtype degrades the same way
    cfg.set_flags(wire_format="fp8")
    WS._degrade_warned.clear()
    assert resolve_wire_format(4, DELTAS, plan_format="fp32", fp8_ok=False) == (
        "fp32", "default"
    )


def test_plan_attaches_buildtime_resolution(rng, wire_flags):
    edges, part = _graph(rng, 4)
    cfg.set_flags(wire_format="auto", tuned_wire_format=None)
    plan, _ = pl.build_edge_plan(edges, part, world_size=4)
    assert plan.wire_format == "fp32"
    cfg.set_flags(wire_format="bf16")
    plan_b, _ = pl.build_edge_plan(edges, part, world_size=4)
    assert plan_b.wire_format == "bf16"
    # a cache round-trip keeps the adopted codec even when the loading
    # process has no env pin / record (rank-identical statics)
    cfg.set_flags(wire_format="auto")
    assert resolve_wire_format(
        4, tuple(plan_b.halo_deltas), plan_format=plan_b.wire_format
    ) == ("bf16", "plan")


def test_sharded_plan_roundtrip_keeps_wire_format(rng, tmp_path, wire_flags):
    from dgraph_tpu.plan import build_plan_shards, load_sharded_plan

    edges, part = _graph(rng, 4)
    cfg.set_flags(wire_format="bf16", tuned_wire_format=None)
    build_plan_shards(
        edges, part, out_dir=str(tmp_path), world_size=4, write_layout=False
    )
    cfg.set_flags(wire_format="auto")
    sub, _ = load_sharded_plan(str(tmp_path), ranks=[0], load_layout=False)
    assert sub.wire_format == "bf16"


def test_serve_health_wire_provenance(rng, wire_flags):
    from dgraph_tpu.serve.health import _wire_provenance

    assert _wire_provenance(None) is None
    edges, part = _graph(rng, 4)
    cfg.set_flags(wire_format="auto", tuned_wire_format=None)
    plan, _ = pl.build_edge_plan(edges, part, world_size=4)
    assert _wire_provenance(plan) == {"format": "fp32", "source": "default"}
    cfg.set_flags(tuned_wire_format="bf16")
    assert _wire_provenance(plan) == {"format": "bf16", "source": "record"}


# ---------------------------------------------------------------------------
# footprint pricing: the acceptance cut (pure)
# ---------------------------------------------------------------------------


def test_footprint_bf16_cuts_wire_bytes_at_least_45pct(rng, wire_flags):
    """The ISSUE's acceptance pin on an arxiv-shaped workload (sparse
    power-law-ish graph, F=128 f32 activations): pricing the halo
    exchange at bf16 must cut wire bytes >= 45% vs fp32 — and the priced
    rows must be exactly the registry's wire_row_bytes."""
    from dgraph_tpu.obs.footprint import plan_footprint

    W, F = 4, 128
    edges, part = _graph(rng, W, V=400, E=2800)
    cfg.set_flags(wire_format="auto", tuned_wire_format=None)
    plan, _ = pl.build_edge_plan(edges, part, world_size=W)

    def exchange_at(fmt):
        cfg.set_flags(wire_format=fmt)
        return plan_footprint(plan, "float32", feat_dim=F)[
            "collectives"]["halo_exchange"]

    ex = {f: exchange_at(f) for f in ("fp32", "bf16", "fp8")
          if f != "fp8" or fp8_available()}
    for name, rep in ex.items():
        assert rep["wire_format"] == name
        assert rep["wire_row_bytes"] == get_format(name).wire_row_bytes(F, 4)
        assert rep["compression_ratio"] == round(
            get_format(name).compression_ratio(F, 4), 4
        )
    base = ex["fp32"]["ici_bytes_total"]
    assert base > 0
    for name, rep in ex.items():
        if name == "fp32":
            continue
        cut = 1.0 - rep["ici_bytes_total"] / base
        assert cut >= 0.45, (name, cut)
        # byte-EXACT scaling: same rows, re-priced per row
        rows = base // ex["fp32"]["wire_row_bytes"]
        assert rep["ici_bytes_total"] == rows * rep["wire_row_bytes"]


def test_delta_skip_accounting_matches_plan(rng):
    edges, part = _graph(rng, 4)
    plan, _ = pl.build_edge_plan(edges, part, world_size=4)
    acc = delta_skip_rows(
        plan.halo_pair_rows, plan.world_size, plan.halo.s_pad
    )
    assert acc["num_halo_deltas"] == len(plan.halo_deltas)
    assert acc["live_rows_max_shard"] <= acc["a2a_rows_per_shard"]
    assert acc["ppermute_rows_per_shard"] == (
        len(plan.halo_deltas) * plan.halo.s_pad
    )


# ---------------------------------------------------------------------------
# hub-row dedup: verified coverage on a real plan's send tables (pure)
# ---------------------------------------------------------------------------


def test_dedup_star_graph_verified_coverage(rng):
    """A star graph concentrates demand on vertex 0's row: the dedup
    pass must find the hub, cut the owner's egress to one direct send,
    and the relay structure must still deliver every original
    (needer, src, row) demand exactly once."""
    from dgraph_tpu.wire.dedup import (
        build_dedup_plan,
        dedup_stats,
        detect_hub_rows,
        verify_dedup_coverage,
    )

    V, E, W = 16, 64, 4
    edges = np.stack([np.zeros(E, np.int64), rng.integers(0, V, E)])
    part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    plan, _ = pl.build_edge_plan(edges, part, world_size=W, edge_owner="dst")
    send_idx = np.asarray(plan.halo.send_idx)
    send_mask = np.asarray(plan.halo.send_mask)
    hubs = detect_hub_rows(send_idx, send_mask)
    assert hubs, "star graph must surface at least one hub row"
    assert max(len(h.needers) for h in hubs) >= 2
    dplan = build_dedup_plan(send_idx, send_mask, s_pad=plan.halo.s_pad)
    assert verify_dedup_coverage(dplan, send_idx, send_mask) == []
    stats = dedup_stats(dplan, send_idx, send_mask)
    assert stats["owner_egress_rows_saved"] > 0
    assert stats["relay_rows"] == stats["owner_egress_rows_saved"]


def test_dedup_identity_on_hubless_traffic():
    """Pairwise-unique traffic: no hubs, no relays, and the direct
    schedule covers the ORIGINAL matrix untouched."""
    from dgraph_tpu.wire.dedup import build_dedup_plan, verify_dedup_coverage

    W, S = 4, 3
    send_idx = np.zeros((W, W, S), np.int32)
    send_mask = np.zeros((W, W, S), np.float32)
    for s in range(W):
        for d in range(W):
            if s != d:
                send_idx[s, d] = [10 * s + 2 * d, 10 * s + 2 * d + 1, 0]
                send_mask[s, d] = [1, 1, 0]
    dplan = build_dedup_plan(send_idx, send_mask, s_pad=S)
    assert not dplan.hubs and not dplan.relay_rounds
    assert verify_dedup_coverage(dplan, send_idx, send_mask) == []


# ---------------------------------------------------------------------------
# analysis tiers under pinned formats (compile-free: trace + lower only)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def audit_workload_f32():
    # f32 compute so the bf16/fp8 codecs actually engage (the audit
    # workload's default bf16 compute makes bf16 the identity format)
    from dgraph_tpu.analysis.trace import build_audit_workload

    return build_audit_workload(2, compute_dtype="float32")


@pytest.mark.parametrize("fmt", ["bf16", "fp8"])
def test_trace_audit_green_under_pinned_format(
    audit_workload_f32, wire_flags, fmt
):
    """Every (program, lowering) pair still passes the byte-exact trace
    audit with a compressed wire: traced operand bytes == footprint-
    priced bytes at the ENCODED width (fp8's F+4 scale lanes included)."""
    if fmt == "fp8" and not fp8_available():
        pytest.skip("float8_e4m3fn dtype unavailable")
    from dgraph_tpu.analysis.trace import audit_workload

    cfg.set_flags(wire_format=fmt, tuned_wire_format=None)
    rep = audit_workload(audit_workload_f32)
    assert rep["ok"], rep["failures"]
    ops = [op for p in rep["programs"] for op in p["collective_operands"]]
    assert ops
    for op in ops:
        assert op["traced_bytes"] == op["footprint_bytes"]


@requires_fp8
def test_hlo_audit_green_under_fp8_p2p(audit_workload_f32, wire_flags):
    """The uint8 wire payload survives lowering as the p2p send tile:
    the DMA-artifact classifier must price it (F+4 scale lanes), not
    report it as an unscheduled collective."""
    from dgraph_tpu.analysis import hlo as H

    cfg.set_flags(wire_format="fp8", tuned_wire_format=None)
    rep = H.audit_workload_hlo(
        audit_workload_f32, impls=("all_to_all", "pallas_p2p")
    )
    assert rep["ok"], rep["failures"]
    tiles = [p for p in rep["programs"] if p["impl"] == "pallas_p2p"]
    assert tiles and all(p["num_tile_gathers"] > 0 for p in tiles)


def test_hlo_audit_green_under_bf16(audit_workload_f32, wire_flags):
    """The LOWERED modules agree too: StableHLO collective operands are
    byte-exact against the bf16-priced footprint (the wire cast must
    survive XLA lowering, not just tracing)."""
    from dgraph_tpu.analysis import hlo as H

    cfg.set_flags(wire_format="bf16", tuned_wire_format=None)
    rep = H.audit_workload_hlo(audit_workload_f32)
    assert rep["ok"], rep["failures"]
    rows = 0
    for p in rep["programs"]:
        for op in p["collective_operands"]:
            assert op["bytes"] == op["footprint_bytes"] > 0, (p["impl"], op)
            rows += 1
    assert rows > 0


def test_fp32_identity_jaxpr_is_unchanged(rng, wire_flags):
    """The structural identity guarantee: pinning wire_format='fp32'
    traces the EXACT jaxpr the default path traces, forward and grad —
    the codec layer adds nothing (so bit-identity is by construction,
    not by luck)."""
    W, F = 4, 6
    edges, part = _graph(rng, W)
    cfg.set_flags(wire_format="auto", tuned_wire_format=None,
                  halo_impl="all_to_all")
    plan, _ = pl.build_edge_plan(edges, part, world_size=W)
    mesh = make_graph_mesh(ranks_per_graph=W, num_replicas=8 // W)
    xs = jnp.zeros((W, plan.n_src_pad, F), jnp.float32)
    ct = jnp.zeros((W, plan.e_pad, F), jnp.float32)

    def fwd(p, x):
        return spmd_apply(mesh, collectives.gather, p, x,
                          static_args=("src", "graph"))

    def loss(p, x):
        return jnp.sum(fwd(p, x) * ct)

    def jaxprs():
        # custom-vjp params print their bwd closures' memory addresses;
        # strip them so the comparison is structural
        return tuple(
            re.sub(r" at 0x[0-9a-f]+", "", s) for s in (
                str(jax.make_jaxpr(fwd)(plan, xs)),
                str(jax.make_jaxpr(jax.grad(loss, argnums=1))(plan, xs)),
            )
        )

    auto = jaxprs()
    cfg.set_flags(wire_format="fp32")
    assert jaxprs() == auto
    # and the lossy format is NOT a no-op on the same program
    cfg.set_flags(wire_format="bf16")
    assert jaxprs() != auto


# ---------------------------------------------------------------------------
# execution parity across lowerings (the file's only compiles)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module", params=[2, 4])
def wire_case(request):
    rng = np.random.default_rng(7)
    W = request.param
    V, E = (48, 300) if W == 2 else (96, 600)
    edges, part = _graph(rng, W, V, E)
    plan, layout = pl.build_edge_plan(
        edges, part, world_size=W, overlap=True
    )
    assert plan.halo_schedule is not None
    mesh = make_graph_mesh(ranks_per_graph=W, num_replicas=8 // W)
    return W, edges, part, plan, layout, mesh


def _gather_once(mesh, plan, xs, *, fmt, impl):
    cfg.set_flags(wire_format=fmt, tuned_wire_format=None, halo_impl=impl,
                  use_pallas_p2p=(impl == "pallas_p2p"))
    return np.asarray(spmd_apply(
        mesh, collectives.gather, plan, xs, static_args=("src", "graph")
    ))


def test_fp32_identity_execution_bitwise(wire_case, wire_flags):
    W, edges, part, plan, layout, mesh = wire_case
    if W != 4:
        pytest.skip("one world size is enough for the executed identity pin")
    rng = np.random.default_rng(11)
    x = rng.normal(size=(len(part), 6)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    base = _gather_once(mesh, plan, xs, fmt="auto", impl="all_to_all")
    got = _gather_once(mesh, plan, xs, fmt="fp32", impl="all_to_all")
    assert (got == base).all(), "fp32 identity drifted from the default path"
    np.testing.assert_allclose(
        unshard_edge_data(got, layout), dense_gather(x, edges, "src"),
        rtol=1e-6,
    )


@pytest.mark.parametrize("fmt", ["bf16", "fp8"])
def test_lossy_gather_forward_within_bound(wire_case, wire_flags, fmt):
    if fmt == "fp8" and not fp8_available():
        pytest.skip("float8_e4m3fn dtype unavailable")
    W, edges, part, plan, layout, mesh = wire_case
    rng = np.random.default_rng(13)
    x = rng.normal(size=(len(part), 6)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    got = _gather_once(mesh, plan, xs, fmt=fmt, impl="all_to_all")
    err = _rel_err(unshard_edge_data(got, layout),
                   dense_gather(x, edges, "src"))
    assert err <= FWD_BOUND[fmt], (W, fmt, err)


def test_bf16_forward_parity_across_lowerings(wire_case, wire_flags):
    """Every lowering quantizes the SAME per-row payloads: transports
    may differ in routing, never in codec arithmetic."""
    W, edges, part, plan, layout, mesh = wire_case
    if W != 4:
        pytest.skip("cross-lowering sweep runs once, on the 4-shard ring")
    rng = np.random.default_rng(17)
    x = rng.normal(size=(len(part), 6)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    out = {impl: _gather_once(mesh, plan, xs, fmt="bf16", impl=impl)
           for impl in ("all_to_all", "ppermute", "overlap", "sched",
                        "pallas_p2p")}
    base = out["all_to_all"]
    for impl in ("overlap", "sched", "pallas_p2p"):
        assert (out[impl] == base).all(), f"{impl} differs from all_to_all"
    np.testing.assert_allclose(out["ppermute"], base, rtol=1e-6, atol=1e-6)
    err = _rel_err(unshard_edge_data(base, layout),
                   dense_gather(x, edges, "src"))
    assert err <= FWD_BOUND["bf16"], err


@requires_fp8
def test_fp8_forward_parity_sched_vs_a2a(wire_case, wire_flags):
    W, edges, part, plan, layout, mesh = wire_case
    if W != 2:
        pytest.skip("the fp8 cross-lowering pin runs once, on 2 shards")
    rng = np.random.default_rng(19)
    x = rng.normal(size=(len(part), 6)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    a2a = _gather_once(mesh, plan, xs, fmt="fp8", impl="all_to_all")
    sched = _gather_once(mesh, plan, xs, fmt="fp8", impl="sched")
    assert (sched == a2a).all(), "sched fp8 payload differs from all_to_all"


def _gather_grad_once(mesh, plan, xs, ct_sh, *, fmt, impl):
    cfg.set_flags(wire_format=fmt, tuned_wire_format=None, halo_impl=impl,
                  use_pallas_p2p=(impl == "pallas_p2p"))

    def loss_fn(xs_):
        out = spmd_apply(mesh, collectives.gather, plan, xs_,
                         static_args=("src", "graph"))
        return jnp.sum(out * ct_sh)

    with jax.set_mesh(mesh):
        return np.asarray(jax.jit(jax.grad(loss_fn))(xs))


@pytest.mark.parametrize("fmt", ["bf16", "fp8"])
def test_lossy_gather_grad_within_bound(wire_case, wire_flags, fmt):
    """Backward: the cotangent rides the reverse wire ENCODED (the
    custom-VJP trips / hand-built reverse legs), so the sharded gradient
    tracks the dense transpose within the format's bound."""
    if fmt == "fp8" and not fp8_available():
        pytest.skip("float8_e4m3fn dtype unavailable")
    W, edges, part, plan, layout, mesh = wire_case
    if W != 2:
        pytest.skip("grad parity runs once, on 2 shards")
    rng = np.random.default_rng(23)
    V, F = len(part), 3
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    ct = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
    ct_sh = jnp.asarray(shard_edge_data(ct, layout, plan.e_pad))
    grad = _gather_grad_once(mesh, plan, xs, ct_sh, fmt=fmt, impl="all_to_all")
    err = _rel_err(unshard_vertex_data(grad, layout.src_counts),
                   dense_scatter_sum(ct, edges, "src", V))
    assert err <= GRAD_BOUND[fmt], (fmt, err)


def test_bf16_grad_parity_across_lowerings(wire_case, wire_flags):
    W, edges, part, plan, layout, mesh = wire_case
    if W != 2:
        pytest.skip("grad cross-lowering pin runs once, on 2 shards")
    rng = np.random.default_rng(29)
    V, F = len(part), 3
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    ct = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
    ct_sh = jnp.asarray(shard_edge_data(ct, layout, plan.e_pad))
    grads = {impl: _gather_grad_once(mesh, plan, xs, ct_sh,
                                     fmt="bf16", impl=impl)
             for impl in ("all_to_all", "overlap", "sched")}
    for impl in ("overlap", "sched"):
        assert (grads[impl] == grads["all_to_all"]).all(), (
            f"{impl} bf16 backward differs from all_to_all"
        )


def test_config_flip_cannot_recompile_a_served_program(
    wire_case, wire_flags
):
    """The serve discipline: the format is resolved ONCE at trace time
    and baked into the executable as a static — flipping the env pin
    under a live jitted program changes NOTHING (no retrace, no
    recompile, bit-identical outputs). Re-resolution (a new engine /
    bench round) is the only way to change wire."""
    W, edges, part, plan, layout, mesh = wire_case
    if W != 2:
        pytest.skip("zero-recompile pin runs once, on 2 shards")
    rng = np.random.default_rng(31)
    x = rng.normal(size=(len(part), 4)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    cfg.set_flags(wire_format="bf16", tuned_wire_format=None,
                  halo_impl="all_to_all")
    f = jax.jit(lambda p, x_: spmd_apply(
        mesh, collectives.gather, p, x_, static_args=("src", "graph")
    ))
    with jax.set_mesh(mesh):
        first = np.asarray(f(plan, xs))
        cfg.set_flags(wire_format="fp32")
        second = np.asarray(f(plan, xs))
    assert (first == second).all(), (
        "a config flip leaked into a compiled executable"
    )
    cache_size = getattr(f, "_cache_size", None)
    if cache_size is not None:
        assert cache_size() == 1, "config flip forced a retrace"
