"""Compiled halo schedules (dgraph_tpu.sched): compiler/IR invariants,
plan attachment, the resolver ladder's 'sched' row, footprint/trace byte
equality, and bit-identical execution vs the all_to_all lowering on 2-
and 4-shard graphs."""

import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu import config as cfg
from dgraph_tpu import plan as pl
from dgraph_tpu.comm import collectives
from dgraph_tpu.comm.mesh import make_graph_mesh
from dgraph_tpu.plan import shard_edge_data, shard_vertex_data, unshard_vertex_data
from dgraph_tpu.sched import compile_halo_schedule, verify_schedule
from dgraph_tpu.sched.ir import HaloSchedule
from dgraph_tpu.testing import (
    dense_gather,
    dense_scatter_sum,
    spmd_apply,
    unshard_edge_data,
)


def _graph(rng, W, V=96, E=600):
    edges = rng.integers(0, V, size=(2, E))
    part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    return edges, part


# ---------------------------------------------------------------------------
# compiler + plan attachment (host-only, zero compiles)
# ---------------------------------------------------------------------------


def test_plan_attaches_verified_schedule(rng):
    W = 4
    edges, part = _graph(rng, W)
    plan, _ = pl.build_edge_plan(edges, part, world_size=W)
    assert plan.halo_pair_rows, "traffic matrix missing from the plan"
    sched = plan.halo_schedule
    assert isinstance(sched, HaloSchedule)
    assert verify_schedule(sched, plan.halo_pair_rows) == []
    # deterministic: an identical build compiles the identical schedule
    plan2, _ = pl.build_edge_plan(edges, part, world_size=W)
    assert plan2.halo_schedule.schedule_id == sched.schedule_id


def test_schedule_roundtrip_identity(rng):
    W = 4
    edges, part = _graph(rng, W)
    plan, _ = pl.build_edge_plan(edges, part, world_size=W)
    sched = plan.halo_schedule
    back = HaloSchedule.from_dict(sched.to_dict())
    assert back == sched
    assert back.schedule_id == sched.schedule_id


def test_assembled_plan_carries_identical_schedule(rng, tmp_path):
    # the sharded-artifact path must compile the SAME schedule the
    # monolithic build attached (rank-identical statics: deadlock class)
    from dgraph_tpu.plan import build_plan_shards, load_sharded_plan

    W = 4
    edges, part = _graph(rng, W)
    plan, _ = pl.build_edge_plan(edges, part, world_size=W)
    build_plan_shards(
        edges, part, out_dir=str(tmp_path), world_size=W, write_layout=False
    )
    for r in range(W):
        sub, _ = load_sharded_plan(str(tmp_path), ranks=[r], load_layout=False)
        assert sub.halo_pair_rows == plan.halo_pair_rows
        assert sub.halo_schedule.schedule_id == plan.halo_schedule.schedule_id


def test_sched_selftest_green():
    from dgraph_tpu.sched.__main__ import _selftest

    out = _selftest()
    assert out["ok"], out["failures"]


def test_large_pairs_split_into_rounds():
    # one hub pair 64 rows + small peers: recursive doubling must chop
    # the hub so no single round is the whole transfer
    pair_rows = (
        (0, 64, 1, 1),
        (1, 0, 1, 0),
        (1, 1, 0, 0),
        (1, 0, 1, 0),
    )
    sched = compile_halo_schedule(pair_rows, s_pad=64, world_size=4)
    assert verify_schedule(sched, pair_rows) == []
    hub = [
        t for rnd in sched.rounds for t in rnd.transfers
        if t.src == 0 and t.dst == 1
    ]
    assert len(hub) > 1, "64-row hub pair was never split"
    assert max(r.row_count for r in sched.rounds) < 64


# ---------------------------------------------------------------------------
# satellite 1: the heuristic weighs per-delta row counts, not delta count
# ---------------------------------------------------------------------------


def test_pick_halo_impl_weighs_row_counts():
    W = 8
    deltas = (1, 2, 3, 4, 5)  # 5 > W//2: the old count-only rule says a2a
    assert pl.pick_halo_impl(W, deltas) == "all_to_all"
    # skewed matrix: one pair carries ~all rows -> effectively ONE round
    # of traffic; the weighted rule must pick ppermute
    skewed = tuple(
        tuple(100 if (i, j) == (0, 1) else (1 if i != j else 0)
              for j in range(W))
        for i in range(W)
    )
    assert pl.pick_halo_impl(W, deltas, skewed) == "ppermute"
    # uniform matrix reduces to the old rule
    uniform = tuple(
        tuple(0 if i == j else 5 for j in range(W)) for i in range(W)
    )
    assert pl.pick_halo_impl(W, deltas, uniform) == "all_to_all"


# ---------------------------------------------------------------------------
# satellite 3: the resolver ladder's 'sched' row
# ---------------------------------------------------------------------------


@pytest.fixture
def ladder():
    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    pl._sched_warned.clear()
    yield
    cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])
    pl._sched_warned.clear()


def test_env_pin_selects_sched(ladder):
    cfg.set_flags(halo_impl="sched", tuned_halo_impl=None)
    assert pl.resolve_halo_impl(4, (1, 2), sched_available=True) == (
        "sched", "env",
    )


def test_env_pin_beats_tuned_record(ladder):
    cfg.set_flags(halo_impl="sched", tuned_halo_impl="all_to_all")
    assert pl.resolve_halo_impl(4, (1, 2), sched_available=True) == (
        "sched", "env",
    )
    cfg.set_flags(halo_impl="all_to_all", tuned_halo_impl="sched")
    assert pl.resolve_halo_impl(4, (1, 2), sched_available=True) == (
        "all_to_all", "env",
    )


def test_tuned_record_selects_sched(ladder):
    cfg.set_flags(halo_impl="auto", tuned_halo_impl="sched")
    assert pl.resolve_halo_impl(4, (1, 2), sched_available=True) == (
        "sched", "record",
    )


def test_pin_degrades_with_one_warning_when_no_schedule(ladder, caplog):
    cfg.set_flags(halo_impl="sched", tuned_halo_impl=None)
    with caplog.at_level(logging.WARNING, logger="dgraph_tpu.plan"):
        impl, source = pl.resolve_halo_impl(4, (1, 2), sched_available=False)
        assert impl != "sched" and source == "heuristic"
        warned = [r for r in caplog.records if "sched" in r.getMessage()]
        assert len(warned) == 1, "pinned-but-unavailable sched must warn"
        # second resolution: same degrade, NO second warning
        impl2, _ = pl.resolve_halo_impl(4, (1, 2), sched_available=False)
        assert impl2 == impl
        warned = [r for r in caplog.records if "sched" in r.getMessage()]
        assert len(warned) == 1, "degrade warning must fire once per source"


def test_heuristic_never_picks_sched(ladder):
    cfg.set_flags(halo_impl="auto", tuned_halo_impl=None)
    for deltas in ((1,), (1, 2), tuple(range(1, 8))):
        impl, source = pl.resolve_halo_impl(8, deltas, sched_available=True)
        assert source == "heuristic"
        assert impl != "sched", "un-A/B'd sched auto-picked by heuristic"


# ---------------------------------------------------------------------------
# satellite 2: the sched_compile ledger record kind
# ---------------------------------------------------------------------------


def test_ledger_ingests_sched_compile(tmp_path):
    from dgraph_tpu.obs.ledger import ingest, read_ledger

    obj = {
        "kind": "sched_compile",
        "workload": {"world_size": 4, "nodes": 96, "feat_dim": 8},
        "schedule_id": "abc123def456",
        "rounds": 3, "transfers": 5,
        "operand_bytes_per_shard": 4096,
        "round_rows": [64, 32, 32],
        "exposed_us": 7.5,
    }
    assert ingest(obj, "test", str(tmp_path))["appended"] == 1
    entries, _ = read_ledger(str(tmp_path))
    (e,) = [x for x in entries if x["kind"] == "sched_compile"]
    assert e["metrics"]["rounds_count"] == 3
    assert e["metrics"]["transfers_count"] == 5
    assert e["metrics"]["operand_bytes"] == 4096
    assert e["meta"]["schedule_id"] == "abc123def456"
    assert e["halo_impl"] == "sched"
    # idempotent by entry id
    again = ingest(obj, "test", str(tmp_path))
    assert again["appended"] == 0 and again["deduped"] == 1


# ---------------------------------------------------------------------------
# footprint pricing == traced operand bytes, per round (zero compiles)
# ---------------------------------------------------------------------------


def test_footprint_prices_traced_rounds(rng):
    from dgraph_tpu.analysis.trace import collect_collectives
    from dgraph_tpu.obs.footprint import plan_footprint

    W, F = 4, 8
    edges, part = _graph(rng, W)
    plan, _ = pl.build_edge_plan(edges, part, world_size=W)
    sched = plan.halo_schedule
    fp = plan_footprint(plan, "float32", feat_dim=F)
    sched_fp = fp["collectives"]["halo_exchange"]["sched"]
    assert sched_fp["rounds"] == sched.num_rounds
    assert sched_fp["schedule_id"] == sched.schedule_id
    assert sum(sched_fp["round_bytes_per_shard"]) == (
        sched_fp["operand_bytes_per_shard"]
    )

    saved = cfg.halo_impl
    cfg.set_flags(halo_impl="sched")
    try:
        mesh = make_graph_mesh(
            ranks_per_graph=W, devices=jax.devices()[:W]
        )
        xs = np.zeros((W, plan.n_src_pad, F), np.float32)
        jaxpr = jax.make_jaxpr(
            lambda p, x: spmd_apply(
                mesh, collectives.gather, p, x, static_args=("src", "graph")
            )
        )(plan, jnp.asarray(xs))
    finally:
        cfg.set_flags(halo_impl=saved)
    traced = sorted(r["bytes"] for r in collect_collectives(jaxpr)["ppermute"])
    assert traced == sorted(sched_fp["round_bytes_per_shard"]), (
        "traced per-round operand bytes != footprint-priced rounds"
    )


# ---------------------------------------------------------------------------
# execution: bit-identical to all_to_all, forward and backward
# ---------------------------------------------------------------------------


@pytest.fixture(params=[2, 4])
def sched_case(request, rng):
    W = request.param
    V, E = (48, 300) if W == 2 else (96, 600)
    edges, part = _graph(rng, W, V, E)
    plan, layout = pl.build_edge_plan(edges, part, world_size=W)
    assert plan.halo_schedule is not None
    mesh = make_graph_mesh(ranks_per_graph=W, num_replicas=8 // W)
    return W, edges, part, plan, layout, mesh


@pytest.fixture
def sched_impl():
    saved = cfg.halo_impl
    yield
    cfg.set_flags(halo_impl=saved)


def _run_both(fn):
    """fn() under halo_impl='sched' and ='all_to_all' -> (sched, a2a)."""
    out = {}
    for impl in ("sched", "all_to_all"):
        cfg.set_flags(halo_impl=impl)
        out[impl] = np.asarray(fn())
    return out["sched"], out["all_to_all"]


def test_sched_gather_bit_identical(sched_case, sched_impl, rng):
    W, edges, part, plan, layout, mesh = sched_case
    V, F = len(part), 6
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    got, want = _run_both(lambda: spmd_apply(
        mesh, collectives.gather, plan, xs, static_args=("src", "graph")
    ))
    assert (got == want).all(), "sched forward differs from all_to_all"
    np.testing.assert_allclose(
        unshard_edge_data(got, layout), dense_gather(x, edges, "src"),
        rtol=1e-6,
    )


def test_sched_gather_grad_bit_identical(sched_case, sched_impl, rng):
    W, edges, part, plan, layout, mesh = sched_case
    V, F = len(part), 3
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
    ct = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
    ct_sh = jnp.asarray(shard_edge_data(ct, layout, plan.e_pad))

    def grad_once():
        def loss_fn(xs_):
            out = spmd_apply(
                mesh, collectives.gather, plan, xs_,
                static_args=("src", "graph"),
            )
            return jnp.sum(out * ct_sh)

        with jax.set_mesh(mesh):
            return jax.jit(jax.grad(loss_fn))(xs)

    got, want = _run_both(grad_once)
    assert (got == want).all(), "sched backward differs from all_to_all"
    np.testing.assert_allclose(
        unshard_vertex_data(got, layout.src_counts),
        dense_scatter_sum(ct, edges, "src", V), rtol=1e-5, atol=1e-5,
    )


def test_sched_scatter_sum_bit_identical(sched_case, sched_impl, rng):
    W, edges, part, plan, layout, mesh = sched_case
    V, F = len(part), 4
    edata = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
    ed = jnp.asarray(shard_edge_data(edata, layout, plan.e_pad))
    got, want = _run_both(lambda: spmd_apply(
        mesh, collectives.scatter_sum, plan, ed, static_args=("src", "graph")
    ))
    assert (got == want).all(), "sched scatter differs from all_to_all"
    np.testing.assert_allclose(
        unshard_vertex_data(got, layout.src_counts),
        dense_scatter_sum(edata, edges, "src", V), rtol=1e-5, atol=1e-5,
    )
