"""Plan-builder tests: hand-analyzed tiny graphs (the reference's
test_comm_info.py strategy — SURVEY.md §4) plus structural invariants on
random graphs.

Hand-analyzed graph (own design, 4 vertices, 2 ranks, contiguous blocks):

    ranks:  v0,v1 -> rank 0;  v2,v3 -> rank 1
    edges:  0->1, 1->2, 2->3, 3->0, 0->2

Reference-convention (edge owner = src) expectations, derived by hand:
  rank 0: local {0,1}; owned edges (0,1),(1,2),(0,2); halo {2};
          sends {0,1} to rank 1 (dedup of (0,r1),(1,r1)); recv 1 vertex (3).
  rank 1: local {2,3}; owned edges (2,3),(3,0); halo {0};
          sends {3} to rank 0; recv {0,1}.
  comm_map = [[0, 2], [1, 0]]
"""

import numpy as np
import pytest

from dgraph_tpu import plan as pl

EDGES = np.array([[0, 1, 2, 3, 0], [1, 2, 3, 0, 2]])
PART = np.array([0, 0, 1, 1])


class TestCommPattern:
    def test_rank0(self):
        cp = pl.build_comm_pattern(EDGES, PART, rank=0, world_size=2)
        assert cp.num_local_vertices == 2
        assert cp.num_halo_vertices == 1
        # local edges: (0,1),(1,2),(0,2) with halo vertex 2 -> local id 2
        assert cp.local_edge_list.tolist() == [[0, 1], [1, 2], [0, 2]]
        assert cp.send_local_idx.tolist() == [0, 1]
        assert cp.send_offset.tolist() == [0, 0, 2]
        assert cp.comm_map.tolist() == [[0, 2], [1, 0]]
        assert cp.recv_offset.tolist() == [0, 0, 1]
        assert cp.put_forward_remote_offset.tolist() == [0, 0]

    def test_rank1(self):
        cp = pl.build_comm_pattern(EDGES, PART, rank=1, world_size=2)
        assert cp.num_local_vertices == 2
        assert cp.num_halo_vertices == 1
        # local edges: (2,3),(3,0); local ids 2->0, 3->1, halo 0 -> 2
        assert cp.local_edge_list.tolist() == [[0, 1], [1, 2]]
        assert cp.send_local_idx.tolist() == [1]  # vertex 3 -> local id 1
        assert cp.send_offset.tolist() == [0, 1, 1]
        assert cp.recv_offset.tolist() == [0, 2, 2]
        # one-sided put offsets: forward = sum of rows < rank of comm_map
        assert cp.put_forward_remote_offset.tolist() == [0, 2]

    def test_comm_map_consistent_across_ranks(self):
        cps = [pl.build_comm_pattern(EDGES, PART, r, 2) for r in range(2)]
        assert np.array_equal(cps[0].comm_map, cps[1].comm_map)
        # row sums == per-rank total sends, col sums == total recvs
        cm = cps[0].comm_map
        for r in range(2):
            assert cm[r].sum() == cps[r].send_offset[-1] - cps[r].send_offset[0]
            assert cm[:, r].sum() == cps[r].recv_offset[-1]


def decode_plan_edges(plan, layout):
    """Reconstruct global [2, E] edges from a padded EdgePlan (test helper)."""
    W = plan.world_size
    src_off = np.concatenate([[0], np.cumsum(layout.src_counts)])
    dst_off = np.concatenate([[0], np.cumsum(layout.dst_counts)])
    halo_off = src_off if plan.halo_side == "src" else dst_off
    send_idx = np.asarray(plan.halo.send_idx)
    s = plan.halo.s_pad
    out = []
    for r in range(W):
        mask = np.asarray(plan.edge_mask[r]) > 0
        for j in np.nonzero(mask)[0]:
            si, di = int(plan.src_index[r, j]), int(plan.dst_index[r, j])

            def decode(idx, n_pad, off, is_halo_side):
                if not is_halo_side or idx < n_pad:
                    return off[r] + idx
                h = idx - n_pad
                p, i = divmod(h, s)
                return halo_off[p] + int(send_idx[p, r, i])

            g_src = decode(si, plan.n_src_pad, src_off, plan.halo_side == "src")
            g_dst = decode(di, plan.n_dst_pad, dst_off, plan.halo_side == "dst")
            out.append((g_src, g_dst))
    return out


class TestEdgePlan:
    def test_hand_analyzed_dst_owner(self):
        plan, layout = pl.build_edge_plan(
            EDGES, PART, world_size=2, edge_owner="dst", pad_multiple=1
        )
        assert plan.halo_side == "src"
        # rank0 owns edges with dst in {0,1}: (0,1),(3,0); rank1: (1,2),(2,3),(0,2)
        assert plan.num_edges.tolist() == [2, 3]
        assert plan.e_pad == 3
        # halo: rank0 needs src 3 (from rank1); rank1 needs srcs {0,1} (from rank0)
        assert layout.halo_counts.tolist() == [[0, 2], [1, 0]]
        assert plan.halo.s_pad == 2
        # sends: rank0 -> rank1: local ids [0,1]; rank1 -> rank0: local id [1]
        assert plan.halo.send_idx[0, 1].tolist() == [0, 1]
        assert plan.halo.send_mask[0, 1].tolist() == [1.0, 1.0]
        assert plan.halo.send_idx[1, 0, 0] == 1
        assert plan.halo.send_mask[1, 0].tolist() == [1.0, 0.0]

    def test_hand_analyzed_src_owner(self):
        plan, layout = pl.build_edge_plan(
            EDGES, PART, world_size=2, edge_owner="src", pad_multiple=1
        )
        assert plan.halo_side == "dst"
        # src ownership: rank0 owns (0,1),(1,2),(0,2); rank1 owns (2,3),(3,0)
        assert plan.num_edges.tolist() == [3, 2]
        # halo: rank0 needs dst 2 (from rank1); rank1 needs dst 0 (from rank0)
        assert layout.halo_counts.tolist() == [[0, 1], [1, 0]]

    @pytest.mark.parametrize("owner", ["src", "dst"])
    @pytest.mark.parametrize("world", [1, 2, 4, 8])
    def test_roundtrip_random_graph(self, owner, world, rng):
        V, E = 50, 400
        edges = rng.integers(0, V, size=(2, E))
        part = np.sort(rng.integers(0, world, size=V)).astype(np.int32)
        plan, layout = pl.build_edge_plan(
            edges, part, world_size=world, edge_owner=owner
        )
        decoded = decode_plan_edges(plan, layout)
        assert sorted(decoded) == sorted(map(tuple, edges.T.tolist()))

    def test_bipartite_relation(self, rng):
        """Hetero relation: 12 src (set A), 20 dst (set B), different partitions
        — the RGAT edge-conditioned plan case (``_NCCLCommPlan.py:103-137``)."""
        Va, Vb, E, W = 12, 20, 60, 4
        edges = np.stack([rng.integers(0, Va, E), rng.integers(0, Vb, E)])
        part_a = np.sort(rng.integers(0, W, Va)).astype(np.int32)
        part_b = np.sort(rng.integers(0, W, Vb)).astype(np.int32)
        plan, layout = pl.build_edge_plan(
            edges, part_a, part_b, world_size=W, edge_owner="dst"
        )
        assert not plan.homogeneous
        decoded = decode_plan_edges(plan, layout)
        assert sorted(decoded) == sorted(map(tuple, edges.T.tolist()))

    def test_edge_data_layout_roundtrip(self, rng):
        from dgraph_tpu.plan import shard_edge_data
        from dgraph_tpu.testing import unshard_edge_data

        V, E, W = 30, 200, 4
        edges = rng.integers(0, V, size=(2, E))
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        plan, layout = pl.build_edge_plan(edges, part, world_size=W)
        w = rng.normal(size=(E, 3)).astype(np.float32)
        sharded = shard_edge_data(w, layout, plan.e_pad)
        assert sharded.shape == (W, plan.e_pad, 3)
        np.testing.assert_array_equal(unshard_edge_data(sharded, layout), w)

    def test_vertex_data_roundtrip(self, rng):
        from dgraph_tpu.plan import shard_vertex_data, unshard_vertex_data

        counts = np.array([3, 5, 2, 4])
        x = rng.normal(size=(14, 6)).astype(np.float32)
        sh = shard_vertex_data(x, counts, n_pad=8)
        assert sh.shape == (4, 8, 6)
        np.testing.assert_array_equal(unshard_vertex_data(sh, counts), x)


class TestPlanEfficiency:
    """Padding-efficiency telemetry + halo-impl auto-pick (VERDICT r1 #8)."""

    def test_ratios_bounds_and_exact(self, rng):
        V, E, W = 64, 300, 4
        edges = rng.integers(0, V, size=(2, E))
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        plan, layout = pl.build_edge_plan(edges, part, world_size=W)
        eff = pl.plan_efficiency(plan, layout)
        for k in ("edge_fill", "halo_fill_active", "halo_wire_fill_all_to_all",
                  "halo_wire_fill_ppermute", "src_vertex_fill"):
            assert 0.0 < eff[k] <= 1.0, (k, eff[k])
        assert eff["edge_fill"] == E / (W * plan.e_pad)
        assert eff["halo_wire_fill_all_to_all"] == layout.halo_counts.sum() / (
            W * (W - 1) * plan.halo.s_pad
        )
        # ppermute only moves live deltas, so its wire fill can't be worse
        assert eff["halo_wire_fill_ppermute"] >= eff["halo_wire_fill_all_to_all"]

    def test_skewed_graph_reports_low_fill(self, rng):
        """One hub vertex inflates s_pad for every peer pair — the telemetry
        must surface it (power-law skew, VERDICT r1 weak #6)."""
        V, W = 64, 8
        part = np.repeat(np.arange(W), V // W).astype(np.int32)
        # star graph: everyone sends to vertex 0 (a hub on rank 0)
        edges = np.stack([np.arange(1, V), np.zeros(V - 1, np.int64)])
        plan, layout = pl.build_edge_plan(edges, part, world_size=W, pad_multiple=1)
        eff = pl.plan_efficiency(plan, layout)
        # only rank 0 owns edges -> 7/8 of edge slots padded
        assert eff["edge_fill"] <= 1.0 / W + 1e-6

    def test_auto_pick(self):
        # dense all-pairs traffic -> all_to_all; sparse neighbor set -> ppermute
        assert pl.pick_halo_impl(8, ()) == "none"
        assert pl.pick_halo_impl(8, (1, 7)) == "ppermute"
        assert pl.pick_halo_impl(8, (1, 2, 3, 4)) == "ppermute"
        assert pl.pick_halo_impl(8, (1, 2, 3, 4, 5)) == "all_to_all"
        assert pl.pick_halo_impl(2, (1,)) == "ppermute"

    def test_ring_partition_picks_ppermute(self, rng):
        """Locality (block) partition of a ring graph has only deltas {1, W-1}."""
        V, W = 64, 8
        part = np.repeat(np.arange(W), V // W).astype(np.int32)
        ring = np.stack([np.arange(V), (np.arange(V) + 1) % V])
        plan, layout = pl.build_edge_plan(ring, part, world_size=W, pad_multiple=1)
        eff = pl.plan_efficiency(plan, layout)
        # dst-owned edges: the halo flows from src owner r to dst owner r+1
        assert set(plan.halo_deltas) == {1}
        assert eff["halo_impl"] == "ppermute"


class TestNativePlanCore:
    """The native streaming plan core must produce EXACTLY the numpy
    builder's output (same sort order, same halo slot numbering)."""

    @pytest.mark.parametrize("edge_owner", ["dst", "src"])
    @pytest.mark.parametrize("hetero", [False, True])
    def test_native_plan_matches_numpy(self, edge_owner, hetero):
        from dgraph_tpu import native
        from dgraph_tpu.plan import build_edge_plan

        if not native.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(11)
        W = 4
        Vs, Vd = 97, 57 if hetero else 97
        E = 5000
        src_part = np.sort(rng.integers(0, W, Vs)).astype(np.int32)
        dst_part = np.sort(rng.integers(0, W, Vd)).astype(np.int32) if hetero else None
        edges = np.stack([rng.integers(0, Vs, E), rng.integers(0, Vd, E)])
        kw = dict(world_size=W, edge_owner=edge_owner, pad_multiple=8)
        plan_np, layout_np = build_edge_plan(
            edges, src_part, dst_part, use_native=False, **kw
        )
        plan_nat, layout_nat = build_edge_plan(
            edges, src_part, dst_part, use_native=True, **kw
        )
        for field in (
            "src_index", "dst_index", "edge_mask", "num_local_src",
            "num_local_dst", "num_edges",
        ):
            np.testing.assert_array_equal(
                np.asarray(getattr(plan_np, field)),
                np.asarray(getattr(plan_nat, field)), err_msg=field,
            )
        np.testing.assert_array_equal(plan_np.halo.send_idx, plan_nat.halo.send_idx)
        np.testing.assert_array_equal(plan_np.halo.send_mask, plan_nat.halo.send_mask)
        assert plan_np.halo.s_pad == plan_nat.halo.s_pad
        assert plan_np.e_pad == plan_nat.e_pad
        assert plan_np.halo_deltas == plan_nat.halo_deltas
        assert plan_np.scatter_mc == plan_nat.scatter_mc
        np.testing.assert_array_equal(layout_np.edge_rank, layout_nat.edge_rank)
        np.testing.assert_array_equal(layout_np.edge_slot, layout_nat.edge_slot)
        np.testing.assert_array_equal(layout_np.halo_counts, layout_nat.halo_counts)


class TestHaloSortRoute:
    """The halo-side sorted route (EdgePlan.halo_sort_perm): a static
    permutation that lets the unsorted halo-side index run its gather-VJP /
    scatter-forward as a SORTED segment reduction (ops.local sort-route
    wrappers) instead of XLA's generic scatter-add."""

    def _plan(self, sort_route=None):
        rng = np.random.default_rng(3)
        V, E, W = 64, 400, 4
        edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        from dgraph_tpu.plan import build_edge_plan

        return build_edge_plan(
            edges, part, world_size=W, edge_owner="dst", sort_route=sort_route
        )[0]

    def test_fields_valid(self):
        plan = self._plan()
        assert plan.halo_sort_perm is not None
        W = plan.world_size
        for r in range(W):
            p = np.asarray(plan.halo_sort_perm[r])
            assert sorted(p.tolist()) == list(range(plan.e_pad))  # permutation
            si = np.asarray(plan.halo_sorted_ids[r])
            assert (np.diff(si) >= 0).all()  # monotone
            np.testing.assert_array_equal(np.asarray(plan.src_index[r])[p], si)
        assert plan.halo_sort_mc >= 1

    def test_route_equals_generic(self):
        """Forward values AND gradients are identical with and without the
        route (route off => jnp generic paths)."""
        import jax
        import jax.numpy as jnp

        from dgraph_tpu.comm import collectives as coll

        plan = self._plan()
        plan_nr = self._plan(sort_route=False)
        assert plan_nr.halo_sort_perm is None
        p0 = jax.tree.map(lambda l: jnp.asarray(l[0]), plan)
        p0n = jax.tree.map(lambda l: jnp.asarray(l[0]), plan_nr)
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((plan.n_src_pad, 8)), jnp.float32)
        ed = jnp.asarray(rng.standard_normal((plan.e_pad, 8)), jnp.float32)

        def loss_g(x, pl):
            return (coll.gather(x, pl, "src", None).astype(jnp.float32) ** 2).sum()

        def loss_s(e, pl):
            return (coll.scatter_sum(e, pl, "src", None).astype(jnp.float32) ** 2).sum()

        for lf, arg in [(loss_g, x), (loss_s, ed)]:
            v1, g1 = jax.value_and_grad(lf)(arg, p0)
            v2, g2 = jax.value_and_grad(lf)(arg, p0n)
            assert np.allclose(v1, v2, rtol=1e-5)
            assert np.allclose(g1, g2, rtol=1e-5, atol=1e-6)

    def test_pallas_kernel_on_route_inputs(self):
        """The Pallas kernel (interpret mode) must agree with numpy on the
        ACTUAL route inputs — per-shard halo_sorted_ids with padded id-0
        edges and the plan-computed halo_sort_mc hint — not just on the
        dense valid ids the bench self-check uses."""
        import jax.numpy as jnp

        from dgraph_tpu.ops.pallas_segment import sorted_segment_sum

        plan = self._plan()
        W = plan.world_size
        n_full = plan.n_src_pad + W * plan.halo.s_pad
        rng = np.random.default_rng(9)
        for r in range(W):
            si = np.asarray(plan.halo_sorted_ids[r])
            data = rng.standard_normal((plan.e_pad, 8)).astype(np.float32)
            want = np.zeros((n_full, 8), np.float32)
            np.add.at(want, si, data)
            got = np.asarray(
                sorted_segment_sum(
                    jnp.asarray(data), jnp.asarray(si), n_full,
                    max_chunks_per_block=plan.halo_sort_mc,
                    block_e=plan.scatter_block_e, block_n=plan.scatter_block_n,
                    interpret=True,
                )
            )
            np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


class TestResolveHaloImplLadder:
    """The full decision ladder of :func:`plan.resolve_halo_impl` — every
    tier asserted via the REPORTED deciding source (env pin > adopted
    tuning record > heuristic > plan), including the pin-without-split
    degrade path. This is the contract ``comm.collectives``'s runtime
    dispatch, ``obs.footprint``'s accounting, and ``plan_efficiency``'s
    report all resolve through; if the ladder drifts, what runs, what is
    priced, and what is reported can disagree."""

    @pytest.fixture(autouse=True)
    def _restore_flags(self):
        from dgraph_tpu import config as cfg

        saved = (cfg.halo_impl, cfg.tuned_halo_impl)
        yield
        cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])

    def _set(self, env="auto", record=None):
        from dgraph_tpu import config as cfg

        cfg.set_flags(halo_impl=env, tuned_halo_impl=record)

    def test_env_pin_beats_record_beats_heuristic(self):
        # heuristic alone: sparse deltas -> ppermute, dense -> all_to_all
        self._set()
        assert pl.resolve_halo_impl(8, (1,)) == ("ppermute", "heuristic")
        assert pl.resolve_halo_impl(8, tuple(range(1, 8))) == (
            "all_to_all", "heuristic")
        # a record overrides the heuristic
        self._set(record="all_to_all")
        assert pl.resolve_halo_impl(8, (1,)) == ("all_to_all", "record")
        # the env pin overrides the record — the operator's word is final
        self._set(env="ppermute", record="all_to_all")
        assert pl.resolve_halo_impl(8, tuple(range(1, 8))) == (
            "ppermute", "env")

    def test_no_traffic_shortcuts_every_tier(self):
        # an empty delta set means there is nothing to choose: even an
        # explicit env pin reports source='plan'
        self._set(env="all_to_all", record="ppermute")
        assert pl.resolve_halo_impl(8, ()) == ("none", "plan")

    def test_overlap_legal_only_with_split(self):
        self._set(env="overlap")
        assert pl.resolve_halo_impl(4, (1,), overlap_available=True) == (
            "overlap", "env")
        self._set(record="overlap")
        assert pl.resolve_halo_impl(4, (1,), overlap_available=True) == (
            "overlap", "record")
        # heuristic adopts overlap whenever the plan carries the split
        self._set()
        assert pl.resolve_halo_impl(4, (1, 2, 3), overlap_available=True) == (
            "overlap", "heuristic")

    def test_env_overlap_pin_without_split_degrades_to_record(self):
        # the pinned tier is SKIPPED (never a silent wrong answer): an
        # env 'overlap' on a split-less plan falls through to the record
        self._set(env="overlap", record="all_to_all")
        assert pl.resolve_halo_impl(8, (1,), overlap_available=False) == (
            "all_to_all", "record")

    def test_record_overlap_without_split_degrades_to_heuristic(self):
        self._set(record="overlap")
        assert pl.resolve_halo_impl(8, (1,), overlap_available=False) == (
            "ppermute", "heuristic")
        # both tiers pinned to overlap, no split anywhere -> heuristic
        self._set(env="overlap", record="overlap")
        assert pl.resolve_halo_impl(
            8, tuple(range(1, 8)), overlap_available=False
        ) == ("all_to_all", "heuristic")

    def test_degrade_warns_once_per_source(self, caplog):
        import logging

        pl._overlap_warned.clear()
        self._set(env="overlap")
        with caplog.at_level(logging.WARNING, logger=pl._logger.name):
            pl.resolve_halo_impl(8, (1,), overlap_available=False)
            pl.resolve_halo_impl(8, (1,), overlap_available=False)
        warns = [r for r in caplog.records if "overlap" in r.getMessage()]
        assert len(warns) == 1, "degrade warning must fire once per source"
        pl._overlap_warned.clear()

    def test_reported_source_reaches_plan_efficiency(self):
        """The deciding source is not just returned — it lands in the
        plan_efficiency report (the operator-facing surface)."""
        plan, layout = pl.build_edge_plan(EDGES, PART, world_size=2)
        self._set(env="all_to_all")
        eff = pl.plan_efficiency(plan, layout)
        assert (eff["halo_impl"], eff["halo_impl_source"]) == (
            "all_to_all", "env")
        self._set(env="auto", record="ppermute")
        eff = pl.plan_efficiency(plan, layout)
        assert (eff["halo_impl"], eff["halo_impl_source"]) == (
            "ppermute", "record")
        self._set()
        eff = pl.plan_efficiency(plan, layout)
        assert eff["halo_impl_source"] == "heuristic"
