"""The bench supervisor must emit ONE parseable JSON line on EVERY exit
path — rounds 1 and 2 were both lost to a bare traceback with no JSON when
backend init failed (VERDICT r2 weak #1). These tests pin the contract
without needing a TPU: a child that can never initialize a backend must
still produce structured output and the documented exit code.

Reference role: the perf-harness reliability the reference gets for free
from its driver scripts (``experiments/OGB/main.py:129-221``).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BENCH = os.path.join(REPO, "bench.py")


def _run(env_extra, timeout=120):
    env = dict(os.environ)
    env.update(env_extra)
    return subprocess.run(
        [sys.executable, BENCH], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=REPO,
    )


@pytest.mark.slow
def test_backend_failure_emits_json_and_rc3():
    # An unknown platform makes every init probe fail fast; with a tiny
    # budget the supervisor must give up, emit JSON, and exit EXIT_EMPTY=3.
    r = _run({
        "JAX_PLATFORMS": "nonexistent_backend",
        "PALLAS_AXON_POOL_IPS": "",
        "DGRAPH_BENCH_TIMEOUT": "8",
    })
    assert r.returncode == 3, (r.returncode, r.stdout, r.stderr[-500:])
    lines = r.stdout.strip().splitlines()
    assert lines, r.stderr[-500:]
    out = json.loads(lines[-1])
    assert out["metric"] == "arxiv_gcn_epoch_time"
    assert out["value"] is None
    assert "error" in out
    # the failure artifact must be diagnosable ALONE: a populated
    # RunHealth record with the probe history and a wedge classification
    # (obs.health) — not just free text (the BENCH_r05 lesson)
    rh = out["run_health"]["supervisor"]
    assert rh["probes"], rh
    assert all(p["outcome"] in ("ok", "error", "hang") for p in rh["probes"])
    assert rh["wedge"] in ("init_failure", "init_wedge"), rh["wedge"]
    assert rh["schema"] == 1 and rh["host"]["hostname"]
    # the probe loop runs under train.supervise: a wedged/unreachable
    # backend lands a structured supervise_lineage (every attempt's
    # outcome/rc/wall) in the round's JSON, not just free text
    lin = out["supervise_lineage"]
    assert lin["kind"] == "supervise_lineage"
    assert lin["attempts"] and lin["final_exit_code"] != 0
    assert lin["budget_exhausted"] or lin["gave_up"]


@pytest.mark.slow
def test_smoke_run_complete_rc0():
    # End-to-end supervisor -> child -> both stages on CPU at smoke scale.
    r = _run({
        "JAX_PLATFORMS": "cpu",
        "PALLAS_AXON_POOL_IPS": "",
        "DGRAPH_BENCH_SMOKE": "1",
        "DGRAPH_BENCH_TIMEOUT": "400",
        # interpret-mode Pallas is exercised elsewhere; keep this fast
        "DGRAPH_TPU_PALLAS_SCATTER": "0",
    }, timeout=420)
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr[-800:])
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["value"] is not None and out["value"] > 0
    assert out["graphcast_step_ms"] is not None
    assert out["config"]["dtype"] == "bfloat16"
    # healthy runs carry their health too: child topology snapshot +
    # supervisor probe history, wedge 'none' on both
    rh = out["run_health"]
    assert rh["child"]["backend"]["platform"] == "cpu"
    assert rh["child"]["wedge"] == "none"
    assert rh["supervisor"]["probes"][-1]["outcome"] == "ok"
    assert rh["supervisor"]["wedge"] == "none"


@pytest.mark.slow
def test_wedged_probe_window_attaches_fallback_tiers():
    """ROADMAP item 5's fallback tiers: when the probe window exhausts
    with no healthy chip, the round's JSON carries BOTH non-null analysis
    signals — ``schedule_drift`` (trace auditor, footprint-vs-traced
    bytes) and ``cpu_scan_delta`` (per-phase step-time attribution per
    halo lowering, obs.attribution) — instead of value:null alone. The
    BENCH_r03–r05 class of fully blind round is designed out: even a
    wedged round lands comparable timing numbers, labeled by tier."""
    r = _run({
        "JAX_PLATFORMS": "nonexistent_backend",
        "PALLAS_AXON_POOL_IPS": "",
        "DGRAPH_BENCH_TIMEOUT": "420",
        "DGRAPH_BENCH_PROBE_BUDGET": "3",
    }, timeout=540)
    assert r.returncode == 3, (r.returncode, r.stdout, r.stderr[-500:])
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["value"] is None and "never initialized" in out["error"]
    assert out["supervise_lineage"]["attempts"]  # tier 0: the probe lineage
    drift = out["schedule_drift"]
    assert drift["kind"] == "schedule_drift", drift
    assert "error" not in drift, drift
    assert drift["drift"] is False
    by_impl = drift["train_step_by_impl"]
    for impl in ("all_to_all", "ppermute", "overlap"):
        assert by_impl[impl]["traced_bytes"] == \
            by_impl[impl]["footprint_bytes"] > 0
    # tier 2: per-phase cpu_scan_delta timing for (at least) the
    # all_to_all and overlap lowerings, labeled by tier, schema-stable
    delta = out["cpu_scan_delta"]
    assert delta["kind"] == "cpu_scan_delta", delta
    assert "error" not in delta, delta
    assert delta["tier"] == "cpu_scan_delta" and delta["schema"] == 1
    assert delta["backend"] == "cpu"
    for impl in ("all_to_all", "overlap"):
        by = delta["by_impl"][impl]
        assert by["full_ms"] is not None and by["full_ms"] > 0, (impl, by)
        assert set(by["phases_ms"]) == {
            "interior", "exchange", "optimizer", "other"
        }
        assert by["phases_ms"]["exchange"] is not None, (impl, by)


@pytest.mark.slow
def test_tiny_budget_skips_analysis_fallbacks():
    """With no budget left BOTH fallbacks must be skipped, not squeezed
    in: the wedge record's JSON still comes out on time (the original
    rc=3 contract, unchanged)."""
    r = _run({
        "JAX_PLATFORMS": "nonexistent_backend",
        "PALLAS_AXON_POOL_IPS": "",
        "DGRAPH_BENCH_TIMEOUT": "8",
    })
    assert r.returncode == 3
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert "schedule_drift" not in out
    assert "cpu_scan_delta" not in out


@pytest.mark.slow
def test_analysis_fallback_env_disables_both_tiers():
    """DGRAPH_BENCH_ANALYSIS_FALLBACK=0 turns the shared subprocess
    helper off uniformly — neither tier may spawn."""
    r = _run({
        "JAX_PLATFORMS": "nonexistent_backend",
        "PALLAS_AXON_POOL_IPS": "",
        "DGRAPH_BENCH_TIMEOUT": "150",
        "DGRAPH_BENCH_PROBE_BUDGET": "3",
        "DGRAPH_BENCH_ANALYSIS_FALLBACK": "0",
    }, timeout=120)
    assert r.returncode == 3
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert "schedule_drift" not in out
    assert "cpu_scan_delta" not in out
