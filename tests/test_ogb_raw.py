"""Byte-real OGB raw-download fixtures through the real-ingestion branch.

VERDICT r4 #7: the npz/stub paths were tested but nothing would catch a
format drift the day egress appears. These tests write tiny datasets in the
OFFICIAL on-disk layout (same file names, gzip csv bytes written the way
ogb's own pandas pipeline writes them, binary npz for papers100M) and drive
``load_ogb_arrays``'s raw-download branch — the branch real downloads will
take in this pip-less environment — through parsing, postprocessing, and a
full training run. No ogb stub is injected anywhere here.
"""

import os

import numpy as np
import pytest

from dgraph_tpu.data import ogb_raw, ogbn


def _toy(V=60, E=240, F=6, C=4, seed=0):
    rng = np.random.default_rng(seed)
    perm = rng.permutation(V)
    return {
        "edge_index": rng.integers(0, V, (2, E)).astype(np.int64),
        "node_feat": rng.normal(size=(V, F)).astype(np.float32).round(4),
        "labels": rng.integers(0, C, V).astype(np.int64),
        "split_idx": {
            "train": np.sort(perm[: V // 2]).astype(np.int64),
            "valid": np.sort(perm[V // 2 : 3 * V // 4]).astype(np.int64),
            "test": np.sort(perm[3 * V // 4 :]).astype(np.int64),
        },
    }


def test_ogb_package_really_absent():
    """The point of the suite: the raw branch runs because ogb is NOT
    importable. If ogb ever appears in the image, the package branch takes
    over and these fixtures stop covering egress-day ingestion — re-point
    them at the package path then."""
    with pytest.raises(ImportError):
        import ogb  # noqa: F401


def test_arxiv_csv_layout_roundtrips_exactly(tmp_path):
    t = _toy()
    ogb_raw.write_node_pred_raw(
        str(tmp_path), "ogbn-arxiv",
        edge_index=t["edge_index"], labels=t["labels"],
        split_idx=t["split_idx"], node_feat=t["node_feat"],
    )
    # layout spot-checks: the exact artifact names the download ships
    base = tmp_path / "ogbn_arxiv"
    for rel in (
        "raw/edge.csv.gz", "raw/node-feat.csv.gz", "raw/node-label.csv.gz",
        "raw/num-node-list.csv.gz", "raw/num-edge-list.csv.gz",
        "split/time/train.csv.gz", "split/time/valid.csv.gz",
        "split/time/test.csv.gz",
    ):
        assert (base / rel).exists(), rel

    arrs = ogbn.load_ogb_arrays("ogbn-arxiv", root=str(tmp_path))
    np.testing.assert_array_equal(arrs["edge_index"], t["edge_index"])
    np.testing.assert_array_equal(arrs["features"], t["node_feat"])
    np.testing.assert_array_equal(arrs["labels"], t["labels"])
    assert arrs["num_nodes"] == 60
    for split in ("train", "valid", "test"):
        got = np.nonzero(arrs[split + "_mask"])[0]
        np.testing.assert_array_equal(got, t["split_idx"][split])


def test_add_inverse_edge_appends_not_interleaves(tmp_path):
    """Pin the documented divergence from ogb's ``read_csv_graph_raw``:
    reversed edges are APPENDED as one block ([fwd..., rev...]), NOT
    interleaved per edge ([e0, rev(e0), e1, rev(e1), ...]) like the
    package does. The edge SET matches the package either way; element
    ORDER does not — nothing may rely on column-order parity with
    package-produced npz artifacts."""
    t = _toy(seed=3)
    ogb_raw.write_node_pred_raw(
        str(tmp_path), "ogbn-products",
        edge_index=t["edge_index"], labels=t["labels"],
        split_idx=t["split_idx"], node_feat=t["node_feat"],
    )
    graph, _, _ = ogb_raw.read_node_pred_raw(str(tmp_path), "ogbn-products")
    E = t["edge_index"].shape[1]
    got = graph["edge_index"]
    assert got.shape == (2, 2 * E)
    # appended layout: first block is the download order, second block is
    # the reversal of the whole first block (same order, rows swapped)
    np.testing.assert_array_equal(got[:, :E], t["edge_index"])
    np.testing.assert_array_equal(got[:, E:], t["edge_index"][::-1])
    # and explicitly NOT ogb's interleaved layout
    interleaved = np.repeat(t["edge_index"], 2, axis=1)
    interleaved[:, 1::2] = interleaved[::-1, 1::2]
    assert not np.array_equal(got, interleaved)
    # the edge SET still matches the package's
    assert (
        set(map(tuple, got.T.tolist()))
        == set(map(tuple, interleaved.T.tolist()))
    )


def test_products_doubles_edges_like_master_csv(tmp_path):
    """ogbn-products ships single-direction edges; ogb's loader doubles
    them (master.csv add_inverse_edge) — the raw reader must too."""
    t = _toy(seed=1)
    ogb_raw.write_node_pred_raw(
        str(tmp_path), "ogbn-products",
        edge_index=t["edge_index"], labels=t["labels"],
        split_idx=t["split_idx"], node_feat=t["node_feat"],
    )
    assert (tmp_path / "ogbn_products/split/sales_ranking/train.csv.gz").exists()
    arrs = ogbn.load_ogb_arrays("ogbn-products", root=str(tmp_path))
    E = t["edge_index"].shape[1]
    assert arrs["edge_index"].shape == (2, 2 * E)
    np.testing.assert_array_equal(arrs["edge_index"][:, :E], t["edge_index"])
    np.testing.assert_array_equal(
        arrs["edge_index"][:, E:], t["edge_index"][::-1]
    )


def test_proteins_species_features_and_multilabel(tmp_path):
    """proteins: no node-feat file, node_species extra file, [V, C] 0/1
    float labels, 8-dim edge features, inverse-edge doubling."""
    V, E, C = 40, 160, 5
    rng = np.random.default_rng(2)
    t = _toy(V=V, E=E, seed=2)
    species = rng.choice([3702, 4932, 9606], V).astype(np.int64)
    labels = rng.integers(0, 2, (V, C)).astype(np.int64)
    edge_feat = rng.uniform(size=(E, 8)).astype(np.float32).round(4)
    ogb_raw.write_node_pred_raw(
        str(tmp_path), "ogbn-proteins",
        edge_index=t["edge_index"], labels=labels,
        split_idx=t["split_idx"], node_species=species, edge_feat=edge_feat,
    )
    arrs = ogbn.load_ogb_arrays("ogbn-proteins", root=str(tmp_path))
    # features = species one-hot + log1p(out-degree on the DOUBLED graph)
    n_species = len(np.unique(species))
    assert arrs["features"].shape == (V, n_species + 1)
    doubled = np.concatenate([t["edge_index"], t["edge_index"][::-1]], axis=1)
    deg = np.bincount(doubled[0], minlength=V).astype(np.float32)
    np.testing.assert_allclose(arrs["features"][:, -1], np.log1p(deg))
    assert arrs["labels"].shape == (V, C)
    assert arrs["labels"].dtype == np.float32
    np.testing.assert_array_equal(arrs["labels"], labels.astype(np.float32))


def test_papers100m_binary_layout_and_nan_labels(tmp_path):
    """papers100M ships raw/data.npz + raw/node-label.npz; unlabeled nodes
    are NaN and must come back as class 0 outside every split mask."""
    V = 50
    t = _toy(V=V, E=200, seed=3)
    labels = t["labels"].astype(np.float32)
    unlabeled = np.setdiff1d(
        np.arange(V), np.concatenate(list(t["split_idx"].values()))
    )
    labels[unlabeled] = np.nan
    ogb_raw.write_node_pred_raw(
        str(tmp_path), "ogbn-papers100M",
        edge_index=t["edge_index"], labels=labels,
        split_idx=t["split_idx"], node_feat=t["node_feat"],
    )
    raw = tmp_path / "ogbn_papers100M/raw"
    assert (raw / "data.npz").exists() and (raw / "node-label.npz").exists()
    assert not (raw / "edge.csv.gz").exists()
    arrs = ogbn.load_ogb_arrays("ogbn-papers100M", root=str(tmp_path))
    assert arrs["labels"].dtype == np.int32
    np.testing.assert_array_equal(arrs["labels"][unlabeled], 0)
    lab = np.nonzero(~np.isnan(labels))[0]
    np.testing.assert_array_equal(
        arrs["labels"][lab], t["labels"][lab].astype(np.int32)
    )


def test_split_dict_pt_short_circuit(tmp_path):
    """Newer ogb releases ship split/{type}/split_dict.pt; it must win over
    the csv files when present (torch.save zip format, as ogb writes it)."""
    import torch

    t = _toy(seed=4)
    ogb_raw.write_node_pred_raw(
        str(tmp_path), "ogbn-arxiv",
        edge_index=t["edge_index"], labels=t["labels"],
        split_idx=t["split_idx"], node_feat=t["node_feat"],
    )
    other = {k: v[: len(v) // 2].copy() for k, v in t["split_idx"].items()}
    torch.save(
        other, str(tmp_path / "ogbn_arxiv/split/time/split_dict.pt")
    )
    got = ogb_raw.read_split(str(tmp_path), "ogbn-arxiv")
    for k in ("train", "valid", "test"):
        np.testing.assert_array_equal(got[k], other[k])


def test_missing_raw_layout_raises_with_recipe(tmp_path):
    with pytest.raises(ImportError, match="raw download layout"):
        ogbn.load_ogb_arrays("ogbn-arxiv", root=str(tmp_path / "empty"))


def test_raw_fixture_trains_end_to_end(tmp_path, monkeypatch):
    """The full egress-day path: official raw layout on disk -> experiment
    CLI with --data.ogb_name + --data.root -> partitioned training on the
    virtual mesh. Learnable SBM arrays so the run is a real training."""
    from dgraph_tpu.data.synthetic import sbm_classification_graph

    data = sbm_classification_graph(
        num_nodes=400, num_classes=4, feat_dim=8, avg_degree=8.0,
        homophily=0.85, seed=5,
    )
    masks = data["masks"]
    split_idx = {
        "train": np.nonzero(masks["train"])[0].astype(np.int64),
        "valid": np.nonzero(masks["val"])[0].astype(np.int64),
        "test": np.nonzero(masks["test"])[0].astype(np.int64),
    }
    ogb_raw.write_node_pred_raw(
        str(tmp_path), "ogbn-arxiv",
        edge_index=np.asarray(data["edge_index"], np.int64),
        labels=np.asarray(data["labels"], np.int64),
        split_idx=split_idx,
        node_feat=np.asarray(data["features"], np.float32),
    )

    from experiments.ogb_gcn import Config, DataConfig, main

    monkeypatch.chdir(tmp_path)  # logs/ lands in tmp
    cfg = Config(
        epochs=3, hidden=16, world_size=0,  # 0 = all (the conftest's 8)
        log_path=str(tmp_path / "log.jsonl"),
        data=DataConfig(ogb_name="ogbn-arxiv", root=str(tmp_path)),
    )
    main(cfg)
    import json

    rows = [
        json.loads(l)
        for l in open(tmp_path / "log.jsonl")
        if l.strip() and not l.startswith("#")
    ]
    assert any("test_acc" in r for r in rows)
    losses = [r["loss"] for r in rows if "loss" in r]
    assert losses[-1] < losses[0]  # it learned something
