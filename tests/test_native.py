"""Native C++ host toolkit vs the numpy oracles (the reference's
CUDA-vs-python dual-implementation test pattern,
``tests/test_local_kernels.py``)."""

import numpy as np
import pytest

from dgraph_tpu import native


@pytest.fixture(scope="module", autouse=True)
def built():
    if not native.available():
        pytest.skip("native toolkit failed to build (no g++?)")


def test_unique_encoded_pairs_matches_numpy(rng):
    keys = rng.integers(0, 7, 5000)
    vals = rng.integers(0, 1000, 5000)
    got = native.unique_encoded_pairs(keys, vals, 1000)
    expected = np.unique(keys.astype(np.int64) * 1000 + vals)
    np.testing.assert_array_equal(got, expected)


def test_greedy_partition_invariants(rng):
    V, E, W = 2000, 12000, 8
    edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
    part = native.greedy_bfs_partition(edges, V, W)
    counts = np.bincount(part, minlength=W)
    assert counts.sum() == V
    cap = -(-V // W)
    assert counts.max() <= cap + 1
    # locality: should beat random assignment's expected cut (1 - 1/W)
    cut = native.edge_cut_count(edges, part) / E
    assert cut < 1 - 1 / W


def test_edge_cut_count_matches_numpy(rng):
    V, E, W = 500, 70000, 4  # above the multithread threshold
    edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
    part = rng.integers(0, W, V).astype(np.int32)
    got = native.edge_cut_count(edges, part)
    assert got == int((part[edges[0]] != part[edges[1]]).sum())


def test_plan_build_uses_native_dedup(rng):
    """Large cross-edge count triggers the native dedup path; plan must be
    identical to the numpy path."""
    from dgraph_tpu import plan as pl

    V, E, W = 3000, 80000, 8
    edges = rng.integers(0, V, size=(2, E))
    part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    p_native, _ = pl.build_edge_plan(edges, part, world_size=W)
    # force numpy path
    import dgraph_tpu.native as nat

    orig = nat.available
    nat.available = lambda: False
    try:
        p_numpy, _ = pl.build_edge_plan(edges, part, world_size=W)
    finally:
        nat.available = orig
    for a, b in zip(
        __import__("jax").tree.leaves(p_native), __import__("jax").tree.leaves(p_numpy)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
