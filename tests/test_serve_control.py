"""Serving control plane: hot-swap checkpoint rollover (parity + jit-cache
pins, chaos rollback with zero dropped in-flight requests), per-tenant
quotas and degraded isolation (noisy-neighbor pin), the reset_degraded
failure-epoch race fix, the model registry's atomic between-batches flip,
and live graph deltas (append -> background replan -> atomic adoption,
pinned bit-identical against a from-scratch rebuild oracle and chaos
sigterm-torn at the commit boundary)."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from dgraph_tpu.serve.bucketing import BucketLadder
from dgraph_tpu.serve.errors import (
    QuotaExceeded,
    SwapRejected,
    TenantDegraded,
)
from dgraph_tpu.serve.tenancy import TenantQuota, TenantTable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# shared warmed stack (same graph/model/ladder shapes as test_serve's
# fixture on purpose: the persistent XLA cache makes the warmup a replay)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def control(mesh8, tmp_path_factory):
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models import GCN
    from dgraph_tpu.obs.metrics import Metrics
    from dgraph_tpu.serve.engine import ServeEngine
    from dgraph_tpu.train.checkpoint import save_checkpoint
    from dgraph_tpu.train.loop import init_params

    data = synthetic.sbm_classification_graph(
        num_nodes=200, num_classes=3, feat_dim=8, avg_degree=6.0
    )
    g = DistributedGraph.from_global(
        data["edge_index"], data["features"], data["labels"], data["masks"],
        world_size=8, partition_method="random",
    )
    comm = Communicator.init_process_group("tpu", world_size=8)
    model = GCN(8, 3, comm=comm, num_layers=2)
    plan = jax.tree.map(jnp.asarray, g.plan)
    batch = jax.tree.map(jnp.asarray, dict(g.batch("train"), y=g.labels))
    params = init_params(model, mesh8, plan, batch, seed=0)
    params2 = init_params(model, mesh8, plan, batch, seed=1)
    ckpt = str(tmp_path_factory.mktemp("rollover") / "ckpt")
    save_checkpoint(ckpt, {"params": params, "step": 0}, 0)
    save_checkpoint(ckpt, {"params": params2, "step": 1}, 1)
    engine = ServeEngine.from_distributed_graph(
        model, mesh8, g, params,
        ladder=BucketLadder((8, 16, 32)), registry=Metrics(),
    )
    engine.ckpt_dir = ckpt
    engine.warmup()
    return engine, g, model, params, params2, ckpt


# ---------------------------------------------------------------------------
# hot-swap rollover
# ---------------------------------------------------------------------------


def test_swap_parity_pin_and_jit_cache_pin(control, rng):
    """The rollover acceptance pin: post-swap served logits are
    bit-identical to the eval forward of the NEW checkpoint, across every
    bucket, with ZERO new jit-cache entries."""
    engine, *_ = control
    before = engine._total_compiles()
    rec = engine.swap_params(step=1)  # resolves against engine.ckpt_dir
    assert rec["adopted"] and not rec["rolled_back"]
    assert rec["step"] == 1
    assert engine._total_compiles() == before
    assert engine.recompiles_since_warmup() == 0
    full_new = engine.full_logits()  # eval forward of the new checkpoint
    for n in (1, 8, 13, 27, 32):
        ids = rng.choice(engine.num_nodes, size=n, replace=False)
        out = engine.infer(ids)
        r, s = engine.rank_slot(ids)
        np.testing.assert_array_equal(out, full_new[r, s])
    assert engine.recompiles_since_warmup() == 0
    # the attempt is on the lineage record (and therefore in serve_health)
    assert any(
        l.get("event") == "swap" and l.get("adopted") and l.get("step") == 1
        for l in engine.lineage
    )
    json.dumps(engine.lineage)


def test_swap_rejects_structural_mismatch_and_nonfinite(control):
    """A checkpoint that cannot replay the warmed executables (different
    tree / shapes) or carries non-finite weights is rolled back before the
    live pointer ever moves."""
    import jax

    engine, *_ = control
    full_before = engine.full_logits()

    wrong = {"not_the_params": np.zeros(3, np.float32)}
    with pytest.raises(SwapRejected) as ei:
        engine.swap_params(params=wrong)
    assert ei.value.context["reason"] == "structure_mismatch"
    assert ei.value.context["rolled_back"] is True

    bad = jax.tree.map(lambda x: np.array(x), engine._params)
    jax.tree.leaves(bad)[0].reshape(-1)[0] = np.nan
    with pytest.raises(SwapRejected) as ei:
        engine.swap_params(params=bad)
    rec = ei.value.record()
    assert rec["reason"] == "nonfinite_params" and rec["error"] == "swap_rejected"
    json.dumps(rec)

    # restore-phase rejections (missing checkpoint) also land one lineage
    # record — the contract is one record per ATTEMPT, adopted or not
    lineage_before = len(engine.lineage)
    with pytest.raises(SwapRejected) as ei:
        engine.swap_params("/nonexistent/ckpt_dir")
    assert ei.value.context["reason"] == "not_found"
    assert len(engine.lineage) == lineage_before + 1
    assert engine.lineage[-1]["reason"] == "not_found"

    # both rollbacks left serving bit-identical, compile-free
    np.testing.assert_array_equal(engine.full_logits(), full_before)
    assert engine.recompiles_since_warmup() == 0


def test_swap_chaos_rollback_zero_dropped_inflight(control, rng):
    """The e2e acceptance pin: a fault injected mid-swap
    (``serve.swap=raise@0``) rolls back to the prior params while
    concurrent in-flight requests ALL resolve, bit-identical to the
    pre-swap oracle — zero drops, zero compiles."""
    from dgraph_tpu import chaos
    from dgraph_tpu.serve.batcher import MicroBatcher

    engine, *_ = control
    full = engine.full_logits()
    bat = MicroBatcher(
        engine, max_batch_size=4, max_delay_ms=1.0, max_queue_depth=64
    )
    try:
        futs, refs = [], []
        for _ in range(12):
            ids = rng.choice(engine.num_nodes, size=int(rng.integers(1, 33)),
                             replace=False)
            futs.append(bat.submit(ids))
            r, s = engine.rank_slot(ids)
            refs.append(full[r, s])
        chaos.arm("serve.swap=raise@0")
        try:
            with pytest.raises(SwapRejected) as ei:
                engine.swap_params(step=0)
            assert ei.value.context["reason"] == "fault"
            assert ei.value.context["rolled_back"] is True
        finally:
            chaos.reset()
        # every in-flight request resolves against the UNmoved params
        for fut, ref in zip(futs, refs):
            np.testing.assert_array_equal(fut.result(timeout=60), ref)
        assert engine.recompiles_since_warmup() == 0
    finally:
        bat.stop()


# ---------------------------------------------------------------------------
# reset_degraded atomicity (the failure-epoch race fix)
# ---------------------------------------------------------------------------


def test_reset_degraded_not_resurrected_by_inflight_failure(control, rng):
    """The satellite pin: an infer DISPATCHED before reset_degraded() whose
    failure lands after it must not resurrect degraded mode. Without the
    failure-epoch gate, the worker's late failure re-degrades the engine
    the instant after the operator re-admitted traffic."""
    from dgraph_tpu import chaos

    engine, *_ = control
    saved = (engine.degrade_after, engine.retry_backoff_s)
    engine.degrade_after, engine.retry_backoff_s = 1, 0.2
    try:
        chaos.arm("serve.infer=raise@0:count=1000")
        errs = []

        def failing_infer():
            try:
                engine.infer(rng.choice(engine.num_nodes, size=3,
                                        replace=False))
            except Exception as e:  # noqa: BLE001
                errs.append(e)

        t = threading.Thread(target=failing_infer)
        t.start()
        time.sleep(0.1)  # in flight: inside the ~0.4s retry backoff window
        engine.reset_degraded()  # operator re-admits mid-request
        t.join(timeout=30)
        assert errs, "chaos-armed infer did not fail"
        # the stale failure was attributed to the OLD epoch: with
        # degrade_after=1 a post-reset attribution would have re-degraded
        assert engine.degraded is False
        assert engine._consecutive_failures == 0
    finally:
        chaos.reset()
        engine.degrade_after, engine.retry_backoff_s = saved
        engine.reset_degraded()


def test_reset_degraded_serializes_under_engine_lock(control):
    """reset_degraded takes the engine lock — a control-plane mutation in
    flight (swap/append/accounting) blocks it rather than interleaving."""
    engine, *_ = control
    done = threading.Event()
    engine._lock.acquire()
    try:
        t = threading.Thread(
            target=lambda: (engine.reset_degraded(), done.set())
        )
        t.start()
        time.sleep(0.05)
        assert not done.is_set(), "reset_degraded did not take the lock"
    finally:
        engine._lock.release()
    assert done.wait(timeout=10)


# ---------------------------------------------------------------------------
# model registry: atomic between-batches flip
# ---------------------------------------------------------------------------


def test_registry_flip_serves_through_batcher(control, rng):
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.registry import ModelRegistry

    engine, *_ = control
    reg = ModelRegistry()
    reg.register("blue", engine, activate=True)
    assert reg.active_name == "blue"
    full = engine.full_logits()
    bat = MicroBatcher(reg, max_batch_size=4, max_delay_ms=0.5)
    try:
        ids = rng.choice(engine.num_nodes, size=9, replace=False)
        r, s = engine.rank_slot(ids)
        np.testing.assert_array_equal(bat.infer(ids), full[r, s])
        # flip to a second named entry mid-traffic (same engine object:
        # the flip machinery, not a second warmup, is under test)
        reg.register("green", engine)
        reg.activate("green")
        assert reg.active_name == "green"
        np.testing.assert_array_equal(bat.infer(ids), full[r, s])
        rec = reg.record()
        assert rec["active"] == "green" and set(rec["models"]) == {"blue", "green"}
        json.dumps(rec)
    finally:
        bat.stop()
    # a replacement whose ladder shrank below the active one's is refused:
    # requests admitted against the old ladder could no longer fit
    class _Tiny:
        ladder = BucketLadder((8,))

    with pytest.raises(ValueError):
        reg.activate("green", _Tiny())
    with pytest.raises(KeyError):
        reg.get("red")
    with pytest.raises(ValueError):
        reg.retire("green")  # active entry
    reg.retire("blue")
    assert reg.names() == ["green"]


def test_registry_empty_fails_loudly():
    from dgraph_tpu.serve.registry import ModelRegistry

    reg = ModelRegistry()
    with pytest.raises(KeyError):
        _ = reg.active_engine


class _BlockingFakeEngine:
    """Fake engine whose infer blocks on an event, with a configurable
    graph size — the deterministic scaffold for flip-under-load tests."""

    def __init__(self, ladder, num_nodes, block=None, started=None):
        from dgraph_tpu.obs.metrics import Metrics

        self.ladder = ladder
        self.num_nodes = num_nodes
        self.registry = Metrics()
        self.calls = []
        self._block = block
        self._started = started

    def infer(self, ids):
        if self._started is not None:
            self._started.set()
        if self._block is not None:
            assert self._block.wait(timeout=30)
        ids = np.asarray(ids)
        if ids.size and ids.max() >= self.num_nodes:
            raise ValueError("engine saw an id it was never validated for")
        self.calls.append(ids)
        return np.zeros((len(ids), 3), np.float32)


def test_registry_flip_revalidates_queued_requests():
    """A request validated against the OLD engine but flushed on a NEW one
    (registry flip to a smaller graph between submit and flush) fails
    individually with a structured stale rejection instead of reaching the
    engine and fanning its failure out to the co-batched requests."""
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.registry import ModelRegistry

    block, started = threading.Event(), threading.Event()
    eng_a = _BlockingFakeEngine(BucketLadder((8,)), 100, block, started)
    eng_b = _BlockingFakeEngine(BucketLadder((8,)), 50)
    reg = ModelRegistry()
    reg.register("m", eng_a, activate=True)
    bat = MicroBatcher(reg, max_batch_size=1, max_delay_ms=0.0,
                       max_queue_depth=8)
    try:
        f0 = bat.submit(np.array([1, 2]))  # holds the worker inside infer
        assert started.wait(timeout=10)
        f_stale = bat.submit(np.array([80]))  # valid on A, stale on B
        f_ok = bat.submit(np.array([10]))  # valid on both
        reg.activate("m", eng_b)  # rollback to a smaller graph
        block.set()
        f0.result(timeout=10)
        with pytest.raises(ValueError, match="engine now active"):
            f_stale.result(timeout=10)
        assert f_ok.result(timeout=10).shape == (1, 3)
        # the stale request never reached engine B (no fan-out, no crash)
        assert all(c.max() < 50 for c in eng_b.calls if c.size)
        assert bat.registry.snapshot()["counters"]["serve.rejected_stale"] == 1
    finally:
        block.set()
        bat.stop()
    # entry-replacing register on the ACTIVE name enforces the same
    # ladder-coverage rule as activate
    with pytest.raises(ValueError):
        reg.register(
            "m", _BlockingFakeEngine(BucketLadder((4,)), 50), activate=True
        )


def test_engine_outage_does_not_degrade_tenants():
    """Engine-level STRUCTURED rejections (degraded shed, backpressure)
    are the engine's state, not any tenant's payload: they must not feed
    per-tenant degrading — a backend outage + reset would otherwise leave
    every innocent tenant individually shed."""
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.errors import QueueFull

    class _DegradedEngine:
        def __init__(self):
            from dgraph_tpu.obs.metrics import Metrics

            self.ladder = BucketLadder((8,))
            self.registry = Metrics()

        def infer(self, ids):
            raise QueueFull("engine degraded; shedding", degraded=True)

    table = TenantTable(
        TenantQuota(rps=0.0, burst=64, max_queue_share=0.9, degrade_after=1)
    )
    bat = MicroBatcher(_DegradedEngine(), max_delay_ms=0.0, tenants=table)
    try:
        for _ in range(3):
            with pytest.raises(QueueFull):
                bat.infer(np.arange(2), tenant="calm")
        snap = table.snapshot()
        # with degrade_after=1, ONE attributed failure would have flipped
        # the tenant — the engine's shed must not count as one
        assert snap["calm"]["degraded"] is False
        assert snap["calm"]["failures"] == 0
    finally:
        bat.stop()


def test_empty_string_tenant_is_its_own_bucket():
    """'' and None must not split across tenant buckets: failure
    attribution, admission, and degrading all key the same resolved id."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    table = TenantTable(
        TenantQuota(rps=0.0, burst=64, max_queue_share=0.9, degrade_after=2)
    )
    eng = _SlowFakeEngine(BucketLadder((8,)), infer_s=0.0)
    eng.num_nodes = 10
    bat = MicroBatcher(eng, max_delay_ms=0.0, tenants=table)
    try:
        for _ in range(2):
            with pytest.raises(ValueError):
                bat.submit(np.array([99]), tenant="")
        with pytest.raises(TenantDegraded):
            bat.submit(np.array([1]), tenant="")
        # the anonymous/default tenant was never touched by ''s poison
        assert bat.infer(np.array([1])).shape == (1, 3)
        snap = table.snapshot()
        assert snap[""]["degraded"] is True
        assert snap.get("default", {}).get("degraded", False) is False
        # the submit-validation path ticks the shared degraded counter
        # exactly like the worker path would
        counters = bat.registry.snapshot()["counters"]
        assert counters["serve.tenant_degraded"] == 1
    finally:
        bat.stop()


def test_tenant_table_caps_lazily_materialized_tenants():
    """Client-supplied tenant ids are unbounded input: past max_tenants,
    unseen ids fold into the shared default bucket instead of growing
    process memory without bound."""
    from dgraph_tpu.serve.tenancy import DEFAULT_TENANT

    table = TenantTable(
        TenantQuota(rps=0.0, burst=8, max_queue_share=0.9), max_tenants=2
    )
    assert table.admit("t1", 64) == "t1"
    assert table.admit("t2", 64) == "t2"
    # the cap: a third distinct id resolves to the shared default bucket
    assert table.admit("t3", 64) == DEFAULT_TENANT
    assert table.admit("t4", 64) == DEFAULT_TENANT
    snap = table.snapshot()
    assert "t3" not in snap and "t4" not in snap
    assert snap[DEFAULT_TENANT]["admitted"] == 2
    with pytest.raises(ValueError):
        TenantTable(max_tenants=0)


# ---------------------------------------------------------------------------
# tenancy: deterministic policy units + noisy-neighbor isolation
# ---------------------------------------------------------------------------


def test_token_bucket_deterministic_clock():
    clock = [0.0]
    table = TenantTable(
        TenantQuota(rps=2.0, burst=2, max_queue_share=1.0),
        clock=lambda: clock[0],
    )
    assert table.admit("a", 64) == "a"
    assert table.admit("a", 64) == "a"
    with pytest.raises(QuotaExceeded) as ei:
        table.admit("a", 64)
    rec = ei.value.record()
    assert rec["error"] == "quota" and rec["reason"] == "rate"
    json.dumps(rec)
    clock[0] += 0.5  # one token refilled at 2 rps
    assert table.admit("a", 64) == "a"
    with pytest.raises(QuotaExceeded):
        table.admit("a", 64)
    # a second tenant has its own bucket
    assert table.admit("b", 64) == "b"


def test_tenant_queue_share_and_release():
    table = TenantTable(TenantQuota(rps=0.0, burst=8, max_queue_share=0.25))
    for _ in range(4):  # 25% of depth 16
        table.admit("a", 16)
    with pytest.raises(QuotaExceeded) as ei:
        table.admit("a", 16)
    assert ei.value.context["reason"] == "queue_share"
    table.release("a")  # one slot frees one admission
    table.admit("a", 16)
    snap = table.snapshot()
    assert snap["a"]["queued"] == 4 and snap["a"]["shed_quota"] == 1


def test_tenant_quota_validation():
    with pytest.raises(ValueError):
        TenantQuota(burst=0)
    with pytest.raises(ValueError):
        TenantQuota(max_queue_share=0.0)
    with pytest.raises(ValueError):
        TenantQuota(max_queue_share=1.5)
    with pytest.raises(ValueError):
        TenantQuota(degrade_after=-1)


class _SlowFakeEngine:
    """Deterministic engine stand-in with a small per-batch cost, so a
    flooding tenant actually creates queue contention."""

    def __init__(self, ladder, infer_s=0.002):
        from dgraph_tpu.obs.metrics import Metrics

        self.ladder = ladder
        self.registry = Metrics()
        self.infer_s = infer_s
        self.calls = 0

    def infer(self, ids):
        self.calls += 1
        time.sleep(self.infer_s)
        return np.zeros((len(ids), 3), np.float32)


def test_noisy_neighbor_flood_sheds_only_the_flooder():
    """The isolation pin: tenant A floods far past its quota; A is shed
    with the structured ``quota`` error, B's requests ALL complete, B is
    never shed, and B's p99 stays bounded."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    table = TenantTable(
        TenantQuota(rps=0.0, burst=8, max_queue_share=0.9),
        quotas={"A": TenantQuota(rps=0.001, burst=4, max_queue_share=0.25)},
    )
    eng = _SlowFakeEngine(BucketLadder((64,)))
    bat = MicroBatcher(
        eng, max_batch_size=4, max_delay_ms=0.2, max_queue_depth=16,
        tenants=table,
    )
    try:
        a_ok, a_shed = 0, 0
        b_futs = []

        def flood_a():
            nonlocal a_ok, a_shed
            for _ in range(40):
                try:
                    bat.submit(np.arange(3), tenant="A")
                    a_ok += 1
                except QuotaExceeded:
                    a_shed += 1

        t = threading.Thread(target=flood_a)
        t.start()
        for _ in range(10):
            b_futs.append(bat.submit(np.arange(2), tenant="B"))
            time.sleep(0.003)
        t.join(timeout=30)
        for f in b_futs:
            assert f.result(timeout=30).shape == (2, 3)  # all of B served
        snap = table.snapshot()
        assert a_shed > 0 and snap["A"]["shed_quota"] == a_shed
        assert snap["B"]["shed_quota"] == 0 and snap["B"]["shed_degraded"] == 0
        # B's p99-under-contention is recorded and bounded (well under the
        # batcher's own timeout — the flood did not starve B's tail)
        b_hist = bat.registry.snapshot()["histograms"].get(
            "serve.tenant.B.request_ms"
        )
        assert b_hist and b_hist["count"] == 10
        assert b_hist["p99"] < 5_000.0
    finally:
        bat.stop()


def test_tenant_degraded_isolation_and_reset():
    """Poisoned payloads degrade ONLY their tenant: bad submissions from
    'poison' flip it into degraded shedding while 'good' keeps flowing;
    reset() re-admits."""
    from dgraph_tpu.serve.batcher import MicroBatcher

    table = TenantTable(
        TenantQuota(rps=0.0, burst=64, max_queue_share=0.9, degrade_after=2)
    )
    eng = _SlowFakeEngine(BucketLadder((8,)), infer_s=0.0)
    eng.num_nodes = 100
    bat = MicroBatcher(eng, max_delay_ms=0.0, max_queue_depth=16,
                       tenants=table)
    try:
        for _ in range(2):  # poisoned payloads: ids out of range
            with pytest.raises(ValueError):
                bat.submit(np.array([500]), tenant="poison")
        with pytest.raises(TenantDegraded) as ei:
            bat.submit(np.array([1]), tenant="poison")
        assert ei.value.record()["error"] == "tenant_degraded"
        # the neighbor is untouched
        assert bat.infer(np.array([1, 2]), tenant="good").shape == (2, 3)
        snap = table.snapshot()
        assert snap["poison"]["degraded"] is True
        assert snap["good"]["degraded"] is False
        table.reset("poison")
        assert bat.infer(np.array([3]), tenant="poison").shape == (1, 3)
    finally:
        bat.stop()


def test_serve_health_carries_tenants_and_lineage(control):
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.health import serve_health_record

    engine, *_ = control
    table = TenantTable(TenantQuota(rps=0.0, burst=8, max_queue_share=0.9))
    bat = MicroBatcher(engine, max_delay_ms=0.2, tenants=table)
    try:
        bat.infer(np.arange(4), tenant="acme")
        rec = serve_health_record(engine, bat)
        assert "acme" in rec["tenants"]
        assert rec["tenants"]["acme"]["admitted"] == 1
        assert rec["tenants"]["acme"]["latency_ms"]["count"] == 1
        assert isinstance(rec["lineage"], list) and rec["lineage"]
        json.dumps(rec, default=str)
    finally:
        bat.stop()


# ---------------------------------------------------------------------------
# live graph deltas: append -> replan -> atomic adoption (+ oracle pin)
# ---------------------------------------------------------------------------


def test_delta_append_replan_adopt_matches_from_scratch_oracle(
    mesh8, tmp_path, rng
):
    """The delta acceptance pin: queries over appended vertices after
    adoption are BIT-IDENTICAL to a from-scratch monolithic rebuild of the
    composed graph; live pad-slot placement matches the re-plan's
    partition; appends and adoption mint zero new executables on the
    running engine."""
    import jax
    import jax.numpy as jnp

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.data import synthetic
    from dgraph_tpu.models import GCN
    from dgraph_tpu.obs.metrics import Metrics
    from dgraph_tpu.partition import renumber_contiguous
    from dgraph_tpu.plan import build_edge_plan, shard_vertex_data
    from dgraph_tpu.serve import deltas
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.engine import ServeEngine
    from dgraph_tpu.serve.registry import ModelRegistry
    from dgraph_tpu.train.loop import init_params

    run_dir = str(tmp_path / "world")
    data = synthetic.sbm_classification_graph(
        num_nodes=96, num_classes=3, feat_dim=8, avg_degree=4.0
    )
    deltas.init_world(
        run_dir, data["edge_index"], data["features"], world_size=8,
        partition_method="random", seed=0,
    )
    comm = Communicator.init_process_group("tpu", world_size=8)
    model = GCN(8, 3, comm=comm, num_layers=2)
    ladder = BucketLadder((8,))

    info0 = deltas.load_generation(run_dir)
    params = init_params(
        model, mesh8, jax.tree.map(jnp.asarray, info0["plan"]),
        jax.tree.map(jnp.asarray, info0["batch"]), seed=0,
    )
    eng0 = deltas.build_engine(run_dir, model, mesh8, params, ladder=ladder,
                               registry=Metrics())
    assert eng0.generation == 0
    eng0.infer(np.arange(8))  # compile the single bucket once

    # durable staging FIRST, then the live install (crash between the two
    # replays the append from disk at the next re-plan)
    new_feats = rng.normal(size=(4, 8)).astype(np.float32)
    new_edges = np.array([[0, 1, 96, 97], [96, 97, 2, 99]])
    drec = deltas.append_delta(run_dir, new_feats, new_edges)
    assert drec["id_base"] == 96 and drec["new_nodes"] == 4
    compiles_before = eng0._total_compiles()
    live_ids = eng0.append_vertices(new_feats)
    np.testing.assert_array_equal(live_ids, [96, 97, 98, 99])
    assert eng0.num_nodes == 100
    # appended vertices are queryable NOW (isolated semantics), compile-free
    assert eng0.infer(live_ids).shape == (4, 3)
    assert eng0._total_compiles() == compiles_before

    # background re-plan + atomic pointer flip
    w1 = deltas.replan(run_dir)
    assert w1["generation"] == 1 and w1["num_nodes"] == 100
    assert deltas.read_world(run_dir)["generation"] == 1

    # adoption: fresh engine over generation 1, flipped live via the
    # registry behind one batcher — old ids and appended ids both served
    eng1 = deltas.build_engine(run_dir, model, mesh8, params, ladder=ladder,
                               registry=Metrics())
    assert eng1.generation == 1
    reg = ModelRegistry()
    reg.register("default", eng0, activate=True)
    bat = MicroBatcher(reg, max_batch_size=4, max_delay_ms=0.5)
    try:
        assert bat.infer(np.arange(5)).shape == (5, 3)
        reg.activate("default", eng1)  # the adoption flip
        out_live = bat.infer(live_ids)
    finally:
        bat.stop()

    full1 = eng1.full_logits()
    r1, s1 = eng1.rank_slot(live_ids)
    np.testing.assert_array_equal(out_live, full1[r1, s1])

    # from-scratch rebuild oracle: monolithic build_edge_plan over the
    # SAME composed graph + partition — a different assembly path whose
    # forward must agree bit-for-bit on EVERY vertex
    g1 = np.load(deltas.graph_path(run_dir, 1))
    ren = renumber_contiguous(np.asarray(g1["partition"]), 8)
    oplan, _ = build_edge_plan(
        np.asarray(ren.perm)[np.asarray(g1["edge_index"])], ren.partition,
        world_size=8, pad_multiple=8,
    )
    feats_sh = shard_vertex_data(
        np.asarray(g1["features"])[ren.inv], ren.counts, oplan.n_src_pad
    ).astype(np.float32)
    vmask = shard_vertex_data(np.ones(100, np.float32), ren.counts,
                              oplan.n_src_pad)
    id_rank = np.asarray(ren.partition)[np.asarray(ren.perm)]
    id_slot = np.asarray(ren.perm) - np.asarray(ren.offsets)[id_rank]
    oracle = ServeEngine(
        model, mesh8, oplan, params, {"x": feats_sh, "vmask": vmask},
        id_rank, id_slot, ladder=ladder, registry=Metrics(),
    )
    all_ids = np.arange(100)
    ra, sa = eng1.rank_slot(all_ids)
    ro, so = oracle.rank_slot(all_ids)
    np.testing.assert_array_equal(full1[ra, sa], oracle.full_logits()[ro, so])

    # live placement == the re-plan's recomputed partition (the shared
    # deterministic waterfill)
    np.testing.assert_array_equal(
        eng0.rank_slot(live_ids)[0], np.asarray(g1["partition"])[96:]
    )


def test_delta_validation_and_pad_budget(mesh8, tmp_path):
    from dgraph_tpu.serve import deltas

    run_dir = str(tmp_path / "world")
    edges = np.stack([np.arange(24), (np.arange(24) + 1) % 24])
    feats = np.ones((24, 4), np.float32)
    deltas.init_world(run_dir, edges, feats, world_size=4,
                      partition_method="block", pad_multiple=4)
    with pytest.raises(deltas.DeltaError):  # wrong feature width
        deltas.append_delta(run_dir, np.ones((2, 5), np.float32),
                            np.zeros((2, 0), np.int64))
    with pytest.raises(deltas.DeltaError):  # edge beyond the id horizon
        deltas.append_delta(run_dir, np.ones((1, 4), np.float32),
                            np.array([[0], [99]]))
    # sequenced appends extend the id horizon
    r1 = deltas.append_delta(run_dir, np.ones((2, 4), np.float32),
                             np.array([[24], [25]]))
    r2 = deltas.append_delta(run_dir, np.ones((1, 4), np.float32),
                             np.array([[26], [0]]))
    assert (r1["id_base"], r2["id_base"]) == (24, 26)
    # a replan with nothing staged is a no-op returning the same pointer
    w1 = deltas.replan(run_dir)
    assert w1["generation"] == 1 and w1["deltas_adopted"] == 2
    assert deltas.replan(run_dir) == deltas.read_world(run_dir)


def test_free_pad_slots_clamps_without_appendable_batch(control):
    engine, *_ = control
    saved = engine._host_x
    try:
        engine._host_x = None
        assert engine.free_pad_slots() == 0
    finally:
        engine._host_x = saved


def _tiny_delta_world(tmp_path):
    from dgraph_tpu.serve import deltas

    run_dir = str(tmp_path / "world")
    edges = np.stack([np.arange(24), (np.arange(24) + 1) % 24])
    deltas.init_world(run_dir, edges, np.ones((24, 4), np.float32),
                      world_size=4, partition_method="block", pad_multiple=4)
    return run_dir


def test_replan_folds_deltas_that_land_mid_build(tmp_path, monkeypatch):
    """A delta appended while the background replan is building must not
    be orphaned: the commit re-snapshots the staged set and folds another
    round instead of adopting a generation that silently drops it."""
    import dgraph_tpu.plan as plan_mod
    from dgraph_tpu.serve import deltas

    run_dir = _tiny_delta_world(tmp_path)
    deltas.append_delta(run_dir, np.ones((2, 4), np.float32),
                        np.array([[0, 24], [24, 25]]))
    real_build = plan_mod.build_plan_shards
    rounds = {"n": 0}

    def racing_build(*args, **kwargs):
        rounds["n"] += 1
        if rounds["n"] == 1:
            # the mid-build append (request thread racing the replanner)
            deltas.append_delta(run_dir, np.full((1, 4), 2.0, np.float32),
                                np.array([[25], [26]]))
        return real_build(*args, **kwargs)

    monkeypatch.setattr(plan_mod, "build_plan_shards", racing_build)
    world = deltas.replan(run_dir)
    assert rounds["n"] == 2  # the commit refused round 1 and re-folded
    assert world["generation"] == 1
    assert world["num_nodes"] == 27  # 24 base + 2 + the late 1
    assert world["deltas_adopted"] == 2
    # gen-0 staged files remain as history; the ADOPTED graph carries them
    assert len(deltas.staged_delta_paths(run_dir, 0)) == 2
    # exhaustion is a structured error, not an orphaning adoption
    def always_racing(*args, **kwargs):
        deltas.append_delta(
            run_dir,
            np.ones((1, 4), np.float32),
            np.zeros((2, 0), np.int64),
        )
        return real_build(*args, **kwargs)

    monkeypatch.setattr(plan_mod, "build_plan_shards", always_racing)
    deltas.append_delta(run_dir, np.ones((1, 4), np.float32),
                        np.zeros((2, 0), np.int64))
    with pytest.raises(deltas.DeltaError, match="quiesce appends"):
        deltas.replan(run_dir, max_rounds=2)
    assert deltas.read_world(run_dir)["generation"] == 1  # nothing adopted


def test_append_delta_concurrent_appends_never_collide(tmp_path):
    """Concurrent appends (request threads) get distinct seq files and a
    contiguous, collision-free id space; a racer's already-published file
    is detected by the no-clobber link and retried, never overwritten."""
    from dgraph_tpu.serve import deltas

    run_dir = _tiny_delta_world(tmp_path)
    recs = []

    def appender(i):
        recs.append(deltas.append_delta(
            run_dir, np.full((1, 4), float(i), np.float32),
            np.zeros((2, 0), np.int64),
        ))

    threads = [threading.Thread(target=appender, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    paths = deltas.staged_delta_paths(run_dir, 0)
    assert len(paths) == 8
    bases = sorted(r["id_base"] for r in recs)
    assert bases == list(range(24, 32))  # contiguous, no collisions
    assert sorted(r["seq"] for r in recs) == list(range(8))


def test_replan_sigterm_is_atomic_old_or_new_never_torn(tmp_path):
    """The chaos acceptance pin, subprocess-for-real: SIGTERM at the
    commit boundary (all generation-1 artifacts durable, pointer not yet
    flipped) leaves generation 0 adopted; SIGTERM mid shard stream leaves
    generation 0 adopted; a chaos-free rerun resumes and adopts
    generation 1 — old or new, never torn."""
    from dgraph_tpu.plan_shards import read_manifest
    from dgraph_tpu.serve import deltas

    worker = os.path.join(REPO, "tests", "_replan_worker.py")
    env_base = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONUNBUFFERED": "1",
    }
    env_base.pop("DGRAPH_CHAOS", None)

    for clause, label in (
        # index 1 = the second serve.replan consult: the commit boundary
        ("serve.replan=sigterm@1", "commit-boundary"),
        # kill mid shard writes: the resumable-build torn window
        ("plan.write=sigterm@2", "mid-shard-stream"),
    ):
        run_dir = str(tmp_path / label)
        out = subprocess.run(
            [sys.executable, worker, run_dir, "init"],
            capture_output=True, text=True, timeout=300, env=env_base,
            cwd=REPO,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert deltas.read_world(run_dir)["generation"] == 0

        out = subprocess.run(
            [sys.executable, worker, run_dir, "replan"],
            capture_output=True, text=True, timeout=300,
            env={**env_base, "DGRAPH_CHAOS": clause}, cwd=REPO,
        )
        assert out.returncode != 0, (
            f"{label}: chaos sigterm did not kill the replan: "
            + out.stdout + out.stderr
        )
        # the adoption contract: pointer still names the OLD generation
        world = deltas.read_world(run_dir)
        assert world["generation"] == 0, f"{label}: torn adoption: {world}"

        # chaos-free rerun: the streaming build resumes, adoption commits
        out = subprocess.run(
            [sys.executable, worker, run_dir, "replan"],
            capture_output=True, text=True, timeout=300, env=env_base,
            cwd=REPO,
        )
        assert out.returncode == 0, out.stdout + out.stderr
        world = deltas.read_world(run_dir)
        assert world["generation"] == 1 and world["num_nodes"] == 51
        manifest = read_manifest(deltas.plan_dir(run_dir, 1))
        assert manifest["complete"], f"{label}: adopted an incomplete plan"
