import numpy as np
import pytest

from dgraph_tpu import partition as pt


def ring_graph(n):
    src = np.arange(n)
    dst = (src + 1) % n
    # symmetrize
    return np.stack([np.concatenate([src, dst]), np.concatenate([dst, src])])


def test_round_robin():
    p = pt.round_robin_partition(10, 4)
    assert p.tolist() == [0, 1, 2, 3, 0, 1, 2, 3, 0, 1]


def test_block_partition_balanced():
    p = pt.block_partition(10, 4)
    counts = np.bincount(p, minlength=4)
    assert counts.sum() == 10 and counts.max() - counts.min() <= 3
    assert np.all(np.diff(p) >= 0)


@pytest.mark.parametrize("method", ["round_robin", "block", "random", "rcm", "greedy_bfs"])
def test_partition_graph_all_methods(method):
    edges = ring_graph(32)
    new_edges, ren = pt.partition_graph(edges, 32, 4, method=method)
    # every vertex assigned, blocks contiguous, perm is a bijection
    assert ren.counts.sum() == 32
    assert np.all(np.diff(ren.partition) >= 0)
    assert sorted(ren.perm.tolist()) == list(range(32))
    # renumbered edges preserve adjacency structure
    old_set = set(map(tuple, edges.T.tolist()))
    back = ren.inv[new_edges]
    assert set(map(tuple, back.T.tolist())) == old_set


def test_rcm_locality_beats_round_robin():
    edges = ring_graph(256)
    rr = pt.round_robin_partition(256, 8)
    rcm = pt.rcm_partition(edges, 256, 8)
    assert pt.edge_cut(edges, rcm) < pt.edge_cut(edges, rr)


def test_renumber_contiguous_inverse():
    part = np.array([2, 0, 1, 0, 2, 1, 0])
    ren = pt.renumber_contiguous(part, 3)
    assert ren.counts.tolist() == [3, 2, 2]
    # inv/perm are inverses
    assert np.all(ren.perm[ren.inv] == np.arange(7))
    # new partition assigns the same rank each old vertex had
    assert np.all(ren.partition[ren.perm] == part)


class TestMultilevel:
    """Multilevel (METIS-shaped) partitioner: validity, balance, and cut
    quality vs greedy BFS on a locality-structured graph."""

    def _ring_of_cliques(self, n_cliques=32, clique=24, seed=0):
        """Planted structure: cliques chained in a ring — ideal partitions
        cut only ring links."""
        import numpy as np

        rng = np.random.default_rng(seed)
        src, dst = [], []
        for c in range(n_cliques):
            base = c * clique
            for i in range(clique):
                for j in range(i + 1, clique):
                    src.append(base + i)
                    dst.append(base + j)
            nxt = ((c + 1) % n_cliques) * clique
            src.append(base)
            dst.append(nxt)
        V = n_cliques * clique
        edge_index = np.stack([np.array(src), np.array(dst)])
        perm = rng.permutation(edge_index.shape[1])
        return edge_index[:, perm], V

    def test_valid_and_balanced(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        edge_index, V = self._ring_of_cliques()
        for W in (2, 4, 8):
            part = pt.multilevel_partition(edge_index, V, W, seed=0)
            assert part.shape == (V,)
            assert part.min() >= 0 and part.max() < W
            counts = np.bincount(part, minlength=W)
            assert counts.max() <= int(np.ceil(V / W) * 1.1) + 1, counts

    def test_beats_greedy_bfs_cut(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        edge_index, V = self._ring_of_cliques()
        W = 8
        ml = pt.multilevel_partition(edge_index, V, W, seed=0)
        bfs = pt.greedy_bfs_partition(edge_index, V, W, seed=0)
        cut_ml = pt.edge_cut(edge_index, ml)
        cut_bfs = pt.edge_cut(edge_index, bfs)
        # on planted-structure graphs multilevel must not be worse; usually
        # it is strictly better (near-zero cut)
        assert cut_ml <= cut_bfs, (cut_ml, cut_bfs)

    def test_partition_graph_method(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        edge_index, V = self._ring_of_cliques(n_cliques=8, clique=12)
        new_edges, ren = pt.partition_graph(edge_index, V, 4, method="metis")
        assert np.all(np.diff(ren.partition) >= 0)  # contiguous blocks
        assert new_edges.max() < V

    def test_isolated_and_self_loop_vertices(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        V, W = 50, 4
        edge_index = np.array([[0, 1, 2, 7, 7], [1, 2, 0, 7, 8]])  # + self loop
        part = pt.multilevel_partition(edge_index, V, W, seed=0)
        assert part.shape == (V,)
        assert part.min() >= 0 and part.max() < W
