import numpy as np
import pytest

from dgraph_tpu import partition as pt


def ring_graph(n):
    src = np.arange(n)
    dst = (src + 1) % n
    # symmetrize
    return np.stack([np.concatenate([src, dst]), np.concatenate([dst, src])])


def test_round_robin():
    p = pt.round_robin_partition(10, 4)
    assert p.tolist() == [0, 1, 2, 3, 0, 1, 2, 3, 0, 1]


def test_block_partition_balanced():
    p = pt.block_partition(10, 4)
    counts = np.bincount(p, minlength=4)
    assert counts.sum() == 10 and counts.max() - counts.min() <= 3
    assert np.all(np.diff(p) >= 0)


@pytest.mark.parametrize("method", ["round_robin", "block", "random", "rcm", "greedy_bfs"])
def test_partition_graph_all_methods(method):
    edges = ring_graph(32)
    new_edges, ren = pt.partition_graph(edges, 32, 4, method=method)
    # every vertex assigned, blocks contiguous, perm is a bijection
    assert ren.counts.sum() == 32
    assert np.all(np.diff(ren.partition) >= 0)
    assert sorted(ren.perm.tolist()) == list(range(32))
    # renumbered edges preserve adjacency structure
    old_set = set(map(tuple, edges.T.tolist()))
    back = ren.inv[new_edges]
    assert set(map(tuple, back.T.tolist())) == old_set


def test_rcm_locality_beats_round_robin():
    edges = ring_graph(256)
    rr = pt.round_robin_partition(256, 8)
    rcm = pt.rcm_partition(edges, 256, 8)
    assert pt.edge_cut(edges, rcm) < pt.edge_cut(edges, rr)


def test_renumber_contiguous_inverse():
    part = np.array([2, 0, 1, 0, 2, 1, 0])
    ren = pt.renumber_contiguous(part, 3)
    assert ren.counts.tolist() == [3, 2, 2]
    # inv/perm are inverses
    assert np.all(ren.perm[ren.inv] == np.arange(7))
    # new partition assigns the same rank each old vertex had
    assert np.all(ren.partition[ren.perm] == part)


class TestMultilevel:
    """Multilevel (METIS-shaped) partitioner: validity, balance, and cut
    quality vs greedy BFS on a locality-structured graph."""

    def _ring_of_cliques(self, n_cliques=32, clique=24, seed=0):
        """Planted structure: cliques chained in a ring — ideal partitions
        cut only ring links."""
        import numpy as np

        rng = np.random.default_rng(seed)
        src, dst = [], []
        for c in range(n_cliques):
            base = c * clique
            for i in range(clique):
                for j in range(i + 1, clique):
                    src.append(base + i)
                    dst.append(base + j)
            nxt = ((c + 1) % n_cliques) * clique
            src.append(base)
            dst.append(nxt)
        V = n_cliques * clique
        edge_index = np.stack([np.array(src), np.array(dst)])
        perm = rng.permutation(edge_index.shape[1])
        return edge_index[:, perm], V

    def test_valid_and_balanced(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        edge_index, V = self._ring_of_cliques()
        for W in (2, 4, 8):
            part = pt.multilevel_partition(edge_index, V, W, seed=0)
            assert part.shape == (V,)
            assert part.min() >= 0 and part.max() < W
            counts = np.bincount(part, minlength=W)
            assert counts.max() <= int(np.ceil(V / W) * 1.1) + 1, counts

    def test_beats_greedy_bfs_cut(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        edge_index, V = self._ring_of_cliques()
        W = 8
        ml = pt.multilevel_partition(edge_index, V, W, seed=0)
        bfs = pt.greedy_bfs_partition(edge_index, V, W, seed=0)
        cut_ml = pt.edge_cut(edge_index, ml)
        cut_bfs = pt.edge_cut(edge_index, bfs)
        # on planted-structure graphs multilevel must not be worse; usually
        # it is strictly better (near-zero cut)
        assert cut_ml <= cut_bfs, (cut_ml, cut_bfs)

    def test_partition_graph_method(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        edge_index, V = self._ring_of_cliques(n_cliques=8, clique=12)
        new_edges, ren = pt.partition_graph(edge_index, V, 4, method="metis")
        assert np.all(np.diff(ren.partition) >= 0)  # contiguous blocks
        assert new_edges.max() < V

    def test_isolated_and_self_loop_vertices(self):
        import numpy as np
        from dgraph_tpu import partition as pt

        V, W = 50, 4
        edge_index = np.array([[0, 1, 2, 7, 7], [1, 2, 0, 7, 8]])  # + self loop
        part = pt.multilevel_partition(edge_index, V, W, seed=0)
        assert part.shape == (V,)
        assert part.min() >= 0 and part.max() < W


class TestMultilevelBig:
    """Memory-bounded coarsen-then-partition path (VERDICT r4 #6): the
    cluster coarsening respects its cap, the projected partition is valid
    and balanced, and cut quality lands in multilevel's neighborhood —
    far better than random/greedy on planted structure."""

    def test_cluster_coarsen_cap_and_coverage(self):
        from dgraph_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        edge_index, V = TestMultilevel()._ring_of_cliques(16, 24)
        cmap, nc = native.cluster_coarsen(edge_index, V, 8, seed=0)
        assert cmap.shape == (V,)
        assert cmap.min() >= 0 and cmap.max() == nc - 1
        sizes = np.bincount(cmap, minlength=nc)
        assert sizes.max() <= 8
        assert np.all(sizes >= 1)  # compacted: no empty cluster ids
        assert nc < V // 3  # it actually coarsened

    def test_valid_balanced_and_near_multilevel_cut(self):
        edge_index, V = TestMultilevel()._ring_of_cliques(32, 24)
        W = 8
        big = pt.multilevel_big_partition(edge_index, V, W, seed=0)
        assert big.shape == (V,)
        assert big.min() >= 0 and big.max() < W
        counts = np.bincount(big, minlength=W)
        assert counts.max() <= int(np.ceil(V / W) * 1.1) + 1, counts
        cut_big = pt.edge_cut(edge_index, big)
        cut_bfs = pt.edge_cut(edge_index, pt.greedy_bfs_partition(
            edge_index, V, W, seed=0))
        assert cut_big <= cut_bfs, (cut_big, cut_bfs)

    def test_memmapped_edges_and_partition_graph_method(self, tmp_path):
        """The edge list can live on disk (the full-scale flow streams it
        from a memmap); partition_graph dispatches the method name."""
        edge_index, V = TestMultilevel()._ring_of_cliques(8, 12)
        path = tmp_path / "edges.npy"
        np.save(path, edge_index)
        mm = np.load(path, mmap_mode="r")
        part = pt.multilevel_big_partition(mm, V, 4, seed=0, chunk=64)
        assert part.shape == (V,) and part.max() < 4
        new_edges, ren = pt.partition_graph(
            edge_index, V, 4, method="multilevel_big"
        )
        assert np.all(np.diff(ren.partition) >= 0)
        assert new_edges.max() < V


class TestMultilevelSampled:
    """Uniform-edge-sample multilevel + full-graph refine (the full-scale
    papers100M partitioner, VERDICT r4 #6)."""

    def test_valid_balanced_and_beats_greedy(self, tmp_path):
        edge_index, V = TestMultilevel()._ring_of_cliques(32, 24)
        W = 8
        # memmapped input: the full-scale flow streams edges from disk
        path = tmp_path / "edges.npy"
        np.save(path, edge_index)
        mm = np.load(path, mmap_mode="r")
        part = pt.multilevel_sampled_partition(
            mm, V, W, seed=0, sample_frac=0.5, chunk=512
        )
        assert part.shape == (V,)
        assert part.min() >= 0 and part.max() < W
        counts = np.bincount(part, minlength=W)
        assert counts.max() <= int(np.ceil(V / W) * 1.1) + 1, counts
        cut = pt.edge_cut(edge_index, part)
        cut_bfs = pt.edge_cut(edge_index, pt.greedy_bfs_partition(
            edge_index, V, W, seed=0))
        assert cut <= cut_bfs, (cut, cut_bfs)

    def test_refine_improves_or_keeps_cut(self):
        from dgraph_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        rng = np.random.default_rng(3)
        edge_index, V = TestMultilevel()._ring_of_cliques(16, 16)
        part = pt.random_partition(V, 4, seed=1)
        before = pt.edge_cut(edge_index, part)
        refined = native.refine_unweighted_csr(
            edge_index, V, 4, part.copy(), passes=4
        )
        after = pt.edge_cut(edge_index, refined)
        assert after <= before, (after, before)
        # balance respected
        assert np.bincount(refined, minlength=4).max() <= int(
            np.ceil(V / 4) * 1.03
        ) + 1

    def test_partition_graph_method_dispatch(self):
        edge_index, V = TestMultilevel()._ring_of_cliques(8, 12)
        new_edges, ren = pt.partition_graph(
            edge_index, V, 4, method="multilevel_sampled"
        )
        assert np.all(np.diff(ren.partition) >= 0)
        assert new_edges.max() < V

    def test_partition_graph_plumbs_sampled_knobs(self):
        """sample_frac/edge_balance reach multilevel_sampled through the
        standard API (ADVICE r5: the measured-good full-scale settings,
        0.35/1.0, were only reachable via scripts/p100m_r5_stages.py).
        Equality with a direct multilevel_sampled_partition call at the
        same seed pins that the values actually arrive."""
        edge_index, V = TestMultilevel()._ring_of_cliques(8, 12)
        _, ren = pt.partition_graph(
            edge_index, V, 4, method="multilevel_sampled", seed=7,
            sample_frac=0.35, edge_balance=1.0,
        )
        direct = pt.multilevel_sampled_partition(
            edge_index, V, 4, seed=7, sample_frac=0.35, edge_balance=1.0
        )
        counts_direct = np.bincount(direct, minlength=4)
        np.testing.assert_array_equal(np.sort(ren.counts), np.sort(counts_direct))
        assert np.all(np.diff(ren.partition) >= 0)

    def test_partition_graph_rejects_knobs_for_other_methods(self):
        """Passing a sampled-only knob with a method that would silently
        ignore it must raise — a 'tuned' run that never saw its tuning is
        the failure mode the plumbing exists to prevent."""
        edge_index, V = TestMultilevel()._ring_of_cliques(8, 12)
        with pytest.raises(ValueError, match="multilevel_sampled"):
            pt.partition_graph(edge_index, V, 4, method="rcm", sample_frac=0.5)
        with pytest.raises(ValueError, match="multilevel_sampled"):
            pt.partition_graph(
                edge_index, V, 4, method="block", edge_balance=1.0
            )

    def test_edge_balance_blend_reduces_edge_imbalance(self):
        """edge_balance trades a little vertex imbalance for owner-edge
        (dst in-degree) balance — the blend that shrinks e_pad on
        hub-heavy graphs (full-scale record: e_imb 1.28 unblended). Needs
        a degree-skewed graph; cliques are uniform so the blend would be
        a no-op there."""
        from dgraph_tpu.data.synthetic import power_law_graph

        V, W = 60_000, 8
        edges = power_law_graph(V, 12.0, seed=4)

        def imbalances(part):
            vc = np.bincount(part, minlength=W)
            ec = np.bincount(part[edges[1]], minlength=W)
            return vc.max() / vc.mean(), ec.max() / ec.mean()

        plain = pt.multilevel_sampled_partition(
            edges, V, W, seed=0, sample_frac=0.5
        )
        blend = pt.multilevel_sampled_partition(
            edges, V, W, seed=0, sample_frac=0.5, edge_balance=1.0
        )
        n0, e0 = imbalances(plain)
        n1, e1 = imbalances(blend)
        assert e1 < e0, (e1, e0)
        # vertex imbalance may grow but stays within the blend envelope
        assert n1 <= 1.15, n1
        # still a quality partition
        assert pt.edge_cut(edges, blend) < 0.9 * pt.edge_cut(
            edges, pt.random_partition(V, W)
        )


class TestRefineStatus:
    """ADVICE r5: the extern C refine entry points now return an int
    status (0 ok, -1 = build_csr32 refused the int32 id bound) instead
    of silently no-op'ing — plus the Python-side assertion layer."""

    def test_raw_c_entry_reports_refusal(self):
        from dgraph_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        lib = native._load()
        src = np.zeros(0, np.int64)
        dst = np.zeros(0, np.int64)
        dummy = np.zeros(1, np.int32)
        # num_vertices at the int32 bound: build_csr32 refuses BEFORE
        # touching part, so the 1-element dummy is safe — and the
        # caller now sees -1 instead of an unrefined partition
        assert lib.refine_unweighted_csr_c(
            src, dst, 0, 2**31, 2, 3, 1.03, dummy
        ) == -1
        vw = np.zeros(0, np.int64)
        assert lib.refine_weighted_csr_c(
            src, dst, 0, 2**31, 2, 3, 1.03, vw, dummy
        ) == -1

    def test_success_status_and_wrapper_precheck(self):
        from dgraph_tpu import native

        if not native.available():
            pytest.skip("native library unavailable")
        lib = native._load()
        E = np.array([[0, 1, 2, 3], [1, 2, 3, 0]], np.int64)
        part = np.ascontiguousarray([0, 0, 1, 1], np.int32)
        assert lib.refine_unweighted_csr_c(
            np.ascontiguousarray(E[0]), np.ascontiguousarray(E[1]),
            4, 4, 2, 1, 1.03, part,
        ) == 0
        # the Python wrappers fail loudly before the C call ever runs
        with pytest.raises(ValueError, match="int32 CSR id bound"):
            native.refine_unweighted_csr(E, 2**31, 2, part.copy())
        with pytest.raises(ValueError, match="int32 CSR id bound"):
            native.refine_weighted_csr(
                E, np.ones(4, np.int64), 2**31, 2, part.copy()
            )


class TestFromGlobalSampledKnobs:
    """ISSUE 15 satellite: sample_frac/edge_balance are first-class
    DistributedGraph.from_global kwargs (forwarded to partition_graph,
    rejected for non-sampled methods) AND part of the plan-cache key —
    a re-blended partition can never warm-hit a stale plan artifact."""

    def _graph(self):
        rng = np.random.default_rng(0)
        E = rng.integers(0, 48, size=(2, 300))
        X = rng.normal(size=(48, 4)).astype(np.float32)
        return E, X

    def test_knobs_reach_partitioner_and_cache_key(self, tmp_path):
        import os

        from dgraph_tpu.data.graph import DistributedGraph

        E, X = self._graph()
        kw = dict(partition_method="multilevel_sampled",
                  plan_cache_dir=str(tmp_path), tune="off")
        DistributedGraph.from_global(
            E, X, None, None, 2, sample_frac=0.4, edge_balance=0.5, **kw
        )
        dirs = sorted(d for d in os.listdir(tmp_path)
                      if d.startswith("plan_"))
        assert len(dirs) == 1
        # same knobs -> warm hit (same artifact); different blend -> a
        # distinct artifact even if the partition happened to collide
        DistributedGraph.from_global(
            E, X, None, None, 2, sample_frac=0.4, edge_balance=0.5, **kw
        )
        assert sorted(
            d for d in os.listdir(tmp_path) if d.startswith("plan_")
        ) == dirs
        DistributedGraph.from_global(
            E, X, None, None, 2, sample_frac=0.9, **kw
        )
        assert len([d for d in os.listdir(tmp_path)
                    if d.startswith("plan_")]) == 2

    def test_rejected_for_other_methods(self):
        from dgraph_tpu.data.graph import DistributedGraph

        E, X = self._graph()
        with pytest.raises(ValueError, match="multilevel_sampled"):
            DistributedGraph.from_global(
                E, X, None, None, 2, partition_method="rcm",
                sample_frac=0.5, tune="off",
            )
