import numpy as np
import pytest

from dgraph_tpu import partition as pt


def ring_graph(n):
    src = np.arange(n)
    dst = (src + 1) % n
    # symmetrize
    return np.stack([np.concatenate([src, dst]), np.concatenate([dst, src])])


def test_round_robin():
    p = pt.round_robin_partition(10, 4)
    assert p.tolist() == [0, 1, 2, 3, 0, 1, 2, 3, 0, 1]


def test_block_partition_balanced():
    p = pt.block_partition(10, 4)
    counts = np.bincount(p, minlength=4)
    assert counts.sum() == 10 and counts.max() - counts.min() <= 3
    assert np.all(np.diff(p) >= 0)


@pytest.mark.parametrize("method", ["round_robin", "block", "random", "rcm", "greedy_bfs"])
def test_partition_graph_all_methods(method):
    edges = ring_graph(32)
    new_edges, ren = pt.partition_graph(edges, 32, 4, method=method)
    # every vertex assigned, blocks contiguous, perm is a bijection
    assert ren.counts.sum() == 32
    assert np.all(np.diff(ren.partition) >= 0)
    assert sorted(ren.perm.tolist()) == list(range(32))
    # renumbered edges preserve adjacency structure
    old_set = set(map(tuple, edges.T.tolist()))
    back = ren.inv[new_edges]
    assert set(map(tuple, back.T.tolist())) == old_set


def test_rcm_locality_beats_round_robin():
    edges = ring_graph(256)
    rr = pt.round_robin_partition(256, 8)
    rcm = pt.rcm_partition(edges, 256, 8)
    assert pt.edge_cut(edges, rcm) < pt.edge_cut(edges, rr)


def test_renumber_contiguous_inverse():
    part = np.array([2, 0, 1, 0, 2, 1, 0])
    ren = pt.renumber_contiguous(part, 3)
    assert ren.counts.tolist() == [3, 2, 2]
    # inv/perm are inverses
    assert np.all(ren.perm[ren.inv] == np.arange(7))
    # new partition assigns the same rank each old vertex had
    assert np.all(ren.partition[ren.perm] == part)
