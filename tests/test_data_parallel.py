"""Distinct-samples data parallelism: the hybrid (replica x graph) mesh
trains on DIFFERENT samples per replica group and its gradient equals the
mean of the per-sample gradients — the semantics the reference builds with
``ranks_per_graph`` partition groups + ``CommAwareDistributedSampler``
(``NCCLBackendEngine.py:56-64``, ``GraphCast/dist_utils.py:50-113``).

Equivalence pinned (VERDICT r1 #6): one step on a 2x4 mesh with samples
(s0, s1) assigned to the two replica groups == one step on a 1x4 mesh with
the two samples' gradients averaged sequentially.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dgraph_tpu.comm import Communicator
from dgraph_tpu.comm.mesh import make_graph_mesh
from dgraph_tpu.data import DistributedGraph, synthetic
from dgraph_tpu.models import GCN
from dgraph_tpu.train.loop import init_params, make_train_step
from dgraph_tpu.train.sampler import ReplicaSampler


def _graph(world):
    data = synthetic.sbm_classification_graph(
        num_nodes=256, num_classes=4, feat_dim=8, avg_degree=6.0, seed=3
    )
    return DistributedGraph.from_global(
        data["edge_index"],
        data["features"],
        data["labels"],
        data["masks"],
        world_size=world,
        partition_method="random",
        add_symmetric_norm=True,
    )


def _sample_batch(g, seed):
    """Same topology, per-sample features/labels (the GraphCast pattern:
    static graph, varying fields)."""
    rng = np.random.default_rng(seed)
    x = rng.standard_normal(g.features.shape).astype(np.float32)
    y = (rng.random(g.labels.shape) * 4).astype(g.labels.dtype)
    return {
        "x": x,
        "y": y,
        "mask": np.asarray(g.masks["train"]),
        "edge_weight": np.asarray(g.edge_weight),
    }


class TestReplicaSampler:
    def test_distinct_indices_across_replicas(self):
        s = ReplicaSampler(num_samples=8, num_replicas=2, seed=0)
        idx = s.indices(0)
        assert len(idx) == 2 and idx[0] != idx[1]

    def test_epoch_covers_all_samples(self):
        s = ReplicaSampler(num_samples=8, num_replicas=2, seed=0)
        seen = set()
        for t in range(s.steps_per_epoch):
            seen.update(s.indices(t))
        assert seen == set(range(8))

    def test_different_epochs_reshuffle(self):
        s = ReplicaSampler(num_samples=16, num_replicas=2, seed=0)
        e0 = [tuple(s.indices(t)) for t in range(s.steps_per_epoch)]
        e1 = [tuple(s.indices(t + s.steps_per_epoch)) for t in range(s.steps_per_epoch)]
        assert e0 != e1

    def test_stacked_shapes(self):
        s = ReplicaSampler(num_samples=4, num_replicas=2, seed=0)
        got = s.stacked(0, lambda i: {"x": np.full((3, 5), i, np.float32)})
        assert got["x"].shape == (2, 3, 5)
        i0, i1 = s.indices(0)
        assert got["x"][0, 0, 0] == i0 and got["x"][1, 0, 0] == i1


def test_hybrid_mesh_equals_sequential_accumulation():
    """2 replicas x 4 shards, distinct samples == mean of the two samples'
    gradients on a 1x4 mesh (SGD(1.0) makes param deltas = -grad)."""
    W = 4
    g = _graph(W)
    plan = jax.tree.map(jnp.asarray, g.plan)
    comm = Communicator.init_process_group("tpu", world_size=W, replica_axis="replica")
    model = GCN(hidden_features=16, out_features=4, comm=comm)
    opt = optax.sgd(1.0)

    b0 = _sample_batch(g, seed=10)
    b1 = _sample_batch(g, seed=11)

    # --- reference: sequential two-sample accumulation on 1x4 ---
    mesh_seq = make_graph_mesh(ranks_per_graph=W, num_replicas=1,
                               devices=jax.devices()[:W])
    params = init_params(model, mesh_seq, plan, jax.tree.map(jnp.asarray, b0))
    # host copies: params/plan must not carry the 1x4 mesh into the 2x4 step
    params = jax.device_get(params)
    step_seq = make_train_step(model, opt, mesh_seq, plan, donate=False)
    deltas = []
    with jax.set_mesh(mesh_seq):
        for b in (b0, b1):
            p2, _, _ = step_seq(params, opt.init(params),
                                jax.tree.map(jnp.asarray, b), plan)
            deltas.append(jax.device_get(jax.tree.map(lambda a, b_: b_ - a, params, p2)))
    want = jax.tree.map(lambda a, b_: (a + b_) / 2, *deltas)

    # --- hybrid: one step on 2x4 with per-replica batches ---
    mesh_h = make_graph_mesh(ranks_per_graph=W, num_replicas=2)
    sampler = ReplicaSampler(num_samples=2, num_replicas=2, seed=0)
    batches = [b0, b1]
    stacked = sampler.stacked(0, lambda i: batches[i])
    # identity permutation not guaranteed; build want accordingly
    i0, i1 = sampler.indices(0)
    assert {i0, i1} == {0, 1}
    plan_h = jax.tree.map(lambda leaf: jnp.asarray(np.asarray(leaf)), plan)
    step_h = make_train_step(model, opt, mesh_h, plan_h, donate=False,
                             per_replica_batch=True)
    with jax.set_mesh(mesh_h):
        p2, _, metrics = step_h(params, opt.init(params),
                                jax.tree.map(jnp.asarray, stacked), plan_h)
    got = jax.tree.map(lambda a, b_: b_ - a, params, p2)

    flat_w = jax.tree.leaves(want)
    flat_g = jax.tree.leaves(got)
    for w, gg in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(gg), np.asarray(w), rtol=2e-4, atol=2e-5)
    assert np.isfinite(float(metrics["loss"]))
