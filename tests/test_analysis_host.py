"""Host-side concurrency & durability auditor (``dgraph_tpu.analysis.
host``): guarded-field inference, lock-order cycles, durable-write and
pointer-flip-last rules, chaos-coverage drift — plus regression pins for
every REAL violation the first clean-tree run surfaced (the PR 6/11
pattern): the batcher's unlocked ``_inflight`` reset, the engine's
piecemeal unlocked snapshot reads, ``ModelRegistry.active_name``,
membership's unlocked ``_seq`` reads, the non-atomic ``np.savez`` graph
snapshots in ``train/shrink.py``, and the fsync-less hand-rolled tuning
record write.

The whole tier is pure stdlib ``ast``: this file performs ZERO XLA
compiles (the only jax-touching test is the CLI smoke, which itself
traces nothing — the tests/README.md budget rule holds trivially).
"""

import ast
import json
import os
import subprocess
import sys

from dgraph_tpu.analysis import host as H
from dgraph_tpu.analysis import lint as L

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lock_findings(path, src):
    return H.class_concurrency_findings(path, ast.parse(src),
                                        src.splitlines())


def _real(relpath):
    return open(os.path.join(REPO, relpath)).read()


# ---------------------------------------------------------------------------
# guarded-field inference units
# ---------------------------------------------------------------------------


def test_guarded_field_inference_flags_unlocked_write():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def locked(self):\n"
        "        with self._lock:\n"
        "            self.n = 1\n"
        "    def racy(self):\n"
        "        self.n = 2\n"
    )
    got = _lock_findings("dgraph_tpu/serve/x.py", src)
    assert len(got) == 1 and got[0].line == 10
    assert "C.n" in got[0].message


def test_guarded_field_inference_flags_unlocked_read():
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "        self.flag = False\n"
        "    def set(self):\n"
        "        with self._cv:\n"
        "            self.flag = True\n"
        "    def peek(self):\n"
        "        return self.flag\n"
    )
    got = _lock_findings("dgraph_tpu/serve/x.py", src)
    assert len(got) == 1 and "read of C.flag" in got[0].message


def test_init_writes_are_exempt_and_do_not_guard():
    # a field only ever written in __init__ is unguarded; a guarded
    # field's __init__ write is not flagged
    src = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.a = 0\n"
        "        self.b = 0\n"
        "    def w(self):\n"
        "        with self._lock:\n"
        "            self.a = 1\n"
        "    def free(self):\n"
        "        return self.b\n"
    )
    assert not _lock_findings("dgraph_tpu/serve/x.py", src)


def test_container_mutation_counts_as_write():
    src = (
        "import threading\n"
        "import collections\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = collections.deque()\n"
        "    def push(self, x):\n"
        "        with self._lock:\n"
        "            self._q.append(x)\n"
        "    def racy_pop(self):\n"
        "        return self._q.popleft()\n"
    )
    got = _lock_findings("dgraph_tpu/serve/x.py", src)
    assert got and all("_q" in f.message for f in got)


def test_thread_target_escapes_enclosing_lock():
    got = _lock_findings("dgraph_tpu/serve/x.py", H._THREAD_ESCAPE_BAD)
    assert len(got) == 1
    assert "write of Engine.state" in got[0].message


def test_private_helper_with_all_locked_callsites_is_blessed():
    # the TenantTable._state pattern: a private helper mutating guarded
    # state, called only with the lock held, is lock-held by fixpoint
    src = (
        "import threading\n"
        "class T:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._m = {}\n"
        "    def _state(self, k):\n"
        "        self._m[k] = 1\n"
        "        return self._m[k]\n"
        "    def admit(self, k):\n"
        "        with self._lock:\n"
        "            return self._state(k)\n"
        "    def observe(self, k):\n"
        "        with self._lock:\n"
        "            return self._state(k)\n"
    )
    assert not _lock_findings("dgraph_tpu/serve/x.py", src)
    # one unlocked call site un-blesses the helper
    src_bad = src + (
        "    def racy(self, k):\n"
        "        return self._state(k)\n"
    )
    assert _lock_findings("dgraph_tpu/serve/x.py", src_bad)


# ---------------------------------------------------------------------------
# regression pins: the REAL violations the first clean-tree run surfaced
# ---------------------------------------------------------------------------


def test_pre_fix_batcher_inflight_shape_fires():
    """PR 15 regression pin: MicroBatcher._loop reset ``_inflight``
    without the cv while stop()/_worker_crashed read it under the cv
    from other threads — the exact fixture mirrors the pre-fix code."""
    got = _lock_findings(H._LOCK_FIXTURE["path"], H._LOCK_FIXTURE["bad"])
    assert got and "_inflight" in got[0].message
    assert not _lock_findings(H._LOCK_FIXTURE["path"],
                              H._LOCK_FIXTURE["good"])


def test_pre_fix_registry_active_name_shape_fires():
    src = (
        "import threading\n"
        "class ModelRegistry:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._active = None\n"
        "    def activate(self, name):\n"
        "        with self._lock:\n"
        "            self._active = name\n"
        "    @property\n"
        "    def active_name(self):\n"
        "        return self._active\n"
    )
    got = _lock_findings("dgraph_tpu/serve/registry.py", src)
    assert len(got) == 1 and "_active" in got[0].message


def test_pre_fix_membership_seq_shape_fires():
    src = (
        "import threading\n"
        "class Membership:\n"
        "    def __init__(self):\n"
        "        self._hb_lock = threading.Lock()\n"
        "        self._seq = 0\n"
        "    def heartbeat(self):\n"
        "        with self._hb_lock:\n"
        "            self._seq += 1\n"
        "    def leave(self):\n"
        "        with open('t', 'w') as fh:\n"
        "            fh.write(str(self._seq))\n"
    )
    got = _lock_findings("dgraph_tpu/comm/membership.py", src)
    assert len(got) == 1 and "_seq" in got[0].message


def test_pre_fix_engine_degraded_read_shape_fires():
    src = (
        "import threading\n"
        "class ServeEngine:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.RLock()\n"
        "        self.degraded = False\n"
        "    def _fail(self):\n"
        "        with self._lock:\n"
        "            self.degraded = True\n"
        "    def infer(self):\n"
        "        if self.degraded:\n"
        "            raise RuntimeError('shed')\n"
    )
    got = _lock_findings("dgraph_tpu/serve/engine.py", src)
    assert len(got) == 1 and "degraded" in got[0].message


def test_fixed_tree_files_are_clean():
    """The shipped control-plane files pass every per-file host rule —
    the pin that each surfaced violation stays fixed."""
    rules = {n: L.RULES[n] for n in H.HOST_FILE_RULES}
    for rel in (
        "dgraph_tpu/serve/batcher.py",
        "dgraph_tpu/serve/engine.py",
        "dgraph_tpu/serve/registry.py",
        "dgraph_tpu/serve/tenancy.py",
        "dgraph_tpu/serve/deltas.py",
        "dgraph_tpu/comm/membership.py",
        "dgraph_tpu/train/shrink.py",
        "dgraph_tpu/tune/record.py",
        "dgraph_tpu/plan_shards.py",
    ):
        got = L.lint_file(os.path.join(REPO, rel), REPO, rules)
        assert not got, (rel, [f.to_dict() for f in got])


def test_engine_guarded_set_inferred_from_real_tree():
    """The inference is not vacuous: the real ServeEngine's lock contract
    (swap/append/degrade state) is recovered from source."""
    ms = H.scan_module("dgraph_tpu/serve/engine.py",
                       ast.parse(_real("dgraph_tpu/serve/engine.py")))
    cs = ms.classes["ServeEngine"]
    assert "_lock" in cs.lock_attrs
    audit = H.run_host_audit(REPO)
    eng = audit["classes"]["dgraph_tpu/serve/engine.py::ServeEngine"]
    assert {"degraded", "_batch", "_id_rank", "_consecutive_failures",
            "num_nodes"} <= set(eng["guarded_fields"])


# ---------------------------------------------------------------------------
# lock-order graph
# ---------------------------------------------------------------------------


def test_seeded_lock_cycle_goes_red():
    bad = {p: ast.parse(s) for p, s in H._ORDER_FIXTURE["bad"].items()}
    got = H.lock_order_findings(bad)
    assert got and "cycle" in got[0].message
    good = {p: ast.parse(s) for p, s in H._ORDER_FIXTURE["good"].items()}
    assert not H.lock_order_findings(good)


def test_non_monotone_three_lock_cycle_goes_red():
    """Review regression pin: cycles whose walk from the minimum lock is
    not monotone in the lock ordering (A -> C -> B -> A) were invisible
    to a path-enumeration shortcut; the SCC detector must find every
    cycle regardless of length or node order."""
    la, lb, lc = ("m", "x", "la"), ("m", "x", "lb"), ("m", "x", "lc")
    cycles = H._find_cycles({
        (la, lc): ("x", 1), (lc, lb): ("x", 2), (lb, la): ("x", 3),
    })
    assert len(cycles) == 1 and set(cycles[0]) == {la, lb, lc}
    bad3 = {p: ast.parse(s) for p, s in H._ORDER_FIXTURE["bad3"].items()}
    got = H.lock_order_findings(bad3)
    assert got and "cycle" in got[0].message
    # the transitive closure may shorten the REPORTED representative
    # (la -> lc -> la here), but the deadlockable order must be found
    # and rendered with real sites
    assert "_la" in got[0].message and "_lc" in got[0].message


def test_real_tree_lock_graph_edges_and_acyclicity():
    audit = H.run_host_audit(REPO)
    edges = audit["lock_edges"]
    # the two real cross-component orderings must stay visible (a graph
    # that lost them would pass vacuously)
    assert any("MicroBatcher._cv" in e and "TenantTable._lock" in e
               for e in edges), edges
    assert any("Membership._hb_lock" in e and "_LOCK" in e
               for e in edges), edges
    assert not [f for f in audit["findings"]
                if f["rule"] == "host-lock-order"]


# ---------------------------------------------------------------------------
# durable writes + pointer-flip-last
# ---------------------------------------------------------------------------


def test_pre_fix_shrink_savez_shape_fires():
    """PR 15 regression pin: train/shrink.py wrote graph_g<N>.npz with a
    bare np.savez (torn-write hazard under the adoption pointer); the
    fixture mirrors the pre-fix shape, and the shipped file now routes
    through plan_shards.atomic_savez."""
    got = H.durable_write_findings(
        H._DURABLE_FIXTURE["path"], ast.parse(H._DURABLE_FIXTURE["bad"]),
        H._DURABLE_FIXTURE["bad"].splitlines(),
    )
    assert len(got) >= 2
    assert not H.durable_write_findings(
        H._DURABLE_FIXTURE["path"], ast.parse(H._DURABLE_FIXTURE["good"]),
        H._DURABLE_FIXTURE["good"].splitlines(),
    )


def test_pre_fix_tune_record_tmp_write_shape_fires():
    """PR 15 regression pin: TuningRecord.save hand-rolled tmp+replace
    WITHOUT the fsync — the taint tracker follows record_path through
    the tmp-name concatenation."""
    src = (
        "import json, os\n"
        "def save(directory, sig, payload):\n"
        "    path = record_path(directory, sig)\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        json.dump(payload, f)\n"
        "    os.replace(tmp, path)\n"
    )
    got = H.durable_write_findings("dgraph_tpu/tune/record.py",
                                   ast.parse(src), src.splitlines())
    assert len(got) == 1 and "tmp" in got[0].message


def test_atomic_writers_are_exempt():
    src = (
        "import json, os\n"
        "def atomic_write_json(path, obj):\n"
        "    tmp = path + '.tmp'\n"
        "    with open(tmp, 'w') as f:\n"
        "        json.dump(obj, f)\n"
        "        f.flush()\n"
        "        os.fsync(f.fileno())\n"
        "    os.replace(tmp, path)\n"
        "def write(plan_dir, man):\n"
        "    atomic_write_json(manifest_path(plan_dir), man)\n"
    )
    assert not H.durable_write_findings("dgraph_tpu/plan_shards.py",
                                        ast.parse(src), src.splitlines())


def test_pointer_flip_before_payload_goes_red():
    got = H.pointer_flip_findings(
        H._FLIP_FIXTURE["path"], ast.parse(H._FLIP_FIXTURE["bad"]),
        H._FLIP_FIXTURE["bad"].splitlines(),
    )
    assert got and "not the last filesystem effect" in got[0].message


def test_flip_then_return_inside_retry_loop_is_green():
    """The replan shape: the commit flips the pointer inside a bounded
    retry loop and RETURNS — the loop's back edge (which rebuilds
    artifacts) never follows the flip, and the CFG walk must know it."""
    assert not H.pointer_flip_findings(
        H._FLIP_FIXTURE["path"], ast.parse(H._FLIP_FIXTURE["good"]),
        H._FLIP_FIXTURE["good"].splitlines(),
    )


def test_finally_after_post_flip_return_goes_red():
    """Review regression pin: a try/finally's finalbody runs AFTER a
    post-flip return — an os.replace hidden there is a payload write
    after the commit point and must be RED."""
    got = H.pointer_flip_findings(
        H._FLIP_FIXTURE["path"],
        ast.parse(H._FLIP_FIXTURE["bad_finally"]),
        H._FLIP_FIXTURE["bad_finally"].splitlines(),
    )
    assert got and "replace" in got[0].message


def test_real_commit_functions_are_flip_last():
    for rel in ("dgraph_tpu/train/shrink.py", "dgraph_tpu/serve/deltas.py"):
        src = _real(rel)
        got = H.pointer_flip_findings(rel, ast.parse(src),
                                      src.splitlines())
        assert not got, (rel, [f.to_dict() for f in got])


# ---------------------------------------------------------------------------
# chaos coverage
# ---------------------------------------------------------------------------


def test_chaos_registry_matches_real_fire_sites():
    got = H.chaos_coverage_findings(REPO)
    assert not got, [f.to_dict() for f in got]
    points = H.chaos_points(REPO)
    from dgraph_tpu import chaos

    # the AST parse of the registry agrees with the imported registry
    assert set(points) == set(chaos.KNOWN_POINTS)


def test_chaos_drift_mutants_go_red():
    got = H.chaos_coverage_findings(
        points=H._CHAOS_FIXTURE["points"],
        modules={p: ast.parse(s)
                 for p, s in H._CHAOS_FIXTURE["bad_modules"].items()},
    )
    msgs = " ".join(f.message for f in got)
    assert "serve.typo" in msgs  # unregistered fire site
    assert "serve.ghost" in msgs  # registered point with no fire site
    # a ghost point covered ONLY by chaos's own selftest stays red
    got = H.chaos_coverage_findings(
        points={"serve.ghost": 1},
        modules={"dgraph_tpu/chaos/__main__.py":
                 ast.parse("def t():\n    chaos.fire('serve.ghost')\n")},
    )
    assert any("serve.ghost" in f.message for f in got)


# ---------------------------------------------------------------------------
# registry / pragma / docs wiring
# ---------------------------------------------------------------------------


def test_host_rules_registered_with_scope():
    for name in H.HOST_RULES:
        assert name in L.RULES
        assert L.RULES[name].scope, name


def test_pragma_suppresses_host_findings():
    src = H._LOCK_FIXTURE["bad"].replace(
        "            self._inflight = []\n",
        "            self._inflight = []"
        "  # lint: allow(host-lock-discipline)\n",
    )
    got = [
        f for f in _lock_findings(H._LOCK_FIXTURE["path"], src)
        if not L._suppressed(src.splitlines(), f.line, f.rule)
    ]
    assert not got


def test_docs_rule_catalog_covers_host_rules():
    """The docs-vs-registry machine check, extended to the host tier:
    every host rule appears in docs/static-analysis.md's catalog table
    (the shared test in test_analysis.py checks the full registry; this
    one pins the host rows specifically)."""
    text = open(os.path.join(REPO, "docs", "static-analysis.md")).read()
    documented = set()
    for line in text.splitlines():
        cell = line.strip().split("|")[1].strip() if (
            line.strip().startswith("| `")
        ) else ""
        if cell.startswith("`") and cell.endswith("`"):
            documented.add(cell.strip("`"))
    missing = set(H.HOST_RULES) - documented
    assert not missing, f"host rules missing from the docs table: {missing}"


def test_run_host_audit_clean_tree():
    audit = H.run_host_audit(REPO)
    assert audit["ok"], audit["failures"]
    assert audit["files_checked"] >= 15
    assert audit["chaos_points"] >= 14


def test_selftest_failures_empty():
    assert H.host_selftest_failures(REPO) == []


def test_host_cli_smoke():
    """`python -m dgraph_tpu.analysis.host --selftest` — the tier-1
    registration path scripts/check.py runs (stdlib ast: no compiles)."""
    proc = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.analysis.host",
         "--selftest", "true"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "host_selftest" and rec["failures"] == []
    assert rec["run_health"]["error"] is None


def test_list_rules_cli_includes_host_rules():
    proc = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.analysis", "--list_rules",
         "true"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    listed = {r["name"]: r["scope"] for r in rec["rules"]}
    for name in H.HOST_RULES:
        assert name in listed and listed[name]
