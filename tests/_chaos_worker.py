"""Supervised training worker for the chaos end-to-end recovery test
(test_chaos.py). Run as:  python tests/_chaos_worker.py <ckpt_dir> <steps>

A deliberately tiny elastic training run — host-side numpy state, one
orbax checkpoint per step, a tight wedge watchdog — whose ONLY job is to
prove the restart contract end to end: the test's supervisor arms
``DGRAPH_CHAOS="step=wedge@K:attempt=0"``, attempt 0 wedges at global step
K and is hard-exited by the watchdog with code 17, the supervisor
restarts, and this process resumes from ``latest_step()``.  The step
update is exact in float64 and checkpoints round-trip bit-exactly, so the
final state must be BIT-IDENTICAL to an uninterrupted run — the
acceptance pin for the whole recovery path.

No jitted step on purpose: the recovery machinery under test is all host
code, and tier-1 cannot afford a fresh XLA compile per subprocess.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def step_fn(state):
    # exact float64 arithmetic: sequential application is bit-deterministic
    # regardless of where a restart split the run
    return {"w": state["w"] * 1.5 + 1.0}


def main() -> None:
    ckpt_dir, num_steps = sys.argv[1], int(sys.argv[2])
    from dgraph_tpu.train.checkpoint import latest_step, restore_checkpoint
    from dgraph_tpu.train.elastic import PreemptionGuard, run_elastic

    state = {"w": np.zeros(4, np.float64)}
    start = 0
    if latest_step(ckpt_dir) is not None:
        got = restore_checkpoint(ckpt_dir, {"state": state, "step": 0})
        state, start = got["state"], int(got["step"])
        print(f"WORKER_RESUME step={start}", flush=True)

    state, last, preempted = run_elastic(
        step_fn,
        state,
        start_step=start,
        num_steps=num_steps,
        ckpt_dir=ckpt_dir,
        checkpoint_every=1,
        step_deadline_s=0.5,  # tight: the injected wedge must die fast
        first_deadline_s=30.0,  # subprocess cold start is not a wedge
        guard=PreemptionGuard(),
    )
    print(
        f"WORKER_DONE step={last} preempted={preempted} "
        f"w0={state['w'][0]!r}",
        flush=True,
    )


if __name__ == "__main__":
    main()
