"""Test environment: force JAX onto 8 virtual CPU devices.

This replaces the reference's torchrun/mpirun multi-process test launches
(``tests/README.md:1-17``): with ``xla_force_host_platform_device_count`` we
get *real* multi-device SPMD semantics (true all_to_all/psum over 8 device
shards) in a single process with no cluster — SURVEY.md §4.

Must run before any test module imports jax. PALLAS_AXON_POOL_IPS is cleared
because the baked axon sitecustomize pins JAX_PLATFORMS to the (single-chip)
TPU tunnel when it is set.
"""

import os

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

# Persistent XLA compilation cache: the suite is compile-dominated (every
# shard_map train step traces to a fresh executable), and jax's
# content-addressed cache (keyed on HLO + compile options + backend) makes
# repeat runs in one container reuse yesterday's binaries. Set via env var
# so subprocess tests (CLI smokes, multi-process launches) inherit it.
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/dgraph_tpu_xla_cache")

# The baked axon sitecustomize imports jax at interpreter startup (before this
# conftest), freezing jax_platforms='axon' from the ambient env. Backend
# initialization is lazy, so overriding the config here (before any jax API
# call touches devices) still redirects to the virtual CPU mesh.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:  # compilation cache knobs (names are stable across 0.4-0.6)
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:  # unknown option on some jax: run uncached, never break
    pass

# jax-version shims (jax.shard_map / jax.set_mesh on 0.4.x) must be in
# place before test modules that use the modern spellings are imported.
import dgraph_tpu.compat  # noqa: E402,F401

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    import jax
    from dgraph_tpu.comm.mesh import make_graph_mesh

    assert len(jax.devices()) == 8, "conftest env did not take effect"
    return make_graph_mesh(ranks_per_graph=8)


@pytest.fixture
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="session")
def tensor_mesh8():
    """8-device 1-D mesh named 'tensor' (tensor-parallel tests)."""
    import jax
    from jax.sharding import Mesh

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("need 8 devices")
    return Mesh(np.array(devs[:8]), ("tensor",))
