"""Smoke tests for the experiment CLI stacks (tiny configs, CPU mesh) —
the reference runs its experiments as scripts; we pin that they stay
runnable end-to-end."""

import json
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def test_ogb_gcn_cli(tmp_path):
    from experiments.ogb_gcn import Config, DataConfig, main

    cfg = Config(
        epochs=3,
        hidden=16,
        log_path=str(tmp_path / "log.jsonl"),
        data=DataConfig(num_nodes=200, num_classes=3, feat_dim=8),
    )
    main(cfg)
    lines = [json.loads(l) for l in open(cfg.log_path) if l.startswith("{")]
    assert any("avg_epoch_ms_excl_first" in l for l in lines)


def test_rgat_cli(tmp_path):
    from experiments.rgat_mag import Config, main

    cfg = Config(
        num_papers=120,
        num_authors=80,
        num_institutions=12,
        feat_dim=8,
        hidden=8,
        epochs=3,
        log_path=str(tmp_path / "log.jsonl"),
    )
    main(cfg)
    lines = [json.loads(l) for l in open(cfg.log_path) if l.startswith("{")]
    assert lines and "loss" in lines[-1]


def test_graphcast_cli(tmp_path):
    from experiments.graphcast_train import Config, main

    cfg = Config(
        mesh_level=1,
        num_lat=10,
        num_lon=18,
        channels=3,
        latent=8,
        processor_layers=1,
        steps=3,
        warmup_steps=1,
        decay_steps=10,
        ckpt_dir=str(tmp_path / "ckpt"),
        save_freq=2,
        log_path=str(tmp_path / "log.jsonl"),
    )
    main(cfg)
    lines = [json.loads(l) for l in open(cfg.log_path) if l.startswith("{")]
    assert any("loss" in l for l in lines)
    # checkpoint written and resumable
    from dgraph_tpu.train.checkpoint import latest_step

    assert latest_step(cfg.ckpt_dir) == 2
    cfg2 = Config(**{**cfg.__dict__, "steps": 4})
    main(cfg2)
    lines2 = [json.loads(l) for l in open(cfg.log_path) if l.startswith("{")]
    assert any("resumed_at_step" in l for l in lines2)


def test_cli_overrides():
    from dgraph_tpu.utils.cli import parse_config
    from experiments.ogb_gcn import Config

    cfg = parse_config(Config, ["--model", "sage", "--data.num_nodes", "42", "epochs=7"])
    assert cfg.model == "sage" and cfg.data.num_nodes == 42 and cfg.epochs == 7


def test_papers100m_cli_smoke(tmp_path):
    """Scaled-down papers100M stack: native partition, plan cache, remat,
    bf16 — end to end."""
    from experiments.papers100m_gcn import Config, main

    cfg = Config(
        synthetic_scale=2e-6,  # ~10k nodes floor
        hidden=16,
        num_layers=2,
        epochs=2,
        plan_cache=str(tmp_path / "plans"),
        log_path=str(tmp_path / "log.jsonl"),
    )
    main(cfg)
    import json

    lines = [json.loads(l) for l in open(cfg.log_path) if l.startswith("{")]
    assert any("loss" in l for l in lines)
    # plan cache populated and reused on second run
    import os

    cached = os.listdir(tmp_path / "plans")
    assert len(cached) == 1
    main(cfg)
    assert os.listdir(tmp_path / "plans") == cached


def test_partition_quality_cli(tmp_path):
    from experiments.partition_quality import Config, main

    cfg = Config(
        num_nodes=2000,
        world_size=4,
        log_path=str(tmp_path / "pq.jsonl"),
    )
    main(cfg)
    lines = [json.loads(l) for l in open(cfg.log_path) if l.startswith("{")]
    # 2 graphs x 3 methods
    assert len(lines) == 6
    by = {(l["graph"], l["method"]): l for l in lines}
    for rec in lines:
        assert 0.0 <= rec["cross_edge_fraction"] <= 1.0
        assert rec["balance"] < 1.2
    # the multilevel+FM partitioner must beat random on the clustered graph
    assert (by[("sbm", "multilevel")]["cross_edge_fraction"]
            < by[("sbm", "random")]["cross_edge_fraction"])


def test_volume_polish_reduces_halo_slots(tmp_path):
    """The volume polish must not increase deduped halo slots on a
    clustered graph (its exact objective), and DGRAPH_HOST_FM=0 must
    reproduce the greedy-only baseline (polish counts as refinement)."""
    import os
    import subprocess
    import sys

    import numpy as np

    from dgraph_tpu import native, partition as pt
    from dgraph_tpu.data.synthetic import sbm_classification_graph
    from experiments.partition_quality import halo_stats

    if not native.available():
        import pytest

        pytest.skip("native library not built")
    data = sbm_classification_graph(
        num_nodes=6000, num_classes=16, feat_dim=1, avg_degree=12.0, seed=3
    )
    edges = data["edge_index"]

    def run(env):
        e = dict(os.environ, **env)
        # subprocess: the env gates are read inside the native call, and
        # the test must not leak env mutations into this process
        out = subprocess.run(
            [sys.executable, "-c", (
                "import numpy as np, sys\n"
                "from dgraph_tpu import partition as pt\n"
                "edges = np.load(sys.argv[1])\n"
                "p = pt.multilevel_partition(edges, 6000, 4, 0)\n"
                "np.save(sys.argv[2], p)\n"
            ), str(tmp_path / "edges.npy"), str(tmp_path / "part.npy")],
            env=e, capture_output=True, text=True, cwd=str(REPO),
        )
        assert out.returncode == 0, out.stderr[-2000:]
        return np.load(tmp_path / "part.npy")

    np.save(tmp_path / "edges.npy", edges)
    part_full = run({})
    part_nopolish = run({"DGRAPH_HOST_VOLUME_POLISH": "0"})
    s_full = halo_stats(edges, part_full, 4)
    s_nopol = halo_stats(edges, part_nopolish, 4)
    mean_full = s_full["halo_slots_mean"]
    mean_nopol = s_nopol["halo_slots_mean"]
    assert mean_full <= mean_nopol, (s_full, s_nopol)

    # FM=0 baseline: polish must NOT run (identical to FM=0 + polish=0)
    part_fm0 = run({"DGRAPH_HOST_FM": "0"})
    part_fm0_p0 = run({"DGRAPH_HOST_FM": "0",
                       "DGRAPH_HOST_VOLUME_POLISH": "0"})
    assert np.array_equal(part_fm0, part_fm0_p0)
