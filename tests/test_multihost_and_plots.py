"""Multi-host mesh helpers (single-process degenerate cases) + plot script."""

import json
import os

import numpy as np


def test_make_pod_mesh_single_slice(mesh8):
    from dgraph_tpu.comm.multihost import make_pod_mesh, process_local_shards

    mesh = make_pod_mesh(ranks_per_graph=4, num_replicas=2)
    assert dict(mesh.shape) == {"replica": 2, "graph": 4}
    shards = process_local_shards(8)
    assert shards == list(range(8))  # single controller owns every shard


def test_generate_plots(tmp_path):
    from experiments.generate_plots import Config, main

    log_dir = tmp_path / "logs"
    os.makedirs(log_dir)
    np.save(log_dir / "comm_bench_gather_times.npy", np.array([1.0, 2.0, 3.0]))
    with open(log_dir / "train.jsonl", "w") as f:
        for i in range(5):
            f.write(json.dumps({"epoch": i, "loss": 1.0 / (i + 1)}) + "\n")
    main(Config(log_dir=str(log_dir), out_dir=str(tmp_path / "plots")))
    assert (tmp_path / "plots" / "comm_latency.png").exists()
    assert (tmp_path / "plots" / "train_loss.png").exists()
