"""Model tests: single-device (SingleComm) vs 8-way distributed (TpuComm)
logit equivalence — the strongest correctness statement: the distributed
model computes bit-for-bit (up to fp tolerance) the same function as the
dense one — plus end-to-end training convergence on a synthetic SBM task.

This mirrors the reference's dummy-communicator model tests
(``experiments/GraphCast/tests/test_single_model.py``) and the pattern that
the same layer code runs under real and fake backends (SURVEY.md §3.5).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from dgraph_tpu.comm import Communicator
from dgraph_tpu.data import DistributedGraph, synthetic
from dgraph_tpu.models import GCN, GAT, GraphSAGE
from dgraph_tpu.plan import unshard_vertex_data
from dgraph_tpu.testing import spmd_apply


@pytest.fixture(scope="module")
def sbm():
    return synthetic.sbm_classification_graph(num_nodes=400, seed=1)


def build_graphs(sbm, world):
    return DistributedGraph.from_global(
        sbm["edge_index"],
        sbm["features"],
        sbm["labels"],
        sbm["masks"],
        world_size=world,
        partition_method="random",  # stress cross-rank edges
        add_symmetric_norm=True,
    )


def to_original_order(x_sharded, g):
    """[W, n_pad, ...] -> [V, ...] in the ORIGINAL (pre-renumbering) ids."""
    xr = unshard_vertex_data(np.asarray(x_sharded), g.ren.counts)
    out = np.empty_like(xr)
    out[g.ren.inv] = xr
    return out


MODELS = {
    "gcn": lambda comm: GCN(hidden_features=32, out_features=4, comm=comm),
    "sage": lambda comm: GraphSAGE(hidden_features=32, out_features=4, comm=comm),
    "gat": lambda comm: GAT(hidden_features=16, out_features=4, comm=comm, num_heads=2),
}


@pytest.mark.parametrize("name", ["gcn", "sage", "gat"])
def test_distributed_matches_single_device(mesh8, sbm, name):
    g1 = build_graphs(sbm, 1)
    g8 = build_graphs(sbm, 8)

    comm1 = Communicator.init_process_group("single")
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    model1, model8 = MODELS[name](comm1), MODELS[name](comm8)

    def args_for(g, shard=None):
        sel = (lambda a: jnp.asarray(a[shard])) if shard is not None else jnp.asarray
        plan = jax.tree.map(sel, g.plan)
        extra = ()
        if name == "gcn":
            extra = (sel(g.edge_weight),)
        return (sel(g.features), plan) + extra

    params = model1.init(jax.random.key(0), *args_for(g1, shard=0))

    out1 = model1.apply(params, *args_for(g1, shard=0))
    ref = to_original_order(np.asarray(out1)[None], g1)

    def fn8(x, *rest):
        plan_shard = rest[-1]
        extra = rest[:-1]
        return model8.apply(params, x, plan_shard, *extra)

    arrays = [jnp.asarray(g8.features)]
    static = ()
    if name == "gcn":
        arrays.append(jnp.asarray(g8.edge_weight))

    def fn(x, *rest):
        # rest = (*extra_arrays, plan_shard)
        extra, plan_shard = rest[:-1], rest[-1]
        return model8.apply(params, x, plan_shard, *extra)

    # spmd_apply passes (arrays..., plan, static...) — adapt ordering
    def body(x, *rest):
        plan_shard = rest[-1]
        extras = rest[:-1]
        return model8.apply(params, x, plan_shard, *extras)

    from dgraph_tpu.testing import spmd_apply as _apply

    def reordered(*a):
        # a = (x, [ew], plan)
        return body(*a)

    out8 = _apply(mesh8, reordered, g8.plan, *arrays)
    got = to_original_order(out8, g8)
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_gcn_trains_on_sbm(mesh8, sbm):
    from dgraph_tpu.train.loop import fit

    g8 = build_graphs(sbm, 8)
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    model = GCN(hidden_features=32, out_features=4, comm=comm8)
    params, history = fit(
        model, g8, mesh8, optimizer=optax.adam(5e-3), num_epochs=60
    )
    assert history[-1]["loss"] < history[0]["loss"] * 0.5
    assert history[-1]["acc"] > 0.75


def test_distributed_gradients_match_single_device(mesh8, sbm):
    """Full train-step gradient equivalence: psum'd distributed grads ==
    dense single-device grads (parity with test_NCCLCommPlan.py's backward
    checks, but end-to-end through the model)."""
    g1 = build_graphs(sbm, 1)
    g8 = build_graphs(sbm, 8)
    comm1 = Communicator.init_process_group("single")
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    m1 = GCN(hidden_features=8, out_features=4, comm=comm1)
    m8 = GCN(hidden_features=8, out_features=4, comm=comm8)

    plan1 = jax.tree.map(lambda l: jnp.asarray(l[0]), g1.plan)
    params = m1.init(
        jax.random.key(0), jnp.asarray(g1.features[0]), plan1, jnp.asarray(g1.edge_weight[0])
    )

    def loss1(p):
        logits = m1.apply(p, jnp.asarray(g1.features[0]), plan1, jnp.asarray(g1.edge_weight[0]))
        logp = jax.nn.log_softmax(logits)
        y = jnp.asarray(g1.labels[0])
        mask = jnp.asarray(g1.masks["train"][0])
        ll = jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
        return -(ll * mask).sum() / mask.sum()

    dense_grads = jax.grad(loss1)(params)

    from dgraph_tpu.train.loop import make_train_step

    # one step with zero LR: metrics + grads path exercised; compare loss
    opt = optax.sgd(0.0)
    batch = {
        "x": jnp.asarray(g8.features),
        "y": jnp.asarray(g8.labels),
        "mask": jnp.asarray(g8.masks["train"]),
        "edge_weight": jnp.asarray(g8.edge_weight),
    }
    plan8 = jax.tree.map(jnp.asarray, g8.plan)
    step = make_train_step(m8, opt, mesh8, plan8, donate=False)
    with jax.set_mesh(mesh8):
        _, _, metrics = step(params, opt.init(params), batch, plan8)
    np.testing.assert_allclose(float(metrics["loss"]), float(loss1(params)), rtol=1e-4)

    # and the distributed grads themselves
    from jax.sharding import PartitionSpec as P
    from dgraph_tpu.comm.mesh import plan_in_specs, squeeze_plan, GRAPH_AXIS
    from dgraph_tpu.train.loop import masked_cross_entropy

    def shard_grads(params, batch, plan):
        plan_s = squeeze_plan(plan)
        b = jax.tree.map(lambda l: l[0], batch)

        def lf(p):
            logits = m8.apply(p, b["x"], plan_s, b["edge_weight"])
            return masked_cross_entropy(logits, b["y"], b["mask"], GRAPH_AXIS)

        # grad w.r.t. replicated params auto-psums across shards on jax
        # 0.6+ (vma); compat inserts the explicit psum on 0.4.x
        from dgraph_tpu import compat as _compat

        return _compat.sync_inbody_grads(jax.grad(lf)(params), (GRAPH_AXIS,))

    batch_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), batch)
    with jax.set_mesh(mesh8):
        dist_grads = jax.jit(
            jax.shard_map(
                shard_grads,
                mesh=mesh8,
                in_specs=(P(), batch_specs, plan_in_specs(plan8)),
                out_specs=P(),
            )
        )(params, batch, plan8)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5),
        dist_grads,
        dense_grads,
    )


def test_multilabel_float_targets_train(mesh8, sbm):
    """ogbn-proteins-shaped path: float [V, C] multi-label targets survive
    DistributedGraph.from_global (no int cast) and train under the BCE loss
    (the reference handles proteins via a per-dataset num_classes table,
    ``ogbn_datasets.py:25-37``)."""
    from dgraph_tpu.train.loop import fit, masked_bce_multilabel

    rng = np.random.default_rng(3)
    C = 6
    multilabels = (rng.random((400, C)) < 0.3).astype(np.float32)
    g8 = DistributedGraph.from_global(
        sbm["edge_index"],
        sbm["features"],
        multilabels,
        sbm["masks"],
        world_size=8,
        partition_method="random",
        add_symmetric_norm=True,
    )
    assert g8.labels.dtype == np.float32 and g8.labels.shape[-1] == C
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    model = GCN(hidden_features=16, out_features=C, comm=comm8)
    params, history = fit(
        model, g8, mesh8, optimizer=optax.adam(5e-3), num_epochs=15,
        loss_fn=masked_bce_multilabel,
    )
    assert history[-1]["loss"] < history[0]["loss"]
    assert np.isfinite(history[-1]["loss"])


def test_chunked_pipeline_one_exchange_per_layer(mesh8, sbm):
    """Structural pin for the feature-chunked edge pipeline: hidden width
    256 = 2 chunks per layer, but the halo all_to_all count must stay ONE
    per conv layer (comm.halo_extend hoists it out of the chunk loop) —
    chunking must never multiply collectives."""
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan

    g = build_graphs(sbm, 8)
    comm = Communicator.init_process_group("tpu", world_size=8)
    model = GCN(hidden_features=256, out_features=4, comm=comm, num_layers=2)
    plan = jax.tree.map(jnp.asarray, g.plan)
    x = jnp.asarray(g.features)
    ew = jnp.asarray(g.edge_weight)
    params = jax.eval_shape(
        lambda: jax.shard_map(
            lambda p_, x_, e_: model.init(jax.random.key(0), x_[0],
                                          squeeze_plan(p_), e_[0]),
            mesh=mesh8,
            in_specs=(plan_in_specs(plan), P(GRAPH_AXIS), P(GRAPH_AXIS)),
            out_specs=P(),
        )(plan, x, ew)
    )
    params = jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), params)

    fwd = jax.shard_map(
        lambda pp, p_, x_, e_: model.apply(pp, x_[0], squeeze_plan(p_),
                                           e_[0])[None],
        mesh=mesh8,
        in_specs=(P(), plan_in_specs(plan), P(GRAPH_AXIS), P(GRAPH_AXIS)),
        out_specs=P(GRAPH_AXIS),
    )
    jaxpr = jax.make_jaxpr(fwd)(params, plan, x, ew)

    def count(j, name):
        n = 0
        for e in j.eqns:
            n += name in e.primitive.name
            for p in e.params.values():
                for item in (p if isinstance(p, (list, tuple)) else [p]):
                    if hasattr(item, "jaxpr"):
                        n += count(getattr(item.jaxpr, "jaxpr", item.jaxpr),
                                   name)
                    elif hasattr(item, "eqns"):
                        n += count(item, name)
        return n

    n_a2a = count(jaxpr.jaxpr, "all_to_all")
    n_pp = count(jaxpr.jaxpr, "ppermute")
    # 2 conv layers x 1 halo side each = EXACTLY 2 exchanges in the
    # forward (the stream side is the halo side; the bias side is local).
    # The random partition makes every peer pair live, so the halo cost
    # model deterministically picks all_to_all (ppermute must be absent —
    # a ppermute-lowered exchange would make the a2a count vacuous).
    assert n_pp == 0, f"unexpected ppermute lowering ({n_pp})"
    assert n_a2a == 2, f"chunking changed the collective count: {n_a2a}"


def test_gat_head_chunked_matches_single_device(mesh8, sbm):
    """GAT at H*D > gather_col_block (4 heads x 64 = 256) so the
    head-group-chunked attention path ENGAGES distributed (the default
    test configs are below the threshold and only cover the full-width
    path). Distributed chunked output must equal the single-device run."""
    from dgraph_tpu.testing import spmd_apply as _apply

    g1 = build_graphs(sbm, 1)
    g8 = build_graphs(sbm, 8)
    comm1 = Communicator.init_process_group("single")
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    mk = lambda comm: GAT(hidden_features=64, out_features=4, comm=comm,
                          num_heads=4)
    model1, model8 = mk(comm1), mk(comm8)

    plan1 = jax.tree.map(lambda a: jnp.asarray(a[0]), g1.plan)
    params = model1.init(jax.random.key(0), jnp.asarray(g1.features[0]), plan1)
    ref = to_original_order(
        np.asarray(model1.apply(params, jnp.asarray(g1.features[0]),
                                plan1))[None], g1)

    out8 = _apply(
        mesh8,
        lambda x, plan_shard: model8.apply(params, x, plan_shard),
        g8.plan, jnp.asarray(g8.features),
    )
    np.testing.assert_allclose(to_original_order(out8, g8), ref,
                               rtol=2e-4, atol=2e-4)


def test_graph_transformer_chunked_local_matches_single(mesh8, sbm):
    """GraphTransformer at latent 256 > gather_col_block so the chunked
    local-branch path engages; distributed must equal single-device."""
    from dgraph_tpu.models import GraphTransformer
    from dgraph_tpu.testing import spmd_apply as _apply

    g1 = build_graphs(sbm, 1)
    g8 = build_graphs(sbm, 8)
    comm1 = Communicator.init_process_group("single")
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    mk = lambda comm: GraphTransformer(latent=256, out_features=4, comm=comm,
                                       num_layers=1, num_heads=4)
    model1, model8 = mk(comm1), mk(comm8)

    plan1 = jax.tree.map(lambda a: jnp.asarray(a[0]), g1.plan)
    vm1 = jnp.asarray(g1.vertex_mask[0])
    params = model1.init(jax.random.key(0), jnp.asarray(g1.features[0]),
                         plan1, vm1)
    ref = to_original_order(
        np.asarray(model1.apply(params, jnp.asarray(g1.features[0]), plan1,
                                vm1))[None], g1)

    out8 = _apply(
        mesh8,
        lambda x, vm, plan_shard: model8.apply(params, x, plan_shard, vm),
        g8.plan, jnp.asarray(g8.features), jnp.asarray(g8.vertex_mask),
    )
    np.testing.assert_allclose(to_original_order(out8, g8), ref,
                               rtol=2e-3, atol=2e-3)
