"""GraphCast stack tests: multimesh structural constants (the reference's
graph-constant regression pattern, ``tests/test_single_graph_data.py:20-34``),
edge-builder invariants, distributed-vs-single model equivalence, training.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from dgraph_tpu.comm import Communicator
from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
from dgraph_tpu.models.graphcast import (
    GraphCast,
    build_graphcast_graphs,
    build_multimesh,
)
from dgraph_tpu.models.graphcast import mesh as mesh_lib
from dgraph_tpu.plan import unshard_vertex_data

LEVEL, NLAT, NLON, CH = 2, 19, 36, 5


class TestMultimesh:
    @pytest.mark.parametrize("level", [0, 1, 2, 3, 4])
    def test_structural_constants(self, level):
        """V = 10*4^L + 2; multimesh E = 2 * 30 * (4^(L+1)-1)/3 — the same
        closed forms that give the paper's level-6 anchors (40962 vertices,
        655320 edges) asserted by the reference."""
        mm = build_multimesh(level)
        assert mm.vertices.shape[0] == 10 * 4**level + 2
        assert mm.edges.shape[1] == 2 * 30 * (4 ** (level + 1) - 1) // 3
        assert mm.faces.shape[0] == 20 * 4**level
        # unit sphere
        np.testing.assert_allclose(np.linalg.norm(mm.vertices, axis=1), 1.0, rtol=1e-12)

    def test_edges_symmetric(self):
        mm = build_multimesh(2)
        fwd = set(map(tuple, mm.edges.T.tolist()))
        assert all((b, a) in fwd for a, b in fwd)

    def test_level6_paper_anchors(self):
        """Reference-scale correctness anchors (level-6 mesh, 721x1440 ERA5
        grid) — the exact constants the reference pins from the paper
        (``experiments/GraphCast/tests/test_single_graph_data.py:20-34``):
        40 962 mesh nodes, 1 618 824 grid2mesh edges, 3 114 720 mesh2grid
        edges. The reference asserts 655 320 mesh edges because its
        face-derived edge list double-counts every directed edge (each
        undirected edge belongs to two faces and its builder
        bidirectionalizes without dedup, ``icosahedral_mesh.py:298-300``);
        our multimesh stores each directed edge once — 327 660, the paper's
        M6 count — so the parity relation is 2x."""
        mm = build_multimesh(6)
        assert mm.vertices.shape[0] == 40_962
        assert mm.edges.shape[1] == 327_660
        assert 2 * mm.edges.shape[1] == 655_320  # reference convention
        _, xyz = mesh_lib.latlon_grid(721, 1440)
        g2m = mesh_lib.grid2mesh_edges(xyz, mm)
        assert g2m.shape[1] == 1_618_824
        m2g = mesh_lib.mesh2grid_edges(xyz, mm)
        assert m2g.shape[1] == 3_114_720


class TestGridMeshEdges:
    def test_mesh2grid_three_per_point(self):
        mm = build_multimesh(LEVEL)
        _, xyz = mesh_lib.latlon_grid(NLAT, NLON)
        m2g = mesh_lib.mesh2grid_edges(xyz, mm)
        assert m2g.shape[1] == 3 * len(xyz)
        counts = np.bincount(m2g[1], minlength=len(xyz))
        assert np.all(counts == 3)

    def test_grid2mesh_covers_grid(self):
        mm = build_multimesh(LEVEL)
        _, xyz = mesh_lib.latlon_grid(NLAT, NLON)
        g2m = mesh_lib.grid2mesh_edges(xyz, mm)
        assert len(np.unique(g2m[0])) == len(xyz)  # every grid point connected


@pytest.fixture(scope="module")
def graphs8():
    return build_graphcast_graphs(LEVEL, NLAT, NLON, world_size=8)


@pytest.fixture(scope="module")
def graphs1():
    return build_graphcast_graphs(LEVEL, NLAT, NLON, world_size=1)


def statics_of(g, sel):
    return {
        "grid_node_static": sel(g.grid_node_static),
        "mesh_node_static": sel(g.mesh_node_static),
        "mesh_edge_static": sel(g.mesh_edge_static),
        "g2m_edge_static": sel(g.g2m_edge_static),
        "m2g_edge_static": sel(g.m2g_edge_static),
    }


def plans_of(g, sel):
    return {
        "mesh": jax.tree.map(sel, g.mesh_plan),
        "g2m": jax.tree.map(sel, g.g2m_plan),
        "m2g": jax.tree.map(sel, g.m2g_plan),
    }


@pytest.mark.parametrize("latent", [16, 192])
def test_graphcast_distributed_matches_single(mesh8, graphs1, graphs8, latent):
    # latent=192 > gather_col_block: the MeshEdgeBlock chunked first stage
    # runs MULTI-chunk, with a 64-wide remainder slice (128 + 64)
    from dgraph_tpu.data.weather import SyntheticWeatherDataset

    comm1 = Communicator.init_process_group("single")
    comm8 = Communicator.init_process_group("tpu", world_size=8)
    kw = dict(latent=latent, processor_layers=2, out_channels=CH)
    m1 = GraphCast(comm=comm1, **kw)
    m8 = GraphCast(comm=comm8, **kw)

    ds1 = SyntheticWeatherDataset(graphs1, NLAT, NLON, CH, num_samples=1)
    ds8 = SyntheticWeatherDataset(graphs8, NLAT, NLON, CH, num_samples=1)
    x1, _ = ds1.get_sharded(0)
    x8, _ = ds8.get_sharded(0)

    sel0 = lambda a: jnp.asarray(a[0])
    params = m1.init(jax.random.key(0), sel0(x1), statics_of(graphs1, sel0), plans_of(graphs1, sel0))
    out1 = m1.apply(params, sel0(x1), statics_of(graphs1, sel0), plans_of(graphs1, sel0))
    ref = unshard_vertex_data(np.asarray(out1)[None], graphs1.grid_ren.counts)
    ref_orig = np.empty_like(ref)
    ref_orig[graphs1.grid_ren.inv] = ref

    ident = lambda a: jnp.asarray(a)
    statics8, plans8 = statics_of(graphs8, ident), plans_of(graphs8, ident)

    def body(x, statics, plans):
        x = x[0]
        statics = {k: v[0] for k, v in statics.items()}
        plans = {k: squeeze_plan(p) for k, p in plans.items()}
        return m8.apply(params, x, statics, plans)[None]

    specs = (
        P(GRAPH_AXIS),
        {k: P(GRAPH_AXIS) for k in statics8},
        {k: plan_in_specs(p) for k, p in plans8.items()},
    )
    fn = jax.shard_map(body, mesh=mesh8, in_specs=specs, out_specs=P(GRAPH_AXIS))
    with jax.set_mesh(mesh8):
        out8 = jax.jit(fn)(jnp.asarray(x8), statics8, plans8)
    got = unshard_vertex_data(np.asarray(out8), graphs8.grid_ren.counts)
    got_orig = np.empty_like(got)
    got_orig[graphs8.grid_ren.inv] = got
    np.testing.assert_allclose(got_orig, ref_orig, rtol=2e-3, atol=2e-3)


def test_graphcast_trains(mesh8, graphs8):
    from dgraph_tpu.data.weather import SyntheticWeatherDataset

    comm8 = Communicator.init_process_group("tpu", world_size=8)
    model = GraphCast(comm=comm8, latent=16, processor_layers=1, out_channels=CH)
    ds = SyntheticWeatherDataset(graphs8, NLAT, NLON, CH, num_samples=2)
    x, y = ds.get_sharded(0)
    x, y = jnp.asarray(x), jnp.asarray(y)
    gmask = jnp.asarray(graphs8.grid_mask)

    ident = lambda a: jnp.asarray(a)
    statics, plans = statics_of(graphs8, ident), plans_of(graphs8, ident)
    specs_sp = {k: P(GRAPH_AXIS) for k in statics}
    specs_pl = {k: plan_in_specs(p) for k, p in plans.items()}

    def init_body(x, statics, plans):
        return model.init(
            jax.random.key(0),
            x[0],
            {k: v[0] for k, v in statics.items()},
            {k: squeeze_plan(p) for k, p in plans.items()},
        )

    with jax.set_mesh(mesh8):
        params = jax.jit(
            jax.shard_map(
                init_body,
                mesh=mesh8,
                in_specs=(P(GRAPH_AXIS), specs_sp, specs_pl),
                out_specs=P(),
            )
        )(x, statics, plans)

    opt = optax.adam(1e-3)
    opt_state = opt.init(params)

    def train_body(params, x, y, mask, statics, plans):
        x_, y_, m_ = x[0], y[0], mask[0]
        st = {k: v[0] for k, v in statics.items()}
        pl = {k: squeeze_plan(p) for k, p in plans.items()}

        def lf(p):
            pred = model.apply(p, x_, st, pl)
            se = ((pred - y_) ** 2).sum(-1) * m_
            cnt = jax.lax.psum(m_.sum(), GRAPH_AXIS)
            return se.sum() / jnp.maximum(cnt, 1.0)

        loss, grads = jax.value_and_grad(lf)(params)
        from dgraph_tpu import compat as _compat

        grads = _compat.sync_inbody_grads(grads, (GRAPH_AXIS,))
        return jax.lax.psum(loss, GRAPH_AXIS), grads

    body = jax.shard_map(
        train_body,
        mesh=mesh8,
        in_specs=(P(), P(GRAPH_AXIS), P(GRAPH_AXIS), P(GRAPH_AXIS), specs_sp, specs_pl),
        out_specs=(P(), P()),
    )

    @jax.jit
    def step(params, opt_state):
        loss, grads = body(params, x, y, gmask, statics, plans)
        updates, opt_state = opt.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    losses = []
    with jax.set_mesh(mesh8):
        for _ in range(15):
            params, opt_state, loss = step(params, opt_state)
            losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, losses


def test_rollout_chains_single_steps(graphs1):
    """rollout's scan must equal literally chaining model.apply — and its
    first step must be exactly one forward pass."""
    from dgraph_tpu.data.weather import SyntheticWeatherDataset
    from dgraph_tpu.models.graphcast import rollout

    comm = Communicator.init_process_group("single")
    model = GraphCast(comm=comm, latent=16, processor_layers=1, out_channels=CH)
    ds = SyntheticWeatherDataset(graphs1, NLAT, NLON, CH, num_samples=1)
    x0, truth = ds.trajectory_sharded(0, 3)
    assert truth.shape[0] == 3

    sel0 = lambda a: jnp.asarray(a[0])
    statics = statics_of(graphs1, sel0)
    plans = plans_of(graphs1, sel0)
    x0 = sel0(x0)
    params = model.init(jax.random.key(0), x0, statics, plans)

    traj = rollout(model, params, x0, statics, plans, 3)
    assert traj.shape == (3,) + x0.shape[:1] + (CH,)
    step1 = model.apply(params, x0, statics, plans)
    np.testing.assert_allclose(np.asarray(traj[0]), np.asarray(step1),
                               rtol=1e-5, atol=1e-5)
    step2 = model.apply(params, step1, statics, plans)
    np.testing.assert_allclose(np.asarray(traj[1]), np.asarray(step2),
                               rtol=1e-5, atol=1e-5)
