"""Composability pin: sequence parallelism (ring attention over 'seq') and
expert parallelism (MoE over 'expert') in ONE shard_map body on a 2-D
(4 seq x 2 expert) mesh, vs the dense single-device oracle.

The parallel/ modules claim their helpers 'compose freely with the other
axes of a mesh'; this test is that claim, executed."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.parallel.expert import moe_apply
from dgraph_tpu.parallel.sequence import dense_attention, ring_attention

SEQ, EXP = 4, 2
T, H, D = 32, 2, 8  # T_loc = 8 per seq shard
F = H * D
CAP = 64  # ample capacity: no drops, exact oracle


def _mesh():
    devs = jax.devices()
    if len(devs) < SEQ * EXP:
        pytest.skip(f"need {SEQ * EXP} devices")
    return Mesh(np.array(devs[: SEQ * EXP]).reshape(SEQ, EXP), ("seq", "expert"))


def _expert_fn(p, x):
    return jnp.tanh(x @ p["w"])


def test_ring_attention_then_moe_on_2d_mesh():
    mesh = _mesh()
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.standard_normal((T, H, D)), jnp.float32)
        for _ in range(3)
    )
    wr = jnp.asarray(rng.standard_normal((F, EXP)).astype(np.float32))
    experts = [
        {"w": rng.standard_normal((F, F)).astype(np.float32) * 0.5}
        for _ in range(EXP)
    ]
    stacked = jax.tree.map(lambda *xs: jnp.asarray(np.stack(xs)), *experts)

    def body(q, k, v, wr, ep):
        # sequence-parallel exact attention over 'seq' (each expert-column
        # of the mesh holds a replica of the sequence shards)
        a = ring_attention(q, k, v, "seq", causal=True)
        toks = a.reshape(-1, F)  # [T_loc, F]
        # expert-parallel MoE over 'expert' on the attention output
        out = moe_apply(
            toks, toks @ wr, _expert_fn, jax.tree.map(lambda l: l[0], ep),
            CAP, "expert",
        )
        return out.reshape(a.shape)

    fn = jax.shard_map(
        body, mesh=mesh,
        in_specs=(P("seq"), P("seq"), P("seq"), P(), P("expert")),
        out_specs=P("seq"),
        check_vma=False,
    )
    got = fn(q, k, v, wr, stacked)

    # dense oracle
    a = dense_attention(q, k, v, causal=True)
    toks = np.asarray(a.reshape(-1, F))
    probs = np.asarray(jax.nn.softmax(jnp.asarray(toks) @ wr, axis=-1))
    eid = probs.argmax(-1)
    gate = np.take_along_axis(probs, eid[:, None], 1)[:, 0]
    want = np.stack([
        gate[t] * np.tanh(toks[t] @ experts[eid[t]]["w"]) for t in range(T)
    ]).reshape(np.asarray(a).shape)
    np.testing.assert_allclose(np.asarray(got), want, rtol=3e-5, atol=3e-5)
