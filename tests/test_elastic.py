"""Preemption-aware training (train/elastic.py) — beyond-reference
subsystem (SURVEY §5: failure detection/elastic absent in the reference)."""

import os
import signal
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

from dgraph_tpu.train.checkpoint import latest_step, restore_checkpoint
from dgraph_tpu.train.elastic import (
    PreemptionGuard,
    StepWatchdog,
    run_elastic,
)


def _mk_step():
    def step(state):
        return {"w": state["w"] + 1.0}

    return step


def test_runs_to_completion_and_checkpoints(tmp_path):
    ckpt = str(tmp_path / "ck")
    state, last, preempted = run_elastic(
        _mk_step(), {"w": jnp.zeros(3)}, start_step=0, num_steps=5,
        ckpt_dir=ckpt, guard=PreemptionGuard(signals=()),
    )
    assert not preempted and last == 5
    assert float(state["w"][0]) == 5.0
    assert latest_step(ckpt) == 5
    got = restore_checkpoint(ckpt, {"state": {"w": jnp.zeros(3)}, "step": 0})
    assert got["step"] == 5
    np.testing.assert_allclose(np.asarray(got["state"]["w"]), 5.0)


def test_preemption_saves_and_stops(tmp_path):
    ckpt = str(tmp_path / "ck")
    guard = PreemptionGuard(signals=())
    calls = {"n": 0}

    def step(state):
        calls["n"] += 1
        if calls["n"] == 3:
            guard.request_stop()  # "SIGTERM" lands during step 3
        return {"w": state["w"] + 1.0}

    state, last, preempted = run_elastic(
        step, {"w": jnp.zeros(2)}, start_step=0, num_steps=100,
        ckpt_dir=ckpt, guard=guard,
    )
    assert preempted and last == 3  # stopped right after the signaled step
    assert latest_step(ckpt) == 3

    # resume from the checkpoint: continues exactly where it stopped
    got = restore_checkpoint(ckpt, {"state": {"w": jnp.zeros(2)}, "step": 0})
    state2, last2, pre2 = run_elastic(
        _mk_step(), got["state"], start_step=got["step"], num_steps=6,
        ckpt_dir=ckpt, guard=PreemptionGuard(signals=()),
    )
    assert not pre2 and last2 == 6
    np.testing.assert_allclose(np.asarray(state2["w"]), 6.0)


def test_sigterm_handler_sets_flag():
    guard = PreemptionGuard(signals=(signal.SIGUSR1,))
    try:
        assert not guard.should_stop()
        os.kill(os.getpid(), signal.SIGUSR1)
        for _ in range(100):
            if guard.should_stop():
                break
            time.sleep(0.01)
        assert guard.should_stop()
    finally:
        guard.uninstall()


def test_watchdog_fires_on_stall():
    fired = threading.Event()
    dog = StepWatchdog(0.3, on_expire=fired.set)
    try:
        time.sleep(0.15)
        dog.beat()  # healthy heartbeat defers expiry
        assert not fired.is_set()
        assert fired.wait(timeout=3.0)  # then stall -> expires
    finally:
        dog.stop()


def test_watchdog_quiet_when_beating():
    fired = threading.Event()
    dog = StepWatchdog(0.5, on_expire=fired.set)
    try:
        for _ in range(4):
            time.sleep(0.1)
            dog.beat()
        assert not fired.is_set()
    finally:
        dog.stop()


def test_run_elastic_plumbs_first_deadline(tmp_path, monkeypatch):
    """Callers must be able to widen the first-step compile allowance —
    a slow trace under the default 10x multiplier is a spurious
    wedge-restart loop."""
    seen = {}
    real_init = StepWatchdog.__init__

    def spy_init(self, deadline_s, on_expire=None, first_deadline_s=None):
        seen["deadline_s"] = deadline_s
        seen["first_deadline_s"] = first_deadline_s
        real_init(self, deadline_s, on_expire=on_expire,
                  first_deadline_s=first_deadline_s)

    monkeypatch.setattr(StepWatchdog, "__init__", spy_init)
    run_elastic(
        _mk_step(), {"w": jnp.zeros(2)}, start_step=0, num_steps=2,
        ckpt_dir=str(tmp_path / "ck"), step_deadline_s=30.0,
        first_deadline_s=123.0, guard=PreemptionGuard(signals=()),
    )
    assert seen == {"deadline_s": 30.0, "first_deadline_s": 123.0}


# ---------------------------------------------------------------------------
# non-finite guard: host monitor + abort rollback
# ---------------------------------------------------------------------------


def test_nonfinite_monitor_consecutive_semantics():
    from dgraph_tpu.train.guard import NonFiniteAbort, NonFiniteMonitor

    mon = NonFiniteMonitor(max_consecutive=3)
    # a finite step resets the streak: skip, skip, ok, skip, skip never
    # reaches 3 consecutive
    for s, skipped in enumerate([1.0, 1.0, 0.0, 1.0, 1.0]):
        mon.observe(skipped, step=s)
    assert mon.total_skipped == 4 and mon.consecutive == 2
    with pytest.raises(NonFiniteAbort) as ei:
        mon.observe(1.0, step=5)
    rec = ei.value.record()
    assert rec["kind"] == "nonfinite_abort"
    assert rec["consecutive"] == 3 and rec["step"] == 5
    assert rec["total_skipped"] == 5
    with pytest.raises(ValueError):
        NonFiniteMonitor(max_consecutive=0)


def test_run_elastic_rolls_back_on_nonfinite_abort(tmp_path):
    from dgraph_tpu.train.guard import NonFiniteAbort

    ckpt = str(tmp_path / "ck")
    calls = {"n": 0}

    def step(state):
        calls["n"] += 1
        if calls["n"] == 5:  # "diverged": steps 0-3 fine, step 4 aborts
            raise NonFiniteAbort("diverged", step=4, consecutive=3,
                                 total_skipped=3)
        return {"w": state["w"] + 1.0}

    state, last, stopped = run_elastic(
        step, {"w": jnp.zeros(2)}, start_step=0, num_steps=10,
        ckpt_dir=ckpt, checkpoint_every=2,
        guard=PreemptionGuard(signals=()),
    )
    # rolled back to the newest checkpoint (after step 4 -> step index 4),
    # not the poisoned in-flight state
    assert stopped and last == 4
    np.testing.assert_allclose(np.asarray(state["w"]), 4.0)
    assert latest_step(ckpt) == 4


def test_run_elastic_abort_propagates_without_checkpoint(tmp_path):
    from dgraph_tpu.train.guard import NonFiniteAbort

    def step(state):
        raise NonFiniteAbort("diverged immediately", step=0,
                             consecutive=3, total_skipped=3)

    with pytest.raises(NonFiniteAbort):
        run_elastic(
            step, {"w": jnp.zeros(2)}, start_step=0, num_steps=4,
            ckpt_dir=str(tmp_path / "empty"),  # exists-not: nothing to roll to
            guard=PreemptionGuard(signals=()),
        )
    with pytest.raises(NonFiniteAbort):
        run_elastic(
            step, {"w": jnp.zeros(2)}, start_step=0, num_steps=4,
            ckpt_dir=None, guard=PreemptionGuard(signals=()),
        )


def test_run_elastic_membership_loss_checkpoints_and_raises(tmp_path):
    # the elastic-membership hook: a peer's lease expires mid-run -> the
    # loop lands a durable checkpoint (its block of the next consistent
    # cut) and raises RankLostError so the worker can exit 19 for the
    # group supervisor's shrink path (tests/test_shrink.py drives the
    # full pipeline; this pins just the loop contract)
    from dgraph_tpu.comm.membership import Membership, RankLostError

    mdir = str(tmp_path / "members")
    me = Membership(mdir, rank=0, world_size=2, lease_s=0.3)
    peer = Membership(mdir, rank=1, world_size=2, lease_s=0.3)
    peer.heartbeat()  # joins once, then falls silent forever
    me.poll()

    def step(state):
        time.sleep(0.05)
        return {"w": state["w"] + 1.0}

    ckpt = str(tmp_path / "ck")
    with pytest.raises(RankLostError) as ei:
        run_elastic(
            step, {"w": np.zeros(2)}, start_step=0, num_steps=500,
            ckpt_dir=ckpt, guard=PreemptionGuard(signals=()),
            membership=me,
        )
    assert ei.value.lost_ranks == (1,)
    # the checkpoint landed BEFORE the raise: resume has a consistent cut
    saved = latest_step(ckpt)
    assert saved is not None and 1 <= saved < 500
