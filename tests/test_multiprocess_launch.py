"""REAL multi-controller execution (SURVEY §2.4 / VERDICT r2 'partial'):
two OS processes, 4 virtual CPU devices each, wired by
``jax.distributed.initialize`` into one 8-device cluster. The graph axis
spans both processes, so every per-layer halo all_to_all is a genuine
cross-process collective; each process materializes only its own shards
(``process_local_shards``) and feeds them with
``jax.make_array_from_process_local_data``.

The transport is Gloo-over-localhost rather than ICI/DCN, but the entire
multi-controller code path — launch, pod mesh, per-host feeding, collective
compile, replicated fetch — is the same one a TPU pod runs.

Reference role: the torchrun/mpirun launcher matrix
(``MPIBackendEngine.py:268-341``) and per-rank dataset slicing
(``data/ogbn_datasets.py:135-148``).
"""

import os
import socket
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_mp_worker.py")


def _free_port() -> int:
    # fixed ports collide across concurrent/back-to-back runs (TIME_WAIT,
    # orphaned coordinators); let the kernel pick
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _launch(port: int, nprocs: int, dpp: int, timeout: int = 220):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # worker sets its own device count
    env["PALLAS_AXON_POOL_IPS"] = ""
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, f"localhost:{port}", str(nprocs),
             str(pid), str(dpp)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            env=env, cwd=REPO,
        )
        for pid in range(nprocs)
    ]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append((p.returncode, out))
    return outs


def _mpok_loss(rc_out):
    rc, out = rc_out
    assert rc == 0, out[-1500:]
    lines = [ln for ln in out.splitlines() if ln.startswith("MPOK ")]
    assert lines, out[-1500:]
    return float(lines[-1].split()[1])


@pytest.mark.slow
def test_two_process_training_step_matches_single_process():
    # 2 processes x 4 devices: cross-process halo collectives
    two = _launch(_free_port(), nprocs=2, dpp=4)
    losses = [_mpok_loss(o) for o in two]
    # the replicated loss must be bitwise-identical across controllers
    assert losses[0] == losses[1], losses

    # 1 process x 8 devices: same global mesh, no process boundary —
    # the multi-process run must compute the same training step
    one = _launch(_free_port(), nprocs=1, dpp=8)
    oracle = _mpok_loss(one[0])
    np.testing.assert_allclose(losses[0], oracle, rtol=1e-5)
