"""Memmap dataset pipeline: chunked generation, per-shard row loading
(the MAG240M memmap pattern, ``MAG240M_dataset.py:116-320``)."""

import numpy as np

from dgraph_tpu import partition as pt
from dgraph_tpu.data import memmap as mm
from dgraph_tpu.plan import shard_vertex_data


def test_create_open_roundtrip(tmp_path, rng):
    d = str(tmp_path / "ds")
    arrays = mm.create_memmap_dataset(
        d, {"features": ((100, 8), "float32"), "labels": ((100,), "int32")}
    )
    ref = rng.normal(size=(100, 8)).astype(np.float32)
    arrays["features"][:] = ref
    arrays["labels"][:] = np.arange(100, dtype=np.int32)
    for a in arrays.values():
        a.flush()
    z = mm.open_memmap_dataset(d)
    assert isinstance(z["features"], np.memmap)
    np.testing.assert_array_equal(np.asarray(z["features"]), ref)
    np.testing.assert_array_equal(np.asarray(z["labels"]), np.arange(100))


def test_generate_chunked_matches_direct(tmp_path):
    d = str(tmp_path / "ds")
    arrays = mm.create_memmap_dataset(d, {"x": ((1000, 4), "float32")})

    def chunk(lo, hi):
        return np.arange(lo, hi, dtype=np.float32)[:, None] * np.ones(4, np.float32)

    mm.generate_chunked(arrays["x"], chunk, chunk_rows=64)
    got = np.asarray(mm.open_memmap_dataset(d)["x"])
    np.testing.assert_array_equal(got[:, 0], np.arange(1000, dtype=np.float32))


def test_shard_rows_matches_full_shard(tmp_path, rng):
    """Per-shard memmap loading == the in-RAM shard_vertex_data path."""
    V, F, W = 257, 8, 4
    feats = rng.normal(size=(V, F)).astype(np.float32)
    part = pt.random_partition(V, W, seed=0)
    ren = pt.renumber_contiguous(part, W)
    n_pad = int(ren.counts.max()) + 3

    full = shard_vertex_data(feats[ren.inv], ren.counts, n_pad)  # [W, n_pad, F]

    d = str(tmp_path / "ds")
    arrays = mm.create_memmap_dataset(d, {"features": ((V, F), "float32")})
    arrays["features"][:] = feats
    arrays["features"].flush()
    z = mm.open_memmap_dataset(d)

    # load only shards {1, 3}
    got = mm.shard_rows(z["features"], ren.inv, ren.offsets, n_pad, [1, 3])
    np.testing.assert_allclose(got[0], full[1], rtol=0, atol=0)
    np.testing.assert_allclose(got[1], full[3], rtol=0, atol=0)


def test_synthetic_papers_like_loadable(tmp_path):
    d = mm.synthetic_papers_like(str(tmp_path / "syn"), num_nodes=500, feat_dim=8)
    z = mm.open_memmap_dataset(d)
    assert z["features"].shape == (500, 8)
    assert z["edge_index"].shape[0] == 2
    assert z["edge_index"].max() < 500
    assert 0 < z["train_mask"].sum() < 500
