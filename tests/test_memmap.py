"""Memmap dataset pipeline: chunked generation, per-shard row loading
(the MAG240M memmap pattern, ``MAG240M_dataset.py:116-320``)."""

import numpy as np

from dgraph_tpu import partition as pt
from dgraph_tpu.data import memmap as mm
from dgraph_tpu.plan import shard_vertex_data


def test_create_open_roundtrip(tmp_path, rng):
    d = str(tmp_path / "ds")
    arrays = mm.create_memmap_dataset(
        d, {"features": ((100, 8), "float32"), "labels": ((100,), "int32")}
    )
    ref = rng.normal(size=(100, 8)).astype(np.float32)
    arrays["features"][:] = ref
    arrays["labels"][:] = np.arange(100, dtype=np.int32)
    for a in arrays.values():
        a.flush()
    z = mm.open_memmap_dataset(d)
    assert isinstance(z["features"], np.memmap)
    np.testing.assert_array_equal(np.asarray(z["features"]), ref)
    np.testing.assert_array_equal(np.asarray(z["labels"]), np.arange(100))


def test_generate_chunked_matches_direct(tmp_path):
    d = str(tmp_path / "ds")
    arrays = mm.create_memmap_dataset(d, {"x": ((1000, 4), "float32")})

    def chunk(lo, hi):
        return np.arange(lo, hi, dtype=np.float32)[:, None] * np.ones(4, np.float32)

    mm.generate_chunked(arrays["x"], chunk, chunk_rows=64)
    got = np.asarray(mm.open_memmap_dataset(d)["x"])
    np.testing.assert_array_equal(got[:, 0], np.arange(1000, dtype=np.float32))


def test_shard_rows_matches_full_shard(tmp_path, rng):
    """Per-shard memmap loading == the in-RAM shard_vertex_data path."""
    V, F, W = 257, 8, 4
    feats = rng.normal(size=(V, F)).astype(np.float32)
    part = pt.random_partition(V, W, seed=0)
    ren = pt.renumber_contiguous(part, W)
    n_pad = int(ren.counts.max()) + 3

    full = shard_vertex_data(feats[ren.inv], ren.counts, n_pad)  # [W, n_pad, F]

    d = str(tmp_path / "ds")
    arrays = mm.create_memmap_dataset(d, {"features": ((V, F), "float32")})
    arrays["features"][:] = feats
    arrays["features"].flush()
    z = mm.open_memmap_dataset(d)

    # load only shards {1, 3}
    got = mm.shard_rows(z["features"], ren.inv, ren.offsets, n_pad, [1, 3])
    np.testing.assert_allclose(got[0], full[1], rtol=0, atol=0)
    np.testing.assert_allclose(got[1], full[3], rtol=0, atol=0)


def test_shard_rows_to_device_matches_host_stack(tmp_path, rng):
    """The streamed device-sharding path == jnp.asarray(shard_rows(all)),
    including sharding layout, from a memmap source (VERDICT r4 weak #6:
    the stacked host copy must never be needed for correctness)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dgraph_tpu.comm import make_graph_mesh
    from dgraph_tpu.comm.mesh import GRAPH_AXIS

    V, F, W = 203, 6, 8
    feats = rng.normal(size=(V, F)).astype(np.float64)
    part = pt.random_partition(V, W, seed=1)
    ren = pt.renumber_contiguous(part, W)
    n_pad = int(ren.counts.max()) + 5

    d = str(tmp_path / "ds")
    arrays = mm.create_memmap_dataset(d, {"features": ((V, F), "float64")})
    arrays["features"][:] = feats
    arrays["features"].flush()
    z = mm.open_memmap_dataset(d)

    mesh = make_graph_mesh(ranks_per_graph=W)
    got = mm.shard_rows_to_device(
        z["features"], ren.inv, ren.offsets, n_pad, mesh, dtype=np.float32
    )
    want = mm.shard_rows(
        feats, ren.inv, ren.offsets, n_pad, range(W), np.float32
    )
    assert got.shape == (W, n_pad, F)
    assert got.dtype == jnp.float32
    assert got.sharding == NamedSharding(mesh, P(GRAPH_AXIS))
    np.testing.assert_array_equal(np.asarray(got), want)
    # 1-D payloads (labels/masks) shard the same way
    labels = rng.integers(0, 7, V).astype(np.int64)
    got1 = mm.shard_rows_to_device(
        labels, ren.inv, ren.offsets, n_pad, mesh, dtype=np.int32
    )
    want1 = mm.shard_rows(labels, ren.inv, ren.offsets, n_pad, range(W), np.int32)
    np.testing.assert_array_equal(np.asarray(got1), want1)


def test_shard_rows_to_device_on_2d_mesh(rng):
    """With a (replica, graph) mesh the graph-axis spec replicates blocks
    across replicas; every replica sees identical rows."""
    import jax.numpy as jnp

    from dgraph_tpu.comm import make_graph_mesh

    V, F, W = 67, 4, 4
    feats = rng.normal(size=(V, F)).astype(np.float32)
    part = pt.random_partition(V, W, seed=2)
    ren = pt.renumber_contiguous(part, W)
    n_pad = int(ren.counts.max()) + 1
    mesh = make_graph_mesh(ranks_per_graph=W, num_replicas=2)
    got = mm.shard_rows_to_device(feats, ren.inv, ren.offsets, n_pad, mesh)
    want = mm.shard_rows(feats, ren.inv, ren.offsets, n_pad, range(W))
    np.testing.assert_array_equal(np.asarray(got), want)


def test_synthetic_papers_like_loadable(tmp_path):
    d = mm.synthetic_papers_like(str(tmp_path / "syn"), num_nodes=500, feat_dim=8)
    z = mm.open_memmap_dataset(d)
    assert z["features"].shape == (500, 8)
    assert z["edge_index"].shape[0] == 2
    assert z["edge_index"].max() < 500
    assert 0 < z["train_mask"].sum() < 500
