"""MAG240M memmap pipeline: derived-feature aggregation correctness and the
synthetic-layout roundtrip into DistributedHeteroGraph + RGAT training.

Reference parity: MAG240M_dataset.py:65-107 (chunked mean-aggregation of
author/institution features) and :116-320 (memmap dataset binding)."""

import numpy as np
import pytest

from dgraph_tpu.data.mag240m import (
    aggregate_mean_features,
    load_mag240m_memmap,
    synthetic_mag240m_memmap,
)


def test_aggregate_mean_matches_dense():
    rng = np.random.default_rng(0)
    N_src, N_dst, F, E = 50, 23, 17, 400
    src_feat = rng.standard_normal((N_src, F)).astype(np.float32)
    dst = rng.integers(0, N_dst, E)
    src = rng.integers(0, N_src, E)
    out = np.zeros((N_dst, F), np.float32)
    aggregate_mean_features(out, src_feat, np.stack([dst, src]),
                            row_chunk=7, col_chunk=5)
    want = np.zeros((N_dst, F), np.float32)
    for d in range(N_dst):
        rows = src[dst == d]
        if len(rows):
            want[d] = src_feat[rows].mean(axis=0)
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)


def test_aggregate_handles_isolated_rows():
    src_feat = np.ones((4, 3), np.float32)
    out = np.full((5, 3), 7.0, np.float32)
    aggregate_mean_features(out, src_feat, np.array([[0], [1]]))
    assert np.all(out[0] == 1.0)
    assert np.all(out[1:] == 0.0)  # untouched rows zeroed, not stale


def test_synthetic_layout_roundtrip(tmp_path):
    out = synthetic_mag240m_memmap(str(tmp_path / "mag"), scale=2e-5,
                                   num_features=8)
    nf, rels, labels, masks, meta = load_mag240m_memmap(out)
    assert meta["num_classes"] == 153
    P, A = meta["num_papers"], meta["num_authors"]
    assert nf["paper"].shape == (P, 8) and nf["paper"].dtype == np.float16
    assert len(rels) == 5
    # author features really are their papers' means (through the memmap)
    ap = rels[("author", "writes", "paper")]
    a0 = int(ap[0][0])
    mine = ap[1][ap[0] == a0]
    want = np.asarray(nf["paper"], np.float32)[mine].mean(axis=0)
    np.testing.assert_allclose(
        np.asarray(nf["author"][a0], np.float32), want, rtol=2e-2, atol=2e-2
    )
    assert masks["paper"]["train"].sum() > 0


def test_raw_download_layout_through_prepare(tmp_path):
    """Byte-real fixture of the official mag240m_kddcup2021 download layout
    (torch.save'd meta.pt/split_dict.pt, float16 node_feat.npy memmap,
    {src}___{rel}___{dst}/edge_index.npy) driven through
    prepare_mag240m_memmap's no-ogb branch (VERDICT r4 #7: the branch real
    data will take in this pip-less environment). Checks the derived
    author features and the -1 relabeling of NaN papers come out of the
    SAME code path the ogb.lsc branch uses."""
    from dgraph_tpu.data.mag240m import (
        RawMAG240M,
        prepare_mag240m_memmap,
        write_mag240m_raw_fixture,
    )

    rng = np.random.default_rng(6)
    P, A, I, F = 40, 25, 6, 8
    paper_feat = rng.standard_normal((P, F)).astype(np.float16)
    paper_label = rng.integers(0, 153, P).astype(np.float32)
    paper_label[::5] = np.nan  # unlabeled, like non-arxiv papers
    writes = np.stack([rng.integers(0, A, 60), rng.integers(0, P, 60)])
    fixture_root = str(tmp_path / "download")
    write_mag240m_raw_fixture(
        fixture_root,
        paper_feat=paper_feat,
        paper_label=paper_label,
        cites=np.stack([rng.integers(0, P, 80), rng.integers(0, P, 80)]),
        writes=writes,
        affiliated=np.stack([rng.integers(0, A, 30), rng.integers(0, I, 30)]),
        num_authors=A, num_institutions=I,
    )

    # the accessor parses the layout like ogb.lsc.MAG240MDataset does
    ds = RawMAG240M(fixture_root)
    assert (ds.num_papers, ds.num_authors, ds.num_institutions) == (P, A, I)
    assert ds.paper_feat.dtype == np.float16
    np.testing.assert_array_equal(
        np.asarray(ds.edge_index("author", "paper")), writes
    )

    out = prepare_mag240m_memmap(
        fixture_root, str(tmp_path / "memmap"), num_features=F
    )
    nf, rels, labels, masks, meta = load_mag240m_memmap(out)
    assert meta["source"] == "raw-download"
    assert meta["num_classes"] == 153
    # NaN papers became -1 (fail-loudly convention), labeled kept values
    lab = np.asarray(labels["paper"])
    assert np.all(lab[::5] == -1)
    keep = np.ones(P, bool)
    keep[::5] = False
    np.testing.assert_array_equal(
        lab[keep], paper_label[keep].astype(np.int32)
    )
    # derived author features are their papers' float16-rounded means
    a0 = int(writes[0][0])
    mine = writes[1][writes[0] == a0]
    want = np.asarray(paper_feat, np.float32)[mine].mean(axis=0)
    np.testing.assert_allclose(
        np.asarray(nf["author"][a0], np.float32), want, rtol=2e-2, atol=2e-2
    )
    # splits cover exactly the labeled papers, disjointly
    got = np.concatenate([
        np.nonzero(masks["paper"][s])[0] for s in ("train", "val", "test")
    ])
    assert len(got) == len(set(got.tolist()))
    np.testing.assert_array_equal(np.sort(got), np.nonzero(keep)[0])


def test_memmap_feeds_hetero_training(tmp_path):
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
    from dgraph_tpu.data.hetero import DistributedHeteroGraph
    from dgraph_tpu.models import RGAT
    from jax.sharding import PartitionSpec as P

    out = synthetic_mag240m_memmap(str(tmp_path / "mag"), scale=1.2e-5,
                                   num_features=8)
    nf, rels, labels, masks, meta = load_mag240m_memmap(out)
    W = 4
    g = DistributedHeteroGraph.from_global(
        nf, rels, W, labels=labels, masks=masks, partition_method="multilevel"
    )
    comm = Communicator.init_process_group("tpu", world_size=W)
    model = RGAT(hidden_features=8, out_features=meta["num_classes"],
                 comm=comm, relations=list(g.plans), num_layers=1,
                 num_heads=2, use_batch_norm=False)
    mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])
    feats = {t: jnp.asarray(v) for t, v in g.features.items()}
    plans = {k: jax.tree.map(jnp.asarray, p) for k, p in g.plans.items()}
    vmasks = {t: jnp.asarray(v) for t, v in g.vertex_masks.items()}
    feat_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), feats)
    plan_specs = {k: plan_in_specs(p) for k, p in plans.items()}
    vm_specs = jax.tree.map(lambda _: P(GRAPH_AXIS), vmasks)

    def body(feats_, plans_, vmasks_):
        f = {t: v[0] for t, v in feats_.items()}
        p = {k: squeeze_plan(pp) for k, pp in plans_.items()}
        v = {t: m[0] for t, m in vmasks_.items()}
        out = model.init(jax.random.key(0), f, p, v, train=False)
        logits = model.apply(out, f, p, v, train=False)
        return logits

    with jax.set_mesh(mesh):
        logits = jax.jit(
            jax.shard_map(body, mesh=mesh,
                          in_specs=(feat_specs, plan_specs, vm_specs),
                          out_specs=P(GRAPH_AXIS))
        )(feats, plans, vmasks)
    assert np.isfinite(np.asarray(logits)).all()
