"""Cross-rank SPMD divergence auditor (``analysis/spmd.py``): per-rank
lowered-module identity, collective issue order, n_deltas symmetry, and
the canonicalization that makes them sound.

The full matrix — 2- AND 4-shard worlds x all four halo lowerings x all
three programs, plus both generations of a real shrink transition and
every vacuity mutant — runs in the ``--selftest`` CLI registration
(``tests/test_analysis.py::test_analysis_selftest_cli``); the tests here
pin each mechanism individually on reduced shapes so a regression names
its own check.  Everything is lower-only: zero new XLA compiles
(tests/README.md), jit-cache counters asserted in the reports.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def plan_dir2(tmp_path_factory):
    from dgraph_tpu.analysis.spmd import build_spmd_fixture

    d = str(tmp_path_factory.mktemp("spmd") / "w2")
    return build_spmd_fixture(2, d)


@pytest.fixture(scope="module")
def plan_dir4(tmp_path_factory):
    from dgraph_tpu.analysis.spmd import build_spmd_fixture

    d = str(tmp_path_factory.mktemp("spmd") / "w4")
    return build_spmd_fixture(4, d)


# ---------------------------------------------------------------------------
# the clean contract: identical programs, identical order, empty jit cache
# ---------------------------------------------------------------------------


def test_clean_cross_rank_audit_is_green(plan_dir2):
    from dgraph_tpu.analysis.spmd import audit_plan_dir_spmd
    from dgraph_tpu.analysis.trace import _train_program

    rep = audit_plan_dir_spmd(
        plan_dir2, impls=("all_to_all", "ppermute"),
        programs={"train_step": _train_program},
    )
    assert rep["ok"], rep["failures"]
    assert rep["world_size"] == 2
    assert rep["num_halo_deltas"] >= 1
    for prec in rep["programs"]:
        assert prec["identical"], prec
        assert len(set(prec["module_hash"].values())) == 1
        assert prec["num_collectives"] > 0  # identity of empty would be vacuous
        # lower-only, per rank, asserted in the report
        assert all(c == 0 for c in prec["jit_cache_entries"].values()), prec
    assert rep["delta_symmetry"] == "symmetric"
    # every rank resolved the same lowering through the real ladder
    assert len({tuple(v) for v in rep["resolution"].values()}) == 1


def test_rank_views_see_their_own_live_deltas(plan_dir4):
    """rank_live_deltas reads the rank's OWN send mask — the locally
    observable half of the delta set the manifest globalizes."""
    from dgraph_tpu.analysis.spmd import rank_live_deltas
    from dgraph_tpu.plan import load_sharded_plan

    full, _ = load_sharded_plan(plan_dir4, load_layout=False)
    global_deltas = set(full.halo_deltas)
    for r in range(4):
        sub, _ = load_sharded_plan(plan_dir4, ranks=[r], load_layout=False)
        live = rank_live_deltas(sub, r)
        assert set(live) <= global_deltas, (r, live, global_deltas)


# ---------------------------------------------------------------------------
# seeded divergences (the deadlock classes) must go RED
# ---------------------------------------------------------------------------


def test_dropped_round_on_one_rank_goes_red(plan_dir4):
    from dgraph_tpu.analysis.spmd import (
        audit_plan_dir_spmd, mutant_dropped_round_program,
    )

    rep = audit_plan_dir_spmd(
        plan_dir4, impls=("ppermute",),
        programs={"mutant": mutant_dropped_round_program},
    )
    assert not rep["ok"]
    assert any(
        "COUNT mismatch" in f or "differs" in f for f in rep["failures"]
    ), rep["failures"]
    # the divergence names rank 1 (the seeded branch) against rank 0
    assert any("rank 1" in f for f in rep["failures"]), rep["failures"]


def test_swapped_collective_order_flagged_as_order(plan_dir4):
    """Equal per-rank totals, different order — only the issue-sequence
    comparator can catch this one."""
    from dgraph_tpu.analysis.spmd import (
        audit_plan_dir_spmd, mutant_swapped_order_program,
    )

    rep = audit_plan_dir_spmd(
        plan_dir4, impls=("ppermute",),
        programs={"mutant": mutant_swapped_order_program},
    )
    assert not rep["ok"]
    assert any("ORDER" in f for f in rep["failures"]), rep["failures"]
    assert not any("COUNT mismatch" in f for f in rep["failures"])


def test_rank_divergent_tune_record_goes_red(plan_dir4):
    """A per-host adopted TuningRecord that disagrees across ranks splits
    the transport family before the first exchange — caught at the
    resolution-agreement check, before anything lowers (impls=())."""
    from dgraph_tpu.analysis.spmd import audit_plan_dir_spmd

    rep = audit_plan_dir_spmd(
        plan_dir4, impls=(), programs={},
        rank_tuned={0: "all_to_all", 1: "ppermute"},
    )
    assert not rep["ok"]
    assert any("resolution" in f for f in rep["failures"]), rep["failures"]


def test_benign_rank_tag_constant_stays_green(plan_dir2):
    """A rank-id constant folded into the module (a metrics tag) is the
    one benign per-rank difference; the canonicalizer must substitute it
    — and must COUNT the substitution, so the check is provably
    non-vacuous."""
    from dgraph_tpu.analysis.spmd import (
        audit_plan_dir_spmd, benign_rank_tag_program,
    )

    rep = audit_plan_dir_spmd(
        plan_dir2, impls=("ppermute",),
        programs={"benign": benign_rank_tag_program},
    )
    assert rep["ok"], rep["failures"]
    assert all(p["rank_tag_lines"] > 0 for p in rep["programs"])


# ---------------------------------------------------------------------------
# canonicalization mechanics (pure text, no lowering)
# ---------------------------------------------------------------------------


def test_canonicalize_substitutes_only_pure_rank_tags():
    from dgraph_tpu.analysis.spmd import RANK_TOKEN, canonicalize_rank_modules

    # a pure rank-tag line is rewritten; rank 0's ubiquitous `0` literals
    # on SHARED lines are untouched
    texts = {
        0: "op_a dense<0> : tensor<i32>\nshared dense<0> : tensor<i32>",
        1: "op_a dense<1> : tensor<i32>\nshared dense<0> : tensor<i32>",
    }
    canon, subs = canonicalize_rank_modules(texts)
    assert subs == 1
    assert canon[0] == canon[1]
    assert RANK_TOKEN in canon[0].splitlines()[0]
    assert "dense<0>" in canon[0].splitlines()[1]  # shared line untouched

    # a structural difference on the same line survives verbatim
    texts = {
        0: "stablehlo.add %a, %b",
        1: "stablehlo.multiply %a, %b",
    }
    canon, subs = canonicalize_rank_modules(texts)
    assert subs == 0
    assert canon[0] != canon[1]

    # float rank-lookalikes are NOT substituted (boundary guard)
    texts = {
        0: "c = dense<0.000000e+00> : tensor<f32>",
        1: "c = dense<1.000000e+00> : tensor<f32>",
    }
    canon, subs = canonicalize_rank_modules(texts)
    assert subs == 0 and canon[0] != canon[1]

    # different line counts = structural divergence, returned unchanged
    texts = {0: "a\nb", 1: "a"}
    canon, subs = canonicalize_rank_modules(texts)
    assert subs == 0 and canon == texts


def test_rank_env_is_restored_after_audit(plan_dir2):
    from dgraph_tpu.analysis.spmd import audit_plan_dir_spmd
    from dgraph_tpu.utils.env import RANK_ENV_VAR

    os.environ[RANK_ENV_VAR] = "7"
    try:
        audit_plan_dir_spmd(plan_dir2, impls=(), programs={})
        assert os.environ[RANK_ENV_VAR] == "7"
    finally:
        os.environ.pop(RANK_ENV_VAR, None)


# ---------------------------------------------------------------------------
# bench fallback record (tier 4)
# ---------------------------------------------------------------------------


def test_spmd_drift_record_shape():
    from dgraph_tpu.analysis.spmd import spmd_drift_record

    rec = spmd_drift_record(2, num_nodes=64, num_edges=256, feat_dim=8)
    assert rec["kind"] == "spmd_drift"
    assert rec["drift"] is False
    assert rec["num_halo_deltas"] >= 1
    for impl in ("all_to_all", "ppermute", "overlap", "pallas_p2p"):
        row = rec["train_step_by_impl"][impl]
        assert row["identical"] is True
        assert row["num_collectives"] > 0


# ---------------------------------------------------------------------------
# shrink generations re-agree (the W -> W-1 path, reduced: one impl)
# ---------------------------------------------------------------------------


def test_shrink_generations_cross_rank_green(tmp_path):
    from dgraph_tpu.analysis.spmd import (
        audit_plan_dir_spmd, build_shrink_fixture,
    )
    from dgraph_tpu.analysis.trace import _train_program
    from dgraph_tpu.train import shrink as shr

    rund = str(tmp_path / "run")
    world = build_shrink_fixture(rund, world_size=3)
    assert world["generation"] == 1 and world["world_size"] == 2
    for gen, wsz in ((0, 3), (1, 2)):
        rep = audit_plan_dir_spmd(
            shr.plan_dir(rund, gen), impls=("ppermute",),
            programs={"train_step": _train_program}, label=f"g{gen}",
        )
        assert rep["world_size"] == wsz
        assert rep["ok"], (gen, rep["failures"])
