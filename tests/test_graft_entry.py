"""The driver's entry points must keep working: entry() compile-checks and
dryrun_multichip() runs a real sharded train step on the virtual CPU mesh."""

import sys
from pathlib import Path

import jax

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def test_entry_compiles():
    import __graft_entry__ as ge

    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.shape[-1] == 4


def test_dryrun_multichip_8():
    import __graft_entry__ as ge

    ge.dryrun_multichip(8)


def test_dryrun_multichip_odd_world():
    import __graft_entry__ as ge

    ge.dryrun_multichip(5)
