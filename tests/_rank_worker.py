"""Multi-rank elastic worker for the rank-kill acceptance test
(test_shrink.py). Run under ``supervise_group`` as:

    DGRAPH_RANK=<r> python tests/_rank_worker.py <run_dir> <steps> <sleep_s>

One member of an elastic world (``train.shrink`` run-dir layout): reads
the ``world.json`` adoption pointer, loads ONLY its own plan shard (the
PR 8 rank-subset path), joins the membership directory via a retrying
rendezvous, and drives a deliberately tiny host-side numpy "training"
loop through ``run_elastic(membership=...)`` — heartbeating every step,
checkpointing every step, and exiting ``RANK_LOST_EXIT_CODE`` (19) after
a durable checkpoint when a peer's lease expires — or
``RANK_JOIN_EXIT_CODE`` (23) when a newcomer announces a join, so the
supervisor can grow the world (test_grow.py).  The per-vertex update
is keyed by ORIGINAL vertex id (``graph_g<g>.npz``'s ``orig_ids``), so a
wrong row anywhere in the shrink/reshard pipeline diverges from the
global oracle the test computes.

No jitted step on purpose: the recovery machinery under test is all host
code, and tier-1 cannot afford a fresh XLA compile per subprocess.  The
PreemptionGuard is INERT (``signals=()``) so the chaos ``sigterm``
rank-kill is an abrupt death — exactly the fault membership must detect
— not a graceful preemption.
"""

import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402


def make_step_fn(orig_ids: np.ndarray, count: int, n_pad: int,
                 sleep_s: float):
    """One deterministic float64 momentum step per call.  ``g`` is keyed
    by original vertex id so state rows are distinguishable through any
    renumbering; pad rows stay exactly zero."""
    g = np.zeros(n_pad, np.float64)
    g[:count] = orig_ids.astype(np.float64) + 1.0

    def step_fn(state):
        if sleep_s:
            time.sleep(sleep_s)
        m = 0.5 * state["opt_state"]["m"] + g
        w = state["params"]["w"] + 0.25 * m
        return {"params": {"w": w}, "opt_state": {"m": m}}

    return step_fn


def main() -> None:
    run_dir, num_steps, sleep_s = (
        sys.argv[1], int(sys.argv[2]), float(sys.argv[3])
    )
    from dgraph_tpu.comm.membership import (
        RANK_JOIN_EXIT_CODE,
        RANK_LOST_EXIT_CODE,
        Membership,
        RankJoinError,
        RankLostError,
        rank_from_env,
    )

    rank = rank_from_env()
    from dgraph_tpu.plan import load_sharded_plan
    from dgraph_tpu.train import shrink
    from dgraph_tpu.train.checkpoint import latest_step, restore_checkpoint
    from dgraph_tpu.train.elastic import PreemptionGuard, run_elastic

    world = shrink.read_world(run_dir)
    gen, W = int(world["generation"]), int(world["world_size"])
    assert rank < W, f"rank {rank} outside adopted world {W}"

    # each-host-loads-its-shard: only THIS rank's plan shard is read
    plan, _ = load_sharded_plan(
        shrink.plan_dir(run_dir, gen), ranks=[rank], load_layout=False
    )
    n_pad = int(plan.n_dst_pad)
    count = int(plan.num_local_dst[0])
    graph = np.load(shrink.graph_path(run_dir, gen))
    offs = np.concatenate([[0], np.cumsum(graph["counts"])])
    orig_ids = np.asarray(graph["orig_ids"])[offs[rank]: offs[rank + 1]]
    assert orig_ids.shape[0] == count

    ckpt = shrink.rank_ckpt_dir(run_dir, gen, rank)
    state = {
        "params": {"w": np.zeros(n_pad, np.float64)},
        "opt_state": {"m": np.zeros(n_pad, np.float64)},
    }
    start = int(world.get("resume_step", 0))
    if latest_step(ckpt) is not None:
        got = restore_checkpoint(ckpt, {"state": state, "step": 0})
        state, start = got["state"], int(got["step"])
        print(f"WORKER_RESUME rank={rank} gen={gen} step={start}", flush=True)

    attempt = int(os.environ.get("DGRAPH_CHAOS_ATTEMPT", "0"))
    mem = Membership(
        shrink.membership_dir(run_dir, gen, attempt),
        rank=rank,
        world_size=W,
        lease_s=float(world["lease_s"]),
        generation=gen,
    )
    roster = mem.rendezvous(deadline_s=60.0)
    print(f"WORKER_JOINED rank={rank} roster={list(roster)}", flush=True)
    # lease maintenance must track the PROCESS, not the step cadence: a
    # loaded machine can stretch one step (orbax write) past the lease,
    # and a live-but-slow rank must never read as dead to its peers
    mem.start_heartbeats()

    try:
        state, last, preempted = run_elastic(
            make_step_fn(orig_ids, count, n_pad, sleep_s),
            state,
            start_step=start,
            num_steps=num_steps,
            ckpt_dir=ckpt,
            checkpoint_every=1,
            guard=PreemptionGuard(signals=()),  # abrupt SIGTERM death
            membership=mem,
        )
    except RankLostError as e:
        print(f"WORKER_RANK_LOST rank={rank} " + json.dumps(e.record()),
              flush=True)
        sys.exit(RANK_LOST_EXIT_CODE)
    except RankJoinError as e:
        # a joiner announced: checkpoint already durable (run_elastic
        # saved before raising) — exit 23 so the supervisor grows W+k
        print(f"WORKER_RANK_JOIN rank={rank} " + json.dumps(e.record()),
              flush=True)
        sys.exit(RANK_JOIN_EXIT_CODE)
    mem.stop_heartbeats()
    mem.leave()
    print(
        f"WORKER_DONE rank={rank} gen={gen} step={last} "
        f"preempted={preempted}",
        flush=True,
    )


if __name__ == "__main__":
    main()
