"""Distributed gather/scatter/halo-exchange vs dense oracles, forward and
backward — the TPU analogue of the reference's ``tests/test_NCCLCommPlan.py``
strategy (SURVEY.md §4: golden values from dense global computation; backward
pinned against the analytic transpose).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu import plan as pl
from dgraph_tpu.comm import collectives
from dgraph_tpu.testing import (
    dense_gather,
    dense_scatter_sum,
    spmd_apply,
    unshard_edge_data,
)
from dgraph_tpu.plan import shard_vertex_data, shard_edge_data, unshard_vertex_data


def random_case(rng, V=64, E=512, W=8, F=5, owner="dst", bipartite=False):
    edges = rng.integers(0, V, size=(2, E))
    part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    if bipartite:
        Vb = V // 2
        edges = np.stack([rng.integers(0, V, E), rng.integers(0, Vb, E)])
        part_b = np.sort(rng.integers(0, W, Vb)).astype(np.int32)
        plan, layout = pl.build_edge_plan(edges, part, part_b, world_size=W, edge_owner=owner)
    else:
        plan, layout = pl.build_edge_plan(edges, part, world_size=W, edge_owner=owner)
    return edges, part, plan, layout


@pytest.mark.parametrize("owner", ["src", "dst"])
@pytest.mark.parametrize("side", ["src", "dst"])
def test_gather_vs_dense(mesh8, rng, owner, side):
    edges, part, plan, layout = random_case(rng, owner=owner)
    V, F = len(part), 5
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = shard_vertex_data(x, layout.src_counts, plan.n_src_pad)

    out = spmd_apply(mesh8, collectives.gather, plan, jnp.asarray(xs), static_args=(side, "graph"))
    got = unshard_edge_data(np.asarray(out), layout)
    np.testing.assert_allclose(got, dense_gather(x, edges, side), rtol=1e-6)


@pytest.mark.parametrize("owner", ["src", "dst"])
@pytest.mark.parametrize("side", ["src", "dst"])
def test_scatter_sum_vs_dense(mesh8, rng, owner, side):
    edges, part, plan, layout = random_case(rng, owner=owner)
    V, F = len(part), 4
    E = edges.shape[1]
    edata = rng.normal(size=(E, F)).astype(np.float32)
    ed_sharded = shard_edge_data(edata, layout, plan.e_pad)

    out = spmd_apply(
        mesh8, collectives.scatter_sum, plan, jnp.asarray(ed_sharded), static_args=(side, "graph")
    )
    counts = layout.src_counts if side == "src" else layout.dst_counts
    got = unshard_vertex_data(np.asarray(out), counts)
    np.testing.assert_allclose(got, dense_scatter_sum(edata, edges, side, V), rtol=1e-5, atol=1e-5)


def test_gather_bipartite_vs_dense(mesh8, rng):
    edges, part, plan, layout = random_case(rng, bipartite=True)
    F = 3
    xa = rng.normal(size=(len(part), F)).astype(np.float32)
    xs = shard_vertex_data(xa, layout.src_counts, plan.n_src_pad)
    out = spmd_apply(mesh8, collectives.gather, plan, jnp.asarray(xs), static_args=("src", "graph"))
    got = unshard_edge_data(np.asarray(out), layout)
    np.testing.assert_allclose(got, dense_gather(xa, edges, "src"), rtol=1e-6)


def test_single_device_matches_dense(rng):
    """World size 1 (SingleComm path): axis_name=None, no collectives."""
    edges, part, plan, layout = random_case(rng, W=1)
    V, F = len(part), 4
    x = rng.normal(size=(V, F)).astype(np.float32)
    xs = shard_vertex_data(x, layout.src_counts, plan.n_src_pad)

    sq = jax.tree.map(lambda leaf: leaf[0], plan)
    got_e = np.asarray(collectives.gather(jnp.asarray(xs[0]), sq, "src", None))
    got_e = unshard_edge_data(got_e[None], layout)
    np.testing.assert_allclose(got_e, dense_gather(x, edges, "src"), rtol=1e-6)

    edata = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
    ed = shard_edge_data(edata, layout, plan.e_pad)
    got_v = np.asarray(collectives.scatter_sum(jnp.asarray(ed[0]), sq, "dst", None))
    got_v = unshard_vertex_data(got_v[None], layout.dst_counts)
    np.testing.assert_allclose(got_v, dense_scatter_sum(edata, edges, "dst", V), rtol=1e-5, atol=1e-5)


class TestGradients:
    """Backward = analytic transpose (gather-bwd is scatter-sum, scatter-bwd
    is gather), tested end-to-end through shard_map + all_to_all — parity
    with ``tests/test_NCCLCommPlan.py:85-359``'s backward checks."""

    def test_gather_grad_is_scatter_of_cotangent(self, mesh8, rng):
        edges, part, plan, layout = random_case(rng, V=48, E=256)
        V, F = len(part), 3
        x = rng.normal(size=(V, F)).astype(np.float32)
        xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))
        ct = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
        ct_sh = jnp.asarray(shard_edge_data(ct, layout, plan.e_pad))

        def loss_fn(xs_):
            out = spmd_apply(mesh8, collectives.gather, plan, xs_, static_args=("src", "graph"))
            return jnp.sum(out * ct_sh)

        with jax.set_mesh(mesh8):
            grad = jax.jit(jax.grad(loss_fn))(xs)
        got = unshard_vertex_data(np.asarray(grad), layout.src_counts)
        expected = dense_scatter_sum(ct, edges, "src", V)
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)

    def test_scatter_grad_is_gather_of_cotangent(self, mesh8, rng):
        edges, part, plan, layout = random_case(rng, V=48, E=256)
        V, F = len(part), 3
        edata = rng.normal(size=(edges.shape[1], F)).astype(np.float32)
        ed = jnp.asarray(shard_edge_data(edata, layout, plan.e_pad))
        ct = rng.normal(size=(V, F)).astype(np.float32)
        ct_sh = jnp.asarray(shard_vertex_data(ct, layout.dst_counts, plan.n_dst_pad))

        def loss_fn(ed_):
            out = spmd_apply(mesh8, collectives.scatter_sum, plan, ed_, static_args=("dst", "graph"))
            return jnp.sum(out * ct_sh)

        with jax.set_mesh(mesh8):
            grad = jax.jit(jax.grad(loss_fn))(ed)
        got = unshard_edge_data(np.asarray(grad), layout)
        expected = dense_gather(ct, edges, "dst")
        np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-5)

    def test_gather_grad_halo_side_accumulates_duplicates(self, mesh8, rng):
        """Duplicate-vertex gradient accumulation across ranks — the property
        the reference gets from doing x[send_idx] outside the Function
        (``haloExchange.py:12-17,137``)."""
        # star graph: every edge's src is vertex 0 -> grad at v0 = sum of all
        V, E, W, F = 16, 64, 8, 2
        edges = np.stack([np.zeros(E, np.int64), rng.integers(0, V, E)])
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        plan, layout = pl.build_edge_plan(edges, part, world_size=W, edge_owner="dst")
        x = rng.normal(size=(V, F)).astype(np.float32)
        xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))

        def loss_fn(xs_):
            out = spmd_apply(mesh8, collectives.gather, plan, xs_, static_args=("src", "graph"))
            return jnp.sum(out)

        with jax.set_mesh(mesh8):
            grad = jax.jit(jax.grad(loss_fn))(xs)
        got = unshard_vertex_data(np.asarray(grad), layout.src_counts)
        assert got[0, 0] == pytest.approx(E, rel=1e-6)
        np.testing.assert_allclose(got[1:], 0.0, atol=1e-6)


def test_halo_exchange_contents(mesh8, rng):
    """Halo buffer rows land at [p*s_pad, ...) in sorted-vid order."""
    edges, part, plan, layout = random_case(rng, V=40, E=300)
    V, F = len(part), 2
    # feature = global vertex id, to make received values identifiable
    x = np.stack([np.arange(V), np.arange(V)], axis=1).astype(np.float32)
    xs = jnp.asarray(shard_vertex_data(x, layout.src_counts, plan.n_src_pad))

    def fn(x_shard, plan_shard):
        return collectives.halo_exchange(x_shard, plan_shard.halo, "graph")

    halo = np.asarray(spmd_apply(mesh8, fn, plan, xs))  # [W, W*S, F]
    W, S = plan.world_size, plan.halo.s_pad
    src_off = np.concatenate([[0], np.cumsum(layout.src_counts)])
    send_idx = np.asarray(plan.halo.send_idx)
    send_mask = np.asarray(plan.halo.send_mask)
    for r in range(W):
        for p in range(W):
            for i in range(S):
                if send_mask[p, r, i] > 0:
                    expected_vid = src_off[p] + send_idx[p, r, i]
                    assert halo[r, p * S + i, 0] == expected_vid


class TestPutFacade:
    """Communicator.put — the BackendEngine.put parity surface
    (Engine.py:67-86) — and the CommPattern one-sided offset vectors it
    subsumes (VERDICT r1 missing #7: untested beyond construction)."""

    def test_put_matches_halo_exchange(self):
        """put(x[send_idx] * mask) must equal halo_exchange(x): the halo
        exchange IS put with plan-precomputed offsets (haloExchange.py:37-64
        builds its send buffer exactly this way)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from dgraph_tpu.comm import Communicator
        from dgraph_tpu.comm.mesh import GRAPH_AXIS, make_graph_mesh, plan_in_specs, squeeze_plan
        from dgraph_tpu.plan import build_edge_plan

        rng = np.random.default_rng(5)
        W, V, E, F = 4, 64, 400, 8
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
        plan, _ = build_edge_plan(edges, part, world_size=W)
        x_global = rng.standard_normal((W, plan.n_src_pad, F)).astype(np.float32)
        comm = Communicator.init_process_group("tpu", world_size=W)
        mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])

        def body(x, plan_):
            p = squeeze_plan(plan_)
            xs = x[0]
            via_halo = comm.halo_exchange(xs, p.halo)
            send = xs[p.halo.send_idx] * p.halo.send_mask[..., None]
            via_put = comm.put(send)
            return via_halo, via_put

        with jax.set_mesh(mesh):
            got_h, got_p = jax.jit(
                jax.shard_map(
                    body, mesh=mesh,
                    in_specs=(P(GRAPH_AXIS), plan_in_specs(plan)),
                    out_specs=(P(GRAPH_AXIS), P(GRAPH_AXIS)),
                )
            )(jnp.asarray(x_global), jax.tree.map(jnp.asarray, plan))
        np.testing.assert_allclose(np.asarray(got_h), np.asarray(got_p))

    def test_put_remote_offsets_are_landing_positions(self):
        """BEHAVIORAL pin of the one-sided semantics (Engine.py:67-86):
        when every sender p writes its block into receiver r's unpadded
        recv stream at ``put_forward_remote_offset[r]`` (as computed ON p),
        the writes must tile the stream exactly — no gap, no overlap — in
        sender-rank order, i.e. produce the same layout the two-sided
        alltoallv (and our ``put``) delivers. Simulated write-side, NOT by
        re-deriving the construction formula."""
        from dgraph_tpu.plan import build_comm_pattern

        rng = np.random.default_rng(6)
        W, V, E = 4, 40, 200
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
        cps = [build_comm_pattern(edges, part, rank=r, world_size=W) for r in range(W)]
        comm_map = cps[0].comm_map
        for r in range(W):
            total = int(comm_map[:, r].sum())
            stream = np.full(total, -1, np.int64)
            for p in range(W):
                off = int(cps[p].put_forward_remote_offset[r])
                cnt = int(comm_map[p, r])
                assert np.all(stream[off : off + cnt] == -1), "overlapping writes"
                stream[off : off + cnt] = p
            # fully tiled, sender-rank order == recv_offsets order on r
            want = np.repeat(np.arange(W), comm_map[:, r])
            np.testing.assert_array_equal(stream, want)
        # backward offsets: the transposed exchange (grads return to the
        # sender) must tile each sender's stream the same way
        for p in range(W):
            total = int(comm_map[p, :].sum())
            stream = np.full(total, -1, np.int64)
            for r in range(W):
                off = int(cps[r].put_backward_remote_offset[p])
                cnt = int(comm_map[p, r])
                assert np.all(stream[off : off + cnt] == -1), "overlapping writes"
                stream[off : off + cnt] = r
            want = np.repeat(np.arange(W), comm_map[p, :])
            np.testing.assert_array_equal(stream, want)
