"""Autotuner tests: signature stability, record round-trip + adoption
rules, analytic ranking, measured-phase NaN guard, knob rejection, halo
lowering override sources, and the tier-1 CLI smoke.

Everything here is host-side numpy plus one subprocess (the compile-free
``--selftest``): the tier-1 suite is compile-dominated and near its
budget, so no test in this file may trigger a fresh XLA compile.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dgraph_tpu.data.synthetic import random_edges
from dgraph_tpu.tune import adopt as tune_adopt
from dgraph_tpu.tune.record import (
    TuningRecord,
    adopt_record,
    lookup_record,
    record_path,
)
from dgraph_tpu.tune.search import search
from dgraph_tpu.tune.signature import (
    degree_histogram,
    graph_signature,
    signature_key,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _isolated_tune_env(tmp_path, monkeypatch):
    """Point the default record dir at an empty tmp dir and clear any pin:
    a developer's real cache/plans records must not leak into assertions."""
    monkeypatch.setenv("DGRAPH_TUNE_DIR", str(tmp_path / "default_records"))
    monkeypatch.delenv("DGRAPH_TUNE_RECORD", raising=False)


@pytest.fixture(autouse=True)
def _reset_tuned_flags():
    from dgraph_tpu import config

    yield
    config.set_flags(tuned_halo_impl=None, tuning_record_id=None)


def _small_graph(seed=0, nodes=512, edges=2048):
    return random_edges(nodes, edges, seed=seed), nodes


# ---------------------------------------------------------------------------
# signatures
# ---------------------------------------------------------------------------


class TestSignature:
    def test_stable_across_calls(self):
        e, n = _small_graph()
        a = graph_signature(e, n, 4, dtype="bfloat16", feat_dim=64)
        b = graph_signature(e.copy(), n, 4, dtype="bfloat16", feat_dim=64)
        assert a == b
        assert signature_key(a) == signature_key(b)

    def test_renumbering_invariant(self):
        """Same graph under a vertex permutation and edge shuffle -> same
        signature (records must survive a re-load that renumbers)."""
        e, n = _small_graph()
        rng = np.random.default_rng(7)
        perm = rng.permutation(n)
        e2 = perm[e][:, rng.permutation(e.shape[1])]
        a = graph_signature(e, n, 2, dtype="float32", feat_dim=8)
        b = graph_signature(e2, n, 2, dtype="float32", feat_dim=8)
        assert a["degree_digest"] == b["degree_digest"]
        assert signature_key(a) == signature_key(b)

    def test_discriminates_workloads(self):
        e, n = _small_graph()
        base = graph_signature(e, n, 2, dtype="float32", feat_dim=8)
        keys = {
            signature_key(base),
            signature_key(graph_signature(e, n, 4, dtype="float32", feat_dim=8)),
            signature_key(graph_signature(e, n, 2, dtype="bfloat16", feat_dim=8)),
            signature_key(graph_signature(e, n, 2, dtype="float32", feat_dim=16)),
            signature_key(
                graph_signature(e[:, :-100], n, 2, dtype="float32", feat_dim=8)
            ),
        }
        assert len(keys) == 5

    def test_dtype_aliases_canonicalized(self):
        e, n = _small_graph()
        a = graph_signature(e, n, 2, dtype="bf16")
        b = graph_signature(e, n, 2, dtype="bfloat16")
        assert signature_key(a) == signature_key(b)

    def test_degree_histogram_counts(self):
        # star graph: hub degree n-1, leaves degree 1
        n = 9
        e = np.stack([np.zeros(n - 1, np.int64), np.arange(1, n)])
        hist = degree_histogram(e, n)
        assert hist.sum() == n
        assert hist[1] == n - 1  # leaves: degree 1 -> bucket 1
        assert hist[4] == 1  # hub: degree 8 -> bucket [8, 16)


# ---------------------------------------------------------------------------
# records: round-trip, lookup, adoption rules
# ---------------------------------------------------------------------------


def _make_record(sig):
    return TuningRecord.create(
        sig,
        {"partition_method": "rcm", "pad_multiple": 8,
         "halo_impl": "ppermute"},
        {"winner_us": 1.0, "default_us": 2.0},
        "analytic",
    )


class TestRecord:
    def test_roundtrip(self, tmp_path):
        e, n = _small_graph()
        sig = graph_signature(e, n, 2)
        rec = _make_record(sig)
        path = rec.save(str(tmp_path))
        assert path == record_path(str(tmp_path), sig)
        loaded = TuningRecord.load(path)
        assert loaded.record_id == rec.record_id
        assert loaded.config == rec.config
        assert loaded.signature == sig

    def test_validate_rejects_garbage(self):
        e, n = _small_graph()
        sig = graph_signature(e, n, 2)
        with pytest.raises(ValueError, match="phase"):
            TuningRecord.create(sig, {"pad_multiple": 8}, {"winner_us": 1}, "vibes")
        with pytest.raises(ValueError, match="pad_multiple"):
            TuningRecord.create(
                sig, {"pad_multiple": -3}, {"winner_us": 1}, "analytic"
            )
        with pytest.raises(ValueError, match="unknown config keys"):
            TuningRecord.create(
                sig, {"warp_speed": 9}, {"winner_us": 1}, "analytic"
            )
        # a partial or wrongly-typed serve dict must fail at validate time,
        # not as a KeyError/shape error deep in serving startup
        with pytest.raises(ValueError, match="serve config"):
            TuningRecord.create(
                sig, {"pad_multiple": 8, "serve": {"growth": 2.0}},
                {"winner_us": 1}, "analytic",
            )
        with pytest.raises(ValueError, match="serve config"):
            TuningRecord.create(
                sig,
                {"pad_multiple": 8,
                 "serve": {"min_bucket": 8.5, "max_bucket": 64,
                           "growth": 2.0}},
                {"winner_us": 1}, "analytic",
            )

    def test_lookup_hit_mismatch_and_corrupt(self, tmp_path):
        e, n = _small_graph()
        sig = graph_signature(e, n, 2)
        rec = _make_record(sig)
        rec.save(str(tmp_path))
        hit = lookup_record(sig, cache_dir=str(tmp_path))
        assert hit is not None and hit.record_id == rec.record_id
        # different workload -> miss (falls back to defaults, no error)
        other = graph_signature(e, n, 8)
        assert lookup_record(other, cache_dir=str(tmp_path)) is None
        # corrupt file -> logged miss, not a crash
        with open(record_path(str(tmp_path), sig), "w") as f:
            f.write("{truncated")
        assert lookup_record(sig, cache_dir=str(tmp_path)) is None

    def test_stored_signature_is_authoritative(self, tmp_path):
        """A record renamed onto another workload's key must not adopt."""
        e, n = _small_graph()
        sig = graph_signature(e, n, 2)
        other = graph_signature(e, n, 8)
        rec = _make_record(sig)
        os.makedirs(tmp_path, exist_ok=True)
        with open(record_path(str(tmp_path), other), "w") as f:
            json.dump(rec.to_dict(), f)
        assert lookup_record(other, cache_dir=str(tmp_path)) is None

    def test_env_pin_and_disable(self, tmp_path, monkeypatch):
        e, n = _small_graph()
        sig = graph_signature(e, n, 2)
        rec = _make_record(sig)
        path = rec.save(str(tmp_path))
        # disable beats an on-disk match
        monkeypatch.setenv("DGRAPH_TUNE_RECORD", "off")
        assert lookup_record(sig, cache_dir=str(tmp_path)) is None
        # pin adopts even for a non-matching signature (warned)
        monkeypatch.setenv("DGRAPH_TUNE_RECORD", path)
        other = graph_signature(e, n, 8)
        pinned = lookup_record(other, cache_dir="")
        assert pinned is not None and pinned.record_id == rec.record_id
        # unreadable pin degrades to disabled, not a crash
        monkeypatch.setenv("DGRAPH_TUNE_RECORD", str(tmp_path / "missing.json"))
        assert lookup_record(sig, cache_dir=str(tmp_path)) is None

    def test_adopt_sets_flags_and_returns_build_kwargs(self):
        from dgraph_tpu import config

        e, n = _small_graph()
        rec = _make_record(graph_signature(e, n, 2))
        kw = adopt_record(rec)
        assert kw == {"partition_method": "rcm", "pad_multiple": 8}
        assert config.tuned_halo_impl == "ppermute"
        assert config.tuning_record_id == rec.record_id


# ---------------------------------------------------------------------------
# halo lowering override sources
# ---------------------------------------------------------------------------


class TestResolveHaloImpl:
    def test_source_precedence(self):
        from dgraph_tpu import config
        from dgraph_tpu.plan import pick_halo_impl, resolve_halo_impl

        deltas = (1, 2, 3, 4, 5, 6, 7)
        saved = config.halo_impl
        try:
            config.set_flags(halo_impl="auto", tuned_halo_impl=None)
            impl, source = resolve_halo_impl(8, deltas)
            assert source == "heuristic"
            assert impl == pick_halo_impl(8, deltas)

            config.set_flags(tuned_halo_impl="ppermute")
            assert resolve_halo_impl(8, deltas) == ("ppermute", "record")

            # env/operator pin beats the record
            config.set_flags(halo_impl="all_to_all")
            assert resolve_halo_impl(8, deltas) == ("all_to_all", "env")

            # no traffic: nothing to choose, whatever the pins say
            assert resolve_halo_impl(8, ()) == ("none", "plan")
        finally:
            config.set_flags(halo_impl=saved, tuned_halo_impl=None)

    def test_plan_efficiency_reports_source(self):
        from dgraph_tpu import config
        from dgraph_tpu.plan import build_edge_plan, plan_efficiency

        e, n = _small_graph()
        from dgraph_tpu import partition as pt

        new_edges, ren = pt.partition_graph(e, n, 2, method="block")
        plan, layout = build_edge_plan(
            new_edges, ren.partition, world_size=2, pad_multiple=8
        )
        saved = config.halo_impl
        try:
            config.set_flags(halo_impl="auto", tuned_halo_impl=None)
            eff = plan_efficiency(plan, layout)
            assert eff["halo_impl_source"] == "heuristic"
            config.set_flags(tuned_halo_impl="all_to_all")
            eff = plan_efficiency(plan, layout)
            assert (eff["halo_impl"], eff["halo_impl_source"]) == (
                "all_to_all", "record",
            )
        finally:
            config.set_flags(halo_impl=saved, tuned_halo_impl=None)

    def test_footprint_reports_source(self):
        from dgraph_tpu import config
        from dgraph_tpu.obs.footprint import plan_footprint
        from dgraph_tpu.plan import build_edge_plan
        from dgraph_tpu import partition as pt

        e, n = _small_graph()
        new_edges, ren = pt.partition_graph(e, n, 2, method="block")
        plan, _ = build_edge_plan(
            new_edges, ren.partition, world_size=2, pad_multiple=8
        )
        saved = config.halo_impl
        try:
            config.set_flags(halo_impl="ppermute", tuned_halo_impl=None)
            fp = plan_footprint(plan, "float32", 8)
            ex = fp["collectives"]["halo_exchange"]
            assert (ex["impl"], ex["impl_source"]) == ("ppermute", "env")
        finally:
            config.set_flags(halo_impl=saved)


# ---------------------------------------------------------------------------
# build_edge_plan knob-compatibility rejection
# ---------------------------------------------------------------------------


class TestKnobRejection:
    def _build(self, nodes=512, edges=2048, **kw):
        from dgraph_tpu.plan import build_edge_plan

        e, n = _small_graph(nodes=nodes, edges=edges)
        part = np.minimum(np.arange(n) // (n // 2), 1).astype(np.int32)
        return build_edge_plan(e, part, world_size=2, **kw)

    def test_e_pad_vs_pad_multiple_named(self):
        with pytest.raises(ValueError) as ei:
            self._build(pad_multiple=8, e_pad=4098)
        assert "e_pad=4098" in str(ei.value)
        assert "pad_multiple=8" in str(ei.value)

    def test_kernel_scale_e_pad_vs_scatter_block_named(self):
        from dgraph_tpu.plan import SCATTER_BLOCK_E

        bad = SCATTER_BLOCK_E + 8  # pad_multiple-aligned but sub-block-off
        with pytest.raises(ValueError) as ei:
            self._build(pad_multiple=8, e_pad=bad)
        assert "scatter_block_e" in str(ei.value)

    def test_sub_block_e_pad_still_allowed(self):
        # hand-pinned tiny sub-block shapes (the test-plan idiom) must keep
        # working: below SCATTER_BLOCK_E the kernel alignment rule is off
        plan, _ = self._build(nodes=64, edges=128, pad_multiple=1, e_pad=300)
        assert plan.e_pad == 300

    def test_bad_pad_multiple_and_s_pad(self):
        with pytest.raises(ValueError, match="pad_multiple=0"):
            self._build(pad_multiple=0)
        with pytest.raises(ValueError, match="s_pad=9"):
            self._build(pad_multiple=8, s_pad=9)


# ---------------------------------------------------------------------------
# search: analytic ranking + measured phase (stubbed measure)
# ---------------------------------------------------------------------------


class TestSearch:
    def test_analytic_ranking_arxiv_shaped(self, tmp_path):
        """Scaled-down arxiv-shaped workload (uniform random, symmetrized):
        the analytic phase must rank every candidate with finite cost,
        best-first, and never place the winner above the defaults."""
        from dgraph_tpu.utils import ExperimentLog

        e, n = _small_graph(seed=3, nodes=1024, edges=4096)
        log = ExperimentLog(str(tmp_path / "trace.jsonl"), echo=False)
        result = search(
            e, n, 4, feat_dim=32, dtype="float32", budget_s=0.0,
            methods=("block", "random", "rcm"), pad_multiples=(8, 128),
            max_request=128, log=log, sweep_log="",
        )
        costs = [c for _, c in result.ranked]
        assert all(np.isfinite(c) and c > 0 for c in costs)
        assert costs == sorted(costs)
        assert result.record.phase == "analytic"
        assert (
            result.record.cost["winner_us"] <= result.record.cost["default_us"]
        )
        cfg = result.record.config
        assert cfg["partition_method"] in ("block", "random", "rcm")
        assert cfg["pad_multiple"] in (8, 128)
        assert cfg["halo_impl"] in ("none", "ppermute", "all_to_all", "overlap")
        assert cfg["serve"]["num_buckets"] >= 1
        # trace landed in the JSONL: one analytic row per candidate + result
        rows = [
            json.loads(l)
            for l in open(tmp_path / "trace.jsonl")
            if l.startswith("{")
        ]
        analytic = [r for r in rows if r.get("phase") == "analytic"]
        assert len(analytic) == 6  # 3 methods x 2 pads
        assert any(r.get("phase") == "result" for r in rows)

    def test_measured_phase_nan_guard(self):
        """A NaN measurement (crashed compile / tunnel noise) must never be
        crowned winner — the survivor with a finite time wins, and the
        record flips to phase='measured'."""
        e, n = _small_graph(seed=5)
        calls = []

        def fake_measure(plan, *, feat_dim, dtype, seed):
            calls.append(plan.e_pad)
            return float("nan") if len(calls) == 1 else 7.5

        result = search(
            e, n, 2, feat_dim=16, budget_s=60.0, top_k=2,
            measure_fn=fake_measure, methods=("block", "random"),
            pad_multiples=(8,), max_request=64, sweep_log="",
        )
        assert len(calls) == 2  # exactly top_k survivors timed
        assert result.record.phase == "measured"
        assert result.record.cost["measured_ms"] == 7.5
        # the winner is the candidate that measured finite, i.e. ranked #2
        assert result.record.config["partition_method"] == result.ranked[1][
            0
        ].split("/")[0]

    def test_measure_exception_is_contained(self):
        e, n = _small_graph(seed=6)

        def exploding_measure(plan, **kw):
            raise RuntimeError("mosaic went sideways")

        result = search(
            e, n, 2, feat_dim=16, budget_s=60.0, top_k=1,
            measure_fn=exploding_measure, methods=("block",),
            pad_multiples=(8,), max_request=64, sweep_log="",
        )
        # every measurement failed -> analytic ranking stands
        assert result.record.phase == "analytic"
        assert result.measured == {}

    def test_sweep_log_feeds_pallas_config(self, tmp_path):
        rows = [
            {"op": "segment_sum_pallas_default", "dtype": "bf16", "F": 128,
             "block_e": 1024, "block_n": 256, "ms": 2.0},
            {"op": "segment_sum_pallas_default", "dtype": "bf16", "F": 128,
             "block_e": 512, "block_n": 256, "ms": float("nan")},
            {"op": "segment_sum_xla", "dtype": "bf16", "F": 128, "ms": 3.0},
        ]
        path = tmp_path / "sweep.jsonl"
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        e, n = _small_graph(seed=8)
        result = search(
            e, n, 2, feat_dim=16, dtype="bfloat16", methods=("block",),
            pad_multiples=(8,), max_request=64, sweep_log=str(path),
        )
        cfg = result.record.config
        assert cfg["use_pallas_scatter"] is True  # 2.0 < 3.0
        assert (cfg["scatter_block_e"], cfg["scatter_block_n"]) == (1024, 256)

    def test_sweep_verdict_picks_nearest_feat_dim(self, tmp_path):
        """Verdicts at several widths: the one measured closest to the
        workload's feat_dim decides (a wide-row PALLAS win must not flip
        a narrow workload)."""
        rows = [
            {"op": "segment_sum_xla", "dtype": "f32", "F": 64, "ms": 2.0},
            {"op": "segment_sum_pallas_highest", "dtype": "f32", "F": 64,
             "ms": 3.0},  # XLA wins at 64
            {"op": "segment_sum_xla", "dtype": "f32", "F": 256, "ms": 4.0},
            {"op": "segment_sum_pallas_highest", "dtype": "f32", "F": 256,
             "ms": 1.0},  # PALLAS wins at 256
        ]
        path = tmp_path / "sweep.jsonl"
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        e, n = _small_graph(seed=9)
        result = search(
            e, n, 2, feat_dim=64, dtype="float32", methods=("block",),
            pad_multiples=(8,), max_request=64, sweep_log=str(path),
        )
        assert result.record.config["use_pallas_scatter"] is False

    def test_rejected_default_candidate_survives(self, monkeypatch):
        """The default candidate failing to build must not crash the
        search — the winner stands in as the cost baseline."""
        from dgraph_tpu import partition as pt

        real = pt.partition_graph

        def no_rcm(*a, **kw):
            if kw.get("method") == "rcm":
                raise ImportError("scipy unavailable (simulated)")
            return real(*a, **kw)

        monkeypatch.setattr(pt, "partition_graph", no_rcm)
        e, n = _small_graph(seed=10)
        result = search(
            e, n, 2, feat_dim=16, methods=("block", "rcm"),
            pad_multiples=(8,), max_request=64, sweep_log="",
        )
        assert result.record.cost["winner_us"] <= result.record.cost["default_us"]
        assert all(not k.startswith("rcm/") for k, _ in result.ranked)


# ---------------------------------------------------------------------------
# sweep winner-picking (folded from scripts/adopt_sweep.py)
# ---------------------------------------------------------------------------


class TestAdoptSweep:
    def test_nan_guard_in_winner_picking(self):
        rows = [
            {"op": "segment_sum_pallas_highest", "dtype": "f32", "F": 64,
             "block_e": 512, "block_n": 256, "ms": 4.0},
            # the NaN row would win a naive min() (x < nan is always False)
            {"op": "segment_sum_pallas_highest", "dtype": "f32", "F": 64,
             "block_e": 1024, "block_n": 256, "ms": float("nan")},
            {"op": "segment_sum_xla", "dtype": "f32", "F": 64, "ms": 3.0},
        ]
        report = tune_adopt.pick_winners(rows)
        key = ("segment_sum_pallas_highest", "f32", 64)
        assert report["winners"][key] == (512, 256)
        (v,) = report["verdicts"]
        assert v["verdict"] == "XLA"  # 4.0 (finite best) vs 3.0

    def test_thin_script_wrapper(self, tmp_path):
        """scripts/adopt_sweep.py keeps its CLI contract (and never imports
        the package / jax: it must run with the TPU lease in any state)."""
        rows = [
            {"op": "gather_sorted_pallas", "dtype": "bf16", "F": 32,
             "block_e": 512, "block_n": 256, "ms": 1.5},
            {"op": "gather_sorted_xla", "dtype": "bf16", "F": 32, "ms": 2.5},
        ]
        path = tmp_path / "kb.jsonl"
        with open(path, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        out = subprocess.run(
            [sys.executable, "scripts/adopt_sweep.py", str(path)],
            capture_output=True, text=True, timeout=120, cwd=REPO,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        assert out.returncode == 0, out.stdout + out.stderr
        assert "WINNER block_e=512" in out.stdout
        assert "use_pallas_gather" in out.stdout and "PALLAS" in out.stdout


# ---------------------------------------------------------------------------
# adoption end-to-end: from_global + serve health attribution
# ---------------------------------------------------------------------------


class TestAdoption:
    def test_from_global_adopts_matching_record(self, tmp_path):
        e, n = _small_graph(seed=11, nodes=300, edges=1200)
        feats = np.random.default_rng(0).normal(size=(n, 12)).astype(np.float32)
        # signed with the compute dtype from_global will look up under
        sig = graph_signature(e, n, 2, dtype="float32", feat_dim=12)
        rec = TuningRecord.create(
            sig,
            {"partition_method": "block", "pad_multiple": 128},
            {"winner_us": 1.0, "default_us": 2.0},
            "analytic",
        )
        rec.save(str(tmp_path))

        from dgraph_tpu.data.graph import DistributedGraph

        g = DistributedGraph.from_global(
            e, feats, None, None, world_size=2, plan_cache_dir=str(tmp_path)
        )
        assert g.tuning_record_id == rec.record_id
        # the record's knobs actually reached the build: pad_multiple=128
        # pads 150 local vertices to 256 (the default 8 would give 152),
        # and the block partition keeps the original contiguous numbering
        assert g.plan.n_src_pad == 256
        from dgraph_tpu.partition import block_partition

        np.testing.assert_array_equal(
            g.ren.partition, block_partition(n, 2)
        )

        # explicit caller choices suppress the lookup entirely
        g2 = DistributedGraph.from_global(
            e, feats, None, None, world_size=2,
            partition_method="block", pad_multiple=8,
            plan_cache_dir=str(tmp_path),
        )
        assert g2.tuning_record is None and g2.tuning_record_id is None

    def test_lookup_miss_clears_prior_adoption(self, tmp_path):
        """A graph with no record must not inherit the previous graph's
        adopted halo lowering (process-global flag hygiene)."""
        from dgraph_tpu import config
        from dgraph_tpu.data.graph import DistributedGraph

        e, n = _small_graph(seed=13, nodes=200, edges=800)
        rec = _make_record(graph_signature(e, n, 2))  # halo_impl=ppermute
        adopt_record(rec)
        assert config.tuned_halo_impl == "ppermute"
        feats = np.zeros((n, 4), np.float32)
        g = DistributedGraph.from_global(
            e, feats, None, None, world_size=2, plan_cache_dir=str(tmp_path)
        )
        assert g.tuning_record is None
        assert config.tuned_halo_impl is None
        assert config.tuning_record_id is None

        # ... and likewise when the lookup is SKIPPED (explicit knobs /
        # tune="off"), not just when it misses
        adopt_record(rec)
        DistributedGraph.from_global(
            e, feats, None, None, world_size=2,
            partition_method="block", pad_multiple=8,
        )
        assert config.tuned_halo_impl is None
        adopt_record(rec)
        DistributedGraph.from_global(
            e, feats, None, None, world_size=2, tune="off",
        )
        assert config.tuned_halo_impl is None

    def test_invalid_tune_arg_raises(self):
        from dgraph_tpu.data.graph import DistributedGraph

        e, n = _small_graph(nodes=64, edges=128)
        with pytest.raises(ValueError, match="tune must be"):
            DistributedGraph.from_global(
                e, np.zeros((n, 4), np.float32), None, None, world_size=2,
                tune="on",
            )

    def test_serve_health_carries_record_id(self):
        from dgraph_tpu.obs.metrics import Metrics
        from dgraph_tpu.serve.bucketing import BucketLadder
        from dgraph_tpu.serve.health import serve_health_record

        class _StubEngine:
            ladder = BucketLadder((8, 16))
            num_nodes = 100
            warmup_s = 0.5
            registry = Metrics()
            tuning_record_id = "tune-deadbeef-v1"

            def recompiles_since_warmup(self):
                return 0

        rec = serve_health_record(_StubEngine())
        assert rec["tuning_record"] == "tune-deadbeef-v1"
        delattr(_StubEngine, "tuning_record_id")
        rec = serve_health_record(_StubEngine())
        assert rec["tuning_record"] is None


# ---------------------------------------------------------------------------
# CLI smoke (tier-1: the whole tuner pipeline on every run, compile-free)
# ---------------------------------------------------------------------------


def test_tune_selftest_cli(tmp_path):
    out = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.tune", "--selftest", "true",
         "--log_path", str(tmp_path / "tune.jsonl")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert out.returncode == 0, out.stdout + out.stderr
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "tune_selftest"
    assert rec["failures"] == []
    assert rec["cost"]["winner_us"] <= rec["cost"]["default_us"]
    assert rec["run_health"]["error"] is None
    # the JSONL artifact carries the search trace + the health record
    rows = [
        json.loads(l)
        for l in open(tmp_path / "tune.jsonl")
        if l.startswith("{")
    ]
    assert any(r.get("kind") == "tune_trace" for r in rows)
    assert any(r.get("kind") == "tune_selftest" for r in rows)
