"""Megatron-style tensor-parallel MLP vs the dense oracle — values,
gradients, and a one-collective structural pin."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.parallel.tensor import (
    shard_columns,
    shard_rows,
    tensor_parallel_mlp,
)

W = 8
B, F, H = 4, 16, 64  # batch, features, hidden (H % W == 0)


def _weights(rng):
    w1 = rng.standard_normal((F, H)).astype(np.float32) * 0.3
    b1 = rng.standard_normal(H).astype(np.float32) * 0.1
    w2 = rng.standard_normal((H, F)).astype(np.float32) * 0.3
    b2 = rng.standard_normal(F).astype(np.float32) * 0.1
    return w1, b1, w2, b2


def _dense(x, w1, b1, w2, b2):
    return jax.nn.silu(x @ w1 + b1) @ w2 + b2


def _sharded_fn(mesh):
    def body(x, w1s, b1s, w2s, b2):
        return tensor_parallel_mlp(
            x, w1s[0], b1s[0], w2s[0], b2, "tensor"
        )

    return jax.shard_map(
        body, mesh=mesh,
        in_specs=(P(), P("tensor"), P("tensor"), P("tensor"), P()),
        out_specs=P(),
        check_vma=False,
    )


def _shards(w1, b1, w2):
    return (
        jnp.asarray(shard_columns(w1, W)),
        jnp.asarray(shard_columns(b1, W)),
        jnp.asarray(shard_rows(w2, W)),
    )


def test_tp_mlp_equals_dense(tensor_mesh8):
    mesh = tensor_mesh8
    rng = np.random.default_rng(0)
    w1, b1, w2, b2 = _weights(rng)
    x = jnp.asarray(rng.standard_normal((B, F)), jnp.float32)
    w1s, b1s, w2s = _shards(w1, b1, w2)
    got = _sharded_fn(mesh)(x, w1s, b1s, w2s, jnp.asarray(b2))
    want = _dense(x, jnp.asarray(w1), jnp.asarray(b1), jnp.asarray(w2),
                  jnp.asarray(b2))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_tp_mlp_gradients_equal_dense(tensor_mesh8):
    mesh = tensor_mesh8
    rng = np.random.default_rng(1)
    w1, b1, w2, b2 = _weights(rng)
    x = jnp.asarray(rng.standard_normal((B, F)), jnp.float32)
    tgt = jnp.asarray(rng.standard_normal((B, F)), jnp.float32)
    w1s, b1s, w2s = _shards(w1, b1, w2)
    fn = _sharded_fn(mesh)

    def loss_tp(x, w1s, b1s, w2s, b2):
        return ((fn(x, w1s, b1s, w2s, b2) - tgt) ** 2).sum()

    def loss_dense(x, w1, b1, w2, b2):
        return ((_dense(x, w1, b1, w2, b2) - tgt) ** 2).sum()

    gt = jax.grad(loss_tp, argnums=(0, 1, 2, 3, 4))(
        x, w1s, b1s, w2s, jnp.asarray(b2)
    )
    gd = jax.grad(loss_dense, argnums=(0, 1, 2, 3, 4))(
        x, jnp.asarray(w1), jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2)
    )
    # re-shard the dense grads to compare shard-for-shard
    gd_sharded = (
        gd[0],
        jnp.asarray(shard_columns(gd[1], W)),
        jnp.asarray(shard_columns(gd[2], W)),
        jnp.asarray(shard_rows(gd[3], W)),
        gd[4],
    )
    for a, b in zip(gt, gd_sharded):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5)


def test_single_forward_collective(tensor_mesh8):
    """Structural pin: exactly one psum in the forward shard_map body."""
    mesh = tensor_mesh8
    rng = np.random.default_rng(2)
    w1, b1, w2, b2 = _weights(rng)
    x = jnp.asarray(rng.standard_normal((B, F)), jnp.float32)
    w1s, b1s, w2s = _shards(w1, b1, w2)
    jaxpr = jax.make_jaxpr(_sharded_fn(mesh))(x, w1s, b1s, w2s, jnp.asarray(b2))
    body = [e for e in jaxpr.jaxpr.eqns if "shard_map" in e.primitive.name][0]
    inner = body.params["jaxpr"]
    inner = getattr(inner, "jaxpr", inner)
    n_psum = sum(1 for e in inner.eqns if "psum" in e.primitive.name)
    assert n_psum == 1, f"expected exactly 1 forward psum, found {n_psum}"
