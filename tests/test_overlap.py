"""Interior/boundary edge split + compute–communication-overlap halo
lowering.

Parity strategy: the overlap lowering (double-buffered ppermute rounds +
split interior/boundary aggregation) must be BIT-IDENTICAL to the padded
all_to_all path, forward and backward, on the 2- and 4-shard synthetic
graphs — same reduction operands, same term order (the overlap schedule
changes WHEN things run, never what is summed). Plan-level invariants and
the footprint's overlapped-schedule pricing are host-only (no compiles).
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from dgraph_tpu import config as cfg
from dgraph_tpu import plan as pl
from dgraph_tpu.comm import collectives
from dgraph_tpu.comm.mesh import make_graph_mesh
from dgraph_tpu.plan import shard_edge_data, shard_vertex_data
from dgraph_tpu.testing import spmd_apply


@pytest.fixture
def impl_flags():
    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    yield
    cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


def _case(rng, W, V=48, E=300):
    part = np.sort(rng.integers(0, W, V)).astype(np.int32)
    edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
    plan, layout = pl.build_edge_plan(edges, part, world_size=W, overlap=True)
    return edges, part, plan, layout


def _run_all(mesh, plan, xs, ed, ct_e, ct_v):
    """One jitted program per lowering: gather fwd+grad and halo-side
    scatter fwd+grad together (keeps the new-compile count low — the
    tier-1 budget rule)."""

    def everything(xs_, ed_):
        out_g = spmd_apply(
            mesh, collectives.gather, plan, xs_, static_args=("src", "graph")
        )
        g_g = jax.grad(
            lambda x: jnp.sum(
                spmd_apply(mesh, collectives.gather, plan, x,
                           static_args=("src", "graph")) * ct_e
            )
        )(xs_)
        out_s = spmd_apply(
            mesh, collectives.scatter_sum, plan, ed_,
            static_args=("src", "graph"),
        )
        g_s = jax.grad(
            lambda e: jnp.sum(
                spmd_apply(mesh, collectives.scatter_sum, plan, e,
                           static_args=("src", "graph")) * ct_v
            )
        )(ed_)
        return out_g, g_g, out_s, g_s

    with jax.set_mesh(mesh):
        return [np.asarray(a) for a in jax.jit(everything)(xs, ed)]


@pytest.mark.parametrize("W", [2, 4])
def test_overlap_bitwise_parity_with_all_to_all(rng, impl_flags, W):
    """halo_exchange_overlap / scatter_sum_overlap (through the gather and
    halo-side scatter they lower) are bit-identical to the all_to_all
    path, forward AND backward — the overlap schedule reorders execution,
    never the summed terms."""
    edges, part, plan, layout = _case(rng, W)
    V, F = len(part), 5
    xs = jnp.asarray(shard_vertex_data(
        rng.normal(size=(V, F)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    ))
    ed = jnp.asarray(shard_edge_data(
        rng.normal(size=(edges.shape[1], F)).astype(np.float32),
        layout, plan.e_pad,
    ))
    ct_e = jnp.asarray(shard_edge_data(
        rng.normal(size=(edges.shape[1], F)).astype(np.float32),
        layout, plan.e_pad,
    ))
    ct_v = jnp.asarray(shard_vertex_data(
        rng.normal(size=(V, F)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    ))
    mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])

    cfg.set_flags(halo_impl="overlap")
    got_ov = _run_all(mesh, plan, xs, ed, ct_e, ct_v)
    cfg.set_flags(halo_impl="all_to_all")
    got_a2a = _run_all(mesh, plan, xs, ed, ct_e, ct_v)
    for name, a, b in zip(
        ("gather fwd", "gather grad", "scatter fwd", "scatter grad"),
        got_ov, got_a2a,
    ):
        np.testing.assert_array_equal(a, b, err_msg=f"{name} not bit-identical")


def test_overlap_models_match_all_to_all(rng, impl_flags):
    """Model-level routing (GCN fused scatter_bias_relu_overlap + SAGE
    gather_scatter_overlap) agrees with the serial lowering — allclose,
    not bitwise: the interior/boundary split regroups the owner-side
    float accumulation."""
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.comm.mesh import GRAPH_AXIS, plan_in_specs, squeeze_plan
    from dgraph_tpu.models.gcn import GraphConvLayer
    from dgraph_tpu.models.sage import SAGEConv

    W, V, E, F = 2, 48, 300, 8
    edges, part, plan, layout = _case(rng, W, V, E)
    mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])
    comm = Communicator.init_process_group("tpu", world_size=W)
    xs = jnp.asarray(shard_vertex_data(
        rng.normal(size=(V, F)).astype(np.float32),
        layout.src_counts, plan.n_src_pad,
    ))
    modules = [
        GraphConvLayer(out_features=8, comm=comm),  # fused bias+relu path
        SAGEConv(out_features=8, comm=comm),  # identity-message path
    ]

    def run(module, impl):
        cfg.set_flags(halo_impl=impl)

        def body(x_, p_):
            psq = squeeze_plan(p_)
            params = module.init(jax.random.key(0), x_[0], psq)
            return module.apply(params, x_[0], psq)[None]

        f = jax.shard_map(
            body, mesh=mesh,
            in_specs=(P(GRAPH_AXIS), plan_in_specs(plan)),
            out_specs=P(GRAPH_AXIS),
        )
        with jax.set_mesh(mesh):
            return np.asarray(jax.jit(f)(xs, jax.tree.map(jnp.asarray, plan)))

    for module in modules:
        a = run(module, "overlap")
        b = run(module, "all_to_all")
        np.testing.assert_allclose(a, b, rtol=0, atol=1e-5)


# ---------------------------------------------------------------------------
# Host-only: plan invariants, resolution, footprint pricing (no compiles)
# ---------------------------------------------------------------------------


class TestOverlapPlan:
    def test_split_tiles_live_edges(self, rng):
        _, _, plan, _ = _case(rng, 4)
        ov = plan.overlap
        assert ov is not None
        counts = pl.interior_boundary_edge_counts(plan)
        np.testing.assert_array_equal(
            np.asarray(ov.num_interior), counts["interior_per_shard"]
        )
        np.testing.assert_array_equal(
            np.asarray(ov.num_interior) + np.asarray(ov.num_boundary),
            np.asarray(plan.num_edges),
        )
        pl.validate_plan(plan)  # all invariants hold on a fresh build

    def test_validate_rejects_corrupt_split(self, rng):
        _, _, plan, _ = _case(rng, 4)
        ov = plan.overlap
        # 1) interior referencing a halo slot
        bad_int = np.asarray(ov.side("interior", plan.halo_side)).copy()
        bad_int[0, 0] = plan.n_src_pad + 1  # halo slot on the halo side
        field = "int_src" if plan.halo_side == "src" else "int_dst"
        corrupt = dataclasses.replace(plan, overlap=dataclasses.replace(
            ov, **{field: bad_int}))
        with pytest.raises(ValueError, match="interior halo-side id"):
            pl.validate_plan(corrupt)
        # 2) boundary slot out of the halo buffer
        bfield = "bnd_src" if plan.halo_side == "src" else "bnd_dst"
        bad_bnd = np.asarray(getattr(ov, bfield)).copy()
        bad_bnd[0, 0] = plan.world_size * plan.halo.s_pad + 3
        corrupt = dataclasses.replace(plan, overlap=dataclasses.replace(
            ov, **{bfield: bad_bnd}))
        with pytest.raises(ValueError, match="boundary slot"):
            pl.validate_plan(corrupt)
        # 3) subset counts that no longer tile the edge set
        corrupt = dataclasses.replace(plan, overlap=dataclasses.replace(
            ov, num_interior=np.asarray(ov.num_interior) + 1))
        with pytest.raises(ValueError, match="int_mask count|tile"):
            pl.validate_plan(corrupt)

    def test_overlap_rejected_without_sorted_edges(self, rng):
        W, V = 2, 32
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([rng.integers(0, V, 64), rng.integers(0, V, 64)])
        with pytest.raises(ValueError, match="overlap=True conflicts"):
            pl.build_edge_plan(
                edges, part, world_size=W, overlap=True, sort_edges=False
            )

    def test_env_pin_builds_spec_and_resolves(self, rng, impl_flags):
        W, V = 2, 32
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([np.arange(V), (np.arange(V) + 1) % V])
        cfg.set_flags(halo_impl="overlap")
        plan, _ = pl.build_edge_plan(edges, part, world_size=W)  # auto
        assert plan.overlap is not None
        impl, source = pl.resolve_halo_impl(
            W, plan.halo_deltas, overlap_available=True)
        assert (impl, source) == ("overlap", "env")

    def test_resolution_degrades_without_spec(self, rng, impl_flags):
        """An 'overlap' pin on a plan with no split must fall back to a
        lowerable impl, never half-lower (mixed lowerings in one step)."""
        cfg.set_flags(halo_impl="overlap")
        # spec-less plan: overlap=False forces the split off despite the pin
        W, V = 2, 32
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        edges = np.stack([np.arange(V), (np.arange(V) + 1) % V])
        plan, _ = pl.build_edge_plan(edges, part, world_size=W, overlap=False)
        assert plan.overlap is None
        impl, source = pl.resolve_halo_impl(
            W, plan.halo_deltas, overlap_available=False)
        assert impl in ("ppermute", "all_to_all")
        assert source == "heuristic"


def test_footprint_arxiv_4shard_overlap_bytes(impl_flags):
    """Acceptance pin: on the arxiv-shaped 4-shard synthetic workload the
    resolved overlap exchange carries strictly fewer collective bytes than
    the padded full-halo all_to_all operand, and the overlapped schedule's
    exposed time never exceeds the serial rounds it replaces."""
    from dgraph_tpu import partition as pt
    from dgraph_tpu.data.synthetic import arxiv_shaped_edges
    from dgraph_tpu.obs.footprint import plan_footprint

    edge_index, num_nodes = arxiv_shaped_edges(0)
    new_edges, ren = pt.partition_graph(
        edge_index, num_nodes, 4, method="block", seed=0
    )
    plan, _ = pl.build_edge_plan(
        new_edges, ren.partition, world_size=4, pad_multiple=128, overlap=True
    )
    cfg.set_flags(halo_impl="auto", tuned_halo_impl=None)
    fp = plan_footprint(plan, "bfloat16", 128)
    ex = fp["collectives"]["halo_exchange"]
    assert ex["impl"] == "overlap"  # spec present -> heuristic adopts it
    # boundary-only rounds vs the padded [W*S, F] full-halo block
    assert ex["operand_bytes_per_shard"] < ex["a2a_operand_bytes_per_shard"]
    assert ex["ici_bytes_per_shard"] == ex["operand_bytes_per_shard"]
    ov = ex["overlap"]
    assert ov["rounds"] == len(plan.halo_deltas)
    assert ov["exposed_us"] <= ov["serial_us"]
    assert ov["hidden_us"] >= 0
    split = fp["edge_split"]
    assert 0 < split["boundary_frac"] < 1
    assert split["interior_frac"] + split["boundary_frac"] == pytest.approx(1.0)
    assert (
        split["interior_total"] + split["boundary_total"]
        == int(np.asarray(plan.num_edges).sum())
    )
    # the activation dtype flows into the runtime-buffer accounting
    assert fp["plan_memory"]["dtype_bytes"] == 2
