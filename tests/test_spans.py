"""Flight recorder (obs.spans) + CPU scan-delta attribution (obs.
attribution): ID propagation, Perfetto export schema, the disabled ==
one-attr-read no-op pin (zero new XLA compiles), the supervised-restart
lineage acceptance pin (both attempts under ONE trace id, valid Chrome
trace JSON), serve per-stage quantiles through a stub engine (zero
compiles), and one scan-delta attribution smoke on the smallest 2-shard
graph — the contracts docs/tracing.md documents."""

import ast
import json
import os
import sys

import numpy as np
import pytest

from dgraph_tpu.obs import spans

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _tracing_off():
    """Every test starts and ends with the default tracer disabled — an
    enabled global tracer leaking between tests would silently change
    other suites' hot paths."""
    spans.disable()
    yield
    spans.disable()


# ---------------------------------------------------------------------------
# core: IDs, propagation, disabled pin
# ---------------------------------------------------------------------------


def test_disabled_is_one_attr_read_noop(tmp_path):
    t = spans.Tracer()
    assert t.span("anything", x=1) is spans.NOOP_SPAN
    assert spans.span("anything") is spans.NOOP_SPAN  # module default too
    # the noop is inert end-to-end: context manager, annotate, end
    with spans.span("x") as s:
        s.annotate(a=1)
        s.end(error="ignored")
    assert not s and s.trace_id is None
    assert spans.current_trace_id() is None
    assert spans.child_env() == {}
    # and nothing was ever written anywhere (no default sink file)
    assert not (tmp_path / "spans.jsonl").exists()


def test_spans_module_is_jax_free_static_pin():
    """The supervisor and bench's standalone loader import spans.py on
    machines where any jax call can hang — pin (statically, so the pin
    holds even with jax preloaded by conftest) that the module never
    imports jax anywhere."""
    tree = ast.parse(open(os.path.join(
        REPO, "dgraph_tpu", "obs", "spans.py")).read())
    for node in ast.walk(tree):
        mods = []
        if isinstance(node, ast.Import):
            mods = [a.name for a in node.names]
        elif isinstance(node, ast.ImportFrom) and node.module:
            mods = [node.module]
        for m in mods:
            assert not (m == "jax" or m.startswith("jax.")), (
                f"spans.py imports {m!r}"
            )


def test_enabled_spans_zero_new_compiles():
    """Tracing around a jitted call must not grow its jit cache: spans are
    host-side only (the obs.metrics zero-overhead discipline, extended)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def f(x):
        return x * 2

    f(jnp.float32(1.0))
    f(jnp.float32(2.0))
    warm = f._cache_size() if hasattr(f, "_cache_size") else None
    recs = []
    spans.enable(sink=recs.append)
    with spans.span("jitted-call"):
        f(jnp.float32(3.0))
    spans.disable()
    f(jnp.float32(4.0))
    if warm is not None:
        assert f._cache_size() == warm, "span tracing caused a recompile"
    assert len(recs) == 1 and recs[0]["name"] == "jitted-call"


def test_id_propagation_and_schema():
    recs = []
    tid = spans.enable(sink=recs.append)
    assert spans.enabled() and spans.current_trace_id() == tid
    with spans.span("outer", component="test") as outer:
        assert spans.current_span() is outer
        with spans.span("inner") as inner:
            assert inner.parent_id == outer.span_id
        manual = spans.span("manual", parent=outer)
        manual.end(n=5)
        manual.end(n=99)  # idempotent: second end must not double-write
    assert spans.current_span() is None
    assert [r["name"] for r in recs] == ["inner", "manual", "outer"]
    for r in recs:
        assert r["kind"] == "span" and r["schema"] == 1
        assert r["trace"] == tid and r["dur_ms"] >= 0
        assert r["status"] == "ok" and r["pid"] == os.getpid()
    by_name = {r["name"]: r for r in recs}
    assert by_name["manual"]["attrs"]["n"] == 5
    assert by_name["outer"]["parent"] is None
    json.dumps(recs)  # JSONL-able as-is


def test_exception_marks_error_and_reraises():
    recs = []
    spans.enable(sink=recs.append)
    with pytest.raises(RuntimeError):
        with spans.span("boom"):
            raise RuntimeError("kapow")
    assert recs[0]["status"] == "error" and "kapow" in recs[0]["error"]


def test_child_env_cross_process_lineage():
    recs = []
    tid = spans.enable(sink=recs.append)
    with spans.span("parent") as p:
        env = spans.child_env()
    assert env[spans.ENV_TRACE_ID] == tid
    assert env[spans.ENV_PARENT] == p.span_id
    child = spans.Tracer()
    assert child.configure_from_env(env)
    child._set_sink(recs.append)
    child.span("child-root").end()
    assert recs[-1]["trace"] == tid and recs[-1]["parent"] == p.span_id
    # a process that inherits the id WITHOUT enabling still reports it
    # (the RunHealth trace_id fallback path)
    old = os.environ.get(spans.ENV_TRACE_ID)
    try:
        os.environ[spans.ENV_TRACE_ID] = "abc123"
        spans.disable()
        assert spans.current_trace_id() == "abc123"
    finally:
        if old is None:
            os.environ.pop(spans.ENV_TRACE_ID, None)
        else:
            os.environ[spans.ENV_TRACE_ID] = old


def test_run_health_carries_trace_id():
    from dgraph_tpu.obs.health import RunHealth

    spans.enable(sink=lambda r: None, trace_id="cafe0000cafe0000")
    h = RunHealth.begin("test.component").finish()
    assert h["trace_id"] == "cafe0000cafe0000"
    spans.disable()
    assert RunHealth.begin("test.component").finish()["trace_id"] is None


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------


def test_perfetto_export_schema(tmp_path):
    recs = []
    spans.enable(sink=recs.append)
    with spans.span("a", component="serve"):
        with spans.span("b"):
            pass
    out_path = str(tmp_path / "trace.json")
    trace = spans.export_perfetto(recs, out_path)
    # the file must load as valid Chrome trace JSON
    loaded = json.load(open(out_path))
    assert loaded == json.loads(json.dumps(trace))
    assert loaded["displayTimeUnit"] == "ms"
    xs = [e for e in loaded["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 2
    for e in xs:
        assert {"name", "cat", "ts", "dur", "pid", "tid", "args"} <= set(e)
        assert e["dur"] >= 0 and e["ts"] > 0
    # span/parent ids survive into args for trace reconstruction
    by_name = {e["name"]: e for e in xs}
    assert by_name["b"]["args"]["parent"] == by_name["a"]["args"]["span"]
    # metadata process_name events are present and well-formed
    assert any(e["ph"] == "M" and e["name"] == "process_name"
               for e in loaded["traceEvents"])


def test_perfetto_export_reads_jsonl_skipping_other_kinds(tmp_path):
    path = str(tmp_path / "log.jsonl")
    with open(path, "w") as fh:
        fh.write("# log opened\n")
        fh.write(json.dumps({"kind": "run_health", "wedge": "none"}) + "\n")
        fh.write(json.dumps({
            "kind": "span", "schema": 1, "trace": "t", "span": "s",
            "parent": None, "name": "x", "ts_unix": 1.0, "dur_ms": 2.0,
            "status": "ok", "pid": 1, "tid": 1,
        }) + "\n")
        fh.write("not json\n")
    trace = spans.export_perfetto(path)
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(xs) == 1 and xs[0]["name"] == "x"


# ---------------------------------------------------------------------------
# acceptance pin: supervised train run with one injected restart -> one
# trace, both attempts, valid Chrome trace JSON (no manual step)
# ---------------------------------------------------------------------------


def test_supervised_restart_one_trace_two_attempts(tmp_path):
    from dgraph_tpu.train.supervise import supervise

    log_path = str(tmp_path / "spans.jsonl")
    tid = spans.enable(sink=log_path)
    # child exits 17 (wedged) on attempt 0, cleanly on attempt 1 — the
    # injected-restart scenario, driven by the supervisor's own
    # DGRAPH_CHAOS_ATTEMPT export
    code = ("import os, sys; "
            "sys.exit(17 if os.environ['DGRAPH_CHAOS_ATTEMPT'] == '0' "
            "else 0)")
    try:
        lineage = supervise([sys.executable, "-c", code], backoff_s=0.01)
    finally:
        spans.disable()
    assert lineage["final_exit_code"] == 0 and lineage["restarts"] == 1
    # lineage is joinable: trace id + per-attempt span ids
    assert lineage["trace_id"] == tid
    span_ids = [a["span_id"] for a in lineage["attempts"]]
    assert len(span_ids) == 2 and all(span_ids)
    # the children inherited the trace env
    recs = spans.read_spans(log_path)
    attempts = [r for r in recs if r["name"] == "supervise.attempt"]
    assert len(attempts) == 2
    assert {r["span"] for r in attempts} == set(span_ids)
    assert all(r["trace"] == tid for r in recs)
    assert attempts[0]["status"] == "error"  # exit 17
    assert attempts[1]["status"] == "ok"
    run = [r for r in recs if r["name"] == "train.supervise"]
    assert len(run) == 1
    assert all(a["parent"] == run[0]["span"] for a in attempts)
    # Perfetto export loads as valid Chrome trace JSON with BOTH attempts
    # under one trace id — pinned here, no manual step
    out = str(tmp_path / "trace.perfetto.json")
    spans.export_perfetto(log_path, out)
    loaded = json.load(open(out))
    xs = [e for e in loaded["traceEvents"] if e["ph"] == "X"]
    assert sum(e["name"] == "supervise.attempt" for e in xs) == 2
    assert {e["args"]["trace"] for e in xs} == {tid}


def test_lineage_without_tracing_is_nullsafe():
    """Tracing off: the lineage schema still carries the (null) join keys
    and nothing else changes — schema 1 readers unaffected."""
    from dgraph_tpu.train.supervise import supervise

    lineage = supervise([sys.executable, "-c", "raise SystemExit(0)"],
                        backoff_s=0.01)
    assert lineage["trace_id"] is None
    assert lineage["attempts"][0]["span_id"] is None


# ---------------------------------------------------------------------------
# serve: per-stage quantiles + trace-id-surviving rejections (stub engine
# -> zero XLA compiles)
# ---------------------------------------------------------------------------


class _StubLadder:
    sizes = (8, 16)
    max_size = 16

    def bucket_for(self, n):
        from dgraph_tpu.serve.errors import RequestTooLarge

        if n > self.max_size:
            raise RequestTooLarge(f"request of {n} exceeds ladder")
        return 8 if n <= 8 else 16


class _StubEngine:
    """Just enough engine surface for the batcher + health record: infer
    returns zeros and stamps stage times like the real engine."""

    def __init__(self, registry):
        from dgraph_tpu.obs.metrics import Metrics

        self.ladder = _StubLadder()
        self.registry = registry or Metrics()
        self.num_nodes = 100
        self.warmup_s = 0.01
        self.degraded = False
        self.tuning_record_id = None

    def infer(self, ids):
        self.last_stage_ms = {"pad": 0.05, "infer": 0.2}
        self.registry.histogram("serve.stage.pad_ms", 0.05)
        self.registry.histogram("serve.stage.infer_ms", 0.2)
        return np.zeros((len(ids), 4), np.float32)

    def recompiles_since_warmup(self):
        return 0


def test_serve_stage_quantiles_and_request_spans():
    from dgraph_tpu.obs.metrics import Metrics
    from dgraph_tpu.serve.batcher import MicroBatcher
    from dgraph_tpu.serve.errors import QueueFull, RequestTooLarge
    from dgraph_tpu.serve.health import serve_health_record

    recs = []
    tid = spans.enable(sink=recs.append)
    reg = Metrics()
    engine = _StubEngine(reg)
    batcher = MicroBatcher(engine, max_batch_size=4, max_delay_ms=1.0,
                           max_queue_depth=8, registry=reg)
    try:
        for _ in range(6):
            out = batcher.infer(np.arange(3))
            assert out.shape == (3, 4)
        # a too-large request still lands an error-status span under the
        # SAME trace id (trace survives the rejection path)
        with pytest.raises(RequestTooLarge):
            batcher.submit(np.arange(40))
    finally:
        batcher.stop()
        spans.disable()

    req = [r for r in recs if r["name"] == "serve.request"]
    assert len(req) == 7
    assert all(r["trace"] == tid for r in recs)
    ok = [r for r in req if r["status"] == "ok"]
    assert len(ok) == 6
    # the request span carries the full stage breakdown
    for r in ok:
        a = r["attrs"]
        assert {"queue_wait_ms", "batch_form_ms", "pad_ms", "infer_ms",
                "reply_ms", "batch_size"} <= set(a)
    rejected = [r for r in req if r["status"] == "error"]
    assert len(rejected) == 1 and rejected[0]["error"] == "too_large"
    # batch spans exist and the engine stage numbers rode through
    assert any(r["name"] == "serve.batch" for r in recs)

    # per-stage p50/p95/p99 folded into the health record
    rec = serve_health_record(engine, batcher)
    stages = rec["stages_ms"]
    for stage in ("queue_wait", "batch_form", "pad", "infer", "reply"):
        assert stages[stage]["count"] > 0, stage
        assert {"p50", "p95", "p99"} <= set(stages[stage]), stage
    json.dumps(rec, default=str)

    # QueueFull shed (degraded) also ends the span with the trace intact
    recs2 = []
    spans.enable(sink=recs2.append, trace_id=tid)
    engine2 = _StubEngine(Metrics())
    batcher2 = MicroBatcher(engine2, max_queue_depth=8,
                            registry=engine2.registry)
    try:
        batcher2._stopped = True  # reject without racing the worker
        from dgraph_tpu.serve.errors import EngineStopped

        with pytest.raises(EngineStopped):
            batcher2.submit(np.arange(2))
    finally:
        batcher2._stopped = False
        batcher2.stop()
        spans.disable()
    errs = [r for r in recs2 if r["name"] == "serve.request"]
    assert errs and errs[0]["status"] == "error"
    assert errs[0]["trace"] == tid
    assert QueueFull  # imported for the API surface; shed path is above


def test_batcher_disabled_tracing_unchanged():
    """Tracing off: the batcher serves normally and writes no spans (the
    noop rides the _Pending record)."""
    from dgraph_tpu.obs.metrics import Metrics
    from dgraph_tpu.serve.batcher import MicroBatcher

    engine = _StubEngine(Metrics())
    batcher = MicroBatcher(engine, registry=engine.registry)
    try:
        out = batcher.infer(np.arange(5))
        assert out.shape == (5, 4)
    finally:
        batcher.stop()
    # stage histograms still populate (metrics are independent of spans)
    snap = engine.registry.snapshot()
    assert snap["histograms"]["serve.stage.queue_wait_ms"]["count"] == 1


# ---------------------------------------------------------------------------
# scan-delta attribution smoke (smallest 2-shard graph, one lowering,
# minimal scan lengths — compile budget guard)
# ---------------------------------------------------------------------------


def test_scan_delta_attribution_smoke(mesh8):
    from dgraph_tpu.obs.attribution import scan_delta_attribution

    # n_long=6: the per-round delta amortizes over 5 steps, which is what
    # keeps sub-ms CPU steps above dispatch jitter (n_long only changes
    # the scan's static length, not the compile count)
    rec = scan_delta_attribution(
        2, num_nodes=48, num_edges=200, feat_dim=8, hidden=8, num_classes=4,
        impls=("all_to_all",), n_long=6, reps=1, fold_multichip=True,
    )
    assert rec["kind"] == "cpu_scan_delta"
    assert rec["tier"] == "cpu_scan_delta" and rec["schema"] == 1
    assert rec["backend"] == "cpu"
    by = rec["by_impl"]["all_to_all"]
    phases = by["phases_ms"]
    assert set(phases) == {"interior", "exchange", "optimizer", "other"}
    # a smoke on CPU must at least land real positive full-step numbers;
    # phase terms are deltas and may individually be None only if the
    # timing protocol failed (which fails this assert via full_ms)
    assert by["full_ms"] is not None and by["full_ms"] > 0
    assert phases["interior"] is not None and phases["interior"] >= 0
    assert phases["exchange"] is not None and phases["exchange"] >= 0
    # schema-stable + strictly valid JSON (no NaN leaks)
    json.dumps(rec, allow_nan=False)
    # the MULTICHIP fold is present (table may be empty on old artifacts)
    mc = rec["multichip_dryrun"]
    assert mc is None or "step_ms_by_family" in mc


def test_multichip_family_table_parses_stamped_tail(tmp_path):
    from dgraph_tpu.obs.attribution import multichip_family_table

    with open(tmp_path / "MULTICHIP_r09.json", "w") as fh:
        json.dump({
            "n_devices": 8, "ok": True,
            "tail": ("dryrun GCN OK: mesh=(2x4) loss=1.44 "
                     "param_delta=8.680e-01 step_ms=123.4\n"
                     "dryrun RGAT OK: mesh=(1x8) loss=1.95 "
                     "param_delta=6.999e-01 step_ms=77.0\n"),
        }, fh)
    table = multichip_family_table(str(tmp_path))
    assert table["source"] == "MULTICHIP_r09.json"
    assert table["step_ms_by_family"] == {"GCN": 123.4, "RGAT": 77.0}
    assert multichip_family_table(str(tmp_path / "nowhere")) is None
