"""Chaos engineering layer (dgraph_tpu/chaos + train/supervise): spec
grammar, deterministic firing, fault-point wiring, the self-healing train
supervisor, and the end-to-end acceptance pin — an injected wedge at step
k makes the child exit 17, the supervisor restarts it, the child resumes
from the last checkpoint, and the final train state is BIT-IDENTICAL to a
fault-free run.

Everything here is compile-free (host-side state, fire-at-entry fault
points, python -c children) — the tier-1 suite is compile-dominated and
near its budget.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from dgraph_tpu import chaos
from dgraph_tpu.chaos import ChaosFault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "_chaos_worker.py")


@pytest.fixture(autouse=True)
def _chaos_clean():
    """Every test leaves the process on env-driven (inert) behavior."""
    yield
    chaos.reset()


# ---------------------------------------------------------------------------
# spec grammar + firing semantics
# ---------------------------------------------------------------------------


def test_parse_spec_clauses():
    cl = chaos.parse_spec(
        "step=wedge@3:sleep_s=60:attempt=0;grads=poison@5:count=2"
    )
    assert len(cl) == 2
    assert cl[0].point == "step" and cl[0].action == "wedge"
    assert cl[0].index == 3 and cl[0].sleep_s == 60.0 and cl[0].attempt == 0
    assert cl[1].point == "grads" and cl[1].count == 2


@pytest.mark.parametrize(
    "bad",
    [
        "",
        "nonsense",
        "unknown.point=raise@0",
        "step=explode@0",
        "step=raise@-1",
        "step=raise@1.5",
        "step=raise@0:count=0",
        "step=raise@0:prob=2.0",
        "step=raise@0:mystery=1",
    ],
)
def test_parse_spec_rejects(bad):
    with pytest.raises(ValueError):
        chaos.parse_spec(bad)


def test_fire_is_inert_by_default():
    chaos.disarm()
    assert chaos.fire("step") is False
    assert chaos.active_spec() is None
    assert chaos.snapshot()["spec"] is None


def test_fire_exact_call_index_and_counter():
    chaos.arm("ckpt.save=raise@2")
    fired = []
    for i in range(4):
        try:
            chaos.fire("ckpt.save")
        except ChaosFault as e:
            fired.append(i)
            assert e.point == "ckpt.save" and e.index == 2
            assert e.record()["kind"] == "chaos_fault"
    assert fired == [2]
    assert chaos.call_count("ckpt.save") == 4


def test_fire_external_index_and_count_window():
    chaos.arm("grads=poison@5:count=2")
    got = [s for s in range(10) if chaos.fire("grads", index=s)]
    assert got == [5, 6]


def test_attempt_gating():
    # the supervisor exports the restart ordinal; a clause pinned to
    # attempt 0 must not re-fire on the resumed attempt
    chaos.arm("step=raise@1:attempt=0", attempt=1)
    for s in range(4):
        chaos.fire("step", index=s)  # no raise
    chaos.arm("step=raise@1:attempt=1", attempt=1)
    with pytest.raises(ChaosFault):
        for s in range(4):
            chaos.fire("step", index=s)


def test_prob_schedule_deterministic():
    def schedule():
        chaos.arm("grads=poison@0:prob=0.5:seed=11")
        return [s for s in range(64) if chaos.fire("grads", index=s)]

    a, b = schedule(), schedule()
    assert a == b and 0 < len(a) < 64


def test_env_var_arming(monkeypatch):
    monkeypatch.setenv(chaos.ENV_VAR, "grads=poison@1")
    chaos.reset()
    assert chaos.active_spec() == "grads=poison@1"
    assert not chaos.fire("grads", index=0)
    assert chaos.fire("grads", index=1)
    monkeypatch.delenv(chaos.ENV_VAR)
    chaos.reset()
    assert chaos.active_spec() is None


def test_poison_helpers():
    x = chaos.poison_array(np.ones(4, np.float32))
    assert np.isnan(x[0]) and np.all(x[1:] == 1.0)
    y = chaos.poison_array(np.arange(3))  # int arrays pass through
    assert np.array_equal(y, np.arange(3))
    tree = chaos.poison_pytree({"x": np.ones((2, 2)), "y": np.arange(2)})
    assert np.isnan(tree["x"][0, 0]) and tree["y"][0] == 0


def test_unknown_point_rejected_when_armed():
    chaos.arm("step=raise@0")
    with pytest.raises(ValueError):
        chaos.fire("not.a.point")


def test_rank_gating():
    # the group supervisor exports DGRAPH_RANK; a clause pinned to rank 2
    # must not fire on any other member (the one-member-kill spec the
    # shrink acceptance test arms group-wide)
    chaos.arm("step=raise@1:rank=2", rank=0)
    for s in range(4):
        chaos.fire("step", index=s)  # no raise
    chaos.arm("step=raise@1:rank=2", rank=2)
    with pytest.raises(ChaosFault):
        for s in range(4):
            chaos.fire("step", index=s)


def test_delay_action_seeded_jitter(monkeypatch):
    # 'delay' sleeps a seeded uniform jitter in [0, sleep_s): the injected
    # straggler. Deterministic per seed; sleep_s defaults small (a
    # wedge-scale default would be a wedge, not a straggle)
    (cl,) = chaos.parse_spec("comm.heartbeat=delay@0")
    assert cl.sleep_s == chaos.DEFAULT_DELAY_SLEEP_S

    def schedule():
        slept = []
        monkeypatch.setattr(chaos.time, "sleep", slept.append)
        chaos.arm("comm.heartbeat=delay@0:count=6:sleep_s=0.4:seed=9")
        for i in range(6):
            assert chaos.fire("comm.heartbeat", index=i) is False
        return slept

    a, b = schedule(), schedule()
    assert len(a) == 6 and a == b
    assert all(0.0 <= s < 0.4 for s in a)
    assert len(set(a)) > 1  # jitter, not a constant


# ---------------------------------------------------------------------------
# fault-point wiring (fire-at-entry: no orbax/plan work needed)
# ---------------------------------------------------------------------------


def test_ckpt_save_point_fires(tmp_path):
    from dgraph_tpu.train.checkpoint import save_checkpoint

    chaos.arm("ckpt.save=raise@0")
    with pytest.raises(ChaosFault):
        save_checkpoint(str(tmp_path), {"w": np.zeros(2)}, 1)


def test_ckpt_read_point_fires(tmp_path):
    from dgraph_tpu.train.checkpoint import restore_checkpoint

    chaos.arm("ckpt.read=raise@0")
    with pytest.raises(ChaosFault):
        restore_checkpoint(str(tmp_path))


def test_data_load_point_fires():
    from dgraph_tpu.data import DistributedGraph

    chaos.arm("data.load=raise@0")
    with pytest.raises(ChaosFault):
        DistributedGraph.from_global(
            np.zeros((2, 0), np.int64), np.zeros((4, 2), np.float32),
            None, None, world_size=2,
        )


def test_runhealth_env_snapshot_records_spec():
    from dgraph_tpu.obs.health import RunHealth

    chaos.arm("step=raise@9")
    assert RunHealth.begin("t").env["chaos"] == "step=raise@9"
    chaos.disarm()
    assert RunHealth.begin("t").env["chaos"] is None


# ---------------------------------------------------------------------------
# supervisor (in-process; python -c children)
# ---------------------------------------------------------------------------


def _pyc(code: str) -> list:
    return [sys.executable, "-c", code]


def test_supervisor_success_first_try():
    from dgraph_tpu.train.supervise import supervise

    lineage = supervise(_pyc("import sys; sys.exit(0)"), backoff_s=0.01)
    assert lineage["kind"] == "supervise_lineage"
    assert lineage["final_exit_code"] == 0 and lineage["restarts"] == 0
    assert lineage["attempts"][0]["outcome"] == "ok"
    assert lineage["run_health"]["wedge"] == "none"
    json.dumps(lineage)


def test_supervisor_restarts_on_wedge_then_succeeds():
    from dgraph_tpu.train.supervise import supervise

    code = (
        "import os, sys; "
        "sys.exit(17 if os.environ['DGRAPH_CHAOS_ATTEMPT'] == '0' else 0)"
    )
    lineage = supervise(_pyc(code), backoff_s=0.01)
    assert lineage["final_exit_code"] == 0 and lineage["restarts"] == 1
    assert [a["outcome"] for a in lineage["attempts"]] == ["wedged", "ok"]
    assert lineage["attempts"][0]["exit_code"] == 17
    # backoff applied before the restart, none before the first attempt
    assert lineage["attempts"][0]["backoff_s"] == 0.0
    assert lineage["attempts"][1]["backoff_s"] > 0.0


def test_supervisor_budget_exhaustion_and_backoff_growth():
    from dgraph_tpu.train.supervise import supervise

    sleeps = []
    lineage = supervise(
        _pyc("import sys; sys.exit(7)"),
        max_restarts=3, backoff_s=1.0, backoff_factor=2.0, backoff_max_s=3.0,
        _sleep=sleeps.append,
    )
    assert lineage["gave_up"] and lineage["final_exit_code"] == 7
    assert lineage["restarts"] == 3
    assert all(a["outcome"] == "crashed" for a in lineage["attempts"])
    # exponential, capped: 1, 2, then clamped to 3
    assert sleeps == [1.0, 2.0, 3.0]
    assert lineage["run_health"]["wedge"] == "stage_failure"
    assert "restart budget" in lineage["run_health"]["error"]


def test_supervisor_wall_budget_fail_fast():
    # budget_s is the overall fail-fast wall budget (bench's
    # --probe-budget-s runs through here): once elapsed + the next backoff
    # would cross it, the supervisor stops restarting instead of burning
    # its whole restart budget against a wedge
    from dgraph_tpu.train.supervise import supervise

    import time as _time

    t0 = _time.monotonic()
    lineage = supervise(
        _pyc("import sys; sys.exit(17)"),
        max_restarts=50, backoff_s=0.3, backoff_factor=1.0,
        budget_s=1.0,
    )
    assert _time.monotonic() - t0 < 10
    assert lineage["budget_exhausted"] and lineage["gave_up"]
    assert lineage["final_exit_code"] == 17
    assert len(lineage["attempts"]) < 50
    assert "wall budget" in lineage["run_health"]["error"]


def test_supervisor_budget_clamps_attempt_timeout():
    # a child that would outlive the budget is killed when the remaining
    # window expires, even with no attempt_timeout_s configured
    from dgraph_tpu.train.supervise import supervise

    import time as _time

    t0 = _time.monotonic()
    lineage = supervise(
        _pyc("import time; time.sleep(60)"), max_restarts=3,
        backoff_s=0.05, budget_s=1.5,
    )
    assert _time.monotonic() - t0 < 15
    assert lineage["attempts"][0]["outcome"] == "timeout"
    assert lineage["budget_exhausted"]


def test_supervisor_stderr_capture_truncates_per_attempt(tmp_path):
    # native-code deaths leave no Python-side error sidecar; the captured
    # stderr tail is the only diagnostic — it must hold the LAST
    # attempt's output only (a stale tail must not mislabel)
    from dgraph_tpu.train.supervise import supervise

    errf = tmp_path / "probe.stderr"
    code = (
        "import os, sys; a = os.environ['DGRAPH_CHAOS_ATTEMPT']; "
        "print('attempt', a, 'diag', file=sys.stderr); "
        "sys.exit(17 if a == '0' else 0)"
    )
    tails = []

    def on_attempt(rec):
        tails.append(errf.read_text().strip())

    lineage = supervise(
        _pyc(code), backoff_s=0.01, stderr_path=str(errf),
        on_attempt=on_attempt,
    )
    assert lineage["final_exit_code"] == 0
    assert tails == ["attempt 0 diag", "attempt 1 diag"]


def test_supervisor_spawn_and_attempt_callbacks():
    from dgraph_tpu.train.supervise import supervise

    procs, recs = [], []
    lineage = supervise(
        _pyc("import os, sys; "
             "sys.exit(17 if os.environ['DGRAPH_CHAOS_ATTEMPT'] == '0' "
             "else 0)"),
        backoff_s=0.01, on_spawn=procs.append, on_attempt=recs.append,
    )
    assert len(procs) == 2 and all(p.poll() is not None for p in procs)
    assert recs == lineage["attempts"]
    assert not lineage["budget_exhausted"]


def test_supervisor_no_restart_on_crash_when_disabled():
    from dgraph_tpu.train.supervise import supervise

    lineage = supervise(
        _pyc("import sys; sys.exit(7)"), restart_on_crash=False,
        backoff_s=0.01,
    )
    assert lineage["final_exit_code"] == 7 and lineage["restarts"] == 0
    assert not lineage["gave_up"]  # stopped by policy, not budget


# membership's selftest fake clock (sleep advances it) — one
# implementation shared by every fake-clock test in the repo
from dgraph_tpu.comm.membership import _FakeClock  # noqa: E402


def test_supervisor_exact_backoff_schedule_fake_clock():
    # the EXACT backoff/cap/budget-clamp schedule, no real sleeps: the
    # injectable monotonic clock advances only through the injected sleep
    from dgraph_tpu.train.supervise import supervise

    fc = _FakeClock()
    sleeps = []

    def fsleep(s):
        sleeps.append(s)
        fc.sleep(s)

    lineage = supervise(
        _pyc("import sys; sys.exit(7)"),
        max_restarts=10, backoff_s=1.0, backoff_factor=2.0,
        backoff_max_s=8.0, budget_s=12.0, _sleep=fsleep, _clock=fc,
    )
    # exponential 1, 2, 4; the next delay (8) would land at 7 + 8 = 15
    # >= 12, so the budget stops the restart loop BEFORE sleeping it
    assert sleeps == [1.0, 2.0, 4.0]
    assert len(lineage["attempts"]) == 4
    assert lineage["budget_exhausted"] and lineage["gave_up"]
    assert [a["backoff_s"] for a in lineage["attempts"]] == [
        0.0, 1.0, 2.0, 4.0
    ]
    assert "wall budget" in lineage["run_health"]["error"]


def test_supervisor_backoff_cap_fake_clock():
    from dgraph_tpu.train.supervise import supervise

    fc = _FakeClock()
    sleeps = []

    def fsleep(s):
        sleeps.append(s)
        fc.sleep(s)

    lineage = supervise(
        _pyc("import sys; sys.exit(7)"),
        max_restarts=5, backoff_s=1.0, backoff_factor=3.0,
        backoff_max_s=5.0, _sleep=fsleep, _clock=fc,
    )
    # exponential then clamped at the cap, full restart budget spent
    assert sleeps == [1.0, 3.0, 5.0, 5.0, 5.0]
    assert lineage["gave_up"] and not lineage["budget_exhausted"]


def test_supervisor_attempt_timeout_counts_as_wedge():
    from dgraph_tpu.train.supervise import supervise

    code = (
        "import os, sys, time; "
        "time.sleep(60 if os.environ['DGRAPH_CHAOS_ATTEMPT'] == '0' else 0)"
    )
    lineage = supervise(
        _pyc(code), attempt_timeout_s=1.0, backoff_s=0.01,
    )
    assert [a["outcome"] for a in lineage["attempts"]] == ["timeout", "ok"]
    assert lineage["attempts"][0]["exit_code"] == 17
    assert lineage["final_exit_code"] == 0


# ---------------------------------------------------------------------------
# multi-rank group supervision (python -c children; the full rank-kill
# acceptance path lives in tests/test_shrink.py)
# ---------------------------------------------------------------------------


def test_group_all_ok_single_attempt():
    from dgraph_tpu.train.supervise import supervise_group

    lineage = supervise_group(
        lambda r, w, a: _pyc("import sys; sys.exit(0)"), 3, backoff_s=0.01,
    )
    assert lineage["kind"] == "supervise_group_lineage"
    assert lineage["final_exit_code"] == 0 and lineage["restarts"] == 0
    assert lineage["final_world_size"] == 3
    a0 = lineage["attempts"][0]
    assert [x["outcome"] for x in a0["ranks"]] == ["ok"] * 3
    assert [x["rank"] for x in a0["ranks"]] == [0, 1, 2]
    json.dumps(lineage)


def test_group_wedge_triggers_collective_restart():
    # one rank exits 17: the still-running peers are killed (aborted) and
    # the WHOLE group relaunches at the same world size
    from dgraph_tpu.train.supervise import supervise_group

    code = (
        "import os, sys, time; "
        "a = os.environ['DGRAPH_CHAOS_ATTEMPT']; "
        "r = os.environ['DGRAPH_RANK']; "
        "assert os.environ['DGRAPH_WORLD_SIZE'] == '3'; "
        "sys.exit(17) if (a == '0' and r == '1') else "
        "(time.sleep(30) if a == '0' else None); sys.exit(0)"
    )
    lineage = supervise_group(
        lambda r, w, a: _pyc(code), 3, backoff_s=0.01,
    )
    assert lineage["final_exit_code"] == 0 and lineage["restarts"] == 1
    a0, a1 = lineage["attempts"]
    assert a0["outcome"] == "wedged" and a0["world_size"] == 3
    outs = {x["rank"]: x["outcome"] for x in a0["ranks"]}
    assert outs[1] == "wedged"
    assert set(outs.values()) == {"wedged", "aborted"}
    assert a1["outcome"] == "ok" and a1["world_size"] == 3
    assert lineage["shrinks"] == []


def test_group_rank_loss_shrinks_via_callback():
    # a crashed rank plus a 19-exiting survivor is a rank loss: the
    # recovery callback picks the new world and the group relaunches
    # renumbered 0..W'-1
    from dgraph_tpu.train.supervise import supervise_group

    code = (
        "import os, sys, time; "
        "a = os.environ['DGRAPH_CHAOS_ATTEMPT']; "
        "r = os.environ['DGRAPH_RANK']; "
        "w = os.environ['DGRAPH_WORLD_SIZE']\n"
        "if a == '0' and r == '2': sys.exit(70)\n"
        "if a == '0': time.sleep(0.2); sys.exit(19)\n"
        "assert w == '2', w\n"
        "sys.exit(0)"
    )
    calls = []

    def on_rank_loss(lost, world):
        calls.append((lost, world))
        return world - len(lost)

    lineage = supervise_group(
        lambda r, w, a: _pyc(code), 3, backoff_s=0.01,
        rank_loss_grace_s=30.0, on_rank_loss=on_rank_loss,
    )
    assert lineage["final_exit_code"] == 0, lineage
    assert calls == [([2], 3)]
    assert lineage["final_world_size"] == 2
    assert lineage["shrinks"] == [
        {"attempt": 0, "lost": [2], "old_world": 3, "new_world": 2}
    ]
    a0 = lineage["attempts"][0]
    outs = {x["rank"]: x["outcome"] for x in a0["ranks"]}
    assert outs[2] == "crashed"
    assert outs[0] == outs[1] == "rank_lost"
    assert a0["shrink"]["new_world"] == 2


def test_group_zombie_rank_killed_after_reporter_quorum():
    # the zombie case: a rank's PROCESS outlives its lease (dead
    # heartbeat thread, storage partition) so it never exits — once every
    # remaining peer has exited 19, the grace window starts and the
    # zombie is killed and counted LOST (waiting on it forever would hang
    # the shrink its peers asked for)
    from dgraph_tpu.train.supervise import supervise_group

    code = (
        "import os, sys, time; r = os.environ['DGRAPH_RANK']; "
        "a = os.environ['DGRAPH_CHAOS_ATTEMPT']\n"
        "if a == '0' and r == '1': time.sleep(120)\n"
        "if a == '0': sys.exit(19)\n"
        "sys.exit(0)"
    )
    losses = []
    lineage = supervise_group(
        lambda r, w, a: _pyc(code), 2, backoff_s=0.01,
        rank_loss_grace_s=1.0,
        on_rank_loss=lambda lost, w: (losses.append((lost, w)),
                                      w - len(lost))[-1],
    )
    assert lineage["final_exit_code"] == 0, lineage
    assert losses == [([1], 2)]
    a0 = lineage["attempts"][0]
    outs = {x["rank"]: x["outcome"] for x in a0["ranks"]}
    assert outs[0] == "rank_lost" and outs[1] == "aborted"
    assert lineage["final_world_size"] == 1


def test_group_rank_loss_without_shrink_path_stops():
    from dgraph_tpu.train.supervise import supervise_group

    code = (
        "import os, sys, time; r = os.environ['DGRAPH_RANK']\n"
        "if r == '1': sys.exit(70)\n"
        "time.sleep(0.2); sys.exit(19)\n"
    )
    lineage = supervise_group(
        lambda r, w, a: _pyc(code), 2, backoff_s=0.01,
        rank_loss_grace_s=30.0,
    )
    assert lineage["final_exit_code"] == 19
    assert lineage["stopped_on_rank_loss"] and not lineage["gave_up"]
    assert "stopped on rank loss" in lineage["run_health"]["error"]


def test_group_plain_crash_restarts_same_world():
    # no survivor exits 19: a crash is a crash — same-world restart
    from dgraph_tpu.train.supervise import supervise_group

    code = (
        "import os, sys; "
        "sys.exit(7 if os.environ['DGRAPH_CHAOS_ATTEMPT'] == '0' else 0)"
    )
    lineage = supervise_group(
        lambda r, w, a: _pyc(code), 2, backoff_s=0.01,
        rank_loss_grace_s=0.2,
        on_rank_loss=lambda lost, w: pytest.fail("not a rank loss"),
    )
    assert lineage["final_exit_code"] == 0 and lineage["restarts"] == 1
    assert lineage["attempts"][0]["outcome"] == "crashed"
    assert lineage["final_world_size"] == 2


def test_group_per_rank_stderr_capture(tmp_path):
    from dgraph_tpu.train.supervise import supervise_group

    code = (
        "import os, sys; "
        "print('rank', os.environ['DGRAPH_RANK'], 'diag', file=sys.stderr)"
    )
    lineage = supervise_group(
        lambda r, w, a: _pyc(code), 2, backoff_s=0.01,
        stderr_path=str(tmp_path / "probe.stderr"),
    )
    assert lineage["final_exit_code"] == 0
    for r in range(2):
        tail = (tmp_path / f"probe.stderr.rank{r}").read_text().strip()
        assert tail == f"rank {r} diag"


def test_group_shared_wall_budget_fail_fast():
    import time as _time

    from dgraph_tpu.train.supervise import supervise_group

    t0 = _time.monotonic()
    lineage = supervise_group(
        lambda r, w, a: _pyc("import sys; sys.exit(17)"), 2,
        max_restarts=50, backoff_s=0.3, backoff_factor=1.0, budget_s=1.0,
    )
    assert _time.monotonic() - t0 < 15
    assert lineage["budget_exhausted"] and lineage["gave_up"]
    assert lineage["final_exit_code"] == 17
    assert len(lineage["attempts"]) < 50


# ---------------------------------------------------------------------------
# CLI selftest (tier-1 registration) + end-to-end recovery
# ---------------------------------------------------------------------------


def test_chaos_selftest_cli():
    r = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.chaos", "--selftest", "true"],
        capture_output=True, text=True, timeout=180, cwd=REPO,
    )
    assert r.returncode == 0, (r.stdout[-800:], r.stderr[-800:])
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "chaos_selftest" and rec["failures"] == []
    assert rec["run_health"]["wedge"] == "none"


def _run_worker_supervised(ckpt_dir, steps, log_path, extra_env):
    env = dict(os.environ)
    env.pop("DGRAPH_CHAOS", None)
    env.update(extra_env)
    cmd = [
        sys.executable, "-m", "dgraph_tpu.train.supervise",
        "--cmd", f"{sys.executable} {WORKER} {ckpt_dir} {steps}",
        "--max_restarts", "2",
        "--backoff_s", "0.05",
        "--ckpt_dir", str(ckpt_dir),
        "--log_path", str(log_path),
    ]
    r = subprocess.run(
        cmd, capture_output=True, text=True, timeout=300, cwd=REPO, env=env,
    )
    assert r.returncode == 0, (r.returncode, r.stdout[-1500:], r.stderr[-1500:])
    return json.loads(r.stdout.strip().splitlines()[-1])


def test_e2e_wedge_restart_resume_bit_identical(tmp_path):
    """THE acceptance pin: wedge injected at global step 4 on attempt 0 ->
    watchdog exits 17 -> supervisor restarts -> worker resumes from the
    last checkpoint -> final state bit-identical to a fault-free run."""
    from dgraph_tpu.train.checkpoint import restore_checkpoint

    steps = 6
    # fault-free oracle run (same worker, no chaos, no restarts)
    clean_ckpt = tmp_path / "clean"
    lineage = _run_worker_supervised(
        clean_ckpt, steps, tmp_path / "clean.jsonl", {},
    )
    assert lineage["restarts"] == 0 and lineage["final_step"] == steps

    # chaotic run: wedge at step 4, first attempt only
    chaotic_ckpt = tmp_path / "chaotic"
    lineage = _run_worker_supervised(
        chaotic_ckpt, steps, tmp_path / "chaotic.jsonl",
        {"DGRAPH_CHAOS": "step=wedge@4:sleep_s=120:attempt=0"},
    )
    assert lineage["final_exit_code"] == 0 and lineage["restarts"] == 1
    a0, a1 = lineage["attempts"]
    assert a0["outcome"] == "wedged" and a0["exit_code"] == 17
    assert a1["outcome"] == "ok"
    # the restart resumed from the checkpoint the wedged attempt left
    # behind (steps 0..3 completed -> checkpoint step 4)
    assert a1["resume_step"] == 4
    assert lineage["final_step"] == steps
    # the artifact records the active fault spec — a chaotic run can never
    # masquerade as a clean one
    assert lineage["run_health"]["env"]["chaos"] == (
        "step=wedge@4:sleep_s=120:attempt=0"
    )

    clean = restore_checkpoint(str(clean_ckpt))
    chaotic = restore_checkpoint(str(chaotic_ckpt))
    assert clean["step"] == chaotic["step"] == steps
    np.testing.assert_array_equal(
        np.asarray(clean["state"]["w"]), np.asarray(chaotic["state"]["w"])
    )
