"""Worker program for the REAL multi-process launch test
(test_multiprocess_launch.py). Every process runs this same file — the
multi-controller contract (multihost.py's module docstring; the reference's
torchrun/mpirun launcher matrix, ``MPIBackendEngine.py:268-341``).

The graph axis spans ALL devices across BOTH processes, so every per-layer
halo all_to_all crosses the process boundary, and each process materializes
only its own shards host-side (``process_local_shards``) and feeds them via
``jax.make_array_from_process_local_data`` — the per-host data loading the
single-controller dryruns can never exercise.

Run by the test as:  python tests/_mp_worker.py <coord> <nprocs> <pid>
Prints one line ``MPOK <loss> <devices> <procs>`` on success.
"""

import os
import sys

# each process gets its share of virtual CPU devices BEFORE jax import
# (argv[4], default 4 — the oracle run uses 1 process x 8 devices)
_DPP = int(sys.argv[4]) if len(sys.argv) > 4 else 4
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + f" --xla_force_host_platform_device_count={_DPP}"
)
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")  # beat the axon sitecustomize pin

import jax.numpy as jnp  # noqa: E402


def main(coord: str, nprocs: int, pid: int) -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    from jax.sharding import NamedSharding, PartitionSpec as P

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.comm.mesh import (
        GRAPH_AXIS,
        plan_in_specs,
        squeeze_plan,
    )
    from dgraph_tpu.comm.multihost import (
        initialize_multihost,
        make_pod_mesh,
        process_local_shards,
    )
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models import GCN

    initialize_multihost(coord, nprocs, pid)
    assert jax.process_count() == nprocs, jax.process_count()
    W = jax.device_count()  # graph axis spans every device of every host
    assert W == _DPP * nprocs, W

    mesh = make_pod_mesh(ranks_per_graph=W, num_replicas=1)
    comm = Communicator.init_process_group("tpu", world_size=W)

    # identical partition on every process (same seed — the single-program
    # contract); each process MATERIALIZES only its own shards
    data = synthetic.sbm_classification_graph(
        num_nodes=128, num_classes=4, feat_dim=8, avg_degree=6.0, seed=0
    )
    g = DistributedGraph.from_global(
        data["edge_index"], data["features"], data["labels"], data["masks"],
        world_size=W, partition_method="random", add_symmetric_norm=True,
    )
    mine = process_local_shards(W)
    assert mine == list(range(pid * _DPP, (pid + 1) * _DPP)), (pid, mine)

    def gsh(spec):
        return NamedSharding(mesh, spec)

    def feed(arr, spec=P(GRAPH_AXIS)):
        """Global [W, ...] array from THIS process's rows only."""
        arr = np.asarray(arr)
        return jax.make_array_from_process_local_data(
            gsh(spec), np.ascontiguousarray(arr[mine]), arr.shape
        )

    plan = jax.tree.map(
        lambda leaf: feed(leaf) if getattr(leaf, "ndim", 0) > 0 else leaf,
        g.plan,
    )
    batch_x = feed(np.asarray(g.features, np.float32))
    batch_y = feed(np.asarray(g.labels))
    batch_m = feed(np.asarray(g.masks["train"]))
    batch_ew = feed(np.asarray(g.edge_weight, np.float32))

    model = GCN(hidden_features=16, out_features=4, comm=comm)

    def init_body(x_, plan_, ew_):
        return model.init(
            jax.random.key(0), x_[0], squeeze_plan(plan_), ew_[0]
        )

    with jax.set_mesh(mesh):
        params = jax.jit(
            jax.shard_map(
                init_body, mesh=mesh,
                in_specs=(P(GRAPH_AXIS), plan_in_specs(plan), P(GRAPH_AXIS)),
                out_specs=P(),
            )
        )(batch_x, plan, batch_ew)

        def body(p, x_, y_, m_, ew_, plan_):
            xx, yy, mm, ew = x_[0], y_[0], m_[0], ew_[0]
            pln = squeeze_plan(plan_)

            def lf(p):
                logits = model.apply(p, xx, pln, ew)
                logp = jax.nn.log_softmax(logits)
                ll = jnp.take_along_axis(logp, yy[:, None], axis=1)[:, 0]
                cnt = jax.lax.psum(mm.sum(), GRAPH_AXIS)
                return -(ll * mm).sum() / jnp.maximum(cnt, 1.0)

            loss, grads = jax.value_and_grad(lf)(p)
            grads = jax.tree.map(
                lambda t: jax.lax.psum(t, GRAPH_AXIS), grads
            )
            return jax.lax.psum(loss, GRAPH_AXIS), grads

        step = jax.jit(
            jax.shard_map(
                body, mesh=mesh,
                in_specs=(P(), P(GRAPH_AXIS), P(GRAPH_AXIS), P(GRAPH_AXIS),
                          P(GRAPH_AXIS), plan_in_specs(plan)),
                out_specs=(P(), P()),
                check_vma=False,
            )
        )
        loss, grads = step(params, batch_x, batch_y, batch_m, batch_ew, plan)
        loss = float(loss)  # replicated: every process fetches the same value
        gnorm = float(
            sum(float(jnp.abs(l).sum()) for l in jax.tree.leaves(grads))
        )
    assert np.isfinite(loss) and gnorm > 0
    print(f"MPOK {loss:.6f} {jax.device_count()} {jax.process_count()}",
          flush=True)


if __name__ == "__main__":
    main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3]))
