"""Ring attention (sequence/context parallelism) vs the dense oracle.

The reference has no sequence-parallel primitive (SURVEY.md §2.3); these
tests pin our addition: 8-way ring attention must equal dense attention on
the gathered sequence — values AND gradients — for causal and masked
variants, with the backward emitting ring comm via AD (no hand transpose).
"""

import functools

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.parallel.sequence import (
    dense_attention,
    ring_attention,
    ring_attention_sharded,
)

W = 8
T, H, D = 64, 4, 16  # T_loc = 8 per shard


def _mesh():
    devs = jax.devices()
    if len(devs) < W:
        pytest.skip(f"need {W} devices")
    return Mesh(np.array(devs[:W]), ("seq",))


def _qkv(seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((T, H, D)), jnp.float32)
        for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_equals_dense(causal):
    mesh = _mesh()
    q, k, v = _qkv()
    out_ring = ring_attention_sharded(q, k, v, mesh, causal=causal)
    out_dense = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_dense), rtol=2e-5, atol=2e-5
    )


def test_ring_kv_mask():
    """Padded tail positions are excluded exactly like the dense mask."""
    mesh = _mesh()
    q, k, v = _qkv(1)
    valid = 50  # last 14 positions are padding
    kv_mask = (jnp.arange(T) < valid).astype(jnp.float32)

    out_ring = ring_attention_sharded(q, k, v, mesh, kv_mask=kv_mask)
    out_dense = dense_attention(q, k, v, kv_mask=kv_mask)
    np.testing.assert_allclose(
        np.asarray(out_ring)[:valid], np.asarray(out_dense)[:valid],
        rtol=2e-5, atol=2e-5,
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_gradients_equal_dense(causal):
    """jax.grad through the ring (scan + ppermute) equals dense-attention
    gradients: AD's transpose of the ring IS the ring backward."""
    mesh = _mesh()
    q, k, v = _qkv(2)
    tgt = jnp.asarray(np.random.default_rng(3).standard_normal((T, H, D)),
                      jnp.float32)

    def loss_ring(q, k, v):
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        return ((out - tgt) ** 2).sum()

    def loss_dense(q, k, v):
        out = dense_attention(q, k, v, causal=causal)
        return ((out - tgt) ** 2).sum()

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gr, gd, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-5,
            err_msg=f"d{name}",
        )


def _walk_eqns(jx):
    """Every eqn in a jaxpr INCLUDING nested sub-jaxprs (shard_map body,
    scan body, custom_vjp calls, ...)."""
    for eqn in jx.eqns:
        yield eqn
        for p in eqn.params.values():
            items = p if isinstance(p, (list, tuple)) else [p]
            for item in items:
                inner = getattr(item, "jaxpr", None)  # ClosedJaxpr
                if inner is not None:
                    yield from _walk_eqns(inner)
                elif hasattr(item, "eqns"):  # raw Jaxpr
                    yield from _walk_eqns(item)


def test_ring_memory_is_blockwise():
    """Structural pin: NO intermediate anywhere in the (recursively walked)
    jaxpr may hold more elements than ~2 K/V blocks — a regression that
    all-gathers K/V ([T, H, D] = W x bigger) or attends densely
    ([T_loc, H, T]) would exceed it. The whole point of the ring is
    O(T_loc) memory per device."""
    mesh = _mesh()
    q, k, v = _qkv(4)
    fn = shard_map(
        functools.partial(ring_attention, axis_name="seq"),
        mesh=mesh,
        in_specs=(P("seq"), P("seq"), P("seq")),
        out_specs=P("seq"),
    )
    jaxpr = jax.make_jaxpr(fn)(q, k, v)
    # outer jaxpr avals are GLOBAL shapes; the memory claim is about the
    # per-shard program, i.e. the shard_map body's jaxpr
    bodies = [
        e for e in jaxpr.jaxpr.eqns if "shard_map" in e.primitive.name
    ]
    assert bodies, "no shard_map eqn found"
    body = bodies[0].params["jaxpr"]
    t_loc = T // W
    block_elems = t_loc * H * D  # one K/V block
    limit = 2 * block_elems  # dense logits [t_loc, H, T] = 4x; K gathered = Wx
    seen = 0
    for eqn in _walk_eqns(getattr(body, "jaxpr", body)):
        for var in eqn.outvars:
            shape = getattr(getattr(var, "aval", None), "shape", ())
            seen += 1
            assert int(np.prod(shape, initial=1)) <= limit, (
                f"over-budget intermediate {shape} in ring jaxpr "
                f"(> {limit} elems = 2 K/V blocks)"
            )
    assert seen > 20, "jaxpr walk saw suspiciously few eqns — recursion broken?"


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_equals_dense(causal):
    """The all-to-all (head-scatter) lowering is exact too."""
    from dgraph_tpu.parallel.sequence import ulysses_attention

    mesh = _mesh()
    H8 = 8  # heads must divide by the axis size
    rng = np.random.default_rng(7)
    q, k, v = (
        jnp.asarray(rng.standard_normal((T, H8, D)), jnp.float32)
        for _ in range(3)
    )
    fn = shard_map(
        functools.partial(ulysses_attention, axis_name="seq", causal=causal),
        mesh=mesh,
        in_specs=(P("seq"), P("seq"), P("seq")),
        out_specs=P("seq"),
    )
    out = fn(q, k, v)
    want = dense_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_kv_mask_and_grads():
    from dgraph_tpu.parallel.sequence import ulysses_attention

    mesh = _mesh()
    H8 = 8
    rng = np.random.default_rng(8)
    q, k, v = (
        jnp.asarray(rng.standard_normal((T, H8, D)), jnp.float32)
        for _ in range(3)
    )
    kv_mask = (jnp.arange(T) < 50).astype(jnp.float32)
    fn = shard_map(
        lambda q, k, v, m: ulysses_attention(q, k, v, "seq", kv_mask=m),
        mesh=mesh,
        in_specs=(P("seq"),) * 4,
        out_specs=P("seq"),
    )

    def loss_u(q, k, v):
        return ((fn(q, k, v, kv_mask)[:50]) ** 2).sum()

    def loss_d(q, k, v):
        return ((dense_attention(q, k, v, kv_mask=kv_mask)[:50]) ** 2).sum()

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(gu, gd, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-5, atol=5e-5, err_msg=f"d{name}")


def test_comm_seq_attention_impl_routing():
    """comm.seq_attention(impl=...) — ring and ulysses agree with the dense
    oracle through the facade, and unknown impls raise."""
    from jax.sharding import PartitionSpec as P

    from dgraph_tpu.comm import Communicator

    mesh = _mesh()
    rng = np.random.default_rng(5)
    q, k, v = (
        jnp.asarray(rng.standard_normal((T, 8, D)), jnp.float32)
        for _ in range(3)
    )  # 8 heads: ulysses needs heads % axis == 0
    comm = Communicator.init_process_group("tpu", world_size=W,
                                           graph_axis="seq")
    want = dense_attention(q, k, v, causal=True)
    for impl in ("ring", "ulysses"):
        fn = jax.shard_map(
            lambda q, k, v: comm.seq_attention(q, k, v, causal=True,
                                               impl=impl),
            mesh=mesh, in_specs=(P("seq"),) * 3, out_specs=P("seq"),
            check_vma=False,
        )
        with jax.set_mesh(mesh):
            got = fn(q, k, v)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5,
            err_msg=impl,
        )
    with pytest.raises(ValueError, match="unknown seq_attention impl"):
        fn = jax.shard_map(
            lambda q, k, v: comm.seq_attention(q, k, v, impl="bogus"),
            mesh=mesh, in_specs=(P("seq"),) * 3, out_specs=P("seq"),
            check_vma=False,
        )
        with jax.set_mesh(mesh):
            fn(q, k, v)


def test_flash_gating_off_tpu():
    """On CPU the flash path must NEVER engage — not in auto, and (since
    the r3 hardening) not even when the flag is pinned True: the kernel
    is Mosaic-only and a pinned flag copied from a TPU runbook must not
    crash CPU runs. Auto additionally requires the chip self-check latch."""
    from dgraph_tpu import config as cfg
    from dgraph_tpu.parallel import sequence as seq

    q = jnp.zeros((256, 2, 128), jnp.float32)
    old = cfg.use_flash_attention
    try:
        cfg.set_flags(use_flash_attention=None)  # auto
        assert seq._flash_applicable(q) is False
        cfg.set_flags(use_flash_attention=True)  # pinned — still CPU
        assert seq._flash_applicable(q) is False
        assert seq._flash_applicable(q, require_pinned=True) is False
    finally:
        cfg.set_flags(use_flash_attention=old)
    assert seq.flash_attention_selfcheck() is False  # off-TPU: no verdict
    assert seq._flash_verified is False  # and the auto latch stays cold


def test_flash_shape_gate(monkeypatch):
    """The T%128 / D%128 shape gate, exercised on CPU by faking the
    backend (the real backend check short-circuits first otherwise — a
    broken shape gate must not wait for a scarce TPU window to surface)."""
    from dgraph_tpu import config as cfg
    from dgraph_tpu.parallel import sequence as seq

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    old = cfg.use_flash_attention
    try:
        cfg.set_flags(use_flash_attention=True)  # pinned: operator override
        assert seq._flash_applicable(jnp.zeros((256, 2, 128))) is True
        assert seq._flash_applicable(
            jnp.zeros((256, 2, 128)), require_pinned=True) is True
        assert seq._flash_applicable(jnp.zeros((250, 2, 128))) is False
        assert seq._flash_applicable(jnp.zeros((256, 2, 64))) is False
        # auto (None) needs the self-check latch even on "tpu"
        cfg.set_flags(use_flash_attention=None)
        assert seq._flash_applicable(jnp.zeros((256, 2, 128))) is False
    finally:
        cfg.set_flags(use_flash_attention=old)


@pytest.mark.parametrize("causal", [False, True])
def test_padded_rows_zero_in_every_impl(causal):
    """The shared contract (_zero_padded_rows): padded QUERY rows are zero
    in every implementation, so FULL tensors agree across impls — not just
    the real-row prefix the other mask tests slice to (flash is TPU-only
    and carries the same zeroing in _flash_dense)."""
    from dgraph_tpu.parallel.sequence import ulysses_attention

    mesh = _mesh()
    H8 = 8
    rng = np.random.default_rng(11)
    q, k, v = (
        jnp.asarray(rng.standard_normal((T, H8, D)), jnp.float32)
        for _ in range(3)
    )
    valid = 41
    kv_mask = (jnp.arange(T) < valid).astype(jnp.float32)

    out_dense = dense_attention(q, k, v, causal=causal, kv_mask=kv_mask)
    pad = np.asarray(out_dense)[valid:]
    np.testing.assert_array_equal(pad, np.zeros_like(pad))

    out_ring = ring_attention_sharded(q, k, v, mesh, causal=causal,
                                      kv_mask=kv_mask)
    out_uly = shard_map(
        lambda q_, k_, v_, m_: ulysses_attention(
            q_, k_, v_, "seq", causal=causal, kv_mask=m_),
        mesh=mesh,
        in_specs=(P("seq"), P("seq"), P("seq"), P("seq")),
        out_specs=P("seq"),
    )(q, k, v, kv_mask)
    # FULL tensors, padded rows included
    np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_dense),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(out_uly), np.asarray(out_dense),
                               rtol=2e-5, atol=2e-5)
