"""DistributedBatchNorm: recompute (remat) variant parity + memory shape.

Reference parity: ``experiments/OGB-LSC/distributed_layers.py:77-107``
(DistributedBN_with_Recompute) — identical math to the plain BN, backward
rematerializes the normalized tensor instead of saving it.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from dgraph_tpu.comm import Communicator
from dgraph_tpu.models import DistributedBatchNorm

W = 8


def _mesh():
    return Mesh(np.array(jax.devices()[:W]), ("graph",))


def _data(seed=0, n_pad=16, F=12):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((W, n_pad, F)).astype(np.float32)
    # ragged real counts per shard — stats must be mask-weighted
    mask = (np.arange(n_pad)[None, :] < rng.integers(6, n_pad, W)[:, None])
    return jnp.asarray(x), jnp.asarray(mask.astype(np.float32))


def _init(bn, mesh, x, mask):
    return jax.jit(
        jax.shard_map(
            lambda x_, m_: bn.init(jax.random.key(0), x_, m_),
            mesh=mesh, in_specs=(P("graph"), P("graph")), out_specs=P(),
            check_vma=False,
        )
    )(x.reshape(-1, x.shape[-1]), mask.reshape(-1))


def _loss_fn(recompute: bool):
    comm = Communicator.init_process_group("tpu", world_size=W)
    bn = DistributedBatchNorm(comm=comm, recompute=recompute)

    def shard_loss(params, x, mask):
        out, _ = bn.apply(params, x, mask, mutable=["batch_stats"])
        return jax.lax.psum((out**2 * mask[:, None]).sum(), "graph")

    return bn, shard_loss


@pytest.mark.parametrize("recompute", [False, True])
def test_recompute_matches_plain(recompute):
    """Outputs AND grads of the recompute variant are bitwise-comparable to
    the plain path (the reference keeps the math identical; only residual
    lifetime changes)."""
    mesh = _mesh()
    x, mask = _data()
    bn_plain, loss_plain = _loss_fn(False)
    bn_re, loss_re = _loss_fn(recompute)

    params = _init(bn_plain, mesh, x, mask)

    def grad_of(loss_fn):
        return jax.jit(
            jax.shard_map(
                jax.value_and_grad(loss_fn),
                mesh=mesh,
                in_specs=(P(), P("graph"), P("graph")),
                out_specs=P(),
                check_vma=False,
            )
        )(params, x.reshape(-1, x.shape[-1]), mask.reshape(-1))

    l0, g0 = grad_of(loss_plain)
    l1, g1 = grad_of(loss_re)
    np.testing.assert_allclose(float(l0), float(l1), rtol=1e-6)
    for a, b in zip(jax.tree.leaves(g0), jax.tree.leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_recompute_saves_no_normalized_residual():
    """The [n_pad, F] normalized tensor must NOT be a saved residual under
    recompute=True: the grad jaxpr contains a remat region and its saved
    residuals exclude everything the checkpoint region produces."""
    mesh = _mesh()
    x, mask = _data()
    _, loss_re = _loss_fn(True)
    bn_plain, _ = _loss_fn(False)
    params = _init(bn_plain, mesh, x, mask)

    jaxpr = jax.make_jaxpr(
        jax.shard_map(
            jax.grad(loss_re),
            mesh=mesh,
            in_specs=(P(), P("graph"), P("graph")),
            out_specs=P(),
            check_vma=False,
        )
    )(params, x.reshape(-1, x.shape[-1]), mask.reshape(-1))
    assert "remat" in str(jaxpr), "recompute=True produced no remat region"
