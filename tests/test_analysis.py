"""Static analysis subsystem: contract linter rules + trace auditor.

Tier-1 registration is the ``python -m dgraph_tpu.analysis --selftest``
CLI smoke (compile-free: every program is traced abstractly via
``jax.make_jaxpr``/``jax.eval_shape``, so this file adds ZERO new XLA
compiles to the suite — the budget rule documented in tests/README.md).
The in-process tests pin the individual contracts, including the two
violations the linter surfaced in the pre-analysis tree (a stray jax
import in ``chaos.poison_pytree``, an unscoped ``psum_mean``) as fixed.
"""

import ast
import json
import os
import subprocess
import sys

import pytest

from dgraph_tpu.analysis import lint as L

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# linter
# ---------------------------------------------------------------------------


def test_lint_tree_is_clean():
    """The shipped tree has zero contract violations — the regression pin
    for every violation the linter surfaced when it first ran (chaos's
    jax-importing poison_pytree, the unscoped psum_mean collective)."""
    report = L.run_lint()
    assert report["ok"], report["findings"]
    assert report["files_checked"] > 50
    assert set(report["rules"]) == set(L.RULES)


def _run_rule(name, path, src, root=""):
    tree = ast.parse(src)
    lines = src.splitlines()
    if name == "jax-free-module":
        got = L.RULES[name].check(path, tree, lines, root=root)
    else:
        got = L.RULES[name].check(path, tree, lines)
    return [f for f in got if not L._suppressed(lines, f.line, f.rule)]


def test_jax_free_rule_pins_the_chaos_regression():
    """The exact shape of the pre-fix chaos.poison_pytree (a function-
    level jax import in a jax-free module) must keep firing."""
    src = (
        "def poison_pytree(tree):\n"
        "    import jax\n"
        "    return jax.tree.map(id, tree)\n"
    )
    got = _run_rule("jax-free-module", "dgraph_tpu/chaos/__init__.py", src)
    assert len(got) == 1 and got[0].line == 2


def test_named_scope_rule_pins_the_psum_mean_regression():
    """The exact shape of the pre-fix psum_mean (public collective with no
    named scope) must keep firing — and the fixed spelling must not."""
    bad = (
        "from jax import lax\n"
        "def psum_mean(x, axis_name):\n"
        "    return lax.pmean(x, axis_name)\n"
    )
    good = (
        "from jax import lax\n"
        "@_scoped('dgraph.psum_mean')\n"
        "def psum_mean(x, axis_name):\n"
        "    return lax.pmean(x, axis_name)\n"
    )
    path = "dgraph_tpu/comm/collectives.py"
    assert _run_rule("named-scope-on-collectives", path, bad)
    assert not _run_rule("named-scope-on-collectives", path, good)


def test_config_read_in_trace_rule():
    """A config attribute read inside a function handed to jit/shard_map
    fires (the PR 4 mixed-lowering hazard); the resolve-outside-and-thread
    pattern does not."""
    path = "dgraph_tpu/comm/collectives.py"
    bad = (
        "from dgraph_tpu import config as _cfg\n"
        "import jax\n"
        "def make(mesh):\n"
        "    def body(x):\n"
        "        if _cfg.halo_impl == 'ppermute':\n"
        "            return -x\n"
        "        return x\n"
        "    return jax.shard_map(body, mesh=mesh)\n"
    )
    good = bad.replace(
        "    def body(x):\n        if _cfg.halo_impl == 'ppermute':\n",
        "    impl = _cfg.halo_impl\n"
        "    def body(x):\n        if impl == 'ppermute':\n",
    )
    assert _run_rule("no-config-read-in-trace", path, bad)
    assert not _run_rule("no-config-read-in-trace", path, good)
    # os.environ inside a traced body is the same hazard
    env_bad = (
        "import jax, os\n"
        "def make():\n"
        "    return jax.jit(lambda x: x if os.environ.get('F') else -x)\n"
    )
    assert _run_rule("no-config-read-in-trace", path, env_bad)


def test_custom_vjp_paired_rule():
    path = "dgraph_tpu/ops/local.py"
    bad = "import jax\n@jax.custom_vjp\ndef f(x):\n    return x\n"
    assert _run_rule("custom-vjp-paired", path, bad)
    good = bad + "f.defvjp(lambda x: (x, None), lambda r, g: (g,))\n"
    assert not _run_rule("custom-vjp-paired", path, good)
    # assignment spelling: g = jax.custom_vjp(fn)
    bad2 = "import jax\ndef fn(x):\n    return x\ng = jax.custom_vjp(fn)\n"
    assert _run_rule("custom-vjp-paired", path, bad2)


def test_nondeterminism_rule():
    path = "dgraph_tpu/partition.py"
    assert _run_rule(
        "no-nondeterminism-in-plan", path,
        "import numpy as np\nperm = np.random.permutation(8)\n",
    )
    assert _run_rule(
        "no-nondeterminism-in-plan", path,
        "import numpy as np\nrng = np.random.default_rng()\n",
    )
    assert _run_rule(
        "no-nondeterminism-in-plan", path,
        "import time\nstamp = time.time()\n",
    )
    assert not _run_rule(
        "no-nondeterminism-in-plan", path,
        "import numpy as np\nrng = np.random.default_rng(7)\n",
    )


def test_pragma_suppression_requires_matching_rule():
    src = (
        "def f(tree):\n"
        "    import jax  # lint: allow(jax-free-module)\n"
    )
    assert not _run_rule("jax-free-module", "dgraph_tpu/chaos/x.py", src)
    wrong = src.replace("jax-free-module)", "custom-vjp-paired)")
    assert _run_rule("jax-free-module", "dgraph_tpu/chaos/x.py", wrong)


def test_rank_branch_in_trace_rule():
    """Rank-identity reads steering Python control flow inside a traced
    body = trace-time SPMD divergence; host-side rank reads outside the
    traced boundary are the sanctioned pattern."""
    bad = (
        "import jax\n"
        "def step(x):\n"
        "    def body(y):\n"
        "        if jax.process_index() == 0:\n"
        "            return y * 2\n"
        "        return y\n"
        "    return jax.jit(body)(x)\n"
    )
    got = _run_rule("no-rank-branch-in-trace", "dgraph_tpu/train/loop.py", bad)
    assert len(got) == 1 and "process_index" in got[0].message

    good = (
        "import jax\n"
        "def launch(x):\n"
        "    if jax.process_index() == 0:\n"
        "        print('leader')\n"
        "    return jax.jit(lambda y: y * 2)(x)\n"
    )
    assert not _run_rule(
        "no-rank-branch-in-trace", "dgraph_tpu/train/loop.py", good
    )

    # the env-var spelling, through the shared RANK_ENV_VAR constant
    env_bad = (
        "import os\n"
        "import jax\n"
        "from dgraph_tpu.utils.env import RANK_ENV_VAR\n"
        "def step(x):\n"
        "    def body(y):\n"
        "        return y[int(os.environ[RANK_ENV_VAR]):]\n"
        "    return jax.jit(body)(x)\n"
    )
    assert _run_rule(
        "no-rank-branch-in-trace", "dgraph_tpu/train/loop.py", env_bad
    )
    # pragma suppression works like every other rule
    suppressed = env_bad.replace(
        "        return y[int(os.environ[RANK_ENV_VAR]):]\n",
        "        # lint: allow(no-rank-branch-in-trace)\n"
        "        return y[int(os.environ[RANK_ENV_VAR]):]\n",
    )
    assert not _run_rule(
        "no-rank-branch-in-trace", "dgraph_tpu/train/loop.py", suppressed
    )


# ---------------------------------------------------------------------------
# rule registry: --list-rules CLI + the docs table pin
# ---------------------------------------------------------------------------


def test_list_rules_cli_prints_the_registry():
    proc = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.analysis", "--list_rules", "true"],
        capture_output=True, text=True, timeout=120, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "rule_catalog"
    listed = {r["name"] for r in rec["rules"]}
    assert listed == set(L.RULES)
    for row in rec["rules"]:
        assert row["description"] == L.RULES[row["name"]].description
        assert row["scope"] == L.RULES[row["name"]].scope
        assert row["scope"], f"rule {row['name']} has no scope string"


def test_docs_rule_catalog_matches_registry():
    """The rule-catalog table in docs/static-analysis.md is maintained by
    hand; after three analysis PRs it can silently drift from the RULES
    registry — machine-check one against the other."""
    path = os.path.join(REPO, "docs", "static-analysis.md")
    text = open(path).read()
    # table rows look like: | `rule-name` | scope | contract |
    documented = set()
    for line in text.splitlines():
        m = line.strip().startswith("| `")
        if not m:
            continue
        cell = line.strip().split("|")[1].strip()
        if cell.startswith("`") and cell.endswith("`"):
            name = cell.strip("`")
            if name in L.RULES or "-" in name:
                documented.add(name)
    undocumented = set(L.RULES) - documented
    assert not undocumented, (
        f"rules missing from the docs/static-analysis.md catalog table: "
        f"{sorted(undocumented)}"
    )
    ghost = {d for d in documented if d not in L.RULES}
    assert not ghost, (
        f"docs/static-analysis.md documents rules the registry does not "
        f"have: {sorted(ghost)}"
    )


# ---------------------------------------------------------------------------
# trace auditor (abstract tracing only — no compiles)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def workload2():
    from dgraph_tpu.analysis.trace import build_audit_workload

    return build_audit_workload(2)


def test_trace_audit_2shard_pins_footprint(workload2):
    """All three lowerings: op counts and operand bytes match what
    obs.footprint prices (the acceptance pin at W=2; --selftest covers
    W=4 in its own process)."""
    from dgraph_tpu.analysis.trace import audit_workload

    rep = audit_workload(workload2)
    assert rep["ok"], rep["failures"]
    assert rep["exchange_legs"]["train_step"] == 2 * rep["exchange_legs"][
        "eval_step"
    ]  # fwd+bwd vs fwd-only
    by_impl = {(p["program"], p["impl"]): p for p in rep["programs"]}
    n_deltas = rep["num_halo_deltas"]
    for prog, legs in rep["exchange_legs"].items():
        assert by_impl[(prog, "all_to_all")]["num_all_to_all"] == legs
        assert by_impl[(prog, "ppermute")]["num_ppermute"] == legs * n_deltas
        assert by_impl[(prog, "overlap")]["num_ppermute"] == legs * n_deltas
    for p in rep["programs"]:
        for op in p["collective_operands"]:
            assert op["traced_bytes"] == op["footprint_bytes"]
    assert rep["donation"]["unmatched"] == []


def test_auditor_rejects_wrong_lowering_family(workload2):
    """Vacuity guard: pin ppermute, audit as all_to_all -> must fail."""
    from dgraph_tpu import config as cfg
    from dgraph_tpu.analysis import trace as T

    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    try:
        cfg.set_flags(halo_impl="ppermute", tuned_halo_impl=None)
        fn, args = T._train_program(workload2)
        failures = []
        T._audit_one_program(
            "t", "all_to_all", fn, args, workload2.plan_np, failures
        )
        assert failures
    finally:
        cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


def test_donation_unmatched_detects_dropped_buffers(workload2):
    from dgraph_tpu.analysis import trace as T

    fn, args = T._train_program(workload2)
    assert T.donation_unmatched(fn, args, (workload2.params,
                                           workload2.opt_state)) == {}
    dropped = lambda p, o, b, pl: fn(p, o, b, pl)[2]  # metrics only
    assert T.donation_unmatched(
        dropped, args, (workload2.params, workload2.opt_state)
    )


def test_collect_collectives_counts_scalar_psums(workload2):
    """The loss psum is a scalar — shape () must not be dropped by the
    collector (regression: falsy-shape skip)."""
    import jax

    from dgraph_tpu.analysis import trace as T

    fn, args = T._eval_program(workload2)
    coll = T.collect_collectives(jax.make_jaxpr(fn)(*args))
    assert coll["psum"], "eval step's loss/accuracy psums not collected"
    assert all(r["dtype"] == "float32" for r in coll["psum"])


def test_walk_eqns_descends_into_custom_vjp_and_pjit(workload2):
    """The canonical traversal reaches collectives nested under
    custom_vjp bodies (the overlap pair) — the descent the dtype-
    discipline tests share."""
    import jax

    from dgraph_tpu import config as cfg
    from dgraph_tpu.analysis import trace as T

    saved = (cfg.halo_impl, cfg.tuned_halo_impl)
    try:
        cfg.set_flags(halo_impl="overlap", tuned_halo_impl=None)
        fn, args = T._train_program(workload2)
        coll = T.collect_collectives(jax.make_jaxpr(fn)(*args))
        assert coll["ppermute"], (
            "overlap rounds live inside custom_vjp bodies; the walker "
            "must descend there"
        )
    finally:
        cfg.set_flags(halo_impl=saved[0], tuned_halo_impl=saved[1])


# ---------------------------------------------------------------------------
# CLI selftest (tier-1: the whole subsystem on every run)
# ---------------------------------------------------------------------------


def test_analysis_selftest_cli(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "dgraph_tpu.analysis", "--selftest", "true",
         "--log_path", str(tmp_path / "analysis.jsonl")],
        capture_output=True, text=True, timeout=420, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rec = json.loads(proc.stdout.strip().splitlines()[-1])
    assert rec["kind"] == "analysis_selftest"
    assert rec["failures"] == []
    # the acceptance pin: both shard counts audited, all lowerings ok
    assert rec["audit"]["2"]["ok"] and rec["audit"]["4"]["ok"]
    assert rec["audit"]["4"]["num_halo_deltas"] >= 1
    # the JSONL stream carries the per-workload audit reports
    rows = [
        json.loads(ln)
        for ln in (tmp_path / "analysis.jsonl").read_text().splitlines()
        if ln.strip() and not ln.startswith("#")
    ]
    assert any(r.get("kind") == "trace_audit" for r in rows)
    assert any(r.get("kind") == "analysis_selftest" for r in rows)


def test_schedule_drift_record_shape():
    """The bench-fallback record: non-null byte comparison per lowering
    (what a wedged round attaches instead of a null metric)."""
    from dgraph_tpu.analysis.trace import schedule_drift_record

    rec = schedule_drift_record(2, num_nodes=64, num_edges=256, feat_dim=8)
    assert rec["kind"] == "schedule_drift"
    assert rec["drift"] is False
    for impl in ("all_to_all", "ppermute", "overlap"):
        row = rec["train_step_by_impl"][impl]
        assert row["traced_bytes"] == row["footprint_bytes"] > 0
