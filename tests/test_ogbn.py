"""OGB ingestion adapter: gated ogb import, npz/memmap export format,
processed-graph cache, lead-first sentinel.

The ogb package isn't in this image, so the package path is tested with a
stub module injected into sys.modules — the adapter only touches
``NodePropPredDataset(name, root)``, ``ds[0]`` and ``ds.get_idx_split()``
(the reference wrapper's exact surface, ``ogbn_datasets.py:86-95``).
"""

import os
import pickle
import sys
import types

import numpy as np
import pytest

from dgraph_tpu.data import ogbn


def _fake_arrays(V=60, E=300, F=8, C=4, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "edge_index": rng.integers(0, V, (2, E)).astype(np.int64),
        "features": rng.normal(size=(V, F)).astype(np.float32),
        "labels": rng.integers(0, C, V).astype(np.int32),
        "num_nodes": V,
    }


class _FakeOGBDataset:
    def __init__(self, name, root=None):
        self.arrs = _fake_arrays()

    def __getitem__(self, i):
        a = self.arrs
        graph = {
            "edge_index": a["edge_index"],
            "node_feat": a["features"],
            "num_nodes": a["num_nodes"],
        }
        # ogb returns [V, 1] float labels for some datasets; exercise the
        # squeeze + NaN handling
        labels = a["labels"].astype(np.float64)[:, None].copy()
        labels[0, 0] = np.nan
        return graph, labels

    def get_idx_split(self):
        V = self.arrs["num_nodes"]
        return {
            "train": np.arange(0, V // 2),
            "valid": np.arange(V // 2, 3 * V // 4),
            "test": np.arange(3 * V // 4, V),
        }


@pytest.fixture
def fake_ogb(monkeypatch):
    mod = types.ModuleType("ogb")
    sub = types.ModuleType("ogb.nodeproppred")
    sub.NodePropPredDataset = _FakeOGBDataset
    mod.nodeproppred = sub
    monkeypatch.setitem(sys.modules, "ogb", mod)
    monkeypatch.setitem(sys.modules, "ogb.nodeproppred", sub)
    yield


def test_import_gate_message():
    with pytest.raises(ImportError, match="export_npz"):
        ogbn.load_ogb_arrays("ogbn-arxiv")


def test_unsupported_name():
    with pytest.raises(ValueError, match="unsupported"):
        ogbn.load_ogb_arrays("ogbn-mag")


def test_load_with_fake_ogb(fake_ogb):
    arrs = ogbn.load_ogb_arrays("ogbn-arxiv")
    assert arrs["features"].shape == (60, 8)
    assert arrs["labels"].dtype == np.int32
    assert arrs["labels"][0] == 0  # NaN -> class 0
    assert arrs["train_mask"].sum() == 30
    assert arrs["valid_mask"].sum() == 15
    assert arrs["test_mask"].sum() == 15


def test_export_npz_roundtrip(fake_ogb, tmp_path):
    p = str(tmp_path / "arxiv.npz")
    ogbn.export_npz("ogbn-arxiv", p)
    back = ogbn.from_npz(p)
    assert back["num_nodes"] == 60
    assert set(back) >= {"edge_index", "features", "labels", "train_mask"}
    np.testing.assert_array_equal(
        back["features"], ogbn.load_ogb_arrays("ogbn-arxiv")["features"]
    )


def test_distributed_dataset_cache(fake_ogb, tmp_path):
    cache_dir = str(tmp_path / "cache")
    ds = ogbn.DistributedOGBDataset(
        "ogbn-arxiv", world_size=2, cache_dir=cache_dir, pad_multiple=8
    )
    assert ds.graph.world_size == 2
    assert ds.plan.world_size == 2
    b = ds.batch("train")
    assert b["x"].shape[0] == 2  # [W, n_pad, F]
    # second construction must come from the pickle cache, not ogb: break
    # the stub to prove it
    sys.modules["ogb.nodeproppred"].NodePropPredDataset = None
    ds2 = ogbn.DistributedOGBDataset(
        "ogbn-arxiv", world_size=2, cache_dir=cache_dir, pad_multiple=8
    )
    np.testing.assert_array_equal(ds2.graph.features, ds.graph.features)


def test_distributed_dataset_from_npz(fake_ogb, tmp_path):
    p = str(tmp_path / "arxiv.npz")
    ogbn.export_npz("ogbn-arxiv", p)
    del sys.modules["ogb"], sys.modules["ogb.nodeproppred"]
    ds = ogbn.DistributedOGBDataset(
        "ogbn-arxiv", world_size=2, data_path=p,
        cache_dir=str(tmp_path / "c2"), pad_multiple=8,
    )
    assert ds.graph.num_nodes == 60


def test_lead_first_sentinel(tmp_path):
    path = str(tmp_path / "artifact.bin")
    calls = []

    def build(p):
        calls.append(p)
        with open(p, "wb") as f:
            f.write(b"x")

    ogbn.lead_first(path, build, is_lead=True)
    assert calls == [path]
    # follower: sentinel exists, build must NOT run
    ogbn.lead_first(path, build, is_lead=False)
    assert calls == [path]


def test_lead_first_follower_timeout(tmp_path):
    with pytest.raises(TimeoutError):
        ogbn.lead_first(
            str(tmp_path / "never.bin"), lambda p: None, is_lead=False,
            poll_s=0.01, timeout_s=0.05,
        )


def test_arxiv_shaped_export_roundtrip(tmp_path):
    """The full export -> from_npz -> DistributedGraph -> train -> accuracy
    loop (VERDICT r1 #5): arxiv-shaped stand-in, real learning measured on
    the held-out split. The real ogbn-arxiv export produces the identical
    format, so this pins every consumer the real arrays will flow through."""
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator, make_graph_mesh
    from dgraph_tpu.data import DistributedGraph
    from dgraph_tpu.data.ogbn import export_arxiv_shaped_npz, from_npz
    from dgraph_tpu.models import GCN
    from dgraph_tpu.train.loop import init_params, make_eval_step, make_train_step

    path = export_arxiv_shaped_npz(str(tmp_path / "arxiv_shaped.npz"), scale=0.01)
    z = from_npz(path)
    assert z["features"].shape[1] == 128 and z["features"].dtype == np.float32
    assert int(np.asarray(z["labels"]).max()) + 1 == 40
    # split proportions follow the real arxiv split
    V = z["num_nodes"]
    assert abs(z["train_mask"].sum() / V - 90_941 / 169_343) < 0.01

    W = 4
    g = DistributedGraph.from_global(
        np.asarray(z["edge_index"]), np.asarray(z["features"]),
        np.asarray(z["labels"]),
        {"train": z["train_mask"], "val": z["valid_mask"], "test": z["test_mask"]},
        world_size=W, partition_method="random", add_symmetric_norm=True,
    )
    mesh = make_graph_mesh(ranks_per_graph=W, devices=jax.devices()[:W])
    comm = Communicator.init_process_group("tpu", world_size=W)
    model = GCN(32, 40, comm=comm, num_layers=2)
    plan = jax.tree.map(jnp.asarray, g.plan)
    batch_tr = jax.tree.map(jnp.asarray, dict(g.batch("train"), y=g.labels))
    batch_te = jax.tree.map(jnp.asarray, dict(g.batch("test"), y=g.labels))
    params = init_params(model, mesh, plan, batch_tr)
    opt = optax.adam(5e-3)
    opt_state = opt.init(params)
    step = make_train_step(model, opt, mesh, plan, donate=False)
    eval_step = make_eval_step(model, mesh)
    with jax.set_mesh(mesh):
        for _ in range(25):
            params, opt_state, _ = step(params, opt_state, batch_tr, plan)
        acc = float(eval_step(params, batch_te, plan)["accuracy"])
    assert acc > 0.3, f"held-out accuracy {acc} not above 40-class chance"
