"""plan_memory_usage + validate_plan (+ a papers100M-direction scale check)."""

import numpy as np
import pytest

from dgraph_tpu import plan as pl
from dgraph_tpu.plan import plan_memory_usage, validate_plan


def test_valid_plan_passes(rng):
    edges = rng.integers(0, 64, size=(2, 400))
    part = np.sort(rng.integers(0, 8, 64)).astype(np.int32)
    plan, _ = pl.build_edge_plan(edges, part, world_size=8)
    validate_plan(plan)  # no raise
    mem = plan_memory_usage(plan, feature_dim=128)
    assert mem["total_runtime_bytes"] > 0
    assert mem["halo_buffer_bytes"] == 8 * plan.halo.s_pad * 128 * 4


def test_corrupted_plan_caught(rng):
    import dataclasses

    edges = rng.integers(0, 64, size=(2, 400))
    part = np.sort(rng.integers(0, 8, 64)).astype(np.int32)
    plan, _ = pl.build_edge_plan(edges, part, world_size=8)
    bad_src = np.asarray(plan.src_index).copy()
    bad_src[0, 0] = 10_000_000
    bad = dataclasses.replace(plan, src_index=bad_src)
    with pytest.raises(ValueError, match="src_index"):
        validate_plan(bad)

    bad_send = np.asarray(plan.halo.send_mask).copy()
    bad_send[2, 2, 0] = 1.0  # self-send
    bad2 = dataclasses.replace(plan, halo=dataclasses.replace(plan.halo, send_mask=bad_send))
    with pytest.raises(ValueError, match="sends to itself"):
        validate_plan(bad2)


@pytest.mark.slow
def test_scale_plan_build_5m_edges(rng):
    """papers100M-direction scale check: 500k vertices / 5M edges through
    partition + plan build + validation within test-tolerable time. (The
    real papers100M build, 111M/1.6B, is a batch job: same code path,
    native dedup, plan cache — SURVEY §7 hard-parts.)"""
    import time

    from dgraph_tpu import partition as pt
    from dgraph_tpu.data.synthetic import power_law_graph

    V, W = 500_000, 16
    edges = power_law_graph(V, 10.0, seed=1)
    t0 = time.time()
    part = pt.greedy_bfs_partition(edges, V, W)
    ren = pt.renumber_contiguous(part, W)
    new_edges = ren.perm[edges]
    plan, layout = pl.build_edge_plan(new_edges, ren.partition, world_size=W)
    dt = time.time() - t0
    validate_plan(plan)
    assert float(np.asarray(plan.edge_mask).sum()) == edges.shape[1]
    assert dt < 120, f"plan build too slow: {dt:.1f}s"


class TestSortRouteValidation:
    """validate_plan's halo-sort-route checks: a valid plan passes; each
    corruption class (non-permutation, non-monotone, ids mismatch) is
    rejected — stale/corrupt cached plans must rebuild, not silently feed
    the Pallas sorted kernels."""

    def _plan(self):
        rng = np.random.default_rng(3)
        V, E, W = 64, 400, 4
        edges = np.stack([rng.integers(0, V, E), rng.integers(0, V, E)])
        part = np.sort(rng.integers(0, W, V)).astype(np.int32)
        return pl.build_edge_plan(edges, part, world_size=W, edge_owner="dst")[0]

    def test_valid_plan_passes(self):
        validate_plan(self._plan())

    def test_non_monotone_sorted_ids_rejected(self):
        import dataclasses

        plan = self._plan()
        bad = dataclasses.replace(
            plan,
            halo_sorted_ids=np.flip(np.asarray(plan.halo_sorted_ids), axis=1),
        )
        with pytest.raises(ValueError, match="not monotone"):
            validate_plan(bad)

    def test_non_permutation_rejected(self):
        import dataclasses

        plan = self._plan()
        perm = np.asarray(plan.halo_sort_perm).copy()
        perm[0, 0] = perm[0, 1]  # duplicate entry: not a permutation
        bad = dataclasses.replace(plan, halo_sort_perm=perm)
        with pytest.raises(ValueError, match="not a permutation"):
            validate_plan(bad)

    def test_ids_mismatch_rejected(self):
        import dataclasses

        plan = self._plan()
        sids = np.asarray(plan.halo_sorted_ids).copy()
        # keep monotone but break the halo_index[perm] == sorted_ids tie
        sids[0] = np.clip(sids[0] + 1, 0, None)
        bad = dataclasses.replace(plan, halo_sorted_ids=sids)
        with pytest.raises(ValueError, match="!= halo_index"):
            validate_plan(bad)
