"""Benchmark harness. Prints ONE JSON line to stdout with the primary
metric (arxiv-scale GCN epoch time) plus roofline context and a GraphCast
reference-scale step time:

  {"metric": "arxiv_gcn_epoch_time", "value": N, "unit": "ms",
   "vs_baseline": R, "mfu_pct": ..., "hbm_pct": ..., "model_tflops_s": ...,
   "graphcast_step_ms": ..., "config": {...}}

Stage progress goes to stderr. vs_baseline compares against OUR recorded
round-1 number in BENCH_BASELINE.json (the reference publishes no numbers,
BASELINE.md); ratio > 1.0 = faster than that recording.

Measured quantities mirror the reference's harnesses:
- per-epoch full-graph GCN training time, avg excluding compile
  (``experiments/OGB/main.py:129-221``) on an arxiv-shaped synthetic graph
  (169 343 vertices / 2.33M directed edges / 128 features / 40 classes);
- GraphCast training step time (``microbenchmark_graphcast.py:63-247``) at
  the paper's level-6 mesh / 721x1440 ERA5 grid scale.

Roofline context (VERDICT r1 #1): model_tflops_s counts the DENSE matmul
FLOPs only (gather/scatter one-hot work is overhead, not model math);
mfu_pct is vs the v5e bf16 peak (197 TFLOP/s), hbm_pct is the achieved
fraction of HBM peak (819 GB/s) for the analytic minimum edge/vertex
stream traffic. Between them they say how far the epoch is from the
hardware ceiling no matter which resource binds.

Timing protocol for the tunneled chip: ``block_until_ready`` is NOT a
reliable completion barrier and repeated same-input dispatches can be
memoized, so run n epochs INSIDE one jit (lax.scan), force completion with
a scalar fetch, and report the delta between two scan lengths — per-call
RPC latency cancels out. If a rep round yields no positive delta (tunnel
noise), the round is retried; persistent failure reports NaN and exits
nonzero rather than a nonsense number (ADVICE r1 #3).

Env knobs: DGRAPH_BENCH_DTYPE (bfloat16|float32, default bfloat16),
DGRAPH_TPU_PALLAS_SCATTER (default on here), DGRAPH_BENCH_GRAPHCAST=0 to
skip stage 2, DGRAPH_BENCH_GC_LATENT / _GC_LEVEL to resize it.
"""

from __future__ import annotations

import json
import os
import sys
import time

V5E_PEAK_TFLOPS = 197.0  # bf16
V5E_PEAK_HBM_GBPS = 819.0


def log(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


_HEALTH_MOD = None
_HEALTH = None  # this process's RunHealth (child or supervisor)
_SPANS_MOD = None
_SUPERVISE_MOD = None
_LEDGER_MOD = None


def _load_standalone(name: str, *relpath: str):
    """Load a repo module by PATH without importing the dgraph_tpu
    package: the package __init__ imports jax, and the supervisor must
    never do that (a wedged lease hangs backend init inside a GIL-holding
    C call — the exact failure this harness exists to survive)."""
    import importlib.util

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), *relpath
    )
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    # register BEFORE exec: dataclass field-type resolution looks the
    # module up in sys.modules while the class is being built
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def _health_mod():
    """obs/health.py, standalone (it is dependency-free by contract)."""
    global _HEALTH_MOD
    if _HEALTH_MOD is None:
        _HEALTH_MOD = _load_standalone(
            "_dgraph_obs_health", "dgraph_tpu", "obs", "health.py"
        )
    return _HEALTH_MOD


def _spans_mod():
    """obs/spans.py, standalone (stdlib-only by the same lint-enforced
    contract): per-probe/per-stage spans from the supervisor and child,
    no-ops unless DGRAPH_TRACE=1. health.py's trace_id lookup finds this
    twin via sys.modules under the name registered here."""
    global _SPANS_MOD
    if _SPANS_MOD is None:
        _SPANS_MOD = _load_standalone(
            "_dgraph_obs_spans", "dgraph_tpu", "obs", "spans.py"
        )
    return _SPANS_MOD


def _ledger_mod():
    """obs/ledger.py, standalone (stdlib-only by the same lint-enforced
    contract): the perf-trajectory ledger. Registered as
    ``_dgraph_obs_ledger`` so supervise.py's lineage hook finds the same
    twin via sys.modules instead of importing the (jax-pulling)
    package."""
    global _LEDGER_MOD
    if _LEDGER_MOD is None:
        _LEDGER_MOD = _load_standalone(
            "_dgraph_obs_ledger", "dgraph_tpu", "obs", "ledger.py"
        )
    return _LEDGER_MOD


def _git_rev() -> str:
    """The commit every round JSON is stamped with (obs.health.git_rev:
    subprocess ``git rev-parse --short HEAD``, ``"unknown"`` fallback)."""
    try:
        return _health_mod().git_rev()
    except Exception:
        return "unknown"


def _ledger_ingest(out: dict) -> None:
    """Append the round's record to the perf ledger. Bench is the one
    emitter where the DGRAPH_LEDGER_DIR knob defaults ON (a bench round
    not in the trajectory is the empty-ledger problem all over again);
    maybe_ingest swallows every failure — the ledger must never cost
    the round's JSON line."""
    try:
        _ledger_mod().maybe_ingest(out, source="bench", default_on=True)
    except Exception as e:
        log(f"ledger ingest failed (ignored): {type(e).__name__}: {e}")


def _supervise_mod():
    """train/supervise.py, standalone (jax-free by the same lint-enforced
    contract): the backend-probe loop runs under the SAME restart/backoff/
    wall-budget policy as the train supervisor, so a wedged lease produces
    a ``supervise_lineage`` record instead of a hand-rolled retry loop's
    free text (ROADMAP item 5). The spans/health twins must be registered
    first — supervise.py detects them in sys.modules."""
    global _SUPERVISE_MOD
    if _SUPERVISE_MOD is None:
        _spans_mod()
        _health_mod()
        _SUPERVISE_MOD = _load_standalone(
            "_dgraph_train_supervise", "dgraph_tpu", "train", "supervise.py"
        )
    return _SUPERVISE_MOD


def _make_runner(scan_fn):
    """(params, opt_state, salt), n -> new state; the trailing float(s)
    scalar fetch is the only trustworthy completion barrier on the tunnel."""

    def run(state, n):
        p, o, s = scan_fn(*state, n)
        float(s)
        return (p, o, s)

    return run


def _timed_scan_ms(epochs_fn, state, n_long, reps=3, max_rounds=6):
    """Median positive (long-short)/(n_long-1) delta in ms; retries noisy
    rounds, returns (ms, state) or (nan, state) if the tunnel never yields a
    positive delta."""
    deltas = []
    rounds = 0
    while len(deltas) < reps and rounds < max_rounds:
        rounds += 1
        t0 = time.perf_counter()
        state = epochs_fn(state, 1)
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        state = epochs_fn(state, n_long)
        t_long = time.perf_counter() - t0
        d = (t_long - t1) / (n_long - 1) * 1000.0
        log(f"  round {rounds}: 1-iter {t1*1000:.1f} ms, {n_long}-iter "
            f"{t_long*1000:.1f} ms -> {d:.2f} ms/iter")
        if d > 0:
            deltas.append(d)
    if not deltas:
        return float("nan"), state
    ds = sorted(deltas)
    mid = len(ds) // 2
    median = ds[mid] if len(ds) % 2 else (ds[mid - 1] + ds[mid]) / 2
    return median, state


# One dtype/precision/tolerance table for EVERY chip self-check: f32/highest
# (atomicAdd-parity path) AND bf16/default (the dtype+precision the bf16
# training VJPs actually emit — a Mosaic acc-dtype bug is invisible to the
# f32 check alone, seen r2). Resolved lazily (jnp import).
def _selfcheck_cases():
    import jax.numpy as jnp

    return [(jnp.float32, "highest", 1e-4), (jnp.bfloat16, "default", 5e-2)]


def _check_one(label: str, run, ref, tol) -> bool:
    """Shared try/compare/log verdict for a chip self-check case."""
    import numpy as np

    try:
        got = np.asarray(run())
        ok = bool(np.allclose(got, ref, rtol=tol, atol=tol))
    except Exception as e:  # Mosaic compile failure = exactly what we gate on
        log(f"self-check {label} raised {type(e).__name__}: {e}")
        ok = False
    log(f"self-check on chip {label}: {'OK' if ok else 'FAILED'}")
    return ok


def pallas_selfcheck() -> bool:
    """Chip-gated Pallas correctness check (VERDICT r1 weak #3): the Mosaic
    lowering class of bug is invisible to the interpret-mode CI tests, so
    verify the real kernel against numpy right before using it."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return False
    from dgraph_tpu.ops.pallas_segment import max_chunks_hint, sorted_segment_sum

    rng = np.random.default_rng(7)
    E, N, F = 8192, 2048, 128
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    data = rng.standard_normal((E, F)).astype(np.float32)
    want = np.zeros((N, F), np.float32)
    np.add.at(want, ids, data)
    ok = True
    # check the exact tile configs the plans emit (a Mosaic bug can be
    # tile-size-dependent), plus the library default
    from dgraph_tpu.plan import SCATTER_BLOCK_E, SCATTER_BLOCK_N

    configs = {(512, 256), (SCATTER_BLOCK_E, SCATTER_BLOCK_N)}
    for be, bn in sorted(configs):
        for dt, prec, tol in _selfcheck_cases():
            ok &= _check_one(
                f"scatter(be={be},bn={bn},{dt.__name__})",
                lambda dt=dt, prec=prec, be=be, bn=bn: sorted_segment_sum(
                    jnp.asarray(data, dt), jnp.asarray(ids), N,
                    max_chunks_per_block=max_chunks_hint(
                        ids, N, block_e=be, block_n=bn
                    ),
                    block_e=be, block_n=bn, precision=prec,
                ).astype(jnp.float32),
                want, tol,
            )
    return ok


def pallas_fused_selfcheck() -> tuple[bool, bool]:
    """Chip gate for the FUSED bias+relu scatter kernel, returning
    (forward_ok, bwd_pair_ok). Graduated veto (ADVICE r4): a Mosaic
    regression in only the backward KERNEL PAIR disables just
    use_pallas_fused_bwd — the fused forward keeps running with the
    composed backward — while a forward failure vetoes the whole op."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return False, False
    from dgraph_tpu.ops.pallas_segment import (
        max_chunks_hint,
        sorted_segment_sum_bias_relu,
    )
    from dgraph_tpu.plan import SCATTER_BLOCK_E, SCATTER_BLOCK_N

    rng = np.random.default_rng(11)
    E, N, F = 8192, 2048, 128
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-64:] = N + 1  # padded-edge tail
    data = rng.standard_normal((E, F)).astype(np.float32)
    bias = rng.standard_normal((N, F)).astype(np.float32)
    w = rng.uniform(0.5, 2.0, E).astype(np.float32)
    want = np.zeros((N, F), np.float32)
    wantw = np.zeros((N, F), np.float32)
    for e in range(E):
        if ids[e] >= N:
            continue
        m = np.maximum(data[e] + bias[ids[e]], 0)
        want[ids[e]] += m
        wantw[ids[e]] += w[e] * m
    ok = True
    be, bn = SCATTER_BLOCK_E, SCATTER_BLOCK_N
    mc = max_chunks_hint(ids, N, block_e=be, block_n=bn)
    for dt, prec, tol in _selfcheck_cases():
        for use_w, ref in [(False, want), (True, wantw)]:
            ok &= _check_one(
                f"fused-bias-relu({dt.__name__},w={use_w})",
                lambda dt=dt, prec=prec, use_w=use_w: sorted_segment_sum_bias_relu(
                    jnp.asarray(data, dt), jnp.asarray(ids),
                    jnp.asarray(bias, dt), N,
                    edge_weight=jnp.asarray(w, dt) if use_w else None,
                    max_chunks_per_block=mc, block_e=be, block_n=bn,
                    precision=prec,
                ).astype(jnp.float32),
                ref, tol,
            )
    if not ok:
        return False, False  # forward broken: nothing downstream to save
    # gradient check: the unweighted VJP runs the fused-bwd KERNEL PAIR
    # (chunk-major gd kernel + epilogue="act" d_bias reduction) when
    # gather_mv > 0 — a Mosaic miscompile there would silently corrupt
    # training, so the chip gate must cover it too. Reference grads by
    # numpy: d_data[e] = act_e * g[ids[e]]; d_bias[v] = g[v] * count_v.
    from dgraph_tpu.ops.pallas_segment import max_vblocks_hint

    mv = max_vblocks_hint(ids, N, block_e=be, block_n=bn)
    tgt = rng.standard_normal((N, F)).astype(np.float32)
    gd_want = np.zeros((E, F), np.float32)
    db_want = np.zeros((N, F), np.float32)
    for e in range(E):
        if ids[e] >= N:
            continue
        act_e = (data[e] + bias[ids[e]] > 0).astype(np.float32)
        gd_want[e] = act_e * tgt[ids[e]]
        db_want[ids[e]] += act_e
    db_want *= tgt

    def loss(d, b):
        out = sorted_segment_sum_bias_relu(
            d, jnp.asarray(ids), b, N, max_chunks_per_block=mc,
            block_e=be, block_n=bn, gather_mv=mv, precision="highest",
        )
        return (out.astype(jnp.float32) * jnp.asarray(tgt)).sum()

    def grads():
        gd, db = jax.grad(loss, argnums=(0, 1))(
            jnp.asarray(data), jnp.asarray(bias)
        )
        # one array so _check_one's single compare covers both
        return jnp.concatenate(
            [gd.astype(jnp.float32).ravel(), db.astype(jnp.float32).ravel()]
        )

    bwd_ok = _check_one(
        "fused-bwd-kernel-pair(grads,f32)", grads,
        np.concatenate([gd_want.ravel(), db_want.ravel()]), 2e-4,
    )

    # bf16/default kernel-pair grads vs the COMPOSED backward (gather_mv=0
    # disables the pair) at the SAME bf16 rounding — an f32 reference
    # would differ by whole elements at ReLU-boundary mask flips. The
    # bf16 variant is the one bf16 training actually runs; "a Mosaic
    # acc-dtype bug is invisible to the f32 check alone" (r2).
    def grads_bf16(gmv):
        def lo(d, b):
            out = sorted_segment_sum_bias_relu(
                d, jnp.asarray(ids), b, N, max_chunks_per_block=mc,
                block_e=be, block_n=bn, gather_mv=gmv, precision="default",
            )
            return (out.astype(jnp.float32) * jnp.asarray(tgt)).sum()

        gd, db = jax.grad(lo, argnums=(0, 1))(
            jnp.asarray(data, jnp.bfloat16), jnp.asarray(bias, jnp.bfloat16)
        )
        return jnp.concatenate(
            [gd.astype(jnp.float32).ravel(), db.astype(jnp.float32).ravel()]
        )

    try:
        ref_bf16 = np.asarray(grads_bf16(0))
    except Exception as e:  # composed-reference failure: the fused op's
        # own fallback bwd is broken — veto the whole op, not just the pair
        log(f"self-check fused-bwd-kernel-pair(grads,bf16) reference "
            f"raised {type(e).__name__}: {e}")
        return False, False
    bwd_ok &= _check_one(
        "fused-bwd-kernel-pair(grads,bf16)", lambda: grads_bf16(mv),
        ref_bf16, 5e-2,
    )
    return ok, bwd_ok


def pallas_gather_selfcheck() -> bool:
    """Chip gate for the sorted ROW-GATHER kernel. Only consulted when the
    env pins DGRAPH_TPU_PALLAS_GATHER=1 (the kernel is explicit-opt-in
    until on-chip A/B data exists); the check still has the final veto."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    if jax.default_backend() != "tpu":
        return False
    from dgraph_tpu.ops.pallas_segment import (
        max_chunks_hint,
        max_vblocks_hint,
        sorted_row_gather,
    )

    from dgraph_tpu.plan import SCATTER_BLOCK_E, SCATTER_BLOCK_N

    rng = np.random.default_rng(13)
    E, N, F = 8192, 2048, 128
    ids = np.sort(rng.integers(0, N, E)).astype(np.int32)
    ids[-64:] = N + 1
    x = rng.standard_normal((N, F)).astype(np.float32)
    want = np.where((ids < N)[:, None], x[np.clip(ids, 0, N - 1)], 0.0)
    ok = True
    # the exact tile configs the plans emit, plus the library default
    # (Mosaic bugs can be tile-size-dependent — same invariant as
    # pallas_selfcheck)
    for be, bn in sorted({(512, 256), (SCATTER_BLOCK_E, SCATTER_BLOCK_N)}):
        mv = max_vblocks_hint(ids, N, block_e=be, block_n=bn)
        mc = max_chunks_hint(ids, N, block_e=be, block_n=bn)
        for dt, prec, tol in _selfcheck_cases():
            ok &= _check_one(
                f"sorted-gather(be={be},bn={bn},{dt.__name__})",
                lambda dt=dt, prec=prec, be=be, bn=bn, mv=mv, mc=mc:
                sorted_row_gather(
                    jnp.asarray(x, dt), jnp.asarray(ids), max_vblocks=mv,
                    block_e=be, block_n=bn, scatter_mc=mc, precision=prec,
                ).astype(jnp.float32),
                want, tol,
            )
    return ok


def bench_gcn(dtype_name: str):
    import functools

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.data.synthetic import ARXIV_EDGES, ARXIV_NODES, random_edges
    from dgraph_tpu.models import GCN
    from dgraph_tpu.plan import build_edge_plan

    # ogbn-arxiv shape (V=169343, E~1.17M directed, symmetrized ~2.33M) —
    # the same construction (data.synthetic.random_edges) the tune CLI
    # signs, so `python -m dgraph_tpu.tune` records adopt here
    V, E_half, F, C, H = ARXIV_NODES, ARXIV_EDGES, 128, 40, 256
    if os.environ.get("DGRAPH_BENCH_SMOKE") == "1":  # CPU path validation
        V, E_half, F, C, H = 4_096, 16_384, 32, 8, 64
    edge_index = random_edges(V, E_half, seed=0)

    # tuning-record adoption (dgraph_tpu.tune): a persisted winner for this
    # exact workload signature overrides the hard-coded pad_multiple and
    # halo lowering; the record id rides the output JSON either way so the
    # number is attributable to its config (null = defaults)
    from dgraph_tpu.tune.record import (
        adopt_record,
        clear_adoption,
        lookup_record,
    )
    from dgraph_tpu.tune.signature import graph_signature

    pad_multiple, record_id, tuned_halo_impl = 128, None, None
    sig = graph_signature(edge_index, V, 1, dtype=dtype_name, feat_dim=F)
    rec = lookup_record(sig)
    if rec is not None:
        tuned = adopt_record(rec)
        pad_multiple = tuned.get("pad_multiple", pad_multiple)
        record_id = rec.record_id
        tuned_halo_impl = rec.config.get("halo_impl")
        log(f"tuning record {record_id} adopted "
            f"(pad_multiple={pad_multiple}, halo_impl={tuned_halo_impl})")
    else:
        clear_adoption()

    log("building plan (host)...")
    part = np.zeros(V, np.int32)  # single-chip: world size 1
    plan_np, _ = build_edge_plan(
        edge_index, part, world_size=1, edge_owner="dst",
        pad_multiple=pad_multiple,
        # both split lowerings ride the interior/boundary split
        overlap=True if tuned_halo_impl in ("overlap", "pallas_p2p") else None,
    )
    # interior/boundary split of the workload (plan.py): the boundary
    # fraction bounds the halo payload, the interior fraction bounds what
    # the overlap lowering can hide it behind — reported next to the
    # adopted record so the lowering choice is auditable from the JSON
    from dgraph_tpu.plan import interior_boundary_edge_counts

    edge_split = interior_boundary_edge_counts(plan_np)
    log(f"edge split: interior {edge_split['interior_frac']:.3f} / "
        f"boundary {edge_split['boundary_frac']:.3f}")
    log("moving plan to device...")
    plan = jax.tree.map(lambda leaf: jnp.asarray(np.asarray(leaf)[0]), plan_np)
    jax.block_until_ready(jax.tree.leaves(plan))

    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    comm = Communicator.init_process_group("single")
    model = GCN(hidden_features=H, out_features=C, comm=comm, num_layers=2, dtype=dtype)

    log("generating data on device...")
    x = jax.random.normal(jax.random.key(0), (plan_np.n_src_pad, F), jnp.float32)
    y = jax.random.randint(jax.random.key(1), (plan_np.n_src_pad,), 0, C)
    mask = (jnp.arange(plan_np.n_src_pad) < V).astype(jnp.float32)
    jax.block_until_ready(x)

    log("initializing model...")
    params = model.init(jax.random.key(2), x, plan)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    @functools.partial(jax.jit, static_argnames="n", donate_argnums=(0, 1))
    def epochs(params, opt_state, salt, n):
        def lf(p):
            logits = model.apply(p, x, plan)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
            return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def body(carry, _):
            p, o, s = carry
            loss, grads = jax.value_and_grad(lf)(p)
            updates, o = optimizer.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o, s + loss * 1e-20), None

        (p, o, s), _ = jax.lax.scan(body, (params, opt_state, salt), None, length=n)
        return p, o, s

    N_LONG = 6
    log(f"compiling (n=1 and n={N_LONG})...")
    state = (params, opt_state, jnp.float32(0.0))
    run = _make_runner(epochs)
    state = run(state, 1)
    state = run(state, N_LONG)
    log("warmup done; timing...")
    dt_ms, state = _timed_scan_ms(run, state, N_LONG)

    # --- roofline context ---
    Vp, Ep = plan_np.n_src_pad, plan_np.e_pad
    b = 2 if dtype_name == "bfloat16" else 4
    # dense model FLOPs: fwd projections (2 per conv layer) + head; x3 for
    # fwd+bwd (dgrad+wgrad)
    dense_fwd = 2 * Vp * F * H * 2 + 2 * Vp * H * H * 2 + 2 * Vp * H * C
    model_flops = 3 * dense_fwd
    # analytic minimum HBM stream traffic per epoch (each E-row tensor
    # counted once per producing/consuming op):
    #   fwd/layer: 2 gathers (write E.H + read V.H each) + 1 scatter
    #     (read E.H, write V.H)
    #   bwd/layer: 1 take (write E.H, read V.H) + 2 segment sums
    #     (read E.H, write V.H each)
    per_layer = 6 * (Ep * H + Vp * H) * b
    hbm_bytes = 2 * per_layer + 3 * (Vp * (F + H) * b)  # + input/proj streams
    # the RESOLVED lowering + deciding source (env pin > record >
    # heuristic > plan), not just the record's wish: an env-pinned or
    # heuristic-chosen lowering was previously invisible in BENCH_r*.json.
    # On this single-chip plan the truthful resolution is usually
    # ('none', 'plan'); halo_impl_env_pin records the operator's raw
    # request alongside, so a pinned-but-degraded state is still visible.
    from dgraph_tpu import config as _dcfg
    from dgraph_tpu.plan import resolve_halo_impl

    _schedule = getattr(plan_np, "halo_schedule", None)
    halo_impl, halo_impl_source = resolve_halo_impl(
        plan_np.world_size, plan_np.halo_deltas,
        overlap_available=plan_np.overlap is not None,
        sched_available=_schedule is not None,
        pair_rows=getattr(plan_np, "halo_pair_rows", ()),
    )
    # the RESOLVED wire format rides the JSON the same way: which codec
    # this run's halo payloads would ship with, who decided (env > record
    # > plan > fp32 default), and the operator's raw env pin
    from dgraph_tpu.wire.spec import resolve_wire_format

    wire_format, wire_format_source = resolve_wire_format(
        plan_np.world_size, tuple(plan_np.halo_deltas),
        plan_format=getattr(plan_np, "wire_format", "fp32"),
    )
    split_info = {
        "interior_edge_frac": round(edge_split["interior_frac"], 4),
        "boundary_edge_frac": round(edge_split["boundary_frac"], 4),
        "tuned_halo_impl": tuned_halo_impl,
        "halo_impl": halo_impl,
        "halo_impl_source": halo_impl_source,
        "halo_impl_env_pin": _dcfg.halo_impl,
        "wire_format": wire_format,
        "wire_format_source": wire_format_source,
        "wire_format_env_pin": _dcfg.wire_format,
        # compiled-schedule identity (dgraph_tpu.sched): the content hash
        # names the exact round order this plan would replay under
        # halo_impl='sched', whether or not sched was the resolved impl
        "halo_schedule_id": _schedule.schedule_id if _schedule else None,
        "halo_schedule_rounds": _schedule.num_rounds if _schedule else 0,
    }
    if _schedule is not None:
        # the compiled schedule joins the perf ledger as its own record
        # kind (regress byte-exact-gates rounds/bytes across commits);
        # _ledger_ingest swallows failures, same as the round JSON
        _ledger_ingest({
            "kind": "sched_compile",
            "workload": {"world_size": plan_np.world_size,
                         "nodes": Vp, "hidden": H},
            "schedule_id": _schedule.schedule_id,
            "rounds": _schedule.num_rounds,
            "transfers": _schedule.num_transfers,
            "operand_bytes_per_shard": sum(_schedule.round_rows()) * H * b,
            "round_rows": list(_schedule.round_rows()),
            "git_rev": _git_rev(),
        })
    # the resolved wire format joins the ledger too: operand_bytes rides
    # regress's byte-exact class, so a codec or pricing change that
    # alters what this workload ships on the wire goes RED across
    # commits (footprint prices the exchange at the resolved format)
    from dgraph_tpu.obs.footprint import plan_footprint

    _fp_ex = plan_footprint(
        plan_np, dtype_name, H
    )["collectives"]["halo_exchange"]
    _ledger_ingest({
        "kind": "wire_compile",
        "workload": {"world_size": plan_np.world_size,
                     "nodes": Vp, "hidden": H},
        "wire_format": wire_format,
        "wire_format_source": wire_format_source,
        "halo_impl": halo_impl,
        "operand_bytes": _fp_ex["operand_bytes_per_shard"],
        "compression_ratio": _fp_ex["compression_ratio"],
        "git_rev": _git_rev(),
    })
    if dt_ms != dt_ms:  # NaN timing: no roofline numbers (keep JSON valid;
        # the record id still rides along — a null metric must stay
        # attributable to the config that failed to produce it)
        return dt_ms, {"tuning_record": record_id, **split_info}
    secs = dt_ms / 1e3
    tflops_s = model_flops / secs / 1e12
    gbps = hbm_bytes / secs / 1e9
    return dt_ms, {
        "model_tflops_s": round(tflops_s, 2),
        "mfu_pct": round(100 * tflops_s / V5E_PEAK_TFLOPS, 2),
        "hbm_gbps_min": round(gbps, 1),
        "hbm_pct": round(100 * gbps / V5E_PEAK_HBM_GBPS, 1),
        "tuning_record": record_id,
        **split_info,
    }


def bench_graphcast(dtype_name: str, level: "int | None" = None):
    """GraphCast train-step time at reference scale (level-6 mesh,
    721x1440 grid) on one chip. Plans come from the host; all feature data
    is generated on device (tunnel budget)."""
    import functools

    import numpy as np
    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.models.graphcast import GraphCast, build_graphcast_graphs

    if level is None:
        level = int(os.environ.get("DGRAPH_BENCH_GC_LEVEL", "6"))
    latent = int(os.environ.get("DGRAPH_BENCH_GC_LATENT", "256"))
    layers = int(os.environ.get("DGRAPH_BENCH_GC_LAYERS", "16"))
    nlat, nlon, ch = 721, 1440, 73
    if os.environ.get("DGRAPH_BENCH_SMOKE") == "1":  # CPU path validation
        level, latent, layers, nlat, nlon, ch = 1, 16, 2, 19, 36, 8
    log(f"graphcast: building level-{level} graphs on host...")
    t0 = time.time()
    graphs = build_graphcast_graphs(level, nlat, nlon, 1)
    log(f"graphcast: graphs built in {time.time()-t0:.1f}s "
        f"(g2m={graphs.g2m_plan.e_pad} m2g={graphs.m2g_plan.e_pad} "
        f"mesh={graphs.mesh_plan.e_pad})")

    dtype = jnp.bfloat16 if dtype_name == "bfloat16" else jnp.float32
    comm = Communicator.init_process_group("single")
    model = GraphCast(
        comm=comm, latent=latent, processor_layers=layers, out_channels=ch,
        dtype=dtype,
    )

    def dev(a):
        return jnp.asarray(np.asarray(a)[0])

    statics = {
        "grid_node_static": dev(graphs.grid_node_static),
        "mesh_node_static": dev(graphs.mesh_node_static),
        "mesh_edge_static": dev(graphs.mesh_edge_static),
        "g2m_edge_static": dev(graphs.g2m_edge_static),
        "m2g_edge_static": dev(graphs.m2g_edge_static),
    }
    plans = {
        "mesh": jax.tree.map(dev, graphs.mesh_plan),
        "g2m": jax.tree.map(dev, graphs.g2m_plan),
        "m2g": jax.tree.map(dev, graphs.m2g_plan),
    }
    jax.block_until_ready(jax.tree.leaves((statics, plans)))
    log("graphcast: statics+plans on device")

    n_grid = plans["g2m"].n_src_pad
    x = jax.random.normal(jax.random.key(3), (n_grid, ch), jnp.float32)
    y = jax.random.normal(jax.random.key(4), (n_grid, ch), jnp.float32)
    gmask = dev(graphs.grid_mask)

    # init on a TINY level-1 graph: params depend only on feature dims
    # (statics are 4-wide at every level), and an eager full-scale init
    # materializes the level-6 forward's intermediates op-by-op — the OOM
    # seen in the first r2 capture happened here, not in the step itself.
    tiny = build_graphcast_graphs(1, 10, 18, 1)
    t_statics = {
        "grid_node_static": dev(tiny.grid_node_static),
        "mesh_node_static": dev(tiny.mesh_node_static),
        "mesh_edge_static": dev(tiny.mesh_edge_static),
        "g2m_edge_static": dev(tiny.g2m_edge_static),
        "m2g_edge_static": dev(tiny.m2g_edge_static),
    }
    t_plans = {
        "mesh": jax.tree.map(dev, tiny.mesh_plan),
        "g2m": jax.tree.map(dev, tiny.g2m_plan),
        "m2g": jax.tree.map(dev, tiny.m2g_plan),
    }
    x_tiny = jnp.zeros((t_plans["g2m"].n_src_pad, ch), jnp.float32)
    params = model.init(jax.random.key(5), x_tiny, t_statics, t_plans)
    opt = optax.adamw(1e-4, weight_decay=0.1)
    opt_state = opt.init(params)
    log("graphcast: params initialized; compiling step scan...")

    @functools.partial(jax.jit, static_argnames="n", donate_argnums=(0, 1))
    def steps(params, opt_state, salt, n):
        def lf(p):
            pred = model.apply(p, x, statics, plans)
            se = ((pred - y) ** 2).sum(-1) * gmask
            return se.sum() / jnp.maximum(gmask.sum(), 1.0)

        def body(carry, _):
            p, o, s = carry
            loss, grads = jax.value_and_grad(lf)(p)
            updates, o = opt.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o, s + loss * 1e-20), None

        (p, o, s), _ = jax.lax.scan(body, (params, opt_state, salt), None, length=n)
        return p, o, s

    run = _make_runner(steps)
    state = (params, opt_state, jnp.float32(0.0))
    state = run(state, 1)
    state = run(state, 4)
    log("graphcast: warmup done; timing...")
    ms, _ = _timed_scan_ms(run, state, 4)
    return ms, {"level": level, "latent": latent, "layers": layers}


# Partial results land here as soon as each stage measures, so the
# watchdog can emit the best-known JSON instead of null when a LATER stage
# (e.g. the GraphCast level-6 compile) blows the budget.
_PARTIAL: dict = {}


def _note_partial(**kw):
    """Record a finished stage in-process AND in the supervisor's state
    file: a hang inside a GIL-holding C call (observed: backend init on a
    wedged lease) silences SIGALRM, so the supervisor process is the only
    layer that can always emit the JSON — it needs the partials on disk."""
    _PARTIAL.update(kw)
    path = os.environ.get("DGRAPH_BENCH_STATE")
    if path:
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(_PARTIAL, f)
            os.replace(tmp, path)
        except OSError as e:
            log(f"state-file write failed: {e}")

# Exit-code contract (ADVICE r2 #4 — callers must be able to tell complete
# / partial / empty apart from rc alone):
#   0 = complete run, all stages measured
#   2 = ran but the timing protocol never got a positive delta (NaN)
#   3 = no metric at all (wedge/backend failure with nothing salvaged)
#   4 = PARTIAL: the primary GCN metric exists but a later stage was cut
#   5 = backend init failed after retries (JSON still emitted)
EXIT_PARTIAL, EXIT_EMPTY, EXIT_BACKEND = 4, 3, 5


def _failure_json(error: str, state: dict, empty_rc: int, wedge=None):
    """The ONE place the failure-path output schema + partial/empty rc rule
    live (child watchdog, child exception paths, and the supervisor all
    funnel here — forking the schema between them would be silent). When
    this process carries a RunHealth record (child or supervisor), it is
    embedded so the artifact alone explains the null (obs.health)."""
    out = {
        "metric": "arxiv_gcn_epoch_time", "value": None, "unit": "ms",
        "vs_baseline": None, "error": error,
        # even a null round is attributable to a commit (the ledger's
        # bisect key)
        "git_rev": _git_rev(),
    }
    out.update(state)  # keep any stage that DID finish
    if _HEALTH is not None:
        role = "supervisor" if "supervisor" in _HEALTH.component else "child"
        out.setdefault("run_health", {})[role] = _HEALTH.finish(error, wedge)
    return out, (EXIT_PARTIAL if state.get("value") else empty_rc)


def _emit_json_and_exit(error: str, empty_rc: int, wedge=None):
    """Child-side abnormal exit: ONE structured JSON line with whatever
    stages did finish (r1+r2 both died as rc=1 tracebacks with parsed:null
    — that class of loss is designed out). Emit sites that KNOW their
    wedge class pass it explicitly so classification never depends on
    substring-matching the error prose (obs.health.classify_wedge stays
    the fallback for sites that don't)."""
    out, rc = _failure_json(error, _PARTIAL, empty_rc, wedge)
    print(json.dumps(out))
    sys.stdout.flush()
    os._exit(rc)


def _arm_watchdog():
    """A wedged tunnel lease hangs ANY device op indefinitely (observed
    r1+r2); fail loudly with a JSON line instead of hanging the driver."""
    import signal

    budget = int(os.environ.get("DGRAPH_BENCH_TIMEOUT", "2400"))

    def _bail(signum, frame):
        _emit_json_and_exit(
            f"watchdog: incomplete within {budget}s (wedged TPU lease?)",
            EXIT_EMPTY, wedge="watchdog_timeout",
        )

    signal.signal(signal.SIGALRM, _bail)
    signal.alarm(budget)
    return budget


def _expected_platform():
    """The platform the bench is REQUIRED to land on. jax's fail_quietly
    path silently falls back to CPU when the tpu plugin can't init (wedged
    lease) — without this check a CPU timing could be recorded as the
    round's chip metric. Explicit JAX_PLATFORMS / smoke mode opt out."""
    if os.environ.get("DGRAPH_BENCH_SMOKE") == "1":
        return None
    forced = os.environ.get("JAX_PLATFORMS", "")
    if forced and "tpu" not in forced and "axon" not in forced:
        return None  # caller explicitly pinned a non-TPU platform
    return "tpu"


def _init_backend_fail_fast():
    """jax.devices() raises UNAVAILABLE when the tunnel lease is wedged at
    startup — the exact failure that zeroed BENCH_r01+r02. JAX caches a
    failed (or wrong-platform) backend init IN-PROCESS, so in-child
    retries mostly re-raise the cached error; the retry that actually
    works is the supervisor's fresh-process respawn (rc=EXIT_BACKEND →
    phase-2 respawn loop). One immediate second attempt covers the only
    in-process-recoverable case (a transient RPC error before the cache
    is populated); anything else fails fast (ADVICE r3 #1)."""
    import jax

    want = _expected_platform()
    if not want:
        # smoke / explicitly non-TPU: re-pin the requested platform via
        # jax.config — the axon sitecustomize re-pins jax_platforms at
        # startup, so the env var alone would leave the child dialing the
        # (possibly wedged) lease. Honor an explicit non-cpu request.
        jax.config.update(
            "jax_platforms", os.environ.get("JAX_PLATFORMS") or "cpu")
    last = None
    for attempt in (1, 2):
        try:
            devs = jax.devices()
            got = jax.default_backend()
            if want and got != want:
                # the wrong backend is now CACHED in-process; retrying
                # can't fix it — fail structured, immediately
                if _HEALTH is not None:
                    _HEALTH.backend = {"platform": got, "expected": want}
                _emit_json_and_exit(
                    f"backend is '{got}', need '{want}' (silent CPU "
                    f"fallback from a wedged lease?)", EXIT_BACKEND,
                    wedge="backend_lost")
            log(f"devices ({got}): {devs}")
            return
        except Exception as e:  # noqa: BLE001
            last = f"{type(e).__name__}: {e}"
            log(f"backend init attempt {attempt} failed: "
                f"{last.splitlines()[0]}")
            if attempt == 1:
                time.sleep(5)
    if _HEALTH is not None:
        # do NOT re-probe via snapshot_backend here: on a wedged lease
        # another jax.devices() can hang past the watchdog's reach
        _HEALTH.backend = {"error": last}
    _emit_json_and_exit(
        f"backend init failed (fail-fast; supervisor respawns): {last}",
        EXIT_BACKEND, wedge="backend_lost",
    )


def _hbm_peak_gb():
    """Cumulative peak HBM (GB) so OOM regressions show as numbers, not
    crashes (VERDICT r2 next #7). PJRT exposes no reset, so per-stage
    attribution is by ordering: read after each stage; a later stage's
    value is that stage's peak iff it exceeds the earlier ones."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and "peak_bytes_in_use" in stats:
            return round(stats["peak_bytes_in_use"] / 1e9, 3)
    except Exception as e:
        log(f"memory_stats unavailable: {type(e).__name__}: {e}")
    return None


def _child_main():
    global _HEALTH

    t_start = time.time()
    _HEALTH = _health_mod().RunHealth.begin("bench.child")
    _arm_watchdog()
    log("importing jax...")
    import jax  # noqa: F401

    _init_backend_fail_fast()
    # backend is up: record the topology the numbers were measured on
    _HEALTH.snapshot_backend()

    from dgraph_tpu import config as cfg

    dtype_name = os.environ.get("DGRAPH_BENCH_DTYPE", "bfloat16")
    # Pallas scatter: default ON for the bench (A/B'd on chip; see
    # logs/kernels_r2.jsonl + VERDICT r1 next-round #2), unless the chip
    # self-check fails or the env explicitly disables it (config.py parsed
    # the tri-state env already — don't re-parse with different semantics).
    want_pallas = cfg.use_pallas_scatter is not False
    cfg.set_flags(use_pallas_scatter=want_pallas and pallas_selfcheck())
    # fused kernel: genuinely independent kill switch. Enabled when the env
    # pins it ON (even with plain scatter off — the A/B-the-fused-alone
    # case) or, in auto mode, when the plain kernel is on; either way the
    # chip self-check has the final veto.
    if cfg.use_pallas_fused is False:
        fused_wanted = False
    elif cfg.use_pallas_fused is True:
        fused_wanted = True
    else:  # auto: follow the plain-scatter decision
        fused_wanted = cfg.use_pallas_scatter
    fused_fwd_ok, fused_bwd_ok = (
        pallas_fused_selfcheck() if fused_wanted else (False, False)
    )
    cfg.set_flags(use_pallas_fused=fused_wanted and fused_fwd_ok)
    # graduated veto: a bwd-pair-only Mosaic failure keeps the fused
    # forward (composed bwd) instead of losing the whole op; an env pin
    # (use_pallas_fused_bwd is False) is already respected by the VJP
    if fused_wanted and fused_fwd_ok and not fused_bwd_ok:
        log("fused-bwd kernel pair vetoed by self-check; "
            "keeping fused fwd with the composed backward")
        cfg.set_flags(use_pallas_fused_bwd=False)
    # sorted row-gather kernel: explicit opt-in only (no auto state yet —
    # see config.use_pallas_gather); the chip self-check has the veto
    if cfg.use_pallas_gather is True:
        cfg.set_flags(use_pallas_gather=pallas_gather_selfcheck())

    sp = _spans_mod()  # stage spans join the supervisor's trace when on
    try:
        with sp.span("bench.gcn", dtype=dtype_name):
            dt_ms, roof = bench_gcn(dtype_name)
    except Exception as e:  # emit JSON, never a bare traceback
        _emit_json_and_exit(f"gcn stage failed: {type(e).__name__}: {e}",
                            EXIT_EMPTY, wedge="stage_failure")
    hbm_gcn = _hbm_peak_gb()
    log(f"gcn epoch time {dt_ms:.2f} ms {roof} hbm_peak={hbm_gcn} GB")
    smoke = os.environ.get("DGRAPH_BENCH_SMOKE") == "1"
    vs = None  # null when there is no measurement (don't imply parity)
    if dt_ms == dt_ms and not smoke:  # a CPU smoke number vs the chip
        # baseline would be a fake metric — the class this harness guards
        base_path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json"
        )
        try:
            base = json.load(open(base_path))
            if base.get("unit") == "ms" and base.get("value"):
                vs = round(float(base["value"]) / dt_ms, 4)  # >1 = faster
        except Exception:
            pass
        # record for the watchdog's partial-result JSON (the GraphCast
        # compile below can blow the budget; the GCN metric must survive)
        _note_partial(value=round(dt_ms, 3), vs_baseline=vs, **roof,
                      hbm_peak_gb_gcn=hbm_gcn)

    gc_ms, gc_info, hbm_gc = float("nan"), {}, None
    gc_enabled = os.environ.get("DGRAPH_BENCH_GRAPHCAST", "1") != "0"
    if gc_enabled:
        # level-fallback ladder: a level-6 OOM must still produce a
        # GraphCast number at the largest level that fits one chip (the
        # config records which level, so a fallback can't masquerade as
        # the reference-scale result). An explicit DGRAPH_BENCH_GC_LEVEL
        # pins a single level (no ladder) — that's the A/B knob.
        if os.environ.get("DGRAPH_BENCH_GC_LEVEL"):
            ladder = [int(os.environ["DGRAPH_BENCH_GC_LEVEL"])]
        elif os.environ.get("DGRAPH_BENCH_SMOKE") == "1":
            ladder = [1]
        else:
            ladder = [6, 5, 4]
        failed_levels = []
        for gc_level in ladder:
            try:
                with sp.span("bench.graphcast", level=gc_level):
                    gc_ms, gc_info = bench_graphcast(dtype_name, level=gc_level)
                if failed_levels:
                    # PJRT's peak counter is cumulative with no reset, so
                    # after a bigger level OOM'd the reading is THAT
                    # level's near-capacity peak, not this one's footprint
                    # — reporting it would claim the fallback barely fits
                    hbm_gc = None
                    gc_info = dict(gc_info,
                                   hbm_tainted_by_failed_levels=failed_levels)
                else:
                    hbm_gc = _hbm_peak_gb()
                log(f"graphcast step time {gc_ms:.2f} ms {gc_info} "
                    f"hbm_peak={hbm_gc} GB")
                if gc_ms == gc_ms:
                    _note_partial(
                        graphcast_step_ms=round(gc_ms, 2),
                        graphcast_config=gc_info,
                        hbm_peak_gb_graphcast=hbm_gc,
                    )
                break
            except Exception as e:  # stage-2 failure must not kill the metric
                log(f"graphcast level {gc_level} failed: "
                    f"{type(e).__name__}: {e}")
                failed_levels.append(gc_level)

    out = {
        "metric": "arxiv_gcn_epoch_time",
        "value": round(dt_ms, 3) if dt_ms == dt_ms else None,
        "unit": "ms",
        "git_rev": _git_rev(),
        "vs_baseline": vs,
        **roof,
        "hbm_peak_gb_gcn": hbm_gcn,
        "graphcast_step_ms": round(gc_ms, 2) if gc_ms == gc_ms else None,
        "graphcast_config": gc_info,
        "hbm_peak_gb_graphcast": hbm_gc,
        "config": {
            "dtype": dtype_name,
            "pallas_scatter": cfg.use_pallas_scatter,
            "pallas_fused": cfg.use_pallas_fused,
            "pallas_gather": cfg.use_pallas_gather,
            "smoke": smoke,  # True = tiny-shape CPU validation run, NOT a
            # chip measurement (platform guard is disabled in smoke mode)
        },
        "wall_s": round(time.time() - t_start, 1),
        # a healthy run records its health too: the artifact documents the
        # topology/config the numbers came from, not only failures
        "run_health": {"child": _HEALTH.finish()},
    }
    print(json.dumps(out))
    if dt_ms != dt_ms:  # NaN: tunnel never produced a positive delta
        sys.exit(2)
    if gc_ms != gc_ms and gc_enabled:
        sys.exit(EXIT_PARTIAL)  # GCN done but the GraphCast stage was lost


def _supervisor_emit(state: dict, error: str, wedge=None) -> int:
    out, rc = _failure_json(error, state, EXIT_EMPTY, wedge)
    print(json.dumps(out))
    sys.stdout.flush()
    # the supervisor-side failure paths are one of the two places a
    # round's final JSON exists exactly once — ledger it here (the other
    # is the child pass-through in _main_guarded)
    _ledger_ingest(out)
    return rc


def _analysis_fallback(kind: str, module: str, budget_s: float,
                       min_budget_s: float = 30.0, extra_argv=()):
    """The ONE budget-bounded subprocess helper behind every wedged-path
    analysis fallback (``schedule_drift``, ``cpu_scan_delta``, and
    ``hlo_drift`` share it — ad-hoc spawns would fork the
    env-pinning/parse/disable logic). Runs ``python -m <module>
    --bench_fallback true [extra_argv...]`` on the virtual-CPU backend and
    returns the last JSON line whose ``kind`` matches. Returns None when
    the remaining budget is under ``min_budget_s`` or the fallbacks are
    disabled (``DGRAPH_BENCH_ANALYSIS_FALLBACK=0`` turns ALL tiers off
    uniformly)."""
    if os.environ.get("DGRAPH_BENCH_ANALYSIS_FALLBACK", "1") == "0":
        return None
    if budget_s < min_budget_s:
        return None
    import subprocess

    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"  # never dial the (wedged) lease
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    argv = [sys.executable, "-m", module, "--bench_fallback", "true",
            *extra_argv]
    try:
        p = subprocess.run(
            argv, capture_output=True, text=True, env=env,
            timeout=min(budget_s, 240),
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        for line in reversed((p.stdout or "").strip().splitlines()):
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == kind:
                rec.pop("run_health", None)  # the bench JSON carries its own
                return rec
        tail = (p.stderr or "").strip().splitlines()
        return {"kind": kind, "error":
                f"no record (rc={p.returncode}): {tail[-1] if tail else '?'}"}
    except Exception as e:  # the fallback must never cost the round's JSON
        return {"kind": kind, "error": f"{type(e).__name__}: {e}"}


def _attach_fallbacks(state: dict, remaining_s) -> dict:
    """Attach every non-null analysis tier the remaining budget allows:
    ``schedule_drift`` (trace auditor, compile-free, ROADMAP item 5 tier
    1), then ``cpu_scan_delta`` (compile-inside-scan per-phase step-time
    attribution per halo lowering, tier 2 — the piece that makes a wedged
    round's perf trajectory non-null, obs.attribution), then
    ``hlo_drift`` (the lowered-artifact auditor, tier 3: per-lowering
    StableHLO collective bytes vs footprint plus the donation census —
    drift in the artifact XLA would have compiled, visible with zero
    chips), then ``spmd_drift`` (the cross-rank SPMD auditor, tier 4:
    per-rank lowered-module identity + collective issue order over the
    rank-subset plan views — whether the ranks would even AGREE on a
    schedule, the deadlock class, visible with zero chips).
    ``remaining_s`` is a callable so each tier sees what the previous
    ones actually left."""
    drift = _analysis_fallback(
        "schedule_drift", "dgraph_tpu.analysis", remaining_s())
    if drift is not None:
        state["schedule_drift"] = drift
    delta = _analysis_fallback(
        "cpu_scan_delta", "dgraph_tpu.obs.attribution", remaining_s(),
        min_budget_s=45.0)
    if delta is not None:
        state["cpu_scan_delta"] = delta
    hlo = _analysis_fallback(
        "hlo_drift", "dgraph_tpu.analysis", remaining_s(),
        min_budget_s=45.0,
        extra_argv=("--fallback_kind", "hlo_drift"))
    if hlo is not None:
        state["hlo_drift"] = hlo
    spmd = _analysis_fallback(
        "spmd_drift", "dgraph_tpu.analysis", remaining_s(),
        min_budget_s=45.0,
        extra_argv=("--fallback_kind", "spmd_drift"))
    if spmd is not None:
        state["spmd_drift"] = spmd
    return state


def main() -> int:
    """Supervisor: never imports jax, so it can ALWAYS emit the JSON line.

    A wedged tunnel lease hangs jax backend init inside a GIL-holding C
    call — in-process SIGALRM handlers never run (this is how BENCH_r01 and
    r02 were lost). The real bench runs as a child process; stage results
    stream to a state file; on child hang/crash the supervisor kills it and
    emits the best-known JSON itself. SIGTERM/SIGINT (e.g. an outer
    `timeout` wrapper) likewise produce the JSON before dying."""
    import signal
    import tempfile

    global _HEALTH

    budget = int(os.environ.get("DGRAPH_BENCH_TIMEOUT", "2400"))
    deadline = time.time() + budget
    _HEALTH = _health_mod().RunHealth.begin("bench.supervisor")
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        state_path = f.name

    def read_state() -> dict:
        try:
            with open(state_path) as fh:
                txt = fh.read()
            return json.loads(txt) if txt.strip() else {}
        except (OSError, ValueError):
            return {}

    child_proc: list = [None]  # the in-flight subprocess (probe OR child)

    def _on_term(signum, frame):
        # an outer `timeout N python bench.py` with N < our budget sends
        # SIGTERM; emit the best-known JSON instead of dying silently —
        # and take the in-flight subprocess down too (a hung probe or the
        # bench child both hold a tunnel session)
        p = child_proc[0]
        if p is not None and p.poll() is None:
            p.kill()
        rc = _supervisor_emit(
            read_state(), f"supervisor received signal {signum}",
            wedge="interrupted")
        try:
            os.unlink(state_path)  # os._exit skips the finally block
        except OSError:
            pass
        os._exit(rc)

    signal.signal(signal.SIGTERM, _on_term)
    signal.signal(signal.SIGINT, _on_term)

    try:
        return _main_guarded(budget, deadline, read_state, child_proc,
                             state_path)
    except Exception as e:  # the LAST unstructured exit path: even an
        # unexpected supervisor bug must not cost the round's JSON
        return _supervisor_emit(
            read_state(), f"supervisor crashed: {type(e).__name__}: {e}",
            wedge="unknown")
    finally:
        try:
            os.unlink(state_path)
        except OSError:
            pass


def _main_guarded(budget, deadline, read_state, child_proc, state_path) -> int:
    import subprocess

    # Phase 1: cheap init probes in throwaway subprocesses (each one a
    # fresh process — no poisoned backend cache). The lease recovers on
    # its own sometimes — but r01–r05 showed ~1200s burned across 7
    # probes on a lease that never came back, so the probe loop gets its
    # OWN budget (--probe-budget-s / DGRAPH_BENCH_PROBE_BUDGET, default
    # 300s), capped at half the total so phase 2 always keeps time. A
    # wedged lease now fails fast with the same structured RunHealth
    # record (every probe attempt is in it) instead of eating the round.
    probe_budget = float(os.environ.get("DGRAPH_BENCH_PROBE_BUDGET", "300"))
    phase1_start = time.time()
    want = _expected_platform()
    check = (f"assert jax.default_backend() == '{want}', "
             f"jax.default_backend()" if want else "pass")
    # non-TPU runs (smoke / explicit JAX_PLATFORMS=cpu) must pin the
    # platform via jax.config INSIDE the probe: the baked axon
    # sitecustomize re-pins jax_platforms at interpreter startup, so the
    # env var alone leaves the probe dialing the (possibly wedged) TPU
    # lease a CPU smoke never needs
    pin = ("" if want else
           "import os; jax.config.update('jax_platforms', "
           "os.environ.get('JAX_PLATFORMS') or 'cpu'); ")
    # the probe must run a real device op + scalar fetch, not just
    # init: a wedged lease can init PJRT fine and hang the first
    # dispatch (the established wedge probe from r1+r2). dup2 folds the
    # probe's stdout into its stderr (the bench contract is ONE JSON
    # line on OUR stdout), which supervise captures to a per-attempt
    # file. On failure the probe writes its own error line to a sidecar
    # so the round's JSON says WHY the backend failed (ImportError vs
    # PJRT init vs device lost); a native-code death (segfault / PJRT
    # abort) never reaches that handler, so the captured stderr tail is
    # the fallback — the wedge record must be diagnosable alone (the
    # BENCH_r05 lesson).
    err_path = state_path + ".probe_err"
    probe = [sys.executable, "-c",
             f"import os; os.dup2(2, 1)\n"
             f"try:\n"
             f"    import jax, jax.numpy as jnp; {pin}jax.devices(); "
             f"{check}; float(jnp.ones((8, 128)).sum())\n"
             f"except BaseException as e:\n"
             f"    open({err_path!r}, 'w').write("
             f"f'{{type(e).__name__}}: {{e}}')\n"
             f"    raise\n"]
    phase1_end = min(phase1_start + probe_budget, deadline - 0.5 * budget)
    # The probe loop runs UNDER train.supervise (loaded standalone like
    # health/spans — this process still never imports jax): restart on
    # failure with capped exponential backoff, each attempt's timeout
    # clamped to the remaining window, and --probe-budget-s as the
    # overall fail-fast wall budget. A wedged lease therefore produces a
    # structured supervise_lineage (every attempt's outcome/wall/rc) in
    # the round's JSON — plus both analysis fallback tiers below —
    # instead of a hung probe (ROADMAP item 5). The attempt spans
    # (supervise.attempt) join this trace when DGRAPH_TRACE=1, as
    # bench.probe spans did before.
    sup = _supervise_mod()
    sp = _spans_mod()  # phase-2 child spans join the same trace

    def _on_spawn(p):
        try:  # a stale tail from the previous attempt must not mislabel
            os.unlink(err_path)
        except OSError:
            pass
        child_proc[0] = p

    stderr_path = state_path + ".probe_stderr"

    def _record_probe(rec):
        status = ("ok" if rec["outcome"] == "ok"
                  else "hang" if rec["outcome"] in ("wedged", "timeout")
                  else "error")
        note = ""
        if status != "ok":
            tail = []
            try:
                with open(err_path) as fh:
                    tail = fh.read().strip().splitlines()
            except OSError:
                pass  # timeout/kill before the probe could write its tail
            if not tail:
                # native-code death (segfault / PJRT abort) never runs
                # the probe's except handler — the captured stderr tail
                # is the only diagnostic left
                try:
                    with open(stderr_path, errors="replace") as fh:
                        tail = fh.read().strip().splitlines()
                except OSError:
                    pass
            note = f": {tail[-1][-300:]}" if tail else ""
            note = f"exit {rec['exit_code']} ({rec['outcome']})" + note
        # operator-facing ordinals are 1-based, matching the RunHealth
        # probes[] record (the lineage JSON keeps supervise's 0-based
        # attempt index — the DGRAPH_CHAOS_ATTEMPT contract)
        log(f"backend probe attempt {rec['attempt'] + 1}: {rec['outcome']} "
            f"(rc={rec['exit_code']}, {rec['wall_s']:.1f}s)"
            + (f" {note}" if note else ""))
        _HEALTH.record_probe(rec["attempt"] + 1, rec["wall_s"], status, note)

    lineage = sup.supervise(
        probe,
        max_restarts=999,  # the wall budget is the binding limit
        backoff_s=5.0, backoff_factor=2.0, backoff_max_s=45.0,
        attempt_timeout_s=150.0,
        budget_s=max(1.0, phase1_end - time.time()),
        stderr_path=stderr_path,
        on_spawn=_on_spawn,
        on_attempt=_record_probe,
    )
    child_proc[0] = None
    for p in (err_path, stderr_path):
        try:
            os.unlink(p)
        except OSError:
            pass
    if lineage["final_exit_code"] != 0:
        # report the window actually probed, not the configured knob —
        # a small total budget can cap the probe phase shorter than
        # the default, and the wedge record must say what happened.
        # With the chip unreachable, spend a slice of the remaining
        # budget landing the analysis fallbacks (schedule drift +
        # cpu scan-delta timing) so the round's artifact is non-null
        # (ROADMAP item 5)
        state = _attach_fallbacks(
            {"supervise_lineage": lineage},
            lambda: deadline - time.time() - 20)
        return _supervisor_emit(
            state,
            f"backend never initialized within {len(lineage['attempts'])} "
            f"probes (~{int(time.time() - phase1_start)}s probe window); "
            f"wedged TPU lease")
    log(f"backend probe OK "
        f"(attempt {lineage['attempts'][-1]['attempt'] + 1})")

    # Phase 2: the real bench, with the remaining budget minus a margin
    # so the child's own watchdog fires first (richer JSON than ours).
    # stderr is inherited: progress must stream live (a silent 30-min
    # compile is indistinguishable from a wedge otherwise).
    # A child that dies on BACKEND init (the lease wedging between our
    # probe and its jax init) is RESPAWNED while budget remains — the
    # lease recovers on its own, and burning the round on a seconds-long
    # child run would waste the whole point of the retry design.
    spawn = 0
    while True:
        spawn += 1
        child_span = sp.span("bench.child", spawn=spawn)
        env = dict(os.environ)
        env["DGRAPH_BENCH_CHILD"] = "1"
        env["DGRAPH_BENCH_STATE"] = state_path
        # the child's stage spans join this trace (no-op when tracing off)
        env.update(sp.child_env(parent=child_span))
        child_budget = max(60, int(deadline - time.time()) - 30)
        env["DGRAPH_BENCH_TIMEOUT"] = str(child_budget)
        p = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__)],
            env=env, stdout=subprocess.PIPE, text=True,
        )
        child_proc[0] = p
        try:
            stdout, _ = p.communicate(timeout=child_budget + 60)
            child_span.end(rc=p.returncode)
        except subprocess.TimeoutExpired:
            p.kill()
            p.communicate()
            child_span.end(error="hung past its watchdog; killed")
            state = read_state()
            if not state.get("value"):
                # the chip wedged before the primary metric landed: attach
                # the CPU-side analysis tiers IF budget remains — a hung
                # child has usually consumed the deadline already, and
                # overrunning it here risks an outer hard-kill eating the
                # round's JSON line (the one unbreakable contract)
                _attach_fallbacks(state, lambda: deadline - time.time() - 20)
            return _supervisor_emit(
                state,
                "bench child hung past its own watchdog; killed",
                wedge="dispatch_wedge")
        last = (stdout or "").strip().splitlines()
        if (p.returncode == EXIT_BACKEND
                and time.time() < deadline - 120):
            log(f"child {spawn} lost its backend (rc=5); waiting 30s and "
                f"respawning with {int(deadline - time.time())}s left")
            time.sleep(30)
            continue
        # pass through the child's JSON line + rc when it produced one,
        # merging the supervisor's probe history into its run_health so
        # the artifact records the whole path onto the chip (the
        # "seven wedged-lease probes" class of context, BENCH_r05)
        if last:
            line = last[-1]
            try:
                out = json.loads(line)
                out.setdefault("run_health", {})["supervisor"] = (
                    _HEALTH.finish())
                line = json.dumps(out)
                # ledger the MERGED record (child metrics + supervisor
                # probe history) — this is the round's artifact of record
                _ledger_ingest(out)
            except ValueError:
                pass  # not JSON: pass the child's words through untouched
            print(line)
            sys.stdout.flush()
            return p.returncode
        return _supervisor_emit(
            read_state(), f"bench child died rc={p.returncode} with no JSON")


if __name__ == "__main__":
    if os.environ.get("DGRAPH_BENCH_CHILD") == "1":
        _child_main()
    else:
        import argparse

        ap = argparse.ArgumentParser(
            description="dgraph_tpu benchmark harness (one JSON line to "
                        "stdout; see module docstring for env knobs)")
        ap.add_argument(
            "--probe-budget-s", type=float, default=None,
            help="phase-1 backend-probe budget in seconds (default 300; a "
                 "wedged TPU lease fails fast with a structured RunHealth "
                 "record instead of burning the run budget on probes)")
        ap.add_argument(
            "--platform", default=None,
            help="JAX_PLATFORMS passthrough for the probe and bench child "
                 "(e.g. 'cpu' to validate off-chip, 'tpu' to require the "
                 "chip); overrides the ambient env var")
        args = ap.parse_args()
        # thread through the environment: the supervisor, its probes, and
        # the bench child all read the same knobs there
        if args.platform is not None:
            os.environ["JAX_PLATFORMS"] = args.platform
        if args.probe_budget_s is not None:
            os.environ["DGRAPH_BENCH_PROBE_BUDGET"] = str(args.probe_budget_s)
        sys.exit(main())
