"""Benchmark harness: full-graph GCN training epoch time at ogbn-arxiv scale.

Prints ONE JSON line to stdout:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
(stage progress goes to stderr).

The reference publishes no numbers (BASELINE.md), so vs_baseline compares
against OUR recorded number in BENCH_BASELINE.json when present (ratio > 1.0
= faster than recorded). The measured quantity mirrors the reference's OGB
harness (per-epoch training time, avg excluding the first/compile epoch —
``experiments/OGB/main.py:129-221``) on an arxiv-shaped synthetic graph
(169 343 vertices / 2.33M directed edges / 128 features / 40 classes).

Device-transfer budget is kept minimal for the tunneled single-chip setup:
features/labels are generated ON device; only the int32 plan crosses the
wire (~30 MB).
"""

from __future__ import annotations

import json
import os
import sys
import time


def log(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def main():
    import numpy as np

    t_start = time.time()
    log("importing jax...")
    import jax
    import jax.numpy as jnp
    import optax

    log(f"devices: {jax.devices()}")

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.models import GCN
    from dgraph_tpu.plan import build_edge_plan

    # ogbn-arxiv shape (V=169343, E~1.17M directed, symmetrized ~2.33M)
    V, E_half, F, C = 169_343, 1_166_243, 128, 40
    rng = np.random.default_rng(0)
    src = rng.integers(0, V, E_half)
    dst = rng.integers(0, V, E_half)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)

    log("building plan (host)...")
    part = np.zeros(V, np.int32)  # single-chip bench: world size 1
    plan_np, layout = build_edge_plan(
        edge_index, part, world_size=1, edge_owner="dst", pad_multiple=128
    )
    log("moving plan to device...")
    plan = jax.tree.map(lambda leaf: jnp.asarray(np.asarray(leaf)[0]), plan_np)
    jax.block_until_ready(jax.tree.leaves(plan))

    comm = Communicator.init_process_group("single")
    model = GCN(hidden_features=256, out_features=C, comm=comm, num_layers=2)

    log("generating data on device...")
    n_pad = plan.src_index.shape  # noqa: F841 (forces plan realized)
    x = jax.random.normal(jax.random.key(0), (plan_np.n_src_pad, F), jnp.float32)
    y = jax.random.randint(jax.random.key(1), (plan_np.n_src_pad,), 0, C)
    mask = (jnp.arange(plan_np.n_src_pad) < V).astype(jnp.float32)
    jax.block_until_ready(x)

    log("initializing model...")
    params = model.init(jax.random.key(2), x, plan)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    import functools

    # Timing protocol for the tunneled chip: `block_until_ready` is NOT a
    # reliable completion barrier there and repeated same-input dispatches
    # can be memoized, so run n epochs INSIDE one jit (lax.scan), force
    # completion with a scalar fetch, and report the delta between two scan
    # lengths — per-call RPC latency cancels out.
    @functools.partial(jax.jit, static_argnames="n", donate_argnums=(0, 1))
    def epochs(params, opt_state, salt, n):
        def lf(p):
            logits = model.apply(p, x, plan)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
            return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        def body(carry, _):
            p, o, s = carry
            loss, grads = jax.value_and_grad(lf)(p)
            updates, o = optimizer.update(grads, o, p)
            p = optax.apply_updates(p, updates)
            return (p, o, s + loss * 1e-20), None

        (p, o, s), _ = jax.lax.scan(
            body, (params, opt_state, salt), None, length=n
        )
        return p, o, s

    N_LONG = 6
    log("compiling (n=1 and n=%d)..." % N_LONG)
    params, opt_state, s = epochs(params, opt_state, jnp.float32(0.0), 1)
    float(s)
    params, opt_state, s = epochs(params, opt_state, s, N_LONG)
    float(s)
    log(f"warmup done ({time.time() - t_start:.1f}s since start); timing...")

    deltas = []
    for rep in range(3):
        t0 = time.perf_counter()
        params, opt_state, s = epochs(params, opt_state, s, 1)
        float(s)  # scalar fetch = the only trustworthy completion barrier
        t1 = time.perf_counter() - t0
        t0 = time.perf_counter()
        params, opt_state, s = epochs(params, opt_state, s, N_LONG)
        float(s)
        t_long = time.perf_counter() - t0
        deltas.append((t_long - t1) / (N_LONG - 1) * 1000.0)
        log(f"rep {rep}: 1-epoch {t1*1000:.1f} ms, {N_LONG}-epoch {t_long*1000:.1f} ms -> {deltas[-1]:.2f} ms/epoch")
    positive = [d for d in deltas if d > 0]
    dt_ms = sorted(positive)[len(positive) // 2] if positive else sorted(deltas)[-1]
    log(f"epoch time {dt_ms:.2f} ms")

    vs = 1.0
    base_path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            base = json.load(open(base_path))
            if base.get("unit") == "ms" and base.get("value"):
                vs = float(base["value"]) / dt_ms  # >1 = faster than baseline
        except Exception:
            pass

    print(
        json.dumps(
            {
                "metric": "arxiv_gcn_epoch_time",
                "value": round(dt_ms, 3),
                "unit": "ms",
                "vs_baseline": round(vs, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
