"""Benchmark harness: full-graph GCN training epoch time at ogbn-arxiv scale.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}.

The reference publishes no numbers (BASELINE.md), so vs_baseline is measured
against OUR recorded round-1 number in BENCH_BASELINE.json when present
(ratio > 1.0 = faster than the recorded baseline), else 1.0. The measured
quantity mirrors the reference's OGB harness (per-epoch training time, avg
excluding first/compile epoch — ``experiments/OGB/main.py:129-221``) on an
arxiv-shaped synthetic graph (169k vertices / 2.3M directed edges, 128
features, 40 classes — ogbn-arxiv's shape).
"""

from __future__ import annotations

import json
import os
import sys
import time


def main():
    import numpy as np

    import jax
    import jax.numpy as jnp
    import optax

    from dgraph_tpu.comm import Communicator
    from dgraph_tpu.data import DistributedGraph, synthetic
    from dgraph_tpu.models import GCN

    # ogbn-arxiv shape (V=169343, E~1.17M directed, symmetrized ~2.33M)
    V, E_half, F, C = 169_343, 1_166_243, 128, 40
    rng = np.random.default_rng(0)
    src = rng.integers(0, V, E_half)
    dst = rng.integers(0, V, E_half)
    edge_index = np.stack(
        [np.concatenate([src, dst]), np.concatenate([dst, src])]
    ).astype(np.int64)
    feats = rng.normal(size=(V, F)).astype(np.float32)
    labels = rng.integers(0, C, V).astype(np.int32)
    masks = {"train": np.ones(V, bool)}

    n_dev = len(jax.devices())
    world = 1  # bench target is the single real TPU chip
    g = DistributedGraph.from_global(
        edge_index, feats, labels, masks, world_size=world,
        partition_method="block", add_symmetric_norm=True, pad_multiple=128,
    )

    comm = Communicator.init_process_group("single")
    model = GCN(hidden_features=256, out_features=C, comm=comm, num_layers=3)

    plan = jax.tree.map(lambda leaf: jnp.asarray(leaf[0]), g.plan)
    x = jnp.asarray(g.features[0])
    y = jnp.asarray(g.labels[0])
    mask = jnp.asarray(g.masks["train"][0])
    ew = jnp.asarray(g.edge_weight[0])

    params = model.init(jax.random.key(0), x, plan, ew)
    optimizer = optax.adam(1e-3)
    opt_state = optimizer.init(params)

    @jax.jit
    def train_step(params, opt_state, x, y, mask, ew):
        def lf(p):
            logits = model.apply(p, x, plan, ew)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32))
            ll = jnp.take_along_axis(logp, y[:, None], axis=1)[:, 0]
            return -(ll * mask).sum() / jnp.maximum(mask.sum(), 1.0)

        loss, grads = jax.value_and_grad(lf)(params)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    # warmup/compile
    params, opt_state, loss = train_step(params, opt_state, x, y, mask, ew)
    jax.block_until_ready(loss)

    n_iters = 20
    t0 = time.perf_counter()
    for _ in range(n_iters):
        params, opt_state, loss = train_step(params, opt_state, x, y, mask, ew)
    jax.block_until_ready(loss)
    dt_ms = (time.perf_counter() - t0) / n_iters * 1000.0

    vs = 1.0
    base_path = os.path.join(os.path.dirname(__file__), "BENCH_BASELINE.json")
    if os.path.exists(base_path):
        try:
            base = json.load(open(base_path))
            if base.get("unit") == "ms" and base.get("value"):
                vs = float(base["value"]) / dt_ms  # >1 = faster than baseline
        except Exception:
            pass

    print(
        json.dumps(
            {
                "metric": "arxiv_gcn_epoch_time",
                "value": round(dt_ms, 3),
                "unit": "ms",
                "vs_baseline": round(vs, 4),
            }
        )
    )


if __name__ == "__main__":
    main()
